"""QLightCone: lazy circuit buffering with compact-register cone reads.

Like :class:`~qrack_tpu.layers.qtensornetwork.QTensorNetwork`, gates
buffer into a :class:`~qrack_tpu.layers.qcircuit.QCircuit` instead of
dispatching (reference: include/qtensornetwork.hpp:30).  The difference
is what a read builds: QTensorNetwork runs the cone-sliced circuit on a
FULL-WIDTH stack (a w80 register still allocates w80 state), while this
engine relabels the cone onto a compact register of cone width and
executes it through the routed ladder (``"route"`` — stabilizer / bdt /
turboquant / dense), so the heavy machinery below (fusion windows,
Pallas kernels, integrity guard, roofline ledger) prices the CONE, not
the declared width.  A w50 depth-4 local expectation costs a w7 dense
ket; the full-width ket is never built.

Relabeling is sound because a gate's control-permutation keys index
control POSITIONS, not qubit numbers (layers/qcircuit.py compile_fn:
perm bit j is the required state of ``controls[j]``), so mapping
target/control indices onto the compact register and keeping payloads +
perm keys verbatim preserves semantics exactly.

Mid-circuit measurement follows the tentpole contract: while the
measured qubit's cone stays narrow (<= QRACK_LIGHTCONE_M_MAX_QB,
default: the dense route cap) the collapse is recorded INTO the buffer
as a normalized projector ``diag(1,0)/sqrt(1-p1)`` / ``diag(0,1)/
sqrt(p1)`` — later cones through the measured qubit replay the
collapse exactly — else the whole buffer materializes into a
full-width base stack (the QTensorNetwork measurement-layer idiom) and
buffering resumes on top of the collapsed base.

Cone engines are cached per cone-qubit set and invalidated on every
buffer mutation; a repeated read (the serve plane polling one
observable) re-uses the materialized cone ket
(``lightcone.cache.hit``).  Reads check the ``lightcone.slice`` fault
site (resilience/faults.py) before slicing, so the integrity soak can
prove a fault here surfaces as a typed error, not silent garbage.
"""

from __future__ import annotations

import math
import os
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from .. import telemetry as _tele
from ..config import FP_NORM_EPSILON
from ..interface import QInterface
from ..layers.qcircuit import QCircuit, QCircuitGate
from ..resilience import faults as _faults


def _route_factory(n, **kw):
    from ..factory import create_quantum_interface
    from ..route.cost import route_mode

    # a pinned QRACK_ROUTE=lightcone applies to SESSIONS, not to the
    # cone stacks each read builds — masking it here (auto: route by
    # cost at cone width) is what keeps the rung from recursing into
    # itself; every other pin passes through
    mode = "auto" if route_mode() == "lightcone" else None
    return create_quantum_interface(("route",), n, route_mode=mode, **kw)


def _m_width_cap() -> int:
    """Cone-width ceiling for buffer-projector measurement; past it a
    mid-circuit M forces full materialization."""
    from ..route import cost as _cost

    raw = os.environ.get("QRACK_LIGHTCONE_M_MAX_QB", "")
    try:
        return int(raw) if raw else _cost.RouteKnobs.from_env().dense_max_qb
    except ValueError:
        return _cost.RouteKnobs.from_env().dense_max_qb


def _reverse_cone(gates, seed) -> set:
    """Qubit set of the past light cone of ``seed`` over ``gates`` —
    the same reverse walk as QCircuit.PastLightCone, set-only."""
    cone = set(seed)
    for g in reversed(gates):
        if set(g.qubits()) & cone:
            cone |= set(g.qubits())
    return cone


def _nonunitary(m) -> bool:
    m = np.asarray(m)
    return not np.allclose(m @ m.conj().T, np.eye(2), atol=1e-9)


def compact_over(circuit: QCircuit, qubits) -> Tuple[QCircuit, list]:
    """(compact, order): `circuit`'s past light cone of `qubits`,
    relabeled onto a register of cone width.  ``order[i]`` is the
    original index of compact qubit i.  Gates append DIRECTLY to the
    compact list (no AppendGate peephole: the buffer is already
    merge-normal and the bit-identical gate sequence is what the cone
    digest and checkpoint contract key on)."""
    sliced = circuit.PastLightCone(qubits)
    cone = set(int(q) for q in qubits)
    for g in sliced.gates:
        cone.update(g.qubits())
    order = sorted(cone)
    qmap = {q: i for i, q in enumerate(order)}
    compact = QCircuit(max(len(order), 1))
    compact.gates = [
        QCircuitGate(qmap[g.target],
                     {p: m.copy() for p, m in g.payloads.items()},
                     tuple(qmap[c] for c in g.controls))
        for g in sliced.gates
    ]
    return compact, order


def sliced_shape_key(circuit: QCircuit) -> Tuple[int, int, str]:
    """Batch-bucket key for a lightcone-routed job: the sub-circuit
    relabeled onto its touched qubits (width-independent), so two w50+
    tenants running the same local structure at different qubit offsets
    share a bucket (serve/service.py admission)."""
    touched = sorted({q for g in circuit.gates for q in g.qubits()})
    compact, _ = compact_over(circuit, touched)
    return compact.shape_key(compact.qubit_count)


class QLightCone(QInterface):
    """Buffering engine whose reads build cone-width kets only."""

    _ckpt_kind = "lightcone"

    def __init__(self, qubit_count: int, init_state: int = 0,
                 stack_factory: Optional[Callable] = None, **kwargs):
        super().__init__(qubit_count, init_state=init_state, **kwargs)
        self._factory = stack_factory or _route_factory
        self._kw = {k: v for k, v in kwargs.items() if k != "rng"}
        self._init_state = int(init_state)
        self.circuit = QCircuit(qubit_count)
        self.sim = None  # full-width base (post-materialization only)
        # dedicated stream for cone/base construction so reads never
        # consume from the measurement stream (reproducibility)
        self._stack_rng = self.rng.spawn()
        # cone-qubit tuple -> materialized compact engine
        self._cones: Dict[Tuple[int, ...], object] = {}

    # ------------------------------------------------------------------

    def _buffering(self) -> bool:
        return bool(self.circuit.gates) or self.sim is None

    def _touched(self) -> set:
        return {q for g in self.circuit.gates for q in g.qubits()}

    def _cone_request(self, qubits) -> Tuple[int, ...]:
        """Close the requested qubit set over recorded measurement
        projectors.  The reverse cone walk elides trailing gates, which
        is sound for unitaries but NOT for a projector: a collapse on a
        qubit entangled with the read changes the read's marginal even
        when no later gate couples them (Bell pair: M(0) fixes Prob(1)).
        Any non-unitary site whose own past cone intersects the read's
        cone is pulled into the request, to a fixpoint, so the compact
        circuit replays every relevant collapse."""
        req = {int(q) for q in qubits}
        gates = self.circuit.gates
        sites = [(i, g.target) for i, g in enumerate(gates)
                 if not g.controls
                 and any(_nonunitary(m) for m in g.payloads.values())]
        while sites:
            cone = _reverse_cone(gates, req)
            add = {q for i, q in sites
                   if q not in req
                   and _reverse_cone(gates[:i + 1], (q,)) & cone}
            if not add:
                break
            req |= add
        return tuple(sorted(req))

    def _slice(self, qubits) -> Tuple[QCircuit, list]:
        directive = _faults.check("lightcone.slice")
        if directive:
            raise RuntimeError(f"lightcone.slice injected fault: {directive}")
        return compact_over(self.circuit, self._cone_request(qubits))

    def _cone_engine(self, qubits):
        """(engine, qmap) for the past light cone of `qubits`: a cached
        compact-register stack holding the cone ket."""
        compact, order = self._slice(qubits)
        qmap = {q: i for i, q in enumerate(order)}
        # keyed by (cone qubits, sliced-circuit digest): two reads can
        # share a qubit set with DIFFERENT gate subsets (a trailing gate
        # on q is elided from Prob(q') cones but not from a full-state
        # read), so the qubit set alone would alias distinct cone kets
        key = (tuple(order), compact.structure_digest())
        eng = self._cones.get(key)
        if eng is not None:
            if _tele._ENABLED:
                _tele.inc("lightcone.cache.hit")
            return eng, qmap
        base = 0
        for i, q in enumerate(order):
            if (self._init_state >> q) & 1:
                base |= 1 << i
        eng = self._factory(compact.qubit_count, init_state=base,
                            rng=self._stack_rng.spawn(), **self._kw)
        # routed admission + dispatch happen inside Run (route_for), so
        # the cone sub-circuit gets the same ladder/fusion/telemetry
        # treatment a directly-submitted circuit would
        compact.Run(eng)
        if _tele._ENABLED:
            _tele.inc("lightcone.cache.miss")
            _tele.observe("lightcone.cone_width", float(compact.qubit_count))
            _tele.inc("lightcone.gates.cone", len(compact.gates))
            _tele.inc("lightcone.gates.elided",
                      max(len(self.circuit.gates) - len(compact.gates), 0))
        self._cones[key] = eng
        return eng, qmap

    def _note_read(self, eng) -> None:
        if not _tele._ENABLED:
            return
        _tele.inc("lightcone.reads")
        cur = getattr(eng, "current_stack", None)
        stack = cur() if callable(cur) else None
        _tele.inc(f"lightcone.reads.{stack or 'direct'}")

    def _cone_query(self, qubits, fn):
        """Evaluate ``fn(engine, qmap)`` on a cone-width stack; ``qmap``
        maps an original qubit index to the engine's index.  With a
        materialized base the query runs full-width on (a clone of) the
        base — cones no longer compose past a collapsed base state —
        and ``qmap`` is the identity."""
        if self.sim is not None:
            if self.circuit.gates:
                tmp = self.sim.Clone()
                self.circuit.PastLightCone(
                    self._cone_request(qubits)).Run(tmp)
            else:
                tmp = self.sim
            self._note_read(self.sim)
            return fn(tmp, lambda q: q)
        eng, qmap = self._cone_engine(tuple(qubits))
        self._note_read(eng)
        return fn(eng, qmap.__getitem__)

    def _materialize(self) -> None:
        """Run the whole buffer into a full-width base stack (the
        QTensorNetwork measurement-layer idiom).  The routed admission
        inside RunFused may refuse (MisrouteError) — that raise happens
        BEFORE the buffer is reset, so a refused materialization leaves
        the session intact."""
        if self.sim is not None and not self.circuit.gates:
            return   # already materialized, nothing buffered on top
        if _tele._ENABLED:
            _tele.inc("lightcone.materialize.full")
        sim = self.sim
        if sim is None:
            sim = self._factory(self.qubit_count,
                                init_state=self._init_state,
                                rng=self._stack_rng.spawn(), **self._kw)
        if self.circuit.gates:
            self.circuit.RunFused(sim)
        self.sim = sim
        self.circuit = QCircuit(self.qubit_count)
        self._cones.clear()

    # ------------------------------------------------------------------
    # gate primitive: buffer (never dispatch)
    # ------------------------------------------------------------------

    def MCMtrxPerm(self, controls, mtrx, target, perm) -> None:
        m = np.asarray(mtrx, dtype=np.complex128).reshape(2, 2)
        self.circuit.append_ctrl(tuple(controls), target, m, perm)
        self._cones.clear()

    # ------------------------------------------------------------------
    # observables: every read is cone-priced
    # ------------------------------------------------------------------

    def Prob(self, q: int) -> float:
        return self._cone_query((q,), lambda s, m: s.Prob(m(q)))

    def ProbParity(self, mask: int) -> float:
        if mask == 0:
            return 0.0
        bits = [q for q in range(self.qubit_count) if (mask >> q) & 1]

        def fn(s, m):
            sub = 0
            for q in bits:
                sub |= 1 << m(q)
            return s.ProbParity(sub)

        return self._cone_query(bits, fn)

    def ProbMask(self, mask: int, perm: int) -> float:
        bits = [q for q in range(self.qubit_count) if (mask >> q) & 1]
        if not bits:
            return 1.0

        def fn(s, m):
            sub_mask = sub_perm = 0
            for q in bits:
                sub_mask |= 1 << m(q)
                if (perm >> q) & 1:
                    sub_perm |= 1 << m(q)
            return s.ProbMask(sub_mask, sub_perm)

        return self._cone_query(bits, fn)

    def ProbMaskAll(self, mask: int) -> np.ndarray:
        bits = [q for q in range(self.qubit_count) if (mask >> q) & 1]
        if not bits:
            return np.ones(1, dtype=np.float64)
        return self.ProbBitsAll(bits)

    def ProbBitsAll(self, bits) -> np.ndarray:
        bits = list(bits)

        def fn(s, m):
            return np.asarray(s.ProbBitsAll([m(b) for b in bits]))

        return self._cone_query(bits, fn)

    def ExpectationBitsAll(self, bits, offset: int = 0) -> float:
        bits = list(bits)

        def fn(s, m):
            return s.ExpectationBitsAll([m(b) for b in bits], offset)

        return self._cone_query(bits, fn)

    def MultiShotMeasureMask(self, q_powers, shots: int) -> dict:
        from ..utils.bits import log2

        bits = [log2(int(p)) for p in q_powers]

        # result keys index q_powers POSITIONS, so remapping the powers
        # onto the compact register preserves every key verbatim
        def fn(s, m):
            return s.MultiShotMeasureMask([1 << m(b) for b in bits], shots)

        return self._cone_query(bits, fn)

    def GetAmplitude(self, perm: int) -> complex:
        if self.sim is not None:
            return self._cone_query(range(self.qubit_count),
                                    lambda s, m: complex(s.GetAmplitude(perm)))
        touched = self._touched()
        # untouched qubits are still exactly |init bit>: they factor out
        # of the amplitude, contributing 1 when the requested bit
        # matches and 0 when it does not
        for q in range(self.qubit_count):
            if q not in touched and ((perm >> q) ^ (self._init_state >> q)) & 1:
                return 0j
        order = sorted(touched) if touched else [0]

        def fn(s, m):
            sub = 0
            for q in order:
                if (perm >> q) & 1:
                    sub |= 1 << m(q)
            return complex(s.GetAmplitude(sub))

        return self._cone_query(order, fn)

    def GetQuantumState(self) -> np.ndarray:
        return self._cone_query(range(self.qubit_count),
                                lambda s, m: np.asarray(s.GetQuantumState()))

    def GetProbs(self) -> np.ndarray:
        return self._cone_query(range(self.qubit_count),
                                lambda s, m: np.asarray(s.GetProbs()))

    # ------------------------------------------------------------------
    # measurement: buffer-projector while the cone is narrow
    # ------------------------------------------------------------------

    def ForceM(self, q: int, result: bool, do_force: bool = True,
               do_apply: bool = True) -> bool:
        if not do_apply:
            return self._cone_query(
                (q,), lambda s, m: s.ForceM(m(q), result, do_force, False))
        if self.sim is not None:
            return self._collapse_on_base(q, result, do_force)
        compact, order = self._slice((q,))
        if len(order) > _m_width_cap():
            # cone too wide for a cheap marginal: fall back to the
            # QTensorNetwork measurement layer (full materialization)
            self._materialize()
            return self._collapse_on_base(q, result, do_force)
        p1 = self._cone_query((q,), lambda s, m: s.Prob(m(q)))
        if do_force:
            res = bool(result)
        elif p1 >= 1.0 - FP_NORM_EPSILON:
            res = True   # deterministic: no rng draw (keeps streams
        elif p1 <= FP_NORM_EPSILON:
            res = False  # aligned with the concrete engines)
        else:
            res = self.Rand() <= p1
        nrm_sq = p1 if res else (1.0 - p1)
        if nrm_sq <= 0.0:
            raise RuntimeError("ForceM: forced result has zero probability")
        proj = np.zeros((2, 2), dtype=np.complex128)
        proj[int(res), int(res)] = 1.0 / math.sqrt(nrm_sq)
        # the recorded (normalized, non-unitary) projector replays the
        # collapse inside every later cone through q — features.py
        # classifies it "general", keeping stabilizer rungs off it
        self.circuit.append_1q(q, proj)
        self._cones.clear()
        if _tele._ENABLED:
            _tele.inc("lightcone.m.projector")
        return res

    def _collapse_on_base(self, q: int, result: bool, do_force: bool) -> bool:
        self._materialize()
        # draw the collapse from OUR measurement stream, then restore
        # the base's own stream (the QTensorNetwork rng-swap idiom)
        saved = self.sim.rng
        self.sim.rng = self.rng
        try:
            return self.sim.ForceM(q, result, do_force, True)
        finally:
            self.sim.rng = saved

    # ------------------------------------------------------------------
    # structure / state
    # ------------------------------------------------------------------

    def SetPermutation(self, perm: int, phase=None) -> None:
        self.circuit = QCircuit(self.qubit_count)
        self.sim = None
        self._init_state = int(perm)
        self._cones.clear()

    def _sync_from_sim(self) -> None:
        self.qubit_count = self.sim.qubit_count
        self.circuit = QCircuit(self.qubit_count)
        self._cones.clear()

    def SetQuantumState(self, state) -> None:
        self._materialize()
        self.sim.SetQuantumState(state)

    def Compose(self, other, start: Optional[int] = None) -> int:
        self._materialize()
        inner = other
        if isinstance(other, QLightCone):
            oc = other.Clone()
            oc._materialize()
            inner = oc.sim
        res = self.sim.Compose(inner, start)
        self._sync_from_sim()
        return res

    def Decompose(self, start: int, dest) -> None:
        self._materialize()
        if isinstance(dest, QLightCone):
            dest._materialize()
            self.sim.Decompose(start, dest.sim)
            dest._sync_from_sim()
        else:
            self.sim.Decompose(start, dest)
        self._sync_from_sim()

    def Dispose(self, start: int, length: int,
                disposed_perm: Optional[int] = None) -> None:
        self._materialize()
        self.sim.Dispose(start, length, disposed_perm)
        self._sync_from_sim()

    def Allocate(self, start: int, length: int = 1) -> int:
        if start == self.qubit_count:
            # append never shifts existing indices: widen the register
            # (new qubits start |0>); cached cones stay valid — the new
            # qubits are untouched by every buffered gate
            if self.sim is not None:
                self.sim.Allocate(start, length)
            self.qubit_count += length
            self.circuit.qubit_count = self.qubit_count
            return start
        self._materialize()
        res = self.sim.Allocate(start, length)
        self._sync_from_sim()
        return res

    def Clone(self) -> "QLightCone":
        c = QLightCone(self.qubit_count, init_state=self._init_state,
                       stack_factory=self._factory, rng=self.rng.spawn(),
                       **self._kw)
        c._stack_rng = self._stack_rng.spawn()
        c.circuit = self.circuit.clone()
        c.sim = self.sim.Clone() if self.sim is not None else None
        return c

    def SumSqrDiff(self, other) -> float:
        a = self.GetQuantumState()
        b = np.asarray(other.GetQuantumState(), dtype=np.complex128)
        inner = np.vdot(a, b)
        return float(max(0.0, 1.0 - abs(inner) ** 2))

    def GetDepth(self) -> int:
        return self.circuit.GetDepth()

    def Finish(self) -> None:
        if self.sim is not None:
            self.sim.Finish()

    def isBuffering(self) -> bool:
        return self._buffering()

    # ------------------------------------------------------------------
    # checkpoint protocol (checkpoint/registry.py, kind "lightcone")
    # ------------------------------------------------------------------

    def _ckpt_capture(self, capture_child):
        from ..checkpoint.registry import rng_state

        arrays = {}
        gates_meta = []
        for i, g in enumerate(self.circuit.gates):
            perms = sorted(int(p) for p in g.payloads)
            gates_meta.append({"t": int(g.target),
                               "c": [int(c) for c in g.controls],
                               "p": perms})
            for p in perms:
                arrays[f"g{i}_p{p}"] = np.asarray(g.payloads[p],
                                                  dtype=np.complex128)
        children = {}
        cones_meta = []
        for idx, key in enumerate(sorted(self._cones)):
            order, digest = key
            cones_meta.append({"order": [int(q) for q in order],
                               "digest": str(digest)})
            children[f"cone{idx}"] = capture_child(self._cones[key])
        if self.sim is not None:
            children["sim"] = capture_child(self.sim)
        return {"kind": "lightcone",
                "meta": {"n": self.qubit_count,
                         "init_state": int(self._init_state),
                         "gates": gates_meta,
                         "cones": cones_meta,
                         "stack_rng": rng_state(self._stack_rng)},
                "arrays": arrays,
                "children": children}

    def _ckpt_restore(self, arrays, meta, children, restore_child):
        from ..checkpoint.registry import restore_rng

        if int(meta["n"]) != self.qubit_count:
            raise ValueError("checkpoint width mismatch")
        self._init_state = int(meta["init_state"])
        circ = QCircuit(self.qubit_count)
        # rebuild the gate list DIRECTLY (no AppendGate peephole): the
        # captured sequence is already merge-normal and must round-trip
        # bit-identically, recorded projectors included
        for i, gm in enumerate(meta.get("gates", [])):
            payloads = {int(p): arrays[f"g{i}_p{p}"] for p in gm["p"]}
            circ.gates.append(QCircuitGate(int(gm["t"]), payloads,
                                           tuple(int(c) for c in gm["c"])))
        self.circuit = circ
        self.sim = (restore_child(children["sim"], self.sim)
                    if "sim" in children else None)
        self._cones = {}
        for idx, cm in enumerate(meta.get("cones", [])):
            key = (tuple(int(q) for q in cm["order"]), str(cm["digest"]))
            self._cones[key] = restore_child(children[f"cone{idx}"])
        if "stack_rng" in meta:
            restore_rng(self._stack_rng, meta["stack_rng"])

    def __repr__(self) -> str:
        return (f"QLightCone(n={self.qubit_count}, "
                f"buffered={len(self.circuit.gates)}, "
                f"cones={len(self._cones)}, "
                f"base={'yes' if self.sim is not None else 'no'})")


__all__ = ["QLightCone", "compact_over", "sliced_shape_key"]
