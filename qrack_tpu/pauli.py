"""Pauli operator ids (reference: include/pauli.hpp — Q#-compatible values)."""

from enum import IntEnum


class Pauli(IntEnum):
    PauliI = 0
    PauliX = 1
    PauliZ = 2
    PauliY = 3
