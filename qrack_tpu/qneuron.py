"""QNeuron: quantum perceptron with per-control-permutation RY weights.

Re-design of the reference neuron (reference: include/qneuron.hpp:25 —
output prepared to |+>-like RY(pi/2), uniformly-controlled RY by input
permutation, activation functions applied to angles, gradient-free
Learn/LearnPermutation by angle nudging)."""

from __future__ import annotations

import math
from enum import IntEnum
from typing import Optional, Sequence

import numpy as np


class ActivationFn(IntEnum):
    Sigmoid = 0
    ReLU = 1
    GeLU = 2
    Generalized_Logistic = 3
    Leaky_ReLU = 4


class QNeuron:
    def __init__(self, qreg, input_indices: Sequence[int], output_index: int,
                 activation_fn: ActivationFn = ActivationFn.Sigmoid,
                 alpha: float = 1.0, tolerance: float = 1e-6):
        self.qreg = qreg
        self.input_indices = list(input_indices)
        self.output_index = int(output_index)
        self.activation_fn = activation_fn
        self.alpha = float(alpha)
        self.tolerance = float(tolerance)
        self.angles = np.zeros(1 << len(self.input_indices), dtype=np.float64)

    # ------------------------------------------------------------------

    def _activated(self) -> np.ndarray:
        a = self.angles
        fn = self.activation_fn
        if fn == ActivationFn.ReLU:
            return np.maximum(0.0, a)
        if fn == ActivationFn.GeLU:
            return a * (1.0 + np.vectorize(math.erf)(a * math.sqrt(0.5)))
        if fn == ActivationFn.Generalized_Logistic:
            return a / np.power(1.0 + np.exp(-self.alpha * a), 1.0 / self.alpha)
        if fn == ActivationFn.Leaky_ReLU:
            return np.maximum(self.alpha * a, a)
        return a  # Sigmoid default: raw angles

    def Predict(self, expected: bool = True, reset_init: bool = True) -> float:
        """(reference: include/qneuron.hpp:128)."""
        q = self.qreg
        if reset_init:
            q.SetBit(self.output_index, False)
            q.RY(math.pi / 2, self.output_index)
        ang = self._activated()
        if not self.input_indices:
            q.RY(float(ang[0]), self.output_index)
        else:
            q.UniformlyControlledRY(self.input_indices, self.output_index, ang)
        prob = q.Prob(self.output_index)
        return prob if expected else (1.0 - prob)

    def Unpredict(self, expected: bool = True) -> float:
        """Uncompute Predict (reference: include/qneuron.hpp:196)."""
        q = self.qreg
        ang = -self._activated()
        if not self.input_indices:
            q.RY(float(ang[0]), self.output_index)
        else:
            q.UniformlyControlledRY(self.input_indices, self.output_index, ang)
        prob = q.Prob(self.output_index)
        return prob if expected else (1.0 - prob)

    def LearnCycle(self, expected: bool = True) -> float:
        """Predict + Unpredict probe (reference: include/qneuron.hpp:253)."""
        result = self.Predict(expected, reset_init=False)
        self.Unpredict(expected)
        return result

    def Learn(self, eta: float, expected: bool = True, reset_init: bool = True) -> None:
        """Nudge every permutation angle (reference: include/qneuron.hpp:269
        — Predict, Unpredict, then probe each permutation)."""
        start = self.Predict(expected, reset_init)
        self.Unpredict(expected)
        if start >= 1.0 - self.tolerance:
            return
        for perm in range(len(self.angles)):
            start = self._learn_internal(expected, eta, perm, start)
            if start >= 1.0 - self.tolerance:
                break

    def LearnPermutation(self, eta: float, expected: bool = True,
                         reset_init: bool = True) -> None:
        """Nudge only the angle of the measured input permutation
        (reference: include/qneuron.hpp:295 — collapsing M on the
        inputs selects an actually-sampled basis state)."""
        start = self.Predict(expected, reset_init)
        self.Unpredict(expected)
        perm = 0
        for j, idx in enumerate(self.input_indices):
            if self.qreg.M(idx):
                perm |= 1 << j
        self._learn_internal(expected, eta, perm, start)

    def _learn_internal(self, expected: bool, eta: float, perm: int,
                        start_prob: float) -> float:
        orig = self.angles[perm]
        self.angles[perm] = orig + eta * math.pi
        plus = self.LearnCycle(expected)
        if plus > start_prob + self.tolerance:
            return plus
        self.angles[perm] = orig - eta * math.pi
        minus = self.LearnCycle(expected)
        if minus > start_prob + self.tolerance:
            return minus
        self.angles[perm] = orig
        return start_prob
