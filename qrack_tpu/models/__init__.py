from . import qft  # noqa: F401
from . import algorithms  # noqa: F401
from . import rcs  # noqa: F401
