from . import qft  # noqa: F401
