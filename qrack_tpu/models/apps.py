"""Physics/optimization application drivers over the QInterface API.

Counterparts of the reference's application scripts (reference:
scripts/tfim_* and ising_depth_series.py — transverse-field Ising
magnetization series; scripts/maxcut_* — QAOA max-cut; scripts/qrng.py
— hardware-style random bits).  Each function drives a user-supplied
simulator through the public gate surface only, so any layer stack
(QUnit, stabilizer hybrid, pager, TPU engine) can run them.
"""

from __future__ import annotations

import math
from typing import List, Sequence, Tuple

import numpy as np

from ..hamiltonian import HamiltonianOp, uniform_hamiltonian_op
from .. import matrices as mat


def tfim_hamiltonian(n: int, j_coupling: float, h_field: float):
    """Open-chain transverse-field Ising H = -J sum Z_i Z_{i+1}
    - h sum X_i as TimeEvolve terms: each ZZ bond is a uniform
    controlled term (payload -J*Z or +J*Z by the control bit), each
    field term a bare -h*X generator."""
    ham: List[HamiltonianOp] = []
    Z = np.asarray(mat.Z2)
    X = np.asarray(mat.X2)
    for i in range(n - 1):
        ham.append(uniform_hamiltonian_op(
            (i,), i + 1, np.stack([-j_coupling * Z, j_coupling * Z])))
    for i in range(n):
        ham.append(HamiltonianOp(target=i, matrix=-h_field * X))
    return ham


def tfim_magnetization_series(qsim, j_coupling: float, h_field: float,
                              dt: float, steps: int) -> List[float]:
    """Trotterized quench from |0...n>: per step, TimeEvolve by dt and
    record the mean magnetization <Z> = 1 - 2*mean(Prob)."""
    n = qsim.GetQubitCount()
    ham = tfim_hamiltonian(n, j_coupling, h_field)
    out = []
    for _ in range(steps):
        qsim.TimeEvolve(ham, dt)
        # all n marginals from ONE full-state pass (n separate Prob(q)
        # calls would each rescan the 2^n amplitudes)
        p = np.asarray(qsim.GetProbs())
        idx = np.arange(p.size)
        mz = sum(1.0 - 2.0 * float(p[((idx >> q) & 1) == 1].sum())
                 for q in range(n))
        out.append(mz / n)
    return out


def qaoa_maxcut_expectation(qsim_factory, edges: Sequence[Tuple[int, int]],
                            n: int, gammas: Sequence[float],
                            betas: Sequence[float]) -> float:
    """Expected cut value of the depth-p QAOA state: cost unitaries are
    ZZ phase rotations (CNOT - RZ - CNOT), the mixer is RX on every
    qubit; <cut> = sum_edges (1 - <Z_a Z_b>)/2 via two-qubit joint
    probabilities."""
    q = qsim_factory(n)
    for i in range(n):
        q.H(i)
    for g, b in zip(gammas, betas):
        for (a, c) in edges:
            q.CNOT(a, c)
            q.RZ(2.0 * g, c)
            q.CNOT(a, c)
        for i in range(n):
            q.RX(2.0 * b, i)
    # every edge's <Z_a Z_b> from ONE full-state pass (per-edge ProbMask
    # calls would each re-densify and rescan the 2^n amplitudes)
    p = np.asarray(q.GetProbs())
    idx = np.arange(p.size)
    total = 0.0
    for (a, c) in edges:
        differ = ((idx >> a) ^ (idx >> c)) & 1
        total += float(p[differ == 1].sum())
    return total


def qaoa_maxcut_grid(qsim_factory, edges, n: int, p: int = 1,
                     resolution: int = 8) -> Tuple[float, Tuple]:
    """Coarse grid search over (gamma, beta)^p (the reference script
    optimizes classically too); returns (best expected cut, angles)."""
    grid = [math.pi * (k + 0.5) / resolution for k in range(resolution)]
    # greedy layer-by-layer extension keeps the search tiny (p=1 is
    # simply one greedy layer = the exhaustive (gamma, beta) grid);
    # the grid has no ~identity angles, so a deeper layer can only
    # hurt — stop (and keep the shallower answer) when it does
    best, best_angles = -1.0, None
    gs: List[float] = []
    bs: List[float] = []
    for _ in range(p):
        layer_best, pick = -1.0, None
        for g in grid:
            for b in grid:
                v = qaoa_maxcut_expectation(
                    qsim_factory, edges, n, gs + [g], bs + [b])
                if v > layer_best:
                    layer_best, pick = v, (g, b)
        if layer_best <= best:
            break
        gs.append(pick[0])
        bs.append(pick[1])
        best, best_angles = layer_best, (tuple(gs), tuple(bs))
    return best, best_angles


def brute_force_maxcut(edges, n: int) -> int:
    best = 0
    for s in range(1 << n):
        cut = sum(1 for (a, b) in edges if ((s >> a) ^ (s >> b)) & 1)
        best = max(best, cut)
    return best


def qrng_bits(qsim_factory, n_bits: int, width: int = 8) -> List[int]:
    """Measurement-based random bits, `width` at a time (reference:
    scripts/qrng.py)."""
    out: List[int] = []
    while len(out) < n_bits:
        q = qsim_factory(width)
        for i in range(width):
            q.H(i)
        v = q.MAll()
        out.extend(((v >> i) & 1) for i in range(width))
    return out[:n_bits]
