"""Fused random-circuit-sampling programs — the RCS headline benchmark.

The reference's RCS benchmarks dispatch one kernel per gate (reference:
test/benchmarks.cpp:4141 test_random_circuit_sampling_nn — random
sqrt-root layers + brick-wall ISwap couplers). TPU-native, a whole
depth-d circuit traces into one XLA executable: single-qubit roots are
plane-mixing 2x2 contractions, couplers are one 4x4 contraction each,
and XLA fuses across layers.
"""

from __future__ import annotations

import numpy as np

import jax
from ..utils.compat import shard_map as _compat_shard_map

from .. import matrices as mat
from ..ops import gatekernels as gk
from ..utils.rng import QrackRandom

_ISWAP4 = np.array(
    [[1, 0, 0, 0], [0, 0, 1j, 0], [0, 1j, 0, 0], [0, 0, 0, 1]],
    dtype=np.complex128,
)
_ROOTS = (mat.SQRTX2, mat.SQRTY2, mat.SQRTW2)


def rcs_layers(n: int, depth: int, seed: int):
    """Deterministic gate plan: per layer, a random root per qubit and the
    brick-wall ISwap pairing (matches models.algorithms.random_circuit_sampling)."""
    rng = QrackRandom(seed)
    plan = []
    for d in range(depth):
        roots = [rng.randint(0, 3) for _ in range(n)]
        off = d & 1
        pairs = [(q, q + 1) for q in range(off, n - 1, 2)]
        plan.append((roots, pairs))
    return plan


def rcs_qcircuit(n: int, depth: int, seed: int):
    """The RCS gate plan as a ``QCircuit`` gate list — the form the
    noisy trajectory engine lowers (qrack_tpu/noise/trajectories.py).
    ``QCircuitGate`` is a controlled-1q payload model, so the brick-wall
    couplers are CZ instead of ISwap: same entangling topology,
    payload-representable."""
    from ..layers.qcircuit import QCircuit

    cz = mat.phase_mtrx(1.0, -1.0)
    c = QCircuit(n)
    for roots, pairs in rcs_layers(n, depth, seed):
        for q, g in enumerate(roots):
            c.append_1q(q, _ROOTS[g])
        for a, b in pairs:
            c.append_ctrl((a,), b, cz, 1)
    return c


def _iswap_layer(planes, n: int, pairs):
    """A whole brick-wall ISwap layer as ONE transpose + ONE phase pass.

    ISwap = SWAP . diag(1, i, i, 1): disjoint pairs make the layer's
    permutation part a product of adjacent bit-axis swaps (a single
    jnp.transpose) and its phase part i^(number of pairs whose bits
    differ) — one fused elementwise multiply.  Collapses the
    reference's kernel-per-coupler chain (test/benchmarks.cpp:4141) to
    2 HBM passes per layer instead of n/2 4x4 contractions, and shrinks
    the traced program accordingly (tunnel compile time scales with op
    count)."""
    import jax.numpy as jnp

    shape = (2,) + (2,) * n
    perm = list(range(n + 1))
    for (a, b) in pairs:
        pa, pb = n - a, n - b  # C-order: axis k holds bit n - k
        perm[pa], perm[pb] = perm[pb], perm[pa]
    out = planes.reshape(shape).transpose(perm).reshape(2, -1)
    idx = gk.iota_for(out)
    k = None
    for (a, b) in pairs:
        t = ((idx >> a) ^ (idx >> b)) & 1
        k = t if k is None else k + t
    k = k & 3
    re = jnp.asarray([1.0, 0.0, -1.0, 0.0], dtype=planes.dtype)[k]
    im = jnp.asarray([0.0, 1.0, 0.0, -1.0], dtype=planes.dtype)[k]
    return gk.cmul(re, im, out)


def _cluster_mats(roots, k: int):
    """Kron the layer's single-qubit roots into per-cluster 2^k x 2^k
    matrices over CONTIGUOUS qubit spans (all roots in a layer act on
    disjoint qubits, so grouping is exact).  np.kron(next, acc) keeps
    the earlier qubit least significant, matching the index convention."""
    out = []
    for c0 in range(0, len(roots), k):
        ms = [_ROOTS[g] for g in roots[c0:c0 + k]]
        acc = ms[0]
        for m in ms[1:]:
            acc = np.kron(m, acc)
        out.append((c0, len(ms), acc))
    return out


def resolve_fuse_qb(n: int, fuse_qb: int | None = None) -> int:
    """Single source of truth for the root-cluster width (also used by
    bench.py's HBM-pass model, so the two can never drift)."""
    import os

    if fuse_qb is None:
        fuse_qb = int(os.environ.get("QRACK_RCS_FUSE_QB", "6"))
    return max(1, min(fuse_qb, n))


def make_rcs_fn(n: int, depth: int, seed: int, fuse_qb: int | None = None):
    """Jittable single-chip whole-RCS program over (2, 2^n) planes.

    Root layers fuse into 2^k-wide cluster contractions (one HBM pass
    per cluster instead of per qubit; the reference dispatches one
    kernel per gate, test/benchmarks.cpp:4141).  k defaults to
    QRACK_RCS_FUSE_QB (6 -> 64-wide MXU matmuls); k=1 recovers the
    per-gate program."""
    fuse_qb = resolve_fuse_qb(n, fuse_qb)
    plan = rcs_layers(n, depth, seed)
    baked = [(_cluster_mats(roots, fuse_qb), pairs)
             for (roots, pairs) in plan]

    def fn(planes):
        for (clusters, pairs) in baked:
            for (c0, w, m) in clusters:
                mp = gk.mtrx_planes(m, planes.dtype)
                planes = gk.apply_kxk(planes, mp, n, c0, w)
            if pairs:
                planes = _iswap_layer(planes, n, pairs)
        return planes

    return fn


def make_sharded_rcs_fn(mesh, n: int, depth: int, seed: int,
                        fuse_qb: int | None = None):
    """Whole-RCS program over a ket sharded across the 'pages' mesh axis
    (BASELINE target 4's RCS counterpart to make_sharded_qft_fn).

    Per brick-wall layer, the coupler set splits by geometry:
      * pairs fully below the page boundary: in-page transpose + phase
        (no communication, same as single-chip);
      * the one pair straddling bit L-1/L: one `lax.ppermute` partner
        exchange + an axis flip + select (the SWAP part) with the ISwap
        i-phase on the moved half;
      * pairs fully in page bits: a pure page permutation (ppermute)
        plus a per-page scalar phase.
    Root clusters apply per page on local axes; clusters are capped at
    the local width so they never straddle the boundary."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    npg = mesh.devices.size
    g = npg.bit_length() - 1
    L = n - g
    assert (1 << g) == npg, "page count must be a power of two"
    assert L >= 1, "at least one local qubit per page"
    k = min(resolve_fuse_qb(n, fuse_qb), L)
    plan = rcs_layers(n, depth, seed)
    sharding = NamedSharding(mesh, P(None, "pages"))

    def body(local):
        from ..ops import sharded as shb

        pid = jax.lax.axis_index("pages")
        dt = local.dtype
        for (roots, pairs) in plan:
            # roots: local spans cluster per page; a paged qubit's root
            # rides the existing half-buffer pair exchange
            for (c0, w, m) in _cluster_mats(roots[:L], k):
                local = gk.apply_kxk(local, gk.mtrx_planes(m, dt), L, c0, w)
            for q in range(L, n):
                mp = gk.mtrx_planes(_ROOTS[roots[q]], dt)
                local = shb.apply_global_2x2(local, mp, npg, q - L,
                                             0, 0, 0, 0)
            if not pairs:
                continue
            idx = gk.iota_for(local)
            loc_pairs = [(a, b) for (a, b) in pairs if b < L]
            straddle = [(a, b) for (a, b) in pairs if a < L <= b]
            page_pairs = [(a, b) for (a, b) in pairs if a >= L]
            if loc_pairs:
                local = _iswap_layer(local, L, loc_pairs)
            for (a, b) in straddle:   # a == L-1, b == L by construction
                gpos = b - L
                perm = [(j, j ^ (1 << gpos)) for j in range(npg)]
                partner = jax.lax.ppermute(local, "pages", perm)
                pb = (pid >> gpos) & 1
                bl = (idx >> a) & 1
                flipped = jnp.flip(
                    partner.reshape(2, 1 << (L - 1 - a), 2, 1 << a),
                    axis=2).reshape(2, -1)
                moved = gk.cmul(jnp.zeros((), dt), jnp.ones((), dt), flipped)
                local = jnp.where(bl == pb, local, moved)
            for (a, b) in page_pairs:
                ga, gb = a - L, b - L
                swap_map = []
                for j in range(npg):
                    ba, bb = (j >> ga) & 1, (j >> gb) & 1
                    t = j & ~((1 << ga) | (1 << gb))
                    swap_map.append((j, t | (bb << ga) | (ba << gb)))
                local = jax.lax.ppermute(local, "pages", swap_map)
                diff = ((pid >> ga) ^ (pid >> gb)) & 1
                local = jnp.where(diff == 1,
                                  gk.cmul(jnp.zeros((), dt), jnp.ones((), dt),
                                          local),
                                  local)
        return local

    fn = jax.jit(
        _compat_shard_map(body, mesh=mesh, in_specs=P(None, "pages"),
                      out_specs=P(None, "pages")),
        donate_argnums=(0,),
    )
    return fn, sharding


def reference_rcs_state(n: int, depth: int, seed: int, engine) -> np.ndarray:
    """Same plan through a gate-at-a-time engine (parity checking)."""
    plan = rcs_layers(n, depth, seed)
    for (roots, pairs) in plan:
        for q, g in enumerate(roots):
            engine.Mtrx(_ROOTS[g], q)
        for (a, b) in pairs:
            engine.Apply4x4(_ISWAP4, a, b)
    return np.asarray(engine.GetQuantumState())
