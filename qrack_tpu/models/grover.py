"""Fused Grover-search program — the loop-structured headline workload.

The reference benchmarks Grover as gate-at-a-time engine calls
(reference: test/benchmarks.cpp test_grover_search; examples/
grovers.cpp drives QInterface H/PhaseFlip per iteration).  TPU-native,
one Grover ITERATION traces into a handful of fused passes (oracle
phase flip + H-ladder + |0> phase flip + H-ladder) and the O(sqrt(N))
iteration count rides `jax.lax.fori_loop` — the compiled HLO is
constant-size no matter how many iterations run, the loop stays on
device, and XLA fuses the phase flips into the neighbouring H
contractions.  H-ladders use 2^k-wide cluster contractions
(H^(x)k kron blocks on the MXU) like models.rcs.

This is the repo's canonical example of XLA-semantics design: a
data-independent loop belongs in `lax.fori_loop`, not unrolled trace
(contrast the QFT, whose per-stage angles differ and therefore unroll).
"""

from __future__ import annotations

import math

import numpy as np

import jax
from ..utils.compat import shard_map as _compat_shard_map
import jax.numpy as jnp

from .. import matrices as mat
from ..ops import gatekernels as gk


def grover_iterations(n: int) -> int:
    """floor(pi/4 * sqrt(N)) — the optimal rotation count."""
    return int(math.floor(math.pi / 4.0 * math.sqrt(float(1 << n))))


# H-ladder cluster width (single source of truth — bench.py's HBM-pass
# model imports this so the two cannot drift)
FUSE_QB = 6


def _h_clusters(n: int, k: int, dtype):
    """H^(x)w kron blocks covering [0, n) in spans of width <= k."""
    out = []
    for c0 in range(0, n, k):
        w = min(k, n - c0)
        acc = np.asarray(mat.H2)
        for _ in range(w - 1):
            acc = np.kron(np.asarray(mat.H2), acc)
        out.append((c0, w, gk.mtrx_planes(acc, dtype)))
    return out


def make_grover_fn(n: int, target: int, iters: int | None = None,
                   fuse_qb: int = FUSE_QB):
    """Jittable whole-search program over (2, 2^n) planes: prepare the
    uniform superposition, then fori_loop the Grover iteration.  Returns
    (fn, iters)."""
    if iters is None:
        iters = grover_iterations(n)
    target &= (1 << n) - 1
    k = max(1, min(fuse_qb, n))

    def fn(planes):
        clusters = _h_clusters(n, k, planes.dtype)
        idx = gk.iota_for(planes)
        oracle = jnp.where(idx == target, -1.0, 1.0).astype(planes.dtype)
        zflip = jnp.where(idx == 0, -1.0, 1.0).astype(planes.dtype)

        def h_all(p):
            for (c0, w, mp) in clusters:
                p = gk.apply_kxk(p, mp, n, c0, w)
            return p

        def iteration(_, p):
            p = p * oracle              # phase oracle on |target>
            p = h_all(p)
            p = p * zflip               # diffusion = H ladder . flip|0> . H ladder
            return h_all(p)

        planes = h_all(planes)          # uniform superposition from |0>
        return jax.lax.fori_loop(0, iters, iteration, planes)

    return fn, iters


def success_probability(planes, target: int) -> float:
    p = planes[0] ** 2 + planes[1] ** 2
    return float(p[target] / p.sum())


def make_sharded_grover_fn(mesh, n: int, target: int,
                           iters: int | None = None, fuse_qb: int = FUSE_QB):
    """Grover over a ket sharded across the 'pages' mesh axis: local
    H-clusters per page, paged H bits via the half-buffer pair exchange,
    phase flips from split (local, page) index reads — all inside the
    same `lax.fori_loop` body, so the HLO stays constant-size and the
    per-iteration collectives ride ICI.  Returns (fn, sharding, iters)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..ops import sharded as shb

    npg = mesh.devices.size
    g = npg.bit_length() - 1
    L = n - g
    assert (1 << g) == npg, "page count must be a power of two"
    assert L >= 1
    if iters is None:
        iters = grover_iterations(n)
    target &= (1 << n) - 1
    t_lo, t_hi = target & ((1 << L) - 1), target >> L
    k = max(1, min(fuse_qb, L))

    def body(local):
        pid = jax.lax.axis_index("pages")
        dt = local.dtype
        hmp2 = gk.mtrx_planes(np.asarray(mat.H2), dt)
        clusters = _h_clusters(L, k, dt)
        idx = gk.iota_for(local)
        is_t = (idx == t_lo) & (pid == t_hi)
        oracle = jnp.where(is_t, -1.0, 1.0).astype(dt)
        is_0 = (idx == 0) & (pid == 0)
        zflip = jnp.where(is_0, -1.0, 1.0).astype(dt)

        def h_all(p):
            for (c0, w, mp) in clusters:
                p = gk.apply_kxk(p, mp, L, c0, w)
            for q in range(L, n):
                p = shb.apply_global_2x2(p, hmp2, npg, q - L, 0, 0, 0, 0)
            return p

        def iteration(_, p):
            p = p * oracle
            p = h_all(p)
            p = p * zflip
            return h_all(p)

        return jax.lax.fori_loop(0, iters, iteration, h_all(local))

    fn = jax.jit(
        _compat_shard_map(body, mesh=mesh, in_specs=P(None, "pages"),
                      out_specs=P(None, "pages")),
        donate_argnums=(0,),
    )
    return fn, NamedSharding(mesh, P(None, "pages")), iters
