"""Whole-circuit QFT programs: the flagship fused workload.

The reference dispatches one GPU kernel per gate (reference:
test/benchmarks.cpp test_qft_* drive QInterface::QFT gate by gate).
TPU-native, the entire circuit is traced into ONE XLA program — the
n H-gates and n(n-1)/2 controlled phases unroll at trace time into a
single fused executable (the reference's QueueItem chain becomes jit
tracing, SURVEY.md §7 step 4), and the sharded variant runs the same
program per page with ppermute pair exchanges over ICI for paged-qubit
targets (reference: src/qpager.cpp:400-447 host-staged ShuffleBuffers).

Gate order matches QInterface::QFT (reference:
src/qinterface/qinterface.cpp:114) so results are bit-for-bit
comparable with the gate-at-a-time path.
"""

from __future__ import annotations

import cmath
import math
import os

import numpy as np

import jax
from ..utils.compat import shard_map as _compat_shard_map
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import gatekernels as gk


def _h_mp(dtype):
    s = 1.0 / math.sqrt(2.0)
    re = jnp.asarray([[s, s], [s, -s]], dtype=dtype)
    return jnp.stack([re, jnp.zeros_like(re)])


def _stage_phase(planes, pairs):
    """ONE fused elementwise pass applying a whole stage's controlled
    phases: diagonal gates commute, so their product is a single
    exp(i*theta(idx)) with theta = sum over (c, t, ang) of
    ang * bit_c(idx) * bit_t(idx).  Collapsing the reference's
    kernel-per-gate chain (test/benchmarks.cpp test_qft_*) to one HBM
    pass per stage bounds both traffic and XLA temp pressure at
    O(n) passes for the whole QFT instead of O(n^2)."""
    acc = jnp.float64 if planes.dtype == jnp.float64 else jnp.float32
    idx = gk.iota_for(planes)
    theta = jnp.zeros(planes.shape[-1], dtype=acc)
    for c, t, ang in pairs:
        on = ((idx >> c) & (idx >> t) & 1).astype(acc)
        theta = theta + on * acc(ang)
    fre = jnp.cos(theta).astype(planes.dtype)
    fim = jnp.sin(theta).astype(planes.dtype)
    return gk.cmul(fre, fim, planes)


def qft_planes(planes, n: int):
    """Single-shard QFT over all n qubits (pure, trace-safe)."""
    hm = _h_mp(planes.dtype)
    end = n - 1
    for i in range(n):
        h_bit = end - i
        if i:
            planes = _stage_phase(planes, [
                (h_bit, h_bit + 1 + j, math.pi / (1 << (j + 1)))
                for j in range(i)])
        planes = gk.apply_2x2(planes, hm, n, h_bit)
    return planes


def iqft_planes(planes, n: int):
    hm = _h_mp(planes.dtype)
    for i in range(n):
        if i:
            planes = _stage_phase(planes, [
                (i - (j + 1), i, -math.pi / (1 << (j + 1)))
                for j in range(i)])
        planes = gk.apply_2x2(planes, hm, n, i)
    return planes


def _carried_phase(planes, frac, h_bit: int, sign: float):
    """One stage's controlled phases from the carried fraction:
    theta(idx) = sign * pi * bit_h(idx) * frac(idx)."""
    acc = frac.dtype
    idx = gk.iota_for(planes)
    on = ((idx >> h_bit) & 1).astype(acc)
    theta = jnp.asarray(sign * math.pi, dtype=acc) * on * frac
    return gk.cmul(jnp.cos(theta).astype(planes.dtype),
                   jnp.sin(theta).astype(planes.dtype), planes)


def qft_planes_fast(planes, n: int, inverse: bool = False):
    """O(n)-op QFT: stage i's angle sum  sum_j bit_{h+1+j} * pi/2^(j+1)
    obeys the exact recurrence  frac_h = (frac_{h+1} + bit_{h+1}) / 2,
    so one carried (2^n,) fraction array replaces the per-stage O(i)
    term sums of `_stage_phase` — the traced HLO shrinks from O(n^2) to
    O(n) ops (an ~n-fold compile-time cut, critical over a remote-compile
    tunnel) at the cost of one extra array's HBM traffic per stage.
    Bit-for-bit the same gate order as qft_planes/iqft_planes
    (reference: QInterface::QFT, src/qinterface/qinterface.cpp:114);
    f32 carried fractions add <= 2^-24 relative angle error."""
    hm = _h_mp(planes.dtype)
    acc = jnp.float64 if planes.dtype == jnp.float64 else jnp.float32
    idx = gk.iota_for(planes)
    frac = jnp.zeros(planes.shape[-1], dtype=acc)
    end = n - 1
    for i in range(n):
        h_bit = i if inverse else end - i
        if i:
            prev = h_bit - 1 if inverse else h_bit + 1
            pb = ((idx >> prev) & 1).astype(acc)
            frac = (frac + pb) * acc(0.5)
            planes = _carried_phase(planes, frac, h_bit,
                                    -1.0 if inverse else 1.0)
        planes = gk.apply_2x2(planes, hm, n, h_bit)
    return planes


# Above this width the O(n^2)-op unrolled programs compile slowly enough
# (especially via the axon remote-compile tunnel) that the O(n)-op
# carried-fraction form wins overall; exact-same gate order either way.
FAST_COMPILE_QB = int(os.environ.get("QRACK_QFT_FAST_QB", "23"))


def default_fast(n: int) -> bool:
    """Platform-aware default: the carried-fraction form trades ~14%
    runtime (one extra array's HBM traffic per stage, measured at w24
    on CPU-XLA) for an ~n-fold smaller HLO.  That trade only pays where
    compilation is expensive — accelerators behind the remote-compile
    tunnel — so CPU backends keep the unrolled form UNLESS the operator
    set QRACK_QFT_FAST_QB explicitly (an explicit threshold wins on
    every backend; otherwise the knob would be dead on CPU).  The env
    var is re-read here so a threshold set after import is honored."""
    env = os.environ.get("QRACK_QFT_FAST_QB")
    threshold = int(env) if env is not None else FAST_COMPILE_QB
    if n < threshold:
        return False
    if env is not None:
        return True
    return jax.default_backend() != "cpu"


def make_qft_fn(n: int, inverse: bool = False, fast: bool | None = None):
    """Jittable single-chip whole-QFT program over (2, 2^n) planes."""
    if fast is None:
        fast = default_fast(n)
    if fast:
        return lambda planes: qft_planes_fast(planes, n, inverse)
    body = iqft_planes if inverse else qft_planes

    def fn(planes):
        return body(planes, n)

    return fn


def qft_qcircuit(n: int, inverse: bool = False):
    """The same QFT as :func:`qft_planes` but as a QCircuit gate-IR
    object — the form the serving layer batches (QCircuit.shape_key /
    compile_batched_fn).  Gate order matches QInterface::QFT exactly
    (reference: src/qinterface/qinterface.cpp:114), so states are
    bit-for-bit comparable with every other QFT path here."""
    from ..layers.qcircuit import QCircuit
    from .. import matrices as mat

    circ = QCircuit(n)
    end = n - 1
    for i in range(n):
        h_bit = i if inverse else end - i
        if i:
            for j in range(i):
                other = h_bit - 1 - j if inverse else h_bit + 1 + j
                ang = (-1.0 if inverse else 1.0) * math.pi / (1 << (j + 1))
                circ.append_ctrl((other,), h_bit,
                                 mat.phase_mtrx(1.0, cmath.exp(1j * ang)), 1)
        circ.append_1q(h_bit, mat.H2)
    return circ


# ---------------------------------------------------------------------------
# sharded whole-circuit program (pages mesh axis)
# ---------------------------------------------------------------------------

def _sharded_h(local, hm, L, npg, target):
    """H inside the shard_map body: local target applies per page; paged
    target rides the pager's half-buffer pair exchange (each ppermute
    payload is half a page — never ship a whole page; reference
    discipline: ShuffleBuffers, src/qpager.cpp:400-447)."""
    if target < L:
        return gk.apply_2x2(local, hm, L, target)
    from ..ops import sharded as shb

    return shb.apply_global_2x2(local, hm, npg, target - L, 0, 0, 0, 0)


def _sharded_stage_phase(local, L, pairs):
    """Whole stage of controlled phases as ONE collective-free
    elementwise pass (split local/page bit reads; see _stage_phase)."""
    pid = jax.lax.axis_index("pages")
    idx = gk.iota_for(local)

    def gbit(b):
        return ((idx >> b) & 1) if b < L else ((pid >> (b - L)) & 1)

    acc = jnp.float64 if local.dtype == jnp.float64 else jnp.float32
    theta = jnp.zeros(local.shape[-1], dtype=acc)
    for c, t, ang in pairs:
        on = (gbit(c) & gbit(t)).astype(acc)
        theta = theta + on * acc(ang)
    fre = jnp.cos(theta).astype(local.dtype)
    fim = jnp.sin(theta).astype(local.dtype)
    return gk.cmul(fre, fim, local)


def make_sharded_qft_fn(mesh: Mesh, n: int, inverse: bool = False,
                        fast: bool | None = None):
    """One jitted program: full QFT over a ket sharded across the 'pages'
    mesh axis — in-page math per device, ppermute over ICI for paged
    targets. Returns (fn, sharding).  `fast` selects the O(n)-op
    carried-fraction form (see qft_planes_fast); the recurrence reads
    each stage's previous bit from the local index or the page id, so it
    is mesh-shape agnostic like the unrolled form."""
    npg = mesh.devices.size
    g = npg.bit_length() - 1
    L = n - g
    assert (1 << g) == npg, "page count must be a power of two"
    if fast is None:
        fast = default_fast(n)
    sharding = NamedSharding(mesh, P(None, "pages"))

    def _gbit(local, b: int):
        if b < L:
            return (gk.iota_for(local) >> b) & 1
        return (jax.lax.axis_index("pages") >> (b - L)) & 1

    def body(local):
        hm = _h_mp(local.dtype)
        end = n - 1
        if fast:
            acc = jnp.float64 if local.dtype == jnp.float64 else jnp.float32
            frac = jnp.zeros(local.shape[-1], dtype=acc)
            for i in range(n):
                h_bit = i if inverse else end - i
                if i:
                    prev = h_bit - 1 if inverse else h_bit + 1
                    frac = (frac + _gbit(local, prev).astype(acc)) * acc(0.5)
                    on = _gbit(local, h_bit).astype(acc)
                    theta = (jnp.asarray(-math.pi if inverse else math.pi,
                                         dtype=acc) * on * frac)
                    local = gk.cmul(jnp.cos(theta).astype(local.dtype),
                                    jnp.sin(theta).astype(local.dtype), local)
                local = _sharded_h(local, hm, L, npg, h_bit)
            return local
        if not inverse:
            for i in range(n):
                h_bit = end - i
                if i:
                    local = _sharded_stage_phase(local, L, [
                        (h_bit, h_bit + 1 + j, math.pi / (1 << (j + 1)))
                        for j in range(i)])
                local = _sharded_h(local, hm, L, npg, h_bit)
        else:
            for i in range(n):
                if i:
                    local = _sharded_stage_phase(local, L, [
                        (i - (j + 1), i, -math.pi / (1 << (j + 1)))
                        for j in range(i)])
                local = _sharded_h(local, hm, L, npg, i)
        return local

    fn = jax.jit(
        _compat_shard_map(body, mesh=mesh, in_specs=P(None, "pages"), out_specs=P(None, "pages")),
        donate_argnums=(0,),
    )
    return fn, sharding


def basis_planes(n: int, perm: int, sharding=None, dtype=jnp.float32):
    """|perm> as (2, 2^n) planes, optionally sharded."""
    st = jnp.zeros((2, 1 << n), dtype=dtype).at[0, perm].set(1.0)
    if sharding is not None:
        st = jax.device_put(st, sharding)
    return st
