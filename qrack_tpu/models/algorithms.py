"""Algorithm workloads over any QInterface stack.

TPU-native counterparts of the reference teaching programs (reference:
examples/grovers.cpp, teleport.cpp, shors_factoring.cpp,
quantum_volume.cpp, test/benchmarks.cpp GHZ/RCS cases)."""

from __future__ import annotations

import math
from fractions import Fraction
from typing import Optional, Tuple

import numpy as np


def ghz(qsim, n: Optional[int] = None) -> None:
    """GHZ preparation (reference: test/benchmarks.cpp:531)."""
    n = n if n is not None else qsim.GetQubitCount()
    qsim.H(0)
    for i in range(n - 1):
        qsim.CNOT(i, i + 1)


def grover_search(qsim, target: int, n: Optional[int] = None) -> int:
    """Grover search for |target> via phase-flip oracle (reference:
    examples/grovers.cpp:1-68 — same oracle construction from
    PhaseFlipIfLess pairs). Returns the measured index."""
    n = n if n is not None else qsim.GetQubitCount()
    for i in range(n):
        qsim.H(i)
    iters = int(math.floor(math.pi / 4 * math.sqrt(1 << n)))
    for _ in range(iters):
        qsim.PhaseFlipIfLess(target + 1, 0, n)
        qsim.PhaseFlipIfLess(target, 0, n)
        for i in range(n):
            qsim.H(i)
        qsim.PhaseFlipIfLess(1, 0, n)
        for i in range(n):
            qsim.H(i)
    return qsim.MAll()


def teleport(qsim, prepare=None) -> Tuple[float, float]:
    """Teleport qubit 0 onto qubit 2 (reference: examples/teleport.cpp).
    Returns (payload P(1) before, target P(1) after)."""
    if prepare is not None:
        prepare(qsim)
    before = qsim.Prob(0)
    qsim.H(1)
    qsim.CNOT(1, 2)
    qsim.CNOT(0, 1)
    qsim.H(0)
    m0 = qsim.M(0)
    m1 = qsim.M(1)
    if m1:
        qsim.X(2)
    if m0:
        qsim.Z(2)
    return before, qsim.Prob(2)


def shor_order_find(qsim, base: int, to_factor: int, width: int) -> Optional[int]:
    """One period-finding round of Shor's algorithm (reference:
    examples/shors_factoring.cpp:98-160). Needs 2*width qubits.
    Returns a nontrivial factor or None."""
    qsim.SetPermutation(0)
    for i in range(width):
        qsim.H(i)
    qsim.POWModNOut(base, to_factor, 0, width, width)
    qsim.IQFT(0, width)
    y = qsim.MReg(0, width)
    if y == 0:
        return None
    # continued-fraction reconstruction of the order
    frac = Fraction(y, 1 << width).limit_denominator(to_factor)
    r = frac.denominator
    if r % 2:
        r *= 2
    apow = pow(base, r // 2, to_factor)
    f1 = math.gcd(apow + 1, to_factor)
    f2 = math.gcd(apow - 1, to_factor)
    for f in (f1, f2):
        if 1 < f < to_factor and to_factor % f == 0:
            return f
    return None


def random_circuit_sampling(qsim, depth: int, rng, n: Optional[int] = None) -> None:
    """Nearest-neighbor RCS layer structure (reference:
    test/benchmarks.cpp:4141 test_random_circuit_sampling_nn): random
    single-qubit roots + brick-wall couplers."""
    n = n if n is not None else qsim.GetQubitCount()
    for d in range(depth):
        for q in range(n):
            g = rng.randint(0, 3)
            if g == 0:
                qsim.SqrtX(q)
            elif g == 1:
                qsim.SqrtY(q)
            else:
                qsim.SqrtW(q)
        off = d & 1
        for q in range(off, n - 1, 2):
            qsim.ISwap(q, q + 1)


def quantum_volume(qsim, depth: Optional[int] = None, rng=None) -> int:
    """QV-style circuit: `depth` rounds of random SU(4)-ish blocks on a
    random qubit pairing (reference: examples/quantum_volume.cpp:1-110).
    Returns the heavy-output count proxy (measured value)."""
    if rng is None:
        rng = qsim.rng
    n = qsim.GetQubitCount()
    depth = depth if depth is not None else n
    for _ in range(depth):
        perm = list(range(n))
        for i in range(n - 1, 0, -1):
            j = rng.randint(0, i + 1)
            perm[i], perm[j] = perm[j], perm[i]
        for k in range(0, n - 1, 2):
            a, b = perm[k], perm[k + 1]
            for q in (a, b):
                qsim.U(q, rng.rand() * math.pi, rng.rand() * 2 * math.pi,
                       rng.rand() * 2 * math.pi)
            qsim.CNOT(a, b)
            for q in (a, b):
                qsim.U(q, rng.rand() * math.pi, rng.rand() * 2 * math.pi,
                       rng.rand() * 2 * math.pi)
    return qsim.MAll()


def xeb_fidelity(probs_ideal: np.ndarray, samples) -> float:
    """Linear cross-entropy benchmark fidelity (reference:
    test_universal_circuit_digital_cross_entropy, test/benchmarks.cpp:4560)."""
    d = probs_ideal.shape[0]
    mean_p = float(np.mean([probs_ideal[int(s)] for s in samples]))
    return d * mean_p - 1.0
