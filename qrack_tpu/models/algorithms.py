"""Algorithm workloads over any QInterface stack.

TPU-native counterparts of the reference teaching programs (reference:
examples/grovers.cpp, teleport.cpp, shors_factoring.cpp,
quantum_volume.cpp, test/benchmarks.cpp GHZ/RCS cases)."""

from __future__ import annotations

import math
from fractions import Fraction
from typing import List, Optional, Sequence, Tuple

import numpy as np


def ghz(qsim, n: Optional[int] = None) -> None:
    """GHZ preparation (reference: test/benchmarks.cpp:531)."""
    n = n if n is not None else qsim.GetQubitCount()
    qsim.H(0)
    for i in range(n - 1):
        qsim.CNOT(i, i + 1)


def grover_search(qsim, target: int, n: Optional[int] = None) -> int:
    """Grover search for |target> via phase-flip oracle (reference:
    examples/grovers.cpp:1-68 — same oracle construction from
    PhaseFlipIfLess pairs). Returns the measured index."""
    n = n if n is not None else qsim.GetQubitCount()
    for i in range(n):
        qsim.H(i)
    iters = int(math.floor(math.pi / 4 * math.sqrt(1 << n)))
    for _ in range(iters):
        qsim.PhaseFlipIfLess(target + 1, 0, n)
        qsim.PhaseFlipIfLess(target, 0, n)
        for i in range(n):
            qsim.H(i)
        qsim.PhaseFlipIfLess(1, 0, n)
        for i in range(n):
            qsim.H(i)
    return qsim.MAll()


def teleport(qsim, prepare=None) -> Tuple[float, float]:
    """Teleport qubit 0 onto qubit 2 (reference: examples/teleport.cpp).
    Returns (payload P(1) before, target P(1) after)."""
    if prepare is not None:
        prepare(qsim)
    before = qsim.Prob(0)
    qsim.H(1)
    qsim.CNOT(1, 2)
    qsim.CNOT(0, 1)
    qsim.H(0)
    m0 = qsim.M(0)
    m1 = qsim.M(1)
    if m1:
        qsim.X(2)
    if m0:
        qsim.Z(2)
    return before, qsim.Prob(2)


def shor_order_find(qsim, base: int, to_factor: int, width: int) -> Optional[int]:
    """One period-finding round of Shor's algorithm (reference:
    examples/shors_factoring.cpp:98-160). Needs 2*width qubits.
    Returns a nontrivial factor or None."""
    qsim.SetPermutation(0)
    for i in range(width):
        qsim.H(i)
    qsim.POWModNOut(base, to_factor, 0, width, width)
    qsim.IQFT(0, width)
    y = qsim.MReg(0, width)
    if y == 0:
        return None
    # continued-fraction reconstruction of the order
    frac = Fraction(y, 1 << width).limit_denominator(to_factor)
    r = frac.denominator
    if r % 2:
        r *= 2
    apow = pow(base, r // 2, to_factor)
    f1 = math.gcd(apow + 1, to_factor)
    f2 = math.gcd(apow - 1, to_factor)
    for f in (f1, f2):
        if 1 < f < to_factor and to_factor % f == 0:
            return f
    return None


def random_circuit_sampling(qsim, depth: int, rng, n: Optional[int] = None) -> None:
    """Nearest-neighbor RCS layer structure (reference:
    test/benchmarks.cpp:4141 test_random_circuit_sampling_nn): random
    single-qubit roots + brick-wall couplers."""
    n = n if n is not None else qsim.GetQubitCount()
    for d in range(depth):
        for q in range(n):
            g = rng.randint(0, 3)
            if g == 0:
                qsim.SqrtX(q)
            elif g == 1:
                qsim.SqrtY(q)
            else:
                qsim.SqrtW(q)
        off = d & 1
        for q in range(off, n - 1, 2):
            qsim.ISwap(q, q + 1)


def quantum_volume(qsim, depth: Optional[int] = None, rng=None) -> int:
    """QV-style circuit: `depth` rounds of random SU(4)-ish blocks on a
    random qubit pairing (reference: examples/quantum_volume.cpp:1-110).
    Returns the heavy-output count proxy (measured value)."""
    if rng is None:
        rng = qsim.rng
    n = qsim.GetQubitCount()
    depth = depth if depth is not None else n
    for _ in range(depth):
        perm = list(range(n))
        for i in range(n - 1, 0, -1):
            j = rng.randint(0, i + 1)
            perm[i], perm[j] = perm[j], perm[i]
        for k in range(0, n - 1, 2):
            a, b = perm[k], perm[k + 1]
            for q in (a, b):
                qsim.U(q, rng.rand() * math.pi, rng.rand() * 2 * math.pi,
                       rng.rand() * 2 * math.pi)
            qsim.CNOT(a, b)
            for q in (a, b):
                qsim.U(q, rng.rand() * math.pi, rng.rand() * 2 * math.pi,
                       rng.rand() * 2 * math.pi)
    return qsim.MAll()


def xeb_fidelity(probs_ideal: np.ndarray, samples) -> float:
    """Linear cross-entropy benchmark fidelity (reference:
    test_universal_circuit_digital_cross_entropy, test/benchmarks.cpp:4560)."""
    d = probs_ideal.shape[0]
    mean_p = float(np.mean([probs_ideal[int(s)] for s in samples]))
    return d * mean_p - 1.0


def grover_lookup_search(qsim, values: Sequence[int], target_value: int,
                         index_length: int, value_length: int) -> int:
    """Grover search over a loaded lookup table (reference:
    examples/grovers_lookup.cpp): superpose the index register, load
    values with the XOR-load oracle, flip the phase of entries equal to
    target_value, unload, amplify."""
    import math

    n_items = 1 << index_length
    iters = max(1, int(round(math.pi / 4 * math.sqrt(n_items))))
    for q in range(index_length):
        qsim.H(q)
    for _ in range(iters):
        # oracle: load value, phase-flip where value == target, unload
        qsim.IndexedLDA(0, index_length, index_length, value_length, values,
                        reset_value=False)
        qsim.PhaseFlipIfLess(target_value + 1, index_length, value_length)
        qsim.PhaseFlipIfLess(target_value, index_length, value_length)
        qsim.IndexedLDA(0, index_length, index_length, value_length, values,
                        reset_value=False)  # XOR-load is self-inverse
        # diffusion on the index register
        for q in range(index_length):
            qsim.H(q)
        qsim.PhaseFlipIfLess(1, 0, index_length)
        for q in range(index_length):
            qsim.H(q)
    return qsim.MReg(0, index_length)


def ordered_list_search(qsim, values: Sequence[int], key_value: int,
                        index_length: int, value_length: int) -> int:
    """Quadrant-narrowing search of an ORDERED list (reference:
    examples/ordered_list_search.cpp): each round superposes the two
    candidate halves' selector qubit, loads the quantum table, and
    compares against the key to decide the half — log2(N) rounds."""
    lo, hi = 0, (1 << index_length) - 1
    for bit in range(index_length - 1, -1, -1):
        mid = lo + (1 << bit)
        if mid > hi:
            continue
        # classical controller queries the quantum-loaded value at `mid`
        qsim.SetReg(0, index_length + value_length, 0)
        qsim.SetReg(0, index_length, mid)
        qsim.IndexedLDA(0, index_length, index_length, value_length, values)
        v = int(round(qsim.ExpectationBitsAll(
            list(range(index_length, index_length + value_length)))))
        if v <= key_value:
            lo = mid
    qsim.SetReg(0, index_length + value_length, 0)
    qsim.SetReg(0, index_length, lo)
    qsim.IndexedLDA(0, index_length, index_length, value_length, values)
    return lo


def pearson_hash_demo(qsim, perm_table: Sequence[int], key_length: int) -> dict:
    """Superposed Pearson-style hashing (reference: examples/pearson32.cpp):
    every possible key is hashed at once through the unitary Hash op;
    sampling the register yields (key-bijective) hash outputs."""
    for q in range(key_length):
        qsim.H(q)
    qsim.Hash(0, key_length, perm_table)
    shots = qsim.MultiShotMeasureMask([1 << q for q in range(key_length)], 64)
    return shots


def quantum_perceptron(qsim, input_qubit: int, output_qubit: int,
                       eta: float = 0.5, epochs: int = 4) -> float:
    """Train a QNeuron to learn NOT(input) (reference:
    examples/quantum_perceptron.cpp); returns the post-training
    prediction accuracy."""
    from ..qneuron import QNeuron

    neuron = QNeuron(qsim, (input_qubit,), output_qubit)
    for _ in range(epochs):
        for x in (0, 1):
            qsim.SetPermutation(x << input_qubit)
            neuron.Learn(eta, expected=(x == 0))
    correct = 0
    for x in (0, 1):
        qsim.SetPermutation(x << input_qubit)
        p = neuron.Predict()
        guess = p >= 0.5
        correct += int(guess == (x == 0))
    return correct / 2.0


def quantum_associative_memory(qsim, patterns: Sequence[Tuple[int, bool]],
                               input_length: int, output_qubit: int,
                               eta: float = 0.5) -> float:
    """Store input->bit associations in QNeuron angles and recall them
    (reference: examples/quantum_associative_memory.cpp); returns the
    recall accuracy over the stored patterns."""
    from ..qneuron import QNeuron

    neuron = QNeuron(qsim, tuple(range(input_length)), output_qubit)
    for key, bit in patterns:
        qsim.SetPermutation(key)
        neuron.LearnPermutation(eta, expected=bit)
    hits = 0
    for key, bit in patterns:
        qsim.SetPermutation(key)
        p = neuron.Predict()
        hits += int((p >= 0.5) == bit)
    return hits / len(patterns)


def cosmology_inflation(qsim_factory, steps: int, rng) -> List[int]:
    """Toy 'inflating universe' (reference: examples/cosmology.cpp): each
    step composes a randomly-prepared qubit onto the register and
    entangles it with a random neighbor; returns the register width per
    step (the reference watches how structure grows under composition)."""
    import math

    reg = qsim_factory(1)
    reg.U(0, 2 * math.pi * rng.rand(), 2 * math.pi * rng.rand(),
          2 * math.pi * rng.rand())
    widths = [reg.qubit_count]
    for _ in range(steps):
        nbit = qsim_factory(1)
        nbit.U(0, 2 * math.pi * rng.rand(), 2 * math.pi * rng.rand(),
               2 * math.pi * rng.rand())
        reg.Compose(nbit)
        partner = rng.randint(0, reg.qubit_count - 1)
        reg.CNOT(partner, reg.qubit_count - 1)
        widths.append(reg.qubit_count)
    return widths


# ----------------------------------------------------------------------
# QCircuit-emitting builders: workloads as submittable IR.
#
# Unlike the eager helpers above (which drive a live engine gate by
# gate), these return layers.qcircuit.QCircuit objects, so the same
# workload can be submitted through QrackService, classified by the
# router (route/), bucketed by shape_key, and batched — the mixed-
# traffic vocabulary for scripts/serve_bench.py --mixed.
# ----------------------------------------------------------------------


def _rz_mtrx(theta: float) -> np.ndarray:
    from .. import matrices as mat

    return mat.phase_mtrx(np.exp(-0.5j * theta), np.exp(0.5j * theta))


def ghz_qcircuit(n: int) -> "QCircuit":
    """GHZ chain as IR: H + CNOT ladder — fully Clifford, so the router
    keeps it tableau-resident at any width (w100+ costs O(n^2))."""
    from .. import matrices as mat
    from ..layers.qcircuit import QCircuit

    circ = QCircuit(n)
    circ.append_1q(0, mat.H2)
    for i in range(n - 1):
        circ.append_ctrl((i,), i + 1, mat.X2, 1)
    return circ


def qaoa_qcircuit(n: int, edges: Optional[Sequence[Tuple[int, int]]] = None,
                  p: int = 1, gammas: Optional[Sequence[float]] = None,
                  betas: Optional[Sequence[float]] = None,
                  rng=None) -> "QCircuit":
    """Depth-p QAOA for MaxCut on `edges` (default: the n-cycle).  Cost
    layers are RZZ(2*gamma) via the CNOT.RZ.CNOT identity; mixers are
    RX(2*beta).  Angles default to rng draws (or fixed values without
    an rng) so the emitted circuit is deterministic under a seed."""
    from .. import matrices as mat
    from ..layers.qcircuit import QCircuit

    if edges is None:
        edges = [(i, (i + 1) % n) for i in range(n)]
    if gammas is None:
        gammas = [(rng.rand() * math.pi if rng is not None
                   else 0.4 + 0.1 * k) for k in range(p)]
    if betas is None:
        betas = [(rng.rand() * math.pi / 2 if rng is not None
                  else 0.7 + 0.05 * k) for k in range(p)]
    circ = QCircuit(n)
    for q in range(n):
        circ.append_1q(q, mat.H2)
    for gamma, beta in zip(gammas, betas):
        for a, b in edges:
            circ.append_ctrl((a,), b, mat.X2, 1)
            circ.append_1q(b, _rz_mtrx(2.0 * gamma))
            circ.append_ctrl((a,), b, mat.X2, 1)
        for q in range(n):
            circ.append_1q(q, mat.u3_mtrx(2.0 * beta, -math.pi / 2,
                                          math.pi / 2))
    return circ


def quantum_volume_qcircuit(n: int, depth: Optional[int] = None,
                            rng=None) -> "QCircuit":
    """QV-style circuit as IR (the dense tenant's workload): `depth`
    rounds of random U3 pairs around CNOTs on a shuffled pairing —
    matches :func:`quantum_volume`'s structure without touching an
    engine.  Requires an rng (utils.rng.QrackRandom or compatible)."""
    from .. import matrices as mat
    from ..layers.qcircuit import QCircuit

    if rng is None:
        from ..utils.rng import QrackRandom

        rng = QrackRandom()
    depth = depth if depth is not None else n
    circ = QCircuit(n)
    for _ in range(depth):
        perm = list(range(n))
        for i in range(n - 1, 0, -1):
            j = rng.randint(0, i + 1)
            perm[i], perm[j] = perm[j], perm[i]
        for k in range(0, n - 1, 2):
            a, b = perm[k], perm[k + 1]
            for q in (a, b):
                circ.append_1q(q, mat.u3_mtrx(
                    rng.rand() * math.pi, rng.rand() * 2 * math.pi,
                    rng.rand() * 2 * math.pi))
            circ.append_ctrl((a,), b, mat.X2, 1)
            for q in (a, b):
                circ.append_1q(q, mat.u3_mtrx(
                    rng.rand() * math.pi, rng.rand() * 2 * math.pi,
                    rng.rand() * 2 * math.pi))
    return circ


def brickwork_theta(q: int) -> float:
    """The per-qubit RY angle :func:`brickwork_qcircuit` uses — exposed
    so callers can check the analytic marginal Prob(q) = sin^2(theta/2)
    (CZ bricks are diagonal, so computational marginals are untouched)."""
    return 0.3 + 0.04 * q


def brickwork_qcircuit(n: int, layers: int = 3) -> "QCircuit":
    """Shallow local brickwork as IR (the lightcone tenant's workload,
    docs/LIGHTCONE.md): one RY(theta_q) root per qubit, then `layers`
    alternating nearest-neighbor CZ brick layers.  Depth is layers+1
    regardless of width, so any local observable's past cone is O(layers)
    qubits — at the default depth the router prices a w50+ circuit at
    max_cone_width 6 and takes the lightcone rung instead of refusing.
    Deterministic: fixed (n, layers) always emits the same circuit."""
    from .. import matrices as mat
    from ..layers.qcircuit import QCircuit

    circ = QCircuit(n)
    for q in range(n):
        circ.append_1q(q, mat.u3_mtrx(brickwork_theta(q), 0.0, 0.0))
    for d in range(layers):
        for a in range(d & 1, n - 1, 2):
            circ.append_ctrl((a,), a + 1, mat.Z2, 1)
    return circ


def trotter_qcircuit(n: int, steps: int = 1, dt: float = 0.1,
                     j: float = 1.0, h: float = 1.0) -> "QCircuit":
    """First-order Trotterized transverse-field Ising evolution as IR:
    exp(-i dt H) per step with H = -j * sum Z_i Z_{i+1} - h * sum X_i —
    RZZ(2*j*dt) on each bond (CNOT.RZ.CNOT) then RX(2*h*dt) mixers.
    Deterministic: a fixed (n, steps, dt, j, h) tuple always emits the
    same circuit, so repeated submissions share one compiled program."""
    from .. import matrices as mat
    from ..layers.qcircuit import QCircuit

    circ = QCircuit(n)
    for _ in range(steps):
        for i in range(n - 1):
            circ.append_ctrl((i,), i + 1, mat.X2, 1)
            circ.append_1q(i + 1, _rz_mtrx(2.0 * j * dt))
            circ.append_ctrl((i,), i + 1, mat.X2, 1)
        for q in range(n):
            circ.append_1q(q, mat.u3_mtrx(2.0 * h * dt, -math.pi / 2,
                                          math.pi / 2))
    return circ


def separability_demo(qsim) -> dict:
    """Entangle, then watch Schmidt separation recover the product
    structure (reference: examples/qunit_separability.cpp /
    separability.cpp)."""
    out = {}
    n = qsim.qubit_count
    qsim.H(0)
    for i in range(n - 1):
        qsim.CNOT(i, i + 1)
    out["entangled_units"] = getattr(qsim, "GetUnitCount", lambda: 1)()
    # un-compute: the state returns to a product and TrySeparate confirms
    for i in range(n - 2, -1, -1):
        qsim.CNOT(i, i + 1)
    qsim.H(0)
    out["separable"] = all(qsim.TrySeparate(q) for q in range(n))
    out["final_units"] = getattr(qsim, "GetUnitCount", lambda: n)()
    return out
