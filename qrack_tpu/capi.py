"""Flat simulator-registry API (the pinvoke surface).

Re-design of the reference's C ABI used by PyQrack and the Q# runtime
(reference: include/pinvoke_api.hpp:42-349 — simulator registry
`init_count_type(...)` mapping layer toggles onto
CreateArrangedLayersFull, flat gate/measure/expectation functions keyed
by simulator id). Here the registry is process-local Python — the same
function names and sid-based calling convention, so a PyQrack-style
consumer ports by changing its import, and a future C shim can bind
these 1:1 (ctypes/cffi) without reshaping the surface."""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from .factory import create_arranged_layers_full
from .utils.rng import QrackRandom

_REGISTRY: Dict[int, object] = {}
_TOGGLES: Dict[int, dict] = {}
_NEXT = [0]
_LOCK = threading.Lock()


def _new_sid() -> int:
    with _LOCK:
        sid = _NEXT[0]
        _NEXT[0] += 1
    return sid


def _sim(sid: int):
    q = _REGISTRY.get(sid)
    if q is None:
        raise KeyError(f"no simulator with id {sid}")
    return q


# ---------------------------------------------------------------------------
# lifecycle (reference: init_count_type / destroy / seed,
# include/pinvoke_api.hpp:42-60)
# ---------------------------------------------------------------------------

def init_count_type(q: int, tn: bool = False, md: bool = False, sd: bool = True,
                    sh: bool = True, bdt: bool = False, pg: bool = True,
                    nw: bool = False, hy: bool = True, oc: bool = True,
                    hp: bool = False) -> int:
    """Create a simulator with the reference's layer toggles; returns sid.
    (hp=host-pointer is meaningless here and accepted for parity.)"""
    sid = _new_sid()
    toggles = dict(nw=nw, md=md, sd=sd, sh=sh, bdt=bdt, pg=pg, tn=tn, hy=hy, oc=oc)
    _TOGGLES[sid] = toggles
    _REGISTRY[sid] = create_arranged_layers_full(
        qubit_count=q, rng=QrackRandom(), **toggles)
    return sid


def init_count(q: int) -> int:
    return init_count_type(q)


def init() -> int:
    return init_count(1)


def init_clone(sid: int) -> int:
    nid = _new_sid()
    _REGISTRY[nid] = _sim(sid).Clone()
    _TOGGLES[nid] = dict(_TOGGLES.get(sid, {}))
    return nid


def destroy(sid: int) -> None:
    _REGISTRY.pop(sid, None)
    _TOGGLES.pop(sid, None)


def seed(sid: int, s: int) -> None:
    _sim(sid).SetRandomSeed(s)


def num_qubits(sid: int) -> int:
    return _sim(sid).GetQubitCount()


def allocateQubit(sid: int, qid: int) -> None:
    q = _sim(sid)
    if qid >= q.GetQubitCount():
        q.Allocate(q.GetQubitCount(), qid - q.GetQubitCount() + 1)


def release(sid: int, qid: int) -> bool:
    q = _sim(sid)
    resp = q.Prob(qid) <= 1e-9
    q.Dispose(qid, 1, None if not resp else 0)
    return resp


# ---------------------------------------------------------------------------
# gates (reference: include/pinvoke_api.hpp:66-220)
# ---------------------------------------------------------------------------

def X(sid, q): _sim(sid).X(q)
def Y(sid, q): _sim(sid).Y(q)
def Z(sid, q): _sim(sid).Z(q)
def H(sid, q): _sim(sid).H(q)
def S(sid, q): _sim(sid).S(q)
def T(sid, q): _sim(sid).T(q)
def AdjS(sid, q): _sim(sid).IS(q)
def AdjT(sid, q): _sim(sid).IT(q)
def SqrtX(sid, q): _sim(sid).SqrtX(q)
def AdjSqrtX(sid, q): _sim(sid).ISqrtX(q)
def U(sid, q, theta, phi, lambd): _sim(sid).U(q, theta, phi, lambd)
def Mtrx(sid, m, q): _sim(sid).Mtrx(np.asarray(m, dtype=np.complex128).reshape(2, 2), q)
def R(sid, basis, phi, q):
    from .pauli import Pauli

    b = Pauli(basis)
    if b == Pauli.PauliX:
        _sim(sid).RX(phi, q)
    elif b == Pauli.PauliY:
        _sim(sid).RY(phi, q)
    elif b == Pauli.PauliZ:
        _sim(sid).RZ(phi, q)
    else:
        # reference RHelper applies e^{i*phi/4} on both target halves
        # (pinvoke_api.cpp:408-414)
        _sim(sid).Exp(phi / 4, q)


def MCX(sid, c: Sequence[int], q): _sim(sid).MCInvert(tuple(c), 1.0, 1.0, q)
def MCY(sid, c, q): _sim(sid).MCInvert(tuple(c), -1j, 1j, q)
def MCZ(sid, c, q): _sim(sid).MCPhase(tuple(c), 1.0, -1.0, q)
def MCH(sid, c, q):
    from . import matrices as mat

    _sim(sid).MCMtrx(tuple(c), mat.H2, q)
def MCS(sid, c, q): _sim(sid).MCPhase(tuple(c), 1.0, 1j, q)
def MCT(sid, c, q):
    import cmath, math

    _sim(sid).MCPhase(tuple(c), 1.0, cmath.exp(0.25j * math.pi), q)
def MCU(sid, c, q, theta, phi, lambd): _sim(sid).CU(tuple(c), q, theta, phi, lambd)
def MCMtrx(sid, c, m, q):
    _sim(sid).MCMtrx(tuple(c), np.asarray(m, dtype=np.complex128).reshape(2, 2), q)
def MACMtrx(sid, c, m, q):
    _sim(sid).MACMtrx(tuple(c), np.asarray(m, dtype=np.complex128).reshape(2, 2), q)
def MCR(sid, basis, phi, c, q):
    """Multi-controlled Pauli rotation with the FULL control list
    (reference: MCRHelper, pinvoke_api.cpp:438)."""
    import cmath
    import math as _m

    from .pauli import Pauli

    sim = _sim(sid)
    ctrls = tuple(c)
    b = Pauli(basis)
    cos, sin = _m.cos(phi / 2), _m.sin(phi / 2)
    if b == Pauli.PauliX:
        m = np.array([[cos, -1j * sin], [-1j * sin, cos]], dtype=np.complex128)
        sim.MCMtrx(ctrls, m, q)
    elif b == Pauli.PauliY:
        m = np.array([[cos, -sin], [sin, cos]], dtype=np.complex128)
        sim.MCMtrx(ctrls, m, q)
    elif b == Pauli.PauliZ:
        sim.MCPhase(ctrls, complex(cos, -sin), complex(cos, sin), q)
    else:
        ph = cmath.exp(0.25j * phi)
        sim.MCPhase(ctrls, ph, ph, q)


def SWAP(sid, q1, q2): _sim(sid).Swap(q1, q2)
def ISWAP(sid, q1, q2): _sim(sid).ISwap(q1, q2)
def AdjISWAP(sid, q1, q2): _sim(sid).IISwap(q1, q2)
def FSim(sid, theta, phi, q1, q2): _sim(sid).FSim(theta, phi, q1, q2)
def CSWAP(sid, c, q1, q2): _sim(sid).CSwap(tuple(c), q1, q2)
def AND(sid, qi1, qi2, qo): _sim(sid).AND(qi1, qi2, qo)
def OR(sid, qi1, qi2, qo): _sim(sid).OR(qi1, qi2, qo)
def XOR(sid, qi1, qi2, qo): _sim(sid).XOR(qi1, qi2, qo)


# ---------------------------------------------------------------------------
# measurement / observables (reference: include/pinvoke_api.hpp:230-300)
# ---------------------------------------------------------------------------

def M(sid, q) -> bool:
    return _sim(sid).M(q)


def ForceM(sid, q, result: bool) -> bool:
    return _sim(sid).ForceM(q, result)


def MAll(sid) -> int:
    return _sim(sid).MAll()


def _transform_pauli_basis(q, bases, qubits) -> int:
    """Delegates to the layer-overridable QInterface method (reference:
    TransformPauliBasis, src/pinvoke_api.cpp)."""
    return q._transform_pauli_basis(bases, qubits)


def _revert_pauli_basis(q, bases, qubits) -> None:
    q._revert_pauli_basis(bases, qubits)


def Measure(sid, bases: Sequence[int], qubits: Sequence[int]) -> bool:
    """Joint Pauli measurement by basis conjugation (reference: Measure)."""
    q = _sim(sid)
    mask = _transform_pauli_basis(q, bases, qubits)
    res = q.ForceMParity(mask, False, do_force=False)
    _revert_pauli_basis(q, bases, qubits)
    return res


def MeasureShots(sid, qubits: Sequence[int], shots: int) -> List[int]:
    """Independently-ordered samples (reference fills an i.i.d. array;
    counts are expanded then shuffled with the simulator's stream —
    exchangeable with i.i.d. draws)."""
    q = _sim(sid)
    counts = q.MultiShotMeasureMask([1 << qi for qi in qubits], shots)
    out: List[int] = []
    for k, v in counts.items():
        out.extend([k] * v)
    arr = np.asarray(out)
    q.rng._gen.shuffle(arr)
    return arr.tolist()


def Prob(sid, q) -> float:
    return _sim(sid).Prob(q)


def PermutationProb(sid, qubits: Sequence[int], perm: int) -> float:
    mask = 0
    val = 0
    for j, qi in enumerate(qubits):
        mask |= 1 << qi
        if (perm >> j) & 1:
            val |= 1 << qi
    return _sim(sid).ProbMask(mask, val)


def PermutationExpectation(sid, qubits: Sequence[int]) -> float:
    return _sim(sid).ExpectationBitsAll(list(qubits))


def Variance(sid, qubits: Sequence[int]) -> float:
    return _sim(sid).VarianceBitsAll(list(qubits))


def JointEnsembleProbability(sid, bases, qubits) -> float:
    q = _sim(sid)
    mask = _transform_pauli_basis(q, bases, qubits)
    p = q.ProbParity(mask)
    _revert_pauli_basis(q, bases, qubits)
    return p


def ResetAll(sid) -> None:
    _sim(sid).SetPermutation(0)


# ---------------------------------------------------------------------------
# structure / state (reference: Compose/Decompose/Dispose, amplitude IO,
# lossy TurboQuant files include/pinvoke_api.hpp:55-56,302-320)
# ---------------------------------------------------------------------------

def Compose(sid1, sid2) -> int:
    return _sim(sid1).Compose(_sim(sid2).Clone())


def Decompose(sid, qubits_start: int, length: int) -> int:
    """Split `length` qubits into a new simulator; returns its sid."""
    nid = _new_sid()
    src = _sim(sid)
    # fresh destination with the same layer toggles (no O(2^n) clone)
    toggles = _TOGGLES.get(sid, {})
    dest = create_arranged_layers_full(qubit_count=length, rng=QrackRandom(),
                                       **toggles)
    src.Decompose(qubits_start, dest)
    _REGISTRY[nid] = dest
    _TOGGLES[nid] = dict(toggles)
    return nid


def Dispose(sid, start: int, length: int, perm: Optional[int] = None) -> None:
    _sim(sid).Dispose(start, length, perm)


def GetAmplitude(sid, perm: int) -> complex:
    return _sim(sid).GetAmplitude(perm)


def InKet(sid, ket: np.ndarray) -> None:
    _sim(sid).SetQuantumState(ket)


def OutKet(sid) -> np.ndarray:
    return np.asarray(_sim(sid).GetQuantumState())


def OutProbs(sid) -> np.ndarray:
    return np.asarray(_sim(sid).GetProbs())


def lossy_out_to_file(sid, path: str) -> None:
    _sim(sid).LossySaveStateVector(path)


def lossy_in_from_file(sid, path: str) -> None:
    _sim(sid).LossyLoadStateVector(path)


def TrySeparate1Qb(sid, q) -> bool:
    return _sim(sid).TrySeparate(q)


def TrySeparate2Qb(sid, q1, q2) -> bool:
    return _sim(sid).TrySeparate((q1, q2))


def GetUnitaryFidelity(sid) -> float:
    return _sim(sid).GetUnitaryFidelity()


def SetReactiveSeparate(sid, flag: bool) -> None:
    _sim(sid).SetReactiveSeparate(flag)


# ---------------------------------------------------------------------------
# ALU (reference: include/pinvoke_api.hpp ALU block)
# ---------------------------------------------------------------------------

def ADD(sid, a: int, start: int, length: int) -> None:
    _sim(sid).INC(a, start, length)


def SUB(sid, a: int, start: int, length: int) -> None:
    _sim(sid).DEC(a, start, length)


def ADDS(sid, a, s_index, start, length) -> None:
    _sim(sid).INCS(a, start, length, s_index)


def MUL(sid, a, start, carry_start, length) -> None:
    _sim(sid).MUL(a, start, carry_start, length)


def DIV(sid, a, start, carry_start, length) -> None:
    _sim(sid).DIV(a, start, carry_start, length)


def MULN(sid, a, mod_n, in_start, out_start, length) -> None:
    _sim(sid).MULModNOut(a, mod_n, in_start, out_start, length)


def POWN(sid, a, mod_n, in_start, out_start, length) -> None:
    _sim(sid).POWModNOut(a, mod_n, in_start, out_start, length)


def LDA(sid, qi, ql, vi, vl, values) -> int:
    return _sim(sid).IndexedLDA(qi, ql, vi, vl, values)


def ADC(sid, c, qi, ql, vi, vl, values) -> int:
    return _sim(sid).IndexedADC(qi, ql, vi, vl, c, values)


def SBC(sid, c, qi, ql, vi, vl, values) -> int:
    return _sim(sid).IndexedSBC(qi, ql, vi, vl, c, values)


def Hash(sid, start, length, values) -> None:
    _sim(sid).Hash(start, length, values)


# ---------------------------------------------------------------------------
# error registry (reference: simulatorErrors[], get_error
# src/pinvoke_api.cpp) — exceptions still raise; callers that want the C
# convention can poll get_error after a guarded call
# ---------------------------------------------------------------------------

_ERRORS: Dict[int, int] = {}


def get_error(sid: int) -> int:
    return _ERRORS.get(sid, 0)




# ---------------------------------------------------------------------------
# additional lifecycle / registry (reference: init_count_pager /
# init_count_stabilizer / Dump / DumpIds / set_device / set_concurrency)
# ---------------------------------------------------------------------------

def init_count_pager(q: int) -> int:
    return init_count_type(q, sd=False, sh=False, pg=True, hy=False)


def init_count_stabilizer(q: int) -> int:
    return init_count_type(q, sd=False, sh=True, pg=False, hy=False)


def Dump(sid) -> np.ndarray:
    """Reference streams amplitudes through a callback; here the ket is
    returned directly."""
    return OutKet(sid)


def DumpIds(sid) -> List[int]:
    return list(range(num_qubits(sid)))


def set_concurrency(sid, threads: int) -> None:
    pass  # XLA owns scheduling; accepted for parity


def set_device(sid, did: int) -> None:
    _sim(sid).SetDevice(did)


def set_device_list(sid, dids: Sequence[int]) -> None:
    _sim(sid).SetDeviceList(list(dids))


def random_choice(sid, probs: Sequence[float]) -> int:
    p = np.asarray(probs, dtype=np.float64)
    return int(_sim(sid).rng.choice_from_probs(p, 1)[0])


# ---------------------------------------------------------------------------
# gate-surface completion (reference: include/pinvoke_api.hpp:66-220)
# ---------------------------------------------------------------------------

def SX(sid, q): _sim(sid).SqrtX(q)
def SY(sid, q): _sim(sid).SqrtY(q)
def AdjSX(sid, q): _sim(sid).ISqrtX(q)
def AdjSY(sid, q): _sim(sid).ISqrtY(q)


def MACX(sid, c, q): _sim(sid).MACInvert(tuple(c), 1.0, 1.0, q)
def MACY(sid, c, q): _sim(sid).MACInvert(tuple(c), -1j, 1j, q)
def MACZ(sid, c, q): _sim(sid).MACPhase(tuple(c), 1.0, -1.0, q)
def MACH(sid, c, q):
    from . import matrices as mat

    _sim(sid).MACMtrx(tuple(c), mat.H2, q)
def MACS(sid, c, q): _sim(sid).MACPhase(tuple(c), 1.0, 1j, q)
def MACT(sid, c, q):
    import cmath, math

    _sim(sid).MACPhase(tuple(c), 1.0, cmath.exp(0.25j * math.pi), q)
def MACU(sid, c, q, theta, phi, lambd): _sim(sid).AntiCU(tuple(c), q, theta, phi, lambd)
def MCAdjS(sid, c, q): _sim(sid).MCPhase(tuple(c), 1.0, -1j, q)
def MACAdjS(sid, c, q): _sim(sid).MACPhase(tuple(c), 1.0, -1j, q)
def MCAdjT(sid, c, q):
    import cmath, math

    _sim(sid).MCPhase(tuple(c), 1.0, cmath.exp(-0.25j * math.pi), q)
def MACAdjT(sid, c, q):
    import cmath, math

    _sim(sid).MACPhase(tuple(c), 1.0, cmath.exp(-0.25j * math.pi), q)


def PhaseRootN(sid, p: int, qubits: Sequence[int]) -> None:
    for q in qubits:
        _sim(sid).PhaseRootN(p, q)


def Multiplex1Mtrx(sid, c, q, mtrxs) -> None:
    """Uniformly-controlled 1q gate: one 2x2 per control permutation
    (reference: Multiplex1Mtrx, include/pinvoke_api.hpp:179)."""
    ms = np.asarray(mtrxs, dtype=np.complex128).reshape(-1, 2, 2)
    _sim(sid).UniformlyControlledSingleBit(tuple(c), q, ms)


def UCMtrx(sid, c, m, q, perm: int) -> None:
    _sim(sid).MCMtrxPerm(tuple(c),
                         np.asarray(m, dtype=np.complex128).reshape(2, 2), q, perm)


def MX(sid, qubits: Sequence[int]) -> None:
    mask = 0
    for q in qubits:
        mask |= 1 << q
    _sim(sid).XMask(mask)


def MY(sid, qubits: Sequence[int]) -> None:
    mask = 0
    for q in qubits:
        mask |= 1 << q
    _sim(sid).YMask(mask)


def MZ(sid, qubits: Sequence[int]) -> None:
    mask = 0
    for q in qubits:
        mask |= 1 << q
    _sim(sid).ZMask(mask)


def PhaseParity(sid, lambd: float, qubits: Sequence[int]) -> None:
    mask = 0
    for q in qubits:
        mask |= 1 << q
    _sim(sid).PhaseParity(lambd, mask)


def Exp(sid, bases: Sequence[int], phi: float, qubits: Sequence[int]) -> None:
    """e^{i phi P} for a Pauli string P (reference: Exp + ExpHelper,
    src/pinvoke_api.cpp)."""
    import cmath

    q = _sim(sid)
    mask = _transform_pauli_basis(q, bases, qubits)
    if mask == 0:
        ph = cmath.exp(1j * phi)
        q.Phase(ph, ph, qubits[0] if qubits else 0)
    else:
        # e^{i phi Z..Z} applies e^{i phi} on even parity, e^{-i phi} odd
        q.UniformParityRZ(mask, -phi)
    _revert_pauli_basis(q, bases, qubits)


def MCExp(sid, bases: Sequence[int], phi: float, controls: Sequence[int],
          qubits: Sequence[int]) -> None:
    import cmath

    q = _sim(sid)
    mask = _transform_pauli_basis(q, bases, qubits)
    if mask == 0:
        ph = cmath.exp(1j * phi)
        q.MCPhase(tuple(controls), ph, ph, qubits[0] if qubits else 0)
    else:
        q.CUniformParityRZ(tuple(controls), mask, -phi)
    _revert_pauli_basis(q, bases, qubits)


def Normalize(sid) -> None:
    _sim(sid).NormalizeState()


def TimeEvolve(sid, t: float, hamiltonian) -> None:
    """Trotterized evolution under HamiltonianOp terms (reference:
    TimeEvolve, include/pinvoke_api.hpp:309)."""
    _sim(sid).TimeEvolve(hamiltonian, t)


# boolean logic completion
def NAND(sid, qi1, qi2, qo): _sim(sid).NAND(qi1, qi2, qo)
def NOR(sid, qi1, qi2, qo): _sim(sid).NOR(qi1, qi2, qo)
def XNOR(sid, qi1, qi2, qo): _sim(sid).XNOR(qi1, qi2, qo)
def CLAND(sid, ci, qi, qo): _sim(sid).CLAND(ci, qi, qo)
def CLOR(sid, ci, qi, qo): _sim(sid).CLOR(ci, qi, qo)
def CLXOR(sid, ci, qi, qo): _sim(sid).CLXOR(ci, qi, qo)
def CLNAND(sid, ci, qi, qo): _sim(sid).CLNAND(ci, qi, qo)
def CLNOR(sid, ci, qi, qo): _sim(sid).CLNOR(ci, qi, qo)
def CLXNOR(sid, ci, qi, qo): _sim(sid).CLXNOR(ci, qi, qo)


def ACSWAP(sid, c, q1, q2): _sim(sid).AntiCSwap(tuple(c), q1, q2)


def QFT(sid, qubits: Sequence[int]) -> None:
    _sim(sid).QFTR(list(qubits))


def IQFT(sid, qubits: Sequence[int]) -> None:
    _sim(sid).IQFTR(list(qubits))


# ---------------------------------------------------------------------------
# arithmetic completion (reference ALU block)
# ---------------------------------------------------------------------------

def SUBS(sid, a, s_index, start, length) -> None:
    _sim(sid).DECS(a, start, length, s_index)


def DIVN(sid, a, mod_n, in_start, out_start, length) -> None:
    _sim(sid).IMULModNOut(a, mod_n, in_start, out_start, length)


def MCADD(sid, a, c, start, length) -> None:
    _sim(sid).CINC(a, start, length, tuple(c))


def MCSUB(sid, a, c, start, length) -> None:
    _sim(sid).CDEC(a, start, length, tuple(c))


def MCMUL(sid, a, c, start, carry_start, length) -> None:
    _sim(sid).CMUL(a, start, carry_start, length, tuple(c))


def MCDIV(sid, a, c, start, carry_start, length) -> None:
    _sim(sid).CDIV(a, start, carry_start, length, tuple(c))


def MCMULN(sid, a, c, mod_n, in_start, out_start, length) -> None:
    _sim(sid).CMULModNOut(a, mod_n, in_start, out_start, length, tuple(c))


def MCDIVN(sid, a, c, mod_n, in_start, out_start, length) -> None:
    _sim(sid).CIMULModNOut(a, mod_n, in_start, out_start, length, tuple(c))


def MCPOWN(sid, a, c, mod_n, in_start, out_start, length) -> None:
    _sim(sid).CPOWModNOut(a, mod_n, in_start, out_start, length, tuple(c))


# ---------------------------------------------------------------------------
# measurement / expectation / variance completion
# (reference: include/pinvoke_api.hpp:61-117)
# ---------------------------------------------------------------------------

def MAllLong(sid) -> int:
    return MAll(sid)  # Python ints are unbounded; same entry point


def HighestProbAll(sid) -> int:
    return int(np.argmax(_sim(sid).GetProbs()))


def HighestProbAllN(sid, n: int) -> int:
    return HighestProbAll(sid)  # >64-bit perms are plain Python ints here


def ProbAll(sid, perm: int) -> float:
    return _sim(sid).ProbAll(perm)


def ProbRdm(sid, q) -> float:
    return _sim(sid).ProbRdm(q)


def PermutationProbRdm(sid, qubits: Sequence[int], perm: int, round_rz: bool) -> float:
    mask = 0
    val = 0
    for j, qi in enumerate(qubits):
        mask |= 1 << qi
        if (perm >> j) & 1:
            val |= 1 << qi
    return _sim(sid).ProbMaskRdm(round_rz, mask, val)


def PermutationExpectationRdm(sid, qubits: Sequence[int], round_rz: bool) -> float:
    return _sim(sid).ExpectationBitsAllRdm(round_rz, list(qubits))


def VarianceRdm(sid, qubits: Sequence[int], round_rz: bool = True) -> float:
    return _sim(sid).VarianceBitsAllRdm(round_rz, list(qubits))


def FactorizedExpectation(sid, qubits: Sequence[int], values: Sequence[int]) -> float:
    return _sim(sid).ExpectationBitsFactorized(list(qubits), list(values))


def FactorizedExpectationRdm(sid, qubits, values, round_rz: bool = True) -> float:
    return FactorizedExpectation(sid, qubits, values)


def FactorizedExpectationFp(sid, qubits: Sequence[int], weights: Sequence[float]) -> float:
    return _sim(sid).ExpectationFloatsFactorized(list(qubits), list(weights))


def FactorizedExpectationFpRdm(sid, qubits, weights, round_rz: bool = True) -> float:
    return FactorizedExpectationFp(sid, qubits, weights)


def FactorizedVariance(sid, qubits: Sequence[int], values: Sequence[int]) -> float:
    return _sim(sid).VarianceBitsFactorized(list(qubits), list(values))


def FactorizedVarianceRdm(sid, qubits, values, round_rz: bool = True) -> float:
    return FactorizedVariance(sid, qubits, values)


def FactorizedVarianceFp(sid, qubits: Sequence[int], weights: Sequence[float]) -> float:
    return _sim(sid).VarianceFloatsFactorized(list(qubits), list(weights))


def FactorizedVarianceFpRdm(sid, qubits, weights, round_rz: bool = True) -> float:
    return FactorizedVarianceFp(sid, qubits, weights)


def PauliExpectation(sid, bases: Sequence[int], qubits: Sequence[int]) -> float:
    """<P> for a Pauli string (reference: PauliExpectation,
    src/pinvoke_api.cpp) — layer-overridable QInterface method."""
    return float(_sim(sid).ExpectationPauliAll(list(qubits), list(bases)))


def PauliVariance(sid, bases: Sequence[int], qubits: Sequence[int]) -> float:
    return float(_sim(sid).VariancePauliAll(list(qubits), list(bases)))


def _rotated_stat(sid, qubits, mtrxs, eigenvals, variance: bool):
    """Expectation/variance of per-qubit observables diagonalized by the
    given 2x2 unitaries (reference: UnitaryExpectation/MatrixExpectation
    family, include/pinvoke_api.hpp:86-104) — delegates to the
    layer-overridable ExpectationUnitaryAll/VarianceUnitaryAll."""
    q = _sim(sid)
    if variance:
        return float(q.VarianceUnitaryAll(list(qubits), mtrxs, eigenvals))
    return float(q.ExpectationUnitaryAll(list(qubits), mtrxs, eigenvals))


def _u3(theta, phi, lambd):
    import cmath, math

    c, s = math.cos(theta / 2), math.sin(theta / 2)
    return np.array([[c, -cmath.exp(1j * lambd) * s],
                     [cmath.exp(1j * phi) * s, cmath.exp(1j * (phi + lambd)) * c]],
                    dtype=np.complex128)


def UnitaryExpectation(sid, qubits, angle_triples) -> float:
    ms = [_u3(*t) for t in np.asarray(angle_triples, dtype=np.float64).reshape(-1, 3)]
    return _rotated_stat(sid, qubits, ms, None, False)


def UnitaryVariance(sid, qubits, angle_triples) -> float:
    ms = [_u3(*t) for t in np.asarray(angle_triples, dtype=np.float64).reshape(-1, 3)]
    return _rotated_stat(sid, qubits, ms, None, True)


def UnitaryExpectationEigenVal(sid, qubits, angle_triples, eigenvals) -> float:
    ms = [_u3(*t) for t in np.asarray(angle_triples, dtype=np.float64).reshape(-1, 3)]
    return _rotated_stat(sid, qubits, ms, eigenvals, False)


def UnitaryVarianceEigenVal(sid, qubits, angle_triples, eigenvals) -> float:
    ms = [_u3(*t) for t in np.asarray(angle_triples, dtype=np.float64).reshape(-1, 3)]
    return _rotated_stat(sid, qubits, ms, eigenvals, True)


def MatrixExpectation(sid, qubits, mtrxs) -> float:
    return _rotated_stat(sid, qubits, mtrxs, None, False)


def MatrixVariance(sid, qubits, mtrxs) -> float:
    return _rotated_stat(sid, qubits, mtrxs, None, True)


def MatrixExpectationEigenVal(sid, qubits, mtrxs, eigenvals) -> float:
    return _rotated_stat(sid, qubits, mtrxs, eigenvals, False)


def MatrixVarianceEigenVal(sid, qubits, mtrxs, eigenvals) -> float:
    return _rotated_stat(sid, qubits, mtrxs, eigenvals, True)


def OutReducedDensityMatrix(sid, qubits: Sequence[int]) -> np.ndarray:
    return np.asarray(_sim(sid).GetReducedDensityMatrix(list(qubits)))


# ---------------------------------------------------------------------------
# separability / approximation / config completion
# (reference: include/pinvoke_api.hpp:287-310)
# ---------------------------------------------------------------------------

def Separate(sid, qubits: Sequence[int]) -> None:
    _sim(sid).TrySeparate(tuple(qubits))


def TrySeparateTol(sid, qubits: Sequence[int], tol: float) -> bool:
    return _sim(sid).TrySeparate(tuple(qubits), tol)


def AreFactorized(sid, qubits: Sequence[int]) -> bool:
    """Non-destructive separability check via a probing clone."""
    c = _sim(sid).Clone()
    return bool(c.TrySeparate(tuple(qubits)))


def SetSdrp(sid, sdrp: float) -> None:
    _sim(sid).SetSdrp(sdrp)


def SetNcrp(sid, ncrp: float) -> None:
    _sim(sid).SetNcrp(ncrp)


def SetSprp(sid, sprp: float) -> None:
    q = _sim(sid)
    if hasattr(q, "sep_threshold"):
        q.sep_threshold = float(sprp)


def SetStochastic(sid, flag: bool) -> None:
    q = _sim(sid)
    if hasattr(q, "SetStochastic"):
        q.SetStochastic(flag)


def SetUseExactNearClifford(sid, flag: bool) -> None:
    q = _sim(sid)
    if hasattr(q, "SetNcrp") and not flag:
        pass  # stochastic rounding toggle accepted for parity


def SetTInjection(sid, flag: bool) -> None:
    _sim(sid).SetTInjection(flag)


def SetNoiseParameter(sid, lam: float) -> None:
    _sim(sid).SetNoiseParameter(lam)


def SetAceMaxQb(sid, qb: int) -> None:
    q = _sim(sid)
    if hasattr(q, "SetAceMaxQubits"):
        q.SetAceMaxQubits(qb)


def SetSparseAceMaxMb(sid, mb: int) -> None:
    q = _sim(sid)
    if hasattr(q, "SetSparseAceMaxMb"):
        q.SetSparseAceMaxMb(int(mb))
    else:
        from .config import get_config

        get_config().max_alloc_mb = int(mb)


def ResetUnitaryFidelity(sid) -> None:
    _sim(sid).ResetUnitaryFidelity()


def SetMajorQuadrant(sid, flag: bool) -> None:
    q = _sim(sid)
    if hasattr(q, "SetMajorQuadrant"):
        q.SetMajorQuadrant(flag)
    else:
        _ERRORS[sid] = 1


def SetQuadrant(sid, t: int, b: bool) -> None:
    q = _sim(sid)
    if hasattr(q, "SetQuadrant"):
        q.SetQuadrant(t, b)
    else:
        _ERRORS[sid] = 1


def FlipQuadrant(sid, t: int) -> None:
    q = _sim(sid)
    if hasattr(q, "FlipQuadrant"):
        q.FlipQuadrant(t)
    else:
        _ERRORS[sid] = 1


# ---------------------------------------------------------------------------
# stabilizer serialization (reference: qstabilizer_out_to_file /
# in_from_file, include/pinvoke_api.hpp:55-56)
# ---------------------------------------------------------------------------

def _find_stabilizer(sim):
    from .layers.stabilizer import QStabilizer
    from .layers.stabilizerhybrid import QStabilizerHybrid

    if isinstance(sim, QStabilizer):
        return sim
    if isinstance(sim, QStabilizerHybrid):
        if sim.engine is not None or sim._anc or any(
                s is not None for s in sim.shards):
            raise ValueError("simulator is not in a pure Clifford state")
        return sim.stab
    if hasattr(sim, "shards") and hasattr(sim, "_order_contiguous"):
        # QUnit-family: entangle everything into one contiguous unit
        unit, base = sim._order_contiguous(list(range(sim.qubit_count)))
        if base != 0:
            raise ValueError("unexpected unit layout")
        return _find_stabilizer(unit)
    raise ValueError(f"no tableau beneath {type(sim).__name__}")


def qstabilizer_out_to_file(sid, path: str) -> None:
    _find_stabilizer(_sim(sid)).SaveToFile(path)


def qstabilizer_in_from_file(sid, path: str) -> None:
    from .layers.stabilizer import QStabilizer
    from .layers.stabilizerhybrid import QStabilizerHybrid

    st = QStabilizer.LoadFromFile(path, rng=QrackRandom())
    hy = QStabilizerHybrid(st.qubit_count, rng=QrackRandom())
    hy.stab = st
    _REGISTRY[sid] = hy


# ---------------------------------------------------------------------------
# QNeuron registry (reference: include/pinvoke_api.hpp qneuron block)
# ---------------------------------------------------------------------------

_NEURONS: Dict[int, object] = {}
_NEURON_NEXT = [0]


def _neuron(nid):
    n = _NEURONS.get(nid)
    if n is None:
        raise KeyError(f"no neuron with id {nid}")
    return n


def init_qneuron(sid, controls: Sequence[int], target: int, activation_fn: int = 0,
                 alpha: float = 1.0, tolerance: float = 1e-6) -> int:
    from .qneuron import ActivationFn, QNeuron

    with _LOCK:
        nid = _NEURON_NEXT[0]
        _NEURON_NEXT[0] += 1
    _NEURONS[nid] = QNeuron(_sim(sid), tuple(controls), target,
                            activation_fn=ActivationFn(activation_fn),
                            alpha=alpha, tolerance=tolerance)
    return nid


def clone_qneuron(nid) -> int:
    import copy

    src = _neuron(nid)
    with _LOCK:
        new = _NEURON_NEXT[0]
        _NEURON_NEXT[0] += 1
    c = copy.copy(src)
    c.angles = src.angles.copy()
    _NEURONS[new] = c
    return new


def destroy_qneuron(nid) -> None:
    _NEURONS.pop(nid, None)


def set_qneuron_sim(nid, sid) -> None:
    _neuron(nid).qreg = _sim(sid)


def set_qneuron_angles(nid, angles: Sequence[float]) -> None:
    n = _neuron(nid)
    n.angles = np.asarray(angles, dtype=np.float64).copy()


def get_qneuron_angles(nid) -> np.ndarray:
    return _neuron(nid).angles.copy()


def qneuron_predict(nid, expected: bool = True, reset_init: bool = True) -> float:
    return _neuron(nid).Predict(expected, reset_init)


def qneuron_unpredict(nid, expected: bool = True) -> float:
    return _neuron(nid).Unpredict(expected)


def qneuron_learn_cycle(nid, expected: bool = True) -> float:
    return _neuron(nid).LearnCycle(expected)


def qneuron_learn(nid, eta: float, expected: bool = True, reset_init: bool = True) -> None:
    _neuron(nid).Learn(eta, expected, reset_init)


def qneuron_learn_permutation(nid, eta: float, expected: bool = True,
                              reset_init: bool = True) -> None:
    _neuron(nid).LearnPermutation(eta, expected, reset_init)


# ---------------------------------------------------------------------------
# QCircuit registry (reference: include/pinvoke_api.hpp qcircuit block)
# ---------------------------------------------------------------------------

_CIRCUITS: Dict[int, object] = {}
_CIRCUIT_NEXT = [0]


def _circuit(cid):
    c = _CIRCUITS.get(cid)
    if c is None:
        raise KeyError(f"no circuit with id {cid}")
    return c


def _new_cid(circ) -> int:
    with _LOCK:
        cid = _CIRCUIT_NEXT[0]
        _CIRCUIT_NEXT[0] += 1
    _CIRCUITS[cid] = circ
    return cid


def init_qcircuit(collapse: bool = True, clifford: bool = False) -> int:
    from .layers.qcircuit import QCircuit

    circ = QCircuit(0)
    # recorded for parity: this IR holds no measurement gates, so the
    # reference's collapse toggle has no observable effect here
    circ.collapse = bool(collapse)
    circ.clifford = bool(clifford)
    return _new_cid(circ)


def init_qcircuit_clone(cid) -> int:
    return _new_cid(_circuit(cid).clone())


def destroy_qcircuit(cid) -> None:
    _CIRCUITS.pop(cid, None)


def get_qcircuit_qubit_count(cid) -> int:
    return _circuit(cid).qubit_count


def qcircuit_swap(cid, q1, q2) -> None:
    from . import matrices as mat

    c = _circuit(cid)
    # swap = 3 CNOTs in the IR (reference: QCircuit::Swap)
    c.append_ctrl((q1,), q2, mat.X2, 1)
    c.append_ctrl((q2,), q1, mat.X2, 1)
    c.append_ctrl((q1,), q2, mat.X2, 1)


def qcircuit_append_1qb(cid, m, q) -> None:
    _circuit(cid).append_1q(q, np.asarray(m, dtype=np.complex128).reshape(2, 2))


def qcircuit_append_mc(cid, m, controls: Sequence[int], q, perm: int) -> None:
    _circuit(cid).append_ctrl(tuple(controls), q,
                              np.asarray(m, dtype=np.complex128).reshape(2, 2), perm)


def qcircuit_run(cid, sid) -> None:
    _circuit(cid).Run(_sim(sid))


def qcircuit_inverse(cid) -> int:
    return _new_cid(_circuit(cid).Inverse())


def qcircuit_past_light_cone(cid, qubits: Sequence[int]) -> int:
    return _new_cid(_circuit(cid).PastLightCone(list(qubits)))


def qcircuit_out_to_string(cid) -> str:
    """Text form: width, gate count, then per gate: target, controls,
    payload map (perm + 8 floats per 2x2)."""
    c = _circuit(cid)
    lines = [str(c.qubit_count), str(len(c.gates))]
    for g in c.gates:
        lines.append(str(g.target))
        lines.append(" ".join(str(x) for x in g.controls))
        lines.append(str(len(g.payloads)))
        for perm, m in sorted(g.payloads.items()):
            flat = np.asarray(m, dtype=np.complex128).reshape(-1)
            nums = " ".join(f"{float(v.real)!r} {float(v.imag)!r}" for v in flat)
            lines.append(f"{perm} {nums}")
    return "\n".join(lines) + "\n"


def qcircuit_out_to_string_length(cid) -> int:
    return len(qcircuit_out_to_string(cid))


def qcircuit_out_to_file(cid, path: str) -> None:
    with open(path, "w") as f:
        f.write(qcircuit_out_to_string(cid))


def qcircuit_in_from_file(cid, path: str) -> None:
    from .layers.qcircuit import QCircuit, QCircuitGate

    with open(path) as f:
        toks = f.read().split("\n")
    it = iter(toks)
    n = int(next(it))
    count = int(next(it))
    circ = QCircuit(n)
    for _ in range(count):
        target = int(next(it))
        cline = next(it).split()
        controls = tuple(int(x) for x in cline)
        payloads = {}
        for _ in range(int(next(it))):
            parts = next(it).split()
            perm = int(parts[0])
            vals = [float(x) for x in parts[1:]]
            m = np.array([complex(vals[2 * i], vals[2 * i + 1]) for i in range(4)],
                         dtype=np.complex128).reshape(2, 2)
            payloads[perm] = m
        circ.AppendGate(QCircuitGate(target, payloads, controls))
    _CIRCUITS[cid] = circ


def _install_error_tracking() -> None:
    """Record the C error convention (reference: simulatorErrors[],
    src/pinvoke_api.cpp catch blocks): any exception from a sid-keyed
    call marks get_error(sid) before re-raising, so C/ctypes consumers
    that poll get_error see failures the shim swallowed."""
    import functools
    import sys

    mod = sys.modules[__name__]
    skip = {"get_error", "init", "init_count", "init_count_type",
            "init_count_pager", "init_count_stabilizer"}
    for name, fn in list(vars(mod).items()):
        if (name.startswith("_") or name in skip or not callable(fn)
                or getattr(fn, "__module__", None) != __name__):
            continue

        def make(f):
            @functools.wraps(f)
            def wrapper(*args, **kwargs):
                try:
                    return f(*args, **kwargs)
                except Exception:
                    if args and isinstance(args[0], int):
                        _ERRORS[args[0]] = 1
                    raise
            return wrapper

        setattr(mod, name, make(fn))


_install_error_tracking()
