"""Flat simulator-registry API (the pinvoke surface).

Re-design of the reference's C ABI used by PyQrack and the Q# runtime
(reference: include/pinvoke_api.hpp:42-349 — simulator registry
`init_count_type(...)` mapping layer toggles onto
CreateArrangedLayersFull, flat gate/measure/expectation functions keyed
by simulator id). Here the registry is process-local Python — the same
function names and sid-based calling convention, so a PyQrack-style
consumer ports by changing its import, and a future C shim can bind
these 1:1 (ctypes/cffi) without reshaping the surface."""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from .factory import create_arranged_layers_full
from .utils.rng import QrackRandom

_REGISTRY: Dict[int, object] = {}
_TOGGLES: Dict[int, dict] = {}
_NEXT = [0]
_LOCK = threading.Lock()


def _new_sid() -> int:
    with _LOCK:
        sid = _NEXT[0]
        _NEXT[0] += 1
    return sid


def _sim(sid: int):
    q = _REGISTRY.get(sid)
    if q is None:
        raise KeyError(f"no simulator with id {sid}")
    return q


# ---------------------------------------------------------------------------
# lifecycle (reference: init_count_type / destroy / seed,
# include/pinvoke_api.hpp:42-60)
# ---------------------------------------------------------------------------

def init_count_type(q: int, tn: bool = False, md: bool = False, sd: bool = True,
                    sh: bool = True, bdt: bool = False, pg: bool = True,
                    nw: bool = False, hy: bool = True, oc: bool = True,
                    hp: bool = False) -> int:
    """Create a simulator with the reference's layer toggles; returns sid.
    (hp=host-pointer is meaningless here and accepted for parity.)"""
    sid = _new_sid()
    toggles = dict(nw=nw, md=md, sd=sd, sh=sh, bdt=bdt, pg=pg, tn=tn, hy=hy, oc=oc)
    _TOGGLES[sid] = toggles
    _REGISTRY[sid] = create_arranged_layers_full(
        qubit_count=q, rng=QrackRandom(), **toggles)
    return sid


def init_count(q: int) -> int:
    return init_count_type(q)


def init() -> int:
    return init_count(1)


def init_clone(sid: int) -> int:
    nid = _new_sid()
    _REGISTRY[nid] = _sim(sid).Clone()
    _TOGGLES[nid] = dict(_TOGGLES.get(sid, {}))
    return nid


def destroy(sid: int) -> None:
    _REGISTRY.pop(sid, None)
    _TOGGLES.pop(sid, None)


def seed(sid: int, s: int) -> None:
    _sim(sid).SetRandomSeed(s)


def num_qubits(sid: int) -> int:
    return _sim(sid).GetQubitCount()


def allocateQubit(sid: int, qid: int) -> None:
    q = _sim(sid)
    if qid >= q.GetQubitCount():
        q.Allocate(q.GetQubitCount(), qid - q.GetQubitCount() + 1)


def release(sid: int, qid: int) -> bool:
    q = _sim(sid)
    resp = q.Prob(qid) <= 1e-9
    q.Dispose(qid, 1, None if not resp else 0)
    return resp


# ---------------------------------------------------------------------------
# gates (reference: include/pinvoke_api.hpp:66-220)
# ---------------------------------------------------------------------------

def X(sid, q): _sim(sid).X(q)
def Y(sid, q): _sim(sid).Y(q)
def Z(sid, q): _sim(sid).Z(q)
def H(sid, q): _sim(sid).H(q)
def S(sid, q): _sim(sid).S(q)
def T(sid, q): _sim(sid).T(q)
def AdjS(sid, q): _sim(sid).IS(q)
def AdjT(sid, q): _sim(sid).IT(q)
def SqrtX(sid, q): _sim(sid).SqrtX(q)
def AdjSqrtX(sid, q): _sim(sid).ISqrtX(q)
def U(sid, q, theta, phi, lambd): _sim(sid).U(q, theta, phi, lambd)
def Mtrx(sid, m, q): _sim(sid).Mtrx(np.asarray(m, dtype=np.complex128).reshape(2, 2), q)
def R(sid, basis, phi, q):
    from .pauli import Pauli

    b = Pauli(basis)
    if b == Pauli.PauliX:
        _sim(sid).RX(phi, q)
    elif b == Pauli.PauliY:
        _sim(sid).RY(phi, q)
    elif b == Pauli.PauliZ:
        _sim(sid).RZ(phi, q)
    else:
        # reference RHelper applies e^{i*phi/4} on both target halves
        # (pinvoke_api.cpp:408-414)
        _sim(sid).Exp(phi / 4, q)


def MCX(sid, c: Sequence[int], q): _sim(sid).MCInvert(tuple(c), 1.0, 1.0, q)
def MCY(sid, c, q): _sim(sid).MCInvert(tuple(c), -1j, 1j, q)
def MCZ(sid, c, q): _sim(sid).MCPhase(tuple(c), 1.0, -1.0, q)
def MCH(sid, c, q):
    from . import matrices as mat

    _sim(sid).MCMtrx(tuple(c), mat.H2, q)
def MCS(sid, c, q): _sim(sid).MCPhase(tuple(c), 1.0, 1j, q)
def MCT(sid, c, q):
    import cmath, math

    _sim(sid).MCPhase(tuple(c), 1.0, cmath.exp(0.25j * math.pi), q)
def MCU(sid, c, q, theta, phi, lambd): _sim(sid).CU(tuple(c), q, theta, phi, lambd)
def MCMtrx(sid, c, m, q):
    _sim(sid).MCMtrx(tuple(c), np.asarray(m, dtype=np.complex128).reshape(2, 2), q)
def MACMtrx(sid, c, m, q):
    _sim(sid).MACMtrx(tuple(c), np.asarray(m, dtype=np.complex128).reshape(2, 2), q)
def MCR(sid, basis, phi, c, q):
    """Multi-controlled Pauli rotation with the FULL control list
    (reference: MCRHelper, pinvoke_api.cpp:438)."""
    import cmath
    import math as _m

    from .pauli import Pauli

    sim = _sim(sid)
    ctrls = tuple(c)
    b = Pauli(basis)
    cos, sin = _m.cos(phi / 2), _m.sin(phi / 2)
    if b == Pauli.PauliX:
        m = np.array([[cos, -1j * sin], [-1j * sin, cos]], dtype=np.complex128)
        sim.MCMtrx(ctrls, m, q)
    elif b == Pauli.PauliY:
        m = np.array([[cos, -sin], [sin, cos]], dtype=np.complex128)
        sim.MCMtrx(ctrls, m, q)
    elif b == Pauli.PauliZ:
        sim.MCPhase(ctrls, complex(cos, -sin), complex(cos, sin), q)
    else:
        ph = cmath.exp(0.25j * phi)
        sim.MCPhase(ctrls, ph, ph, q)


def SWAP(sid, q1, q2): _sim(sid).Swap(q1, q2)
def ISWAP(sid, q1, q2): _sim(sid).ISwap(q1, q2)
def AdjISWAP(sid, q1, q2): _sim(sid).IISwap(q1, q2)
def FSim(sid, theta, phi, q1, q2): _sim(sid).FSim(theta, phi, q1, q2)
def CSWAP(sid, c, q1, q2): _sim(sid).CSwap(tuple(c), q1, q2)
def AND(sid, qi1, qi2, qo): _sim(sid).AND(qi1, qi2, qo)
def OR(sid, qi1, qi2, qo): _sim(sid).OR(qi1, qi2, qo)
def XOR(sid, qi1, qi2, qo): _sim(sid).XOR(qi1, qi2, qo)


# ---------------------------------------------------------------------------
# measurement / observables (reference: include/pinvoke_api.hpp:230-300)
# ---------------------------------------------------------------------------

def M(sid, q) -> bool:
    return _sim(sid).M(q)


def ForceM(sid, q, result: bool) -> bool:
    return _sim(sid).ForceM(q, result)


def MAll(sid) -> int:
    return _sim(sid).MAll()


def _transform_pauli_basis(q, bases, qubits) -> int:
    """Rotate X/Y observables into Z; returns the joint mask (reference:
    TransformPauliBasis, src/pinvoke_api.cpp)."""
    from .pauli import Pauli

    mask = 0
    for b, qi in zip(bases, qubits):
        p = Pauli(b)
        if p == Pauli.PauliX:
            q.H(qi)
        elif p == Pauli.PauliY:
            q.IS(qi)
            q.H(qi)
        if p != Pauli.PauliI:
            mask |= 1 << qi
    return mask


def _revert_pauli_basis(q, bases, qubits) -> None:
    from .pauli import Pauli

    for b, qi in zip(bases, qubits):
        p = Pauli(b)
        if p == Pauli.PauliX:
            q.H(qi)
        elif p == Pauli.PauliY:
            q.H(qi)
            q.S(qi)


def Measure(sid, bases: Sequence[int], qubits: Sequence[int]) -> bool:
    """Joint Pauli measurement by basis conjugation (reference: Measure)."""
    q = _sim(sid)
    mask = _transform_pauli_basis(q, bases, qubits)
    res = q.ForceMParity(mask, False, do_force=False)
    _revert_pauli_basis(q, bases, qubits)
    return res


def MeasureShots(sid, qubits: Sequence[int], shots: int) -> List[int]:
    """Independently-ordered samples (reference fills an i.i.d. array;
    counts are expanded then shuffled with the simulator's stream —
    exchangeable with i.i.d. draws)."""
    q = _sim(sid)
    counts = q.MultiShotMeasureMask([1 << qi for qi in qubits], shots)
    out: List[int] = []
    for k, v in counts.items():
        out.extend([k] * v)
    arr = np.asarray(out)
    q.rng._gen.shuffle(arr)
    return arr.tolist()


def Prob(sid, q) -> float:
    return _sim(sid).Prob(q)


def PermutationProb(sid, qubits: Sequence[int], perm: int) -> float:
    mask = 0
    val = 0
    for j, qi in enumerate(qubits):
        mask |= 1 << qi
        if (perm >> j) & 1:
            val |= 1 << qi
    return _sim(sid).ProbMask(mask, val)


def PermutationExpectation(sid, qubits: Sequence[int]) -> float:
    return _sim(sid).ExpectationBitsAll(list(qubits))


def Variance(sid, qubits: Sequence[int]) -> float:
    return _sim(sid).VarianceBitsAll(list(qubits))


def JointEnsembleProbability(sid, bases, qubits) -> float:
    q = _sim(sid)
    mask = _transform_pauli_basis(q, bases, qubits)
    p = q.ProbParity(mask)
    _revert_pauli_basis(q, bases, qubits)
    return p


def ResetAll(sid) -> None:
    _sim(sid).SetPermutation(0)


# ---------------------------------------------------------------------------
# structure / state (reference: Compose/Decompose/Dispose, amplitude IO,
# lossy TurboQuant files include/pinvoke_api.hpp:55-56,302-320)
# ---------------------------------------------------------------------------

def Compose(sid1, sid2) -> int:
    return _sim(sid1).Compose(_sim(sid2).Clone())


def Decompose(sid, qubits_start: int, length: int) -> int:
    """Split `length` qubits into a new simulator; returns its sid."""
    nid = _new_sid()
    src = _sim(sid)
    # fresh destination with the same layer toggles (no O(2^n) clone)
    toggles = _TOGGLES.get(sid, {})
    dest = create_arranged_layers_full(qubit_count=length, rng=QrackRandom(),
                                       **toggles)
    src.Decompose(qubits_start, dest)
    _REGISTRY[nid] = dest
    _TOGGLES[nid] = dict(toggles)
    return nid


def Dispose(sid, start: int, length: int, perm: Optional[int] = None) -> None:
    _sim(sid).Dispose(start, length, perm)


def GetAmplitude(sid, perm: int) -> complex:
    return _sim(sid).GetAmplitude(perm)


def InKet(sid, ket: np.ndarray) -> None:
    _sim(sid).SetQuantumState(ket)


def OutKet(sid) -> np.ndarray:
    return np.asarray(_sim(sid).GetQuantumState())


def OutProbs(sid) -> np.ndarray:
    return np.asarray(_sim(sid).GetProbs())


def lossy_out_to_file(sid, path: str) -> None:
    _sim(sid).LossySaveStateVector(path)


def lossy_in_from_file(sid, path: str) -> None:
    _sim(sid).LossyLoadStateVector(path)


def TrySeparate1Qb(sid, q) -> bool:
    return _sim(sid).TrySeparate(q)


def TrySeparate2Qb(sid, q1, q2) -> bool:
    return _sim(sid).TrySeparate((q1, q2))


def GetUnitaryFidelity(sid) -> float:
    return _sim(sid).GetUnitaryFidelity()


def SetReactiveSeparate(sid, flag: bool) -> None:
    _sim(sid).SetReactiveSeparate(flag)


# ---------------------------------------------------------------------------
# ALU (reference: include/pinvoke_api.hpp ALU block)
# ---------------------------------------------------------------------------

def ADD(sid, a: int, start: int, length: int) -> None:
    _sim(sid).INC(a, start, length)


def SUB(sid, a: int, start: int, length: int) -> None:
    _sim(sid).DEC(a, start, length)


def ADDS(sid, a, s_index, start, length) -> None:
    _sim(sid).INCS(a, start, length, s_index)


def MUL(sid, a, start, carry_start, length) -> None:
    _sim(sid).MUL(a, start, carry_start, length)


def DIV(sid, a, start, carry_start, length) -> None:
    _sim(sid).DIV(a, start, carry_start, length)


def MULN(sid, a, mod_n, in_start, out_start, length) -> None:
    _sim(sid).MULModNOut(a, mod_n, in_start, out_start, length)


def POWN(sid, a, mod_n, in_start, out_start, length) -> None:
    _sim(sid).POWModNOut(a, mod_n, in_start, out_start, length)


def LDA(sid, qi, ql, vi, vl, values) -> int:
    return _sim(sid).IndexedLDA(qi, ql, vi, vl, values)


def ADC(sid, c, qi, ql, vi, vl, values) -> int:
    return _sim(sid).IndexedADC(qi, ql, vi, vl, c, values)


def SBC(sid, c, qi, ql, vi, vl, values) -> int:
    return _sim(sid).IndexedSBC(qi, ql, vi, vl, c, values)


def Hash(sid, start, length, values) -> None:
    _sim(sid).Hash(start, length, values)
