from .qengine import QEngine  # noqa: F401
from .cpu import QEngineCPU  # noqa: F401
from .sparse import QEngineSparse  # noqa: F401
from .turboquant import QEngineTurboQuant  # noqa: F401
