"""QEngineTPU: dense state vector in TPU HBM as split real/imag planes.

The TPU-native successor of the reference's GPU engines (reference:
include/qengine_opencl.hpp:168 QEngineOCL / qengine_cuda.hpp). Design
mapping (SURVEY.md §7 step 4):

  * The reference's QueueItem chain + event callbacks (opencl.cpp:412)
    become JAX async dispatch: every void gate op returns immediately,
    device work is ordered by data dependence, and only non-void ops
    (Prob/M/amplitude reads) synchronize — the reference's
    clFinish-on-read discipline (opencl.cpp:329).
  * The 8 apply2x2 kernel variants (opencl.cpp:810-1016) collapse into
    three jitted XLA program families (generic/diagonal/invert) whose
    compile-cache keys are (width, target axis) only — control
    placement, control count, and matrix values are dynamic operands.
  * Amplitudes are (2, 2^n) float32 planes (TPUs have no complex ALU;
    see ops/gatekernels.py). bf16 storage is a dtype switch.
  * Buffers are donated back to XLA on every gate, so the ket updates
    in place in HBM like the reference's persistent stateBuffer.
  * The OpenCL binary-kernel cache (oclengine.cpp:150-202) is XLA's
    own compilation cache.
"""

from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp

from ..ops import gatekernels as gk
from .qengine import QEngine
from .. import matrices as mat
from .. import telemetry as _tele
from .. import resilience as _res


# ---------------------------------------------------------------------------
# module-level jitted programs, shared by every engine instance.  The
# telemetry wrapper classifies each call as compile.<name>.miss (the jit
# cache grew — XLA compiled) or .hit; with telemetry disabled it is a
# single boolean test over the raw jitted callable.  The resilience
# wrapper outside it guards the whole compile-or-dispatch at site
# "tpu.compile" (watchdog / retry / breaker) — same off-by-default
# one-boolean-test discipline.
# ---------------------------------------------------------------------------

def _jit(name, fn, **kw):
    return _res.instrument_dispatch(
        "tpu.compile", _tele.instrument_jit(f"tpu.{name}", jax.jit(fn, **kw)))


def _device_get(fn, *args):
    """Host-read boundary (site "tpu.device_get"): the only sync that
    proves completion over the relay — and therefore the one that hangs
    when the tunnel wedges mid-flight."""
    if _res._ACTIVE:
        out = _res.call_guarded("tpu.device_get", fn, args)
        from ..resilience import integrity as _integ

        if _integ.enabled():
            # boundary invariant piggybacked on the value the caller
            # already forced to host — no extra HBM sweep
            _integ.check_host("tpu.device_get", out)
        return out
    return fn(*args)


def _discover(device_id: int):
    """jax.devices() backend init (site "discover") — the single worst
    hang site (CLAUDE.md: wedges for hours).  With resilience active it
    is breaker-gated and, under QRACK_TPU_PROBE_FIRST=1, preceded by a
    SIGTERM-first subprocess probe so the wedge is detected by a
    killable child instead of this process."""
    if device_id < 0:
        return None
    if not _res._ACTIVE:
        return jax.devices()[device_id]
    import os as _os

    if _os.environ.get("QRACK_TPU_PROBE_FIRST", "") not in ("", "0"):
        from ..resilience import probe as _probe
        from ..resilience.errors import DispatchGiveUp, DispatchTimeout

        r = _probe.ensure_backend()
        if not r.ok:
            _res.get_breaker().record_failure("discover")
            raise DispatchGiveUp(
                "discover", DispatchTimeout("discover", detail="probe failed"))
    return _res.call_guarded("discover", lambda: jax.devices()[device_id])


_j_apply_2x2 = _jit("apply_2x2", gk.apply_2x2, static_argnums=(2, 3), donate_argnums=(0,))
_j_apply_diag = _jit("apply_diag", gk.apply_diag, static_argnums=(5,), donate_argnums=(0,))
_j_apply_invert = _jit("apply_invert", gk.apply_invert, static_argnums=(5, 6), donate_argnums=(0,))
_j_apply_4x4 = _jit("apply_4x4", gk.apply_4x4, static_argnums=(2, 3, 4), donate_argnums=(0,))
_j_swap_bits = _jit("swap_bits", gk.swap_bits, static_argnums=(1, 2, 3), donate_argnums=(0,))
_j_gather = _jit("gather", gk.gather, donate_argnums=(0,))
_j_phase_apply = _jit("phase_apply", gk.phase_factor_apply, donate_argnums=(0,))
_j_prob_mask = _jit("prob_mask", gk.prob_mask_sum)
_j_collapse = _jit("collapse", gk.collapse, donate_argnums=(0,))
_j_normalize = _jit("normalize", gk.normalize, donate_argnums=(0,))
_j_probs = _jit("probs", gk.probs)
_j_sum_sqr_diff = _jit("sum_sqr_diff", gk.sum_sqr_diff)
_j_sample = _jit("sample", gk.sample)
_j_multishot = _jit("multishot", gk.multishot_mask_keys)
_j_uc_2x2 = _jit("uc_2x2", gk.uc_2x2, static_argnums=(2, 3, 4), donate_argnums=(0,))
# out-of-place device copy for the copy-on-write boundary below — never
# donates (its whole job is to leave the source buffer alive)
_j_copy = _jit("copy_planes", jnp.copy)


# ---------------------------------------------------------------------------
# plane pin registry (serve/prefix_cache.py): buffers whose identity is
# registered here were handed out as SHARED refs (a cache entry plus any
# number of seeded session engines may alias one buffer) and must NEVER
# be donated to a jitted program — donation would invalidate every other
# alias.  Keyed by id() of the jax array object — not by engine — because
# the executor's failover rollback re-assigns the SAME cached ref back
# into an engine (serve/executor.py pre_planes), and an engine-level flag
# would not survive that round trip.  A pin lives exactly as long as the
# buffer does (weakref finalizer), NOT as long as the cache entry: after
# an eviction, engines still aliasing the buffer remain protected from
# each other.  The dict is empty whenever the prefix cache is off, so the
# hot-path probe in _owned_state is one falsy check.
# ---------------------------------------------------------------------------

_PLANE_PINS: dict = {}


def pin_planes(planes) -> None:
    """Register `planes` as shared: donation sites copy-on-write."""
    if planes is None:
        return
    k = id(planes)
    if k in _PLANE_PINS:
        return
    import weakref

    try:
        _PLANE_PINS[k] = weakref.ref(
            planes, lambda _r, _k=k: _PLANE_PINS.pop(_k, None))
    except TypeError:
        _PLANE_PINS[k] = None  # unweakrefable buffer: pinned for life


def unpin_planes(planes) -> None:
    """Force-drop a pin (tests only — live aliases lose protection)."""
    if planes is not None:
        _PLANE_PINS.pop(id(planes), None)


def planes_pinned(planes) -> bool:
    return planes is not None and id(planes) in _PLANE_PINS


# one-chip dense f32 width ceiling: int32 flat indices + HBM for
# (2, 2^n) planes with gate transients (single source — the compressed
# engines derive their higher caps from it)
MAX_DENSE_QB = 30


class QEngineTPU(QEngine):
    """Dense ket on one accelerator device (TPU; CPU backend in tests)."""

    _xp = jnp
    _tele_name = "tpu"

    def __init__(self, qubit_count: int, init_state: int = 0, dtype=None,
                 device_id: int = -1, **kwargs):
        super().__init__(qubit_count, init_state=init_state, **kwargs)
        self._check_capacity(qubit_count)
        if dtype is None:
            # FPPOW policy (config.py): float32 default; float64 / bf16 /
            # f16 via QRACK_TPU_FPPOW (reference FPPOW,
            # include/common/qrack_types.hpp:88-138)
            from ..config import get_config

            dtype = get_config().device_real_dtype()
        self.dtype = jnp.dtype(dtype)  # plane dtype (f32/f64/bf16/f16)
        if self.dtype == jnp.dtype("float64") and not jax.config.jax_enable_x64:
            jax.config.update("jax_enable_x64", True)
        # f32 norm-drift escalation: every K gates compute total
        # probability; past the threshold, planes re-cast to float64 in
        # place (the deep-circuit failure class the bf16 matmul finding
        # proved matters on this hardware — docs/TPU_EVIDENCE.md:26-35)
        import os as _os

        self._drift_thresh = float(_os.environ.get(
            "QRACK_TPU_AUTO_F64_DRIFT", "0"))
        self._drift_check_every = max(1, int(_os.environ.get(
            "QRACK_TPU_DRIFT_CHECK_GATES", "64")))
        self._gate_count = 0
        self._device = _discover(device_id)
        self._device_id = device_id
        # lazy gate-stream fusion (ops/fusion.py): install BEFORE the
        # first _state write so the property sees a fuser from day one
        from ..ops import fusion as _fusion

        self._fuser = _fusion.make_fuser(self)
        self._state_raw = None  # (2, 2^n) planes
        self.SetPermutation(init_state)

    # ------------------------------------------------------------------
    # helpers
    # ------------------------------------------------------------------

    _fuse_capable = True

    @property
    def _state(self):
        """Resident planes.  EVERY read is a fusion boundary: a pending
        gate window flushes before the value escapes (Prob*/M*/sample/
        device_get/checkpoint capture/failover snapshot/serve batch edge
        all land here), so no reader can observe a ket that is behind
        the gate stream."""
        f = self._fuser
        if f is not None and f.gates and not f._flushing:
            f.flush("read")
        return self._state_raw

    @_state.setter
    def _state(self, planes) -> None:
        # a direct write while gates are pending is a blind overwrite
        # (SetPermutation/SetQuantumState/restore): the queued gates
        # acted on state that no longer exists — drop them.  Kernel
        # read-modify-writes never hit this: their RHS read flushed the
        # window first, and the flush's own write-back is re-entrant
        # (_flushing) so it passes straight through.
        f = self._fuser
        if f is not None and f.gates and not f._flushing:
            f.drop("overwritten")
        self._state_raw = planes

    def _owned_state(self):
        """The resident planes as a DONATABLE buffer.  When the serving
        prefix cache holds the current ref (_PLANE_PINS), return a fresh
        device copy and make IT resident first — copy-on-write at the
        donation boundary, so no jitted program ever consumes a buffer a
        cache entry still aliases.  One falsy dict probe when nothing is
        pinned."""
        st = self._state  # property read: flushes any pending window
        if _PLANE_PINS and id(st) in _PLANE_PINS:
            st = _j_copy(st)
            self._state_raw = st
            _tele.inc("serve.prefix.cow")
        return st

    @property
    def device_planes(self):
        """The resident (2, 2^n) split-plane ket on device.  The serving
        batcher stacks these across sessions into one (B, 2, 2^n) vmap
        operand and writes each slice back after the batched dispatch."""
        return self._state

    @device_planes.setter
    def device_planes(self, planes) -> None:
        self._state = planes

    def _check_capacity(self, qubit_count: int) -> None:
        # int32 index math and one-chip HBM both cap a dense shard at
        # MAX_DENSE_QB qubits; Compose/Allocate growth funnels through
        # this too.
        if qubit_count > MAX_DENSE_QB:
            raise MemoryError(
                f"QEngineTPU width {qubit_count} exceeds a single dense shard; "
                "use the QPager/QUnit layers above this engine"
            )

    def _put(self, arr):
        return jax.device_put(arr, self._device) if self._device is not None else jnp.asarray(arr)

    def _rand_phase(self) -> complex:
        if self.rand_global_phase:
            ang = 2.0 * math.pi * self.Rand()
            return complex(math.cos(ang), math.sin(ang))
        return 1.0 + 0.0j

    @staticmethod
    def _cmask_cval(controls, perm):
        from ..utils.bits import control_offset

        cmask = 0
        for c in controls:
            cmask |= 1 << c
        return cmask, control_offset(controls, perm)

    # ------------------------------------------------------------------
    # kernel contract
    # ------------------------------------------------------------------

    def _drift_tick(self) -> None:
        """Opt-in f32->f64 precision escalation (QRACK_TPU_AUTO_F64_DRIFT):
        every K gates read back total probability; unitary circuits keep
        it at 1, so sustained drift means the f32 planes are rotting —
        re-cast to float64 in place (QHybrid's dense halves inherit this,
        which is its precision-escalation policy).  Ticked from every
        MIXING kernel (2x2/invert/diag/4x4/uc); swaps and gathers are
        exact permutations and cannot drift the norm."""
        self._drift_tick_n(1)

    def _drift_tick_n(self, k: int) -> None:
        """Advance the drift accounting by `k` gates at once (a fused
        window applies its whole gate run in one dispatch)."""
        if self._drift_thresh <= 0 or self.dtype == jnp.dtype("float64"):
            return
        before = self._gate_count
        self._gate_count += k
        if (before // self._drift_check_every) == (
                self._gate_count // self._drift_check_every):
            return
        nrm = float(_j_prob_mask(self._state, 0, 0))  # total probability
        if abs(1.0 - nrm) > self._drift_thresh:
            self.EscalateToF64(nrm)

    def EscalateToF64(self, observed_norm: float = None) -> None:
        """Re-cast the resident planes to float64 (reference analogue:
        rebuilding at a higher FPPOW, qrack_types.hpp:88-138 — here it
        is a live dtype switch, no state round-trip).

        CAVEAT (the QRACK_TPU_AUTO_F64_DRIFT opt-in buys into this):
        float64 planes require ``jax_enable_x64``, and that flag is
        PROCESS-GLOBAL — flipping it mid-run changes default dtype
        promotion for every JAX computation in the process, not just
        this engine, and invalidates already-compiled programs (XLA
        recompiles on the next dispatch of each).  Engines created
        before the flip keep working — their f32 planes carry explicit
        dtypes — but any tracing that relied on x64-off weak-type
        defaults may see different dtypes from here on.  When the flip
        happens after tracing has begun (some program already compiled),
        an extra warning + telemetry event flags the recompile storm."""
        import warnings

        if not jax.config.jax_enable_x64:
            already_traced = False
            try:
                already_traced = _j_apply_2x2._cache_size() > 0
            except Exception:
                pass
            jax.config.update("jax_enable_x64", True)
            _tele.event("engine.tpu.x64_flip",
                        after_tracing=bool(already_traced),
                        observed_norm=observed_norm)
            if already_traced:
                warnings.warn(
                    "QRACK_TPU_AUTO_F64_DRIFT escalation enabled "
                    "jax_enable_x64 AFTER programs were already traced: "
                    "the flag is process-global, so every live jitted "
                    "program recompiles on next dispatch and non-qrack "
                    "JAX code in this process now sees x64 defaults",
                    RuntimeWarning)
        if self.dtype == jnp.dtype("float64"):
            return
        _tele.event("engine.tpu.f64_escalation",
                    observed_norm=observed_norm,
                    drift_thresh=self._drift_thresh,
                    width=self.qubit_count)
        warnings.warn(
            f"f32 norm drift {observed_norm!r} exceeded "
            f"QRACK_TPU_AUTO_F64_DRIFT={self._drift_thresh}: escalating "
            "amplitude planes to float64", RuntimeWarning)
        self.dtype = jnp.dtype(jnp.float64)
        if self._state is not None:
            self._state = self._state.astype(jnp.float64)

    # ------------------------------------------------------------------
    # fusion hooks (ops/fusion.py)
    # ------------------------------------------------------------------

    def _fuse_admit(self, m, target, controls) -> bool:
        # every 2x2 gate lowers into a dense parametric window
        return True

    def _fuse_tick(self) -> None:
        # drift accounting advances per LOGICAL gate at queue time (the
        # eager kernels tick per dispatch; a fused window would otherwise
        # under-count merged-away gates).  A boundary crossing reads the
        # state norm, which flushes the pending window first.
        self._drift_tick()

    def _fuse_flush(self, gates) -> int:
        """Lower the pending window into ONE parametric program dispatch
        (guarded site tpu.fuse.flush).  A window that merged down to a
        single op reuses the shared per-gate program families instead of
        minting a one-op window program."""
        from ..ops import fusion as fu

        ops = fu.lower_gates(gates)
        if not ops:
            return 0
        n = self.qubit_count
        if len(ops) == 1:
            op = ops[0]
            m = op.m
            if op.kind in ("cphase", "diag"):
                d0, d1 = complex(m[0, 0]), complex(m[1, 1])
                self._state = _j_apply_diag(
                    self._owned_state(), d0.real, d0.imag, d1.real, d1.imag,
                    n, 1 << op.target, op.cmask, op.cval)
            elif op.kind == "inv":
                tr, bl = complex(m[0, 1]), complex(m[1, 0])
                self._state = _j_apply_invert(
                    self._owned_state(), tr.real, tr.imag, bl.real, bl.imag,
                    n, op.target, op.cmask, op.cval)
            else:
                mp = gk.mtrx_planes(m, self.dtype)
                self._state = _j_apply_2x2(
                    self._owned_state(), mp, n, op.target, op.cmask, op.cval)
            return 1
        structure = fu.structure_of(ops)
        operands = fu.dense_operands(ops, self.dtype)
        plan, why = fu.kernel_lowering(n, structure)
        if plan is not None:
            prog = fu.kernel_window_program(
                n, structure, self.dtype, interpret=plan["interpret"],
                block_pow=plan["block_pow"])
            self._state = prog(self._owned_state(), *operands)
            fu.record_kernel_flush(self._tele_name, len(ops), plan["sweeps"],
                                   width=n,
                                   esize=jnp.dtype(self.dtype).itemsize)
            return 1
        fu.record_kernel_fallback(why)
        prog = fu.dense_window_program(n, structure, self.dtype)
        self._state = prog(self._owned_state(), *operands)
        fu.record_xla_flush(self._tele_name, len(ops), width=n,
                            esize=jnp.dtype(self.dtype).itemsize)
        return 1

    def _k_apply_2x2(self, m2, target, controls, perm) -> None:
        cmask, cval = self._cmask_cval(controls, perm)
        if mat.is_invert(m2):
            tr, bl = m2[0, 1], m2[1, 0]
            self._state = _j_apply_invert(
                self._owned_state(), float(tr.real), float(tr.imag),
                float(bl.real), float(bl.imag),
                self.qubit_count, target, cmask, cval,
            )
        else:
            mp = gk.mtrx_planes(m2, self.dtype)
            self._state = _j_apply_2x2(self._owned_state(), mp,
                                       self.qubit_count, target, cmask, cval)
        self._drift_tick()

    def _k_apply_diag(self, d0, d1, target, controls, perm) -> None:
        cmask, cval = self._cmask_cval(controls, perm)
        d0, d1 = complex(d0), complex(d1)
        self._state = _j_apply_diag(
            self._owned_state(), d0.real, d0.imag, d1.real, d1.imag,
            self.qubit_count, 1 << target, cmask, cval,
        )
        self._drift_tick()

    def _k_apply_4x4(self, m4, q1, q2) -> None:
        mp = gk.mtrx_planes(m4, self.dtype)
        self._state = _j_apply_4x4(self._owned_state(), mp,
                                   self.qubit_count, q1, q2)
        self._drift_tick()

    def UCMtrx(self, controls, mtrxs, target, mtrx_skip_powers=(), mtrx_skip_value_mask=0) -> None:
        """Uniformly-controlled gate in one fused kernel (reference kernel
        uniformlycontrolled, qengine.cl:409)."""
        if mtrx_skip_powers:
            return super().UCMtrx(controls, mtrxs, target, mtrx_skip_powers, mtrx_skip_value_mask)
        stack = np.stack([np.asarray(m, dtype=np.complex128).reshape(2, 2) for m in mtrxs])
        mps = jnp.stack([
            jnp.asarray(stack.real, dtype=self.dtype),
            jnp.asarray(stack.imag, dtype=self.dtype),
        ])
        self._state = _j_uc_2x2(self._owned_state(), mps, self.qubit_count,
                                target, tuple(controls))
        self._drift_tick()

    def _k_gather(self, src_fn, split=None) -> None:
        st = self._owned_state()
        src = src_fn(gk.iota_for(st))
        self._state = _j_gather(st, src)

    def _k_out_of_place(self, src_idx, dst_idx, passthrough_cmask) -> None:
        src_idx = jnp.asarray(src_idx, dtype=gk.IDX_DTYPE)
        dst_idx = jnp.asarray(dst_idx, dtype=gk.IDX_DTYPE)
        new = jnp.zeros_like(self._state)
        if passthrough_cmask is not None:
            idx = gk.iota_for(self._state)
            keep = (idx & passthrough_cmask) != passthrough_cmask
            new = jnp.where(keep, self._state, new)
        new = new.at[:, dst_idx].set(self._state[:, src_idx])
        self._state = new

    def _k_phase_fn(self, fn, split=None) -> None:
        st = self._owned_state()
        fre, fim = fn(jnp, gk.iota_for(st))
        self._state = _j_phase_apply(st, fre, fim)

    def _k_probs(self) -> np.ndarray:
        return np.asarray(_j_probs(self._state), dtype=np.float64)

    def _k_prob_mask(self, mask, perm) -> float:
        p = float(_j_prob_mask(self._state, mask, perm))
        return min(max(p, 0.0), 1.0)

    def _k_collapse(self, mask, val, nrm_sq) -> None:
        self._state = _j_collapse(self._owned_state(), mask, val, nrm_sq)

    def MAll(self) -> int:
        """Device-side categorical sample; no 2^n host transfer
        (reference MAll ships probabilities to host)."""
        r = float(self.Rand())
        result = _device_get(lambda st: int(_j_sample(st, r)), self._state)
        self.SetPermutation(result)
        return result

    def MultiShotMeasureMask(self, q_powers, shots: int) -> dict:
        """Batched sampling with device-side bit compaction: the draw,
        the masked-bit gather, and the key packing are one jitted
        program; only (shots,) small ints reach the host, which then
        histograms them with one np.unique (no per-shot Python loop)."""
        from ..utils.bits import log2

        u = jnp.asarray(self.rng.uniform(shots), dtype=self.dtype)
        bits = jnp.asarray([log2(int(pw)) for pw in q_powers],
                           dtype=gk.IDX_DTYPE)
        keys = np.asarray(_j_multishot(self._state, u, bits))
        vals, counts = np.unique(keys, return_counts=True)
        return {int(v): int(c) for v, c in zip(vals, counts)}

    def _k_compose(self, other, start) -> None:
        other_planes = gk.to_planes(other.GetQuantumState(), self.dtype)
        self._state = gk.compose(
            self._state, other_planes, self.qubit_count, other.qubit_count, start
        )

    def _k_decompose(self, start, length) -> np.ndarray:
        m = gk.split_matrix(self._state, self.qubit_count, start, length)
        row_norms = jnp.sum(m[0] ** 2 + m[1] ** 2, axis=1)
        r0 = int(jnp.argmax(row_norms))
        nrm = jnp.sqrt(row_norms[r0])
        dest = m[:, r0, :] / nrm  # (2, 2^L)
        # rem = M @ conj(dest): plane algebra
        rem_re = m[0] @ dest[0] + m[1] @ dest[1]
        rem_im = m[1] @ dest[0] - m[0] @ dest[1]
        rem = jnp.stack([rem_re, rem_im])
        rn = jnp.sqrt(jnp.sum(rem[0] ** 2 + rem[1] ** 2))
        self._state = jnp.where(rn > 0, rem / rn, rem)
        return gk.from_planes(dest)

    def _k_dispose(self, start, length, perm) -> None:
        m = gk.split_matrix(self._state, self.qubit_count, start, length)
        if perm is not None:
            rem = m[:, :, perm]
        else:
            row_norms = jnp.sum(m[0] ** 2 + m[1] ** 2, axis=1)
            r0 = int(jnp.argmax(row_norms))
            dest = m[:, r0, :] / jnp.sqrt(row_norms[r0])
            rem_re = m[0] @ dest[0] + m[1] @ dest[1]
            rem_im = m[1] @ dest[0] - m[0] @ dest[1]
            rem = jnp.stack([rem_re, rem_im])
        rn = jnp.sqrt(jnp.sum(rem[0] ** 2 + rem[1] ** 2))
        self._state = jnp.where(rn > 0, rem / rn, rem)

    def _k_allocate(self, start, length) -> None:
        self._state = gk.allocate(self._state, self.qubit_count, start, length)

    def _k_normalize(self, nrm_sq) -> None:
        self._state = _j_normalize(self._owned_state(), nrm_sq)

    def _k_sum_sqr_diff(self, other) -> float:
        if isinstance(other, QEngineTPU):
            b = other._state.astype(self.dtype)
        else:
            b = gk.to_planes(other.GetQuantumState(), self.dtype)
        return float(_j_sum_sqr_diff(self._state, b))

    def _k_swap_bits(self, q1, q2) -> None:
        self._state = _j_swap_bits(self._owned_state(),
                                   self.qubit_count, q1, q2)

    def ExpectationBitsAll(self, bits, offset: int = 0) -> float:
        """One device reduction; the distribution never reaches the host."""
        return float(gk.expectation_bits(self._state, tuple(bits), offset))

    # ------------------------------------------------------------------
    # state access (host boundary: complex <-> planes)
    # ------------------------------------------------------------------

    def GetQuantumState(self) -> np.ndarray:
        return _device_get(gk.from_planes, self._state)

    def SetQuantumState(self, state) -> None:
        st = np.asarray(state).reshape(-1)
        if st.shape[0] != (1 << self.qubit_count):
            raise ValueError("state length mismatch")
        self._state = self._put(gk.to_planes(st, self.dtype))

    def GetAmplitude(self, perm: int) -> complex:
        amp = _device_get(
            lambda st: np.asarray(st[:, perm], dtype=np.float64), self._state)
        return complex(amp[0], amp[1])

    def SetAmplitude(self, perm: int, amp: complex) -> None:
        amp = complex(amp)
        self._state = self._state.at[:, perm].set(
            jnp.asarray([amp.real, amp.imag], dtype=self.dtype)
        )

    def SetPermutation(self, perm: int, phase=None) -> None:
        ph = self._rand_phase() if phase is None else complex(phase)
        st = jnp.zeros((2, 1 << self.qubit_count), dtype=self.dtype)
        st = st.at[:, perm].set(jnp.asarray([ph.real, ph.imag], dtype=self.dtype))
        self._state = self._put(st)
        self.running_norm = 1.0

    def Clone(self) -> "QEngineTPU":
        c = QEngineTPU(
            self.qubit_count, dtype=self.dtype, device_id=self._device_id,
            rng=self.rng.spawn(), do_normalize=self.do_normalize,
            rand_global_phase=self.rand_global_phase,
        )
        c._state = jnp.array(self._state, copy=True)
        return c

    def CloneEmpty(self) -> "QEngineTPU":
        return QEngineTPU(
            self.qubit_count, dtype=self.dtype, device_id=self._device_id,
            rng=self.rng.spawn(), do_normalize=self.do_normalize,
            rand_global_phase=self.rand_global_phase,
        )

    # -- async discipline (reference: DispatchQueue / clFinish) --

    def Finish(self) -> None:
        if self._state is not None:
            _device_get(self._state.block_until_ready)

    # -- device placement (reference: SetDevice, opencl.cpp:535) --

    def SetDevice(self, device_id: int) -> None:
        if device_id == self._device_id:
            return
        self._device = _discover(device_id)
        self._device_id = device_id
        self._state = self._put(self._state)

    def GetDevice(self) -> int:
        return self._device_id

    # -- cross-engine data plane --

    def ZeroAmplitudes(self) -> None:
        self._state = jnp.zeros_like(self._state)

    def IsZeroAmplitude(self) -> bool:
        return not bool(jnp.any(self._state != 0))

    def GetAmplitudePage(self, offset: int, length: int) -> np.ndarray:
        return _device_get(
            lambda st: gk.from_planes(st[:, offset:offset + length]),
            self._state)

    def SetAmplitudePage(self, page, offset: int) -> None:
        self._state = self._state.at[:, offset:offset + len(page)].set(
            gk.to_planes(page, self.dtype)
        )

    # ------------------------------------------------------------------
    # checkpoint protocol (checkpoint/registry.py)
    # ------------------------------------------------------------------

    _ckpt_kind = "tpu"

    def _ckpt_capture(self, capture_child):
        # bf16/f16 planes upcast losslessly to f32 for the archive; the
        # device dtype string restores the resident representation
        host_dt = (np.float64 if jnp.dtype(self.dtype).itemsize >= 8
                   else np.float32)
        planes = np.asarray(jax.device_get(self._state)).astype(host_dt)
        return {"kind": "tpu",
                "meta": {"n": self.qubit_count, "dtype": str(self.dtype),
                         "gate_count": int(self._gate_count),
                         "running_norm": float(self.running_norm)},
                "arrays": {"planes": planes}}

    def _ckpt_restore(self, arrays, meta, children, restore_child):
        if int(meta["n"]) != self.qubit_count:
            raise ValueError("checkpoint width mismatch")
        self.dtype = jnp.dtype(meta["dtype"])
        if self.dtype == jnp.dtype("float64") and not jax.config.jax_enable_x64:
            jax.config.update("jax_enable_x64", True)
        self._state = self._put(jnp.asarray(np.asarray(arrays["planes"]),
                                            dtype=self.dtype))
        self._gate_count = int(meta.get("gate_count", 0))
        self.running_norm = float(meta.get("running_norm", 1.0))
