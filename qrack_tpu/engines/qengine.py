"""QEngine: abstract dense ("Schrödinger") state-vector engine.

Re-design of the reference's QEngine contract (reference:
include/qengine.hpp:31-299 — Apply2x2/ApplyM/ProbReg/ProbMask/
GetAmplitudePage/SetAmplitudePage/ShuffleBuffers/CloneEmpty/queued-norm;
common measurement logic src/qengine/qengine.cpp). A concrete engine
(numpy oracle, JAX/TPU) implements the `_k_*` kernel contract below;
everything else — the whole QInterface surface, the ALU, parity,
sampling — is provided here once, shared by all dense backends.

Kernel contract (the analogue of the reference's OCLAPI enum,
include/common/oclapi.hpp:19-99):

  _k_apply_2x2(m2, target, controls, perm)     generic 2x2 (apply2x2*)
  _k_apply_diag(d0, d1, target, controls, perm) phase fast path (phase/z)
  _k_gather(src_idx)                            basis permutation (ALU, xmask, rol)
  _k_out_of_place(src, dst, passthrough)        mul/div/*modnout scatter
  _k_phase_fn(fn)                               diagonal complex factor:
                                                fn(xp, idx) -> (re, im)
  _k_probs()                                    |amp|^2 vector (host numpy)
  _k_prob_mask(mask, perm)                      masked-probability reduce
  _k_collapse(mask, val, nrm_sq)                projective collapse (applym/applymreg)
  _k_compose(other, start)                      tensor product (compose kernel)
  _k_decompose(start, length) -> dest_state     split separable subsystem
  _k_dispose(start, length, perm)               drop separable subsystem
  _k_allocate(start, length)                    insert |0> qubits
  _k_normalize(nrm_sq)                          nrmlze kernel
  _k_sum_sqr_diff(other)                        approxcompare kernel
  _k_swap_bits(q1, q2)                          swap as index relabel
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as np

from ..config import FP_NORM_EPSILON
from ..interface import QInterface
from ..ops import alu_kernels as alu
from .. import matrices as mat
from .. import telemetry as _tele
from ..utils.bits import bit_reg_mask, log2, is_pow2


def _parity_rz_split(mask):
    """Shared split-index body for the parity-phase family: factor
    cc + i*(±ss) selected on the parity of (index & mask); PhaseParity
    and UniformParityRZ differ only in their host-side angle prep."""
    def body(xp, pid, lidx, L, cc, ss):
        par = alu.split_parity(xp, pid, lidx, L, mask)
        return cc, xp.where(par == 1, ss, -ss)
    return body


class QEngine(QInterface):
    """Dense-ket engine base; see module docstring for the kernel contract."""

    # numpy-compatible module used by index kernels (jnp for the TPU engine)
    _xp = np

    # engine label in telemetry counter names (gate.<label>.<kind>.w<n>)
    _tele_name = "engine"

    # lazy gate-stream fusion (ops/fusion.py): engines that can lower a
    # pending gate window into one parametric program set _fuse_capable
    # and install a GateStreamFuser in __init__; the base class stays
    # eager (the CPU oracle must dispatch gate-at-a-time so fused stacks
    # can be differenced against it)
    _fuser = None
    _fuse_capable = False

    def _fuse_tick(self) -> None:
        """Per-logical-gate hook from GateStreamFuser.queue (drift
        accounting on the dense TPU engine; no-op elsewhere)."""

    # ------------------------------------------------------------------
    # gate primitive dispatch
    # ------------------------------------------------------------------

    def MCMtrxPerm(self, controls, mtrx, target, perm) -> None:
        self._check_qubit(target)
        for c in controls:
            self._check_qubit(c)
        m = np.asarray(mtrx, dtype=np.complex128).reshape(2, 2)
        if mat.is_identity(m) and abs(m[0, 0] - 1.0) <= 1e-14:
            return
        # gate.* counters record logical gates REQUESTED; the fused path
        # accounts its (fewer) physical sweeps under fuse.*/compile.fuse
        if mat.is_phase(m):
            if _tele._ENABLED:
                _tele.inc(f"gate.{self._tele_name}.diag.w{self.qubit_count}")
            fuser = self._fuser
            if fuser is not None and fuser.queue(tuple(controls), m, target, perm):
                return
            self._k_apply_diag(m[0, 0], m[1, 1], target, tuple(controls), perm)
        else:
            if _tele._ENABLED:
                _tele.inc(f"gate.{self._tele_name}.2x2.w{self.qubit_count}")
            fuser = self._fuser
            if fuser is not None and fuser.queue(tuple(controls), m, target, perm):
                return
            self._k_apply_2x2(m, target, tuple(controls), perm)

    # fast paths: X on many bits is one gather; Z/phase masks are diagonal
    # (reference kernels xmask/phasemask, src/common/qengine.cl:266-340)

    def XMask(self, mask: int) -> None:
        if not mask:
            return
        if _tele._ENABLED:
            _tele.inc(f"gate.{self._tele_name}.permute.w{self.qubit_count}")
        self._k_gather(
            lambda idx: idx ^ mask,
            split=(("xmask", mask),
                   lambda xp, pid, lidx, L: alu.xor_split(
                       xp, pid, lidx, L, mask & ((1 << L) - 1), mask >> L),
                   ()))

    def ZMask(self, mask: int) -> None:
        if not mask:
            return
        if _tele._ENABLED:
            _tele.inc(f"gate.{self._tele_name}.phase_mask.w{self.qubit_count}")

        def fn(xp, idx):
            par = self._parity_of(xp, idx, mask)
            return xp.where(par == 1, -1.0, 1.0), 0.0

        self._k_phase_fn(fn, split=(
            ("zmask", mask),
            lambda xp, pid, lidx, L: (
                xp.where(alu.split_parity(xp, pid, lidx, L, mask) == 1, -1.0, 1.0),
                0.0),
            ()))

    @staticmethod
    def _parity_of(xp, idx, mask):
        v = idx & mask
        # O(log n) parity fold; skip shifts >= the index dtype width
        width = v.dtype.itemsize * 8 if hasattr(v, "dtype") else 64
        for s in (32, 16, 8, 4, 2, 1):
            if s < width:
                v = v ^ (v >> s)
        return v & 1

    def PhaseParity(self, radians: float, mask: int) -> None:
        if not mask:
            return
        c, s_ = math.cos(radians / 2), math.sin(radians / 2)

        def fn(xp, idx):
            par = self._parity_of(xp, idx, mask)
            return c, xp.where(par == 1, s_, -s_)

        self._k_phase_fn(fn, split=(("parz", mask), _parity_rz_split(mask), (c, s_)))

    def Swap(self, q1: int, q2: int) -> None:
        if q1 == q2:
            return
        if _tele._ENABLED:
            _tele.inc(f"gate.{self._tele_name}.swap.w{self.qubit_count}")
        self._k_swap_bits(q1, q2)

    def Apply4x4(self, m: np.ndarray, q1: int, q2: int) -> None:
        if _tele._ENABLED:
            _tele.inc(f"gate.{self._tele_name}.4x4.w{self.qubit_count}")
        self._k_apply_4x4(np.asarray(m, dtype=np.complex128), q1, q2)

    def _k_apply_4x4(self, m4, q1, q2) -> None:
        # default: two-level synthesis (engines override with tensor op)
        from ..interface.synth import apply_small_unitary_via_primitive

        apply_small_unitary_via_primitive(self, m4, (q1, q2))

    # ------------------------------------------------------------------
    # probability / measurement
    # ------------------------------------------------------------------

    def Prob(self, q: int) -> float:
        self._check_qubit(q)
        return self._k_prob_mask(1 << q, 1 << q)

    def ProbAll(self, perm: int) -> float:
        return abs(self.GetAmplitude(perm)) ** 2

    def ProbReg(self, start: int, length: int, perm: int) -> float:
        return self._k_prob_mask(bit_reg_mask(start, length), perm << start)

    def ProbMask(self, mask: int, perm: int) -> float:
        return self._k_prob_mask(mask, perm)

    def ForceM(self, q: int, result: bool, do_force: bool = True, do_apply: bool = True) -> bool:
        self._check_qubit(q)
        prob_one = self.Prob(q)
        if do_force:
            res = bool(result)
        elif prob_one >= 1.0 - FP_NORM_EPSILON:
            res = True   # deterministic: no RNG draw (keeps streams
        elif prob_one <= FP_NORM_EPSILON:
            res = False  # aligned with the tableau engines)
        else:
            res = self.Rand() <= prob_one
        nrm_sq = prob_one if res else (1.0 - prob_one)
        if nrm_sq <= 0.0:
            raise RuntimeError("ForceM: forced result has zero probability")
        if do_apply:
            self._k_collapse(1 << q, (1 << q) if res else 0, nrm_sq)
        return res

    def ForceMParity(self, mask: int, result: bool, do_force: bool = True) -> bool:
        odd_prob = self.ProbParity(mask)
        if not do_force:
            if odd_prob >= 1.0 - FP_NORM_EPSILON:
                result = True   # deterministic: no draw (stream-aligned
            elif odd_prob <= FP_NORM_EPSILON:
                result = False  # with ForceM and the tableau path)
            else:
                result = self.Rand() <= odd_prob
        nrm_sq = odd_prob if result else (1.0 - odd_prob)
        if nrm_sq <= 0.0:
            raise RuntimeError("ForceMParity: forced result has zero probability")
        want = 1 if result else 0
        scale = 1.0 / math.sqrt(nrm_sq)

        def fn(xp, idx):
            par = self._parity_of(xp, idx, mask)
            return xp.where(par == want, scale, 0.0), 0.0

        self._k_phase_fn(fn, split=(
            ("forcempar", mask, want),
            lambda xp, pid, lidx, L, sc: (
                xp.where(alu.split_parity(xp, pid, lidx, L, mask) == want, sc, 0.0),
                0.0),
            (scale,)))
        return bool(result)

    def MAll(self) -> int:
        """Vectorized full measurement: sample one index from |amp|^2 and
        collapse (reference: per-engine MAll / SetPermutation)."""
        probs = self._k_probs()
        result = int(self.rng.choice_from_probs(probs, 1)[0])
        self.SetPermutation(result)
        return result

    def MultiShotMeasureMask(self, q_powers: Sequence[int], shots: int) -> dict:
        """Sampling without collapse via the masked marginal distribution
        (reference: src/qinterface/qinterface.cpp:807, engine-vectorized)."""
        bits = [log2(p) for p in q_powers]
        dist = self.ProbBitsAll(bits)
        draws = self.rng.choice_from_probs(dist, shots)
        out: dict = {}
        for d in draws:
            d = int(d)
            out[d] = out.get(d, 0) + 1
        return out

    def GetProbs(self) -> np.ndarray:
        return self._k_probs()

    # ------------------------------------------------------------------
    # ALU overrides: vectorized index-map kernels
    # (reference: qheader_alu.cl via src/qengine/arithmetic.cpp)
    # ------------------------------------------------------------------

    def INC(self, to_add: int, start: int, length: int) -> None:
        if not length:
            return
        self._check_range(start, length)
        to_add &= (1 << length) - 1
        if not to_add:
            return
        self._k_gather(
            lambda idx: alu.inc_src(self._xp, idx, to_add, start, length),
            split=(("inc", start, length),
                   lambda xp, pid, lidx, L, ta: alu.inc_src_split(
                       xp, pid, lidx, L, ta, start, length),
                   (to_add,)))

    def CINC(self, to_add: int, start: int, length: int, controls) -> None:
        controls = tuple(controls)
        if not controls:
            return self.INC(to_add, start, length)
        if not length:
            return
        to_add &= (1 << length) - 1
        if not to_add:
            return
        perm = (1 << len(controls)) - 1
        self._k_gather(
            lambda idx: alu.inc_src(self._xp, idx, to_add, start, length, controls, perm),
            split=(("cinc", start, length, controls),
                   lambda xp, pid, lidx, L, ta: alu.inc_src_split(
                       xp, pid, lidx, L, ta, start, length, controls, perm),
                   (to_add,)))

    def INCDECC(self, to_add: int, start: int, length: int, carry_index: int) -> None:
        if not length:
            return
        to_add &= (1 << (length + 1)) - 1
        if not to_add:
            return
        self._k_gather(
            lambda idx: alu.incdecc_src(self._xp, idx, to_add, start, length, carry_index),
            split=(("incdecc", start, length, carry_index),
                   lambda xp, pid, lidx, L, ta: alu.incdecc_src_split(
                       xp, pid, lidx, L, ta, start, length, carry_index),
                   (to_add,)))

    def INCBCD(self, to_add: int, start: int, length: int) -> None:
        """Packed-BCD add of decimal `to_add` (reference kernel incbcd,
        src/common/qheader_bcd.cl:1-67; QEngineCPU::INCBCD,
        src/qengine/arithmetic.cpp:777). Register length must be a
        multiple of 4; non-BCD basis states pass through."""
        if not length:
            return
        if length % 4:
            raise ValueError("BCD register length must be a multiple of 4")
        self._check_range(start, length)
        to_add %= 10 ** (length // 4)
        if not to_add:
            return
        self._k_gather(
            lambda idx: alu.incbcd_src(self._xp, idx, to_add, start, length),
            split=(("incbcd", start, length),
                   lambda xp, pid, lidx, L, digits: alu.incbcd_src_split(
                       xp, pid, lidx, L, digits, start, length),
                   (alu.bcd_digits(to_add, length // 4),)))

    def INCDECBCDC(self, to_add: int, start: int, length: int, carry_index: int) -> None:
        """Packed-BCD add with carry-out XOR (reference kernel
        incdecbcdc, src/common/qheader_bcd.cl:67-143)."""
        if not length:
            return
        if length % 4:
            raise ValueError("BCD register length must be a multiple of 4")
        self._check_range(start, length)
        to_add %= 10 ** (length // 4)
        self._k_gather(
            lambda idx: alu.incdecbcdc_src(
                self._xp, idx, to_add, start, length, carry_index),
            split=(("incdecbcdc", start, length, carry_index),
                   lambda xp, pid, lidx, L, digits: alu.incdecbcdc_src_split(
                       xp, pid, lidx, L, digits, start, length, carry_index),
                   (alu.bcd_digits(to_add, length // 4),)))

    def INCS(self, to_add: int, start: int, length: int, overflow_index: int) -> None:
        if not length:
            return
        self._k_gather(
            lambda idx: alu.incs_src(self._xp, idx, to_add, start, length, overflow_index),
            split=(("incs", start, length, overflow_index),
                   lambda xp, pid, lidx, L, ta: alu.incs_src_split(
                       xp, pid, lidx, L, ta, start, length, overflow_index),
                   (to_add & ((1 << length) - 1),)))

    def INCDECSC(self, to_add: int, start: int, length: int, *flags) -> None:
        if not length:
            return
        if len(flags) == 2:
            overflow_index, carry_index = flags
        else:
            overflow_index, carry_index = None, flags[0]
        self._k_gather(
            lambda idx: alu.incdecsc_src(
                self._xp, idx, to_add, start, length, carry_index, overflow_index
            ),
            split=(("incdecsc", start, length, carry_index, overflow_index),
                   lambda xp, pid, lidx, L, ta: alu.incdecsc_src_split(
                       xp, pid, lidx, L, ta, start, length, carry_index,
                       overflow_index),
                   (to_add & ((1 << (length + 1)) - 1),)))

    def ROL(self, shift: int, start: int, length: int) -> None:
        if length < 2 or not (shift % length):
            return
        sh = shift % length
        self._k_gather(
            lambda idx: alu.rol_src(self._xp, idx, sh, start, length),
            split=(("rol", sh, start, length),
                   lambda xp, pid, lidx, L: alu.rol_src_split(
                       xp, pid, lidx, L, sh, start, length),
                   ()))

    def ROR(self, shift: int, start: int, length: int) -> None:
        self.ROL(length - (shift % length) if length else 0, start, length)

    def MUL(self, to_mul: int, in_out_start: int, carry_start: int, length: int) -> None:
        if to_mul == 1 or not length:
            return
        if getattr(self, "_wide_alu", False):
            return self._muldiv_wide(to_mul, in_out_start, carry_start, length, False)
        src, dst = alu.mul_pair(self._xp, self.qubit_count, to_mul, in_out_start, carry_start, length)
        self._k_out_of_place(src, dst, None)

    def DIV(self, to_div: int, in_out_start: int, carry_start: int, length: int) -> None:
        if to_div == 1 or not length:
            return
        if getattr(self, "_wide_alu", False):
            return self._muldiv_wide(to_div, in_out_start, carry_start, length, True)
        src, dst = alu.mul_pair(self._xp, self.qubit_count, to_div, in_out_start, carry_start, length)
        self._k_out_of_place(dst, src, None)

    def CMUL(self, to_mul, in_out_start, carry_start, length, controls) -> None:
        controls = tuple(controls)
        if not controls:
            return self.MUL(to_mul, in_out_start, carry_start, length)
        if to_mul == 1 or not length:
            return
        if getattr(self, "_wide_alu", False):
            return self._muldiv_wide(to_mul, in_out_start, carry_start, length,
                                     False, controls)
        src, dst = alu.mul_pair(self._xp, self.qubit_count, to_mul, in_out_start, carry_start, length)
        self._ctrl_out_of_place(src, dst, controls)

    def CDIV(self, to_div, in_out_start, carry_start, length, controls) -> None:
        controls = tuple(controls)
        if not controls:
            return self.DIV(to_div, in_out_start, carry_start, length)
        if to_div == 1 or not length:
            return
        if getattr(self, "_wide_alu", False):
            return self._muldiv_wide(to_div, in_out_start, carry_start, length,
                                     True, controls)
        src, dst = alu.mul_pair(self._xp, self.qubit_count, to_div, in_out_start, carry_start, length)
        self._ctrl_out_of_place(dst, src, controls)

    def _muldiv_wide(self, to_mul, in_out_start, carry_start, length,
                     inverse, controls=()) -> None:
        """Width-generic MUL/DIV: the pair-scatter path builds full-width
        host index arrays, so past int32 widths the same map runs as a
        split-index gather — with host-built product tables below the
        table RAM cap, else recomputing products per-lane in uint32 limb
        arithmetic (the 2^L table RAM ceiling is gone; the MUL/DIV
        *register* itself stays <= 31 bits, the int32 lane bound —
        total ket width is unbounded)
        (reference width-generic mul/div kernels, qheader_alu.cl:~260)."""
        import os

        perm_all = (1 << len(controls)) - 1
        cap = min(int(os.environ.get("QRACK_WIDE_MUL_TABLE_QB", "24")), 31)
        table_free = (os.environ.get("QRACK_WIDE_MUL_TABLE_FREE") == "1"
                      or length > cap)
        if table_free:
            k, consts = alu.mul_consts(to_mul, length)
            src_split = (alu.div_src_split_tf if inverse
                         else alu.mul_src_split_tf)

            def body(xp, pid, lidx, L, consts_op):
                sp, sl, keep = src_split(xp, pid, lidx, L, consts_op, k,
                                         in_out_start, carry_start, length)
                if controls:
                    ok = alu.split_ctrl_match(xp, pid, lidx, L, controls,
                                              perm_all)
                    sp = xp.where(ok, sp, pid)
                    sl = xp.where(ok, sl, lidx)
                    keep = keep | ~ok
                return sp, sl, keep

            # to_mul rides the operand vector, NOT the cache key: every
            # multiplier with the same 2-adic valuation k shares one
            # compiled ring-gather program
            key = ("divwtf" if inverse else "mulwtf", k,
                   in_out_start, carry_start, length, controls)
            return self._k_gather(None, split=(key, body, (consts,)))
        lo, hi, inv, k = alu.mul_tables(to_mul, length)
        src_split = alu.div_src_split if inverse else alu.mul_src_split

        def body(xp, pid, lidx, L, lo_t, hi_t, inv_t):
            sp, sl, keep = src_split(xp, pid, lidx, L, lo_t, hi_t, inv_t, k,
                                     in_out_start, carry_start, length)
            if controls:
                ok = alu.split_ctrl_match(xp, pid, lidx, L, controls, perm_all)
                sp = xp.where(ok, sp, pid)
                sl = xp.where(ok, sl, lidx)
                keep = keep | ~ok
            return sp, sl, keep

        key = ("divw" if inverse else "mulw", k,
               in_out_start, carry_start, length, controls)
        self._k_gather(None, split=(key, body, (lo, hi, inv)))

    def _ctrl_out_of_place(self, src, dst, controls) -> None:
        """Restrict an out-of-place map to the control-matching subspace;
        everything else passes through (reference kernels cmul/cdiv)."""
        xp = self._xp
        cmask = 0
        for c in controls:
            cmask |= 1 << c
        sel = (src & cmask) == cmask
        self._k_out_of_place(src[sel], dst[sel] | cmask, cmask)

    # -- width-generic (split-index) modular out-of-place family --------
    # (the pair/scatter path builds full-size host index arrays; past
    #  int32 widths the gather form with an exact host-built residue
    #  table runs device-side at any width)

    def _modnout_wide(self, res_fn, in_start, length, out_start, ol,
                      inverse, key, controls=()):
        import numpy as _np

        # exact Python-int arithmetic on the host; values < 2^ol fit int32
        table = _np.asarray([res_fn(v) for v in range(1 << length)],
                            dtype=_np.int32)
        perm_all = (1 << len(controls)) - 1

        def body(xp, pid, lidx, L, tbl):
            sp, sl, keep = alu.modnout_gather_split(
                xp, pid, lidx, L, tbl, in_start, length, out_start, ol,
                inverse=inverse)
            if controls:
                ok = alu.split_ctrl_match(xp, pid, lidx, L, controls, perm_all)
                sp = xp.where(ok, sp, pid)
                sl = xp.where(ok, sl, lidx)
                keep = keep | ~ok
            return sp, sl, keep

        self._k_gather(None, split=(key, body, (table,)))

    def _mod_out_len(self, mod_n: int) -> int:
        return log2(mod_n) if is_pow2(mod_n) else (log2(mod_n) + 1)

    def MULModNOut(self, to_mul, mod_n, in_start, out_start, length) -> None:
        ol = self._mod_out_len(mod_n)
        if getattr(self, "_wide_alu", False):
            return self._modnout_wide(
                lambda v: (v * to_mul) % mod_n,
                in_start, length, out_start, ol, False,
                ("mulmod", in_start, length, out_start, ol))
        src, dst = alu.mulmodnout_pair(
            self._xp, self.qubit_count, to_mul, mod_n, in_start, out_start, length, ol
        )
        self._k_out_of_place(src, dst, None)

    def IMULModNOut(self, to_mul, mod_n, in_start, out_start, length) -> None:
        ol = self._mod_out_len(mod_n)
        if getattr(self, "_wide_alu", False):
            return self._modnout_wide(
                lambda v: (v * to_mul) % mod_n,
                in_start, length, out_start, ol, True,
                ("imulmod", in_start, length, out_start, ol))
        src, dst = alu.mulmodnout_pair(
            self._xp, self.qubit_count, to_mul, mod_n, in_start, out_start, length, ol
        )
        self._k_out_of_place(dst, src, None)

    def CMULModNOut(self, to_mul, mod_n, in_start, out_start, length, controls) -> None:
        controls = tuple(controls)
        if not controls:
            return self.MULModNOut(to_mul, mod_n, in_start, out_start, length)
        ol = self._mod_out_len(mod_n)
        if getattr(self, "_wide_alu", False):
            return self._modnout_wide(
                lambda v: (v * to_mul) % mod_n,
                in_start, length, out_start, ol, False,
                ("cmulmod", in_start, length, out_start, ol, controls), controls)
        src, dst = alu.mulmodnout_pair(
            self._xp, self.qubit_count, to_mul, mod_n, in_start, out_start, length, ol
        )
        self._ctrl_out_of_place(src, dst, controls)

    def CIMULModNOut(self, to_mul, mod_n, in_start, out_start, length, controls) -> None:
        controls = tuple(controls)
        if not controls:
            return self.IMULModNOut(to_mul, mod_n, in_start, out_start, length)
        ol = self._mod_out_len(mod_n)
        if getattr(self, "_wide_alu", False):
            return self._modnout_wide(
                lambda v: (v * to_mul) % mod_n,
                in_start, length, out_start, ol, True,
                ("cimulmod", in_start, length, out_start, ol, controls), controls)
        src, dst = alu.mulmodnout_pair(
            self._xp, self.qubit_count, to_mul, mod_n, in_start, out_start, length, ol
        )
        self._ctrl_out_of_place(dst, src, controls)

    def POWModNOut(self, base: int, mod_n: int, in_start, out_start, length) -> None:
        ol = self._mod_out_len(mod_n)
        if getattr(self, "_wide_alu", False):
            return self._modnout_wide(
                lambda v: pow(base, v, mod_n),
                in_start, length, out_start, ol, False,
                ("powmod", in_start, length, out_start, ol))
        src, dst = alu.powmodnout_pair(
            self._xp, self.qubit_count, base, mod_n, in_start, out_start, length, ol
        )
        self._k_out_of_place(src, dst, None)

    def CPOWModNOut(self, base, mod_n, in_start, out_start, length, controls) -> None:
        controls = tuple(controls)
        if not controls:
            return self.POWModNOut(base, mod_n, in_start, out_start, length)
        ol = self._mod_out_len(mod_n)
        if getattr(self, "_wide_alu", False):
            return self._modnout_wide(
                lambda v: pow(base, v, mod_n),
                in_start, length, out_start, ol, False,
                ("cpowmod", in_start, length, out_start, ol, controls), controls)
        src, dst = alu.powmodnout_pair(
            self._xp, self.qubit_count, base, mod_n, in_start, out_start, length, ol
        )
        self._ctrl_out_of_place(src, dst, controls)

    def IndexedLDA(self, index_start, index_length, value_start, value_length, values,
                   reset_value: bool = True) -> int:
        if reset_value:
            # reference zeroes the value register before loading
            # (src/qengine/arithmetic.cpp IndexedLDA: SetReg(..., 0))
            self.SetReg(value_start, value_length, 0)
        tbl64 = np.asarray(values, dtype=np.int64)
        self._k_gather(
            lambda idx: alu.indexed_lda_src(
                self._xp, idx, index_start, index_length, value_start,
                value_length, self._xp.asarray(tbl64)
            ),
            split=(("ilda", index_start, index_length, value_start, value_length),
                   lambda xp, pid, lidx, L, tbl: alu.indexed_lda_src_split(
                       xp, pid, lidx, L, tbl, index_start, index_length,
                       value_start, value_length),
                   (tbl64.astype(np.int32),)))
        return int(round(self.ExpectationBitsAll(
            list(range(value_start, value_start + value_length)))))

    def IndexedADC(self, index_start, index_length, value_start, value_length, carry_index, values) -> int:
        tbl64 = np.asarray(values, dtype=np.int64)
        self._k_gather(
            lambda idx: alu.indexed_adc_src(
                self._xp, idx, index_start, index_length, value_start, value_length,
                carry_index, self._xp.asarray(tbl64), sign=1,
            ),
            split=(("iadc", index_start, index_length, value_start, value_length,
                    carry_index),
                   lambda xp, pid, lidx, L, tbl: alu.indexed_adc_src_split(
                       xp, pid, lidx, L, tbl, index_start, index_length,
                       value_start, value_length, carry_index, sign=1),
                   (tbl64.astype(np.int32),)))
        return int(round(self.ExpectationBitsAll(
            list(range(value_start, value_start + value_length)))))

    def IndexedSBC(self, index_start, index_length, value_start, value_length, carry_index, values) -> int:
        tbl64 = np.asarray(values, dtype=np.int64)
        self._k_gather(
            lambda idx: alu.indexed_adc_src(
                self._xp, idx, index_start, index_length, value_start, value_length,
                carry_index, self._xp.asarray(tbl64), sign=-1,
            ),
            split=(("isbc", index_start, index_length, value_start, value_length,
                    carry_index),
                   lambda xp, pid, lidx, L, tbl: alu.indexed_adc_src_split(
                       xp, pid, lidx, L, tbl, index_start, index_length,
                       value_start, value_length, carry_index, sign=-1),
                   (tbl64.astype(np.int32),)))
        return int(round(self.ExpectationBitsAll(
            list(range(value_start, value_start + value_length)))))

    def Hash(self, start: int, length: int, values) -> None:
        tbl = np.asarray(values, dtype=np.int64)
        inv = np.empty_like(tbl)
        inv[tbl] = np.arange(tbl.shape[0], dtype=np.int64)
        inv_dev = self._xp.asarray(inv)
        self._k_gather(
            lambda idx: alu.hash_src(self._xp, idx, start, length, inv_dev),
            split=(("hash", start, length),
                   lambda xp, pid, lidx, L, tbl: alu.hash_src_split(
                       xp, pid, lidx, L, tbl, start, length),
                   (inv,)))

    def PhaseFlipIfLess(self, greater_perm: int, start: int, length: int) -> None:
        self._k_phase_fn(
            lambda xp, idx: (alu.phase_flip_less_factor(
                xp, idx, greater_perm, start, length), 0.0),
            split=(("pfless", start, length),
                   lambda xp, pid, lidx, L, gp: (alu.phase_flip_less_factor_split(
                       xp, pid, lidx, L, gp, start, length), 0.0),
                   (greater_perm,)))

    def CPhaseFlipIfLess(self, greater_perm: int, start: int, length: int, flag_index: int) -> None:
        self._k_phase_fn(
            lambda xp, idx: (alu.phase_flip_less_factor(
                xp, idx, greater_perm, start, length, flag_index), 0.0),
            split=(("cpfless", start, length, flag_index),
                   lambda xp, pid, lidx, L, gp: (alu.phase_flip_less_factor_split(
                       xp, pid, lidx, L, gp, start, length, flag_index), 0.0),
                   (greater_perm,)))

    def PhaseFlip(self) -> None:
        self._k_phase_fn(lambda xp, idx: (-1.0, 0.0),
                         split=(("pflip",),
                                lambda xp, pid, lidx, L: (-1.0, 0.0), ()))

    def UniformParityRZ(self, mask: int, angle: float) -> None:
        c, s_ = math.cos(angle), math.sin(angle)

        def fn(xp, idx):
            par = self._parity_of(xp, idx, mask)
            return c, xp.where(par == 1, s_, -s_)

        self._k_phase_fn(fn, split=(("parz", mask), _parity_rz_split(mask), (c, s_)))

    def CUniformParityRZ(self, controls, mask: int, angle: float) -> None:
        controls = tuple(controls)
        if not controls:
            return self.UniformParityRZ(mask, angle)
        c, s_ = math.cos(angle), math.sin(angle)
        cmask = 0
        for ctl in controls:
            cmask |= 1 << ctl
        perm_all = (1 << len(controls)) - 1

        def fn(xp, idx):
            par = self._parity_of(xp, idx, mask)
            active = (idx & cmask) == cmask
            fre = xp.where(active, c, 1.0)
            fim = xp.where(active, xp.where(par == 1, s_, -s_), 0.0)
            return fre, fim

        def body(xp, pid, lidx, L, cc, ss):
            par = alu.split_parity(xp, pid, lidx, L, mask)
            active = alu.split_ctrl_match(xp, pid, lidx, L, controls, perm_all)
            fre = xp.where(active, cc, 1.0)
            fim = xp.where(active, xp.where(par == 1, ss, -ss), 0.0)
            return fre, fim

        self._k_phase_fn(fn, split=(("cuprz", mask, controls), body, (c, s_)))

    # ------------------------------------------------------------------
    # structure ops
    # ------------------------------------------------------------------

    def Compose(self, other, start: Optional[int] = None) -> int:
        if start is None:
            start = self.qubit_count
        self._check_capacity(self.qubit_count + other.qubit_count)
        self._k_compose(other, start)
        self.qubit_count += other.qubit_count
        return start

    def Decompose(self, start: int, dest) -> None:
        length = dest.qubit_count
        self._check_range(start, length)
        dest_state = self._k_decompose(start, length)
        self.qubit_count -= length
        dest.SetQuantumState(dest_state)

    def Dispose(self, start: int, length: int, disposed_perm: Optional[int] = None) -> None:
        self._check_range(start, length)
        self._k_dispose(start, length, disposed_perm)
        self.qubit_count -= length

    def Allocate(self, start: int, length: int = 1) -> int:
        if length == 0:
            return start
        self._check_capacity(self.qubit_count + length)
        self._k_allocate(start, length)
        self.qubit_count += length
        return start

    def _check_capacity(self, qubit_count: int) -> None:
        """Growth guard (reference: allocation guards, oclengine.cpp:388);
        engines override with their width ceilings."""

    # ------------------------------------------------------------------
    # norm bookkeeping (reference: include/qengine.hpp:100-152)
    # ------------------------------------------------------------------

    def GetRunningNorm(self) -> float:
        return self.running_norm

    def UpdateRunningNorm(self, norm_thresh: float = -1.0) -> None:
        self.running_norm = float(self._k_probs().sum())

    def NormalizeState(self, nrm: float = -1.0, norm_thresh: float = -1.0, phase_arg: float = 0.0) -> None:
        if nrm < 0:
            self.UpdateRunningNorm()
            nrm = self.running_norm
        if nrm > 0 and abs(nrm - 1.0) > FP_NORM_EPSILON:
            self._k_normalize(nrm)
            self.running_norm = 1.0

    def SumSqrDiff(self, other) -> float:
        return self._k_sum_sqr_diff(other)

    # ------------------------------------------------------------------
    # kernel contract (subclass responsibilities)
    # ------------------------------------------------------------------

    def _k_apply_2x2(self, m2, target, controls, perm) -> None:
        raise NotImplementedError

    def _k_apply_diag(self, d0, d1, target, controls, perm) -> None:
        raise NotImplementedError

    def _k_gather(self, src_fn, split=None) -> None:
        raise NotImplementedError

    def _k_out_of_place(self, src_idx, dst_idx, passthrough_cmask) -> None:
        raise NotImplementedError

    def _k_phase_fn(self, fn, split=None) -> None:
        """Apply a per-index complex factor: fn(xp, idx) -> (re, im).
        `split` optionally carries the width-generic (key, body, targs)
        form, body(xp, pid, lidx, L, *targs) -> (re, im), used by paged
        engines past int32 widths (single-shard engines ignore it)."""
        raise NotImplementedError

    def _k_probs(self) -> np.ndarray:
        raise NotImplementedError

    def _k_prob_mask(self, mask, perm) -> float:
        raise NotImplementedError

    def _k_collapse(self, mask, val, nrm_sq) -> None:
        raise NotImplementedError

    def _k_compose(self, other, start) -> None:
        raise NotImplementedError

    def _k_decompose(self, start, length) -> np.ndarray:
        raise NotImplementedError

    def _k_dispose(self, start, length, perm) -> None:
        raise NotImplementedError

    def _k_allocate(self, start, length) -> None:
        raise NotImplementedError

    def _k_normalize(self, nrm_sq) -> None:
        raise NotImplementedError

    def _k_sum_sqr_diff(self, other) -> float:
        raise NotImplementedError

    def _k_swap_bits(self, q1, q2) -> None:
        raise NotImplementedError

    # -- cross-engine data plane (reference: include/qengine.hpp:128-145) --

    def ZeroAmplitudes(self) -> None:
        raise NotImplementedError

    def IsZeroAmplitude(self) -> bool:
        raise NotImplementedError

    def CopyStateVec(self, other) -> None:
        self.SetQuantumState(other.GetQuantumState())

    def GetAmplitudePage(self, offset: int, length: int) -> np.ndarray:
        raise NotImplementedError

    def SetAmplitudePage(self, page: np.ndarray, offset: int) -> None:
        raise NotImplementedError

    def ShuffleBuffers(self, other) -> None:
        """Swap the top half of self's ket with the bottom half of other's
        (reference: include/qengine.hpp:143; kernel shufflebuffers
        src/common/qengine.cl:1059)."""
        half = self.GetMaxQPower() >> 1
        top = self.GetAmplitudePage(half, half)
        bot = other.GetAmplitudePage(0, half)
        self.SetAmplitudePage(bot, half)
        other.SetAmplitudePage(top, 0)

    def CloneEmpty(self) -> "QEngine":
        raise NotImplementedError
