"""QEngineSparse: map-style sparse state vector on host.

Re-design of the reference's sparse storage (reference:
include/statevector.hpp StateVectorSparse — hash map of nonzero
amplitudes under QEngineCPU; Apply2x2Sparse src/qengine/state.cpp:535;
truncation env controls QRACK_SPARSE_TRUNCATION_THRESHOLD /
QRACK_SPARSE_MAX_ALLOC_MB README.md:96-100).

Representation: parallel sorted arrays (int64 indices, complex128
amplitudes) — numpy-vectorized merge/pair algebra instead of a hash
map, which keeps every gate O(nnz log nnz) and sampling O(nnz). Widths
to 62 qubits are exact as long as the support stays small (the role the
reference fills for beyond-memory registers)."""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

from ..config import FP_NORM_EPSILON
from ..interface import QInterface
from ..ops import alu_kernels as alu
from .. import matrices as mat


class QEngineSparse(QInterface):
    def __init__(self, qubit_count: int, init_state: int = 0,
                 truncation_threshold: Optional[float] = None,
                 max_entries: Optional[int] = None, **kwargs):
        super().__init__(qubit_count, init_state=init_state, **kwargs)
        if qubit_count > 62:
            raise MemoryError("QEngineSparse indexes with int64 (<= 62 qubits)")
        import os

        self.trunc = (truncation_threshold if truncation_threshold is not None
                      else float(os.environ.get("QRACK_SPARSE_TRUNCATION_THRESHOLD",
                                                "1e-16")))
        if max_entries is None:
            mb = int(os.environ.get("QRACK_SPARSE_MAX_ALLOC_MB", "512"))
            max_entries = (mb << 20) // 24  # 8B index + 16B amplitude
        self.max_entries = max_entries
        self._idx = np.array([init_state], dtype=np.int64)
        self._amp = np.array([self._rand_phase()], dtype=np.complex128)

    # ------------------------------------------------------------------

    def _rand_phase(self) -> complex:
        if self.rand_global_phase:
            ang = 2.0 * math.pi * self.Rand()
            return complex(math.cos(ang), math.sin(ang))
        return 1.0 + 0j

    def nnz(self) -> int:
        return int(self._idx.shape[0])

    def _prune(self) -> None:
        keep = (self._amp.real ** 2 + self._amp.imag ** 2) > self.trunc
        if not keep.all():
            self._idx = self._idx[keep]
            self._amp = self._amp[keep]
        if self._idx.shape[0] > self.max_entries:
            self.TruncateBySize(self.max_entries)

    def TruncateBySize(self, k: int) -> None:
        """Keep the k largest amplitudes then renormalize (reference:
        TruncateBySize include/qengine_cpu.hpp:111)."""
        if self._idx.shape[0] <= k:
            return
        p = self._amp.real ** 2 + self._amp.imag ** 2
        top = np.argpartition(p, -k)[-k:]
        order = np.argsort(self._idx[top])
        self._idx = self._idx[top][order]
        self._amp = self._amp[top][order]
        self.SparseRenorm()

    def SparseRenorm(self) -> None:
        """(reference: SparseRenorm include/qengine_cpu.hpp:118)."""
        nrm = np.linalg.norm(self._amp)
        if nrm > 0:
            self._amp = self._amp / nrm

    def _sort(self) -> None:
        order = np.argsort(self._idx)
        self._idx = self._idx[order]
        self._amp = self._amp[order]

    def _ctrl_sel(self, controls, perm):
        cmask = 0
        cval = 0
        for j, c in enumerate(controls):
            cmask |= 1 << c
            if (perm >> j) & 1:
                cval |= 1 << c
        return (self._idx & cmask) == cval

    # ------------------------------------------------------------------
    # gate primitive (reference: Apply2x2Sparse, state.cpp:535)
    # ------------------------------------------------------------------

    def MCMtrxPerm(self, controls, mtrx, target, perm) -> None:
        self._check_qubit(target)
        m = np.asarray(mtrx, dtype=np.complex128).reshape(2, 2)
        sel = self._ctrl_sel(tuple(controls), perm)
        tpow = np.int64(1 << target)
        if mat.is_phase(m):
            bit = (self._idx & tpow) != 0
            f = np.where(bit, m[1, 1], m[0, 0])
            self._amp = np.where(sel, self._amp * f, self._amp)
            self._prune()
            return
        if mat.is_invert(m):
            # an entry with target bit b flips to 1-b and picks up the
            # <1-b|M|b> coefficient
            bit = (self._idx & tpow) != 0
            f = np.where(bit, m[0, 1], m[1, 0])
            self._amp = np.where(sel, self._amp * f, self._amp)
            self._idx = np.where(sel, self._idx ^ tpow, self._idx)
            self._sort()
            self._prune()
            return
        # general: merge pairs over the participating base set
        part_idx = self._idx[sel]
        part_amp = self._amp[sel]
        rest_idx = self._idx[~sel]
        rest_amp = self._amp[~sel]
        base = np.unique(part_idx & ~tpow)
        # gather existing amplitudes at base and base|tpow
        a0 = np.zeros(base.shape[0], dtype=np.complex128)
        a1 = np.zeros(base.shape[0], dtype=np.complex128)
        pos0 = np.searchsorted(part_idx, base)
        hit0 = (pos0 < part_idx.shape[0])
        hit0 &= part_idx[np.minimum(pos0, part_idx.shape[0] - 1)] == base
        a0[hit0] = part_amp[pos0[hit0]]
        hi = base | tpow
        pos1 = np.searchsorted(part_idx, hi)
        hit1 = (pos1 < part_idx.shape[0])
        hit1 &= part_idx[np.minimum(pos1, part_idx.shape[0] - 1)] == hi
        a1[hit1] = part_amp[pos1[hit1]]
        n0 = m[0, 0] * a0 + m[0, 1] * a1
        n1 = m[1, 0] * a0 + m[1, 1] * a1
        self._idx = np.concatenate([rest_idx, base, hi])
        self._amp = np.concatenate([rest_amp, n0, n1])
        self._sort()
        self._prune()

    def Swap(self, q1: int, q2: int) -> None:
        if q1 == q2:
            return
        b1 = (self._idx >> q1) & 1
        b2 = (self._idx >> q2) & 1
        x = b1 ^ b2
        self._idx = self._idx ^ ((x << q1) | (x << q2))
        self._sort()

    def XMask(self, mask: int) -> None:
        if not mask:
            return
        self._idx = self._idx ^ np.int64(mask)
        self._sort()

    # ------------------------------------------------------------------
    # probability / measurement
    # ------------------------------------------------------------------

    def _probs_arr(self) -> np.ndarray:
        return self._amp.real ** 2 + self._amp.imag ** 2

    def Prob(self, q: int) -> float:
        self._check_qubit(q)
        bit = (self._idx >> q) & 1
        p = float(self._probs_arr()[bit == 1].sum())
        return min(max(p, 0.0), 1.0)

    def ProbMask(self, mask: int, perm: int) -> float:
        sel = (self._idx & mask) == perm
        return float(min(max(self._probs_arr()[sel].sum(), 0.0), 1.0))

    def ProbReg(self, start: int, length: int, perm: int) -> float:
        from ..utils.bits import bit_reg_mask

        return self.ProbMask(bit_reg_mask(start, length), perm << start)

    def ForceM(self, q: int, result: bool, do_force: bool = True, do_apply: bool = True) -> bool:
        p1 = self.Prob(q)
        if do_force:
            res = bool(result)
        elif p1 >= 1.0 - FP_NORM_EPSILON:
            res = True
        elif p1 <= FP_NORM_EPSILON:
            res = False
        else:
            res = self.Rand() <= p1
        nrm_sq = p1 if res else (1.0 - p1)
        if nrm_sq <= 0.0:
            raise RuntimeError("ForceM: forced result has zero probability")
        if do_apply:
            keep = (((self._idx >> q) & 1) == 1) == res
            self._idx = self._idx[keep]
            self._amp = self._amp[keep] / math.sqrt(nrm_sq)
        return res

    def MAll(self) -> int:
        p = self._probs_arr()
        pick = int(self.rng.choice_from_probs(p, 1)[0])
        result = int(self._idx[pick])
        self.SetPermutation(result)
        return result

    def MultiShotMeasureMask(self, q_powers, shots: int) -> dict:
        from ..utils.bits import log2

        p = self._probs_arr()
        draws = self.rng.choice_from_probs(p, shots)
        bits = [log2(int(pw)) for pw in q_powers]
        out: dict = {}
        for d in draws:
            i = int(self._idx[int(d)])
            key = 0
            for j, b in enumerate(bits):
                if (i >> b) & 1:
                    key |= 1 << j
            out[key] = out.get(key, 0) + 1
        return out

    # ------------------------------------------------------------------
    # ALU: forward index maps (reuse the kernel algebra with negated
    # operands — the reference mirrors this relationship between its
    # gather kernels and the sparse map update)
    # ------------------------------------------------------------------

    def INC(self, to_add: int, start: int, length: int) -> None:
        if not length:
            return
        self._idx = alu.inc_src(np, self._idx, -(to_add), start, length)
        self._sort()

    def CINC(self, to_add: int, start: int, length: int, controls) -> None:
        controls = tuple(controls)
        if not controls:
            return self.INC(to_add, start, length)
        perm = (1 << len(controls)) - 1
        self._idx = alu.inc_src(np, self._idx, -(to_add), start, length, controls, perm)
        self._sort()

    def INCDECC(self, to_add: int, start: int, length: int, carry_index: int) -> None:
        self._idx = alu.incdecc_src(np, self._idx, -(to_add), start, length, carry_index)
        self._sort()

    def ROL(self, shift: int, start: int, length: int) -> None:
        if length < 2 or not (shift % length):
            return
        self._idx = alu.rol_src(np, self._idx, length - (shift % length), start, length)
        self._sort()

    def ROR(self, shift: int, start: int, length: int) -> None:
        self.ROL(length - (shift % length) if length else 0, start, length)

    def Hash(self, start: int, length: int, values) -> None:
        tbl = np.asarray(values, dtype=np.int64)
        self._idx = alu.hash_src(np, self._idx, start, length, tbl)
        self._sort()

    def PhaseFlipIfLess(self, greater_perm: int, start: int, length: int) -> None:
        v = (self._idx >> start) & ((1 << length) - 1)
        self._amp = np.where(v < greater_perm, -self._amp, self._amp)

    # -- out-of-place arithmetic as forward maps over the nonzero list
    #    (reference kernels mul/div/*modnout, qheader_alu.cl; amplitudes
    #    outside the contract subspace are dropped, per reference) -----

    def _ctrl_keep(self, controls):
        if not controls:
            return np.ones_like(self._idx, dtype=bool)
        cmask = 0
        for c in controls:
            cmask |= 1 << c
        return (self._idx & cmask) == cmask

    def _apply_oop(self, fire, keep, new_idx) -> None:
        """Entries where fire&keep map to new_idx; fire&~keep drop;
        ~fire pass through."""
        ok = ~fire | keep
        idx = np.where(fire, new_idx, self._idx)[ok]
        amp = self._amp[ok]
        self._idx, self._amp = idx, amp
        self._sort()

    def MUL(self, to_mul: int, in_out_start: int, carry_start: int, length: int) -> None:
        self.CMUL(to_mul, in_out_start, carry_start, length, ())

    def CMUL(self, to_mul, in_out_start, carry_start, length, controls) -> None:
        if to_mul == 1 or not length:
            return
        lm = (1 << length) - 1
        fire = self._ctrl_keep(tuple(controls))
        x = (self._idx >> in_out_start) & lm
        c = (self._idx >> carry_start) & lm
        prod = x * int(to_mul)
        ni = alu._reg_set(np, self._idx, in_out_start, length, prod & lm)
        ni = alu._reg_set(np, ni, carry_start, length, (prod >> length) & lm)
        self._apply_oop(fire, c == 0, ni)

    def DIV(self, to_div: int, in_out_start: int, carry_start: int, length: int) -> None:
        self.CDIV(to_div, in_out_start, carry_start, length, ())

    def CDIV(self, to_div, in_out_start, carry_start, length, controls) -> None:
        if to_div == 1 or not length:
            return
        lm = (1 << length) - 1
        fire = self._ctrl_keep(tuple(controls))
        x = (self._idx >> in_out_start) & lm
        c = (self._idx >> carry_start) & lm
        combined = (c << length) | x
        keep = (combined % int(to_div)) == 0
        q = combined // int(to_div)
        keep &= q <= lm
        ni = alu._reg_set(np, self._idx, in_out_start, length, q & lm)
        ni = alu._reg_set(np, ni, carry_start, length, np.zeros_like(q))
        self._apply_oop(fire, keep, ni)

    def _mod_res(self, x, fn):
        ux, inv = np.unique(x, return_inverse=True)
        return np.asarray([fn(int(v)) for v in ux], dtype=np.int64)[inv]

    def _modnout(self, res_fn, mod_n, in_start, out_start, length, controls,
                 inverse: bool) -> None:
        ol = self._mod_out_length(mod_n)
        lm = (1 << length) - 1
        om = (1 << ol) - 1
        fire = self._ctrl_keep(tuple(controls))
        x = (self._idx >> in_start) & lm
        out = (self._idx >> out_start) & om
        res = self._mod_res(x, res_fn)
        if inverse:
            keep = out == res
            ni = alu._reg_set(np, self._idx, out_start, ol, np.zeros_like(res))
        else:
            keep = out == 0
            ni = alu._reg_set(np, self._idx, out_start, ol, res)
        self._apply_oop(fire, keep, ni)

    def MULModNOut(self, to_mul, mod_n, in_start, out_start, length) -> None:
        self._modnout(lambda v: (v * to_mul) % mod_n, mod_n,
                      in_start, out_start, length, (), False)

    def IMULModNOut(self, to_mul, mod_n, in_start, out_start, length) -> None:
        self._modnout(lambda v: (v * to_mul) % mod_n, mod_n,
                      in_start, out_start, length, (), True)

    def CMULModNOut(self, to_mul, mod_n, in_start, out_start, length, controls) -> None:
        self._modnout(lambda v: (v * to_mul) % mod_n, mod_n,
                      in_start, out_start, length, tuple(controls), False)

    def CIMULModNOut(self, to_mul, mod_n, in_start, out_start, length, controls) -> None:
        self._modnout(lambda v: (v * to_mul) % mod_n, mod_n,
                      in_start, out_start, length, tuple(controls), True)

    def POWModNOut(self, base, mod_n, in_start, out_start, length) -> None:
        self._modnout(lambda v: pow(base, v, mod_n), mod_n,
                      in_start, out_start, length, (), False)

    def CPOWModNOut(self, base, mod_n, in_start, out_start, length, controls) -> None:
        self._modnout(lambda v: pow(base, v, mod_n), mod_n,
                      in_start, out_start, length, tuple(controls), False)

    def IndexedLDA(self, index_start, index_length, value_start, value_length,
                   values, reset_value: bool = True) -> int:
        if reset_value:
            self.SetReg(value_start, value_length, 0)
        tbl = np.asarray(values, dtype=np.int64)
        # XOR-load is self-inverse, so the gather source map IS the
        # forward map
        self._idx = alu.indexed_lda_src(
            np, self._idx, index_start, index_length, value_start,
            value_length, tbl)
        self._sort()
        return int(round(self.ExpectationBitsAll(
            list(range(value_start, value_start + value_length)))))

    def IndexedADC(self, index_start, index_length, value_start, value_length,
                   carry_index, values) -> int:
        tbl = np.asarray(values, dtype=np.int64)
        self._idx = alu.indexed_adc_src(
            np, self._idx, index_start, index_length, value_start,
            value_length, carry_index, tbl, sign=-1)
        self._sort()
        return int(round(self.ExpectationBitsAll(
            list(range(value_start, value_start + value_length)))))

    def IndexedSBC(self, index_start, index_length, value_start, value_length,
                   carry_index, values) -> int:
        tbl = np.asarray(values, dtype=np.int64)
        self._idx = alu.indexed_adc_src(
            np, self._idx, index_start, index_length, value_start,
            value_length, carry_index, tbl, sign=1)
        self._sort()
        return int(round(self.ExpectationBitsAll(
            list(range(value_start, value_start + value_length)))))

    # ------------------------------------------------------------------
    # structure / state
    # ------------------------------------------------------------------

    def Compose(self, other, start: Optional[int] = None) -> int:
        if start is None:
            start = self.qubit_count
        if start != self.qubit_count:
            raise NotImplementedError("mid-insertion Compose on sparse engine")
        if self.qubit_count + other.qubit_count > 62:
            raise MemoryError("QEngineSparse indexes with int64 (<= 62 qubits)")
        if isinstance(other, QEngineSparse):
            oi, oa = other._idx, other._amp
        else:
            st = np.asarray(other.GetQuantumState())
            oi = np.nonzero(np.abs(st) > 1e-16)[0].astype(np.int64)
            oa = st[oi]
        self._idx = (self._idx[None, :] | (oi[:, None] << self.qubit_count)).reshape(-1)
        self._amp = (self._amp[None, :] * oa[:, None]).reshape(-1)
        self.qubit_count += other.qubit_count
        self._sort()
        self._prune()
        return start

    def Dispose(self, start: int, length: int, disposed_perm: Optional[int] = None) -> None:
        self._check_range(start, length)
        mask = ((1 << length) - 1) << start
        if disposed_perm is None:
            # qubits must be separable-deterministic: measure them out
            # (collapse leaves every entry agreeing on the disposed bits,
            # so the compaction below needs no projection)
            for i in range(length):
                self.M(start + i)
        else:
            keep = (self._idx & mask) == (disposed_perm << start)
            self._idx = self._idx[keep]
            self._amp = self._amp[keep]
            self.SparseRenorm()
        low = self._idx & ((1 << start) - 1)
        high = (self._idx >> (start + length)) << start
        self._idx = low | high
        self.qubit_count -= length
        self._sort()

    def Allocate(self, start: int, length: int = 1) -> int:
        if start < 0 or start > self.qubit_count:
            raise ValueError("Allocate start out of range")
        if self.qubit_count + length > 62:
            raise MemoryError("QEngineSparse indexes with int64 (<= 62 qubits)")
        low = self._idx & ((1 << start) - 1)
        high = (self._idx >> start) << (start + length)
        self._idx = low | high
        self.qubit_count += length
        return start

    def Decompose(self, start: int, dest) -> None:
        length = dest.qubit_count
        mask = ((1 << length) - 1) << start
        sub = (self._idx & mask) >> start
        # separable split: group by sub value; take dominant profile
        dense_sub = np.zeros(1 << length, dtype=np.complex128)
        np.add.at(dense_sub, sub, self._probs_arr())
        amps = np.sqrt(dense_sub.real)
        # recover phases from a representative entry per sub value
        for v in np.nonzero(amps)[0]:
            i = np.nonzero(sub == v)[0][0]
            ph = self._amp[i] / abs(self._amp[i])
            dense_sub[v] = amps[v] * ph
        dn = np.linalg.norm(dense_sub)
        if dn > 0:
            dense_sub = dense_sub / dn
        dest.SetQuantumState(dense_sub)
        # remainder: project onto the dominant sub value
        v0 = int(np.argmax(np.abs(dense_sub)))
        keep = sub == v0
        self._idx = self._idx[keep]
        self._amp = self._amp[keep]
        low = self._idx & ((1 << start) - 1)
        high = (self._idx >> (start + length)) << start
        self._idx = low | high
        self.qubit_count -= length
        self.SparseRenorm()
        self._sort()

    def GetAmplitude(self, perm: int) -> complex:
        pos = np.searchsorted(self._idx, perm)
        if pos < self._idx.shape[0] and self._idx[pos] == perm:
            return complex(self._amp[pos])
        return 0j

    def SetAmplitude(self, perm: int, amp: complex) -> None:
        pos = int(np.searchsorted(self._idx, perm))
        if pos < self._idx.shape[0] and self._idx[pos] == perm:
            self._amp[pos] = amp
        else:
            self._idx = np.insert(self._idx, pos, perm)
            self._amp = np.insert(self._amp, pos, amp)

    def GetQuantumState(self) -> np.ndarray:
        if self.qubit_count > 28:
            raise MemoryError("sparse state too wide to densify")
        out = np.zeros(1 << self.qubit_count, dtype=np.complex128)
        out[self._idx] = self._amp
        return out

    def SetQuantumState(self, state) -> None:
        st = np.asarray(state, dtype=np.complex128).reshape(-1)
        if st.shape[0] != (1 << self.qubit_count):
            raise ValueError("state length mismatch")
        nz = np.nonzero(np.abs(st) > 1e-16)[0]
        self._idx = nz.astype(np.int64)
        self._amp = st[nz]

    def SetPermutation(self, perm: int, phase=None) -> None:
        self._idx = np.array([perm], dtype=np.int64)
        self._amp = np.array([self._rand_phase() if phase is None else phase],
                             dtype=np.complex128)

    def Clone(self) -> "QEngineSparse":
        c = QEngineSparse(self.qubit_count, rng=self.rng.spawn(),
                          truncation_threshold=self.trunc,
                          max_entries=self.max_entries,
                          rand_global_phase=self.rand_global_phase)
        c._idx = self._idx.copy()
        c._amp = self._amp.copy()
        return c

    def SumSqrDiff(self, other) -> float:
        if isinstance(other, QEngineSparse):
            common, ia, ib = np.intersect1d(self._idx, other._idx,
                                            return_indices=True)
            inner = np.vdot(self._amp[ia], other._amp[ib])
        else:
            b = np.asarray(other.GetQuantumState(), dtype=np.complex128)
            inner = np.vdot(self._amp, b[self._idx])
        return float(max(0.0, 1.0 - abs(inner) ** 2))

    def GetProbs(self) -> np.ndarray:
        if self.qubit_count > 28:
            raise MemoryError("sparse state too wide to densify")
        out = np.zeros(1 << self.qubit_count, dtype=np.float64)
        out[self._idx] = self._probs_arr()
        return out

    def UpdateRunningNorm(self, norm_thresh: float = -1.0) -> None:
        self.running_norm = float(self._probs_arr().sum())

    def NormalizeState(self, nrm: float = -1.0, norm_thresh: float = -1.0,
                       phase_arg: float = 0.0) -> None:
        self.SparseRenorm()
        self.running_norm = 1.0

    # ------------------------------------------------------------------
    # checkpoint protocol (checkpoint/registry.py)
    # ------------------------------------------------------------------

    _ckpt_kind = "sparse"

    def _ckpt_capture(self, capture_child):
        return {"kind": "sparse",
                "meta": {"n": self.qubit_count, "trunc": float(self.trunc),
                         "max_entries": int(self.max_entries),
                         "running_norm": float(self.running_norm)},
                "arrays": {"idx": self._idx, "amp": self._amp}}

    def _ckpt_restore(self, arrays, meta, children, restore_child):
        if int(meta["n"]) != self.qubit_count:
            raise ValueError("checkpoint width mismatch")
        self.trunc = float(meta.get("trunc", self.trunc))
        self.max_entries = int(meta.get("max_entries", self.max_entries))
        self._idx = np.ascontiguousarray(arrays["idx"], dtype=np.int64)
        self._amp = np.ascontiguousarray(arrays["amp"], dtype=np.complex128)
        self.running_norm = float(meta.get("running_norm", 1.0))
