"""QEngineTurboQuant: block-compressed dense ket as the RESIDENT form.

Live-runtime counterpart of the reference's StateVectorTurboQuant
(reference: include/statevector_turboquant.hpp — each 2^p-amplitude
block is rotated by a random orthogonal matrix and quantized at b bits;
read/write decompress one block, operate, recompress; get_probs
decompresses block-by-block; serialization stores the seed, not the
matrices).  There it is a storage class under QEngineCPU; here it is an
engine whose amplitudes live in HBM as b-bit integer codes, giving a
4x (int8) or 2x (int16) wider single-device ket than float32 planes.

TPU-first mapping:

* codes (B, 2D) int8/int16 + scales (B,) f32 are the state.  The
  rotation is one shared seed-derived (2D, 2D) matrix, so
  decompress/compress is a batched matmul (128-wide at the default
  p=6) — MXU work, not scalar loops (storage/turboquant.py).
* Gates run CHUNK-WISE: a chunk of blocks is decompressed to f32
  planes, the existing XLA gate kernel applied, and the chunk
  recompressed — the float32 working set is bounded by the chunk size
  no matter the register width (the reference's per-block
  decompress-operate-recompress, scaled to batches the MXU likes).
  Targets above the chunk boundary pair chunks the way QPager pairs
  pages (parallel/pager.py), mixing two decompressed chunks.
* Normalization never touches codes: dequantization is linear in the
  per-block scales, so _k_normalize is a pure scale multiply.
* Untouched chunks (failed high-bit control tests) keep their exact
  codes — requantization error accrues only where a gate acted.

Everything the chunked hot path does not cover (ALU permutations,
compose/decompose, amplitude pages) falls back through the `_state`
property, which materializes f32 planes transiently — the analogue of
the reference QPager's CombineAndOp escape hatch.  `peak_transient_amps`
records the largest f32 materialization for memory-honesty tests.
"""

from __future__ import annotations

import math
import os

import numpy as np

import jax
import jax.numpy as jnp

from ..ops import gatekernels as gk
from ..storage import turboquant as tq
from .tpu import QEngineTPU


# ---------------------------------------------------------------------------
# module-level jitted programs (shape-polymorphic via jit cache)
# ---------------------------------------------------------------------------

@jax.jit
def _j_dec_rows(codes, scales, rot_t, qmax):
    """codes (B, 2D) -> original-space rows (B, 2D)."""
    y = codes.astype(jnp.float32) * (scales / qmax)[:, None]
    return y @ rot_t


@jax.jit
def _j_comp_rows(rows, rot, qmax_i):
    """original-space rows (B, 2D) -> (codes, scales)."""
    y = rows @ rot
    scales = jnp.max(jnp.abs(y), axis=1)
    safe = jnp.where(scales > 0, scales, 1.0)
    codes = jnp.round(y / safe[:, None] * qmax_i)
    return codes, scales


def _rows_to_planes(rows, block: int):
    b = rows.shape[0]
    return rows.reshape(b, 2, block).transpose(1, 0, 2).reshape(2, -1)


def _planes_to_rows(planes, block: int):
    b = planes.shape[-1] // block
    return planes.reshape(2, b, block).transpose(1, 0, 2).reshape(b, 2 * block)


@jax.jit
def _j_pair_mix(a, b, mp, lo_cmask, lo_cval):
    """2x2 mix of two decompressed chunks (the cross-chunk gate pair,
    like QPager's half-buffer exchange): new_a = m00*a + m01*b,
    new_b = m10*a + m11*b, applied only where the low control test
    passes."""
    mre, mim = mp[0], mp[1]

    def cm(re_f, im_f, v):
        return jnp.stack([v[0] * re_f - v[1] * im_f,
                          v[0] * im_f + v[1] * re_f])

    na = cm(mre[0, 0], mim[0, 0], a) + cm(mre[0, 1], mim[0, 1], b)
    nb = cm(mre[1, 0], mim[1, 0], a) + cm(mre[1, 1], mim[1, 1], b)
    idx = gk.iota_for(a)
    keep = (idx & lo_cmask) == lo_cval
    return jnp.where(keep, na, a), jnp.where(keep, nb, b)


@jax.jit
def _j_chunk_probs(codes, scales, rot_t, qmax):
    rows = _j_dec_rows(codes, scales, rot_t, qmax)
    return jnp.sum(rows * rows)


from functools import partial


@partial(jax.jit, static_argnums=(7,))
def _j_chunk_prob_mask(codes, scales, rot_t, qmax, base, mask, val, block):
    rows = _j_dec_rows(codes, scales, rot_t, qmax)
    pl = _rows_to_planes(rows, block)
    idx = base + gk.iota_for(pl)
    p = pl[0] ** 2 + pl[1] ** 2
    return jnp.sum(jnp.where((idx & mask) == val, p, 0.0))


class QEngineTurboQuant(QEngineTPU):
    """Dense ket resident as rotated b-bit block codes (lossy)."""

    def __init__(self, qubit_count: int, init_state: int = 0,
                 bits: int = None, block_pow: int = None,
                 chunk_qb: int = None, seed_rot: int = tq.DEFAULT_SEED,
                 **kwargs):
        self._tq_bits = int(bits if bits is not None
                            else os.environ.get("QRACK_TURBO_BITS",
                                                tq.DEFAULT_BITS))
        bp = int(block_pow if block_pow is not None
                 else os.environ.get("QRACK_TURBO_BLOCK_POW",
                                     tq.DEFAULT_BLOCK_POW))
        self._tq_block_pow = min(bp, qubit_count)
        cq = int(chunk_qb if chunk_qb is not None
                 else os.environ.get("QRACK_TURBOQUANT_CHUNK_QB", "20"))
        self._tq_chunk_pow = max(self._tq_block_pow, min(cq, qubit_count))
        self._tq_seed = seed_rot
        d = 1 << self._tq_block_pow
        self._rot = jnp.asarray(tq.rotation_matrix(2 * d, seed_rot))
        self._rot_t = self._rot.T
        self._qmax = float(tq.qmax(self._tq_bits))
        self._code_np = tq.code_dtype(self._tq_bits)
        self._codes = None
        self._scales = None
        self.peak_transient_amps = 0
        super().__init__(qubit_count, init_state=init_state, **kwargs)

    # ------------------------------------------------------------------
    # compressed <-> planes
    # ------------------------------------------------------------------

    @property
    def _block(self) -> int:
        return 1 << self._tq_block_pow

    @property
    def _chunk_amps(self) -> int:
        return 1 << self._tq_chunk_pow

    @property
    def _chunk_blocks(self) -> int:
        return self._chunk_amps // self._block

    def resident_bytes(self) -> int:
        """HBM bytes of the resident representation."""
        if self._codes is None:
            return 0
        return self._codes.nbytes + self._scales.nbytes

    def _compress_planes(self, planes):
        rows = _planes_to_rows(jnp.asarray(planes, jnp.float32), self._block)
        codes, scales = _j_comp_rows(rows, self._rot, self._qmax)
        self._codes = codes.astype(self._code_np)
        self._scales = scales

    def _decompress_planes(self):
        rows = _j_dec_rows(self._codes, self._scales, self._rot_t, self._qmax)
        return _rows_to_planes(rows, self._block)

    # the fallback data plane: any inherited kernel that reads/writes
    # `_state` transparently decompresses/recompresses the whole ket
    @property
    def _state(self):
        if self._codes is None:
            return None
        self.peak_transient_amps = max(self.peak_transient_amps,
                                       1 << self.qubit_count)
        return self._decompress_planes()

    @_state.setter
    def _state(self, planes) -> None:
        if planes is None:
            self._codes = None
            self._scales = None
            return
        # width may have changed (compose/decompose/allocate funnel
        # through the fallback): re-derive the block layout
        n_amps = planes.shape[-1]
        self.qubit_count = int(round(math.log2(n_amps)))
        if self._tq_block_pow > self.qubit_count:
            self._tq_block_pow = self.qubit_count
            d = 1 << self._tq_block_pow
            self._rot = jnp.asarray(tq.rotation_matrix(2 * d, self._tq_seed))
            self._rot_t = self._rot.T
        self._tq_chunk_pow = max(self._tq_block_pow,
                                 min(self._tq_chunk_pow, self.qubit_count))
        self._compress_planes(planes)

    # ------------------------------------------------------------------
    # chunk helpers
    # ------------------------------------------------------------------

    def _n_chunks(self) -> int:
        return max(1, (1 << self.qubit_count) // self._chunk_amps)

    def _chunk_slice(self, c: int) -> slice:
        cb = self._chunk_blocks
        return slice(c * cb, (c + 1) * cb)

    def _dec_chunk(self, c: int):
        sl = self._chunk_slice(c)
        rows = _j_dec_rows(self._codes[sl], self._scales[sl],
                           self._rot_t, self._qmax)
        return _rows_to_planes(rows, self._block)

    def _comp_chunk(self, planes):
        rows = _planes_to_rows(planes, self._block)
        codes, scales = _j_comp_rows(rows, self._rot, self._qmax)
        return codes.astype(self._code_np), scales

    def _scatter_chunks(self, updates) -> None:
        """Write back {chunk_index: (codes, scales)} in one pass."""
        if not updates:
            return
        cparts, sparts = [], []
        for c in range(self._n_chunks()):
            sl = self._chunk_slice(c)
            if c in updates:
                cc, ss = updates[c]
                cparts.append(cc)
                sparts.append(ss)
            else:
                cparts.append(self._codes[sl])
                sparts.append(self._scales[sl])
        self._codes = jnp.concatenate(cparts)
        self._scales = jnp.concatenate(sparts)

    def _note_transient(self, n_chunks_live: int) -> None:
        self.peak_transient_amps = max(
            self.peak_transient_amps, n_chunks_live * self._chunk_amps)

    # ------------------------------------------------------------------
    # chunked kernel overrides (the hot path)
    # ------------------------------------------------------------------

    def _k_apply_2x2(self, m2, target, controls, perm) -> None:
        cmask, cval = self._cmask_cval(controls, perm)
        mp = gk.mtrx_planes(np.asarray(m2, dtype=np.complex128), jnp.float32)
        ca = self._tq_chunk_pow
        cs = self._chunk_amps
        hi_cmask, hi_cval = cmask >> ca, cval >> ca
        lo_cmask, lo_cval = cmask & (cs - 1), cval & (cs - 1)
        updates = {}
        if target < ca:
            self._note_transient(1)
            for c in range(self._n_chunks()):
                if (c & hi_cmask) != hi_cval:
                    continue
                pl = gk.apply_2x2(self._dec_chunk(c), mp, ca, target,
                                  lo_cmask, lo_cval)
                updates[c] = self._comp_chunk(pl)
        else:
            self._note_transient(2)
            tb = 1 << (target - ca)
            for c in range(self._n_chunks()):
                if c & tb:
                    continue
                if (c & hi_cmask) != hi_cval:
                    continue
                a, b = self._dec_chunk(c), self._dec_chunk(c | tb)
                na, nb = _j_pair_mix(a, b, mp, lo_cmask, lo_cval)
                updates[c] = self._comp_chunk(na)
                updates[c | tb] = self._comp_chunk(nb)
        self._scatter_chunks(updates)

    def _k_apply_diag(self, d0, d1, target, controls, perm) -> None:
        cmask, cval = self._cmask_cval(controls, perm)
        ca = self._tq_chunk_pow
        cs = self._chunk_amps
        hi_cmask, hi_cval = cmask >> ca, cval >> ca
        lo_cmask, lo_cval = cmask & (cs - 1), cval & (cs - 1)
        updates = {}
        self._note_transient(1)
        for c in range(self._n_chunks()):
            if (c & hi_cmask) != hi_cval:
                continue
            if target >= ca:
                # the whole chunk shares the target bit value
                f = d1 if (c >> (target - ca)) & 1 else d0
                if lo_cmask == 0 and f == 1.0:
                    continue
                pl = gk.apply_diag(self._dec_chunk(c), f.real, f.imag,
                                   f.real, f.imag, ca, 0,
                                   lo_cmask, lo_cval)
            else:
                pl = gk.apply_diag(self._dec_chunk(c),
                                   complex(d0).real, complex(d0).imag,
                                   complex(d1).real, complex(d1).imag,
                                   ca, 1 << target, lo_cmask, lo_cval)
            updates[c] = self._comp_chunk(pl)
        self._scatter_chunks(updates)

    def _k_phase_fn(self, fn, split=None) -> None:
        cs = self._chunk_amps
        updates = {}
        self._note_transient(1)
        for c in range(self._n_chunks()):
            pl = self._dec_chunk(c)
            idx = jnp.asarray(c * cs, gk.IDX_DTYPE) + gk.iota_for(pl)
            fre, fim = fn(jnp, idx)
            updates[c] = self._comp_chunk(gk.cmul(fre, fim, pl))
        self._scatter_chunks(updates)

    def _k_prob_mask(self, mask, perm) -> float:
        cs = self._chunk_amps
        total = 0.0
        for c in range(self._n_chunks()):
            sl = self._chunk_slice(c)
            total += float(_j_chunk_prob_mask(
                self._codes[sl], self._scales[sl], self._rot_t, self._qmax,
                c * cs, mask, perm, int(self._block)))
        return min(max(total, 0.0), 1.0)

    def _k_collapse(self, mask, val, nrm_sq) -> None:
        cs = self._chunk_amps
        scale = 1.0 / math.sqrt(nrm_sq)
        updates = {}
        self._note_transient(1)
        for c in range(self._n_chunks()):
            pl = self._dec_chunk(c)
            idx = jnp.asarray(c * cs, gk.IDX_DTYPE) + gk.iota_for(pl)
            keep = (idx & mask) == val
            pl = jnp.where(keep, pl * scale, jnp.zeros((), pl.dtype))
            updates[c] = self._comp_chunk(pl)
        self._scatter_chunks(updates)

    def _k_normalize(self, nrm_sq) -> None:
        # dequantization is linear in scales: normalization never
        # decompresses (see module docstring)
        self._scales = self._scales * (1.0 / math.sqrt(nrm_sq))

    def MAll(self) -> int:
        """Two-stage chunked sampling: categorical over per-chunk
        probability masses, then within the drawn chunk — never
        materializes more than one chunk."""
        n_ch = self._n_chunks()
        masses = np.asarray([
            float(_j_chunk_probs(self._codes[self._chunk_slice(c)],
                                 self._scales[self._chunk_slice(c)],
                                 self._rot_t, self._qmax))
            for c in range(n_ch)])
        tot = masses.sum()
        u = self.Rand() * tot
        acc = 0.0
        chosen = n_ch - 1
        for c in range(n_ch):
            acc += masses[c]
            if u <= acc:
                chosen = c
                break
        self._note_transient(1)
        pl = self._dec_chunk(chosen)
        local = int(_j_sample_chunk(pl, float(self.Rand())))
        result = chosen * self._chunk_amps + local
        self.SetPermutation(result)
        return result

    # ------------------------------------------------------------------
    # serialization: seed + scales + codes (reference stores the seed,
    # never the matrices — statevector_turboquant.hpp serialization)
    # ------------------------------------------------------------------

    def SaveTurboQuant(self, path: str) -> None:
        np.savez_compressed(path, codes=np.asarray(self._codes),
                            scales=np.asarray(self._scales),
                            n=self.qubit_count, bits=self._tq_bits,
                            block_pow=self._tq_block_pow, seed=self._tq_seed)

    @classmethod
    def LoadTurboQuant(cls, path: str, **kwargs):
        with np.load(path if str(path).endswith(".npz")
                     else str(path) + ".npz") as z:
            eng = cls(int(z["n"]), bits=int(z["bits"]),
                      block_pow=int(z["block_pow"]), seed_rot=int(z["seed"]),
                      **kwargs)
            eng._codes = jnp.asarray(z["codes"])
            eng._scales = jnp.asarray(z["scales"])
        return eng


@jax.jit
def _j_sample_chunk(planes, u):
    p = planes[0] ** 2 + planes[1] ** 2
    cdf = jnp.cumsum(p)
    idx = jnp.searchsorted(cdf, u * cdf[-1], side="right")
    return jnp.minimum(idx, p.shape[0] - 1)
