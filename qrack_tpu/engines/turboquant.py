"""QEngineTurboQuant: block-compressed dense ket as the RESIDENT form.

Live-runtime counterpart of the reference's StateVectorTurboQuant
(reference: include/statevector_turboquant.hpp — each 2^p-amplitude
block is rotated by a random orthogonal matrix and quantized at b bits;
read/write decompress one block, operate, recompress; get_probs
decompresses block-by-block; serialization stores the seed, not the
matrices).  There it is a storage class under QEngineCPU; here it is an
engine whose amplitudes live in HBM as b-bit integer codes, giving a
4x (int8) or 2x (int16) wider single-device ket than float32 planes.
The sharded composition (parallel/turboquant_pager.py QPagerTurboQuant)
distributes the chunk axis over a pages mesh, so the beyond-HBM width
story multiplies with the beyond-single-chip one.

TPU-first mapping:

* codes (B, 2D) int8/int16 + scales (B,) f32 are the state.  The
  rotation is one shared seed-derived (2D, 2D) matrix, so
  decompress/compress is a batched matmul (128-wide at the default
  p=6) — MXU work, not scalar loops (storage/turboquant.py).
* Gates run CHUNK-WISE: a chunk of blocks is decompressed to f32
  planes, the existing XLA gate kernel applied, and the chunk
  recompressed — the float32 working set is bounded by the chunk size
  no matter the register width (the reference's per-block
  decompress-operate-recompress, scaled to batches the MXU likes).
  Targets above the chunk boundary pair chunks the way QPager pairs
  pages (parallel/pager.py), mixing two decompressed chunks.
* The chunk axis is a `lax.map` dimension INSIDE one cached jitted
  program per gate family: a gate is O(1) dispatches and one in-place
  donated update of the resident code array regardless of chunk count,
  while the loop body keeps the decompressed working set at one (or
  one pair of) chunk(s).  Index math inside the loop is split
  (chunk_id, local_index) int32 pairs — exact past 31 qubits without
  int64, the same scheme as QPager's (page, local) masks.
* Normalization never touches codes: dequantization is linear in the
  per-block scales, so _k_normalize is a pure scale multiply.
* Untouched chunks (failed high-bit control tests) keep their exact
  codes — requantization error accrues only where a gate acted.

Everything the chunked hot path does not cover (ALU permutations,
compose/decompose, amplitude pages) falls back through the `_state`
property, which materializes f32 planes transiently — the analogue of
the reference QPager's CombineAndOp escape hatch.  `peak_transient_amps`
records the largest f32 materialization for memory-honesty tests.
"""

from __future__ import annotations

import math
import os

import numpy as np

import jax
import jax.numpy as jnp

from ..ops import gatekernels as gk
from ..storage import turboquant as tq
from .. import matrices as mat
from .. import telemetry as _tele
from ..telemetry import roofline as _roofline
from .tpu import QEngineTPU


# ---------------------------------------------------------------------------
# module-level jitted programs (shape-polymorphic via jit cache)
# ---------------------------------------------------------------------------

# compiled chunked-gate programs, keyed on (kind, layout, gate statics) —
# the same cached-builder discipline as parallel/pager.py's _PROGRAMS,
# but BOUNDED: an LRU with a cap (QRACK_TQ_PROGRAM_CACHE_CAP) so a
# long-lived process stops accumulating compiled programs forever, and
# mesh-derived key parts (QPagerTurboQuant._layout_key) are weakly tied
# to their mesh — entries die with it instead of pinning it.  Hit/miss/
# eviction stats surface as compile.turboquant.* telemetry counters and
# via _PROGRAMS.stats().
_PROGRAMS = _tele.ProgramCache(
    "turboquant", cap_env="QRACK_TQ_PROGRAM_CACHE_CAP", default_cap=256)


def _program(key, builder, site: str = "turboquant.dispatch"):
    # cached-with-the-program resilience wrapper — same discipline as
    # parallel/pager.py's _program (disabled cost: one boolean test)
    from .. import resilience as _res

    return _PROGRAMS.get_or_build(
        key, lambda: _res.instrument_dispatch(site, builder()))


def _dec_rows_f(codes, scales, rot_t, qmax):
    """Decompress codes (B, 2D) -> original-space rows (trace-safe:
    composes inside lax.map bodies as well as under plain jit)."""
    y = codes.astype(jnp.float32) * (scales / qmax)[:, None]
    return y @ rot_t


def _comp_rows_f(rows, rot, qmax, code_dtype):
    """Recompress original-space rows (B, 2D) -> (codes, scales)."""
    y = rows @ rot
    scales = jnp.max(jnp.abs(y), axis=1)
    safe = jnp.where(scales > 0, scales, 1.0)
    codes = jnp.round(y / safe[:, None] * qmax).astype(code_dtype)
    return codes, scales


_j_dec_rows = jax.jit(_dec_rows_f)


from functools import partial


@partial(jax.jit, static_argnums=(3,))
def _j_comp_full(rows, rot, qmax, code_dtype_name):
    return _comp_rows_f(rows, rot, qmax, jnp.dtype(code_dtype_name))


def _rows_to_planes(rows, block: int):
    b = rows.shape[0]
    return rows.reshape(b, 2, block).transpose(1, 0, 2).reshape(2, -1)


def _planes_to_rows(planes, block: int):
    b = planes.shape[-1] // block
    return planes.reshape(2, b, block).transpose(1, 0, 2).reshape(b, 2 * block)


def _pair_mix_f(a, b, mp, lo_cmask, lo_cval):
    """2x2 mix of two decompressed chunks (the cross-chunk gate pair,
    like QPager's half-buffer exchange): new_a = m00*a + m01*b,
    new_b = m10*a + m11*b, applied only where the low control test
    passes."""
    mre, mim = mp[0], mp[1]

    def cm(re_f, im_f, v):
        return jnp.stack([v[0] * re_f - v[1] * im_f,
                          v[0] * im_f + v[1] * re_f])

    na = cm(mre[0, 0], mim[0, 0], a) + cm(mre[0, 1], mim[0, 1], b)
    nb = cm(mre[1, 0], mim[1, 0], a) + cm(mre[1, 1], mim[1, 1], b)
    idx = gk.iota_for(a)
    keep = (idx & lo_cmask) == lo_cval
    return jnp.where(keep, na, a), jnp.where(keep, nb, b)


@jax.jit
def _j_chunk_masses(codes3, scales2, qmax):
    """Per-chunk probability masses WITHOUT decompressing: the block
    rotation is orthogonal, so row norms are invariant and each chunk's
    mass is sum((codes * scale/qmax)^2) — one elementwise reduction
    over the resident int codes, no matmul, no f32 ket."""
    y = codes3.astype(jnp.float32) * (scales2 / qmax)[..., None]
    return jnp.sum(y * y, axis=(1, 2))


# ---------------------------------------------------------------------------
# chunked-gate run bodies, shared by the single-device engine (plain jit,
# cid0=0) and the sharded QPagerTurboQuant (shard_map, cid0=page offset).
# Each returns a pure fn over chunk-major views (C, cb, 2D)/(C, cb); the
# trailing cid0 operand is the GLOBAL id of local chunk 0.
# ---------------------------------------------------------------------------


def _mk_gate_low(ca, block, cdt, qmax, target):
    def run(codes3, scales2, rot, rot_t, mp,
            hi_cmask, hi_cval, lo_cmask, lo_cval, cid0):
        def body(args):
            cid, cc, ss = args
            pl = _rows_to_planes(_dec_rows_f(cc, ss, rot_t, qmax), block)
            out = gk.apply_2x2(pl, mp, ca, target, lo_cmask, lo_cval)
            nc, ns = _comp_rows_f(_planes_to_rows(out, block), rot, qmax, cdt)
            sel = (cid & hi_cmask) == hi_cval
            return jnp.where(sel, nc, cc), jnp.where(sel, ns, ss)

        cids = cid0 + jnp.arange(codes3.shape[0], dtype=gk.IDX_DTYPE)
        return jax.lax.map(body, (cids, codes3, scales2))

    return run


def _mk_gate_pair(ca, block, cdt, qmax, tb_pos):
    """Pair mixing for a target whose chunk bit is LOCAL to the shard
    (tb_pos below the sharded page bits; cid0 is a multiple of the local
    chunk count, so local pair structure equals global)."""

    def run(codes3, scales2, rot, rot_t, mp,
            hi_cmask, hi_cval, lo_cmask, lo_cval, cid0):
        C, cb, twoD = codes3.shape
        lo_n = 1 << tb_pos
        hi_n = C // (2 * lo_n)
        # chunk id bits [hi | pair-bit | lo]: expose the pair axis,
        # map over (hi, lo) pairs
        c5 = (codes3.reshape(hi_n, 2, lo_n, cb, twoD)
              .transpose(1, 0, 2, 3, 4).reshape(2, C // 2, cb, twoD))
        s4 = (scales2.reshape(hi_n, 2, lo_n, cb)
              .transpose(1, 0, 2, 3).reshape(2, C // 2, cb))

        def body(args):
            pid, cca, ccb, ssa, ssb = args
            lpart = pid & (lo_n - 1)
            cid_a = cid0 + (((pid >> tb_pos) << (tb_pos + 1)) | lpart)
            a = _rows_to_planes(_dec_rows_f(cca, ssa, rot_t, qmax), block)
            b = _rows_to_planes(_dec_rows_f(ccb, ssb, rot_t, qmax), block)
            na, nb = _pair_mix_f(a, b, mp, lo_cmask, lo_cval)
            nca, nsa = _comp_rows_f(_planes_to_rows(na, block), rot,
                                    qmax, cdt)
            ncb, nsb = _comp_rows_f(_planes_to_rows(nb, block), rot,
                                    qmax, cdt)
            # controls never sit on the target bit, so the hi test is
            # identical for both pair halves
            sel = (cid_a & hi_cmask) == hi_cval
            return (jnp.where(sel, nca, cca), jnp.where(sel, ncb, ccb),
                    jnp.where(sel, nsa, ssa), jnp.where(sel, nsb, ssb))

        pids = jnp.arange(C // 2, dtype=gk.IDX_DTYPE)
        nca, ncb, nsa, nsb = jax.lax.map(
            body, (pids, c5[0], c5[1], s4[0], s4[1]))
        nc = (jnp.stack([nca, ncb]).reshape(2, hi_n, lo_n, cb, twoD)
              .transpose(1, 0, 2, 3, 4).reshape(C, cb, twoD))
        ns = (jnp.stack([nsa, nsb]).reshape(2, hi_n, lo_n, cb)
              .transpose(1, 0, 2, 3).reshape(C, cb))
        return nc, ns

    return run


def _mk_diag(ca, block, cdt, qmax):
    def run(codes3, scales2, rot, rot_t, d0re, d0im, d1re, d1im,
            tmask_lo, tb_hi, lo_cmask, lo_cval, hi_cmask, hi_cval, cid0):
        def body(args):
            cid, cc, ss = args
            pl = _rows_to_planes(_dec_rows_f(cc, ss, rot_t, qmax), block)
            lidx = gk.iota_for(pl)
            hi_bit = (cid & tb_hi) != 0
            bit = ((lidx & tmask_lo) != 0) | hi_bit
            fre = jnp.where(bit, d1re, d0re)
            fim = jnp.where(bit, d1im, d0im)
            active = (lidx & lo_cmask) == lo_cval
            fre = jnp.where(active, fre, 1.0)
            fim = jnp.where(active, fim, 0.0)
            out = gk.cmul(fre, fim, pl)
            nc, ns = _comp_rows_f(_planes_to_rows(out, block), rot,
                                  qmax, cdt)
            # exactness: a chunk whose factor is constant 1 (target
            # above the chunk selecting a unit diagonal, no low
            # controls) must keep its codes bit-for-bit
            cf_re = jnp.where(hi_bit, d1re, d0re)
            cf_im = jnp.where(hi_bit, d1im, d0im)
            ident = ((tmask_lo == 0) & (lo_cmask == 0)
                     & (cf_re == 1.0) & (cf_im == 0.0))
            sel = ((cid & hi_cmask) == hi_cval) & ~ident
            return jnp.where(sel, nc, cc), jnp.where(sel, ns, ss)

        cids = cid0 + jnp.arange(codes3.shape[0], dtype=gk.IDX_DTYPE)
        return jax.lax.map(body, (cids, codes3, scales2))

    return run


def _mk_fuse_window(ca, block, cdt, qmax, structure):
    """Fused gate window on the compressed ket: ONE decompress -> every
    window op -> ONE recompress per chunk, inside one lax.map program.
    This is where fusion pays double on this engine — each eager gate
    costs a full decompress/recompress round trip AND a requantization;
    a W-op window amortizes both to 1/W.  Payloads/masks are runtime
    operands in the ops/fusion.py sharded layout with the chunk axis
    standing in for the page axis (lo = in-chunk index, hi = chunk id).
    Non-diagonal targets at/above the chunk axis never reach here
    (_fuse_admit routes them to the eager pair-mixing program).  A chunk
    no window op acted on keeps its codes bit-for-bit — same exactness
    contract as the per-gate kernels.

    The per-op tile math lives in ops/pallas_kernels.py's shared tile
    primitives (one implementation for the dense Pallas kernel, the
    pager's per-page kernel body and this decompress->window->recompress
    sweep); only the dirty/ident exact-keep accounting is local."""
    from ..ops import pallas_kernels as pk

    lbits = (1 << ca) - 1

    def run(codes3, scales2, rot, rot_t, cid0, *operands):
        def body(args):
            cid, cc, ss = args
            pl = _rows_to_planes(_dec_rows_f(cc, ss, rot_t, qmax), block)
            lidx = gk.iota_for(pl)
            dirty = jnp.zeros((), jnp.bool_)
            i = 0
            for kind, target, has_ctrl in structure:
                p = operands[i]
                i += 1
                if kind == "cphase":
                    if has_ctrl:
                        clo, chi = operands[i], operands[i + 1]
                        i += 2
                    else:
                        comb = 1 << target
                        clo, chi = comb & lbits, comb >> ca
                    # chi carries the target's high bit too, so hi_ok
                    # is already exact per chunk (factor-1 chunks stay)
                    pl, hi_ok = pk.tile_cphase(pl, lidx, cid, clo, chi,
                                               p[0], p[1])
                    dirty = dirty | hi_ok
                    continue
                if has_ctrl:
                    lo_cm, lo_cv, hi_cm, hi_cv = operands[i:i + 4]
                    i += 4
                else:
                    lo_cm = lo_cv = hi_cm = hi_cv = 0
                if kind == "diag":
                    pl, hi_ok = pk.tile_diag(
                        pl, lidx, cid, target, ca,
                        p[0, 0], p[0, 1], p[1, 0], p[1, 1],
                        lo_cm, lo_cv, hi_cm, hi_cv)
                    if target >= ca:
                        # whole-chunk constant factor: exact-keep chunks
                        # whose factor is identically 1 (_mk_diag ident)
                        hi_bit = (cid & (1 << (target - ca))) != 0
                        cf_re = jnp.where(hi_bit, p[1, 0], p[0, 0])
                        cf_im = jnp.where(hi_bit, p[1, 1], p[0, 1])
                        ident = ((lo_cm == 0) & (cf_re == 1.0)
                                 & (cf_im == 0.0))
                        dirty = dirty | (hi_ok & ~ident)
                    else:
                        dirty = dirty | hi_ok
                else:  # gen: target < ca guaranteed by _fuse_admit
                    pl, hi_ok = pk.tile_local_2x2(pl, lidx, cid, target, p,
                                                  lo_cm, lo_cv,
                                                  hi_cm, hi_cv)
                    dirty = dirty | hi_ok
            nc, ns = _comp_rows_f(_planes_to_rows(pl, block), rot,
                                  qmax, cdt)
            return jnp.where(dirty, nc, cc), jnp.where(dirty, ns, ss)

        cids = cid0 + jnp.arange(codes3.shape[0], dtype=gk.IDX_DTYPE)
        return jax.lax.map(body, (cids, codes3, scales2))

    return run


def _mk_phase_split(ca, block, cdt, qmax, body_fn):
    def run(codes3, scales2, rot, rot_t, cid0, *targs):
        def body(args):
            cid, cc, ss = args
            pl = _rows_to_planes(_dec_rows_f(cc, ss, rot_t, qmax), block)
            lidx = gk.iota_for(pl)
            fre, fim = body_fn(jnp, cid, lidx, ca, *targs)
            out = gk.cmul(fre, fim, pl)
            return _comp_rows_f(_planes_to_rows(out, block), rot, qmax, cdt)

        cids = cid0 + jnp.arange(codes3.shape[0], dtype=gk.IDX_DTYPE)
        return jax.lax.map(body, (cids, codes3, scales2))

    return run


def _mk_prob_mask(ca, block, qmax):
    def run(codes3, scales2, rot_t, mask_lo, val_lo, mask_hi, val_hi, cid0):
        def body(args):
            cid, cc, ss = args
            pl = _rows_to_planes(_dec_rows_f(cc, ss, rot_t, qmax), block)
            lidx = gk.iota_for(pl)
            ok = (((lidx & mask_lo) == val_lo)
                  & ((cid & mask_hi) == val_hi))
            p = pl[0] ** 2 + pl[1] ** 2
            return jnp.sum(jnp.where(ok, p, 0.0))

        cids = cid0 + jnp.arange(codes3.shape[0], dtype=gk.IDX_DTYPE)
        return jnp.sum(jax.lax.map(body, (cids, codes3, scales2)))

    return run


def _mk_collapse(ca, block, cdt, qmax):
    def run(codes3, scales2, rot, rot_t, mask_lo, val_lo,
            mask_hi, val_hi, scale, cid0):
        def body(args):
            cid, cc, ss = args
            pl = _rows_to_planes(_dec_rows_f(cc, ss, rot_t, qmax), block)
            lidx = gk.iota_for(pl)
            keep = (((lidx & mask_lo) == val_lo)
                    & ((cid & mask_hi) == val_hi))
            pl = jnp.where(keep, pl * scale, jnp.zeros((), pl.dtype))
            return _comp_rows_f(_planes_to_rows(pl, block), rot, qmax, cdt)

        cids = cid0 + jnp.arange(codes3.shape[0], dtype=gk.IDX_DTYPE)
        return jax.lax.map(body, (cids, codes3, scales2))

    return run


def _mk_collapse_scales():
    def run(scales2, mask_hi, val_hi, scale, cid0):
        cids = cid0 + jnp.arange(scales2.shape[0], dtype=gk.IDX_DTYPE)
        sel = (cids & mask_hi) == val_hi
        return jnp.where(sel[:, None], scales2 * scale,
                         jnp.zeros((), scales2.dtype))

    return run


_ZERO = 0  # cid0 for the single-device engine (weak-typed int32 operand)


class QEngineTurboQuant(QEngineTPU):
    """Dense ket resident as rotated b-bit block codes (lossy)."""

    _tele_name = "turboquant"

    def __init__(self, qubit_count: int, init_state: int = 0,
                 bits: int = None, block_pow: int = None,
                 chunk_qb: int = None, seed_rot: int = tq.DEFAULT_SEED,
                 **kwargs):
        self._tq_bits = int(bits if bits is not None
                            else os.environ.get("QRACK_TURBO_BITS",
                                                tq.DEFAULT_BITS))
        bp = int(block_pow if block_pow is not None
                 else os.environ.get("QRACK_TURBO_BLOCK_POW",
                                     tq.DEFAULT_BLOCK_POW))
        self._tq_block_pow = min(bp, self._max_chunk_pow(qubit_count))
        cq = int(chunk_qb if chunk_qb is not None
                 else os.environ.get("QRACK_TURBOQUANT_CHUNK_QB", "20"))
        self._tq_chunk_pow = max(self._tq_block_pow,
                                 min(cq, self._max_chunk_pow(qubit_count)))
        self._tq_seed = seed_rot
        d = 1 << self._tq_block_pow
        self._rot = jnp.asarray(tq.rotation_matrix(2 * d, seed_rot))
        self._rot_t = self._rot.T
        self._qmax = float(tq.qmax(self._tq_bits))
        self._code_np = tq.code_dtype(self._tq_bits)
        self._codes = None
        self._scales = None
        self.peak_transient_amps = 0
        super().__init__(qubit_count, init_state=init_state, **kwargs)

    # ------------------------------------------------------------------
    # compressed <-> planes
    # ------------------------------------------------------------------

    def _max_chunk_pow(self, qubit_count: int) -> int:
        """Largest legal chunk power at this width (the sharded subclass
        subtracts its page bits so every page owns >= 1 chunk)."""
        return qubit_count

    def _compressed_cap(self) -> int:
        """Per-device width ceiling: codes store 4x (int8) / 2x (int16)
        more amplitudes per HBM byte than f32 planes — +2 / +1 qubits
        over the dense cap.  The CHUNKED kernels index with split
        (chunk, local) int32 pairs, so they are not int32-bound past
        the dense limit (ADVICE r4 fix); the dense `_state` fallback IS
        still bound, and its property guard enforces that separately."""
        from .tpu import MAX_DENSE_QB

        return MAX_DENSE_QB + (2 if self._tq_bits <= 8 else 1)

    def _check_capacity(self, qubit_count: int) -> None:
        cap = self._compressed_cap()
        if qubit_count > cap:
            raise MemoryError(
                f"QEngineTurboQuant width {qubit_count} exceeds the "
                f"compressed single-device cap ({cap} at "
                f"{self._tq_bits}-bit codes); use QPagerTurboQuant or "
                "the pager/QUnit layers above this engine")
        # GROWTH (Compose/Allocate on a live engine) routes through the
        # dense f32 fallback plane, which is only sound to MAX_DENSE_QB;
        # fresh construction is codes-native and may use the full cap
        from .tpu import MAX_DENSE_QB

        if (qubit_count > MAX_DENSE_QB
                and getattr(self, "_codes", None) is not None):
            raise MemoryError(
                f"growing a compressed engine past {MAX_DENSE_QB} qubits "
                "requires the dense fallback plane (unsound at that "
                "width); construct at the target width instead")

    @property
    def _block(self) -> int:
        return 1 << self._tq_block_pow

    @property
    def _chunk_amps(self) -> int:
        return 1 << self._tq_chunk_pow

    @property
    def _chunk_blocks(self) -> int:
        return self._chunk_amps // self._block

    def resident_bytes(self) -> int:
        """HBM bytes of the resident representation."""
        if self._codes is None:
            return 0
        return self._codes.nbytes + self._scales.nbytes

    # resident-form access: every read of the code/scale arrays (gate
    # kernels, prob/collapse, Dump, checkpoint capture) flushes the
    # pending gate window first, and a blind write drops it — the same
    # laziness boundary the dense engines put on `_state`
    # (ops/fusion.py).  The `_state` fallback plane inherits the
    # discipline for free: its getter/setter go through these.
    @property
    def _codes(self):
        f = self._fuser
        if f is not None and f.gates and not f._flushing:
            f.flush("read")
        return self._codes_raw

    @_codes.setter
    def _codes(self, v) -> None:
        f = self._fuser
        if f is not None and f.gates and not f._flushing:
            f.drop("overwritten")
        self._codes_raw = v

    @property
    def _scales(self):
        f = self._fuser
        if f is not None and f.gates and not f._flushing:
            f.flush("read")
        return self._scales_raw

    @_scales.setter
    def _scales(self, v) -> None:
        f = self._fuser
        if f is not None and f.gates and not f._flushing:
            f.drop("overwritten")
        self._scales_raw = v

    def _compress_planes(self, planes):
        rows = _planes_to_rows(jnp.asarray(planes, jnp.float32), self._block)
        codes, scales = _j_comp_full(rows, self._rot, self._qmax,
                                     jnp.dtype(self._code_np).name)
        self._codes = codes
        self._scales = scales
        self._note_resident()

    def _note_resident(self) -> None:
        """Resident-footprint gauges: codes+scales bytes vs what the
        same ket would cost as two f32 planes (the compression-ratio
        numerator/denominator telemetry_report's == compression ==
        section reads).  Reads the raw arrays — the public properties
        flush the fuser, which must not fire from bookkeeping."""
        if not _tele._ENABLED:
            return
        codes = getattr(self, "_codes_raw", None)
        if codes is None:
            return
        _tele.gauge("tq.resident.bytes",
                    float(codes.nbytes + self._scales_raw.nbytes))
        _tele.gauge("tq.resident.dense_equiv_bytes",
                    float(8 * (1 << self.qubit_count)))

    def _note_sweeps(self, n: int = 2) -> None:
        """Counted decompress/recompress passes over the resident codes
        (one of each per dispatched program) — the denominator of the
        single-pass fused-window win.  Each pass reads or writes the
        full compressed residency, so the planned bytes also enter the
        roofline ledger (`roofline.tq.sweep.*`) — raw arrays again, the
        public properties would flush the fuser from bookkeeping."""
        if _tele._ENABLED:
            _tele.inc("tq.sweeps", n)
            codes = getattr(self, "_codes_raw", None)
            if codes is not None:
                _roofline.note_bytes(
                    "tq.sweep",
                    float(n) * (codes.nbytes + self._scales_raw.nbytes))

    def _decompress_planes(self):
        rows = _j_dec_rows(self._codes, self._scales, self._rot_t, self._qmax)
        return _rows_to_planes(rows, self._block)

    # the fallback data plane: any inherited kernel that reads/writes
    # `_state` transparently decompresses/recompresses the whole ket
    @property
    def _state(self):
        if self._codes is None:
            return None
        from .tpu import MAX_DENSE_QB

        if self.qubit_count > MAX_DENSE_QB:
            # beyond the dense cap, full f32 planes exceed HBM AND the
            # dense kernels' int32 flat indices — the chunked op set
            # (gates, prob, collapse, measurement, SetPermutation) is
            # the only sound surface at these widths
            raise MemoryError(
                f"this operation needs the dense f32 fallback plane, "
                f"which is unsound past {MAX_DENSE_QB} qubits (width "
                f"{self.qubit_count}): flat int32 indices overflow and "
                "the planes exceed HBM.  At this width the chunked op "
                "set (gates, prob, collapse, measurement, "
                "SetPermutation, amplitude/page reads) is the "
                "supported surface")
        self.peak_transient_amps = max(self.peak_transient_amps,
                                       1 << self.qubit_count)
        return self._decompress_planes()

    @_state.setter
    def _state(self, planes) -> None:
        if planes is None:
            self._codes = None
            self._scales = None
            return
        # width may have changed (compose/decompose/allocate funnel
        # through the fallback): re-derive the block layout from the
        # planes WITHOUT touching qubit_count — QEngine's structure ops
        # adjust it themselves after the kernel, so mutating it here
        # double-counted the width change (round-4 defect caught by the
        # sharded Dispose regression test)
        n_amps = planes.shape[-1]
        n_new = int(round(math.log2(n_amps)))
        from .tpu import MAX_DENSE_QB

        if n_new > MAX_DENSE_QB:
            # belt to the growth guard in _check_capacity: full-width
            # f32 planes past the dense cap are unsound (HBM + int32)
            raise MemoryError(
                f"dense fallback write at width {n_new} is unsound past "
                f"{MAX_DENSE_QB} qubits on the compressed engine")
        max_cp = self._max_chunk_pow(n_new)
        if self._tq_block_pow > max_cp:
            self._tq_block_pow = max_cp
            d = 1 << self._tq_block_pow
            self._rot = jnp.asarray(tq.rotation_matrix(2 * d, self._tq_seed))
            self._rot_t = self._rot.T
        self._tq_chunk_pow = max(self._tq_block_pow,
                                 min(self._tq_chunk_pow, max_cp))
        self._compress_planes(planes)

    # ------------------------------------------------------------------
    # chunk helpers
    # ------------------------------------------------------------------

    def _n_chunks(self) -> int:
        return max(1, (1 << self.qubit_count) // self._chunk_amps)

    def _chunk_slice(self, c: int) -> slice:
        cb = self._chunk_blocks
        return slice(c * cb, (c + 1) * cb)

    def _dec_chunk(self, c: int):
        sl = self._chunk_slice(c)
        rows = _j_dec_rows(self._codes[sl], self._scales[sl],
                           self._rot_t, self._qmax)
        return _rows_to_planes(rows, self._block)

    def _chunk3(self):
        """Chunk-major views of the resident arrays: (C, cb, 2D), (C, cb)."""
        C, cb = self._n_chunks(), self._chunk_blocks
        return (self._codes.reshape(C, cb, -1), self._scales.reshape(C, cb))

    def _store3(self, codes3, scales2) -> None:
        self._codes = codes3.reshape(-1, codes3.shape[-1])
        self._scales = scales2.reshape(-1)
        self._note_resident()

    def _layout_key(self):
        return (self.qubit_count, self._tq_chunk_pow, self._tq_block_pow,
                self._tq_bits)

    def _note_transient(self, n_chunks_live: int) -> None:
        self.peak_transient_amps = max(
            self.peak_transient_amps, n_chunks_live * self._chunk_amps)

    # ------------------------------------------------------------------
    # chunked kernel overrides (the hot path).  Each gate is ONE cached
    # jitted program whose chunk axis is a lax.map dimension: O(1)
    # dispatches and an in-place donated update of the code array, with
    # the decompressed f32 working set still bounded by one (or a pair
    # of) chunk(s).  Chunks whose high-control test fails — or whose
    # diagonal factor is identically 1 — keep their EXACT codes via a
    # per-chunk select, so requantization error accrues only where a
    # gate acted (same exactness contract as the old host loop).
    # ------------------------------------------------------------------

    def _p_gate_low(self, target: int):
        run = _mk_gate_low(self._tq_chunk_pow, self._block, self._code_np,
                           self._qmax, target)

        def build():
            return jax.jit(
                lambda c3, s2, rot, rot_t, mp, hm, hv, lm, lv:
                run(c3, s2, rot, rot_t, mp, hm, hv, lm, lv, _ZERO),
                donate_argnums=(0, 1))

        return _program(("tq_low", self._layout_key(), target), build)

    def _p_gate_pair(self, tb_pos: int):
        run = _mk_gate_pair(self._tq_chunk_pow, self._block, self._code_np,
                            self._qmax, tb_pos)

        def build():
            return jax.jit(
                lambda c3, s2, rot, rot_t, mp, hm, hv, lm, lv:
                run(c3, s2, rot, rot_t, mp, hm, hv, lm, lv, _ZERO),
                donate_argnums=(0, 1))

        return _program(("tq_pair", self._layout_key(), tb_pos), build)

    # opt-in fused Pallas path (ops/pallas_turboquant.py): one HBM
    # read+write of the b-bit CODES per gate.  Single-device only (the
    # sharded subclass keeps the shard_map XLA programs); same
    # QRACK_USE_PALLAS flag as the dense segment sweep.
    _pallas_capable = True
    _PALLAS_TILE_POW = int(os.environ.get("QRACK_PALLAS_TQ_TILE_QB", "18"))

    def _use_pallas(self) -> bool:
        return (self._pallas_capable
                and os.environ.get("QRACK_USE_PALLAS") == "1")

    def _pallas_interpret(self) -> bool:
        return jax.default_backend() != "tpu"

    def _pallas_tile_pow(self) -> int:
        # tile must cover whole blocks (a tile smaller than one code
        # row breaks the kernel's reshapes) and fit the register
        return max(min(self._PALLAS_TILE_POW, self.qubit_count),
                   self._tq_block_pow)

    def _p_pallas_low(self, target: int, tp: int):
        from ..ops import pallas_turboquant as ptq

        def build():
            # donated like every sibling chunk program: without it each
            # gate holds TWO full code arrays in HBM
            return jax.jit(ptq.make_tq_gate_low(
                self.qubit_count, self._tq_block_pow, self._tq_bits,
                target, tile_pow=tp, interpret=self._pallas_interpret()),
                donate_argnums=(0, 1))

        return _program(("tq_pl_low", self._layout_key(), target, tp),
                        build)

    def _p_pallas_diag(self, tp: int):
        from ..ops import pallas_turboquant as ptq

        def build():
            return jax.jit(ptq.make_tq_diag(
                self.qubit_count, self._tq_block_pow, self._tq_bits,
                tile_pow=tp, interpret=self._pallas_interpret()),
                donate_argnums=(0, 1))

        return _program(("tq_pl_diag", self._layout_key(), tp), build)

    def _k_apply_2x2(self, m2, target, controls, perm) -> None:
        self._note_sweeps()
        cmask, cval = self._cmask_cval(controls, perm)
        mp = gk.mtrx_planes(np.asarray(m2, dtype=np.complex128), jnp.float32)
        ca = self._tq_chunk_pow
        cs = self._chunk_amps
        tp = self._pallas_tile_pow()
        if self._use_pallas() and target < tp:
            self._note_transient(1)
            T = 1 << tp
            self._codes, self._scales = self._p_pallas_low(target, tp)(
                self._codes, self._scales, self._rot, self._rot_t, mp,
                cmask >> tp, cval >> tp, cmask & (T - 1), cval & (T - 1))
            return
        if target < ca:
            self._note_transient(1)
            prog = self._p_gate_low(target)
        else:
            self._note_transient(2)
            prog = self._p_gate_pair(target - ca)
        c3, s2 = self._chunk3()
        nc, ns = prog(c3, s2, self._rot, self._rot_t, mp,
                      cmask >> ca, cval >> ca, cmask & (cs - 1),
                      cval & (cs - 1))
        self._store3(nc, ns)

    def _p_diag(self):
        run = _mk_diag(self._tq_chunk_pow, self._block, self._code_np,
                       self._qmax)

        def build():
            return jax.jit(
                lambda c3, s2, rot, rot_t, *sc:
                run(c3, s2, rot, rot_t, *sc, _ZERO),
                donate_argnums=(0, 1))

        return _program(("tq_diag", self._layout_key()), build)

    def _k_apply_diag(self, d0, d1, target, controls, perm) -> None:
        self._note_sweeps()
        cmask, cval = self._cmask_cval(controls, perm)
        ca = self._tq_chunk_pow
        cs = self._chunk_amps
        d0, d1 = complex(d0), complex(d1)
        if self._use_pallas():
            self._note_transient(1)
            tp = self._pallas_tile_pow()
            T = 1 << tp
            dp = np.zeros((2, 2, 2), np.float32)
            dp[0, 0, 0], dp[0, 0, 1] = d0.real, d1.real
            dp[1, 0, 0], dp[1, 0, 1] = d0.imag, d1.imag
            tm_lo = (1 << target) if target < tp else 0
            tb_hi = 0 if target < tp else (1 << (target - tp))
            self._codes, self._scales = self._p_pallas_diag(tp)(
                self._codes, self._scales, self._rot, self._rot_t, dp,
                tm_lo, tb_hi, cmask & (T - 1), cval & (T - 1),
                cmask >> tp, cval >> tp)
            return
        tmask_lo = (1 << target) if target < ca else 0
        tb_hi = 0 if target < ca else (1 << (target - ca))
        self._note_transient(1)
        c3, s2 = self._chunk3()
        nc, ns = self._p_diag()(c3, s2, self._rot, self._rot_t,
                                d0.real, d0.imag, d1.real, d1.imag,
                                tmask_lo, tb_hi, cmask & (cs - 1),
                                cval & (cs - 1), cmask >> ca, cval >> ca)
        self._store3(nc, ns)

    # ------------------------------------------------------------------
    # gate-stream fusion hooks (ops/fusion.py GateStreamFuser)
    # ------------------------------------------------------------------

    def _fuse_admit(self, m, target, controls) -> bool:
        # both backends fuse whole windows into ONE decompress -> ops ->
        # recompress pass now; only cross-boundary non-diagonal targets
        # (pair mixing above the chunk/tile axis) stay per-gate
        if self._use_pallas():
            return mat.is_phase(m) or target < self._pallas_tile_pow()
        return mat.is_phase(m) or target < self._tq_chunk_pow

    def _fuse_tick(self) -> None:
        # the chunked kernels never ticked drift accounting (norm checks
        # would force a full decompress); keep that contract under fusion
        pass

    def _p_fuse_window(self, structure):
        run = _mk_fuse_window(self._tq_chunk_pow, self._block,
                              self._code_np, self._qmax, structure)

        def build():
            return _tele.instrument_jit("fuse.window", jax.jit(
                lambda c3, s2, rot, rot_t, *ops:
                run(c3, s2, rot, rot_t, _ZERO, *ops),
                donate_argnums=(0, 1)))

        return _program(("tq_fusewin", self._layout_key(), structure),
                        build, site="tpu.fuse.flush")

    def _p_pallas_window(self, structure, tp: int):
        from ..ops import pallas_turboquant as ptq

        def build():
            return _tele.instrument_jit("fuse.window", jax.jit(
                ptq.make_tq_window(
                    self.qubit_count, self._tq_block_pow, self._tq_bits,
                    structure, tile_pow=tp,
                    interpret=self._pallas_interpret()),
                donate_argnums=(0, 1)))

        return _program(("tq_pl_fusewin", self._layout_key(), tp,
                         structure), build, site="tpu.fuse.flush")

    def _note_window(self, n_ops: int) -> None:
        """Single-pass window accounting: one decompress + one
        recompress sweep total, where the per-gate path would have paid
        a pair per op — `fuse.tq.sweeps_saved` is the difference."""
        self._note_sweeps()
        if _tele._ENABLED:
            _tele.inc("fuse.tq.windows")
            _tele.inc("fuse.tq.ops", n_ops)
            _tele.inc("fuse.tq.sweeps_saved", 2 * (n_ops - 1))

    def _fuse_flush(self, gates) -> int:
        from ..ops import fusion as fu

        ops = fu.lower_gates(gates)
        if len(ops) == 1:
            # merged down to one op: the per-gate chunk programs already
            # exist and skip the recompress of untouched chunk pairs
            op = ops[0]
            controls, perm = fu.controls_perm(op)
            m = np.asarray(op.m)
            if op.kind in ("cphase", "diag"):
                self._k_apply_diag(m[0, 0], m[1, 1], op.target,
                                   controls, perm)
            else:
                self._k_apply_2x2(m, op.target, controls, perm)
            return 1
        structure = fu.sharded_structure_of(ops)
        if self._use_pallas():
            # single-pass per VMEM tile: masks split at the tile
            # boundary, whole window in-register between dequant/requant
            tp = self._pallas_tile_pow()
            operands = fu.sharded_operands(ops, tp, jnp.float32)
            self._note_transient(1)
            self._note_window(len(ops))
            prog = self._p_pallas_window(structure, tp)
            self._codes, self._scales = prog(
                self._codes, self._scales, self._rot, self._rot_t,
                *operands)
            self._note_resident()
            return 1
        operands = fu.sharded_operands(ops, self._tq_chunk_pow,
                                       jnp.float32)
        self._note_transient(1)
        self._note_window(len(ops))
        prog = self._p_fuse_window(structure)
        c3, s2 = self._chunk3()
        nc, ns = prog(c3, s2, self._rot, self._rot_t, *operands)
        self._store3(nc, ns)
        return 1

    def _p_phase_split(self, key, body_fn, n_targs: int):
        run = _mk_phase_split(self._tq_chunk_pow, self._block, self._code_np,
                              self._qmax, body_fn)

        def build():
            return jax.jit(
                lambda c3, s2, rot, rot_t, *targs:
                run(c3, s2, rot, rot_t, _ZERO, *targs),
                donate_argnums=(0, 1))

        if key is None:  # unkeyed generic fn: trace per call
            return build()
        return _program(("tq_phase", self._layout_key(), tuple(key)), build)

    def _k_phase_fn(self, fn, split=None) -> None:
        self._note_sweeps()
        self._note_transient(1)
        if split is not None:
            # split (chunk_id, local_idx) form: exact past 31 qubits,
            # program cached on the op's split key
            key, body, targs = split
            prog = self._p_phase_split(key, body, len(targs))
            c3, s2 = self._chunk3()
            nc, ns = prog(c3, s2, self._rot, self._rot_t,
                          *[jnp.asarray(t) for t in targs])
        else:
            if self.qubit_count > 31:
                raise NotImplementedError(
                    "this diagonal op lacks a split-index form for "
                    ">31-qubit compressed kets (see the `split=` forms "
                    "in engines/qengine.py)")
            cs = self._chunk_amps

            def body(xp, cid, lidx, L):
                return fn(xp, cid * cs + lidx)

            prog = self._p_phase_split(None, body, 0)
            c3, s2 = self._chunk3()
            nc, ns = prog(c3, s2, self._rot, self._rot_t)
        self._store3(nc, ns)

    def _p_prob_mask(self):
        run = _mk_prob_mask(self._tq_chunk_pow, self._block, self._qmax)

        def build():
            return jax.jit(lambda c3, s2, rot_t, ml, vl, mh, vh:
                           run(c3, s2, rot_t, ml, vl, mh, vh, _ZERO))

        return _program(("tq_probmask", self._layout_key()), build)

    @staticmethod
    def _host_scalar(x) -> float:
        """Host value of a (possibly replicated, possibly not fully
        addressable) device scalar — the multi-host-legal read pattern
        (parallel/pager.py _host_read)."""
        if getattr(x, "is_fully_addressable", True):
            return float(np.asarray(x))
        return float(np.asarray(x.addressable_shards[0].data))

    def _k_prob_mask(self, mask, perm) -> float:
        ca, cs = self._tq_chunk_pow, self._chunk_amps
        c3, s2 = self._chunk3()
        total = self._host_scalar(self._p_prob_mask()(
            c3, s2, self._rot_t, mask & (cs - 1), perm & (cs - 1),
            mask >> ca, perm >> ca))
        return min(max(total, 0.0), 1.0)

    def _p_collapse(self):
        run = _mk_collapse(self._tq_chunk_pow, self._block, self._code_np,
                           self._qmax)

        def build():
            return jax.jit(
                lambda c3, s2, rot, rot_t, ml, vl, mh, vh, sc:
                run(c3, s2, rot, rot_t, ml, vl, mh, vh, sc, _ZERO),
                donate_argnums=(0, 1))

        return _program(("tq_collapse", self._layout_key()), build)

    def _p_collapse_scales(self):
        run = _mk_collapse_scales()

        def build():
            return jax.jit(lambda s2, mh, vh, sc: run(s2, mh, vh, sc, _ZERO),
                           donate_argnums=(0,))

        return _program(("tq_collapse_s", self._layout_key()), build)

    def _k_collapse(self, mask, val, nrm_sq) -> None:
        ca, cs = self._tq_chunk_pow, self._chunk_amps
        scale = 1.0 / math.sqrt(nrm_sq)
        c3, s2 = self._chunk3()
        if (mask & (cs - 1)) == 0:
            # chunk-aligned mask: collapse is a pure per-chunk scale
            # update (match -> *scale, else -> 0); codes stay exact and
            # nothing decompresses (the linear-in-scales property again)
            nc, ns = c3, self._p_collapse_scales()(s2, mask >> ca,
                                                   val >> ca, scale)
        else:
            self._note_transient(1)
            nc, ns = self._p_collapse()(c3, s2, self._rot, self._rot_t,
                                        mask & (cs - 1), val & (cs - 1),
                                        mask >> ca, val >> ca, scale)
        self._store3(nc, ns)

    def _k_normalize(self, nrm_sq) -> None:
        # dequantization is linear in scales: normalization never
        # decompresses (see module docstring)
        self._scales = self._scales * (1.0 / math.sqrt(nrm_sq))

    def MAll(self) -> int:
        """Two-stage chunked sampling: categorical over per-chunk
        probability masses (computed WITHOUT decompressing — rotation
        orthogonality preserves norms), then within the drawn chunk —
        never materializes more than one chunk."""
        n_ch = self._n_chunks()
        c3, s2 = self._chunk3()
        masses = self._chunk_masses(c3, s2)
        tot = masses.sum()
        u = self.Rand() * tot
        acc = 0.0
        chosen = n_ch - 1
        for c in range(n_ch):
            acc += masses[c]
            if u <= acc:
                chosen = c
                break
        self._note_transient(1)
        pl = self._dec_chunk(chosen)
        local = int(self._host_scalar(_j_sample_chunk(
            pl, float(self.Rand()))))
        result = chosen * self._chunk_amps + local
        self.SetPermutation(result)
        return result

    def _chunk_masses(self, c3, s2) -> np.ndarray:
        """Host copy of per-chunk masses (sharded subclass overrides
        with an all-gather program so the read is multi-host legal)."""
        return np.asarray(_j_chunk_masses(c3, s2, self._qmax),
                          dtype=np.float64)

    # ------------------------------------------------------------------
    # codes-native initialization: a basis state occupies ONE block, so
    # SetPermutation writes that block's rotated one-hot row directly —
    # no full-width f32 materialization (the inherited dense path would
    # transiently allocate 2^n f32 planes, capping the engine at f32
    # widths and defeating the 4x-wider-ket point; reference: the
    # compressed storage is written in place, statevector_turboquant.hpp)
    # ------------------------------------------------------------------

    def _perm_out_shardings(self):
        """Output placement for the SetPermutation program (sharded
        subclass returns its mesh shardings)."""
        if self._device is not None:
            from jax.sharding import SingleDeviceSharding

            return (SingleDeviceSharding(self._device),) * 2
        return None

    def _p_setperm(self, n_chunks: int, cb: int, twoD: int):
        cdt = self._code_np
        sh = self._perm_out_shardings()

        def build():
            def run(row_codes, scale, cid, bid):
                # two-level (chunk, block-in-chunk) scatter: both
                # indices stay int32 at ANY width (a flat block index
                # would overflow int32 at max pager widths)
                codes = (jnp.zeros((n_chunks, cb, twoD), dtype=cdt)
                         .at[cid, bid].set(row_codes))
                scales = (jnp.zeros((n_chunks, cb), dtype=jnp.float32)
                          .at[cid, bid].set(scale.astype(jnp.float32)))
                return codes.reshape(n_chunks * cb, twoD), scales.reshape(-1)

            kw = {"out_shardings": sh} if sh is not None else {}
            return jax.jit(run, **kw)

        return _program(("tq_setperm", self._layout_key(),
                         getattr(self, "_device_id", -1), n_chunks, cb),
                        build)

    def SetPermutation(self, perm: int, phase=None) -> None:
        ph = self._rand_phase() if phase is None else complex(phase)
        D = self._block
        cs = self._chunk_amps
        cb = self._chunk_blocks
        cid, bid, d = perm // cs, (perm % cs) // D, perm % D
        # rotated one-hot row (re at row-slot d, im at slot D+d), built
        # DEVICE-side from the resident rotation.  The zero-fill +
        # scatter runs inside a jitted program with explicit output
        # shardings, so the codes materialize directly where they live
        # (per-shard on the pager's mesh) — no full-size default-device
        # transient, which at w32+ would alone exceed one chip's HBM.
        row = ph.real * self._rot[d] + ph.imag * self._rot[D + d]
        scale = jnp.max(jnp.abs(row))
        safe = jnp.where(scale > 0, scale, 1.0)
        q = tq.qmax(self._tq_bits)
        row_codes = jnp.round(row / safe * q).astype(self._code_np)
        self._codes, self._scales = self._p_setperm(
            self._n_chunks(), cb, 2 * D)(
            row_codes, scale, jnp.asarray(cid, gk.IDX_DTYPE),
            jnp.asarray(bid, gk.IDX_DTYPE))
        self.running_norm = 1.0

    # ------------------------------------------------------------------
    # block-local reads: one amplitude needs only its own block decoded
    # (the reference's decompress-per-block read access,
    # statevector_turboquant.hpp) — no dense fallback, sound at ANY
    # width, ~2D bytes over the wire
    # ------------------------------------------------------------------

    def _rot_host_np(self) -> np.ndarray:
        cached = getattr(self, "_rot_host", None)
        if cached is None or cached.shape[0] != 2 * self._block:
            cached = np.asarray(self._rot, dtype=np.float32)
            self._rot_host = cached
        return cached

    def _fetch_blocks(self, b0: int, nb: int):
        """Host (codes, scales) for blocks [b0, b0+nb) — the sharded
        subclass overrides with a replicated collective fetch so the
        read stays multi-host legal."""
        return (np.asarray(self._codes[b0:b0 + nb], dtype=np.float32),
                np.asarray(self._scales[b0:b0 + nb], dtype=np.float32))

    def GetAmplitude(self, perm: int) -> complex:
        D = self._block
        b, d = perm // D, perm % D
        codes, scales = self._fetch_blocks(b, 1)
        scale = float(scales[0])
        if scale == 0.0:
            return 0j
        rot = self._rot_host_np()
        y = codes[0] * (scale / self._qmax)
        # decompress just the two needed coordinates: row @ rot.T at
        # columns d (re) and D+d (im) = dot with rot's rows d / D+d
        re = float(y @ rot[d])
        im = float(y @ rot[D + d])
        return complex(re, im)

    def _p_setamp(self):
        sh = self._perm_out_shardings()

        def build():
            def run(codes3, scales2, row, scale, cid, bid):
                # two-level (chunk, block-in-chunk) scatter, like
                # _p_setperm: a flat block index silently wraps int32
                # at max pager widths; output shardings keep the write
                # on-mesh for the sharded subclass
                C, cb, twoD = codes3.shape
                codes3 = codes3.at[cid, bid].set(row)
                scales2 = scales2.at[cid, bid].set(
                    scale.astype(jnp.float32))
                return codes3.reshape(C * cb, twoD), scales2.reshape(-1)

            kw = {"out_shardings": sh} if sh is not None else {}
            return jax.jit(run, donate_argnums=(0, 1), **kw)

        return _program(("tq_setamp", self._layout_key(),
                         getattr(self, "_device_id", -1)), build)

    def SetAmplitude(self, perm: int, amp: complex) -> None:
        """Block-local write: decode the one covered block, poke the
        amplitude, requantize that block only."""
        amp = complex(amp)
        D = self._block
        cs = self._chunk_amps
        b, d = perm // D, perm % D
        cid, bid = perm // cs, (perm % cs) // D
        codes, scales = self._fetch_blocks(b, 1)
        rot = self._rot_host_np()
        vec = (codes[0] * (float(scales[0]) / self._qmax)) @ rot.T
        vec[d] = amp.real
        vec[D + d] = amp.imag
        y = vec @ rot
        scale = float(np.max(np.abs(y)))
        safe = scale if scale > 0 else 1.0
        row = np.round(y / safe * self._qmax).astype(self._code_np)
        c3, s2 = self._chunk3()
        self._codes, self._scales = self._p_setamp()(
            c3, s2, jnp.asarray(row), jnp.float32(scale),
            jnp.asarray(cid, gk.IDX_DTYPE), jnp.asarray(bid, gk.IDX_DTYPE))

    def GetAmplitudePage(self, offset: int, length: int) -> np.ndarray:
        """Block-aligned page read: decode only the covered blocks."""
        D = self._block
        b0 = offset // D
        b1 = (offset + length - 1) // D + 1
        codes, scales = self._fetch_blocks(b0, b1 - b0)
        rot = self._rot_host_np()
        rows = (codes * (scales / self._qmax)[:, None]) @ rot.T
        flat_re = rows[:, :D].reshape(-1)
        flat_im = rows[:, D:].reshape(-1)
        lo = offset - b0 * D
        return (flat_re[lo:lo + length]
                + 1j * flat_im[lo:lo + length]).astype(np.complex128)

    # ------------------------------------------------------------------
    # serialization: seed + scales + codes (reference stores the seed,
    # never the matrices — statevector_turboquant.hpp serialization)
    # ------------------------------------------------------------------

    def SaveTurboQuant(self, path: str) -> None:
        from ..checkpoint.container import save_container

        p = path if str(path).endswith(".npz") else str(path) + ".npz"
        # scalar members mirror the pre-container layout so older
        # readers still load these archives as bare npz
        save_container(p, {"codes": np.asarray(self._codes),
                           "scales": np.asarray(self._scales),
                           "n": np.asarray(self.qubit_count),
                           "bits": np.asarray(self._tq_bits),
                           "block_pow": np.asarray(self._tq_block_pow),
                           "seed": np.asarray(self._tq_seed)},
                       meta={"n": self.qubit_count, "bits": self._tq_bits,
                             "block_pow": self._tq_block_pow,
                             "seed": self._tq_seed},
                       kind="turboquant-codes")

    @classmethod
    def LoadTurboQuant(cls, path: str, **kwargs):
        from ..checkpoint.container import load_container

        p = path if str(path).endswith(".npz") else str(path) + ".npz"
        kind, meta, z = load_container(p, legacy_ok=True)
        if kind is None:  # legacy bare-npz archive (pre-container)
            meta = {k: int(z[k]) for k in ("n", "bits", "block_pow", "seed")}
        eng = cls(int(meta["n"]), bits=int(meta["bits"]),
                  block_pow=int(meta["block_pow"]), seed_rot=int(meta["seed"]),
                  **kwargs)
        eng._ckpt_place(np.asarray(z["codes"], dtype=eng._code_np),
                        np.asarray(z["scales"], dtype=np.float32))
        return eng

    # ------------------------------------------------------------------
    # checkpoint protocol (checkpoint/registry.py)
    # ------------------------------------------------------------------

    _ckpt_kind = "turboquant"

    def _ckpt_place(self, codes: np.ndarray, scales: np.ndarray) -> None:
        """Land host (codes, scales) where this engine keeps them (the
        sharded subclass overrides with its mesh placement)."""
        self._codes = jnp.asarray(codes)
        self._scales = jnp.asarray(scales)

    def _ckpt_capture(self, capture_child):
        return {"kind": self._ckpt_kind,
                "meta": {"n": self.qubit_count, "bits": self._tq_bits,
                         "block_pow": self._tq_block_pow,
                         "chunk_pow": self._tq_chunk_pow,
                         "seed": self._tq_seed,
                         "running_norm": float(self.running_norm)},
                "arrays": {"codes": np.asarray(self._codes),
                           "scales": np.asarray(self._scales)}}

    def _ckpt_restore(self, arrays, meta, children, restore_child):
        if int(meta["n"]) != self.qubit_count:
            raise ValueError("checkpoint width mismatch")
        if (int(meta["bits"]) != self._tq_bits
                or int(meta["block_pow"]) != self._tq_block_pow
                or int(meta["seed"]) != self._tq_seed):
            raise ValueError(
                "turboquant layout mismatch (bits/block_pow/seed)")
        codes = np.asarray(arrays["codes"], dtype=self._code_np)
        if self._codes is not None and codes.shape != tuple(self._codes.shape):
            raise ValueError(
                "turboquant chunk layout mismatch (QRACK_TURBOQUANT_CHUNK_QB "
                "differs from the saving process)")
        self._ckpt_place(codes, np.asarray(arrays["scales"],
                                           dtype=np.float32))
        self.running_norm = float(meta.get("running_norm", 1.0))


@jax.jit
def _j_sample_chunk(planes, u):
    p = planes[0] ** 2 + planes[1] ** 2
    cdf = jnp.cumsum(p)
    idx = jnp.searchsorted(cdf, u * cdf[-1], side="right")
    return jnp.minimum(idx, p.shape[0] - 1)
