"""QHybrid: transparent CPU <-> TPU <-> pager switching by width.

Re-design of the reference QHybrid (reference: include/qhybrid.hpp:35,
SwitchGpuMode :105, SwitchPagerMode :127): below `tpu_threshold_qubits`
the numpy engine wins (TPU dispatch latency dwarfs the math on tiny
kets — SURVEY.md §7 "Tiny-state dispatch overhead"); above it the JAX
engine; above `max_page_qubits` the sharded QPager. The wrapper forwards
the entire QInterface surface to the active engine and re-materializes
the ket across representations on width changes (the reference's
CopyStateVec hand-off).

Precision escalation: the dense halves honor the FPPOW policy
(QRACK_TPU_FPPOW, config.py) and — with QRACK_TPU_AUTO_F64_DRIFT set —
self-escalate their planes f32->f64 when running-norm drift exceeds the
threshold (QEngineTPU._drift_tick), so deep circuits under QHybrid
upgrade precision in place without a CPU round-trip."""

from __future__ import annotations

from typing import Optional

from .. import resilience as _res
from .. import telemetry as _tele
from ..config import get_config
from ..utils.rng import QrackRandom
from .cpu import QEngineCPU
from .tpu import QEngineTPU


class QHybrid:
    def __init__(self, qubit_count: int, init_state: int = 0,
                 rng: Optional[QrackRandom] = None,
                 tpu_threshold_qubits: Optional[int] = None,
                 pager_threshold_qubits: Optional[int] = None,
                 devices=None, **kwargs):
        cfg = get_config()
        self._tpu_threshold = (
            tpu_threshold_qubits if tpu_threshold_qubits is not None
            else cfg.hybrid_tpu_threshold_qubits
        )
        self._pager_threshold = (
            pager_threshold_qubits if pager_threshold_qubits is not None
            else cfg.max_page_qubits
        )
        self._devices = devices
        self._kwargs = dict(kwargs)
        self._kwargs["rng"] = rng if rng is not None else QrackRandom()
        # failover ceiling: None = healthy; "tpu" = pager died, never
        # re-promote past single-device; "cpu" = tunnel unusable, pin
        # to host (resilience layer, docs/RESILIENCE.md)
        self._failed_over: Optional[str] = None
        self._engine = self._make_engine(qubit_count, init_state)

    # ------------------------------------------------------------------

    def _mode_for(self, qubit_count: int) -> str:
        if self._failed_over == "cpu" or qubit_count < self._tpu_threshold:
            return "cpu"
        if qubit_count <= self._pager_threshold or self._failed_over == "tpu":
            return "tpu"
        return "pager"

    def _make_engine(self, qubit_count: int, init_state: int = 0, mode: Optional[str] = None):
        if mode is None:
            mode = self._mode_for(qubit_count)
        try:
            if mode == "cpu":
                return QEngineCPU(qubit_count, init_state=init_state, **self._kwargs)
            if mode == "tpu":
                return QEngineTPU(qubit_count, init_state=init_state, **self._kwargs)
            from ..parallel.pager import QPager

            return QPager(qubit_count, init_state=init_state, devices=self._devices,
                          **self._kwargs)
        except _res.FAILOVER_ERRORS as e:
            # construction-time failover (discover/first-compile died):
            # degrade the target mode and rebuild
            from .tpu import MAX_DENSE_QB

            fallback = ("tpu" if mode == "pager"
                        and qubit_count <= MAX_DENSE_QB else "cpu")
            self._failed_over = fallback
            if _tele._ENABLED:
                _tele.event(f"resilience.failover.init_{mode}_to_{fallback}",
                            width=qubit_count, cause=type(e).__name__)
                _tele.inc("resilience.failovers")
            return self._make_engine(qubit_count, init_state, mode=fallback)

    def _maybe_switch(self) -> None:
        """Re-materialize the ket when the width crosses a threshold
        (reference: SwitchGpuMode / SwitchPagerMode)."""
        n = self._engine.qubit_count
        want = self._mode_for(n)
        have = (
            "cpu" if isinstance(self._engine, QEngineCPU)
            else "tpu" if isinstance(self._engine, QEngineTPU)
            else "pager"
        )
        if want == have:
            return
        if _tele._ENABLED:
            _tele.event(f"hybrid.switch.{have}_to_{want}", width=n)
        state = self._engine.GetQuantumState()
        rng = self._engine.rng
        new = self._make_engine(n)
        new.rng = rng
        new.SetQuantumState(state)
        self._engine = new

    # ------------------------------------------------------------------
    # full-surface forwarding with structural hooks
    # ------------------------------------------------------------------

    def _fail_over(self, cause) -> None:
        """In-place degradation: snapshot the ket off the failing engine
        and continue the circuit on the next engine down (elastic pager
        shrink → tpu → cpu).  A tpu/cpu landing pins the ceiling; the
        un-pin probe (:meth:`_maybe_recover`) lifts it at a later call
        boundary once the device looks healthy again."""
        from ..resilience.failover import fail_over_engine

        fallback = fail_over_engine(self._engine, cause)
        self._commit_fallback(fallback)

    def _commit_fallback(self, engine) -> None:
        from ..resilience.failover import _engine_kind

        self._engine = engine
        kind = _engine_kind(engine)
        if kind in ("tpu", "cpu"):
            # a shrunk pager is NOT a ceiling — it re-expands on its own
            # through the elastic probe; only terminal hops pin the mode
            self._failed_over = kind

    def _maybe_recover(self) -> None:
        """Breaker-gated un-pin probe — the inverse of :meth:`_fail_over`
        (docs/ELASTICITY.md).  At a call boundary: re-expand a degraded
        pager in place, and when a tpu/cpu ceiling is pinned but the
        health probe passes, rebuild the width-appropriate engine and
        carry state+rng onto it, re-adopting the recovered device
        instead of staying down until process restart."""
        from ..resilience import elastic as _elastic

        eng = self._engine
        if getattr(eng, "_elastic_target_g", None) is not None:
            _elastic.maybe_reexpand(eng)
        if self._failed_over is None:
            return
        if not _elastic.health_probe():
            return
        prev = self._failed_over
        self._failed_over = None
        n = self._engine.qubit_count
        want = self._mode_for(n)
        have = (
            "cpu" if isinstance(self._engine, QEngineCPU)
            else "tpu" if isinstance(self._engine, QEngineTPU)
            else "pager"
        )
        if want == have:
            return  # ceiling lifted; the current engine already fits
        try:
            state = self._engine.GetQuantumState()
            rng = self._engine.rng
            new = self._make_engine(n)  # re-pins the ceiling on failure
            new.rng = rng
            new.SetQuantumState(state)
            self._engine = new
            if _tele._ENABLED:
                _tele.event(f"hybrid.unpin.{prev}_to_{want}", width=n)
                _tele.inc("elastic.hybrid.unpinned")
        except _res.FAILOVER_ERRORS:
            self._failed_over = prev

    def __getattr__(self, name):
        val = getattr(self._engine, name)
        if not _res._ACTIVE or not callable(val):
            return val

        def call(*args, **kwargs):
            if (self._failed_over is not None
                    or getattr(self._engine, "_elastic_target_g", None)
                    is not None):
                self._maybe_recover()
            try:
                return getattr(self._engine, name)(*args, **kwargs)
            except _res.FAILOVER_ERRORS as e:
                from ..resilience.failover import replay_with_failover

                _, out = replay_with_failover(
                    self._engine, e,
                    lambda fb: getattr(fb, name)(*args, **kwargs),
                    commit=self._commit_fallback)
                return out

        return call

    def _grow_to(self, n_new: int, mode: str, full_state) -> None:
        """Host-stage into a target-mode engine at the grown width (it
        may not exist at the current width, e.g. a pager with more pages
        than 2^n_cur)."""
        if _tele._ENABLED:
            _tele.event(f"hybrid.grow.{mode}", width=n_new)
        rng = self._engine.rng
        grown = self._make_engine(n_new, mode=mode)
        grown.rng = rng
        grown.SetQuantumState(full_state)
        self._engine = grown

    def Compose(self, other, start=None) -> int:
        inner = other._engine if isinstance(other, QHybrid) else other
        n_cur = self._engine.qubit_count
        n_new = n_cur + inner.qubit_count
        want = self._mode_for(n_new)
        if want == self._mode_for(n_cur):
            return self._engine.Compose(inner, start)
        from ..utils.states import compose_states

        if start is None:
            start = n_cur
        self._grow_to(n_new, want, compose_states(
            self._engine.GetQuantumState(), inner.GetQuantumState(),
            n_cur, inner.qubit_count, start))
        return start

    def Decompose(self, start, dest) -> None:
        inner = dest._engine if isinstance(dest, QHybrid) else dest
        self._engine.Decompose(start, inner)
        self._maybe_switch()
        if isinstance(dest, QHybrid):
            dest._maybe_switch()

    def Dispose(self, start, length, disposed_perm=None) -> None:
        self._engine.Dispose(start, length, disposed_perm)
        self._maybe_switch()

    def Allocate(self, start, length=1) -> int:
        n_cur = self._engine.qubit_count
        want = self._mode_for(n_cur + length)
        if want != self._mode_for(n_cur):
            import numpy as np

            from ..utils.states import compose_states

            zeros = np.zeros(1 << length, dtype=np.complex128)
            zeros[0] = 1.0
            self._grow_to(n_cur + length, want, compose_states(
                self._engine.GetQuantumState(), zeros, n_cur, length, start))
            return start
        res = self._engine.Allocate(start, length)
        self._maybe_switch()
        return res

    def Clone(self) -> "QHybrid":
        c = QHybrid.__new__(QHybrid)
        c._tpu_threshold = self._tpu_threshold
        c._pager_threshold = self._pager_threshold
        c._devices = self._devices
        c._kwargs = dict(self._kwargs)
        # fresh stream: the clone must not consume the original's RNG
        c._kwargs["rng"] = self._kwargs["rng"].spawn()
        c._failed_over = self._failed_over
        c._engine = self._engine.Clone()
        return c

    @property
    def qubit_count(self) -> int:
        return self._engine.qubit_count

    # ------------------------------------------------------------------
    # checkpoint protocol (checkpoint/registry.py): thresholds +
    # failover ceiling + the live engine (restored INTO this stack's
    # engine when the mode matches, else rebuilt standalone)
    # ------------------------------------------------------------------

    _ckpt_kind = "hybrid"

    def _ckpt_capture(self, capture_child):
        return {"kind": "hybrid",
                "meta": {"n": self.qubit_count,
                         "tpu_threshold": int(self._tpu_threshold),
                         "pager_threshold": int(self._pager_threshold),
                         "failed_over": self._failed_over},
                "children": {"engine": capture_child(self._engine)}}

    def _ckpt_restore(self, arrays, meta, children, restore_child):
        if int(meta["n"]) != self.qubit_count:
            raise ValueError("checkpoint width mismatch")
        self._tpu_threshold = int(meta["tpu_threshold"])
        self._pager_threshold = int(meta["pager_threshold"])
        self._failed_over = meta.get("failed_over")
        self._engine = restore_child(children["engine"], self._engine)
        rng = getattr(self._engine, "rng", None)
        if rng is not None:
            # future mode switches must carry the restored stream
            self._kwargs["rng"] = rng
