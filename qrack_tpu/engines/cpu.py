"""QEngineCPU: dense state vector on host, the conformance oracle.

Re-design of the reference CPU engine (reference:
include/qengine_cpu.hpp:36; hot loop src/qengine/state.cpp:392-511
par_for_mask): the skip-bit strided loops become vectorized numpy index
algebra (deposit_indices == the par_for_mask index walk), SIMD complex2
math becomes numpy ufuncs. Default dtype is complex128 — this engine is
the accuracy oracle the BASELINE L2-parity metric compares against —
with complex64 available for width parity with the TPU engine.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

from ..utils.bits import deposit_indices, control_offset
from .qengine import QEngine


class QEngineCPU(QEngine):
    _xp = np
    _tele_name = "cpu"

    def __init__(self, qubit_count: int, init_state: int = 0, dtype=np.complex128, **kwargs):
        super().__init__(qubit_count, init_state=init_state, **kwargs)
        self._check_capacity(qubit_count)
        self.dtype = np.dtype(dtype)
        self._state = np.zeros(1 << qubit_count, dtype=self.dtype)
        self.SetPermutation(init_state)
        self._idx_cache: Optional[np.ndarray] = None

    # ------------------------------------------------------------------
    # helpers
    # ------------------------------------------------------------------

    def _check_capacity(self, qubit_count: int) -> None:
        if qubit_count > self.config.max_cpu_qubits:
            raise MemoryError(
                f"QEngineCPU width {qubit_count} exceeds QRACK_MAX_CPU_QB="
                f"{self.config.max_cpu_qubits}"
            )

    @property
    def _idx(self) -> np.ndarray:
        if self._idx_cache is None or self._idx_cache.shape[0] != self._state.shape[0]:
            self._idx_cache = np.arange(self._state.shape[0], dtype=np.int64)
        return self._idx_cache

    def _rand_phase(self) -> complex:
        if self.rand_global_phase:
            ang = 2.0 * math.pi * self.Rand()
            return complex(math.cos(ang), math.sin(ang))
        return 1.0 + 0.0j

    # ------------------------------------------------------------------
    # kernel contract
    # ------------------------------------------------------------------

    def _k_apply_2x2(self, m2, target, controls, perm) -> None:
        n = self.qubit_count
        skip = [target] + list(controls)
        base = deposit_indices(n, skip)
        base = base | control_offset(controls, perm)
        i1 = base | (1 << target)
        a0 = self._state[base]
        a1 = self._state[i1]
        m = m2.astype(self.dtype)
        self._state[base] = m[0, 0] * a0 + m[0, 1] * a1
        self._state[i1] = m[1, 0] * a0 + m[1, 1] * a1

    def _k_apply_diag(self, d0, d1, target, controls, perm) -> None:
        n = self.qubit_count
        skip = [target] + list(controls)
        base = deposit_indices(n, skip)
        base = base | control_offset(controls, perm)
        if abs(d0 - 1.0) > 1e-15:
            self._state[base] *= self.dtype.type(d0)
        if abs(d1 - 1.0) > 1e-15:
            i1 = base | (1 << target)
            self._state[i1] *= self.dtype.type(d1)

    def _k_apply_4x4(self, m4, q1, q2) -> None:
        n = self.qubit_count
        base = deposit_indices(n, [q1, q2])
        p1, p2 = 1 << q1, 1 << q2
        rows = [base, base | p1, base | p2, base | p1 | p2]
        amps = [self._state[r] for r in rows]
        m = m4.astype(self.dtype)
        for r_i, row in enumerate(rows):
            acc = m[r_i, 0] * amps[0]
            for c_i in range(1, 4):
                if m[r_i, c_i] != 0:
                    acc = acc + m[r_i, c_i] * amps[c_i]
            self._state[row] = acc

    def _k_gather(self, src_fn, split=None) -> None:
        self._state = self._state[src_fn(self._idx)]

    def _k_out_of_place(self, src_idx, dst_idx, passthrough_cmask) -> None:
        new = np.zeros_like(self._state)
        if passthrough_cmask is not None:
            keep = (self._idx & passthrough_cmask) != passthrough_cmask
            new[keep] = self._state[keep]
        new[dst_idx] = self._state[src_idx]
        self._state = new

    def _k_phase_fn(self, fn, split=None) -> None:
        fre, fim = fn(np, self._idx)
        if np.isscalar(fim) and fim == 0.0:
            # pure-real factor (Z/phase flips): skip the complex promote
            self._state = (self._state * fre).astype(self.dtype, copy=False)
        else:
            self._state = (self._state * (np.asarray(fre) + 1j * np.asarray(fim))).astype(
                self.dtype, copy=False)

    def _k_probs(self) -> np.ndarray:
        return (self._state.real.astype(np.float64) ** 2
                + self._state.imag.astype(np.float64) ** 2)

    def _k_prob_mask(self, mask, perm) -> float:
        sel = (self._idx & mask) == perm
        p = self._k_probs()[sel].sum()
        return float(min(max(p, 0.0), 1.0))

    def _k_collapse(self, mask, val, nrm_sq) -> None:
        sel = (self._idx & mask) == val
        nrm = 1.0 / math.sqrt(nrm_sq)
        self._state = np.where(sel, self._state * self.dtype.type(nrm),
                               np.zeros((), dtype=self.dtype))

    def _k_compose(self, other, start) -> None:
        n, m = self.qubit_count, other.qubit_count
        other_state = np.asarray(other.GetQuantumState(), dtype=self.dtype)
        if start == n:
            self._state = np.kron(other_state, self._state)
            return
        from ..utils.states import compose_states

        self._state = compose_states(self._state, other_state, n, m, start).astype(self.dtype)

    def _split_matrix(self, start, length) -> np.ndarray:
        """Reshape ket to M[remainder, dest] for dest = [start, start+length)."""
        n = self.qubit_count
        t = self._state.reshape((2,) * n)
        dest_axes = [n - 1 - q for q in range(start + length - 1, start - 1, -1)]
        rem_axes = [a for a in range(n) if a not in dest_axes]
        tt = np.transpose(t, rem_axes + dest_axes)
        return tt.reshape(1 << (n - length), 1 << length)

    def _k_decompose(self, start, length) -> np.ndarray:
        m = self._split_matrix(start, length)
        row_norms = (np.abs(m) ** 2).sum(axis=1)
        r0 = int(np.argmax(row_norms))
        dest = m[r0] / math.sqrt(row_norms[r0])
        rem = m @ np.conj(dest)
        nrm = np.linalg.norm(rem)
        if nrm > 0:
            rem = rem / nrm
        self._state = rem.astype(self.dtype)
        self._idx_cache = None
        return dest.astype(self.dtype)

    def _k_dispose(self, start, length, perm) -> None:
        m = self._split_matrix(start, length)
        if perm is not None:
            rem = m[:, perm]
        else:
            row_norms = (np.abs(m) ** 2).sum(axis=1)
            r0 = int(np.argmax(row_norms))
            dest = m[r0] / math.sqrt(row_norms[r0])
            rem = m @ np.conj(dest)
        nrm = np.linalg.norm(rem)
        if nrm > 0:
            rem = rem / nrm
        self._state = rem.astype(self.dtype)
        self._idx_cache = None

    def _k_allocate(self, start, length) -> None:
        n = self.qubit_count
        new = np.zeros(1 << (n + length), dtype=self.dtype)
        pos = deposit_indices(n + length, list(range(start, start + length)))
        new[pos] = self._state
        self._state = new
        self._idx_cache = None

    def _k_normalize(self, nrm_sq) -> None:
        self._state = self._state / self.dtype.type(math.sqrt(nrm_sq))

    def _k_sum_sqr_diff(self, other) -> float:
        # phase-invariant: 1 - |<a|b>|^2, matching the reference
        # (src/qengine/state.cpp SumSqrDiff returns 1 - norm(inner))
        a = self._state.astype(np.complex128)
        b = np.asarray(other.GetQuantumState(), dtype=np.complex128)
        inner = np.vdot(a, b)
        return float(max(0.0, 1.0 - abs(inner) ** 2))

    def _k_swap_bits(self, q1, q2) -> None:
        p1, p2 = 1 << q1, 1 << q2

        def src(idx):
            b1 = (idx >> q1) & 1
            b2 = (idx >> q2) & 1
            x = b1 ^ b2
            return idx ^ ((x << q1) | (x << q2))

        self._k_gather(src)

    # ------------------------------------------------------------------
    # state access
    # ------------------------------------------------------------------

    def GetQuantumState(self) -> np.ndarray:
        return self._state.copy()

    def SetQuantumState(self, state) -> None:
        st = np.asarray(state, dtype=self.dtype).reshape(-1)
        if st.shape[0] != (1 << self.qubit_count):
            raise ValueError("state length mismatch")
        self._state = st.copy()

    def GetAmplitude(self, perm: int) -> complex:
        return complex(self._state[perm])

    def SetAmplitude(self, perm: int, amp: complex) -> None:
        self._state[perm] = amp

    def SetPermutation(self, perm: int, phase=None) -> None:
        self._state = np.zeros(1 << self.qubit_count, dtype=self.dtype)
        self._state[perm] = self._rand_phase() if phase is None else phase
        self.running_norm = 1.0

    def Clone(self) -> "QEngineCPU":
        c = QEngineCPU(
            self.qubit_count,
            dtype=self.dtype,
            rng=self.rng.spawn(),
            do_normalize=self.do_normalize,
            rand_global_phase=self.rand_global_phase,
        )
        c._state = self._state.copy()
        return c

    def CloneEmpty(self) -> "QEngineCPU":
        return QEngineCPU(
            self.qubit_count,
            dtype=self.dtype,
            rng=self.rng.spawn(),
            do_normalize=self.do_normalize,
            rand_global_phase=self.rand_global_phase,
        )

    # -- cross-engine data plane --

    def ZeroAmplitudes(self) -> None:
        self._state[:] = 0

    def IsZeroAmplitude(self) -> bool:
        return not np.any(self._state)

    def GetAmplitudePage(self, offset: int, length: int) -> np.ndarray:
        return self._state[offset:offset + length].copy()

    def SetAmplitudePage(self, page, offset: int) -> None:
        self._state[offset:offset + len(page)] = np.asarray(page, dtype=self.dtype)

    # ------------------------------------------------------------------
    # checkpoint protocol (checkpoint/registry.py)
    # ------------------------------------------------------------------

    _ckpt_kind = "cpu"

    def _ckpt_capture(self, capture_child):
        return {"kind": "cpu",
                "meta": {"n": self.qubit_count, "dtype": str(self.dtype),
                         "running_norm": float(self.running_norm)},
                "arrays": {"ket": self._state}}

    def _ckpt_restore(self, arrays, meta, children, restore_child):
        if int(meta["n"]) != self.qubit_count:
            raise ValueError("checkpoint width mismatch")
        self.dtype = np.dtype(meta["dtype"])
        self._state = np.ascontiguousarray(arrays["ket"], dtype=self.dtype)
        self.running_norm = float(meta.get("running_norm", 1.0))
        self._idx_cache = None
