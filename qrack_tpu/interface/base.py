"""QInterface base: the universal gate-level simulator API.

TPU-native re-design of the reference's `QInterface` abstract class
(reference: include/qinterface.hpp:141 — ~400 virtual methods;
src/qinterface/qinterface.cpp — default syntheses). Everything a layer
or engine must implement is reduced to a small primitive contract:

  * ``MCMtrxPerm(controls, mtrx, target, perm)`` — the one gate primitive
  * ``Prob(q)`` / ``ForceM(q, ...)``           — measurement
  * ``Compose / Decompose / Dispose / Allocate`` — structure changes
  * ``GetQuantumState / SetQuantumState / GetAmplitude / SetPermutation``
  * ``Clone`` / ``SumSqrDiff``

Every other method (named gates, rotations, register ops, ALU,
expectation/variance, sampling) is synthesized here, exactly mirroring
how the reference keeps its engines small (reference:
src/qinterface/gates.cpp, rotational.cpp, arithmetic.cpp, logic.cpp).

Index convention matches the reference: qubit 0 is the least-significant
bit of a basis-state permutation index.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence

import numpy as np

from ..config import FP_NORM_EPSILON, get_config
from ..utils.bits import bit_reg_mask, popcount, pow2
from ..utils.rng import QrackRandom
from .. import matrices as mat


class QInterfaceBase:
    """Core state, primitive contract, measurement, and structure ops."""

    # ------------------------------------------------------------------
    # Construction
    # ------------------------------------------------------------------

    def __init__(
        self,
        qubit_count: int,
        init_state: int = 0,
        rng: Optional[QrackRandom] = None,
        do_normalize: bool = True,
        rand_global_phase: bool = True,
        amplitude_floor: float = 0.0,
        **kwargs,
    ):
        self.qubit_count = int(qubit_count)
        self.do_normalize = do_normalize
        self.rand_global_phase = rand_global_phase
        self.amplitude_floor = amplitude_floor
        self.rng = rng if rng is not None else QrackRandom()
        self.running_norm = 1.0
        self.config = get_config()

    # -- capacity accessors (reference: include/qinterface.hpp:330-380) --

    def GetQubitCount(self) -> int:
        return self.qubit_count

    def GetMaxQPower(self) -> int:
        return pow2(self.qubit_count)

    def SetRandomSeed(self, seed: int) -> None:
        self.rng.seed(seed)

    def Rand(self) -> float:
        return self.rng.rand()

    # ------------------------------------------------------------------
    # Primitive contract (abstract)
    # ------------------------------------------------------------------

    def MCMtrxPerm(
        self,
        controls: Sequence[int],
        mtrx: np.ndarray,
        target: int,
        perm: int,
    ) -> None:
        """Apply `mtrx` to `target` when controls[j] == bit j of `perm`.

        The single gate primitive; subsumes Mtrx/MCMtrx/MACMtrx/UCMtrx
        (reference: Apply2x2 offset computation, src/qengine/qengine.cpp).
        """
        raise NotImplementedError

    def Prob(self, q: int) -> float:
        """P(qubit q == 1) (reference: include/qinterface.hpp:2483)."""
        raise NotImplementedError

    def ForceM(self, q: int, result: bool, do_force: bool = True, do_apply: bool = True) -> bool:
        """Measure q, optionally forcing the outcome
        (reference: include/qinterface.hpp:1031)."""
        raise NotImplementedError

    def Compose(self, other: "QInterfaceBase", start: Optional[int] = None) -> int:
        """Tensor `other` into self at `start` (default: append); returns
        the mapped start index (reference: include/qinterface.hpp:382)."""
        raise NotImplementedError

    def Decompose(self, start: int, dest: "QInterfaceBase") -> None:
        """Split `dest.qubit_count` qubits out of self into dest
        (must be separable) (reference: include/qinterface.hpp:443)."""
        raise NotImplementedError

    def Dispose(self, start: int, length: int, disposed_perm: Optional[int] = None) -> None:
        """Drop `length` separable qubits (reference: include/qinterface.hpp:468)."""
        raise NotImplementedError

    def Allocate(self, start: int, length: int = 1) -> int:
        """Add `length` |0> qubits at `start` (reference: include/qinterface.hpp:485)."""
        raise NotImplementedError

    def GetQuantumState(self) -> np.ndarray:
        raise NotImplementedError

    def SetQuantumState(self, state: np.ndarray) -> None:
        raise NotImplementedError

    def GetAmplitude(self, perm: int) -> complex:
        raise NotImplementedError

    def SetAmplitude(self, perm: int, amp: complex) -> None:
        raise NotImplementedError

    def SetPermutation(self, perm: int, phase: complex = 1.0) -> None:
        raise NotImplementedError

    def Clone(self) -> "QInterfaceBase":
        raise NotImplementedError

    def SumSqrDiff(self, other: "QInterfaceBase") -> float:
        """1 - |<self|other>|^2 distance proxy
        (reference: include/qinterface.hpp:2844)."""
        raise NotImplementedError

    # ------------------------------------------------------------------
    # Gate-primitive conveniences (reference: include/qinterface.hpp:503-650)
    # ------------------------------------------------------------------

    def Mtrx(self, mtrx: np.ndarray, target: int) -> None:
        self.MCMtrxPerm((), mtrx, target, 0)

    def MCMtrx(self, controls: Sequence[int], mtrx: np.ndarray, target: int) -> None:
        self.MCMtrxPerm(controls, mtrx, target, (1 << len(controls)) - 1)

    def MACMtrx(self, controls: Sequence[int], mtrx: np.ndarray, target: int) -> None:
        self.MCMtrxPerm(controls, mtrx, target, 0)

    def UCMtrx(
        self,
        controls: Sequence[int],
        mtrxs: Sequence[np.ndarray],
        target: int,
        mtrx_skip_powers: Sequence[int] = (),
        mtrx_skip_value_mask: int = 0,
    ) -> None:
        """Uniformly-controlled gate: one 2x2 payload per control permutation
        (reference: src/qinterface/gates.cpp:23)."""
        n = len(controls)
        for perm in range(1 << n):
            m_index = perm
            if mtrx_skip_powers:
                # splice skip bits into the matrix index (reference semantics)
                for j, p in enumerate(sorted(mtrx_skip_powers)):
                    low = m_index & (p - 1)
                    m_index = ((m_index & ~(p - 1)) << 1) | low
                m_index |= mtrx_skip_value_mask
            self.MCMtrxPerm(controls, np.asarray(mtrxs[m_index]), target, perm)

    # Phase/Invert specializations — engines override with diagonal fast
    # paths (reference: Phase/Invert include/qinterface.hpp:512-540).

    def Phase(self, top_left: complex, bottom_right: complex, target: int) -> None:
        self.Mtrx(mat.phase_mtrx(top_left, bottom_right), target)

    def Invert(self, top_right: complex, bottom_left: complex, target: int) -> None:
        self.Mtrx(mat.invert_mtrx(top_right, bottom_left), target)

    def MCPhase(self, controls, top_left: complex, bottom_right: complex, target: int) -> None:
        self.MCMtrx(controls, mat.phase_mtrx(top_left, bottom_right), target)

    def MCInvert(self, controls, top_right: complex, bottom_left: complex, target: int) -> None:
        self.MCMtrx(controls, mat.invert_mtrx(top_right, bottom_left), target)

    def MACPhase(self, controls, top_left: complex, bottom_right: complex, target: int) -> None:
        self.MACMtrx(controls, mat.phase_mtrx(top_left, bottom_right), target)

    def MACInvert(self, controls, top_right: complex, bottom_left: complex, target: int) -> None:
        self.MACMtrx(controls, mat.invert_mtrx(top_right, bottom_left), target)

    def UCPhase(self, controls, top_left, bottom_right, target, perm) -> None:
        self.MCMtrxPerm(controls, mat.phase_mtrx(top_left, bottom_right), target, perm)

    def UCInvert(self, controls, top_right, bottom_left, target, perm) -> None:
        self.MCMtrxPerm(controls, mat.invert_mtrx(top_right, bottom_left), target, perm)

    # ------------------------------------------------------------------
    # Measurement & sampling defaults
    # (reference: include/qinterface.hpp:1031-1038, 2379-2396, 2802-2818;
    #  src/qinterface/qinterface.cpp:228, :807)
    # ------------------------------------------------------------------

    def M(self, q: int) -> bool:
        return self.ForceM(q, False, do_force=False)

    def ForceMReg(
        self, start: int, length: int, result: int, do_force: bool = True, do_apply: bool = True
    ) -> int:
        """Measure a register; returns the measured integer
        (reference: src/qinterface/qinterface.cpp:228 ForceM-many)."""
        res = 0
        for i in range(length):
            bit = bool((result >> i) & 1)
            if self.ForceM(start + i, bit, do_force=do_force, do_apply=do_apply):
                res |= 1 << i
        return res

    def MReg(self, start: int, length: int) -> int:
        return self.ForceMReg(start, length, 0, do_force=False)

    def MAll(self) -> int:
        return self.MReg(0, self.qubit_count)

    def ForceMBits(self, bits: Sequence[int], values: int, do_apply: bool = True) -> int:
        res = 0
        for j, q in enumerate(bits):
            bit = bool((values >> j) & 1)
            if self.ForceM(q, bit, do_force=True, do_apply=do_apply):
                res |= 1 << j
        return res

    def MultiShotMeasureMask(self, q_powers: Sequence[int], shots: int) -> dict:
        """Repeated non-collapsing sampling of the qubits in `q_powers`
        (reference: src/qinterface/qinterface.cpp:807 — clone-based default;
        dense engines override with a vectorized categorical draw)."""
        results: dict = {}
        for _ in range(shots):
            clone = self.Clone()
            all_bits = clone.MAll()
            key = 0
            for j, p in enumerate(q_powers):
                if all_bits & p:
                    key |= 1 << j
            results[key] = results.get(key, 0) + 1
        return results

    def SampleClone(self, q_powers: Sequence[int]) -> int:
        clone = self.Clone()
        all_bits = clone.MAll()
        key = 0
        for j, p in enumerate(q_powers):
            if all_bits & p:
                key |= 1 << j
        return key

    # ------------------------------------------------------------------
    # Probability / expectation / variance defaults
    # (reference: include/qinterface.hpp:2483-2798;
    #  src/qinterface/qinterface.cpp:423-850)
    # ------------------------------------------------------------------

    def ProbAll(self, perm: int) -> float:
        return abs(self.GetAmplitude(perm)) ** 2

    def CProb(self, control: int, target: int) -> float:
        """P(target==1 | control==1) (reference: include/qinterface.hpp:2495)."""
        return self._prob_cond(control, target, True)

    def ACProb(self, control: int, target: int) -> float:
        return self._prob_cond(control, target, False)

    def _prob_cond(self, control: int, target: int, control_on: bool) -> float:
        probs = self.GetProbs()
        idx = np.arange(probs.shape[0])
        cmask = (idx >> control) & 1
        sel = cmask == (1 if control_on else 0)
        denom = float(probs[sel].sum())
        if denom <= FP_NORM_EPSILON:
            return 0.0
        tsel = sel & (((idx >> target) & 1) == 1)
        return float(probs[tsel].sum()) / denom

    def GetProbs(self) -> np.ndarray:
        state = self.GetQuantumState()
        return (state.real ** 2 + state.imag ** 2).astype(np.float64)

    def ProbReg(self, start: int, length: int, perm: int) -> float:
        """P(register [start,start+length) == perm)
        (reference: include/qinterface.hpp:2520)."""
        return self.ProbMask(bit_reg_mask(start, length), perm << start)

    def ProbMask(self, mask: int, perm: int) -> float:
        """P(masked bits == perm) (reference: src/qinterface/qinterface.cpp:423)."""
        probs = self.GetProbs()
        idx = np.arange(probs.shape[0], dtype=np.int64)
        return float(probs[(idx & mask) == perm].sum())

    def ProbMaskAll(self, mask: int) -> np.ndarray:
        """Distribution over all permutations of the masked bits
        (reference: src/qinterface/qinterface.cpp:423 ProbMaskAll)."""
        bits = [i for i in range(self.qubit_count) if (mask >> i) & 1]
        probs = self.GetProbs()
        idx = np.arange(probs.shape[0], dtype=np.int64)
        key = np.zeros_like(idx)
        for j, b in enumerate(bits):
            key |= ((idx >> b) & 1) << j
        out = np.zeros(1 << len(bits), dtype=np.float64)
        np.add.at(out, key, probs)
        return out

    def ProbBitsAll(self, bits: Sequence[int]) -> np.ndarray:
        mask = 0
        for b in bits:
            mask |= 1 << b
        return self.ProbMaskAll(mask)

    def ExpectationBitsAll(self, bits: Sequence[int], offset: int = 0) -> float:
        """<integer value of bits> (reference: src/qinterface/qinterface.cpp:478)."""
        dist = self.ProbBitsAll(bits)
        vals = np.arange(dist.shape[0], dtype=np.float64) + offset
        return float((dist * vals).sum())

    def ExpectationBitsFactorized(
        self, bits: Sequence[int], perms: Sequence[int], offset: int = 0
    ) -> float:
        """Expectation with per-bit integer weights: value of outcome is
        sum_j perms[2*j + bit_j] (reference: ExpectationBitsFactorized)."""
        dist = self.ProbBitsAll(bits)
        vals = np.zeros(dist.shape[0], dtype=np.float64)
        for k in range(dist.shape[0]):
            v = offset
            for j in range(len(bits)):
                v += perms[2 * j + ((k >> j) & 1)]
            vals[k] = v
        return float((dist * vals).sum())

    def ExpectationFloatsFactorized(self, bits: Sequence[int], weights: Sequence[float]) -> float:
        dist = self.ProbBitsAll(bits)
        vals = np.zeros(dist.shape[0], dtype=np.float64)
        for k in range(dist.shape[0]):
            v = 0.0
            for j in range(len(bits)):
                v += weights[2 * j + ((k >> j) & 1)]
            vals[k] = v
        return float((dist * vals).sum())

    def _variance_from(self, dist: np.ndarray, vals: np.ndarray) -> float:
        mean = float((dist * vals).sum())
        return float((dist * (vals - mean) ** 2).sum())

    def VarianceBitsAll(self, bits: Sequence[int], offset: int = 0) -> float:
        dist = self.ProbBitsAll(bits)
        vals = np.arange(dist.shape[0], dtype=np.float64) + offset
        return self._variance_from(dist, vals)

    def VarianceBitsFactorized(
        self, bits: Sequence[int], perms: Sequence[int], offset: int = 0
    ) -> float:
        dist = self.ProbBitsAll(bits)
        vals = np.zeros(dist.shape[0], dtype=np.float64)
        for k in range(dist.shape[0]):
            v = offset
            for j in range(len(bits)):
                v += perms[2 * j + ((k >> j) & 1)]
            vals[k] = v
        return self._variance_from(dist, vals)

    def VarianceFloatsFactorized(self, bits: Sequence[int], weights: Sequence[float]) -> float:
        dist = self.ProbBitsAll(bits)
        vals = np.zeros(dist.shape[0], dtype=np.float64)
        for k in range(dist.shape[0]):
            v = 0.0
            for j in range(len(bits)):
                v += weights[2 * j + ((k >> j) & 1)]
            vals[k] = v
        return self._variance_from(dist, vals)

    # -- Pauli / single-qubit-unitary tensor observables, overridable at
    #    the layer level (reference: ExpectationPauliAll /
    #    VariancePauliAll / ExpectationUnitaryAll,
    #    include/qinterface.hpp:2688-2712; ExpVarUnitaryAll,
    #    src/qinterface/qinterface.cpp:478) --

    def _transform_pauli_basis(self, paulis, bits) -> int:
        """Rotate X/Y observables into Z; returns the joint Z mask
        (reference: TransformPauliBasis, src/pinvoke_api.cpp)."""
        from ..pauli import Pauli

        mask = 0
        for b, qi in zip(paulis, bits):
            p = Pauli(b)
            if p == Pauli.PauliX:
                self.H(qi)
            elif p == Pauli.PauliY:
                self.IS(qi)
                self.H(qi)
            if p != Pauli.PauliI:
                mask |= 1 << qi
        return mask

    def _revert_pauli_basis(self, paulis, bits) -> None:
        from ..pauli import Pauli

        for b, qi in zip(paulis, bits):
            p = Pauli(b)
            if p == Pauli.PauliX:
                self.H(qi)
            elif p == Pauli.PauliY:
                self.H(qi)
                self.S(qi)

    def ExpectationPauliAll(self, bits: Sequence[int], paulis: Sequence[int]) -> float:
        """<P_1 (x) P_2 (x) ...> by basis conjugation: +-1 eigenvalues
        weighted by joint parity."""
        mask = self._transform_pauli_basis(paulis, bits)
        try:
            p_odd = self.ProbParity(mask) if mask else 0.0
        finally:
            self._revert_pauli_basis(paulis, bits)
        return 1.0 - 2.0 * p_odd

    def VariancePauliAll(self, bits: Sequence[int], paulis: Sequence[int]) -> float:
        e = self.ExpectationPauliAll(bits, paulis)
        return max(0.0, 1.0 - e * e)  # P^2 == I for any Pauli string

    def _unitary_stat(self, bits, basis_ops, eigen_vals, variance: bool) -> float:
        """Expectation/variance of per-qubit observables diagonalized by
        the given 2x2 unitaries; conjugation is applied and undone."""
        ms = [np.asarray(m, dtype=np.complex128).reshape(2, 2)
              for m in basis_ops]
        for qi, m in zip(bits, ms):
            self.Mtrx(np.conj(m.T), qi)
        try:
            w = ([1.0, -1.0] * len(list(bits)) if eigen_vals is None
                 else [float(v) for v in eigen_vals])
            stat = (self.VarianceFloatsFactorized(list(bits), w) if variance
                    else self.ExpectationFloatsFactorized(list(bits), w))
        finally:
            for qi, m in zip(bits, ms):
                self.Mtrx(m, qi)
        return float(stat)

    def ExpectationUnitaryAll(self, bits: Sequence[int], basis_ops,
                              eigen_vals=None) -> float:
        return self._unitary_stat(bits, basis_ops, eigen_vals, False)

    def VarianceUnitaryAll(self, bits: Sequence[int], basis_ops,
                           eigen_vals=None) -> float:
        return self._unitary_stat(bits, basis_ops, eigen_vals, True)

    # Reduced-density-matrix ("Rdm") variants: for exact simulation these
    # coincide with the plain versions; approximate layers override
    # (reference: include/qinterface.hpp:2483-2798 *Rdm family).

    def ProbRdm(self, q: int) -> float:
        return self.Prob(q)

    def ProbAllRdm(self, round_rz: bool, perm: int) -> float:
        return self.ProbAll(perm)

    def ProbMaskRdm(self, round_rz: bool, mask: int, perm: int) -> float:
        return self.ProbMask(mask, perm)

    def ExpectationBitsAllRdm(self, round_rz: bool, bits: Sequence[int], offset: int = 0) -> float:
        return self.ExpectationBitsAll(bits, offset)

    def VarianceBitsAllRdm(self, round_rz: bool, bits: Sequence[int], offset: int = 0) -> float:
        return self.VarianceBitsAll(bits, offset)

    def GetReducedDensityMatrix(self, bits: Sequence[int]) -> np.ndarray:
        """Dense RDM over `bits` by partial trace
        (reference: src/qinterface/qinterface.cpp:886)."""
        n = self.qubit_count
        state = np.asarray(self.GetQuantumState(), dtype=np.complex128)
        tensor = state.reshape((2,) * n)
        # numpy axis k corresponds to qubit n-1-k
        keep_axes = [n - 1 - b for b in bits]
        other = [a for a in range(n) if a not in keep_axes]
        perm = keep_axes + other
        t = np.transpose(tensor, perm).reshape(1 << len(bits), -1)
        return t @ t.conj().T

    # ------------------------------------------------------------------
    # Comparison / normalization
    # (reference: include/qinterface.hpp:2834-2906)
    # ------------------------------------------------------------------

    def ApproxCompare(self, other: "QInterfaceBase", error_tol: float = 1e-4) -> bool:
        return self.SumSqrDiff(other) <= error_tol

    def UpdateRunningNorm(self, norm_thresh: float = -1.0) -> None:
        pass

    def NormalizeState(self, nrm: float = -1.0, norm_thresh: float = -1.0, phase_arg: float = 0.0) -> None:
        pass

    def Finish(self) -> None:
        """Block until queued work completes (reference:
        include/qinterface.hpp:2873; JAX analogue: block_until_ready)."""
        pass

    def isFinished(self) -> bool:
        return True

    def Dump(self) -> None:
        pass

    # ------------------------------------------------------------------
    # Fidelity / approximation controls
    # (reference: include/qinterface.hpp:2925-3104)
    # ------------------------------------------------------------------

    def TrySeparate(self, qubits, error_tol: Optional[float] = None) -> bool:
        """Attempt Schmidt separation (no-op outside QUnit)."""
        return False

    def GetUnitaryFidelity(self) -> float:
        return 1.0

    def ResetUnitaryFidelity(self) -> None:
        pass

    def SetSdrp(self, sdrp: float) -> None:
        pass

    def SetNcrp(self, ncrp: float) -> None:
        pass

    def SetReactiveSeparate(self, flag: bool) -> None:
        pass

    def GetReactiveSeparate(self) -> bool:
        return False

    def SetTInjection(self, flag: bool) -> None:
        pass

    def GetTInjection(self) -> bool:
        return False

    def SetNoiseParameter(self, lam: float) -> None:
        pass

    def isClifford(self, q: Optional[int] = None) -> bool:
        return False

    def isBinaryDecisionTree(self) -> bool:
        return False

    def isOpenCL(self) -> bool:  # legacy name kept for API parity
        return False

    def SetDevice(self, device_id: int) -> None:
        pass

    def SetDeviceList(self, device_ids: Sequence[int]) -> None:
        pass

    def GetDevice(self) -> int:
        return -1

    def GetDeviceList(self) -> List[int]:
        return []

    # ------------------------------------------------------------------
    # Noise (reference: include/qinterface.hpp:3104)
    # ------------------------------------------------------------------

    def DepolarizingChannelWeak1Qb(self, q: int, lam: float) -> None:
        """Weak (stochastic-unraveling) single-qubit depolarizing channel:
        with probability 3λ/4 apply a uniformly random non-identity Pauli."""
        if lam <= 0.0:
            return
        if self.Rand() < 0.75 * lam:
            which = self.rng.randint(0, 3)
            if which == 0:
                self.X(q)
            elif which == 1:
                self.Y(q)
            else:
                self.Z(q)

    # ------------------------------------------------------------------
    # Lossy save/load (reference: include/qinterface.hpp:302-307;
    # src/qinterface/qinterface.cpp:855-884)
    # ------------------------------------------------------------------

    def LossySaveStateVector(self, path: str, bits: int = 8, block_pow: int = 12) -> None:
        from ..storage.turboquant import lossy_save

        lossy_save(self.GetQuantumState(), path, bits=bits, block_pow=block_pow)

    def LossyLoadStateVector(self, path: str) -> None:
        from ..storage.turboquant import lossy_load

        self.SetQuantumState(lossy_load(path))

    # ------------------------------------------------------------------
    # misc helpers shared by mixins
    # ------------------------------------------------------------------

    def _check_qubit(self, q: int) -> None:
        if q < 0 or q >= self.qubit_count:
            raise ValueError(f"qubit index {q} out of range (n={self.qubit_count})")

    def _check_range(self, start: int, length: int) -> None:
        if start < 0 or length < 0 or start + length > self.qubit_count:
            raise ValueError(
                f"register [{start}, {start + length}) out of range (n={self.qubit_count})"
            )
