"""Two-level (Givens + Gray-code) synthesis of small unitaries.

Lets any layer apply an arbitrary 2^k x 2^k unitary through the single
MCMtrxPerm primitive, the same role the reference's compositional
fallbacks play (reference: src/qinterface/gates.cpp — Swap/FSim built
from CNOT ladders). Dense engines override Apply4x4 with a native
tensor contraction; this path exists so *every* layer supports the full
two-qubit gate family.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

_X2 = np.array([[0, 1], [1, 0]], dtype=np.complex128)


def two_level_decompose(u: np.ndarray) -> List[Tuple[int, int, np.ndarray]]:
    """Factor unitary `u` into two-level unitaries.

    Returns ops [(i, j, m2), ...] such that applying each m2 on the
    (|i>, |j>) subspace *in list order* implements `u`.
    """
    d = u.shape[0]
    w = u.astype(np.complex128).copy()
    t_list: List[Tuple[int, int, np.ndarray]] = []  # T_k ... T_1 w = I
    for c in range(d - 1):
        for r in range(c + 1, d):
            a = w[c, c]
            b = w[r, c]
            if abs(b) < 1e-14:
                continue
            n = np.sqrt(abs(a) ** 2 + abs(b) ** 2)
            g = np.array(
                [[np.conj(a) / n, np.conj(b) / n], [b / n, -a / n]], dtype=np.complex128
            )
            # rows c, r of w <- g @ [row c; row r]
            rows = np.stack([w[c, :], w[r, :]])
            rows = g @ rows
            w[c, :] = rows[0]
            w[r, :] = rows[1]
            t_list.append((c, r, g))
        # normalize the diagonal phase of column c
        ph = w[c, c]
        if abs(ph - 1.0) > 1e-14:
            g = np.array([[np.conj(ph), 0], [0, 1]], dtype=np.complex128)
            w[c, :] = np.conj(ph) * w[c, :]
            # the (c, c) "two-level" phase needs a partner index; use d-1
            t_list.append((c, d - 1, np.array([[np.conj(ph), 0], [0, 1]], dtype=np.complex128)))
            # undo the unintended identity action on row d-1 (none: bottom-right is 1)
    ph = w[d - 1, d - 1]
    if abs(ph - 1.0) > 1e-14:
        t_list.append((d - 2, d - 1, np.array([[1, 0], [0, np.conj(ph)]], dtype=np.complex128)))
        w[d - 1, :] = np.conj(ph) * w[d - 1, :]
    # w is now I; u = T_1^† ... T_k^†, applied right-to-left ⇒ op order T_k^†, ..., T_1^†
    ops = [(i, j, np.conj(g.T)) for (i, j, g) in reversed(t_list)]
    return ops


def apply_small_unitary_via_primitive(
    qi,
    u: np.ndarray,
    qubits: Sequence[int],
    controls: Sequence[int] = (),
    perm: int = 0,
) -> None:
    """Apply `u` over `qubits` (qubits[0] = least-significant subspace bit)
    via MCMtrxPerm, optionally under external `controls` at permutation
    `perm`."""
    k = len(qubits)
    assert u.shape == (1 << k, 1 << k)
    for (i, j, m2) in two_level_decompose(u):
        _apply_two_level(qi, qubits, i, j, m2, controls, perm)


def _apply_two_level(qi, qubits, i, j, m2, ext_controls, ext_perm) -> None:
    diff = i ^ j
    bits = [t for t in range(len(qubits)) if (diff >> t) & 1]
    # Gray-code walk i -> j; last flip is the gate target
    path = [i]
    cur = i
    for b in bits:
        cur ^= 1 << b
        path.append(cur)
    # permutation steps mapping amplitude of i to path[-2]
    for t in range(1, len(path) - 1):
        _pair_x(qi, qubits, path[t - 1], path[t], ext_controls, ext_perm)
    a, b = path[-2], path[-1]
    tbit = (a ^ b).bit_length() - 1
    # basis order: m2 is expressed on (|i>, |j>) ~ (|a>, |b>) after the walk
    if (a >> tbit) & 1:
        g = _X2 @ m2 @ _X2  # a has target=1: reorder to (|target=0>, |target=1>)
    else:
        g = m2
    _controlled_on_pair(qi, qubits, a, tbit, g, ext_controls, ext_perm)
    for t in reversed(range(1, len(path) - 1)):
        _pair_x(qi, qubits, path[t - 1], path[t], ext_controls, ext_perm)


def _pair_x(qi, qubits, a, b, ext_controls, ext_perm) -> None:
    tbit = (a ^ b).bit_length() - 1
    _controlled_on_pair(qi, qubits, a, tbit, _X2, ext_controls, ext_perm)


def _controlled_on_pair(qi, qubits, rep, tbit, g, ext_controls, ext_perm) -> None:
    """Apply 2x2 `g` to qubits[tbit], controlled on every other subspace
    qubit matching index `rep`, plus the external controls."""
    ctrls = []
    perm = 0
    pos = 0
    for t, q in enumerate(qubits):
        if t == tbit:
            continue
        ctrls.append(q)
        if (rep >> t) & 1:
            perm |= 1 << pos
        pos += 1
    for jx, c in enumerate(ext_controls):
        ctrls.append(c)
        if (ext_perm >> jx) & 1:
            perm |= 1 << pos
        pos += 1
    qi.MCMtrxPerm(tuple(ctrls), g, qubits[tbit], perm)
