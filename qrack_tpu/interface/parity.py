"""Parity measurement/rotation mixin (the QParity surface).

Reference: include/qparity.hpp:22-56 — ProbParity / ForceMParity /
UniformParityRZ / CUniformParityRZ; engine kernels probparity /
forcemparity / uniformparityrz (src/common/qengine.cl:452-948).
Defaults here are universal syntheses; dense engines override with
vectorized diagonal kernels.
"""

from __future__ import annotations

import math

import numpy as np


class ParityMixin:
    def _mask_bits(self, mask: int):
        return [i for i in range(self.qubit_count) if (mask >> i) & 1]

    def ProbParity(self, mask: int) -> float:
        """P(odd parity) over the masked bits."""
        probs = self.GetProbs()
        idx = np.arange(probs.shape[0], dtype=np.uint64)
        par = np.bitwise_count(idx & np.uint64(mask)) & 1
        return float(probs[par == 1].sum())

    def ForceMParity(self, mask: int, result: bool, do_force: bool = True) -> bool:
        """Measure (or force) the joint parity of the masked bits."""
        odd_prob = self.ProbParity(mask)
        if not do_force:
            result = self.Rand() <= odd_prob
        nrm_sq = odd_prob if result else (1.0 - odd_prob)
        if nrm_sq <= 0.0:
            raise RuntimeError("ForceMParity: forced outcome has zero probability")
        state = np.asarray(self.GetQuantumState(), dtype=np.complex128).copy()
        idx = np.arange(state.shape[0], dtype=np.uint64)
        par = (np.bitwise_count(idx & np.uint64(mask)) & 1).astype(bool)
        keep = par if result else ~par
        state[~keep] = 0.0
        state /= math.sqrt(nrm_sq)
        self.SetQuantumState(state)
        return bool(result)

    def UniformParityRZ(self, mask: int, angle: float) -> None:
        """Parity phase: e^{+i*angle} on odd parity of the masked bits,
        e^{-i*angle} on even (reference kernel uniformparityrz,
        src/common/qengine.cl:452; phase factors src/qengine/opencl.cpp:1145)."""
        bits = self._mask_bits(mask)
        if not bits:
            return
        for i in range(len(bits) - 1):
            self.CNOT(bits[i], bits[i + 1])
        self.RZ(2.0 * angle, bits[-1])
        for i in reversed(range(len(bits) - 1)):
            self.CNOT(bits[i], bits[i + 1])

    def CUniformParityRZ(self, controls, mask: int, angle: float) -> None:
        bits = self._mask_bits(mask)
        if not bits:
            return
        controls = tuple(controls)
        for i in range(len(bits) - 1):
            self.CNOT(bits[i], bits[i + 1])
        c, s = math.cos(angle), math.sin(angle)
        self.MCPhase(controls, complex(c, -s), complex(c, s), bits[-1])
        for i in reversed(range(len(bits) - 1)):
            self.CNOT(bits[i], bits[i + 1])
