"""Register-spanning operations: QFT family, circular shifts, bitwise gates.

Mirrors the reference's register API (reference: QFT/IQFT/QFTR
src/qinterface/qinterface.cpp:114-180; ROL/ROR :297-330 swap-reversal
algorithm; bitwise gate loops include/qinterface.hpp:1737-2141, gated
there by ENABLE_REG_GATES — always available here).
"""

from __future__ import annotations

import cmath
import math
from typing import Sequence


class RegistersMixin:
    # ---------------- QFT family ----------------

    def PhaseRootNMask(self, n: int, mask: int) -> None:
        q = 0
        m = mask
        while m:
            if m & 1:
                self.PhaseRootN(n, q)
            m >>= 1
            q += 1

    def CPhaseRootN(self, n: int, control: int, target: int) -> None:
        if n == 0:
            return
        self.MCPhase((control,), 1.0, cmath.exp(1j * math.pi / (1 << (n - 1))), target)

    def CIPhaseRootN(self, n: int, control: int, target: int) -> None:
        if n == 0:
            return
        self.MCPhase((control,), 1.0, cmath.exp(-1j * math.pi / (1 << (n - 1))), target)

    def AntiCPhaseRootN(self, n: int, control: int, target: int) -> None:
        if n == 0:
            return
        self.MACPhase((control,), 1.0, cmath.exp(1j * math.pi / (1 << (n - 1))), target)

    def AntiCIPhaseRootN(self, n: int, control: int, target: int) -> None:
        if n == 0:
            return
        self.MACPhase((control,), 1.0, cmath.exp(-1j * math.pi / (1 << (n - 1))), target)

    def QFT(self, start: int, length: int, try_separate: bool = False) -> None:
        """QFT optimized for |0>/|1> -> |+>/|-> (reference:
        src/qinterface/qinterface.cpp:114)."""
        if not length:
            return
        end = start + length - 1
        for i in range(length):
            h_bit = end - i
            for j in range(i):
                c = h_bit
                t = h_bit + 1 + j
                self.CPhaseRootN(j + 2, c, t)
                if try_separate:
                    self.TrySeparate((c, t))
            self.H(h_bit)

    def IQFT(self, start: int, length: int, try_separate: bool = False) -> None:
        if not length:
            return
        for i in range(length):
            for j in range(i):
                c = (start + i) - (j + 1)
                t = start + i
                self.CIPhaseRootN(j + 2, c, t)
                if try_separate:
                    self.TrySeparate((c, t))
            self.H(start + i)

    def QFTR(self, qubits: Sequence[int], try_separate: bool = False) -> None:
        """QFT over an arbitrary qubit list (reference:
        src/qinterface/qinterface.cpp:157)."""
        if not qubits:
            return
        end = len(qubits) - 1
        for i in range(len(qubits)):
            self.H(qubits[end - i])
            for j in range(len(qubits) - 1 - i):
                self.CPhaseRootN(j + 2, qubits[end - i - (j + 1)], qubits[end - i])
            if try_separate:
                self.TrySeparate(qubits[end - i])

    def IQFTR(self, qubits: Sequence[int], try_separate: bool = False) -> None:
        if not qubits:
            return
        for i in range(len(qubits)):
            for j in range(i):
                self.CIPhaseRootN(i - j + 1, qubits[j], qubits[i])
            self.H(qubits[i])
            if try_separate:
                self.TrySeparate(qubits[i])

    # ---------------- circular shifts (reference: qinterface.cpp:297) ------

    def Reverse(self, first: int, last: int) -> None:
        """Reverse qubit order in [first, last) via swaps."""
        last -= 1
        while first < last:
            self.Swap(first, last)
            first += 1
            last -= 1

    def ROL(self, shift: int, start: int, length: int) -> None:
        if length < 2:
            return
        shift %= length
        if not shift:
            return
        end = start + length
        self.Reverse(start, end)
        self.Reverse(start, start + shift)
        self.Reverse(start + shift, end)

    def ROR(self, shift: int, start: int, length: int) -> None:
        if length < 2:
            return
        shift %= length
        if not shift:
            return
        end = start + length
        self.Reverse(start + shift, end)
        self.Reverse(start, start + shift)
        self.Reverse(start, end)

    # ---------------- classical register set ----------------

    def SetReg(self, start: int, length: int, value: int) -> None:
        """Set a register to a classical value (reference: SetReg —
        measure then flip differing bits)."""
        measured = self.MReg(start, length)
        diff = measured ^ value
        for i in range(length):
            if (diff >> i) & 1:
                self.X(start + i)

    def SetBit(self, q: int, value: bool) -> None:
        if self.M(q) != value:
            self.X(q)

    # ---------------- bitwise register gates ----------------
    # (reference: include/qinterface.hpp:1737-2141)

    def HReg(self, start: int, length: int) -> None:
        for i in range(length):
            self.H(start + i)

    def XReg(self, start: int, length: int) -> None:
        for i in range(length):
            self.X(start + i)

    def YReg(self, start: int, length: int) -> None:
        for i in range(length):
            self.Y(start + i)

    def ZReg(self, start: int, length: int) -> None:
        for i in range(length):
            self.Z(start + i)

    def SReg(self, start: int, length: int) -> None:
        for i in range(length):
            self.S(start + i)

    def ISReg(self, start: int, length: int) -> None:
        for i in range(length):
            self.IS(start + i)

    def TReg(self, start: int, length: int) -> None:
        for i in range(length):
            self.T(start + i)

    def ITReg(self, start: int, length: int) -> None:
        for i in range(length):
            self.IT(start + i)

    def SqrtXReg(self, start: int, length: int) -> None:
        for i in range(length):
            self.SqrtX(start + i)

    def ISqrtXReg(self, start: int, length: int) -> None:
        for i in range(length):
            self.ISqrtX(start + i)

    def PhaseRootNReg(self, n: int, start: int, length: int) -> None:
        for i in range(length):
            self.PhaseRootN(n, start + i)

    def IPhaseRootNReg(self, n: int, start: int, length: int) -> None:
        for i in range(length):
            self.IPhaseRootN(n, start + i)

    def CNOTReg(self, control_start: int, target_start: int, length: int) -> None:
        for i in range(length):
            self.CNOT(control_start + i, target_start + i)

    def AntiCNOTReg(self, control_start: int, target_start: int, length: int) -> None:
        for i in range(length):
            self.AntiCNOT(control_start + i, target_start + i)

    def CCNOTReg(self, c1_start: int, c2_start: int, target_start: int, length: int) -> None:
        for i in range(length):
            self.CCNOT(c1_start + i, c2_start + i, target_start + i)

    def CYReg(self, control_start: int, target_start: int, length: int) -> None:
        for i in range(length):
            self.CY(control_start + i, target_start + i)

    def CZReg(self, control_start: int, target_start: int, length: int) -> None:
        for i in range(length):
            self.CZ(control_start + i, target_start + i)

    def SwapReg(self, start1: int, start2: int, length: int) -> None:
        for i in range(length):
            self.Swap(start1 + i, start2 + i)

    def ISwapReg(self, start1: int, start2: int, length: int) -> None:
        for i in range(length):
            self.ISwap(start1 + i, start2 + i)

    def SqrtSwapReg(self, start1: int, start2: int, length: int) -> None:
        for i in range(length):
            self.SqrtSwap(start1 + i, start2 + i)

    def CSwapReg(self, control_start: int, start1: int, start2: int, length: int) -> None:
        for i in range(length):
            self.CSwap((control_start + i,), start1 + i, start2 + i)

    def ANDReg(self, a_start: int, b_start: int, out_start: int, length: int) -> None:
        for i in range(length):
            self.AND(a_start + i, b_start + i, out_start + i)

    def ORReg(self, a_start: int, b_start: int, out_start: int, length: int) -> None:
        for i in range(length):
            self.OR(a_start + i, b_start + i, out_start + i)

    def XORReg(self, a_start: int, b_start: int, out_start: int, length: int) -> None:
        for i in range(length):
            self.XOR(a_start + i, b_start + i, out_start + i)

    def CLANDReg(self, classical: int, q_start: int, out_start: int, length: int) -> None:
        for i in range(length):
            self.CLAND(bool((classical >> i) & 1), q_start + i, out_start + i)

    def CLORReg(self, classical: int, q_start: int, out_start: int, length: int) -> None:
        for i in range(length):
            self.CLOR(bool((classical >> i) & 1), q_start + i, out_start + i)

    def CLXORReg(self, classical: int, q_start: int, out_start: int, length: int) -> None:
        for i in range(length):
            self.CLXOR(bool((classical >> i) & 1), q_start + i, out_start + i)

    def RTReg(self, radians: float, start: int, length: int) -> None:
        for i in range(length):
            self.RT(radians, start + i)

    def RXReg(self, radians: float, start: int, length: int) -> None:
        for i in range(length):
            self.RX(radians, start + i)

    def RYReg(self, radians: float, start: int, length: int) -> None:
        for i in range(length):
            self.RY(radians, start + i)

    def RZReg(self, radians: float, start: int, length: int) -> None:
        for i in range(length):
            self.RZ(radians, start + i)

    def CRZReg(self, radians: float, control_start: int, target_start: int, length: int) -> None:
        for i in range(length):
            self.CRZ(radians, control_start + i, target_start + i)

    def ExpReg(self, radians: float, start: int, length: int) -> None:
        for i in range(length):
            self.Exp(radians, start + i)

    def ExpXReg(self, radians: float, start: int, length: int) -> None:
        for i in range(length):
            self.ExpX(radians, start + i)

    def ExpYReg(self, radians: float, start: int, length: int) -> None:
        for i in range(length):
            self.ExpY(radians, start + i)

    def ExpZReg(self, radians: float, start: int, length: int) -> None:
        for i in range(length):
            self.ExpZ(radians, start + i)
