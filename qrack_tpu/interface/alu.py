"""Quantum arithmetic-logic default syntheses (the QAlu surface).

Mirrors the reference's ALU API and fallback constructions (reference:
include/qalu.hpp:22-249; src/qalu.cpp — carry/borrow wrappers;
src/qinterface/arithmetic.cpp:20-420 — CNOT/CCNOT-ladder INC/CINC,
shift-add MULModNOut, full-adder chains). Dense engines override the
hot ops with vectorized index-permutation kernels
(qrack_tpu/ops/alu_kernels.py — the analogue of the reference's
qheader_alu.cl kernel set).

Register convention matches the reference: `start` is the LSB of a
`length`-bit little-endian register; signed ops use two's complement
with the sign at bit `length-1`.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from .. import matrices as mat


def _range_to_cubes(lo: int, hi: int, length: int) -> List[Tuple[int, int]]:
    """Decompose integer range [lo, hi) over `length`-bit values into
    aligned blocks (bit_count k, block_index m) with block = [m*2^k, (m+1)*2^k).

    Bounds are clamped to the representable values here — an
    out-of-range bound (e.g. PhaseFlipIfLess with greater_perm >=
    2^length) must never emit impossible-value cubes, which mis-fire as
    extra flips (fuzz-soak regression, round 5)."""
    lo = max(lo, 0)
    hi = min(hi, 1 << length)
    cubes: List[Tuple[int, int]] = []
    k = 0
    while lo < hi:
        # close lowest-aligned blocks from the left
        while k < length and (lo & ((1 << (k + 1)) - 1)) == 0 and lo + (1 << (k + 1)) <= hi:
            k += 1
        while (lo & ((1 << k) - 1)) != 0 or lo + (1 << k) > hi:
            k -= 1
        cubes.append((k, lo >> k))
        lo += 1 << k
    return cubes


class AluMixin:
    # ------------------------------------------------------------------
    # helpers
    # ------------------------------------------------------------------

    def _flip_if_in_range(self, lo: int, hi: int, start: int, length: int, target: int,
                          extra_controls: Sequence[int] = (), extra_perm: int = 0) -> None:
        """X `target` for every basis state whose [start,length) register
        value lies in [lo, hi) — used for carry/overflow flags.
        Bounds are clamped by _range_to_cubes."""
        if length == 0:
            # a zero-bit register has value 0: unconditional flip iff
            # 0 is in range (matches the engine kernels' v-in-range test)
            if lo <= 0 < hi:
                self.MCMtrxPerm(tuple(extra_controls), mat.X2, target,
                                extra_perm)
            return
        if lo >= hi or hi <= 0 or lo >= (1 << length):
            return
        for (k, m) in _range_to_cubes(lo, hi, length):
            ctrls = list(extra_controls)
            perm = extra_perm
            pos = len(ctrls)
            for b in range(k, length):
                ctrls.append(start + b)
                if (m >> (b - k)) & 1:
                    perm |= 1 << pos
                pos += 1
            self.MCMtrxPerm(tuple(ctrls), mat.X2, target, perm)

    def _phase_flip_if_in_range(self, lo: int, hi: int, start: int, length: int,
                                extra_controls: Sequence[int] = (), extra_perm: int = 0) -> None:
        """-1 phase on every basis state whose register value is in
        [lo, hi).  Bounds are clamped by _range_to_cubes."""
        minus_i2 = np.array([[-1, 0], [0, -1]], dtype=np.complex128)
        if length == 0:
            # zero-bit register: value 0 — global flip iff 0 in range
            # (-I on any qubit outside the controls is a global -1)
            if lo <= 0 < hi:
                ctrls = tuple(extra_controls)
                t = 0
                while t in ctrls:
                    t += 1
                if t < self.qubit_count:
                    self.MCMtrxPerm(ctrls, minus_i2, t, extra_perm)
                elif ctrls:
                    # every qubit is a control: demote the last control
                    # to the target with a one-sided phase matrix — the
                    # -1 fires on exactly the same basis states (a bare
                    # scan here used to pick t == qubit_count and throw)
                    pos = len(ctrls) - 1
                    want1 = (extra_perm >> pos) & 1
                    ph = (mat.phase_mtrx(1, -1) if want1
                          else mat.phase_mtrx(-1, 1))
                    self.MCMtrxPerm(ctrls[:pos], ph, ctrls[pos],
                                    extra_perm & ((1 << pos) - 1))
                # zero-qubit interface: nothing to phase, silently done
            return
        if lo >= hi or hi <= 0 or lo >= (1 << length):
            return
        for (k, m) in _range_to_cubes(lo, hi, length):
            ctrls = list(extra_controls)
            perm = extra_perm
            pos = len(ctrls)
            if k > 0:
                # at least one free register bit: controlled -I on it
                for b in range(k, length):
                    ctrls.append(start + b)
                    if (m >> (b - k)) & 1:
                        perm |= 1 << pos
                    pos += 1
                self.MCMtrxPerm(tuple(ctrls), minus_i2, start, perm)
            else:
                # fully specified value: fold lowest bit into the phase payload
                for b in range(1, length):
                    ctrls.append(start + b)
                    if (m >> b) & 1:
                        perm |= 1 << pos
                    pos += 1
                ph = mat.phase_mtrx(-1, 1) if (m & 1) == 0 else mat.phase_mtrx(1, -1)
                self.MCMtrxPerm(tuple(ctrls), ph, start, perm)

    # ------------------------------------------------------------------
    # add/subtract (reference: src/qinterface/arithmetic.cpp:20-125)
    # ------------------------------------------------------------------

    def INC(self, to_add: int, start: int, length: int) -> None:
        if not length:
            return
        to_add &= (1 << length) - 1
        if not to_add:
            return
        # Increment by each set power of two: MCX carry cascade, high to low.
        for k in range(length):
            if not (to_add >> k) & 1:
                continue
            for i in range(length - 1, k, -1):
                ctrls = tuple(start + b for b in range(k, i))
                self.MCMtrxPerm(ctrls, mat.X2, start + i, (1 << len(ctrls)) - 1)
            self.X(start + k)

    def DEC(self, to_sub: int, start: int, length: int) -> None:
        self.INC((1 << length) - (to_sub & ((1 << length) - 1)), start, length)

    def CINC(self, to_add: int, start: int, length: int, controls: Sequence[int]) -> None:
        controls = tuple(controls)
        if not controls:
            return self.INC(to_add, start, length)
        if not length:
            return
        to_add &= (1 << length) - 1
        cperm = (1 << len(controls)) - 1
        for k in range(length):
            if not (to_add >> k) & 1:
                continue
            for i in range(length - 1, k, -1):
                reg_ctrls = tuple(start + b for b in range(k, i))
                ctrls = reg_ctrls + controls
                perm = ((1 << len(reg_ctrls)) - 1) | (cperm << len(reg_ctrls))
                self.MCMtrxPerm(ctrls, mat.X2, start + i, perm)
            self.MCMtrxPerm(controls, mat.X2, start + k, cperm)

    def CDEC(self, to_sub: int, start: int, length: int, controls: Sequence[int]) -> None:
        self.CINC((1 << length) - (to_sub & ((1 << length) - 1)), start, length, controls)

    def INCDECC(self, to_add: int, start: int, length: int, carry_index: int) -> None:
        """Add over the (length+1)-bit register whose top bit is the carry
        qubit (reference: src/qinterface/arithmetic.cpp:53)."""
        self.CINCDECC(to_add, start, length, carry_index, ())

    def CINCDECC(self, to_add: int, start: int, length: int, carry_index: int,
                 controls: Sequence[int]) -> None:
        """Controlled carry-extended add (building block for the modular
        arithmetic syntheses below)."""
        if not length:
            return
        controls = tuple(controls)
        cperm = (1 << len(controls)) - 1
        to_add &= (1 << (length + 1)) - 1
        ext = length + 1

        def bit_q(i: int) -> int:
            return carry_index if i == length else start + i

        for k in range(ext):
            if not (to_add >> k) & 1:
                continue
            for i in range(ext - 1, k, -1):
                reg = tuple(bit_q(b) for b in range(k, i))
                ctrls = reg + controls
                perm = ((1 << len(reg)) - 1) | (cperm << len(reg))
                self.MCMtrxPerm(ctrls, mat.X2, bit_q(i), perm)
            self.MCMtrxPerm(controls, mat.X2, bit_q(k), cperm)

    def INCC(self, to_add: int, start: int, length: int, carry_index: int) -> None:
        """Carry-in + carry-out add (reference: src/qalu.cpp INCC). The
        +1 from a consumed carry-in is NOT masked to `length` bits — the
        2^length term must reach the carry qubit via INCDECC."""
        if not length:
            return
        if self.M(carry_index):
            self.X(carry_index)
            self.INCDECC(to_add + 1, start, length, carry_index)
        else:
            self.INCDECC(to_add, start, length, carry_index)

    def DECC(self, to_sub: int, start: int, length: int, carry_index: int) -> None:
        has_carry = self.M(carry_index)
        # unmasked: to_sub == 0 gives inv == 2^length, which must flip carry
        inv = (1 << length) - (to_sub & ((1 << length) - 1))
        if has_carry:
            self.X(carry_index)
        else:
            inv -= 1
        self.INCDECC(inv, start, length, carry_index)

    # -- BCD derived ops over the INCBCD/INCDECBCDC primitives
    #    (reference: src/qalu.cpp:155-189 DECBCD/INCBCDC/DECBCDC) --

    def DECBCD(self, to_sub: int, start: int, length: int) -> None:
        max_val = 10 ** (length // 4) if length else 1
        self.INCBCD(max_val - (to_sub % max_val), start, length)

    def INCBCDC(self, to_add: int, start: int, length: int, carry_index: int) -> None:
        if self.M(carry_index):
            self.X(carry_index)
            to_add = to_add + 1
        self.INCDECBCDC(to_add, start, length, carry_index)

    def DECBCDC(self, to_sub: int, start: int, length: int, carry_index: int) -> None:
        if self.M(carry_index):
            self.X(carry_index)
        else:
            to_sub = to_sub + 1
        max_val = 10 ** (length // 4) if length else 1
        self.INCDECBCDC(max_val - (to_sub % max_val), start, length, carry_index)

    # -- signed variants (reference: src/qalu.cpp INCS/INCSC/DECS/DECSC) --

    def _signed_overflow_range(self, to_add: int, length: int) -> Tuple[int, int]:
        s = 1 << (length - 1)
        c = to_add & ((1 << length) - 1)
        if c == 0:
            return (0, 0)
        if c < s:
            return (s - c, s)
        return (s, (1 << length) + s - c)

    def INCS(self, to_add: int, start: int, length: int, overflow_index: int) -> None:
        lo, hi = self._signed_overflow_range(to_add, length)
        self._flip_if_in_range(lo, hi, start, length, overflow_index)
        self.INC(to_add, start, length)

    def DECS(self, to_sub: int, start: int, length: int, overflow_index: int) -> None:
        inv = ((1 << length) - to_sub) & ((1 << length) - 1)
        self.INCS(inv, start, length, overflow_index)

    def INCDECSC(self, to_add: int, start: int, length: int, *flags) -> None:
        """(length+1)-bit add with carry top bit; optional signed-overflow
        flag qubit (reference kernels incdecsc1/incdecsc2,
        src/common/qheader_alu.cl)."""
        if len(flags) == 2:
            overflow_index, carry_index = flags
            lo, hi = self._signed_overflow_range(to_add & ((1 << length) - 1), length)
            self._flip_if_in_range(lo, hi, start, length, overflow_index)
        else:
            (carry_index,) = flags
        self.INCDECC(to_add, start, length, carry_index)

    def INCSC(self, to_add: int, start: int, length: int, *flags) -> None:
        if not length:
            return
        carry_index = flags[-1]
        if self.M(carry_index):
            self.X(carry_index)
            self.INCDECSC(to_add + 1, start, length, *flags)
        else:
            self.INCDECSC(to_add, start, length, *flags)

    def DECSC(self, to_sub: int, start: int, length: int, *flags) -> None:
        carry_index = flags[-1]
        has_carry = self.M(carry_index)
        inv = (1 << length) - (to_sub & ((1 << length) - 1))
        if has_carry:
            self.X(carry_index)
        else:
            inv -= 1
        self.INCDECSC(inv, start, length, *flags)

    # ------------------------------------------------------------------
    # full adders (reference: src/qinterface/arithmetic.cpp:276-420)
    # ------------------------------------------------------------------

    def FullAdd(self, input1: int, input2: int, carry_in_sum_out: int, carry_out: int) -> None:
        self.CFullAdd((), input1, input2, carry_in_sum_out, carry_out)

    def IFullAdd(self, input1: int, input2: int, carry_in_sum_out: int, carry_out: int) -> None:
        self.CIFullAdd((), input1, input2, carry_in_sum_out, carry_out)

    def CFullAdd(self, controls, input1, input2, carry_in_sum_out, carry_out) -> None:
        controls = tuple(controls)
        cp = (1 << len(controls)) - 1

        def mcx(extra, target):
            ctrls = controls + tuple(extra)
            self.MCMtrxPerm(ctrls, mat.X2, target, cp | (((1 << len(extra)) - 1) << len(controls)))

        mcx((input1, input2), carry_out)
        mcx((input1,), input2)
        mcx((input2, carry_in_sum_out), carry_out)
        mcx((input2,), carry_in_sum_out)
        mcx((input1,), input2)

    def CIFullAdd(self, controls, input1, input2, carry_in_sum_out, carry_out) -> None:
        controls = tuple(controls)
        cp = (1 << len(controls)) - 1

        def mcx(extra, target):
            ctrls = controls + tuple(extra)
            self.MCMtrxPerm(ctrls, mat.X2, target, cp | (((1 << len(extra)) - 1) << len(controls)))

        mcx((input1,), input2)
        mcx((input2,), carry_in_sum_out)
        mcx((input2, carry_in_sum_out), carry_out)
        mcx((input1,), input2)
        mcx((input1, input2), carry_out)

    def ADC(self, input1: int, input2: int, output: int, length: int, carry: int) -> None:
        """Ripple add two registers into a zeroed output register with
        carry-in/out (reference: src/qinterface/arithmetic.cpp:330).
        Deviation: the reference's chain leaves sum bits scrambled across
        output/carry; here output holds the plain binary sum and `carry`
        the carry-out (IADC remains the exact inverse)."""
        self.CADC((), input1, input2, output, length, carry)

    def IADC(self, input1: int, input2: int, output: int, length: int, carry: int) -> None:
        self.CIADC((), input1, input2, output, length, carry)

    def CADC(self, controls, input1, input2, output, length, carry) -> None:
        controls = tuple(controls)
        for i in range(length):
            # FullAdd leaves sum in the carry slot and carry-out in
            # output+i; the swap puts them in their proper places.
            self.CFullAdd(controls, input1 + i, input2 + i, carry, output + i)
            if controls:
                self.CSwap(controls, carry, output + i)
            else:
                self.Swap(carry, output + i)

    def CIADC(self, controls, input1, input2, output, length, carry) -> None:
        controls = tuple(controls)
        for i in range(length - 1, -1, -1):
            if controls:
                self.CSwap(controls, carry, output + i)
            else:
                self.Swap(carry, output + i)
            self.CIFullAdd(controls, input1 + i, input2 + i, carry, output + i)

    # ------------------------------------------------------------------
    # modular multiply, out of place.
    # The reference synthesizes these by shift-adding residues into the
    # out register without modular reduction (reference:
    # src/qinterface/arithmetic.cpp:127-275), which wraps at 2^oLength
    # instead of modN for some operand combinations. Here the default is
    # a correct Vedral-style modular adder using one allocated ancilla.
    # Dense engines override with exact index-permutation kernels.
    # ------------------------------------------------------------------

    def _mod_out_length(self, mod_n: int) -> int:
        from ..utils.bits import is_pow2, log2

        return log2(mod_n) if is_pow2(mod_n) else (log2(mod_n) + 1)

    def _c_add_mod_n(self, a: int, mod_n: int, start: int, length: int,
                     controls: Sequence[int]) -> None:
        """Controlled (reg := reg + a mod mod_n), valid for reg < mod_n.

        One-ancilla comparator construction: extended add, subtract N,
        conditionally restore, then uncompute the borrow flag."""
        from ..utils.bits import is_pow2

        controls = tuple(controls)
        a %= mod_n
        if a == 0:
            return
        if is_pow2(mod_n):
            self.CINC(a, start, length, controls)
            return
        cperm = (1 << len(controls)) - 1
        anc = self.Allocate(self.qubit_count, 1)
        ext_mod = 1 << (length + 1)
        # reg+anc := x + a
        self.CINCDECC(a, start, length, anc, controls)
        # reg+anc := x + a - N  (anc becomes 1 iff x + a < N)
        self.CINCDECC(ext_mod - mod_n, start, length, anc, controls)
        # if anc: reg += N (low bits only) -> reg = (x + a) mod N
        self.CINC(mod_n, start, length, controls + (anc,))
        # uncompute anc: borrow of (reg - a) tells whether reduction happened
        self.CINCDECC(ext_mod - a, start, length, anc, controls)
        self.MCMtrxPerm(controls, mat.X2, anc, cperm)
        self.CINC(a, start, length, controls)
        self.Dispose(anc, 1, 0)

    def _c_sub_mod_n(self, a: int, mod_n: int, start: int, length: int,
                     controls: Sequence[int]) -> None:
        self._c_add_mod_n(mod_n - (a % mod_n), mod_n, start, length, controls)

    def MULModNOut(self, to_mul: int, mod_n: int, in_start: int, out_start: int, length: int) -> None:
        self.CMULModNOut(to_mul, mod_n, in_start, out_start, length, ())

    def IMULModNOut(self, to_mul: int, mod_n: int, in_start: int, out_start: int, length: int) -> None:
        self.CIMULModNOut(to_mul, mod_n, in_start, out_start, length, ())

    def CMULModNOut(self, to_mul, mod_n, in_start, out_start, length, controls) -> None:
        controls = tuple(controls)
        o_length = self._mod_out_length(mod_n)
        for i in range(length):
            part = (to_mul << i) % mod_n
            if part:
                self._c_add_mod_n(part, mod_n, out_start, o_length, controls + (in_start + i,))

    def CIMULModNOut(self, to_mul, mod_n, in_start, out_start, length, controls) -> None:
        controls = tuple(controls)
        o_length = self._mod_out_length(mod_n)
        for i in range(length - 1, -1, -1):
            part = (to_mul << i) % mod_n
            if part:
                self._c_sub_mod_n(part, mod_n, out_start, o_length, controls + (in_start + i,))

    # ------------------------------------------------------------------
    # engine-level ops (no universal synthesis; dense engines implement
    # via index-permutation kernels, layers forward)
    # ------------------------------------------------------------------

    def MUL(self, to_mul: int, in_out_start: int, carry_start: int, length: int) -> None:
        raise NotImplementedError

    def DIV(self, to_div: int, in_out_start: int, carry_start: int, length: int) -> None:
        raise NotImplementedError

    def CMUL(self, to_mul, in_out_start, carry_start, length, controls) -> None:
        raise NotImplementedError

    def CDIV(self, to_div, in_out_start, carry_start, length, controls) -> None:
        raise NotImplementedError

    def POWModNOut(self, base: int, mod_n: int, in_start: int, out_start: int, length: int) -> None:
        raise NotImplementedError

    def CPOWModNOut(self, base, mod_n, in_start, out_start, length, controls) -> None:
        raise NotImplementedError

    def IndexedLDA(self, index_start, index_length, value_start, value_length, values,
                   reset_value: bool = True) -> int:
        raise NotImplementedError

    def IndexedADC(self, index_start, index_length, value_start, value_length, carry_index, values) -> int:
        raise NotImplementedError

    def IndexedSBC(self, index_start, index_length, value_start, value_length, carry_index, values) -> int:
        raise NotImplementedError

    def Hash(self, start: int, length: int, values) -> None:
        raise NotImplementedError

    # ------------------------------------------------------------------
    # comparator phase flips (reference: c/phaseflipifless kernels,
    # src/common/qheader_alu.cl:780-810) — universal synthesis here
    # ------------------------------------------------------------------

    def PhaseFlipIfLess(self, greater_perm: int, start: int, length: int) -> None:
        self._phase_flip_if_in_range(0, greater_perm, start, length)

    def CPhaseFlipIfLess(self, greater_perm: int, start: int, length: int, flag_index: int) -> None:
        self._phase_flip_if_in_range(0, greater_perm, start, length,
                                     extra_controls=(flag_index,), extra_perm=1)

    def PhaseFlip(self) -> None:
        """Global -1 phase (reference: include/qinterface.hpp PhaseFlip)."""
        self._phase_flip_if_in_range(0, 2, 0, 1)
