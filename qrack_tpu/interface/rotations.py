"""Rotation gates: radian + dyadic-fraction + Pauli-exponentiation forms.

Conventions match the reference exactly (reference:
src/qinterface/rotational.cpp:170-290; dyadAngle
src/qinterface/qinterface.cpp:1310 = -2*pi*numerator / 2^denomPower;
note the reference's CRX/CRT sign quirks are reproduced deliberately).
"""

from __future__ import annotations

import cmath
import math

import numpy as np

from .. import matrices as mat


def _dyad_angle(numerator: int, denom_power: int) -> float:
    return (-math.pi * numerator * 2) / (1 << denom_power)


class RotationsMixin:
    # ---------------- radian rotations ----------------

    def RT(self, radians: float, q: int) -> None:
        """Phase shift: e^{i*radians/2} on |1> (reference: rotational.cpp:173)."""
        self.Phase(1.0, cmath.exp(0.5j * radians), q)

    def RX(self, radians: float, q: int) -> None:
        c, s = math.cos(radians / 2), math.sin(radians / 2)
        self.Mtrx(np.array([[c, -1j * s], [-1j * s, c]], dtype=np.complex128), q)

    def RY(self, radians: float, q: int) -> None:
        c, s = math.cos(radians / 2), math.sin(radians / 2)
        self.Mtrx(np.array([[c, -s], [s, c]], dtype=np.complex128), q)

    def RZ(self, radians: float, q: int) -> None:
        c, s = math.cos(radians / 2), math.sin(radians / 2)
        self.Phase(complex(c, -s), complex(c, s), q)

    def CRT(self, radians: float, control: int, target: int) -> None:
        self.MCPhase((control,), 1.0, cmath.exp(0.5j * radians), target)

    def CRX(self, radians: float, control: int, target: int) -> None:
        # Sign matches the reference's controlled-X rotation (+i*sin),
        # reference: rotational.cpp:281-287.
        c, s = math.cos(radians / 2), math.sin(radians / 2)
        self.MCMtrx((control,), np.array([[c, 1j * s], [1j * s, c]], dtype=np.complex128), target)

    def CRY(self, radians: float, control: int, target: int) -> None:
        c, s = math.cos(radians / 2), math.sin(radians / 2)
        self.MCMtrx((control,), np.array([[c, -s], [s, c]], dtype=np.complex128), target)

    def CRZ(self, radians: float, control: int, target: int) -> None:
        c, s = math.cos(radians / 2), math.sin(radians / 2)
        self.MCPhase((control,), complex(c, -s), complex(c, s), target)

    # ---------------- dyadic-fraction rotations ----------------
    # (reference: src/qinterface/qinterface.cpp:1310-1380; angle sign is
    #  reversed and not divided by two, per include/qinterface.hpp:1505)

    def RTDyad(self, numerator: int, denom_power: int, q: int) -> None:
        self.RT(_dyad_angle(numerator, denom_power), q)

    def RXDyad(self, numerator: int, denom_power: int, q: int) -> None:
        self.RX(_dyad_angle(numerator, denom_power), q)

    def RYDyad(self, numerator: int, denom_power: int, q: int) -> None:
        self.RY(_dyad_angle(numerator, denom_power), q)

    def RZDyad(self, numerator: int, denom_power: int, q: int) -> None:
        self.RZ(_dyad_angle(numerator, denom_power), q)

    def CRTDyad(self, numerator: int, denom_power: int, control: int, target: int) -> None:
        self.CRT(_dyad_angle(numerator, denom_power), control, target)

    def CRXDyad(self, numerator: int, denom_power: int, control: int, target: int) -> None:
        self.CRX(_dyad_angle(numerator, denom_power), control, target)

    def CRYDyad(self, numerator: int, denom_power: int, control: int, target: int) -> None:
        self.CRY(_dyad_angle(numerator, denom_power), control, target)

    def CRZDyad(self, numerator: int, denom_power: int, control: int, target: int) -> None:
        self.CRZ(_dyad_angle(numerator, denom_power), control, target)

    # ---------------- Pauli exponentiation ----------------
    # (reference: rotational.cpp:227-270 — note e^{i*radians*P}, no /2)

    def Exp(self, radians: float, q: int) -> None:
        ph = cmath.exp(1j * radians)
        self.Phase(ph, ph, q)

    def ExpX(self, radians: float, q: int) -> None:
        ph = cmath.exp(1j * radians)
        self.Invert(ph, ph, q)

    def ExpY(self, radians: float, q: int) -> None:
        ph = cmath.exp(1j * radians)
        self.Invert(ph * -1j, ph * 1j, q)

    def ExpZ(self, radians: float, q: int) -> None:
        ph = cmath.exp(1j * radians)
        self.Phase(ph, -ph, q)

    def ExpMtrx(self, controls, q: int, mtrx: np.ndarray, anti_ctrled: bool = False) -> None:
        """exp(i * mtrx) under controls (reference: Exp(controls,...)
        rotational.cpp:234)."""
        m = mat.exp_mtrx(1j * np.asarray(mtrx, dtype=np.complex128))
        if anti_ctrled:
            self.MACMtrx(tuple(controls), m, q)
        else:
            self.MCMtrx(tuple(controls), m, q)

    def ExpDyad(self, numerator: int, denom_power: int, q: int) -> None:
        self.Exp(_dyad_angle(numerator, denom_power), q)

    def ExpXDyad(self, numerator: int, denom_power: int, q: int) -> None:
        self.ExpX(_dyad_angle(numerator, denom_power), q)

    def ExpYDyad(self, numerator: int, denom_power: int, q: int) -> None:
        self.ExpY(_dyad_angle(numerator, denom_power), q)

    def ExpZDyad(self, numerator: int, denom_power: int, q: int) -> None:
        self.ExpZ(_dyad_angle(numerator, denom_power), q)
