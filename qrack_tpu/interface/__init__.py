"""Assembles the full QInterface from its mixin layers.

Reference parity: include/qinterface.hpp:141 (QInterface),
include/qparity.hpp (QParity), include/qalu.hpp (QAlu) — here a single
Python class built from cooperative mixins over one primitive contract.
"""

from .base import QInterfaceBase
from .gates import GatesMixin
from .rotations import RotationsMixin
from .registers import RegistersMixin
from .alu import AluMixin
from .parity import ParityMixin


class QInterface(GatesMixin, RotationsMixin, RegistersMixin, AluMixin, ParityMixin, QInterfaceBase):
    """The universal gate-level simulator API (see module docstrings)."""

    def TimeEvolve(self, hamiltonian, time_diff: float) -> None:
        """First-order trotterized e^{-i H t}: apply e^{-i H_k t} per term
        (reference: src/qinterface/gates.cpp:426). Unlike the reference's
        uniform-op branch (which omits the i factor), uniform payloads here
        are exponentiated as unitaries too."""
        import numpy as np

        from .. import matrices as mat

        if abs(time_diff) <= 1e-12:
            return
        for op in hamiltonian:
            if op.toggles:
                for j, c in enumerate(op.controls):
                    if op.toggles[j]:
                        self.X(c)
            if op.uniform:
                payloads = [mat.exp_mtrx(-1j * time_diff * m) for m in op.matrix]
                self.UCMtrx(tuple(op.controls), payloads, op.target)
            else:
                u = mat.exp_mtrx(-1j * time_diff * op.matrix)
                if not op.controls:
                    self.Mtrx(u, op.target)
                elif op.anti:
                    self.MACMtrx(tuple(op.controls), u, op.target)
                else:
                    self.MCMtrx(tuple(op.controls), u, op.target)
            if op.toggles:
                for j, c in enumerate(op.controls):
                    if op.toggles[j]:
                        self.X(c)


__all__ = ["QInterface", "QInterfaceBase"]
