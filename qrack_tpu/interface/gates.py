"""Named single/multi-qubit gates and the swap family.

Default synthesis mirroring the reference (reference:
include/qinterface.hpp:931-1422 named gates; :2399-2415 swap family;
src/qinterface/gates.cpp:166-247 Swap/ISwap/SqrtSwap; src/qinterface/logic.cpp
AND/OR/XOR). Everything reduces to the MCMtrxPerm primitive, so every
layer/engine inherits the full set.
"""

from __future__ import annotations

import cmath
import math

import numpy as np

from .. import matrices as mat


class GatesMixin:
    # ---------------- single-qubit named gates ----------------

    def H(self, q: int) -> None:
        self.Mtrx(mat.H2, q)

    def X(self, q: int) -> None:
        self.Invert(1.0, 1.0, q)

    def Y(self, q: int) -> None:
        self.Invert(-1j, 1j, q)

    def Z(self, q: int) -> None:
        self.Phase(1.0, -1.0, q)

    def S(self, q: int) -> None:
        self.Phase(1.0, 1j, q)

    def IS(self, q: int) -> None:
        self.Phase(1.0, -1j, q)

    def T(self, q: int) -> None:
        self.Phase(1.0, cmath.exp(0.25j * math.pi), q)

    def IT(self, q: int) -> None:
        self.Phase(1.0, cmath.exp(-0.25j * math.pi), q)

    def SqrtX(self, q: int) -> None:
        self.Mtrx(mat.SQRTX2, q)

    def ISqrtX(self, q: int) -> None:
        self.Mtrx(mat.ISQRTX2, q)

    def SqrtY(self, q: int) -> None:
        self.Mtrx(mat.SQRTY2, q)

    def ISqrtY(self, q: int) -> None:
        self.Mtrx(mat.ISQRTY2, q)

    def SqrtW(self, q: int) -> None:
        """sqrt((X+Y)/sqrt(2)) — Sycamore gate set member
        (reference: SqrtW usage in test_quantum_supremacy,
        test/benchmarks.cpp:3635)."""
        self.Mtrx(mat.SQRTW2, q)

    def ISqrtW(self, q: int) -> None:
        self.Mtrx(np.conj(mat.SQRTW2.T), q)

    def SH(self, q: int) -> None:
        """H then S (reference: include/qinterface.hpp:975)."""
        self.H(q)
        self.S(q)

    def HIS(self, q: int) -> None:
        """IS then H (inverse of SH)."""
        self.IS(q)
        self.H(q)

    def PhaseRootN(self, n: int, q: int) -> None:
        """Z^(1/2^(n-1)) — n=1 is Z, n=2 is S, n=3 is T
        (reference: include/qinterface.hpp:1392)."""
        if n == 0:
            return
        self.Phase(1.0, cmath.exp(1j * math.pi / (1 << (n - 1))), q)

    def IPhaseRootN(self, n: int, q: int) -> None:
        if n == 0:
            return
        self.Phase(1.0, cmath.exp(-1j * math.pi / (1 << (n - 1))), q)

    def U(self, q: int, theta: float, phi: float, lambd: float) -> None:
        """General 3-parameter unitary (reference: src/qinterface/rotational.cpp:18)."""
        self.Mtrx(mat.u3_mtrx(theta, phi, lambd), q)

    def U2(self, q: int, phi: float, lambd: float) -> None:
        self.U(q, math.pi / 2, phi, lambd)

    def IU2(self, q: int, phi: float, lambd: float) -> None:
        """Inverse of U2 (reference: include/qinterface.hpp:856)."""
        self.U(q, math.pi / 2, -lambd - math.pi, -phi + math.pi)

    def AI(self, q: int, azimuth: float, inclination: float) -> None:
        """Bloch azimuth/inclination gate (reference:
        src/qinterface/rotational.cpp:55)."""
        self.Mtrx(mat.ai_mtrx(azimuth, inclination), q)

    def IAI(self, q: int, azimuth: float, inclination: float) -> None:
        self.Mtrx(np.conj(mat.ai_mtrx(azimuth, inclination).T), q)

    # ---------------- controlled named gates ----------------

    def CNOT(self, control: int, target: int) -> None:
        self.MCInvert((control,), 1.0, 1.0, target)

    CX = CNOT

    def AntiCNOT(self, control: int, target: int) -> None:
        self.MACInvert((control,), 1.0, 1.0, target)

    def CY(self, control: int, target: int) -> None:
        self.MCInvert((control,), -1j, 1j, target)

    def AntiCY(self, control: int, target: int) -> None:
        self.MACInvert((control,), -1j, 1j, target)

    def CZ(self, control: int, target: int) -> None:
        self.MCPhase((control,), 1.0, -1.0, target)

    def AntiCZ(self, control: int, target: int) -> None:
        self.MACPhase((control,), 1.0, -1.0, target)

    def CH(self, control: int, target: int) -> None:
        self.MCMtrx((control,), mat.H2, target)

    def AntiCH(self, control: int, target: int) -> None:
        self.MACMtrx((control,), mat.H2, target)

    def CS(self, control: int, target: int) -> None:
        self.MCPhase((control,), 1.0, 1j, target)

    def CIS(self, control: int, target: int) -> None:
        self.MCPhase((control,), 1.0, -1j, target)

    def CT(self, control: int, target: int) -> None:
        self.MCPhase((control,), 1.0, cmath.exp(0.25j * math.pi), target)

    def CIT(self, control: int, target: int) -> None:
        self.MCPhase((control,), 1.0, cmath.exp(-0.25j * math.pi), target)

    def CCNOT(self, c1: int, c2: int, target: int) -> None:
        self.MCInvert((c1, c2), 1.0, 1.0, target)

    Toffoli = CCNOT

    def AntiCCNOT(self, c1: int, c2: int, target: int) -> None:
        self.MACInvert((c1, c2), 1.0, 1.0, target)

    def CCY(self, c1: int, c2: int, target: int) -> None:
        self.MCInvert((c1, c2), -1j, 1j, target)

    def AntiCCY(self, c1: int, c2: int, target: int) -> None:
        self.MACInvert((c1, c2), -1j, 1j, target)

    def CCZ(self, c1: int, c2: int, target: int) -> None:
        self.MCPhase((c1, c2), 1.0, -1.0, target)

    def AntiCCZ(self, c1: int, c2: int, target: int) -> None:
        self.MACPhase((c1, c2), 1.0, -1.0, target)

    def CU(self, controls, target: int, theta: float, phi: float, lambd: float) -> None:
        self.MCMtrx(tuple(controls), mat.u3_mtrx(theta, phi, lambd), target)

    def AntiCU(self, controls, target: int, theta: float, phi: float, lambd: float) -> None:
        self.MACMtrx(tuple(controls), mat.u3_mtrx(theta, phi, lambd), target)

    def CAI(self, control: int, target: int, azimuth: float, inclination: float) -> None:
        self.MCMtrx((control,), mat.ai_mtrx(azimuth, inclination), target)

    def CIAI(self, control: int, target: int, azimuth: float, inclination: float) -> None:
        self.MCMtrx((control,), np.conj(mat.ai_mtrx(azimuth, inclination).T), target)

    def AntiCAI(self, control: int, target: int, azimuth: float, inclination: float) -> None:
        self.MACMtrx((control,), mat.ai_mtrx(azimuth, inclination), target)

    def AntiCIAI(self, control: int, target: int, azimuth: float, inclination: float) -> None:
        self.MACMtrx((control,), np.conj(mat.ai_mtrx(azimuth, inclination).T), target)

    # ---------------- uniformly controlled rotations ----------------
    # (reference: UniformlyControlledSingleBit / UniformlyControlledRY/RZ,
    #  include/qinterface.hpp; kernel uniformlycontrolled qengine.cl:409)

    def UniformlyControlledSingleBit(self, controls, target: int, mtrxs) -> None:
        self.UCMtrx(tuple(controls), mtrxs, target)

    def UniformlyControlledRY(self, controls, target: int, angles) -> None:
        ms = []
        for a in angles:
            c, s = math.cos(a / 2), math.sin(a / 2)
            ms.append(np.array([[c, -s], [s, c]], dtype=np.complex128))
        self.UCMtrx(tuple(controls), ms, target)

    def UniformlyControlledRZ(self, controls, target: int, angles) -> None:
        ms = []
        for a in angles:
            ms.append(np.array([[cmath.exp(-0.5j * a), 0], [0, cmath.exp(0.5j * a)]],
                               dtype=np.complex128))
        self.UCMtrx(tuple(controls), ms, target)

    # ---------------- multi-target X/Z/phase masks ----------------

    def XMask(self, mask: int) -> None:
        """X on every set bit of mask (reference: include/qinterface.hpp:1196;
        engines override with one fused kernel, xmask src/common/qengine.cl:266)."""
        q = 0
        while mask:
            if mask & 1:
                self.X(q)
            mask >>= 1
            q += 1

    def YMask(self, mask: int) -> None:
        q = 0
        while mask:
            if mask & 1:
                self.Y(q)
            mask >>= 1
            q += 1

    def ZMask(self, mask: int) -> None:
        q = 0
        while mask:
            if mask & 1:
                self.Z(q)
            mask >>= 1
            q += 1

    def PhaseParity(self, radians: float, mask: int) -> None:
        """exp(i*radians/2*parity(mask bits)) phase
        (reference: src/qinterface/gates.cpp:399; kernel phaseparity
        src/common/qengine.cl:306). Default synthesis: CNOT ladder + RZ."""
        bits = [i for i in range(self.qubit_count) if (mask >> i) & 1]
        if not bits:
            return
        for i in range(len(bits) - 1):
            self.CNOT(bits[i], bits[i + 1])
        self.RZ(radians, bits[-1])
        for i in reversed(range(len(bits) - 1)):
            self.CNOT(bits[i], bits[i + 1])

    # ---------------- swap family ----------------
    # (reference: src/qinterface/gates.cpp:166-247; include/qinterface.hpp:2399)

    def Swap(self, q1: int, q2: int) -> None:
        if q1 == q2:
            return
        self.CNOT(q1, q2)
        self.CNOT(q2, q1)
        self.CNOT(q1, q2)

    def ISwap(self, q1: int, q2: int) -> None:
        """Swap + i phase on |01>,|10> (reference: gates.cpp:189)."""
        if q1 == q2:
            return
        self.Swap(q1, q2)
        self.CZ(q1, q2)
        self.S(q1)
        self.S(q2)

    def IISwap(self, q1: int, q2: int) -> None:
        if q1 == q2:
            return
        self.IS(q2)
        self.IS(q1)
        self.CZ(q1, q2)
        self.Swap(q1, q2)

    def SqrtSwap(self, q1: int, q2: int) -> None:
        """Half-way swap (reference: gates.cpp:205)."""
        if q1 == q2:
            return
        self.Apply4x4(_SQRT_SWAP4, q1, q2)

    def ISqrtSwap(self, q1: int, q2: int) -> None:
        if q1 == q2:
            return
        self.Apply4x4(_ISQRT_SWAP4, q1, q2)

    def CSwap(self, controls, q1: int, q2: int) -> None:
        """Controlled swap (reference: CSwap include/qinterface.hpp:2408);
        synthesized as CNOT + CCNOT + CNOT."""
        controls = tuple(controls)
        self.CNOT(q2, q1)
        self.MCInvert(controls + (q1,), 1.0, 1.0, q2)
        self.CNOT(q2, q1)

    def AntiCSwap(self, controls, q1: int, q2: int) -> None:
        controls = tuple(controls)
        for c in controls:
            self.X(c)
        self.CSwap(controls, q1, q2)
        for c in controls:
            self.X(c)

    def CSqrtSwap(self, controls, q1: int, q2: int) -> None:
        self._controlled_two_qubit(controls, q1, q2, _SQRT_SWAP4, anti=False)

    def AntiCSqrtSwap(self, controls, q1: int, q2: int) -> None:
        self._controlled_two_qubit(controls, q1, q2, _SQRT_SWAP4, anti=True)

    def CISqrtSwap(self, controls, q1: int, q2: int) -> None:
        self._controlled_two_qubit(controls, q1, q2, _ISQRT_SWAP4, anti=False)

    def AntiCISqrtSwap(self, controls, q1: int, q2: int) -> None:
        self._controlled_two_qubit(controls, q1, q2, _ISQRT_SWAP4, anti=True)

    def FSim(self, theta: float, phi: float, q1: int, q2: int) -> None:
        """Fermionic simulation gate (reference: FSim
        include/qinterface.hpp:2415; gates.cpp synthesis)."""
        cos = math.cos(theta)
        sin = math.sin(theta)
        m = np.array(
            [
                [1, 0, 0, 0],
                [0, cos, -1j * sin, 0],
                [0, -1j * sin, cos, 0],
                [0, 0, 0, cmath.exp(-1j * phi)],
            ],
            dtype=np.complex128,
        )
        self.Apply4x4(m, q1, q2)

    # ---------------- two-qubit 4x4 fallback ----------------

    def Apply4x4(self, m: np.ndarray, q1: int, q2: int) -> None:
        """Apply an arbitrary 4x4 unitary on (q2:high, q1:low) via the
        cosine-sine-free generic decomposition: two-level rotations through
        the MCMtrxPerm primitive. Engines override with a native tensor op."""
        # Decompose into controlled 2x2 operations using Gray-code two-level
        # synthesis on the 4-dim space spanned by the two qubits.
        from .synth import apply_small_unitary_via_primitive

        apply_small_unitary_via_primitive(self, m, (q1, q2))

    # ---------------- classical logic (reference: src/qinterface/logic.cpp) ----

    def AND(self, a: int, b: int, out: int) -> None:
        self.CCNOT(a, b, out)

    def OR(self, a: int, b: int, out: int) -> None:
        self.X(out)
        self.AntiCCNOT(a, b, out)

    def XOR(self, a: int, b: int, out: int) -> None:
        if a == out:
            self.CNOT(b, out)
            return
        if b == out:
            self.CNOT(a, out)
            return
        self.CNOT(a, out)
        self.CNOT(b, out)

    def NAND(self, a: int, b: int, out: int) -> None:
        self.AND(a, b, out)
        self.X(out)

    def NOR(self, a: int, b: int, out: int) -> None:
        self.OR(a, b, out)
        self.X(out)

    def XNOR(self, a: int, b: int, out: int) -> None:
        self.XOR(a, b, out)
        self.X(out)

    def CLAND(self, classical: bool, q: int, out: int) -> None:
        if classical:
            self.CNOT(q, out)

    def CLOR(self, classical: bool, q: int, out: int) -> None:
        if classical:
            self.X(out)
        else:
            self.CNOT(q, out)

    def CLXOR(self, classical: bool, q: int, out: int) -> None:
        if q != out:
            self.CNOT(q, out)
        if classical:
            self.X(out)

    def CLNAND(self, classical: bool, q: int, out: int) -> None:
        self.CLAND(classical, q, out)
        self.X(out)

    def CLNOR(self, classical: bool, q: int, out: int) -> None:
        self.CLOR(classical, q, out)
        self.X(out)

    def CLXNOR(self, classical: bool, q: int, out: int) -> None:
        self.CLXOR(classical, q, out)
        self.X(out)

    def _controlled_two_qubit(self, controls, q1, q2, m4, anti: bool) -> None:
        from .synth import apply_small_unitary_via_primitive

        controls = tuple(controls)
        perm = 0 if anti else (1 << len(controls)) - 1
        apply_small_unitary_via_primitive(self, m4, (q1, q2), controls=controls, perm=perm)


_SQRT_SWAP4 = np.array(
    [
        [1, 0, 0, 0],
        [0, 0.5 + 0.5j, 0.5 - 0.5j, 0],
        [0, 0.5 - 0.5j, 0.5 + 0.5j, 0],
        [0, 0, 0, 1],
    ],
    dtype=np.complex128,
)
_ISQRT_SWAP4 = np.conj(_SQRT_SWAP4.T)
