"""Hamiltonian term containers for TimeEvolve trotterization.

Reference: include/hamiltonian.hpp:29-99 — HamiltonianOp (controlled 2x2
generator term, optional anti-control and per-control toggles) and
UniformHamiltonianOp (one 2x2 payload per control permutation). A
Hamiltonian is a plain list of these ops.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import numpy as np


@dataclass
class HamiltonianOp:
    target: int
    matrix: np.ndarray  # 2x2 generator term (or [2^k, 2, 2] when uniform)
    controls: Sequence[int] = ()
    anti: bool = False
    uniform: bool = False
    toggles: Optional[Sequence[bool]] = None

    def __post_init__(self):
        self.matrix = np.asarray(self.matrix, dtype=np.complex128)


def uniform_hamiltonian_op(controls: Sequence[int], target: int, matrices: np.ndarray) -> HamiltonianOp:
    """One generator payload per control permutation (reference:
    UniformHamiltonianOp include/hamiltonian.hpp:69)."""
    m = np.asarray(matrices, dtype=np.complex128).reshape(-1, 2, 2)
    return HamiltonianOp(target=target, matrix=m, controls=tuple(controls), uniform=True)


Hamiltonian = List[HamiltonianOp]
