"""Global numeric/config policy for qrack-tpu.

TPU-native analogue of the reference's build-time numeric knobs
(reference: include/common/qrack_types.hpp:40-138 — FPPOW float width,
QBCAPPOW index width) and its run-time `QRACK_*` environment controls
(reference: README.md:62-118, src/common/oclengine.cpp:362-388).

Differences by design:
  * Index math ("bitCapInt") is a plain Python int — arbitrary precision,
    so >64-qubit indexing needs no big_integer.hpp equivalent on the host.
    Device-side indices are int32/int64 lanes, valid for any dense shard
    that fits in HBM (a shard never exceeds 2^40 amplitudes in practice).
  * Float width is a runtime policy (fp16/bf16/fp32/fp64), not a compile
    flag; complex arithmetic on TPU is performed by XLA as pairs of real
    ops, so bf16 mode stores split real/imag planes.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

import numpy as np

# ---------------------------------------------------------------------------
# Float-width policy (reference FPPOW analogue)
# ---------------------------------------------------------------------------

_REAL_DTYPES = {
    "float16": np.float16,
    "bfloat16": None,  # resolved lazily via ml_dtypes/jnp to avoid jax import here
    "float32": np.float32,
    "float64": np.float64,
}

_COMPLEX_FOR_REAL = {
    "float32": np.complex64,
    "float64": np.complex128,
    # fp16/bf16 have no numpy complex; engines store split planes and
    # up-cast to complex64 at the host boundary.
    "float16": np.complex64,
    "bfloat16": np.complex64,
}


@dataclass
class QrackConfig:
    """Runtime configuration, seeded from QRACK_TPU_* environment variables.

    Mirrors the reference env-var tier (SURVEY.md §5 "Config / flag system").
    """

    # FPPOW analogue: default fp32 amplitudes (complex64).
    real_dtype_name: str = field(
        default_factory=lambda: (
            os.environ.get("QRACK_TPU_FPPOW", "").strip() or "float32")
    )
    # Qubit-count threshold below which QHybrid prefers the CPU engine
    # (reference: QHybrid gpuThresholdQubits, include/qhybrid.hpp:74).
    hybrid_tpu_threshold_qubits: int = field(
        default_factory=lambda: int(os.environ.get("QRACK_TPU_THRESHOLD_QB", "13"))
    )
    # Largest qubit count a single dense page/engine may hold
    # (reference: QRACK_MAX_PAGE_QB, src/qpager.cpp:170-222).
    max_page_qubits: int = field(
        default_factory=lambda: int(os.environ.get("QRACK_MAX_PAGE_QB", "30"))
    )
    # Largest coherent dense width before paging must engage
    # (reference: QRACK_MAX_PAGING_QB).
    max_paging_qubits: int = field(
        default_factory=lambda: int(os.environ.get("QRACK_MAX_PAGING_QB", "36"))
    )
    # Largest width the CPU engine will allocate
    # (reference: QRACK_MAX_CPU_QB).
    max_cpu_qubits: int = field(
        default_factory=lambda: int(os.environ.get("QRACK_MAX_CPU_QB", "28"))
    )
    # HBM allocation guard, MB (reference: QRACK_MAX_ALLOC_MB,
    # src/common/oclengine.cpp:388).
    max_alloc_mb: int = field(
        default_factory=lambda: int(os.environ.get("QRACK_MAX_ALLOC_MB", "0"))
    )
    # QUnit separability rounding threshold (reference:
    # QRACK_QUNIT_SEPARABILITY_THRESHOLD, README.md:108).
    separability_threshold: float = field(
        default_factory=lambda: float(
            os.environ.get("QRACK_QUNIT_SEPARABILITY_THRESHOLD", "0.0")
        )
    )
    # Near-Clifford RZ rounding (reference:
    # QRACK_NONCLIFFORD_ROUNDING_THRESHOLD, README.md:112).
    nonclifford_rounding_threshold: float = field(
        default_factory=lambda: float(
            os.environ.get("QRACK_NONCLIFFORD_ROUNDING_THRESHOLD", "0.0")
        )
    )
    # Depolarizing noise applied by QInterfaceNoisy when set (reference:
    # QRACK_GATE_DEPOLARIZATION, include/qinterface_noisy.hpp:~35).
    gate_depolarization: float = field(
        default_factory=lambda: float(os.environ.get("QRACK_GATE_DEPOLARIZATION", "0.0"))
    )
    # Disable the QUnit fidelity guard (reference:
    # QRACK_DISABLE_QUNIT_FIDELITY_GUARD, include/qunit.hpp:109).
    disable_fidelity_guard: bool = field(
        default_factory=lambda: bool(
            int(os.environ.get("QRACK_DISABLE_QUNIT_FIDELITY_GUARD", "0"))
        )
    )
    # Comma-separated device list for the pager (reference:
    # QRACK_QPAGER_DEVICES, src/qpager.cpp:170).
    pager_devices: str = field(
        default_factory=lambda: os.environ.get("QRACK_QPAGER_DEVICES", "")
    )

    @property
    def real_dtype(self):
        name = self.real_dtype_name
        if name == "bfloat16":
            import ml_dtypes  # ships with jax

            return np.dtype(ml_dtypes.bfloat16)
        return np.dtype(_REAL_DTYPES[name])

    @property
    def complex_dtype(self):
        return np.dtype(_COMPLEX_FOR_REAL[self.real_dtype_name])

    def device_real_dtype(self):
        """jnp plane dtype honoring the FPPOW policy on the DEVICE path
        (engines/tpu.py, parallel/pager.py default to this).  float64
        turns on jax x64 — without it jnp silently downgrades f64
        arrays to f32, exactly the trap VERDICT r4 flagged."""
        import jax
        import jax.numpy as jnp

        name = self.real_dtype_name
        if name == "float64":
            if not jax.config.jax_enable_x64:
                jax.config.update("jax_enable_x64", True)
            return jnp.dtype(jnp.float64)
        return jnp.dtype({"float32": jnp.float32,
                          "bfloat16": jnp.bfloat16,
                          "float16": jnp.float16}[name])


_config = QrackConfig()


def get_config() -> QrackConfig:
    return _config


def set_config(**kwargs) -> QrackConfig:
    global _config
    for k, v in kwargs.items():
        if not hasattr(_config, k):
            raise AttributeError(f"unknown config field {k!r}")
        setattr(_config, k, v)
    return _config


# ---------------------------------------------------------------------------
# Numeric tolerances (reference: include/common/qrack_types.hpp:250-267)
# ---------------------------------------------------------------------------

# Amplitude treated as zero (reference REAL1_EPSILON-class clamps).
FP_NORM_EPSILON = 1.1920929e-07  # fp32 machine eps
# Probability clamp used by separation decisions
# (reference TRYDECOMPOSE_EPSILON, include/common/qrack_types.hpp:265).
TRYDECOMPOSE_EPSILON = 2.0 * FP_NORM_EPSILON ** 0.5
# Minimum log-fidelity before QUnit's ACE guard trips
# (reference FIDELITY_MIN via CheckFidelity, include/qunit.hpp:107-118).
FIDELITY_MIN = -23.025850929940457  # ln(1e-10)

PI = float(np.pi)
