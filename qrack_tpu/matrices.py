"""Canonical 2x2 gate matrices and small matrix utilities.

Replaces the reference's inline constant tables and the 2x2
exp/log/sqrt helpers (reference: src/common/functions.cpp:1-328).
All host-side matrices are complex128 for accuracy; engines down-cast
to their storage dtype at dispatch time.
"""

from __future__ import annotations

import cmath
import math

import numpy as np

SQRT1_2 = 1.0 / math.sqrt(2.0)

I2 = np.eye(2, dtype=np.complex128)
X2 = np.array([[0, 1], [1, 0]], dtype=np.complex128)
Y2 = np.array([[0, -1j], [1j, 0]], dtype=np.complex128)
Z2 = np.array([[1, 0], [0, -1]], dtype=np.complex128)
H2 = np.array([[SQRT1_2, SQRT1_2], [SQRT1_2, -SQRT1_2]], dtype=np.complex128)
S2 = np.array([[1, 0], [0, 1j]], dtype=np.complex128)
IS2 = np.array([[1, 0], [0, -1j]], dtype=np.complex128)
T2 = np.array([[1, 0], [0, cmath.exp(0.25j * math.pi)]], dtype=np.complex128)
IT2 = np.array([[1, 0], [0, cmath.exp(-0.25j * math.pi)]], dtype=np.complex128)
# sqrt(X) and its inverse (reference: SqrtX include/qinterface.hpp:1010)
SQRTX2 = 0.5 * np.array([[1 + 1j, 1 - 1j], [1 - 1j, 1 + 1j]], dtype=np.complex128)
ISQRTX2 = 0.5 * np.array([[1 - 1j, 1 + 1j], [1 + 1j, 1 - 1j]], dtype=np.complex128)
SQRTY2 = 0.5 * np.array([[1 + 1j, -1 - 1j], [1 + 1j, 1 + 1j]], dtype=np.complex128)
ISQRTY2 = 0.5 * np.array([[1 - 1j, 1 - 1j], [-1 + 1j, 1 - 1j]], dtype=np.complex128)
# sqrt(W), W = (X+Y)/sqrt(2) — Sycamore gate set (reference: SqrtW usage
# in test/benchmarks.cpp supremacy circuits). W is Hermitian-unitary with
# eigenvalues ±1, so the principal square root below is unitary.
_W2 = (X2 + Y2) / math.sqrt(2.0)
_w_vals, _w_vecs = np.linalg.eigh(_W2)
SQRTW2 = (_w_vecs * np.sqrt(_w_vals.astype(np.complex128))) @ _w_vecs.conj().T


def phase_mtrx(top_left: complex, bottom_right: complex) -> np.ndarray:
    return np.array([[top_left, 0], [0, bottom_right]], dtype=np.complex128)


def invert_mtrx(top_right: complex, bottom_left: complex) -> np.ndarray:
    return np.array([[0, top_right], [bottom_left, 0]], dtype=np.complex128)


def u3_mtrx(theta: float, phi: float, lambd: float) -> np.ndarray:
    """General single-qubit rotation (reference: U, src/qinterface/rotational.cpp:18)."""
    cos = math.cos(theta / 2)
    sin = math.sin(theta / 2)
    return np.array(
        [
            [cos, -cmath.exp(1j * lambd) * sin],
            [cmath.exp(1j * phi) * sin, cmath.exp(1j * (phi + lambd)) * cos],
        ],
        dtype=np.complex128,
    )


def ai_mtrx(azimuth: float, inclination: float) -> np.ndarray:
    """Bloch-vector azimuth/inclination prep (reference: AI,
    src/qinterface/rotational.cpp:55-129)."""
    cosine = math.cos(inclination / 2)
    sine = math.sin(inclination / 2)
    e_az = cmath.exp(1j * azimuth)
    return np.array([[cosine, -sine / e_az], [sine * e_az, cosine]], dtype=np.complex128)


def exp_mtrx(m: np.ndarray) -> np.ndarray:
    """2x2 matrix exponential via eigendecomposition (reference: exp2x2,
    src/common/functions.cpp)."""
    w, v = np.linalg.eig(m)
    return (v * np.exp(w)) @ np.linalg.inv(v)


def sqrt_mtrx(m: np.ndarray) -> np.ndarray:
    w, v = np.linalg.eig(m)
    return (v * np.sqrt(w.astype(np.complex128))) @ np.linalg.inv(v)


def is_phase(m: np.ndarray, tol: float = 1e-12) -> bool:
    """True if the matrix is diagonal (phase-only fast path,
    reference: IS_NORM_0 checks in src/qengine/opencl.cpp:810-900)."""
    return abs(m[0, 1]) <= tol and abs(m[1, 0]) <= tol


def is_invert(m: np.ndarray, tol: float = 1e-12) -> bool:
    """True if the matrix is anti-diagonal (X-like fast path)."""
    return abs(m[0, 0]) <= tol and abs(m[1, 1]) <= tol


def is_identity(m: np.ndarray, tol: float = 1e-12) -> bool:
    ph = m[0, 0]
    return (
        abs(m[0, 1]) <= tol
        and abs(m[1, 0]) <= tol
        and abs(m[1, 1] - ph) <= tol
        and abs(abs(ph) - 1.0) <= tol
    )


def is_clifford_mtrx(m: np.ndarray, tol: float = 1e-6) -> bool:
    """Heuristic single-qubit Clifford membership test, used by the
    stabilizer-hybrid layer (reference: QStabilizerHybrid gate triage,
    src/qstabilizerhybrid.cpp:206-239)."""
    from itertools import product

    cliffords = _clifford_cache()
    for c in cliffords:
        # compare up to global phase
        inner = np.trace(c.conj().T @ m) / 2.0
        if abs(abs(inner) - 1.0) <= tol:
            return True
    return False


_CLIFFORD_CACHE = None


def _clifford_cache():
    global _CLIFFORD_CACHE
    if _CLIFFORD_CACHE is None:
        gens = [I2, H2, S2]
        group = [I2]
        frontier = [I2]
        while frontier:
            nxt = []
            for g in frontier:
                for h in gens:
                    cand = h @ g
                    # normalize global phase: make first nonzero entry real positive
                    flat = cand.flatten()
                    nz = flat[np.argmax(np.abs(flat) > 1e-9)]
                    cand_n = cand * (abs(nz) / nz)
                    if not any(np.allclose(cand_n, m, atol=1e-9) for m in group):
                        group.append(cand_n)
                        nxt.append(cand_n)
            frontier = nxt
        _CLIFFORD_CACHE = group  # 24 elements
    return _CLIFFORD_CACHE
