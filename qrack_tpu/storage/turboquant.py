"""TurboQuant block compression: shared core + lossy checkpoints.

Role parity with the reference's TurboQuant storage family (reference:
include/statevector_turboquant.hpp:1-120 — per-2^p-block random
orthogonal rotation + b-bit quantization, decompress-per-block access,
seed-not-matrices serialization; LossySaveStateVector
src/qinterface/qinterface.cpp:855-884).  The design here is
TPU-idiomatic rather than a port:

* A block of D = 2^p complex amplitudes is one row of a (B, 2D) real
  matrix ([re_0..re_{D-1}, im_0..im_{D-1}] concatenated planes), so the
  decorrelating rotation is a batched (B, 2D) @ (2D, 2D) matmul — at
  the default p=6 that is a 128-wide contraction the MXU tiles
  natively.  The reference rotates per-block vectors one at a time on
  CPU threads.
* One rotation matrix is shared by every block (the reference draws one
  per block).  Decorrelation only needs SOME fixed Haar-ish rotation,
  and sharing turns decompress/compress into a single large matmul and
  the serialized format into one 8-byte seed total.
* Quantization is symmetric b-bit against a per-block max-abs scale.
  The rotation flattens heavy-tailed blocks (a lone spike spreads into
  ~Gaussian coordinates), which is exactly why the reference rotates
  before quantizing — max-abs on unrotated spiky blocks wastes almost
  the whole code range on one coordinate.
* Dequantize(codes, scales) is LINEAR in scales, so state
  normalization on the compressed representation is a pure scale
  update — no decompression at all (the live engine exploits this,
  engines/turboquant.py).

The checkpoint functions (lossy_save/lossy_load) store the rotation
seed, never the matrix (O(1) vs O(D^2) — the reference's serialization
property).
"""

from __future__ import annotations

import numpy as np

DEFAULT_BLOCK_POW = 6   # D = 64 complex amps -> 128x128 rotation (MXU tile)
DEFAULT_BITS = 8
DEFAULT_SEED = 0x7142_7142_7142_7142


def rotation_matrix(d: int, seed: int = DEFAULT_SEED) -> np.ndarray:
    """Deterministic random orthogonal (d, d) float32 matrix from a seed
    (reference: _tq_make_rotation, statevector_turboquant.hpp — Gaussian
    fill + orthonormalization; here QR with sign-fixed diagonal)."""
    rng = np.random.Generator(np.random.PCG64(seed))
    q, r = np.linalg.qr(rng.standard_normal((d, d)))
    q *= np.sign(np.diagonal(r))
    return np.ascontiguousarray(q, dtype=np.float32)


def code_dtype(bits: int):
    return np.int8 if bits <= 8 else np.int16


def qmax(bits: int) -> int:
    return (1 << (bits - 1)) - 1


def planes_to_rows(planes: np.ndarray, block: int) -> np.ndarray:
    """(2, N) planes -> (B, 2D) block rows (concatenated re/im)."""
    b = planes.shape[-1] // block
    return (planes.reshape(2, b, block).transpose(1, 0, 2)
            .reshape(b, 2 * block))


def rows_to_planes(rows: np.ndarray, block: int) -> np.ndarray:
    """(B, 2D) block rows -> (2, N) planes."""
    b = rows.shape[0]
    return (rows.reshape(b, 2, block).transpose(1, 0, 2)
            .reshape(2, b * block))


def quantize_blocks(state: np.ndarray, bits: int = DEFAULT_BITS,
                    block_pow: int = 12, seed: int = DEFAULT_SEED):
    """Complex vector -> (scales, codes) per rotated block."""
    state = np.asarray(state).reshape(-1)
    n = state.shape[0]
    block = min(1 << block_pow, n)
    pad = (-n) % block
    if pad:
        state = np.concatenate([state, np.zeros(pad, dtype=state.dtype)])
    planes = np.stack([state.real, state.imag]).astype(np.float32)
    rows = planes_to_rows(planes, block)
    rot = rows @ rotation_matrix(2 * block, seed)
    scales = np.max(np.abs(rot), axis=1)
    safe = np.where(scales > 0, scales, 1.0)
    q = qmax(bits)
    codes = np.round(rot / safe[:, None] * q).astype(code_dtype(bits))
    return scales.astype(np.float32), codes, n


def dequantize_blocks(scales: np.ndarray, codes: np.ndarray, n: int,
                      bits: int = DEFAULT_BITS, seed: int = DEFAULT_SEED,
                      normalize: bool = True) -> np.ndarray:
    block = codes.shape[1] // 2
    rot = codes.astype(np.float32) * (scales / qmax(bits))[:, None]
    rows = rot @ rotation_matrix(2 * block, seed).T
    flat = rows_to_planes(rows, block)
    out = (flat[0] + 1j * flat[1]).astype(np.complex128)[:n]
    if normalize:
        # renormalize: quantization perturbs the norm slightly
        nrm = np.linalg.norm(out)
        if nrm > 0:
            out = out / nrm
    return out


def dequantize_blocks_v1(scales: np.ndarray, codes: np.ndarray, n: int,
                         bits: int = DEFAULT_BITS,
                         normalize: bool = True) -> np.ndarray:
    """Decode the round-<=3 pre-rotation block format: per-plane max-abs
    int codes with (2, B) scales and no decorrelating rotation.  Kept so
    v1 per-factor/per-page archives written before the rotated format
    landed still load (same math as lossy_load's legacy branch)."""
    q = qmax(bits)
    planes = codes.astype(np.float32) * (scales[..., None] / q)
    flat = planes.reshape(2, -1)
    out = (flat[0] + 1j * flat[1]).astype(np.complex128)[:n]
    if normalize:
        nrm = np.linalg.norm(out)
        if nrm > 0:
            out = out / nrm
    return out


LOSSY_KIND = "turboquant-lossy-ket"


def _npz_path(path) -> str:
    # np.savez_compressed appended .npz to bare paths; keep that naming
    # so existing callers' paths stay valid across the container switch
    return path if str(path).endswith(".npz") else str(path) + ".npz"


def lossy_save(state: np.ndarray, path: str, bits: int = DEFAULT_BITS,
               block_pow: int = 12, seed: int = DEFAULT_SEED) -> None:
    from ..checkpoint.container import save_container

    scales, codes, n = quantize_blocks(state, bits=bits,
                                       block_pow=block_pow, seed=seed)
    # the payload keeps the pre-container member layout (scales/codes/
    # n/bits/seed), so readers that predate the manifest still load
    # these files as bare npz; the manifest adds checksums + versioning
    save_container(_npz_path(path),
                   {"scales": scales, "codes": codes,
                    "n": np.asarray(n), "bits": np.asarray(bits),
                    "seed": np.asarray(seed)},
                   meta={"n": int(n), "bits": int(bits), "seed": int(seed)},
                   kind=LOSSY_KIND)


def lossy_load(path: str) -> np.ndarray:
    from ..checkpoint.container import load_container

    # container files verify checksums here; bare legacy npz (kind None)
    # loads unverified — both carry the same member layout
    _, _, z = load_container(_npz_path(path), legacy_ok=True)

    def scalar(key):  # container members are at-least-1-d
        return int(np.ravel(z[key])[0])

    if "seed" in z:
        return dequantize_blocks(z["scales"], z["codes"], scalar("n"),
                                 scalar("bits"), seed=scalar("seed"))
    # pre-rotation checkpoint format (round <=3): per-plane max-abs
    # int codes with (2, B) scales, no decorrelating rotation
    return dequantize_blocks_v1(z["scales"], z["codes"], scalar("n"),
                                scalar("bits"))
