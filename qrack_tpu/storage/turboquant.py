"""Lossy block-quantized state-vector checkpoints.

Role parity with the reference's TurboQuant lossy save/load
(reference: include/statevector_turboquant.hpp:1-120 — per-2^p-block
random-rotation + b-bit quantization; LossySaveStateVector
src/qinterface/qinterface.cpp:855-884). Format here is TPU-idiomatic
rather than a port: amplitudes are stored as per-block scaled b-bit
integers for real/imag planes (npz container), which reconstructs with
bounded relative error per block and compresses ~8x at 8 bits.
"""

from __future__ import annotations

import numpy as np


def quantize_blocks(state: np.ndarray, bits: int = 8, block_pow: int = 12):
    """Quantize a complex vector into (scales, codes) per block."""
    state = np.asarray(state).reshape(-1)
    n = state.shape[0]
    block = min(1 << block_pow, n)
    pad = (-n) % block
    if pad:
        state = np.concatenate([state, np.zeros(pad, dtype=state.dtype)])
    planes = np.stack([state.real, state.imag]).astype(np.float32)
    planes = planes.reshape(2, -1, block)
    scales = np.max(np.abs(planes), axis=2, keepdims=True)
    safe = np.where(scales > 0, scales, 1.0)
    qmax = (1 << (bits - 1)) - 1
    codes = np.round(planes / safe * qmax).astype(np.int8 if bits <= 8 else np.int16)
    return scales.squeeze(-1).astype(np.float32), codes, n


def dequantize_blocks(scales: np.ndarray, codes: np.ndarray, n: int, bits: int = 8,
                      normalize: bool = True) -> np.ndarray:
    qmax = (1 << (bits - 1)) - 1
    planes = codes.astype(np.float32) * (scales[..., None] / qmax)
    flat = planes.reshape(2, -1)
    out = (flat[0] + 1j * flat[1]).astype(np.complex128)[:n]
    if normalize:
        # renormalize: quantization shrinks the norm slightly
        nrm = np.linalg.norm(out)
        if nrm > 0:
            out = out / nrm
    return out


def lossy_save(state: np.ndarray, path: str, bits: int = 8, block_pow: int = 12) -> None:
    scales, codes, n = quantize_blocks(state, bits=bits, block_pow=block_pow)
    np.savez_compressed(path, scales=scales, codes=codes, n=n, bits=bits)


def lossy_load(path: str) -> np.ndarray:
    with np.load(path if str(path).endswith(".npz") else str(path) + ".npz") as z:
        return dequantize_blocks(z["scales"], z["codes"], int(z["n"]), int(z["bits"]))
