from . import turboquant  # noqa: F401
