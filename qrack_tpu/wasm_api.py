"""Second binding surface: JSON-RPC veneer over the flat API.

Role parity with the reference's wasm_api (reference:
include/wasm_api.hpp:158-414, src/wasm_api.cpp — the same simulator
surface re-idiomized for emscripten/JS consumers with vectors instead
of raw pointers).  The TPU-native equivalent of "callable from a web
runtime" is a transport-friendly JSON-RPC 2.0 dispatcher: every
function exported by qrack_tpu.capi is callable by name with JSON
params, complex values marshal as [re, im] pairs and arrays as lists,
so a JS/WASM (or any remote) consumer drives simulators over a pipe or
socket without Python bindings.

    >>> dispatch('{"jsonrpc":"2.0","method":"init_count","params":[2],"id":1}')
    '{"jsonrpc": "2.0", "result": 0, "id": 1}'

`serve_stdio()` runs a newline-delimited request loop (the shape an
emscripten worker or electron sidecar would speak).
"""

from __future__ import annotations

import json
import sys
from typing import Any

import numpy as np

from . import capi


def _to_jsonable(v: Any) -> Any:
    if isinstance(v, complex):
        return [v.real, v.imag]
    if isinstance(v, np.complexfloating):
        return [float(v.real), float(v.imag)]
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, np.ndarray):
        if np.iscomplexobj(v):
            return [[float(x.real), float(x.imag)] for x in v.reshape(-1)]
        return [_to_jsonable(x) for x in v.reshape(-1)]
    if isinstance(v, dict):
        return {str(k): _to_jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_to_jsonable(x) for x in v]
    if isinstance(v, (bool, int, float, str)) or v is None:
        return v
    return repr(v)


def _from_jsonable(v: Any) -> Any:
    # [re, im] number pairs arrive as lists; leave them — capi accepts
    # sequences and numpy coercion handles pairs where complex matrices
    # are expected via `_complex_list`
    return v


def _complex_list(flat):
    """JSON matrix payloads: flat [re, im, re, im, ...] or [[re, im], ...]."""
    arr = np.asarray(flat, dtype=np.float64)
    if arr.ndim == 2 and arr.shape[1] == 2:
        return arr[:, 0] + 1j * arr[:, 1]
    return arr.reshape(-1, 2)[:, 0] + 1j * arr.reshape(-1, 2)[:, 1]


# methods whose named positional arg is a complex 2x2 (or list of them):
# the JSON side sends real/imag pairs
_MATRIX_ARG = {"Mtrx": 1, "MCMtrx": 2, "MACMtrx": 2, "UCMtrx": 2,
               "Multiplex1Mtrx": 3}


def call(method: str, params) -> Any:
    if method.startswith("_") or not hasattr(capi, method):
        raise AttributeError(f"unknown method {method!r}")
    fn = getattr(capi, method)
    params = list(params or [])
    if method in _MATRIX_ARG:
        i = _MATRIX_ARG[method]
        params[i] = _complex_list(params[i])
    if method == "InKet":
        params[1] = _complex_list(params[1])
    return fn(*params)


def dispatch(request: str) -> str:
    """Handle one JSON-RPC 2.0 request string; returns the response."""
    rid = None
    try:
        req = json.loads(request)
        rid = req.get("id")
        result = call(req["method"], req.get("params", []))
        return json.dumps({"jsonrpc": "2.0",
                           "result": _to_jsonable(result), "id": rid})
    except Exception as exc:  # JSON-RPC error object, never an exception
        return json.dumps({"jsonrpc": "2.0",
                           "error": {"code": -32000,
                                     "message": f"{type(exc).__name__}: {exc}"},
                           "id": rid})


def serve_stdio(stdin=None, stdout=None) -> None:
    """Newline-delimited JSON-RPC loop (EOF or 'quit' ends it)."""
    stdin = stdin or sys.stdin
    stdout = stdout or sys.stdout
    for line in stdin:
        line = line.strip()
        if not line:
            continue
        if line == "quit":
            break
        stdout.write(dispatch(line) + "\n")
        stdout.flush()


if __name__ == "__main__":
    serve_stdio()
