"""Dedicated JS/WASM binding surface.

Role parity with the reference's wasm_api (reference:
include/wasm_api.hpp:27-414, src/wasm_api.cpp — the simulator surface
re-idiomized for emscripten/JS consumers: typed structs instead of raw
pointers).  The TPU-native equivalent of "callable from a web runtime"
is a transport-friendly JSON-RPC 2.0 service with an EXPLICIT export
registry mirroring the reference's export list name for name, plus the
same typed payloads re-idiomized as JSON objects:

    QubitIndexState        {"q": 0, "v": true}
    QubitIntegerExpVar     {"q": 0, "val": 3}      (or "val": [v0, v1])
    QubitRealExpVar        {"q": 0, "val": 0.5}    (or "val": [v0, v1])
    QubitPauliBasis        {"q": 0, "b": 3}
    QubitU3Basis           {"q": 0, "b": [theta, phi, lambda]}
    QubitMatrixBasis       {"q": 0, "b": [[re,im],[re,im],[re,im],[re,im]]}
    ...EigenVal variants   + {"e": [e0, e1]}

Complex scalars marshal as [re, im]; complex matrices as flat pair
lists.  `describe()` returns the export table so a JS client can
enumerate the surface.  `dispatch()` speaks JSON-RPC 2.0 with proper
error codes (-32700 parse, -32601 unknown method, -32602 bad params,
-32000 runtime) and batch arrays; `serve_stdio()` runs the
newline-delimited loop an emscripten worker or electron sidecar would
speak.  Methods of the flat C ABI (capi.py, the pinvoke mirror) that
the reference's wasm surface does not re-export remain reachable as a
documented superset.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Callable, Dict, List

import numpy as np

from . import capi

# ---------------------------------------------------------------------------
# typed payload decoding (reference structs, include/wasm_api.hpp:29-140)
# ---------------------------------------------------------------------------


def _cpx_matrix(flat):
    """2x2 (or larger) complex payload from pair-list JSON."""
    arr = np.asarray(flat, dtype=np.float64)
    if arr.ndim == 2 and arr.shape[1] == 2:
        return arr[:, 0] + 1j * arr[:, 1]
    return arr.reshape(-1, 2)[:, 0] + 1j * arr.reshape(-1, 2)[:, 1]


def _index_states(structs):
    """[{"q", "v"}] -> (qubits, packed perm) for the mask helpers."""
    qubits, perm = [], 0
    for j, s in enumerate(structs):
        qubits.append(int(s["q"]))
        if s.get("v"):
            perm |= 1 << j
    return qubits, perm


def _expvar_pairs(structs, is_int: bool):
    """[{"q", "val"}] -> (qubits, flat per-bit weights).  A scalar val
    weights the |1> branch (|0> weighs 0); a 2-list gives both branch
    weights explicitly."""
    qubits, weights = [], []
    for s in structs:
        qubits.append(int(s["q"]))
        v = s["val"]
        if isinstance(v, (list, tuple)):
            w0, w1 = v[0], v[1]
        else:
            w0, w1 = 0, v
        if is_int:
            weights.extend([int(w0), int(w1)])
        else:
            weights.extend([float(w0), float(w1)])
    return qubits, weights


def _pauli_bases(structs):
    qubits = [int(s["q"]) for s in structs]
    bases = [int(s["b"]) for s in structs]
    return bases, qubits


def _eigen_of(structs, require: bool):
    """Flattened per-qubit eigenvalue pairs; every struct must agree on
    carrying "e" or not (reference: the EigenVal struct variants,
    include/wasm_api.hpp:103-140)."""
    have = ["e" in s for s in structs]
    if require and not all(have):
        raise ValueError("every struct needs 2 eigenvalues ('e') here")
    if not any(have):
        return None
    if not all(have):
        raise ValueError("mixed structs: either all or none carry 'e'")
    eigen = []
    for s in structs:
        eigen.extend([float(x) for x in s["e"]])
    return eigen


def _u3_bases(structs, require_eigen: bool = False):
    qubits = [int(s["q"]) for s in structs]
    triples = [[float(x) for x in s["b"]] for s in structs]
    return qubits, triples, _eigen_of(structs, require_eigen)


def _matrix_bases(structs, require_eigen: bool = False):
    qubits = [int(s["q"]) for s in structs]
    mats = [_cpx_matrix(s["b"]).reshape(2, 2) for s in structs]
    return qubits, mats, _eigen_of(structs, require_eigen)


# ---------------------------------------------------------------------------
# export registry (reference export list, include/wasm_api.hpp:158-414)
# ---------------------------------------------------------------------------

EXPORTS: Dict[str, Callable] = {}


def _export(name: str, fn: Callable = None):
    if fn is None:
        def deco(f):
            EXPORTS[name] = f
            return f

        return deco
    EXPORTS[name] = fn
    return fn


# -- exports whose calling shape already matches the flat ABI --
for _n in ("init_count_type", "init_count", "init_count_stabilizer", "init",
           "init_clone", "destroy", "seed", "set_concurrency", "set_device",
           "set_device_list", "allocateQubit", "release", "num_qubits",
           "qstabilizer_out_to_file",
           "qstabilizer_in_from_file", "random_choice", "Prob", "ProbRdm",
           "PermutationExpectation", "PermutationExpectationRdm", "Variance",
           "VarianceRdm", "PhaseParity", "PhaseRootN",
           "JointEnsembleProbability", "M", "ForceM", "MAll", "ResetAll",
           "X", "Y", "Z", "H", "S", "SX", "SY", "T", "AdjS", "AdjSX",
           "AdjSY", "AdjT", "U", "MCX", "MCY", "MCZ", "MCH", "MCS", "MCT",
           "MCAdjS", "MCAdjT", "MCU", "MACX", "MACY", "MACZ", "MACH",
           "MACS", "MACT", "MACAdjS", "MACAdjT", "MX", "MY", "MZ", "R",
           "MCR", "Exp", "MCExp", "SWAP", "ISWAP", "AdjISWAP", "FSim",
           "CSWAP", "ACSWAP", "Compose", "Decompose", "Dispose", "AND",
           "OR", "XOR", "NAND", "NOR", "XNOR", "CLAND", "CLOR", "CLXOR",
           "CLNAND", "CLNOR", "CLXNOR", "QFT", "IQFT", "ADD", "SUB",
           "ADDS", "SUBS", "MCADD", "MCSUB", "MUL", "DIV", "MULN", "DIVN",
           "POWN", "MCMUL", "MCDIV", "MCMULN", "MCDIVN", "MCPOWN", "LDA",
           "ADC", "SBC", "Hash", "TrySeparate1Qb", "TrySeparate2Qb",
           "TrySeparateTol", "Separate", "GetUnitaryFidelity",
           "ResetUnitaryFidelity", "SetSdrp", "SetNcrp",
           "SetReactiveSeparate", "SetTInjection", "SetNoiseParameter",
           "Normalize", "init_qneuron", "clone_qneuron", "destroy_qneuron",
           "set_qneuron_angles", "qneuron_predict", "qneuron_unpredict",
           "qneuron_learn_cycle", "qneuron_learn",
           "qneuron_learn_permutation", "init_qcircuit",
           "init_qcircuit_clone", "qcircuit_inverse",
           "qcircuit_past_light_cone", "destroy_qcircuit",
           "get_qcircuit_qubit_count", "qcircuit_swap", "qcircuit_run",
           "qcircuit_out_to_file", "qcircuit_in_from_file"):
    _export(_n, getattr(capi, _n))


@_export("SetPermutation")
def _set_permutation(sid, perm: int):
    """Reference: SetPermutation(quid, bitCapInt) — wasm-only export
    (the pinvoke mirror reaches it through ResetAll + X chains)."""
    return capi._sim(sid).SetPermutation(int(perm))


@_export("init_qbdd_count")
def _init_qbdd_count(q: int) -> int:
    """Reference: init_qbdd_count — pure QBdt-stack simulator."""
    from .layers.qbdthybrid import QBdtHybrid

    sid = capi._new_sid()
    capi._REGISTRY[sid] = QBdtHybrid(q)
    return sid


@_export("Mtrx")
def _mtrx(sid, m, q):
    return capi.Mtrx(sid, _cpx_matrix(m), q)


@_export("MCMtrx")
def _mcmtrx(sid, c, m, q):
    return capi.MCMtrx(sid, c, _cpx_matrix(m), q)


@_export("MACMtrx")
def _macmtrx(sid, c, m, q):
    return capi.MACMtrx(sid, c, _cpx_matrix(m), q)


@_export("UCMtrx")
def _ucmtrx(sid, c, m, q, perm):
    return capi.UCMtrx(sid, c, _cpx_matrix(m), q, perm)


@_export("Multiplex1Mtrx")
def _multiplex(sid, c, q, m):
    return capi.Multiplex1Mtrx(sid, c, q, _cpx_matrix(m))


@_export("qcircuit_append_1qb")
def _qc_append_1qb(cid, m, q):
    return capi.qcircuit_append_1qb(cid, _cpx_matrix(m), q)


@_export("qcircuit_append_mc")
def _qc_append_mc(cid, m, c, q, perm):
    return capi.qcircuit_append_mc(cid, _cpx_matrix(m), c, q, perm)


@_export("InKet")
def _inket(sid, ket):
    return capi.InKet(sid, _cpx_matrix(ket))


# -- typed-struct observables (reference wasm_api.cpp:1878-2130) --

@_export("PermutationProb")
def _perm_prob(sid, structs):
    qubits, perm = _index_states(structs)
    return capi.PermutationProb(sid, qubits, perm)


@_export("PermutationProbRdm")
def _perm_prob_rdm(sid, structs, r=True):
    qubits, perm = _index_states(structs)
    return capi.PermutationProbRdm(sid, qubits, perm, r)


@_export("FactorizedExpectation")
def _fact_exp(sid, structs):
    qubits, vals = _expvar_pairs(structs, True)
    return capi.FactorizedExpectation(sid, qubits, vals)


@_export("FactorizedExpectationRdm")
def _fact_exp_rdm(sid, structs, r=True):
    qubits, vals = _expvar_pairs(structs, True)
    return capi.FactorizedExpectationRdm(sid, qubits, vals, r)


@_export("FactorizedExpectationFp")
def _fact_exp_fp(sid, structs):
    qubits, ws = _expvar_pairs(structs, False)
    return capi.FactorizedExpectationFp(sid, qubits, ws)


@_export("FactorizedExpectationFpRdm")
def _fact_exp_fp_rdm(sid, structs, r=True):
    qubits, ws = _expvar_pairs(structs, False)
    return capi.FactorizedExpectationFpRdm(sid, qubits, ws, r)


@_export("FactorizedVariance")
def _fact_var(sid, structs):
    qubits, vals = _expvar_pairs(structs, True)
    return capi.FactorizedVariance(sid, qubits, vals)


@_export("FactorizedVarianceRdm")
def _fact_var_rdm(sid, structs, r=True):
    qubits, vals = _expvar_pairs(structs, True)
    return capi.FactorizedVarianceRdm(sid, qubits, vals, r)


@_export("FactorizedVarianceFp")
def _fact_var_fp(sid, structs):
    qubits, ws = _expvar_pairs(structs, False)
    return capi.FactorizedVarianceFp(sid, qubits, ws)


@_export("FactorizedVarianceFpRdm")
def _fact_var_fp_rdm(sid, structs, r=True):
    qubits, ws = _expvar_pairs(structs, False)
    return capi.FactorizedVarianceFpRdm(sid, qubits, ws, r)


@_export("PauliExpectation")
def _pauli_exp(sid, structs):
    bases, qubits = _pauli_bases(structs)
    return capi.PauliExpectation(sid, bases, qubits)


@_export("PauliVariance")
def _pauli_var(sid, structs):
    bases, qubits = _pauli_bases(structs)
    return capi.PauliVariance(sid, bases, qubits)


@_export("Measure")
def _measure(sid, structs):
    bases, qubits = _pauli_bases(structs)
    return capi.Measure(sid, bases, qubits)


@_export("UnitaryExpectation")
def _unitary_exp(sid, structs):
    qubits, triples, eigen = _u3_bases(structs)
    if eigen is not None:
        return capi.UnitaryExpectationEigenVal(sid, qubits, triples, eigen)
    return capi.UnitaryExpectation(sid, qubits, triples)


@_export("UnitaryVariance")
def _unitary_var(sid, structs):
    qubits, triples, eigen = _u3_bases(structs)
    if eigen is not None:
        return capi.UnitaryVarianceEigenVal(sid, qubits, triples, eigen)
    return capi.UnitaryVariance(sid, qubits, triples)


@_export("UnitaryExpectationEigenVal")
def _unitary_exp_ev(sid, structs):
    qubits, triples, eigen = _u3_bases(structs, require_eigen=True)
    return capi.UnitaryExpectationEigenVal(sid, qubits, triples, eigen)


@_export("UnitaryVarianceEigenVal")
def _unitary_var_ev(sid, structs):
    qubits, triples, eigen = _u3_bases(structs, require_eigen=True)
    return capi.UnitaryVarianceEigenVal(sid, qubits, triples, eigen)


@_export("MatrixExpectation")
def _matrix_exp(sid, structs):
    qubits, mats, eigen = _matrix_bases(structs)
    if eigen is not None:
        return capi.MatrixExpectationEigenVal(sid, qubits, mats, eigen)
    return capi.MatrixExpectation(sid, qubits, mats)


@_export("MatrixVariance")
def _matrix_var(sid, structs):
    qubits, mats, eigen = _matrix_bases(structs)
    if eigen is not None:
        return capi.MatrixVarianceEigenVal(sid, qubits, mats, eigen)
    return capi.MatrixVariance(sid, qubits, mats)


@_export("MatrixExpectationEigenVal")
def _matrix_exp_ev(sid, structs):
    qubits, mats, eigen = _matrix_bases(structs, require_eigen=True)
    return capi.MatrixExpectationEigenVal(sid, qubits, mats, eigen)


@_export("MatrixVarianceEigenVal")
def _matrix_var_ev(sid, structs):
    qubits, mats, eigen = _matrix_bases(structs, require_eigen=True)
    return capi.MatrixVarianceEigenVal(sid, qubits, mats, eigen)


# -- QNeuron knobs the flat ABI exposes via the object (reference:
#    set_qneuron_alpha family, include/wasm_api.hpp:380-392) --

@_export("set_qneuron_alpha")
def _set_alpha(nid, alpha: float):
    capi._neuron(nid).alpha = float(alpha)


@_export("get_qneuron_alpha")
def _get_alpha(nid) -> float:
    return float(capi._neuron(nid).alpha)


@_export("set_qneuron_activation_fn")
def _set_act(nid, f: int):
    from .qneuron import ActivationFn

    capi._neuron(nid).activation_fn = ActivationFn(int(f))


@_export("get_qneuron_activation_fn")
def _get_act(nid) -> int:
    return int(capi._neuron(nid).activation_fn)


def describe() -> List[str]:
    """The export table (reference analogue: the emscripten
    EXPORTED_FUNCTIONS list) — JS clients enumerate this to build
    their bindings."""
    return sorted(EXPORTS)


# ---------------------------------------------------------------------------
# JSON-RPC 2.0 transport
# ---------------------------------------------------------------------------


def _to_jsonable(v: Any) -> Any:
    if isinstance(v, complex):
        return [v.real, v.imag]
    if isinstance(v, np.complexfloating):
        return [float(v.real), float(v.imag)]
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, np.ndarray):
        if np.iscomplexobj(v):
            return [[float(x.real), float(x.imag)] for x in v.reshape(-1)]
        return [_to_jsonable(x) for x in v.reshape(-1)]
    if isinstance(v, dict):
        return {str(k): _to_jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_to_jsonable(x) for x in v]
    if isinstance(v, (bool, int, float, str)) or v is None:
        return v
    return repr(v)


class UnknownMethod(Exception):
    pass


def call(method: str, params) -> Any:
    """Resolve through the typed registry first; the flat C ABI
    (pinvoke mirror, capi.py) remains reachable as a documented
    superset for methods the reference wasm surface lacks."""
    if method == "describe":
        return describe()
    fn = EXPORTS.get(method)
    if fn is None:
        if method.startswith("_") or not hasattr(capi, method):
            raise UnknownMethod(method)
        fn = getattr(capi, method)
    return fn(*(params or []))


def _handle_one(req):
    """Response dict for one request, or None for a notification (a
    request without an "id" gets no response, per JSON-RPC 2.0)."""
    rid = req.get("id") if isinstance(req, dict) else None
    if isinstance(req, dict) and "method" in req and "id" not in req:
        try:
            call(req["method"], req.get("params", []))
        except Exception:
            pass  # notifications never get error responses either
        return None
    if not isinstance(req, dict) or "method" not in req:
        return {"jsonrpc": "2.0", "id": rid,
                "error": {"code": -32600, "message": "invalid request"}}
    try:
        result = call(req["method"], req.get("params", []))
    except UnknownMethod as exc:
        return {"jsonrpc": "2.0", "id": rid,
                "error": {"code": -32601, "message": f"unknown method {exc}"}}
    except (TypeError, IndexError, ValueError) as exc:
        return {"jsonrpc": "2.0", "id": rid,
                "error": {"code": -32602,
                          "message": f"{type(exc).__name__}: {exc}"}}
    except Exception as exc:
        return {"jsonrpc": "2.0", "id": rid,
                "error": {"code": -32000,
                          "message": f"{type(exc).__name__}: {exc}"}}
    return {"jsonrpc": "2.0", "result": _to_jsonable(result), "id": rid}


def dispatch(request: str) -> str:
    """Handle one JSON-RPC 2.0 request string (single or batch)."""
    try:
        req = json.loads(request)
    except Exception as exc:
        return json.dumps({"jsonrpc": "2.0", "id": None,
                           "error": {"code": -32700,
                                     "message": f"parse error: {exc}"}})
    if isinstance(req, list):
        if not req:
            return json.dumps({"jsonrpc": "2.0", "id": None,
                               "error": {"code": -32600,
                                         "message": "empty batch"}})
        out = [r for r in (_handle_one(x) for x in req) if r is not None]
        # all-notification batches get no response body
        return json.dumps(out) if out else ""
    res = _handle_one(req)
    return json.dumps(res) if res is not None else ""


def serve_stdio(stdin=None, stdout=None) -> None:
    """Newline-delimited JSON-RPC loop (EOF or 'quit' ends it)."""
    stdin = stdin or sys.stdin
    stdout = stdout or sys.stdout
    for line in stdin:
        line = line.strip()
        if not line:
            continue
        if line == "quit":
            break
        resp = dispatch(line)
        if resp:  # notifications produce no response line
            stdout.write(resp + "\n")
            stdout.flush()


if __name__ == "__main__":
    serve_stdio()
