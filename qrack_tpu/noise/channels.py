"""Single-qubit Kraus channel algebra + the sequential trajectory oracle.

Channel semantics follow the reference's noisy wrapper
(include/qinterface_noisy.hpp:26, `DepolarizingChannelWeak1Qb`
interface/base.py:576): a channel is attached after every gate on every
touched qubit, and ONE Kraus branch is sampled per application — the
Monte-Carlo unraveling, not the density-matrix evolution.

Branch sampling is **counter-based**: every channel application in a
circuit has a monotone application counter `app_seq`, and the uniform
that decides its branch is a pure function of
``(key, trajectory_id, app_seq)`` (numpy Philox, no sequential stream
state).  Both the batched trajectory engine (trajectories.py) and the
sequential :class:`QNoisy` oracle below derive branches from the same
function, which is what makes single-trajectory reproducibility — and
hence parity testing and mid-batch checkpoint resume — exact rather
than statistical.

Branch application has two regimes (docs/NOISE.md):

* **mixed-unitary** channels (depolarizing, dephasing): every Kraus
  operator is sqrt(q_i)·U_i.  Sampling branch i with probability q_i
  and applying the *unitary* U_i is an exact unraveling — trajectory
  weight stays 1.
* **general** channels (amplitude damping, arbitrary Kraus): branches
  are sampled from the state-independent prior q_i = tr(K_i†K_i)/2, the
  *raw* K_i is applied, the ket renormalized, and the trajectory weight
  multiplied by ‖K_i|ψ⟩‖²/q_i — an importance-weighted unraveling with
  E[w·|ψ̃⟩⟨ψ̃|] = Σ_i K_i ρ K_i† (unbiased without state-dependent
  branch probabilities, which would force a device→host sync per gate).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

_I2 = np.eye(2, dtype=np.complex128)

PAULI = {
    "I": _I2,
    "X": np.array([[0, 1], [1, 0]], dtype=np.complex128),
    "Y": np.array([[0, -1j], [1j, 0]], dtype=np.complex128),
    "Z": np.array([[1, 0], [0, -1]], dtype=np.complex128),
}


class ChannelError(ValueError):
    """Raised for non-CPTP Kraus sets or malformed channel specs."""


# ---------------------------------------------------------------------------
# counter-based per-trajectory rng
# ---------------------------------------------------------------------------

# Domain constants keep the channel-branch stream and the terminal
# measurement draw on disjoint Philox keys even at equal counters.
BRANCH_DOMAIN = 0x6E6F6973  # "nois"
MEASURE_DOMAIN = 0x6D656173  # "meas"

_U64 = (1 << 64) - 1


def traj_uniform(key: int, trajectory_id: int, app_seq: int,
                 domain: int = BRANCH_DOMAIN) -> float:
    """The one uniform that decides channel application `app_seq` of
    trajectory `trajectory_id` under batch seed `key`.

    Counter-based (Philox keyed on the full coordinate, zero stream
    state): any single draw is computable in isolation, so a resumed
    chunk, a sequential oracle, and the full batch all see identical
    randomness without replaying a stream prefix.
    """
    gen = np.random.Generator(np.random.Philox(
        key=[int(key) & _U64, int(domain) & _U64],
        counter=[int(trajectory_id) & _U64, int(app_seq) & _U64, 0, 0]))
    return float(gen.random())


# ---------------------------------------------------------------------------
# channels
# ---------------------------------------------------------------------------

class KrausChannel:
    """A single-qubit channel as an explicit Kraus set {K_i}.

    `priors` are the state-independent branch probabilities
    q_i = tr(K_i†K_i)/2 (they sum to 1 by CPTP); `unitary` is True when
    every branch is a scaled unitary, i.e. the channel is mixed-unitary
    and the unraveling is exact with unit trajectory weight.
    `branch_matrix(i)` is what a trajectory actually applies: U_i for
    mixed-unitary channels, raw K_i (renormalize + weight) otherwise.
    """

    __slots__ = ("name", "kraus", "priors", "unitary", "_cum", "_branch")

    def __init__(self, name: str, kraus: Sequence[np.ndarray],
                 atol: float = 1e-8):
        mats = [np.asarray(k, dtype=np.complex128).reshape(2, 2)
                for k in kraus]
        if not mats:
            raise ChannelError(f"channel {name!r}: empty Kraus set")
        total = np.zeros((2, 2), dtype=np.complex128)
        for k in mats:
            total += k.conj().T @ k
        if not np.allclose(total, _I2, atol=max(atol, 1e-8)):
            raise ChannelError(
                f"channel {name!r}: Kraus completeness violated, "
                f"sum K^dag K = {total.tolist()!r}")
        self.name = str(name)
        self.kraus = mats
        self.priors = np.array(
            [float(np.trace(k.conj().T @ k).real) / 2.0 for k in mats])
        self.unitary = True
        self._branch: List[np.ndarray] = []
        for k, q in zip(mats, self.priors):
            if q <= atol:
                self._branch.append(k)
                continue
            u = k / np.sqrt(q)
            if np.allclose(u @ u.conj().T, _I2, atol=1e-6):
                self._branch.append(u)
            else:
                self.unitary = False
        if not self.unitary:
            self._branch = list(mats)
        self._cum = np.cumsum(self.priors)

    def __len__(self) -> int:
        return len(self.kraus)

    def __repr__(self) -> str:
        kind = "mixed-unitary" if self.unitary else "general"
        return f"KrausChannel({self.name!r}, {len(self.kraus)} branches, {kind})"

    def sample(self, u: float) -> int:
        """Branch index for uniform u in [0, 1): inverse-CDF over the
        priors in listed order.  For :func:`depolarizing` the listed
        order (X, Y, Z, I) reproduces the reference weak-channel rule —
        u < 0.75·lam picks a uniform Pauli, else identity."""
        i = int(np.searchsorted(self._cum, u, side="right"))
        return min(i, len(self.kraus) - 1)

    def branch_matrix(self, i: int) -> np.ndarray:
        return self._branch[i]

    # -- serialization (WAL journaling of trajectory jobs) -------------

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "kraus": [[[z.real, z.imag] for z in k.ravel()]
                      for k in self.kraus],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "KrausChannel":
        mats = [np.array([complex(re, im) for re, im in k],
                         dtype=np.complex128).reshape(2, 2)
                for k in d["kraus"]]
        return cls(d.get("name", "kraus"), mats)


def depolarizing(lam: float) -> KrausChannel:
    """Weak depolarizing channel matching the reference
    `DepolarizingChannelWeak1Qb` (interface/base.py:576): with
    probability 0.75·lam apply a uniformly random Pauli, else identity.
    Branch order (X, Y, Z, I) so inverse-CDF sampling reproduces the
    reference's `Rand() < 0.75*lam` threshold rule exactly."""
    lam = float(lam)
    if not 0.0 <= lam <= 1.0:
        raise ChannelError(f"depolarizing lam {lam} outside [0, 1]")
    p = lam / 4.0
    return KrausChannel(f"depolarizing({lam})", [
        np.sqrt(p) * PAULI["X"],
        np.sqrt(p) * PAULI["Y"],
        np.sqrt(p) * PAULI["Z"],
        np.sqrt(1.0 - 3.0 * p) * PAULI["I"],
    ])


def dephasing(p: float) -> KrausChannel:
    """Phase-flip channel: Z with probability p, identity otherwise."""
    p = float(p)
    if not 0.0 <= p <= 1.0:
        raise ChannelError(f"dephasing p {p} outside [0, 1]")
    return KrausChannel(f"dephasing({p})", [
        np.sqrt(p) * PAULI["Z"],
        np.sqrt(1.0 - p) * PAULI["I"],
    ])


def amplitude_damping(gamma: float) -> KrausChannel:
    """T1 decay: K0 = diag(1, sqrt(1-gamma)), K1 = sqrt(gamma)|0><1|.
    Non-unitary branches — trajectories renormalize and carry an
    importance weight."""
    g = float(gamma)
    if not 0.0 <= g <= 1.0:
        raise ChannelError(f"amplitude_damping gamma {g} outside [0, 1]")
    k0 = np.array([[1.0, 0.0], [0.0, np.sqrt(1.0 - g)]],
                  dtype=np.complex128)
    k1 = np.array([[0.0, np.sqrt(g)], [0.0, 0.0]], dtype=np.complex128)
    return KrausChannel(f"amplitude_damping({g})", [k0, k1])


def kraus_channel(name: str, kraus: Sequence[np.ndarray]) -> KrausChannel:
    """General single-qubit channel from explicit Kraus matrices
    (CPTP-validated)."""
    return KrausChannel(name, kraus)


# ---------------------------------------------------------------------------
# noise model
# ---------------------------------------------------------------------------

class NoiseModel:
    """Per-gate/per-qubit channel attachment, the way the reference's
    `QInterfaceNoisy` does it: after every gate, every touched qubit
    (target ∪ controls) receives the attached channels in deterministic
    order — `default` first, then any per-qubit extras.

    The attachment order plus the sorted-qubit iteration defines the
    channel-application schedule (one `app_seq` per slot) shared by the
    batch pre-sampler and the sequential oracle.
    """

    def __init__(self, default: Optional[KrausChannel] = None,
                 per_qubit: Optional[Dict[int, Sequence[KrausChannel]]] = None):
        self.default = default
        self.per_qubit: Dict[int, List[KrausChannel]] = {
            int(q): list(chs) for q, chs in (per_qubit or {}).items()}

    @property
    def trivial(self) -> bool:
        return self.default is None and not any(self.per_qubit.values())

    def channels_for(self, qubit: int) -> List[KrausChannel]:
        out: List[KrausChannel] = []
        if self.default is not None:
            out.append(self.default)
        out.extend(self.per_qubit.get(int(qubit), ()))
        return out

    def slots_for(self, qubits: Iterable[int]) -> List[Tuple[int, KrausChannel]]:
        """The channel-application slots one gate on `qubits` produces,
        in schedule order."""
        out: List[Tuple[int, KrausChannel]] = []
        for q in sorted(set(int(q) for q in qubits)):
            for ch in self.channels_for(q):
                out.append((q, ch))
        return out

    def to_dict(self) -> dict:
        return {
            "default": self.default.to_dict() if self.default else None,
            "per_qubit": {str(q): [c.to_dict() for c in chs]
                          for q, chs in self.per_qubit.items() if chs},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "NoiseModel":
        default = (KrausChannel.from_dict(d["default"])
                   if d.get("default") else None)
        per_qubit = {int(q): [KrausChannel.from_dict(c) for c in chs]
                     for q, chs in (d.get("per_qubit") or {}).items()}
        return cls(default=default, per_qubit=per_qubit)


# ---------------------------------------------------------------------------
# sequential oracle engine (factory terminal "noisy")
# ---------------------------------------------------------------------------

class QNoisy:
    """One-trajectory noisy engine over an inner simulator — the
    sequential CPU oracle the batch engine is tested against, and the
    library-path terminal ``"noisy"`` in the factory.

    Unlike the `QInterfaceNoisy` *wrapper layer* (which draws from the
    engine's sequential rng stream), branches here come from
    :func:`traj_uniform` at this engine's ``(key, trajectory_id)`` and a
    monotone application counter, so this engine IS trajectory
    `trajectory_id` of the equivalent batched job — bit-for-bit in its
    branch choices.
    """

    _is_noisy_trajectory = True

    def __init__(self, qubit_count: int, model: Optional[NoiseModel] = None,
                 noise: Optional[float] = None, key: int = 0,
                 trajectory_id: int = 0, inner=None,
                 inner_layers="cpu", init_state: int = 0, **kw):
        if model is None:
            model = (NoiseModel(default=depolarizing(noise))
                     if noise else NoiseModel())
        self.model = model
        self.key = int(key)
        self.trajectory_id = int(trajectory_id)
        self.app_seq = 0
        self.weight = 1.0
        self.qubit_count = int(qubit_count)
        if inner is None:
            from ..factory import create_quantum_interface

            inner = create_quantum_interface(
                inner_layers, qubit_count, init_state=init_state, **kw)
        self.inner = inner

    # -- gate primitives: inner op, then the channel schedule ----------

    def MCMtrxPerm(self, controls, mtrx, target, perm):
        self.inner.MCMtrxPerm(controls, mtrx, target, perm)
        self._apply_noise((target,) + tuple(controls))

    def Mtrx(self, mtrx, target):
        self.inner.MCMtrxPerm((), mtrx, target, 0)
        self._apply_noise((target,))

    def MCMtrx(self, controls, mtrx, target):
        self.inner.MCMtrxPerm(controls, mtrx, target,
                              (1 << len(controls)) - 1)
        self._apply_noise((target,) + tuple(controls))

    def Swap(self, q1, q2):
        self.inner.Swap(q1, q2)
        self._apply_noise((q1, q2))

    def run_circuit(self, circuit) -> None:
        """Run a QCircuit gate list under the SAME schedule the batch
        engine lowers: per gate, payload perms in sorted order, then
        the gate's channel slots."""
        for g in circuit.gates:
            for perm in sorted(g.payloads):
                self.inner.MCMtrxPerm(g.controls, g.payloads[perm],
                                      g.target, perm)
            self._apply_noise((g.target,) + tuple(g.controls))

    def _apply_noise(self, qubits) -> None:
        for q, ch in self.model.slots_for(qubits):
            u = traj_uniform(self.key, self.trajectory_id, self.app_seq)
            self.app_seq += 1
            i = ch.sample(u)
            m = ch.branch_matrix(i)
            if ch.unitary:
                self.inner.Mtrx(m, q)
                continue
            # general Kraus branch: apply raw K on the host state,
            # renormalize, accumulate the importance weight n2/q_i
            psi = np.asarray(self.inner.GetQuantumState(),
                             dtype=np.complex128)
            n = self.qubit_count
            v = psi.reshape(1 << (n - 1 - q), 2, 1 << q)
            v = np.einsum("ab,hbl->hal", m, v).reshape(-1)
            n2 = float(np.vdot(v, v).real)
            if n2 <= 0.0:
                # branch annihilated the state: dead trajectory —
                # importance weight 0, ket reset to |0...0> so the
                # remaining schedule stays well-defined.  The batch
                # body (trajectories.py) does the identical thing, so
                # bit parity survives the edge.
                v = np.zeros_like(psi)
                v[0] = 1.0
                self.inner.SetQuantumState(v)
                self.weight = 0.0
                continue
            self.inner.SetQuantumState(v / np.sqrt(n2))
            self.weight *= n2 / float(ch.priors[i])

    def measure_uniform(self) -> float:
        """The terminal measurement uniform for this trajectory —
        shared with the batch engine's per-trajectory sample draw."""
        return traj_uniform(self.key, self.trajectory_id, 0,
                            domain=MEASURE_DOMAIN)

    def __getattr__(self, attr):
        return getattr(self.inner, attr)
