"""Monte-Carlo noisy trajectories (docs/NOISE.md).

Two halves of one contract:

* :mod:`channels` — single-qubit Kraus channel algebra, the
  :class:`NoiseModel` attachment policy, the counter-based
  per-trajectory rng, and the sequential :class:`QNoisy` oracle engine
  (factory terminal ``"noisy"``).
* :mod:`trajectories` — the batched engine: (circuit, NoiseModel, B)
  lowers into ONE window program with a leading trajectory axis, branch
  choices pre-sampled host-side into runtime operands, dispatched
  vmapped through the ``tpu.fuse.flush`` guarded site.

The load-bearing property: a trajectory is a pure function of
``(key, trajectory_id)`` — the batch engine and the sequential oracle
draw the same uniforms at the same channel-application counters, so any
single trajectory is reproducible in isolation (parity tests, soak
oracle, checkpoint resume all lean on this).
"""

from .channels import (  # noqa: F401
    ChannelError,
    KrausChannel,
    NoiseModel,
    QNoisy,
    amplitude_damping,
    dephasing,
    depolarizing,
    kraus_channel,
    traj_uniform,
)
from .trajectories import (  # noqa: F401
    TrajectoryJob,
    TrajectoryResult,
    run_trajectories,
    traj_chunk,
)
