"""Shot-parallel Monte-Carlo trajectories: the batched channel engine.

A (circuit, NoiseModel, B) job lowers into ONE window program with a
leading trajectory axis.  The stochastic part — which Kraus branch fired
at each channel-application slot of each trajectory — is sampled
host-side from the counter-based rng (:func:`channels.traj_uniform`)
into per-trajectory **runtime operand tensors**, exactly like the
parametric gate payloads in :mod:`qrack_tpu.ops.fusion`: the traced
structure is `(kind, target, controlled?)` per op, never the branch
values, so same-structure windows never retrace regardless of which
branches fired.  The whole B-trajectory batch then runs as one
``jax.vmap``-ed dispatch through the existing ``tpu.fuse.flush``
guarded site — thousands of noisy shots for one compile and one
devget-honest read.

Memory: B dense kets of width w are ``B * 16 * 2^w`` resident bytes
(route/cost.py's dense coefficient).  ``QRACK_NOISE_TRAJ_CHUNK``
overrides the trajectory chunk; by default the largest chunk that fits
:func:`route.cost.hbm_budget_bytes` is used and the batch runs as
ceil(B/chunk) dispatches (telemetry ``noise.traj.chunked``).

Windowing: by default the whole lowered stream is one program.
``QRACK_NOISE_TRAJ_WINDOW=k`` splits it into k-op windows (the parity
tests drive this at 1 and 16) with the ket planes and the trajectory
weight threaded between windows.
"""

from __future__ import annotations

import os
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import resilience as _res
from .. import telemetry as _tele
from ..config import get_config
from ..ops import fusion as fu
from ..ops import gatekernels as gk
from ..resilience import faults as _faults
from ..telemetry import roofline as _roofline
from .channels import MEASURE_DOMAIN, KrausChannel, NoiseModel, traj_uniform

# Structure-keyed program cache, sibling of fusion.PROGRAMS: emits
# compile.noise.{hit,miss,eviction}.
PROGRAMS = _tele.ProgramCache("noise", cap_env="QRACK_NOISE_CACHE_CAP",
                              default_cap=64)


def traj_window_len() -> int:
    """Ops per trajectory window; 0 (default) = whole stream as ONE
    program."""
    try:
        w = int(os.environ.get("QRACK_NOISE_TRAJ_WINDOW", "0"))
    except ValueError:
        w = 0
    return max(0, w)


def traj_chunk(width: int, trajectories: int) -> int:
    """Trajectory chunk size: ``QRACK_NOISE_TRAJ_CHUNK`` override, else
    the largest chunk whose resident batch (chunk · 16 · 2^w, the
    route/cost.py dense coefficient) fits the HBM budget."""
    env = os.environ.get("QRACK_NOISE_TRAJ_CHUNK", "")
    if env:
        try:
            return max(1, min(int(trajectories), int(env)))
        except ValueError:
            pass
    from ..route import cost as _cost

    budget = _cost.hbm_budget_bytes()
    per = float(_cost.DENSE_BYTES_PER_AMP) * float(2 ** int(width))
    fit = int(budget // per) if per > 0 else int(trajectories)
    return max(1, min(int(trajectories), fit))


# ---------------------------------------------------------------------------
# lowering: (circuit, NoiseModel) -> flat noisy op stream
# ---------------------------------------------------------------------------

class NoiseSlot:
    """One channel application in the schedule: channel `ch` on `qubit`
    at application counter `seq` (the rng coordinate)."""

    __slots__ = ("qubit", "ch", "seq")

    def __init__(self, qubit: int, ch: KrausChannel, seq: int):
        self.qubit = qubit
        self.ch = ch
        self.seq = seq


def lower_noisy(circuit, model: NoiseModel) -> List[object]:
    """Interleave the circuit's lowered gate ops with the model's
    channel slots: per QCircuitGate, its FusedOps (payload perms in
    sorted order), then one slot per touched qubit per attached channel
    — the same schedule :meth:`channels.QNoisy.run_circuit` walks, with
    `seq` numbering the slots monotonically."""
    ops: List[object] = []
    seq = 0
    for g in circuit.gates:
        ops.extend(fu.lower_gates([g]))
        for q, ch in model.slots_for((g.target,) + tuple(g.controls)):
            ops.append(NoiseSlot(q, ch, seq))
            seq += 1
    return ops


def structure_of(ops: Sequence[object]) -> Tuple:
    """Program-cache identity.  Mixed-unitary noise slots are
    structurally plain "gen" ops — which branch fired is operand data —
    while general-Kraus slots get their own "kraus" kind (they carry a
    prior operand and touch the weight)."""
    out = []
    for op in ops:
        if isinstance(op, NoiseSlot):
            out.append(("kraus" if not op.ch.unitary else "gen",
                        op.qubit, False))
        else:
            out.append((op.kind, op.target, op.cmask != 0))
    return tuple(out)


# ---------------------------------------------------------------------------
# the traced bodies
# ---------------------------------------------------------------------------

def _traj_body(n: int, structure: Tuple):
    """Single-trajectory traced body: fn(planes, weight, *operands) ->
    (planes, weight).  Gate dispatch mirrors fusion.window_fn; the
    "kraus" kind applies the raw branch, renormalizes, and accumulates
    the importance weight ‖K|ψ⟩‖²/q."""

    def fn(planes, weight, *operands):
        i = 0
        for kind, target, has_ctrl in structure:
            p = operands[i]
            i += 1
            if kind == "kraus":
                prior = operands[i]
                i += 1
                planes = gk.apply_2x2(planes, p, n, target)
                n2 = jnp.sum(planes * planes)
                # a branch can annihilate the state (e.g. amplitude
                # damping's K1 on a qubit with no |1> amplitude): the
                # trajectory is dead — weight 0, ket reset to |0...0>
                # so the rest of the schedule stays finite.  QNoisy
                # mirrors this exactly (rng parity contract).
                dead = n2 <= jnp.zeros((), dtype=n2.dtype)
                safe = jnp.where(dead, jnp.ones_like(n2), n2)
                reset = jnp.zeros_like(planes).at[0, 0].set(1)
                planes = jnp.where(
                    dead, reset,
                    planes * jax.lax.rsqrt(safe).astype(planes.dtype))
                weight = jnp.where(
                    dead, jnp.zeros_like(weight),
                    weight * (n2.astype(weight.dtype) / prior))
                continue
            if has_ctrl:
                cm = operands[i]
                cv = operands[i + 1]
                i += 2
            else:
                cm = 0
                cv = 0
            if kind == "cphase":
                comb = ((1 << target) | cm) if has_ctrl else (1 << target)
                hit = (gk.iota_for(planes) & comb) == comb
                one = jnp.ones((), planes.dtype)
                zero = jnp.zeros((), planes.dtype)
                planes = gk.cmul(jnp.where(hit, p[0], one),
                                 jnp.where(hit, p[1], zero), planes)
            elif kind == "diag":
                planes = gk.apply_diag(planes, p[0, 0], p[0, 1], p[1, 0],
                                       p[1, 1], n, 1 << target, cm, cv)
            elif kind == "inv":
                planes = gk.apply_invert(planes, p[0, 0], p[0, 1], p[1, 0],
                                         p[1, 1], n, target, cm, cv)
            else:
                planes = gk.apply_2x2(planes, p, n, target, cm, cv)
        return planes, weight

    return fn


def _traj_final(n: int, structure: Tuple):
    """Final-window traced body: runs the ops, then computes the
    per-trajectory readout on device — per-qubit P(1), the categorical
    measurement draw from uniform `u` — so only O(B·n) scalars cross to
    the host, never B·2^n amplitudes."""
    body = _traj_body(n, structure)

    def fn(planes, weight, u, *operands):
        planes, weight = body(planes, weight, *operands)
        p = planes[0] * planes[0] + planes[1] * planes[1]
        idx = gk.iota_for(planes)
        norm = jnp.sum(p)
        p1 = jnp.stack([
            jnp.sum(jnp.where(((idx >> q) & 1) == 1, p, 0.0))
            for q in range(n)]) / norm
        cdf = jnp.cumsum(p)
        s = jnp.searchsorted(cdf, u.astype(p.dtype) * cdf[-1], side="right")
        s = jnp.minimum(s, p.shape[0] - 1)
        return planes, weight, p1, s

    return fn


def _program(n: int, structure: Tuple, batch: int, dtype, final: bool):
    """One guarded vmapped program per (width, dtype, structure, chunk,
    final?) — branch payloads ride the operand vector, so every
    same-shape window is a compile.noise hit.  Dispatch goes through
    the same ``tpu.fuse.flush`` guarded site as the gate fuser."""
    key = ("traj", n, str(jnp.dtype(dtype)), structure, int(batch),
           bool(final))

    def build():
        body = _traj_final(n, structure) if final else _traj_body(n, structure)
        return _res.instrument_dispatch(
            "tpu.fuse.flush",
            _tele.instrument_jit(
                "noise.window", jax.jit(jax.vmap(body),
                                        donate_argnums=(0,))))

    return PROGRAMS.get_or_build(key, build)


# ---------------------------------------------------------------------------
# host-side branch pre-sampling (the noise.sample guarded site)
# ---------------------------------------------------------------------------

def _sample_operands(ops: Sequence[object], key: int,
                     tids: Sequence[int], dtype) -> List:
    """Materialize the runtime operand vector for one window and one
    trajectory chunk: gate payloads broadcast across the batch, noise
    slots sampled per trajectory from (key, trajectory_id, seq)."""
    directive = _faults.check("noise.sample")
    if directive:
        raise RuntimeError(f"noise.sample injected fault: {directive}")
    B = len(tids)
    out: List = []
    for op in ops:
        if isinstance(op, NoiseSlot):
            idxs = [op.ch.sample(traj_uniform(key, t, op.seq))
                    for t in tids]
            mats = np.stack([op.ch.branch_matrix(i) for i in idxs])
            out.append(jnp.asarray(
                np.stack([mats.real, mats.imag], axis=1), dtype=dtype))
            if not op.ch.unitary:
                out.append(jnp.asarray(
                    np.asarray([op.ch.priors[i] for i in idxs]),
                    dtype=jnp.float32))
            continue
        single = fu.dense_operands([op], dtype)
        for arr in single:
            out.append(jnp.broadcast_to(arr, (B,) + arr.shape))
    return out


# ---------------------------------------------------------------------------
# results
# ---------------------------------------------------------------------------

class TrajectoryResult:
    """Per-trajectory readout + the channel-averaged aggregate.

    `p1` is (B, n) per-qubit P(1), `weights` (B,) importance weights
    (all-ones for mixed-unitary models), `samples` (B,) the terminal
    measurement draw of each trajectory, `aggregate_p1` the
    weight-averaged per-qubit P(1) — the Monte-Carlo estimate of the
    channel-averaged observable.
    """

    __slots__ = ("width", "key", "trajectory_ids", "p1", "weights",
                 "samples", "chunks", "planes")

    def __init__(self, width: int, key: int, trajectory_ids, p1, weights,
                 samples, chunks: int, planes=None):
        self.width = int(width)
        self.key = int(key)
        self.trajectory_ids = np.asarray(trajectory_ids, dtype=np.int64)
        self.p1 = np.asarray(p1, dtype=np.float64)
        self.weights = np.asarray(weights, dtype=np.float64)
        self.samples = np.asarray(samples, dtype=np.int64)
        self.chunks = int(chunks)
        self.planes = planes

    @property
    def trajectories(self) -> int:
        return int(self.p1.shape[0])

    @property
    def aggregate_p1(self) -> np.ndarray:
        w = self.weights
        return (w[:, None] * self.p1).sum(axis=0) / w.sum()

    def expectation_z(self, qubit: int) -> float:
        """Channel-averaged <Z_qubit> = 1 - 2 P(1)."""
        return float(1.0 - 2.0 * self.aggregate_p1[int(qubit)])

    def to_dict(self) -> dict:
        return {
            "width": self.width,
            "key": self.key,
            "trajectory_ids": self.trajectory_ids.tolist(),
            "p1": self.p1.tolist(),
            "weights": self.weights.tolist(),
            "samples": self.samples.tolist(),
            "chunks": self.chunks,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "TrajectoryResult":
        return cls(d["width"], d["key"], d["trajectory_ids"], d["p1"],
                   d["weights"], d["samples"], d["chunks"])


# ---------------------------------------------------------------------------
# the job object (chunk loop + mid-batch checkpoint)
# ---------------------------------------------------------------------------

class TrajectoryJob:
    """Chunked execution of a trajectory batch with mid-batch
    checkpointing.

    Because every trajectory is a pure function of (key, trajectory_id),
    a snapshot needs only the finished chunks' outputs and the next
    chunk index — resuming re-derives the remaining trajectories'
    randomness from the counters and lands bit-identical to an
    uninterrupted run.
    """

    def __init__(self, circuit, model: NoiseModel, trajectories: int, *,
                 width: int, key: int = 0,
                 trajectory_ids: Optional[Sequence[int]] = None,
                 dtype=None, keep_planes: bool = False):
        self.circuit = circuit
        self.model = model
        self.width = int(width)
        self.key = int(key)
        if trajectory_ids is None:
            trajectory_ids = range(int(trajectories))
        self.tids = [int(t) for t in trajectory_ids]
        if len(self.tids) != int(trajectories):
            raise ValueError("trajectory_ids length != trajectories")
        self.dtype = dtype if dtype is not None else \
            get_config().device_real_dtype()
        self.keep_planes = bool(keep_planes)
        self.chunk = traj_chunk(self.width, len(self.tids))
        self._ops = lower_noisy(circuit, model)
        self._next = 0
        self._done: List[dict] = []
        self._planes: List[np.ndarray] = []

    # -- chunk geometry ------------------------------------------------

    @property
    def n_chunks(self) -> int:
        B = len(self.tids)
        return max(1, (B + self.chunk - 1) // self.chunk)

    def _chunk_tids(self, ci: int) -> List[int]:
        return self.tids[ci * self.chunk:(ci + 1) * self.chunk]

    @property
    def finished(self) -> bool:
        return self._next >= self.n_chunks

    # -- execution -----------------------------------------------------

    def _windows(self) -> List[List[object]]:
        w = traj_window_len()
        if w <= 0 or w >= len(self._ops):
            return [list(self._ops)]
        return [list(self._ops[i:i + w])
                for i in range(0, len(self._ops), w)]

    def step(self) -> None:
        """Run the next trajectory chunk: one vmapped dispatch per
        window, devget-honest read of the final outputs."""
        if self.finished:
            return
        tids = self._chunk_tids(self._next)
        C = len(tids)
        n = self.width
        esize = jnp.dtype(self.dtype).itemsize
        planes_np = np.zeros((C, 2, 1 << n), dtype=np.dtype(str(jnp.dtype(
            self.dtype))) if jnp.dtype(self.dtype) != jnp.bfloat16
            else np.float32)
        planes_np[:, 0, 0] = 1.0
        planes = jnp.asarray(planes_np, dtype=self.dtype)
        weight = jnp.ones((C,), dtype=jnp.float32)
        windows = self._windows()
        u = jnp.asarray(
            [traj_uniform(self.key, t, 0, domain=MEASURE_DOMAIN)
             for t in tids], dtype=jnp.float32)
        for wi, ops in enumerate(windows):
            struct = structure_of(ops)
            operands = _sample_operands(ops, self.key, tids, self.dtype)
            final = wi == len(windows) - 1
            prog = _program(n, struct, C, self.dtype, final)
            if final:
                planes, weight, p1, samp = prog(planes, weight, u, *operands)
            else:
                planes, weight = prog(planes, weight, *operands)
            if _tele._ENABLED:
                _tele.inc("noise.traj.windows")
            _roofline.note_bytes(
                "tpu.fuse.flush",
                len(ops) * C * _roofline.plane_pass_bytes(n, esize))
        # devget-honest settle: host reads are the only trustworthy
        # completion signal over the relay (CLAUDE.md timing honesty)
        p1_h = jax.device_get(p1)
        self._done.append({
            "tids": tids,
            "p1": np.asarray(p1_h, dtype=np.float64),
            "weights": np.asarray(jax.device_get(weight), dtype=np.float64),
            "samples": np.asarray(jax.device_get(samp), dtype=np.int64),
        })
        if self.keep_planes:
            self._planes.append(np.asarray(
                jax.device_get(planes), dtype=np.float64))
        self._next += 1

    def run(self) -> "TrajectoryJob":
        while not self.finished:
            self.step()
        return self

    # -- checkpoint / resume -------------------------------------------

    def snapshot(self) -> dict:
        """JSON-serializable mid-batch state: finished chunk outputs +
        the resume cursor.  The rng needs no saved position — it is the
        (key, trajectory_id, seq) counters."""
        return {
            "kind": "noise.trajectories",
            "width": self.width,
            "key": self.key,
            "trajectory_ids": list(self.tids),
            "chunk": self.chunk,
            "next": self._next,
            "done": [{
                "tids": list(d["tids"]),
                "p1": d["p1"].tolist(),
                "weights": d["weights"].tolist(),
                "samples": d["samples"].tolist(),
            } for d in self._done],
        }

    @classmethod
    def resume(cls, circuit, model: NoiseModel, snap: dict,
               dtype=None) -> "TrajectoryJob":
        job = cls(circuit, model, len(snap["trajectory_ids"]),
                  width=snap["width"], key=snap["key"],
                  trajectory_ids=snap["trajectory_ids"], dtype=dtype)
        job.chunk = int(snap["chunk"])
        job._next = int(snap["next"])
        job._done = [{
            "tids": [int(t) for t in d["tids"]],
            "p1": np.asarray(d["p1"], dtype=np.float64),
            "weights": np.asarray(d["weights"], dtype=np.float64),
            "samples": np.asarray(d["samples"], dtype=np.int64),
        } for d in snap["done"]]
        return job

    # -- assembly ------------------------------------------------------

    def result(self) -> TrajectoryResult:
        if not self.finished:
            raise RuntimeError("trajectory job not finished")
        tids = [t for d in self._done for t in d["tids"]]
        p1 = np.concatenate([d["p1"] for d in self._done]) if self._done \
            else np.zeros((0, self.width))
        weights = np.concatenate([d["weights"] for d in self._done]) \
            if self._done else np.zeros((0,))
        samples = np.concatenate([d["samples"] for d in self._done]) \
            if self._done else np.zeros((0,), dtype=np.int64)
        planes = np.concatenate(self._planes) if self._planes else None
        return TrajectoryResult(self.width, self.key, tids, p1, weights,
                                samples, self.n_chunks, planes=planes)


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def run_trajectories(circuit, model: NoiseModel, trajectories: int, *,
                     width: Optional[int] = None, key: int = 0,
                     trajectory_ids: Optional[Sequence[int]] = None,
                     dtype=None, keep_planes: bool = False
                     ) -> TrajectoryResult:
    """Run B noisy Monte-Carlo trajectories of `circuit` under `model`
    as vmapped batch dispatches (docs/NOISE.md).

    Telemetry: ``noise.traj.batches/trajectories/chunks/windows/slots``
    counters, ``noise.traj.chunk_size`` gauge, ``noise.traj.wall_s``
    histogram, ``noise.traj.rate`` gauge (trajectories/s, devget-honest
    wall); compile behavior under ``compile.noise.*``.
    """
    if width is None:
        width = max((max((g.target,) + tuple(g.controls))
                     for g in circuit.gates), default=0) + 1
    B = int(trajectories)
    if B <= 0:
        raise ValueError("trajectories must be positive")
    t0 = time.perf_counter()
    job = TrajectoryJob(circuit, model, B, width=width, key=key,
                        trajectory_ids=trajectory_ids, dtype=dtype,
                        keep_planes=keep_planes)
    job.run()
    wall = time.perf_counter() - t0
    if _tele._ENABLED:
        _tele.inc("noise.traj.batches")
        _tele.inc("noise.traj.trajectories", float(B))
        _tele.inc("noise.traj.chunks", float(job.n_chunks))
        if job.n_chunks > 1:
            _tele.inc("noise.traj.chunked")
        nslots = sum(1 for op in job._ops if isinstance(op, NoiseSlot))
        _tele.inc("noise.traj.slots", float(nslots * B))
        _tele.gauge("noise.traj.chunk_size", job.chunk)
        _tele.observe("noise.traj.wall_s", wall)
        if wall > 0:
            _tele.gauge("noise.traj.rate", B / wall)
    return job.result()
