"""Stack factory: runtime-composable simulator layer assembly.

Re-design of the reference factory (reference: include/qfactory.hpp:49
CreateQuantumInterface — recursive layer construction from a type
vector; :265 CreateArrangedLayersFull — boolean layer toggles; enum
QInterfaceEngine include/qinterface.hpp:37-132, QINTERFACE_OPTIMAL
:114-131). Layer names here:

  "tensor_network"     QTensorNetwork (circuit buffering + light cone)
  "noisy"              QInterfaceNoisy wrapper
  "unit" / "unit_multi" QUnit / QUnitMulti Schmidt factoring
  "stabilizer_hybrid"  Clifford tableau until forced off
  "stabilizer"         bare CHP tableau (Clifford-only)
  "unit_clifford"      QUnit factoring over per-subsystem tableaus
  "bdt" / "bdt_hybrid" QBdt decision tree / auto-switching hybrid
  "bdt_attached"       QBdt with dense leaf kets under the tree
                       (attached_qubits kwarg; default n//2 or
                       QRACK_QBDT_ATTACH_QB)
  "pager"              QPager sharded dense engine over the device mesh
  "hybrid"             QHybrid CPU<->TPU<->pager width switching
  "tpu"                QEngineTPU single-device dense engine
  "cpu"                QEngineCPU host oracle
  "sparse"             QEngineSparse map-style sparse state vector
  "turboquant"         QEngineTurboQuant block-compressed resident ket
  "turboquant_pager"   QPagerTurboQuant compressed ket sharded over the
                       device mesh (compressed ICI pair exchange)
  "route"              QRouted lazy per-job stack selection: the first
                       submitted QCircuit picks the representation
                       (route/, docs/ROUTING.md; QRACK_ROUTE pins it)
  "lightcone"          QLightCone circuit buffering: reads build
                       cone-width kets through the routed ladder, never
                       the full-width ket (lightcone/, docs/LIGHTCONE.md)

create_quantum_interface(layers, n) composes them top-down; OPTIMAL is
["unit", "stabilizer_hybrid", "hybrid"] — the reference's production
stack shape with the TPU-native dense bottom."""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Union

from . import resilience as _res
from . import telemetry as _tele

OPTIMAL = ("unit", "stabilizer_hybrid", "hybrid")
OPTIMAL_MULTI = ("unit_multi", "stabilizer_hybrid", "hybrid")

_TERMINAL = {"cpu", "tpu", "pager", "hybrid", "stabilizer", "bdt",
             "bdt_attached", "unit_clifford", "sparse", "turboquant",
             "turboquant_pager", "route", "lightcone"}


def _counted(name: str, fn: Callable) -> Callable:
    """Count stack instantiations per layer (telemetry: factory.create.*).
    The wrapper only runs at construction time, never per gate."""
    def make(n, **kw):
        if _tele._ENABLED:
            _tele.inc(f"factory.create.{name}")
        return fn(n, **kw)
    return make


# terminals that dispatch over the tunnel without their own failover
# logic (QHybrid fails over in place; cpu/stabilizer/... never dispatch)
_ACCEL_TERMINALS = {"tpu", "pager", "turboquant", "turboquant_pager"}


def touches_accelerator(layers: Union[str, Sequence[str]]) -> bool:
    """True when a layer spec's terminal dispatches over the TPU tunnel
    (directly, or via QHybrid's width switch).  The serving layer uses
    this to classify sessions for breaker-aware load shedding before an
    engine exists; a live session is classified by its actual engine."""
    if isinstance(layers, str):
        if layers in ("optimal", "optimal_multi"):
            return True  # OPTIMAL terminates in "hybrid"
        layers = (layers,)
    term = layers[-1] if layers else ""
    return term in _ACCEL_TERMINALS or term == "hybrid"


def _maybe_resilient(name: str, fn: Callable) -> Callable:
    """Wrap a bare accelerator terminal in ResilientEngine when the
    resilience layer is active, so a factory-built stack gets the same
    TPU→CPU degradation QHybrid provides (construction-time failures
    included).  _ACTIVE is re-read per construction: enabling resilience
    after import still takes effect."""
    if name not in _ACCEL_TERMINALS:
        return fn

    def make(n, **kw):
        if not _res._ACTIVE:
            return fn(n, **kw)
        from .resilience.failover import ResilientEngine

        return ResilientEngine.build(fn, n, **kw)

    return make


def _terminal_factory(name: str, **opts) -> Callable:
    if name == "cpu":
        from .engines.cpu import QEngineCPU

        return lambda n, **kw: QEngineCPU(n, **{**opts, **kw})
    if name == "tpu":
        from .engines.tpu import QEngineTPU

        return lambda n, **kw: QEngineTPU(n, **{**opts, **kw})
    if name == "pager":
        from .parallel.pager import QPager

        return lambda n, **kw: QPager(n, **{**opts, **kw})
    if name == "hybrid":
        from .engines.hybrid import QHybrid

        return lambda n, **kw: QHybrid(n, **{**opts, **kw})
    if name == "stabilizer":
        from .layers.stabilizer import QStabilizer

        return lambda n, **kw: QStabilizer(n, **{**opts, **kw})
    if name == "bdt":
        from .layers.qbdt import QBdt

        return lambda n, **kw: QBdt(n, **{**opts, **kw})
    if name == "bdt_attached":
        import os

        from .layers.qbdt import QBdt

        def mk_attached(n, **kw):
            kw = {**opts, **kw}
            if "attached_qubits" not in kw:
                kw["attached_qubits"] = int(os.environ.get(
                    "QRACK_QBDT_ATTACH_QB", str(n // 2)))
            return QBdt(n, **kw)

        return mk_attached
    if name == "sparse":
        from .engines.sparse import QEngineSparse

        return lambda n, **kw: QEngineSparse(n, **{**opts, **kw})
    if name == "turboquant":
        from .engines.turboquant import QEngineTurboQuant

        return lambda n, **kw: QEngineTurboQuant(n, **{**opts, **kw})
    if name == "turboquant_pager":
        from .parallel.turboquant_pager import QPagerTurboQuant

        return lambda n, **kw: QPagerTurboQuant(n, **{**opts, **kw})
    if name == "unit_clifford":
        from .layers.qunitclifford import QUnitClifford

        return lambda n, **kw: QUnitClifford(n, **{**opts, **kw})
    if name == "route":
        # pseudo-terminal: construction is free (no engine exists until
        # routing picks one), and the chosen stack is built through
        # this same factory, so resilience wrapping and per-layer
        # creation counters apply to whatever the router instantiates
        from .route.router import QRouted

        return lambda n, **kw: QRouted(n, **{**opts, **kw})
    if name == "lightcone":
        # pseudo-terminal like "route": gates buffer host-side and the
        # cone-width stacks built at read time come back through this
        # factory (via the "route" spec), so resilience wrapping and
        # creation counters apply to whatever each cone builds
        from .lightcone.engine import QLightCone

        return lambda n, **kw: QLightCone(n, **{**opts, **kw})
    raise ValueError(f"unknown terminal layer {name!r}")


def build_factory(layers: Sequence[str], **opts) -> Callable:
    """Compose a constructor fn(n, **kw) from a top-down layer list
    (reference: CreateQuantumInterface recursion, qfactory.hpp:189-258)."""
    if not layers:
        raise ValueError("empty layer list")
    head, rest = layers[0], layers[1:]
    if head in _TERMINAL:
        if rest:
            raise ValueError(f"terminal layer {head!r} must be last")
        return _counted(head, _maybe_resilient(head, _terminal_factory(head, **opts)))
    below = build_factory(rest, **opts) if rest else None

    if head == "unit":
        from .layers.qunit import QUnit

        return _counted(head, lambda n, **kw: QUnit(n, unit_factory=below, **kw))
    if head == "unit_multi":
        from .layers.qunitmulti import QUnitMulti

        return _counted(head, lambda n, **kw: QUnitMulti(n, unit_factory=below, **kw))
    if head == "stabilizer_hybrid":
        from .layers.stabilizerhybrid import QStabilizerHybrid

        return _counted(head, lambda n, **kw: QStabilizerHybrid(n, engine_factory=below, **kw))
    if head == "tensor_network":
        from .layers.qtensornetwork import QTensorNetwork

        return _counted(head, lambda n, **kw: QTensorNetwork(n, stack_factory=below, **kw))
    if head == "bdt_hybrid":
        from .layers.qbdthybrid import QBdtHybrid

        return _counted(head, lambda n, **kw: QBdtHybrid(n, engine_factory=below, **kw))
    if head == "noisy":
        noise = opts.get("noise")
        if below is None:
            # terminal form: the trajectory-rng QNoisy engine over a CPU
            # oracle — branch choices come from (key, trajectory_id,
            # app_seq) counters, not the engine's sequential rng stream
            # (noise/channels.py, docs/NOISE.md)
            from .noise.channels import QNoisy

            model = opts.get("model")
            return _counted(head, lambda n, **kw: QNoisy(
                n, model=model, noise=noise, **kw))
        from .layers.noisy import QInterfaceNoisy

        return _counted(head, lambda n, **kw: QInterfaceNoisy(
            n, inner_factory=below, noise=noise, **kw))
    raise ValueError(f"unknown layer {head!r}")


def create_quantum_interface(layers: Union[str, Sequence[str]], qubit_count: int,
                             init_state: int = 0, **kwargs):
    """Build a simulator stack (reference: CreateQuantumInterface,
    include/qfactory.hpp:49).

    `layers` may be "optimal", "optimal_multi", a single layer name, or a
    top-down sequence, e.g. ["tensor_network", "unit",
    "stabilizer_hybrid", "hybrid"]."""
    if isinstance(layers, str):
        if layers == "optimal":
            layers = OPTIMAL
        elif layers == "optimal_multi":
            layers = OPTIMAL_MULTI
        else:
            layers = (layers,)
    opts = {k: kwargs.pop(k) for k in ("noise", "model", "devices",
                                       "n_pages", "dtype")
            if k in kwargs}
    if _tele._ENABLED:
        _tele.inc("factory.create_interface")
    factory = build_factory(tuple(layers), **opts)
    return factory(qubit_count, init_state=init_state, **kwargs)


def create_arranged_layers_full(nw: bool = False, md: bool = False, sd: bool = True,
                                sh: bool = True, bdt: bool = False, pg: bool = True,
                                tn: bool = False, hy: bool = True, oc: bool = True,
                                qubit_count: int = 1, **kwargs):
    """Boolean layer toggles matching the reference's pinvoke `init`
    signature (reference: include/qfactory.hpp:265
    CreateArrangedLayersFull; pinvoke init_count_type
    include/pinvoke_api.hpp:42): nw=noisy wrapper, md=multi-device QUnit,
    sd=Schmidt decomposition (QUnit), sh=stabilizer hybrid, bdt=binary
    decision tree hybrid, pg=paging, tn=tensor network, hy=hybrid,
    oc="OpenCL"→accelerator (TPU here)."""
    layers: List[str] = []
    if nw:
        layers.append("noisy")
    if tn:
        layers.append("tensor_network")
    if sd:
        layers.append("unit_multi" if md else "unit")
    if sh:
        layers.append("stabilizer_hybrid")
    if bdt:
        layers.append("bdt_hybrid")
    if hy:
        layers.append("hybrid")
    elif pg and oc:
        layers.append("pager")
    elif oc:
        layers.append("tpu")
    else:
        layers.append("cpu")
    return create_quantum_interface(layers, qubit_count, **kwargs)
