"""Persistent warm start: compile once per machine, not once per process.

Two layers, both rooted in the checkpoint directory:

* :func:`enable_warm_start` points JAX's persistent compilation cache
  at ``<dir>/xla_cache`` with the thresholds zeroed, so every XLA
  executable this process compiles — fused circuit programs, vmapped
  batch programs, gate kernels — lands on disk and a later process
  deserializes instead of recompiling.
* :class:`ProgramManifest` records every circuit shape the serving
  batcher compiles (digest-keyed by ``QCircuit.shape_key`` + batch
  size, the exact program-cache identity) together with the circuit
  itself in a container file.  A fresh process calls :meth:`prewarm`
  BEFORE taking traffic: each recorded circuit re-traces and re-jits —
  cheap, because the XLA cache supplies the compiled binary — so the
  first real job is a program-cache hit instead of a cold compile.

Nothing here imports jax at module load; both hooks are wired lazily
by QrackService when QRACK_SERVE_CHECKPOINT_DIR is set.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Optional

from .. import telemetry as _tele
from .container import CheckpointCorrupt, CheckpointError
from .store import load_circuit, save_circuit

_ENABLED_DIR: Optional[str] = None


def enable_warm_start(cache_dir: str) -> str:
    """Point the JAX persistent compilation cache at `cache_dir` (with
    the size/time admission thresholds disabled — serving programs are
    many and individually small).  Idempotent; returns the directory."""
    global _ENABLED_DIR
    cache_dir = str(cache_dir)
    if _ENABLED_DIR == cache_dir:
        return cache_dir
    os.makedirs(cache_dir, exist_ok=True)
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    _ENABLED_DIR = cache_dir
    if _tele._ENABLED:
        _tele.event("checkpoint.warmstart.enabled", dir=cache_dir)
    return cache_dir


class ProgramManifest:
    """Digest-keyed record of every (circuit, width, batch) program the
    batcher compiled, durable enough to pre-trace them next boot."""

    def __init__(self, root: str):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        self._index_path = os.path.join(self.root, "programs.json")
        try:
            with open(self._index_path) as f:
                self._index = json.load(f)
        except (OSError, json.JSONDecodeError):
            self._index = {}

    @staticmethod
    def _key(shape_key, batch: int) -> str:
        n, bucket, digest = shape_key
        return f"{n}:{batch}:{digest}"

    def record(self, circuit, n: int, batch: int) -> None:
        """Idempotent: a known (shape, batch) is a no-op, so the hot
        batcher path costs one dict probe.  Best-effort: the record is
        advisory warm-start metadata, so a store that has vanished out
        from under the manifest (dir removed after its service closed —
        the batcher module global outlives any one service) must never
        fail the dispatch it rides on."""
        shape = circuit.shape_key(n)
        key = self._key(shape, batch)
        if key in self._index:
            return
        # circuit files are keyed by the structure digest alone: the
        # same circuit served at several widths/batches is stored once
        digest = shape[2]
        path = os.path.join(self.root, f"{digest}.qckpt")
        try:
            if not os.path.exists(path):
                save_circuit(path, circuit)
            self._index[key] = {"width": int(n), "batch": int(batch),
                                "circuit": os.path.basename(path)}
            self._write_index()
        except OSError:
            return
        if _tele._ENABLED:
            _tele.inc("checkpoint.warmstart.recorded")

    def _write_index(self) -> None:
        fd, tmp = tempfile.mkstemp(prefix=".programs-", suffix=".tmp",
                                   dir=self.root)
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(self._index, f, sort_keys=True)
            os.replace(tmp, self._index_path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def __len__(self) -> int:
        return len(self._index)

    def prewarm(self, limit: Optional[int] = None) -> int:
        """Re-trace + re-compile every recorded program and leave it hot
        in the batcher's program cache AND jit's dispatch cache.  With
        the persistent XLA cache enabled the compile step is a disk
        read; returns how many programs were warmed.  Damaged circuit
        files are dropped from the manifest, not fatal."""
        import jax.numpy as jnp

        from ..config import get_config
        from ..serve import batcher as _batcher

        dtype = get_config().device_real_dtype()
        warmed = 0
        dead = []
        for key, rec in list(self._index.items()):
            if limit is not None and warmed >= limit:
                break
            path = os.path.join(self.root, rec["circuit"])
            try:
                circ, _ = load_circuit(path)
            except (CheckpointCorrupt, CheckpointError, OSError):
                dead.append(key)
                continue
            n, batch = int(rec["width"]), int(rec["batch"])
            fn = _batcher.batch_program(circ, n, batch)
            # jax.jit is lazy — building the wrapper traces nothing.
            # Run it once on dummy |0..0> plane lanes (same pytree shape
            # and dtype run_batch dispatches) so trace + compile happen
            # HERE, not under the first tenant's job.
            plane = jnp.zeros((2, 1 << n), dtype=dtype).at[0, 0].set(1.0)
            _batcher.sync_scalar(fn([plane] * batch))
            warmed += 1
        for key in dead:
            self._index.pop(key, None)
        if dead:
            self._write_index()
        if warmed and _tele._ENABLED:
            _tele.inc("checkpoint.warmstart.prewarmed", warmed)
        return warmed
