"""Durable state: snapshots, spill/restore, crash recovery, warm start.

Four pieces (docs/CHECKPOINT.md):

* **container** — the versioned, checksummed npz+manifest file format
  with atomic writes and corruption detection.
* **registry** — ``save_state(obj, path)`` / ``load_state(path,
  into=None)`` over every simulator representation, rng streams
  included.
* **store** — the bounded on-disk session store backing serve's idle
  spill, crash-recovery manifest, and pending-job journal.
* **warmstart** — JAX persistent compilation cache wiring + the
  digest-keyed program manifest that lets a fresh serving process
  pre-trace previously served circuit shapes.

This package is NOT imported by ``import qrack_tpu`` — the library
path costs nothing unless checkpointing is enabled (serve wires it
lazily behind QRACK_SERVE_CHECKPOINT_DIR).
"""

from __future__ import annotations

from .container import (FORMAT, VERSION, CheckpointCorrupt, CheckpointError,
                        CheckpointVersionError, load_container,
                        save_container)
from .registry import (build, capture, load_snapshot, load_state,
                       restore_into, save_state)

__all__ = [
    "FORMAT", "VERSION",
    "CheckpointError", "CheckpointCorrupt", "CheckpointVersionError",
    "save_container", "load_container",
    "capture", "restore_into", "build",
    "save_state", "load_state", "load_snapshot",
    "CheckpointStore", "StoreLeaseHeld", "StoreLockTimeout",
    "enable_warm_start", "ProgramManifest",
]


def __getattr__(name):
    # store/warmstart stay un-imported until first touched
    if name in ("CheckpointStore", "StoreLeaseHeld", "StoreLockTimeout"):
        from . import store

        return getattr(store, name)
    if name in ("enable_warm_start", "ProgramManifest"):
        from . import warmstart

        return getattr(warmstart, name)
    raise AttributeError(name)
