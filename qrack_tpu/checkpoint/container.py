"""The durable container format: checksummed npz + embedded manifest.

A checkpoint file is a VALID npz archive whose last member,
``__qckpt__``, is a uint8-encoded JSON manifest::

    {"format": "qrack-checkpoint", "version": 1, "kind": "<payload kind>",
     "meta": {...},                      # JSON-able payload description
     "payload": {key: {"sha256", "dtype", "shape"}, ...}}

Durability discipline:

* **Atomic writes** — the archive is written to a same-directory temp
  file, fsync'd, then ``os.replace``d into place, so a reader never
  observes a half-written file under the final name and a crash
  mid-save leaves the previous checkpoint intact.
* **Corruption detection** — every payload array carries a sha256 over
  its dtype/shape/raw bytes; a truncated archive (torn write), a
  bit-flipped member, a key-set mismatch, or a missing manifest raises
  :class:`CheckpointCorrupt` instead of loading garbage.
* **Versioning** — files newer than this reader raise
  :class:`CheckpointVersionError` (forward-incompatible by policy, see
  docs/CHECKPOINT.md); bare legacy npz files (no manifest) still load
  through ``legacy_ok=True`` so pre-container archives stay readable.

Save/load are guarded fault sites ("checkpoint.save" /
"checkpoint.restore", resilience/faults.py) — the ``torn-write`` kind
truncates the payload mid-write so tests can prove the loader rejects
the result.  Durations reported to telemetry are host-complete by
construction: every array is materialized on the host (``np.asarray``
forces the device read) before the archive bytes are hashed/written.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import time
import zipfile
import zlib
from typing import Dict, Optional, Tuple

import numpy as np

from .. import telemetry as _tele

FORMAT = "qrack-checkpoint"
VERSION = 1
MANIFEST_KEY = "__qckpt__"


class CheckpointError(RuntimeError):
    """Base class for checkpoint failures."""


class CheckpointCorrupt(CheckpointError):
    """The file is not a well-formed checkpoint (truncation, checksum
    mismatch, damaged archive, missing/garbled manifest)."""


class CheckpointVersionError(CheckpointError):
    """The file was written by a NEWER format version than this reader
    understands."""


def _sha256(arr: np.ndarray) -> str:
    a = np.ascontiguousarray(arr)
    h = hashlib.sha256()
    h.update(str(a.dtype).encode())
    h.update(repr(tuple(a.shape)).encode())
    h.update(a.tobytes())
    return h.hexdigest()


def _fault_directive(site: str) -> Optional[str]:
    """Consult the resilience fault injector at `site` — only when the
    resilience layer is active, so the default save/load path never
    imports it.  Raise-type kinds propagate; directive strings other
    than "torn-write" are meaningless here and ignored by callers."""
    import sys

    res = sys.modules.get("qrack_tpu.resilience")
    if res is None or not getattr(res, "_ACTIVE", False):
        return None
    from ..resilience import faults as _faults

    return _faults.check(site)


def save_container(path: str, arrays: Dict[str, np.ndarray],
                   meta: Optional[dict] = None, kind: str = "raw") -> int:
    """Atomically write `arrays` + manifest to `path`; returns the final
    file size in bytes.  Array keys must not collide with the manifest
    member."""
    t0 = time.perf_counter()
    directive = _fault_directive("checkpoint.save")
    host: Dict[str, np.ndarray] = {}
    payload: Dict[str, dict] = {}
    for key, arr in arrays.items():
        if key.startswith("__"):
            raise CheckpointError(f"reserved array key {key!r}")
        a = np.ascontiguousarray(np.asarray(arr))
        host[key] = a
        payload[key] = {"sha256": _sha256(a), "dtype": str(a.dtype),
                        "shape": list(a.shape)}
    manifest = {"format": FORMAT, "version": VERSION, "kind": kind,
                "meta": meta or {}, "payload": payload}
    mbytes = np.frombuffer(
        json.dumps(manifest, sort_keys=True).encode(), dtype=np.uint8)
    path = str(path)
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(prefix=".qckpt-", suffix=".tmp", dir=d)
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez_compressed(f, **host, **{MANIFEST_KEY: mbytes})
            f.flush()
            os.fsync(f.fileno())
        if directive == "torn-write":
            # model a power cut that committed the rename but lost
            # trailing data blocks: truncate mid-payload, then land it
            size = os.path.getsize(tmp)
            with open(tmp, "r+b") as f:
                f.truncate(max(1, (size * 3) // 5))
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    nbytes = os.path.getsize(path)
    if _tele._ENABLED:
        _tele.inc("checkpoint.save")
        _tele.inc("checkpoint.save.bytes", nbytes)
        _tele.observe("checkpoint.save", time.perf_counter() - t0)
    return nbytes


def peek_meta(path: str) -> Tuple[Optional[str], dict]:
    """Read ONLY a container's ``(kind, meta)`` — the manifest member is
    decompressed but no array payload is touched or checksummed.  The
    recovery path uses this to read snapshot bookkeeping (e.g. the
    ``wal_high`` a state container carries) without paying a full state
    load for sessions it may not even adopt."""
    path = str(path)
    try:
        with np.load(path, allow_pickle=False) as z:
            if MANIFEST_KEY not in z.files:
                raise CheckpointCorrupt(
                    f"{path}: no {MANIFEST_KEY} member — not a checkpoint "
                    "container")
            try:
                manifest = json.loads(bytes(z[MANIFEST_KEY].tobytes()))
            except (UnicodeDecodeError, json.JSONDecodeError) as e:
                raise CheckpointCorrupt(f"{path}: garbled manifest: {e}")
    except (zipfile.BadZipFile, zlib.error, EOFError) as e:
        raise CheckpointCorrupt(f"{path}: damaged archive: {e}") from None
    except ValueError as e:
        raise CheckpointCorrupt(f"{path}: damaged archive member: {e}"
                                ) from None
    if manifest.get("format") != FORMAT:
        raise CheckpointCorrupt(
            f"{path}: wrong format tag {manifest.get('format')!r}")
    return manifest.get("kind"), manifest.get("meta", {})


def load_container(path: str, expect_kind: Optional[str] = None,
                   legacy_ok: bool = False
                   ) -> Tuple[Optional[str], dict, Dict[str, np.ndarray]]:
    """Read and verify a container; returns ``(kind, meta, arrays)``.

    With ``legacy_ok`` a bare npz (no manifest member) loads unverified
    as ``(None, {}, arrays)`` — the compatibility path for pre-container
    archives.  Everything else malformed raises CheckpointCorrupt; a
    newer format version raises CheckpointVersionError."""
    t0 = time.perf_counter()
    directive = _fault_directive("checkpoint.restore")
    del directive  # only raise-type kinds are meaningful on the read path
    path = str(path)
    try:
        with np.load(path, allow_pickle=False) as z:
            names = set(z.files)
            if MANIFEST_KEY not in names:
                if legacy_ok:
                    return None, {}, {k: z[k] for k in z.files}
                raise CheckpointCorrupt(
                    f"{path}: no {MANIFEST_KEY} member — not a checkpoint "
                    "container")
            try:
                manifest = json.loads(bytes(z[MANIFEST_KEY].tobytes()))
            except (UnicodeDecodeError, json.JSONDecodeError) as e:
                raise CheckpointCorrupt(f"{path}: garbled manifest: {e}")
            if manifest.get("format") != FORMAT:
                raise CheckpointCorrupt(
                    f"{path}: wrong format tag {manifest.get('format')!r}")
            version = int(manifest.get("version", 0))
            if version > VERSION:
                raise CheckpointVersionError(
                    f"{path}: format version {version} is newer than this "
                    f"reader (supports <= {VERSION})")
            payload = manifest.get("payload", {})
            if set(payload) != names - {MANIFEST_KEY}:
                raise CheckpointCorrupt(
                    f"{path}: archive members do not match the manifest "
                    "payload listing")
            arrays: Dict[str, np.ndarray] = {}
            for key, spec in payload.items():
                a = z[key]
                if _sha256(a) != spec["sha256"]:
                    raise CheckpointCorrupt(
                        f"{path}: checksum mismatch on array {key!r}")
                arrays[key] = a
    except (zipfile.BadZipFile, zlib.error, EOFError) as e:
        raise CheckpointCorrupt(f"{path}: damaged archive: {e}") from None
    except ValueError as e:
        # np.load raises ValueError for truncated/garbled .npy members
        raise CheckpointCorrupt(f"{path}: damaged archive member: {e}"
                                ) from None
    kind = manifest.get("kind")
    if expect_kind is not None and kind != expect_kind:
        raise CheckpointError(
            f"{path}: holds {kind!r}, expected {expect_kind!r}")
    nbytes = os.path.getsize(path)
    if _tele._ENABLED:
        _tele.inc("checkpoint.restore")
        _tele.inc("checkpoint.restore.bytes", nbytes)
        _tele.observe("checkpoint.restore", time.perf_counter() - t0)
    return kind, manifest.get("meta", {}), arrays
