"""CheckpointStore: the bounded on-disk session store behind serving.

One directory owns everything a serving process needs to survive an
idle eviction or an outright crash:

    <root>/manifest.json        live-session manifest (atomic rewrite)
    <root>/sessions/<sid>.qckpt spilled / checkpointed session state
    <root>/wal/<seq>-<sid>.qckpt pending-job journal (one circuit each)

* **Spill/restore** — SessionManager's idle evictor hands the engine
  here instead of discarding it; the state container (registry.py)
  lands under ``sessions/`` and the session keeps only its manifest
  entry until the next job faults it back in (restore-INTO a fresh
  factory-built stack, so wiring closures survive).
* **Crash recovery** — the manifest records every live session's
  constructor recipe (width/layers/seed/engine kwargs) the moment it is
  created, not just when it is spilled; QrackService(recover=True)
  replays it into a fresh process and re-runs any journaled jobs.
* **Bounded** — ``max_bytes`` caps the on-disk footprint; oldest
  state files evict first, EXCEPT those of currently-spilled live
  sessions (``protected_sids``, wired by SessionManager): deleting one
  of those would strand a session that can no longer be faulted back
  in.  Checkpoint snapshots of resident sessions are fair game — the
  live engine still holds the state.  The current footprint is
  exported as the ``checkpoint.store.bytes`` gauge.

Within one process, all mutation happens on the serve executor thread
(the same single-owner discipline as every other engine touch).  ACROSS
processes the store is a shared migration plane (docs/ELASTICITY.md):
N services may point at one root, so

* the manifest is **merge-on-write** under an ``flock`` on
  ``<root>/.store.lock`` — each process only overlays the sessions it
  OWNS (created/adopted here, tracked in ``_owned``) onto what is on
  disk, and only deletes sids it explicitly unregistered
  (``_dropped``), so two processes' manifests never clobber each other;
* ``recover=True`` is gated by an **ownership lease** recorded in the
  manifest (:meth:`acquire_lease`): exactly one process may replay the
  WAL.  Liveness is pid-based on the same host (a kill -9'd owner frees
  the lease instantly) with a TTL fallback across hosts
  (``QRACK_CKPT_LEASE_TTL_S``, default 300 s).
"""

from __future__ import annotations

import fcntl
import json
import os
import socket
import tempfile
import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from .. import telemetry as _tele
from .container import (CheckpointCorrupt, CheckpointError, load_container,
                        peek_meta, save_container)
from .registry import load_state, save_state

MANIFEST_VERSION = 1
CIRCUIT_KIND = "qrack-circuit"
PREFIX_KIND = "qrack-prefix"
DEFAULT_LEASE_TTL_S = 300.0
DEFAULT_LOCK_TIMEOUT_S = 30.0
ACKS_MAX_BYTES = 1 << 20  # settled-tag log rotates past this


class StoreLeaseHeld(CheckpointError):
    """Another live process holds this store's recovery lease."""


class StoreLockTimeout(CheckpointError):
    """.store.lock was held past QRACK_CKPT_LOCK_TIMEOUT_S.

    A peer wedged mid-manifest-write (SIGSTOP, a hung device read under
    its flock, a dead NFS client) must not block a healthy worker's
    save/register forever — the caller gets this typed error after the
    timeout and decides (the fleet supervisor treats it like any other
    worker fault; a library caller can retry)."""

    def __init__(self, path: str, waited_s: float):
        self.path = path
        self.waited_s = waited_s
        super().__init__(
            f"{path}: lock not acquired after {waited_s:.1f}s "
            "(QRACK_CKPT_LOCK_TIMEOUT_S) — a peer is wedged holding it")


# -- circuit <-> container (WAL entries + warm-start program manifest) --


def circuit_payload(circuit) -> Tuple[dict, Dict[str, np.ndarray]]:
    """(meta, arrays) capturing a QCircuit exactly: per-gate payload
    stacks keyed ``g<i>`` with targets/controls/perms in meta."""
    meta_gates = []
    arrays: Dict[str, np.ndarray] = {}
    for i, g in enumerate(circuit.gates):
        perms = sorted(g.payloads)
        meta_gates.append({"target": int(g.target),
                           "controls": [int(c) for c in g.controls],
                           "perms": [int(p) for p in perms]})
        arrays[f"g{i}"] = np.stack(
            [np.asarray(g.payloads[p], dtype=np.complex128) for p in perms])
    return {"n": int(circuit.qubit_count), "gates": meta_gates}, arrays


def circuit_from_payload(meta: dict, arrays: Dict[str, np.ndarray]):
    from ..layers.qcircuit import QCircuit, QCircuitGate

    circ = QCircuit(int(meta["n"]))
    for i, gm in enumerate(meta["gates"]):
        stack = np.asarray(arrays[f"g{i}"], dtype=np.complex128)
        payloads = {int(p): stack[j] for j, p in enumerate(gm["perms"])}
        # bypass AppendGate: the journal replays the merged gate list
        # verbatim, it must not re-merge
        circ.gates.append(QCircuitGate(int(gm["target"]), payloads,
                                       tuple(gm["controls"])))
    return circ


def save_circuit(path: str, circuit, extra_meta: Optional[dict] = None) -> int:
    meta, arrays = circuit_payload(circuit)
    if extra_meta:
        meta.update(extra_meta)
    return save_container(path, arrays, meta=meta, kind=CIRCUIT_KIND)


def load_circuit(path: str):
    """Returns (circuit, meta)."""
    _, meta, arrays = load_container(path, expect_kind=CIRCUIT_KIND)
    return circuit_from_payload(meta, arrays), meta


def _json_safe(kwargs: dict) -> dict:
    out = {}
    for k, v in kwargs.items():
        try:
            json.dumps(v)
        except (TypeError, ValueError):
            continue
        out[k] = v
    return out


class CheckpointStore:
    def __init__(self, root: str, max_bytes: int = 512 * 1024 * 1024):
        self.root = str(root)
        self.max_bytes = int(max_bytes)
        # liveness callback: sids whose state files the budget evictor
        # must never touch (live spilled sessions — SessionManager wires
        # this); None means nothing is protected beyond the fresh write
        self.protected_sids: Optional[Callable[[], Iterable[str]]] = None
        self._sessions_dir = os.path.join(self.root, "sessions")
        self._wal_dir = os.path.join(self.root, "wal")
        # spilled prefix-cache planes (serve/prefix_cache.py): evict-
        # first under the byte budget — a prefix is always
        # re-materializable from its circuit, session state is not
        self._prefix_dir = os.path.join(self.root, "prefix")
        os.makedirs(self._sessions_dir, exist_ok=True)
        os.makedirs(self._wal_dir, exist_ok=True)
        os.makedirs(self._prefix_dir, exist_ok=True)
        self._manifest_path = os.path.join(self.root, "manifest.json")
        self._lock_path = os.path.join(self.root, ".store.lock")
        self._acks_path = os.path.join(self.root, "acks.log")
        # cross-process manifest ownership: only sids in _owned are
        # overlaid from memory onto disk at write time; only sids in
        # _dropped are deleted.  Everything else on disk belongs to
        # some other process sharing this root and passes through.
        self._owned: set = set()
        self._dropped: set = set()
        self._manifest = self._read_manifest()
        # WAL appends come from submitter threads (everything else is
        # executor-thread-only); the sequence counter needs the lock
        self._wal_lock = threading.Lock()
        self._wal_seq = self._scan_wal_seq()
        self._update_gauge()

    # -- manifest ------------------------------------------------------

    @contextmanager
    def _file_lock(self):
        """Advisory exclusive lock serializing manifest read-merge-write
        cycles across every process sharing this root (flock works
        between threads of one process too — each entry opens its own
        file description).  Acquisition is BOUNDED: LOCK_NB polled up to
        ``QRACK_CKPT_LOCK_TIMEOUT_S`` (default 30 s, 0 = wait forever),
        then :class:`StoreLockTimeout` — a peer wedged under the flock
        must not wedge every healthy worker's save with it."""
        timeout_s = float(os.environ.get("QRACK_CKPT_LOCK_TIMEOUT_S",
                                         str(DEFAULT_LOCK_TIMEOUT_S)))
        with open(self._lock_path, "a+") as f:
            if timeout_s <= 0:
                fcntl.flock(f.fileno(), fcntl.LOCK_EX)
            else:
                deadline = time.monotonic() + timeout_s
                delay = 0.001
                while True:
                    try:
                        fcntl.flock(f.fileno(),
                                    fcntl.LOCK_EX | fcntl.LOCK_NB)
                        break
                    except OSError:
                        if time.monotonic() >= deadline:
                            if _tele._ENABLED:
                                _tele.inc("checkpoint.lock.timeout")
                            raise StoreLockTimeout(self._lock_path,
                                                   timeout_s)
                        time.sleep(delay)
                        delay = min(delay * 2, 0.05)
            try:
                yield
            finally:
                fcntl.flock(f.fileno(), fcntl.LOCK_UN)

    def _read_manifest(self) -> dict:
        try:
            with open(self._manifest_path) as f:
                m = json.load(f)
        except FileNotFoundError:
            return {"version": MANIFEST_VERSION, "sessions": {}}
        except (OSError, json.JSONDecodeError):
            # a torn manifest must not kill recovery of the state files
            return {"version": MANIFEST_VERSION, "sessions": {}}
        if int(m.get("version", 0)) > MANIFEST_VERSION:
            raise CheckpointError(
                f"{self._manifest_path}: manifest version "
                f"{m.get('version')} is newer than this reader")
        m.setdefault("sessions", {})
        return m

    def _write_raw(self, manifest: dict) -> None:
        """Atomic rewrite (tmp + fsync + os.replace) — call under
        :meth:`_file_lock` when other processes may share the root."""
        fd, tmp = tempfile.mkstemp(prefix=".manifest-", suffix=".tmp",
                                   dir=self.root)
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(manifest, f, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._manifest_path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def _write_manifest(self) -> None:
        """Merge-on-write: overlay only the sessions this process owns
        onto the manifest currently on disk, preserving other processes'
        records and the lease verbatim, then rewrite atomically."""
        with self._file_lock():
            disk = self._read_manifest()
            sessions = disk["sessions"]
            for sid in self._dropped:
                sessions.pop(sid, None)
            for sid in self._owned:
                rec = self._manifest["sessions"].get(sid)
                if rec is not None:
                    sessions[sid] = rec
            self._write_raw(disk)
            self._manifest = disk

    def register(self, sid: str, width: int, layers, seed,
                 engine_kwargs: Optional[dict] = None) -> None:
        """Record a live session's constructor recipe for recovery."""
        self._owned.add(sid)
        self._dropped.discard(sid)
        self._manifest["sessions"][sid] = {
            "width": int(width),
            "layers": layers if isinstance(layers, str) else list(layers),
            "seed": None if seed is None else int(seed),
            "engine_kwargs": _json_safe(engine_kwargs or {}),
            # True once the session's state has advanced beyond what the
            # on-disk snapshot (or a fresh |0..0>) captures — recovery
            # must not replay WAL jobs onto a base that is wrong
            "dirty": False,
        }
        self._write_manifest()

    def mark_dirty(self, sid: str) -> None:
        """Record that `sid`'s live state is no longer captured on disk
        (a job completed, or its snapshot was consumed).  No-op when
        already dirty, so the per-job cost is one dict probe."""
        rec = self._manifest["sessions"].get(sid)
        if rec is not None and not rec.get("dirty", False):
            rec["dirty"] = True
            self._write_manifest()

    def _mark_clean(self, sid: str) -> None:
        rec = self._manifest["sessions"].get(sid)
        if rec is not None and rec.get("dirty", True):
            rec["dirty"] = False
            self._write_manifest()

    def is_dirty(self, sid: str) -> bool:
        rec = self._manifest["sessions"].get(sid)
        return bool(rec.get("dirty", False)) if rec else False

    def unregister(self, sid: str) -> None:
        self._owned.discard(sid)
        self._dropped.add(sid)
        if self._manifest["sessions"].pop(sid, None) is not None:
            self._write_manifest()
        self.drop_state(sid)
        for path, _, wal_sid in self._wal_files():
            if wal_sid == sid:
                self._unlink(path)
        self._update_gauge()

    def disown(self, sid: str) -> None:
        """Stop overlaying `sid` at manifest writes WITHOUT deleting its
        record or state file — the drain handoff: the entry stays on
        disk for whichever process adopts it, and this process's later
        writes can no longer clobber the adopter's updates."""
        self._owned.discard(sid)

    def reload(self) -> None:
        """Re-read shared disk state (manifest + WAL sequence).  An
        adoption pass calls this first: another process may have drained
        sessions into the store since this one last looked."""
        with self._file_lock():
            self._manifest = self._read_manifest()
        with self._wal_lock:
            self._wal_seq = max(self._wal_seq, self._scan_wal_seq())

    def sessions(self) -> Dict[str, dict]:
        return dict(self._manifest["sessions"])

    # -- recovery lease (multi-process WAL-replay exclusivity) ---------

    def _lease_live(self, lease: Optional[dict]) -> bool:
        """Same-host pid liveness is authoritative (kill -9 frees the
        lease the moment the pid is gone); cross-host falls back to the
        recorded TTL."""
        if not lease:
            return False
        if lease.get("host") == socket.gethostname() and lease.get("pid"):
            try:
                os.kill(int(lease["pid"]), 0)
                return True
            except (OSError, ValueError):
                return False
        return time.time() < float(lease.get("expires_at", 0))

    def acquire_lease(self, owner: str, ttl_s: Optional[float] = None) -> bool:
        """Take (or refresh) the store's recovery lease.  False when a
        DIFFERENT live owner holds it — the caller must not replay the
        WAL.  A dead owner's lease (pid gone / TTL expired) is claimed
        over."""
        if ttl_s is None:
            ttl_s = float(os.environ.get("QRACK_CKPT_LEASE_TTL_S",
                                         str(DEFAULT_LEASE_TTL_S)))
        with self._file_lock():
            disk = self._read_manifest()
            cur = disk.get("lease")
            if cur and cur.get("owner") != owner and self._lease_live(cur):
                if _tele._ENABLED:
                    _tele.inc("checkpoint.lease.denied")
                    _tele.event("checkpoint.lease.denied", owner=owner,
                                holder=str(cur.get("owner")))
                return False
            now = time.time()
            disk["lease"] = {"owner": owner, "host": socket.gethostname(),
                             "pid": os.getpid(), "acquired_at": now,
                             "expires_at": now + ttl_s}
            self._write_raw(disk)
            self._manifest = disk
        if _tele._ENABLED:
            _tele.inc("checkpoint.lease.acquired")
            _tele.event("checkpoint.lease.acquired", owner=owner)
        return True

    def release_lease(self, owner: str) -> bool:
        """Drop the lease iff `owner` holds it (drain / clean shutdown
        hand the store to the next process immediately)."""
        with self._file_lock():
            disk = self._read_manifest()
            cur = disk.get("lease")
            if not cur or cur.get("owner") != owner:
                return False
            del disk["lease"]
            self._write_raw(disk)
            self._manifest = disk
        if _tele._ENABLED:
            _tele.inc("checkpoint.lease.released")
        return True

    def lease_info(self) -> Optional[dict]:
        lease = self._manifest.get("lease")
        return dict(lease) if lease else None

    # -- session state (spill / checkpoint / restore) ------------------

    def _state_path(self, sid: str) -> str:
        return os.path.join(self._sessions_dir, f"{sid}.qckpt")

    def has_state(self, sid: str) -> bool:
        return os.path.exists(self._state_path(sid))

    def save(self, sid: str, engine,
             wal_seq: Optional[int] = None) -> str:
        """Persist `engine`'s full state for `sid` (spill or explicit
        checkpoint — the caller decides whether to drop residency).

        `wal_seq` records the highest journal sequence whose effect the
        snapshot already CONTAINS (manifest ``wal_high``): recovery
        skips entries at or below it, so the
        snapshot-then-settle order of QRACK_SERVE_CKPT_EVERY_JOB can
        never double-replay the job a crash interrupted mid-settle.
        The value also rides INSIDE the state container (same atomic
        replace as the state itself): a kill -9 in the window between
        the state write and the manifest rewrite used to leave a
        snapshot that already contained the job next to a manifest
        that said it didn't — recovery replayed the surviving WAL entry
        onto it and the job applied twice (:meth:`state_wal_high` is
        the recovery-side reader)."""
        path = self._state_path(sid)
        extra = None if wal_seq is None else {"wal_high": int(wal_seq)}
        save_state(engine, path, extra_meta=extra)
        rec = self._manifest["sessions"].get(sid)
        if rec is not None:
            changed = rec.get("dirty", True)
            rec["dirty"] = False  # disk now captures the state exactly
            if wal_seq is not None and int(wal_seq) > rec.get("wal_high",
                                                              -1):
                rec["wal_high"] = int(wal_seq)
                changed = True
            if changed:
                self._write_manifest()
        self._enforce_budget(protect=path)
        self._update_gauge()
        return path

    def load(self, sid: str, into=None):
        """Restore `sid`'s state; raises CheckpointError when absent."""
        path = self._state_path(sid)
        if not os.path.exists(path):
            raise CheckpointError(f"no spilled state for session {sid}")
        return load_state(path, into=into)

    def state_wal_high(self, sid: str) -> int:
        """The ``wal_high`` recorded inside `sid`'s state container, or
        -1 (no snapshot / no record / unreadable).  Authoritative over
        the manifest copy during recovery: the container's value commits
        atomically with the state, the manifest's lags by one write."""
        path = self._state_path(sid)
        if not os.path.exists(path):
            return -1
        try:
            _, meta = peek_meta(path)
        except (CheckpointCorrupt, CheckpointError):
            return -1
        try:
            return int(meta.get("wal_high", -1))
        except (TypeError, ValueError):
            return -1

    def drop_state(self, sid: str) -> None:
        self._unlink(self._state_path(sid))
        self._update_gauge()

    def _enforce_budget(self, protect: Optional[str] = None) -> List[str]:
        """Evict oldest state files until under max_bytes.  Protected:
        the just-written file (a single oversized session must not evict
        itself into a lost update) and every live spilled session's
        state (the only copy of that session — deleting it would make
        its next restore fail for the life of the process)."""
        if self.max_bytes <= 0:
            return []
        live = set(self.protected_sids()) if self.protected_sids else set()
        evicted = []
        while self.total_bytes() > self.max_bytes:
            # spilled prefixes (rank 0) go before session state (rank 1):
            # a prefix is always re-materializable from its circuit
            victims = sorted(
                [(0, os.path.getmtime(p), p) for p in self._prefix_files()
                 if p != protect]
                + [(1, os.path.getmtime(p), p) for p in self._state_files()
                   if p != protect
                   and os.path.basename(p)[:-len(".qckpt")] not in live])
            if not victims:
                break
            _, _, path = victims[0]
            self._unlink(path)
            evicted.append(path)
        if evicted and _tele._ENABLED:
            _tele.inc("checkpoint.store.evicted", len(evicted))
        return evicted

    # -- spilled prefix-cache planes (serve/prefix_cache.py) -----------

    def _prefix_path(self, digest: str, width: int, stack: str) -> str:
        return os.path.join(self._prefix_dir,
                            f"{digest}-w{int(width)}-{stack}.qckpt")

    def save_prefix(self, digest: str, width: int, stack: str,
                    arrays: Dict[str, np.ndarray],
                    meta: Optional[dict] = None) -> str:
        """Spill a prefix-cache entry's planes; returns the path.  The
        container's per-array sha256 gives disk-level integrity; the
        cache layers its own host fingerprint on top (fault-back-in
        verifies BOTH before any tenant is seeded from the entry)."""
        path = self._prefix_path(digest, width, stack)
        m = dict(meta or {})
        m.update({"digest": digest, "width": int(width), "stack": stack})
        save_container(path, arrays, meta=m, kind=PREFIX_KIND)
        self._enforce_budget(protect=path)
        self._update_gauge()
        return path

    def load_prefix(self, digest: str, width: int, stack: str):
        """(meta, arrays) for a spilled prefix entry; CheckpointError
        when absent, CheckpointCorrupt on a bad container hash."""
        path = self._prefix_path(digest, width, stack)
        if not os.path.exists(path):
            raise CheckpointError(
                f"no spilled prefix {digest[:12]}… w{width} {stack}")
        _, meta, arrays = load_container(path, expect_kind=PREFIX_KIND)
        return meta, arrays

    def has_prefix(self, digest: str, width: int, stack: str) -> bool:
        return os.path.exists(self._prefix_path(digest, width, stack))

    def drop_prefix(self, digest: str, width: int, stack: str) -> None:
        self._unlink(self._prefix_path(digest, width, stack))
        self._update_gauge()

    def prefix_entries(self) -> List[Tuple[str, int, str]]:
        """[(digest, width, stack)] for every spilled prefix on disk —
        a recovered service probes these to rebuild a warm cache."""
        out = []
        try:
            names = os.listdir(self._prefix_dir)
        except OSError:
            return []
        for name in names:
            if not name.endswith(".qckpt"):
                continue
            stem = name[:-len(".qckpt")]
            digest, _, rest = stem.partition("-w")
            width_s, _, stack = rest.partition("-")
            try:
                out.append((digest, int(width_s), stack))
            except ValueError:
                continue
        return out

    def _prefix_files(self) -> List[str]:
        try:
            return [os.path.join(self._prefix_dir, n)
                    for n in os.listdir(self._prefix_dir)
                    if n.endswith(".qckpt")]
        except OSError:
            return []

    # -- pending-job journal (WAL) -------------------------------------

    def _scan_wal_seq(self) -> int:
        seqs = [seq for _, seq, _ in self._wal_files()]
        return max(seqs) + 1 if seqs else 0

    def _wal_files(self) -> List[Tuple[str, int, str]]:
        """[(path, seq, sid)] sorted by seq."""
        out = []
        try:
            names = os.listdir(self._wal_dir)
        except OSError:
            return []
        for name in names:
            if not name.endswith(".qckpt"):
                continue
            stem = name[:-len(".qckpt")]
            seq_s, _, sid = stem.partition("-")
            try:
                seq = int(seq_s)
            except ValueError:
                continue
            out.append((os.path.join(self._wal_dir, name), seq, sid))
        out.sort(key=lambda t: t[1])
        return out

    def wal_append(self, sid: str, circuit,
                   tag: Optional[str] = None) -> str:
        """Journal a submitted circuit; the executor deletes the entry
        at job completion, so entries still present at startup are
        exactly the jobs a crash interrupted.  `tag` is an opaque
        caller token persisted in the entry's meta — the fleet front
        door stamps each RPC submit so a resubmit decision after a
        worker death can check :meth:`wal_pending_tags` instead of
        guessing (docs/FLEET.md exactly-once discussion)."""
        with self._wal_lock:
            seq = self._wal_seq
            self._wal_seq += 1
        path = os.path.join(self._wal_dir, f"{seq:09d}-{sid}.qckpt")
        meta = {"sid": sid, "seq": seq}
        if tag is not None:
            meta["tag"] = str(tag)
        save_circuit(path, circuit, extra_meta=meta)
        self._update_gauge()
        return path

    def wal_pending_tags(self, sids: Optional[Iterable[str]] = None
                         ) -> set:
        """Tags of journal entries still pending (optionally scoped to
        `sids`).  A tag present here is a submit whose effect WILL be
        applied by whichever process adopts the session — the caller
        must not resubmit it.  Damaged entries are left for
        wal_entries() to reap."""
        want = None if sids is None else set(sids)
        tags = set()
        for path, _, sid in self._wal_files():
            if want is not None and sid not in want:
                continue
            try:
                _, meta = load_circuit(path)
            except (CheckpointCorrupt, CheckpointError):
                continue
            tag = meta.get("tag")
            if tag is not None:
                tags.add(tag)
        return tags

    def wal_remove(self, path: str) -> None:
        self._unlink(path)
        self._update_gauge()

    # -- settled-tag acks (fleet exactly-once) -------------------------

    def ack_tag(self, tag: str) -> None:
        """Durably record that the submit carrying `tag` SETTLED —
        appended by the executor after the job's effect is snapshotted
        (or journaled past) but BEFORE its WAL entry is removed.  The
        fleet front door's resubmit decision consults
        :meth:`tag_acked`: without this record, a worker killed in the
        instant between settling a job and writing its result frame
        looks identical to one killed before executing it, and the
        front door's only safe-looking move — resubmit — applies the
        job twice.  Cross-process safe: appends hold the store flock
        and stay under the pipe-atomicity size."""
        line = (str(tag).replace("\n", " ") + "\n").encode()
        with self._file_lock():
            try:
                if (os.path.exists(self._acks_path)
                        and os.path.getsize(self._acks_path)
                        > ACKS_MAX_BYTES):
                    self._rotate_acks()
            except OSError:
                pass
            with open(self._acks_path, "ab") as f:
                f.write(line)
                f.flush()

    def _rotate_acks(self) -> None:
        """Keep the newest half of the ack log (caller holds the store
        flock).  Resubmit decisions happen within seconds of a worker
        death, so dropping months-old tags can't reopen the window."""
        try:
            with open(self._acks_path, "rb") as f:
                data = f.read()
        except OSError:
            return
        keep = data[len(data) // 2:]
        nl = keep.find(b"\n")
        if nl >= 0:
            keep = keep[nl + 1:]
        fd, tmp = tempfile.mkstemp(prefix=".acks-", suffix=".tmp",
                                   dir=self.root)
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(keep)
            os.replace(tmp, self._acks_path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def tag_acked(self, tag: str) -> bool:
        """True when `tag`'s submit settled on SOME worker sharing this
        store (exact-line match against the ack log)."""
        try:
            with open(self._acks_path, "rb") as f:
                data = f.read()
        except OSError:
            return False
        return str(tag).encode() in data.split(b"\n")

    def wal_entries(self, sids: Optional[Iterable[str]] = None,
                    with_meta: bool = False
                    ) -> List[Tuple]:
        """[(sid, seq, circuit)] in submit order; damaged entries (torn
        writes at crash time) are skipped and removed.  With `sids`,
        only those sessions' entries are returned — scoped adoption
        (fleet re-placement) must not read a live peer's journal.
        With `with_meta`, 4-tuples (sid, seq, circuit, meta) — the
        serve recovery path reads the entry tag to distinguish circuit
        replays from journaled trajectory jobs (docs/NOISE.md)."""
        want = None if sids is None else set(sids)
        out = []
        for path, seq, sid in self._wal_files():
            if want is not None and sid not in want:
                continue
            try:
                circ, meta = load_circuit(path)
            except (CheckpointCorrupt, CheckpointError):
                self._unlink(path)
                continue
            out.append((sid, seq, circ, meta) if with_meta
                       else (sid, seq, circ))
        return out

    def clear_wal(self, sids: Optional[Iterable[str]] = None) -> None:
        """Drop journal entries — all of them (legacy whole-store
        adoption), or only the named sessions' (scoped adoption: a
        fleet peer adopting a dead worker's sids must leave every other
        worker's pending entries in place)."""
        want = None if sids is None else set(sids)
        for path, _, sid in self._wal_files():
            if want is not None and sid not in want:
                continue
            self._unlink(path)
        self._update_gauge()

    # -- footprint -----------------------------------------------------

    def _state_files(self) -> List[str]:
        try:
            return [os.path.join(self._sessions_dir, n)
                    for n in os.listdir(self._sessions_dir)
                    if n.endswith(".qckpt")]
        except OSError:
            return []

    def total_bytes(self) -> int:
        total = 0
        for d in (self._sessions_dir, self._wal_dir, self._prefix_dir):
            try:
                for name in os.listdir(d):
                    try:
                        total += os.path.getsize(os.path.join(d, name))
                    except OSError:
                        pass
            except OSError:
                pass
        return total

    def stats(self) -> dict:
        return {
            "root": self.root,
            "sessions": len(self._manifest["sessions"]),
            "spilled": len(self._state_files()),
            "spilled_prefixes": len(self._prefix_files()),
            "wal_entries": len(self._wal_files()),
            "bytes": self.total_bytes(),
            "max_bytes": self.max_bytes,
        }

    @staticmethod
    def _unlink(path: str) -> None:
        try:
            os.unlink(path)
        except OSError:
            pass

    def _update_gauge(self) -> None:
        if _tele._ENABLED:
            _tele.gauge("checkpoint.store.bytes", self.total_bytes())
