"""save_state / load_state: one registry over every representation.

Each engine/layer class owns its serialization next to its state
layout — a ``_ckpt_capture(capture_child)`` method returning a
snapshot node and a ``_ckpt_restore(arrays, meta, children,
restore_child)`` method rebuilding in place — and declares its kind
tag as a ``_ckpt_kind`` class attribute.  The registry composes them
into whole-stack snapshot TREES (QUnit recurses into its Schmidt
factors, the hybrids into their live half) and flattens each tree into
one container file (container.py).

Restore is **restore-INTO**: layered stacks hold unserializable
factory closures (layer wiring built by factory.py), so the natural
recovery path builds a fresh stack through the same factory and then
loads the snapshot into it — child engines are constructed by the
LIVE object's own factory and only their state is overwritten.
``load_state(path)`` without a target builds default-wired objects
from the snapshot's recorded constructor metadata, which round-trips
every preset the engine matrix tests.

rng stream positions (PCG64 bit-generator state, utils/rng.py) ride in
every node's meta and are restored LAST, after any child-spawning the
restore itself performed — a restored stack continues bit-identically,
measurement streams included.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from .container import (CheckpointError, load_container, save_container)

STATE_KIND_PREFIX = "qrack-state:"


# -- rng stream position -----------------------------------------------


def rng_state(rng) -> dict:
    """JSON-able PCG64 position for a utils.rng.QrackRandom."""
    return {"seed": int(rng._seed), "state": rng._gen.bit_generator.state}


def restore_rng(rng, st: dict) -> None:
    rng.seed(int(st["seed"]))
    bg = dict(st["state"])
    inner = dict(bg.get("state", {}))
    # JSON round-trips ints losslessly (arbitrary precision), but
    # normalize key types defensively
    bg["state"] = {k: int(v) for k, v in inner.items()}
    rng._gen.bit_generator.state = bg


def _maybe_rng_meta(obj, meta: dict) -> None:
    if "rng" in meta:
        return
    rng = getattr(obj, "rng", None)
    if rng is not None and hasattr(rng, "_gen"):
        meta["rng"] = rng_state(rng)


# -- capture / restore -------------------------------------------------


def kind_of(obj) -> Optional[str]:
    """The object's snapshot kind tag (forwarded through proxies)."""
    return getattr(obj, "_ckpt_kind", None)


def capture(obj) -> dict:
    """Snapshot `obj` (and its children, recursively) into a tree of
    ``{"kind", "meta", "arrays", "children"}`` nodes.  Host-complete:
    every device array is materialized via np.asarray before return."""
    cap = getattr(obj, "_ckpt_capture", None)
    if cap is None:
        raise CheckpointError(
            f"{type(obj).__name__} does not support checkpointing")
    snap = cap(capture)
    snap.setdefault("meta", {})
    snap.setdefault("arrays", {})
    snap.setdefault("children", {})
    _maybe_rng_meta(obj, snap["meta"])
    # base interface flags every stack level shares
    for attr in ("do_normalize", "rand_global_phase"):
        if attr not in snap["meta"] and hasattr(obj, attr):
            snap["meta"][attr] = bool(getattr(obj, attr))
    return snap


def restore_into(obj, snap: dict):
    """Load snapshot tree `snap` into live object `obj` in place (the
    stack keeps its own factories/wiring; only state is overwritten).
    Returns `obj`."""
    if type(obj).__name__ == "ResilientEngine":
        inner = obj.engine
        if kind_of(inner) != snap["kind"]:
            object.__setattr__(obj, "_engine", build(snap))
        else:
            restore_into(inner, snap)
        return obj
    if kind_of(obj) != snap["kind"]:
        raise CheckpointError(
            f"snapshot kind {snap['kind']!r} does not match live "
            f"{type(obj).__name__} (kind {kind_of(obj)!r})")
    meta = snap.get("meta", {})
    obj._ckpt_restore(snap.get("arrays", {}), meta,
                      snap.get("children", {}), restore_child)
    for attr in ("do_normalize", "rand_global_phase"):
        if attr in meta and hasattr(obj, attr):
            setattr(obj, attr, bool(meta[attr]))
    # LAST: pin the rng stream position (restore above may have spawned
    # children off this stream; the snapshot position wins)
    rng = getattr(obj, "rng", None)
    if "rng" in meta and rng is not None and hasattr(rng, "_gen"):
        restore_rng(rng, meta["rng"])
    return obj


def restore_child(snap: dict, into=None):
    """Helper handed to _ckpt_restore implementations: restore a child
    snapshot into `into` when it exists and matches, else build a
    standalone object from the snapshot."""
    if into is not None and kind_of(into) == snap["kind"]:
        return restore_into(into, snap)
    return build(snap)


def build(snap: dict):
    """Construct a default-wired object for `snap` from its recorded
    constructor metadata, then restore the snapshot into it."""
    kind = snap["kind"]
    meta = snap.get("meta", {})
    n = int(meta["n"])
    if kind == "cpu":
        from ..engines.cpu import QEngineCPU

        obj = QEngineCPU(n, dtype=np.dtype(meta.get("dtype", "complex128")))
    elif kind == "tpu":
        from ..engines.tpu import QEngineTPU

        obj = QEngineTPU(n, dtype=meta.get("dtype"))
    elif kind == "sparse":
        from ..engines.sparse import QEngineSparse

        obj = QEngineSparse(n)
    elif kind == "pager":
        from ..parallel.pager import QPager

        # honor the recorded page layout: MAll's per-page draw pattern
        # depends on n_pages, and bit-identical continuation needs the
        # same pattern (restore-INTO an existing pager may still remap)
        n_pages = meta.get("n_pages")
        try:
            obj = QPager(n, n_pages=int(n_pages) if n_pages else None)
        except ValueError:
            obj = QPager(n)  # fewer devices here than at save time
    elif kind == "turboquant":
        from ..engines.turboquant import QEngineTurboQuant

        obj = QEngineTurboQuant(n, bits=int(meta["bits"]),
                                block_pow=int(meta["block_pow"]),
                                seed_rot=int(meta["seed"]))
    elif kind == "turboquant_pager":
        from ..parallel.turboquant_pager import QPagerTurboQuant

        obj = QPagerTurboQuant(n, bits=int(meta["bits"]),
                               block_pow=int(meta["block_pow"]),
                               seed_rot=int(meta["seed"]))
    elif kind == "stabilizer":
        from ..layers.stabilizer import QStabilizer

        obj = QStabilizer(n)
    elif kind == "unit":
        from ..layers.qunit import QUnit

        obj = QUnit(n)
    elif kind == "unit_multi":
        from ..layers.qunitmulti import QUnitMulti

        obj = QUnitMulti(n)
    elif kind == "unit_clifford":
        from ..layers.qunitclifford import QUnitClifford

        obj = QUnitClifford(n)
    elif kind == "stabilizer_hybrid":
        from ..layers.stabilizerhybrid import QStabilizerHybrid

        obj = QStabilizerHybrid(n)
    elif kind == "bdt":
        from ..layers.qbdt import QBdt

        obj = QBdt(n, attached_qubits=int(meta.get("attached_qubits", 0)))
    elif kind == "bdt_hybrid":
        from ..layers.qbdthybrid import QBdtHybrid

        obj = QBdtHybrid(
            n, attached_qubits=int(meta.get("attached_qubits", 0)))
    elif kind == "hybrid":
        from ..engines.hybrid import QHybrid

        obj = QHybrid(
            n,
            tpu_threshold_qubits=int(meta["tpu_threshold"]),
            pager_threshold_qubits=int(meta["pager_threshold"]))
    elif kind == "routed":
        from ..route.router import QRouted

        # the wrapper's _ckpt_restore rebuilds the recorded stack from
        # the snapshot's layer list; a fresh QRouted carries no engine
        obj = QRouted(n)
    elif kind == "lightcone":
        from ..lightcone.engine import QLightCone

        # the engine's _ckpt_restore rebuilds the buffered circuit from
        # the snapshot's gate arrays and rehydrates cone/base children
        obj = QLightCone(n)
    else:
        raise CheckpointError(f"unknown snapshot kind {kind!r}")
    return restore_into(obj, snap)


# -- tree <-> flat container -------------------------------------------


def _flatten(snap: dict, prefix: str, out: Dict[str, np.ndarray]) -> dict:
    node = {"kind": snap["kind"], "meta": snap.get("meta", {}),
            "arrays": {}, "children": {}}
    for name, arr in snap.get("arrays", {}).items():
        key = f"{prefix}{name}"
        out[key] = arr
        node["arrays"][name] = key
    for name, child in snap.get("children", {}).items():
        node["children"][name] = _flatten(child, f"{prefix}{name}/", out)
    return node


def _unflatten(node: dict, arrays: Dict[str, np.ndarray]) -> dict:
    return {
        "kind": node["kind"], "meta": node.get("meta", {}),
        "arrays": {name: arrays[key]
                   for name, key in node.get("arrays", {}).items()},
        "children": {name: _unflatten(child, arrays)
                     for name, child in node.get("children", {}).items()},
    }


# -- public file API ---------------------------------------------------


def save_state(obj, path: str, extra_meta: Optional[dict] = None) -> int:
    """Snapshot `obj` (any supported engine/layer stack, resilience
    proxy included) into one container file; returns bytes written.
    `extra_meta` rides in the container manifest itself, so bookkeeping
    like the store's ``wal_high`` commits in the SAME atomic replace as
    the state it describes (no torn crash window between them)."""
    snap = capture(obj)
    flat: Dict[str, np.ndarray] = {}
    tree = _flatten(snap, "", flat)
    meta = {"tree": tree}
    if extra_meta:
        meta.update(extra_meta)
    return save_container(path, flat, meta=meta,
                          kind=STATE_KIND_PREFIX + snap["kind"])


def load_snapshot(path: str) -> dict:
    """Read a state container back into a snapshot tree (no objects
    constructed yet)."""
    kind, meta, arrays = load_container(path)
    if not (kind or "").startswith(STATE_KIND_PREFIX):
        raise CheckpointError(f"{path}: not a state checkpoint ({kind!r})")
    return _unflatten(meta["tree"], arrays)


def load_state(path: str, into=None):
    """Restore a saved stack: into a live object when given (the spill/
    recovery path — state loads into the session's own factory-built
    stack), else build default-wired objects from the snapshot."""
    snap = load_snapshot(path)
    if into is not None:
        return restore_into(into, snap)
    return build(snap)
