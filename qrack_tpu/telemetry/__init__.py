"""Process-wide telemetry: counters, honest-sync spans, and exporters.

The whole engine stack is instrumented through this module (see
docs/OBSERVABILITY.md for the metric namespace).  Everything is gated
on ``QRACK_TPU_TELEMETRY=1`` (or :func:`enable`): when disabled, every
entry point returns after one module-global boolean read and records
NOTHING — hot gate paths guard with ``if telemetry._ENABLED:`` so even
the counter-name f-string is never built.

Three surfaces:

* **counters** — :func:`inc` monotonic named counters (gate dispatches
  by kind/width/engine, compile-cache hits/misses/evictions, pager
  exchange events + bytes, layer escalations).
* **spans** — ``with telemetry.span("qft.w28", sync=planes):`` nestable
  wall-clock timers.  With ``sync=`` the exit is bracketed by a real
  1-amplitude ``jax.device_get`` read and the empty-queue round trip is
  subtracted — the utils/timing.py methodology, because
  ``block_until_ready`` over the axon relay acks dispatch, not
  completion (docs/TPU_EVIDENCE.md).  A span without ``sync=`` is
  host-wall only and is marked ``synced: False`` in the trace.
* **export** — :func:`snapshot` (plain dict), :func:`write_jsonl`
  (atexit-armed via ``QRACK_TPU_TELEMETRY_OUT=path``),
  :func:`chrome_trace` (Perfetto-loadable trace-event JSON), and
  :func:`xplane_bracket` (a ``jax.profiler`` trace bracket whose dumps
  ``scripts/analyze_xplane.py`` consumes).

Compile-cache accounting comes from two helpers:
:class:`ProgramCache`, the bounded-LRU replacement for the module-level
``_PROGRAMS`` dicts (parallel/pager.py, engines/turboquant.py), and
:func:`instrument_jit`, a thin wrapper over module-level ``jax.jit``
programs (engines/tpu.py) that classifies each call as hit or miss via
the jitted function's ``_cache_size()``.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

__all__ = [
    "enabled", "enable", "disable", "inc", "event", "span", "observe",
    "gauge", "snapshot", "reset", "write_jsonl", "chrome_trace",
    "write_chrome_trace", "xplane_bracket", "instrument_jit",
    "ProgramCache",
]

# single hot-path gate: instrumentation sites read this module attribute
# directly (`if telemetry._ENABLED:`) so the disabled cost is one dict
# lookup + truth test, with no call and no string formatting
_ENABLED: bool = os.environ.get("QRACK_TPU_TELEMETRY", "") not in ("", "0")

_LOCK = threading.Lock()
_EPOCH = time.perf_counter()  # trace timestamps are relative to import

_COUNTERS: Dict[str, float] = {}
_GAUGES: Dict[str, float] = {}        # name -> last observed value
_SPANS: Dict[str, List[float]] = {}   # name -> [count, total_s, min_s, max_s]
_TRACE: List[dict] = []               # chrome-trace "X" complete events
_EVENTS: List[dict] = []              # discrete annotated events
_TRACE_CAP = int(os.environ.get("QRACK_TPU_TELEMETRY_TRACE_CAP", "65536"))
_EVENT_CAP = int(os.environ.get("QRACK_TPU_TELEMETRY_EVENT_CAP", "4096"))

_TLS = threading.local()  # per-thread span stack (nesting depth)


def enabled() -> bool:
    return _ENABLED


def enable() -> None:
    """Turn telemetry on at runtime (tests; equivalent of the env gate).
    Arms the atexit JSONL dump if QRACK_TPU_TELEMETRY_OUT is set."""
    global _ENABLED
    _ENABLED = True
    from . import export

    export.arm_atexit()


def disable() -> None:
    global _ENABLED
    _ENABLED = False


def reset() -> None:
    """Drop all recorded data (counters, spans, traces, events)."""
    with _LOCK:
        _COUNTERS.clear()
        _GAUGES.clear()
        _SPANS.clear()
        _TRACE.clear()
        _EVENTS.clear()


# ---------------------------------------------------------------------------
# counters + events
# ---------------------------------------------------------------------------

def inc(name: str, n: float = 1) -> None:
    """Add `n` to the named monotonic counter (no-op when disabled)."""
    if not _ENABLED:
        return
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + n


def gauge(name: str, value: float) -> None:
    """Record the latest value of a named gauge (last-write-wins; the
    serving layer uses these for queue depth / p50-p99 latencies)."""
    if not _ENABLED:
        return
    with _LOCK:
        _GAUGES[name] = float(value)


def observe(name: str, seconds: float) -> None:
    """Feed one measured duration into the named span aggregate without
    a context manager — for durations measured externally (queue waits,
    per-job latencies) where enter/exit bracketing does not fit."""
    if not _ENABLED:
        return
    with _LOCK:
        agg = _SPANS.get(name)
        if agg is None:
            _SPANS[name] = [1, seconds, seconds, seconds]
        else:
            agg[0] += 1
            agg[1] += seconds
            agg[2] = min(agg[2], seconds)
            agg[3] = max(agg[3], seconds)


def event(name: str, **fields) -> None:
    """Record a discrete annotated event AND bump its counter.  Events
    are capped at QRACK_TPU_TELEMETRY_EVENT_CAP; drops are counted."""
    if not _ENABLED:
        return
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + 1
        if len(_EVENTS) < _EVENT_CAP:
            _EVENTS.append({"name": name,
                            "t_s": time.perf_counter() - _EPOCH, **fields})
        else:
            _COUNTERS["telemetry.events.dropped"] = \
                _COUNTERS.get("telemetry.events.dropped", 0) + 1


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

class _NullSpan:
    """Shared do-nothing context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("name", "sync", "t0", "depth")

    def __init__(self, name: str, sync=None):
        self.name = name
        self.sync = sync

    def __enter__(self):
        stack = getattr(_TLS, "stack", None)
        if stack is None:
            stack = _TLS.stack = []
        self.depth = len(stack)
        stack.append(self.name)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self.sync is not None:
            # honest completion: a real device->host read, then subtract
            # the empty-queue round trip of that read itself
            # (utils/timing.py devget_sync / empty_queue_sync_s —
            # block_until_ready over the relay acks dispatch only)
            from ..utils.timing import devget_sync, empty_queue_sync_s

            devget_sync(self.sync)
            t1 = time.perf_counter()
            sync_s = empty_queue_sync_s(self.sync, reps=1)
            wall = max(t1 - self.t0 - sync_s, 0.0)
        else:
            wall = time.perf_counter() - self.t0
        _TLS.stack.pop()
        with _LOCK:
            agg = _SPANS.get(self.name)
            if agg is None:
                _SPANS[self.name] = [1, wall, wall, wall]
            else:
                agg[0] += 1
                agg[1] += wall
                agg[2] = min(agg[2], wall)
                agg[3] = max(agg[3], wall)
            if len(_TRACE) < _TRACE_CAP:
                _TRACE.append({
                    "name": self.name,
                    "ts_s": self.t0 - _EPOCH,
                    "dur_s": wall,
                    "tid": threading.get_ident(),
                    "depth": self.depth,
                    "synced": self.sync is not None,
                })
            else:
                _COUNTERS["telemetry.trace.dropped"] = \
                    _COUNTERS.get("telemetry.trace.dropped", 0) + 1
        return False


def span(name: str, sync=None):
    """Nestable wall-clock timer.  `sync` takes the device array (e.g.
    the (2, 2^n) planes) whose queue the span must drain before its
    clock stops — without it the span is an untrusted host wall."""
    if not _ENABLED:
        return _NULL_SPAN
    return _Span(name, sync)


# ---------------------------------------------------------------------------
# compile-cache accounting
# ---------------------------------------------------------------------------

class _JitProgram:
    """Transparent wrapper over a module-level jitted program that
    counts `compile.<name>.miss` (a call that grew the jit cache — XLA
    compiled) vs `.hit` (dispatch straight from cache).  Disabled path:
    one boolean test, then the raw call."""

    __slots__ = ("_fn", "_name")

    def __init__(self, name: str, fn):
        self._fn = fn
        self._name = name

    def __call__(self, *args, **kwargs):
        if not _ENABLED:
            return self._fn(*args, **kwargs)
        try:
            before = self._fn._cache_size()
        except Exception:
            before = None
        out = self._fn(*args, **kwargs)
        if before is None:
            inc(f"compile.{self._name}.call")
        elif self._fn._cache_size() > before:
            inc(f"compile.{self._name}.miss")
        else:
            inc(f"compile.{self._name}.hit")
        return out

    def __getattr__(self, attr):  # lower/_cache_size/etc. pass through
        return getattr(self._fn, attr)


def instrument_jit(name: str, fn):
    """Wrap a jitted callable for per-call compile hit/miss counting."""
    return _JitProgram(name, fn)


class ProgramCache:
    """Bounded LRU of compiled programs with hit/miss/eviction stats.

    Replacement for the module-global ``_PROGRAMS: dict`` pattern: a
    long-lived process no longer accumulates one compiled program (and
    its closed-over mesh) per key forever.  Keys are tuples; a key part
    produced by :meth:`mesh_token` is weakly tied to its mesh — when the
    mesh is garbage-collected every entry keyed to it is dropped, so
    dead meshes cannot pin compiled programs until LRU pressure.

    Stats are kept unconditionally (they are O(1) ints); the telemetry
    counters mirror them only while telemetry is enabled.
    """

    def __init__(self, name: str, cap: Optional[int] = None,
                 cap_env: str = "QRACK_TPU_PROGRAM_CACHE_CAP",
                 default_cap: int = 256):
        if cap is None:
            cap = int(os.environ.get(cap_env, str(default_cap)))
        self.name = name
        self.cap = max(1, cap)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self._od: "OrderedDict" = OrderedDict()
        self._lock = threading.Lock()

    def get_or_build(self, key, builder):
        with self._lock:
            fn = self._od.get(key)
            if fn is not None:
                self._od.move_to_end(key)
                self.hits += 1
                if _ENABLED:
                    inc(f"compile.{self.name}.hit")
                return fn
        fn = builder()  # build outside the lock: builders trace/compile
        with self._lock:
            self._od[key] = fn
            self._od.move_to_end(key)
            self.misses += 1
            if _ENABLED:
                inc(f"compile.{self.name}.miss")
            while len(self._od) > self.cap:
                self._od.popitem(last=False)
                self.evictions += 1
                if _ENABLED:
                    inc(f"compile.{self.name}.eviction")
        return fn

    def mesh_token(self, mesh) -> int:
        """A cache-key part for `mesh` that is weakly tied to it: a
        finalizer drops every entry containing the token once the mesh
        is collected (id() alone would let dead meshes pin programs)."""
        import weakref

        token = id(mesh)
        try:
            weakref.finalize(mesh, self._drop_token, token)
        except TypeError:
            pass  # non-weakref-able key source: LRU cap still bounds us
        return token

    def _drop_token(self, token: int) -> None:
        def has(part) -> bool:
            if part == token and isinstance(part, int):
                return True
            if isinstance(part, tuple):
                return any(has(p) for p in part)
            return False

        with self._lock:
            dead = [k for k in self._od if has(k)]
            for k in dead:
                del self._od[k]
                self.evictions += 1
            if dead and _ENABLED:
                inc(f"compile.{self.name}.eviction", len(dead))

    def stats(self) -> dict:
        return {"size": len(self._od), "cap": self.cap, "hits": self.hits,
                "misses": self.misses, "evictions": self.evictions}

    def __len__(self) -> int:
        return len(self._od)

    def __contains__(self, key) -> bool:
        return key in self._od

    def clear(self) -> None:
        with self._lock:
            self._od.clear()


# ---------------------------------------------------------------------------
# snapshot
# ---------------------------------------------------------------------------

def snapshot(include_events: bool = True) -> dict:
    """Plain-dict view of everything recorded so far (JSON-safe)."""
    with _LOCK:
        out = {
            "enabled": _ENABLED,
            "pid": os.getpid(),
            "counters": dict(_COUNTERS),
            "gauges": dict(_GAUGES),
            "spans": {
                name: {"count": int(agg[0]), "total_s": agg[1],
                       "min_s": agg[2], "max_s": agg[3]}
                for name, agg in _SPANS.items()
            },
        }
        if include_events:
            out["events"] = list(_EVENTS)
    return out


# exporters live in export.py; re-export the public surface
from .export import (  # noqa: E402  (cycle-safe: export imports nothing above lazily)
    chrome_trace, write_chrome_trace, write_jsonl, xplane_bracket,
)

# arm the atexit JSONL dump when the env gate + out path are both set
if _ENABLED and os.environ.get("QRACK_TPU_TELEMETRY_OUT"):
    from .export import arm_atexit as _arm

    _arm()
