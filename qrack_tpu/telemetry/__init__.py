"""Process-wide telemetry: counters, honest-sync spans, and exporters.

The whole engine stack is instrumented through this module (see
docs/OBSERVABILITY.md for the metric namespace).  Everything is gated
on ``QRACK_TPU_TELEMETRY=1`` (or :func:`enable`): when disabled, every
entry point returns after one module-global boolean read and records
NOTHING — hot gate paths guard with ``if telemetry._ENABLED:`` so even
the counter-name f-string is never built.

Three surfaces:

* **counters** — :func:`inc` monotonic named counters (gate dispatches
  by kind/width/engine, compile-cache hits/misses/evictions, pager
  exchange events + bytes, layer escalations).  :func:`observe` feeds a
  named duration into both the span aggregate and a merge-able
  log-bucket :class:`~qrack_tpu.telemetry.histogram.Histogram`, so
  :func:`percentile` can answer p50/p95/p99 SLO questions per process
  and — after the supervisor merges heartbeat-flushed snapshots —
  fleet-wide (docs/OBSERVABILITY.md "Fleet observability plane").
* **spans** — ``with telemetry.span("qft.w28", sync=planes):`` nestable
  wall-clock timers.  With ``sync=`` the exit is bracketed by a real
  1-amplitude ``jax.device_get`` read and the empty-queue round trip is
  subtracted — the utils/timing.py methodology, because
  ``block_until_ready`` over the axon relay acks dispatch, not
  completion (docs/TPU_EVIDENCE.md).  A span without ``sync=`` is
  host-wall only and is marked ``synced: False`` in the trace.  Spans
  and events carry the thread's current distributed-trace id
  (:func:`set_trace` / :func:`current_trace`) so per-process traces can
  be correlated across a fleet; timestamps are relative to the import
  epoch, whose wall-clock anchor (``epoch_unix_s``) rides in every
  snapshot so exporters can merge processes onto one timeline.
* **export** — :func:`snapshot` (plain dict), :func:`write_jsonl`
  (atexit-armed via ``QRACK_TPU_TELEMETRY_OUT=path``),
  :func:`chrome_trace` (Perfetto-loadable trace-event JSON), and
  :func:`xplane_bracket` (a ``jax.profiler`` trace bracket whose dumps
  ``scripts/analyze_xplane.py`` consumes).

The hardware-truth profiling plane lives in two sibling modules:
:mod:`~qrack_tpu.telemetry.roofline` (per-dispatch planned-bytes ledger,
device-class fingerprints, the implied-bandwidth honesty clamp) and
:mod:`~qrack_tpu.telemetry.sentinel` (stdlib-only shared formula, peak
table, and the perf-regression sentinel over committed evidence) —
import them explicitly (``from qrack_tpu.telemetry import roofline``);
they are deliberately not re-exported here so this module stays
importable without touching them.

Compile-cache accounting comes from two helpers:
:class:`ProgramCache`, the bounded-LRU replacement for the module-level
``_PROGRAMS`` dicts (parallel/pager.py, engines/turboquant.py), and
:func:`instrument_jit`, a thin wrapper over module-level ``jax.jit``
programs (engines/tpu.py) that classifies each call as hit or miss via
the jitted function's ``_cache_size()``.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque
from typing import Deque, Dict, List, Optional

from .histogram import Histogram

__all__ = [
    "enabled", "enable", "disable", "inc", "event", "span", "record_span",
    "observe",
    "gauge", "percentile", "set_trace", "current_trace", "snapshot",
    "merge_snapshots",
    "reset", "write_jsonl", "chrome_trace", "write_chrome_trace",
    "merged_chrome_trace", "write_merged_chrome_trace",
    "local_trace_source", "xplane_bracket", "instrument_jit",
    "ProgramCache", "Histogram", "FlightRecorder", "read_blackbox",
]

# single hot-path gate: instrumentation sites read this module attribute
# directly (`if telemetry._ENABLED:`) so the disabled cost is one dict
# lookup + truth test, with no call and no string formatting
_ENABLED: bool = os.environ.get("QRACK_TPU_TELEMETRY", "") not in ("", "0")

_LOCK = threading.Lock()
# trace timestamps are relative to import; the wall clock sampled at the
# same instant anchors them to an absolute timeline (epoch_unix_s in
# every snapshot / black box) so N processes' traces can be merged
_EPOCH = time.perf_counter()
_EPOCH_WALL = time.time()

_TRACE_CAP = int(os.environ.get("QRACK_TPU_TELEMETRY_TRACE_CAP", "65536"))
_EVENT_CAP = int(os.environ.get("QRACK_TPU_TELEMETRY_EVENT_CAP", "4096"))
_HIST_CAP = int(os.environ.get("QRACK_TPU_TELEMETRY_HIST_CAP", "1024"))

_COUNTERS: Dict[str, float] = {}
_GAUGES: Dict[str, float] = {}        # name -> last observed value
_SPANS: Dict[str, List[float]] = {}   # name -> [count, total_s, min_s, max_s]
_HISTS: Dict[str, Histogram] = {}     # name -> log-bucket distribution
# both rings drop OLDEST on overflow (drops counted): the tail is what a
# postmortem needs — the black box must hold what the worker was doing
# when it died, not what it did at boot
_TRACE: Deque[dict] = deque(maxlen=_TRACE_CAP)  # chrome-trace "X" events
_EVENTS: Deque[dict] = deque(maxlen=_EVENT_CAP)  # discrete annotated events

_TLS = threading.local()  # per-thread span stack (nesting depth) + trace id


def enabled() -> bool:
    return _ENABLED


def enable() -> None:
    """Turn telemetry on at runtime (tests; equivalent of the env gate).
    Arms the atexit JSONL dump if QRACK_TPU_TELEMETRY_OUT is set."""
    global _ENABLED
    _ENABLED = True
    from . import export

    export.arm_atexit()


def disable() -> None:
    global _ENABLED
    _ENABLED = False


def reset() -> None:
    """Drop all recorded data (counters, spans, hists, traces, events).
    The rings are rebuilt from the CURRENT cap globals, so tests may
    shrink ``_EVENT_CAP``/``_TRACE_CAP`` and reset to apply them."""
    global _TRACE, _EVENTS
    with _LOCK:
        _COUNTERS.clear()
        _GAUGES.clear()
        _SPANS.clear()
        _HISTS.clear()
        _TRACE = deque(maxlen=_TRACE_CAP)
        _EVENTS = deque(maxlen=_EVENT_CAP)


# ---------------------------------------------------------------------------
# counters + events
# ---------------------------------------------------------------------------

def inc(name: str, n: float = 1) -> None:
    """Add `n` to the named monotonic counter (no-op when disabled)."""
    if not _ENABLED:
        return
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + n


def gauge(name: str, value: float) -> None:
    """Record the latest value of a named gauge (last-write-wins; the
    serving layer uses these for queue depth / p50-p99 latencies)."""
    if not _ENABLED:
        return
    with _LOCK:
        _GAUGES[name] = float(value)


def observe(name: str, seconds: float) -> None:
    """Feed one measured duration into the named span aggregate AND the
    named log-bucket histogram, without a context manager — for
    durations measured externally (queue waits, per-job latencies)
    where enter/exit bracketing does not fit.  The histogram is what
    :func:`percentile` and the fleet SLO gauges read; the name space is
    bounded (`QRACK_TPU_TELEMETRY_HIST_CAP`) against label cardinality
    blowups — overflow names keep their span aggregate but drop the
    distribution (counted in ``telemetry.hists.dropped``)."""
    if not _ENABLED:
        return
    with _LOCK:
        agg = _SPANS.get(name)
        if agg is None:
            _SPANS[name] = [1, seconds, seconds, seconds]
        else:
            agg[0] += 1
            agg[1] += seconds
            agg[2] = min(agg[2], seconds)
            agg[3] = max(agg[3], seconds)
        h = _HISTS.get(name)
        if h is None:
            if len(_HISTS) >= _HIST_CAP:
                _COUNTERS["telemetry.hists.dropped"] = \
                    _COUNTERS.get("telemetry.hists.dropped", 0) + 1
                return
            h = _HISTS[name] = Histogram()
        h.record(seconds)


def percentile(name: str, q: float) -> Optional[float]:
    """p`q` of the named observed distribution (None when unrecorded)."""
    with _LOCK:
        h = _HISTS.get(name)
        return h.percentile(q) if h is not None else None


def event(name: str, **fields) -> None:
    """Record a discrete annotated event AND bump its counter.  The
    event ring holds the most recent QRACK_TPU_TELEMETRY_EVENT_CAP
    events (drop-OLDEST; evictions are counted) — postmortems need the
    tail, not the boot transcript.  The thread's current trace id, if
    any, is attached."""
    if not _ENABLED:
        return
    tid = getattr(_TLS, "trace", None)
    if tid is not None and "trace" not in fields:
        fields["trace"] = tid
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + 1
        if len(_EVENTS) == _EVENTS.maxlen:
            _COUNTERS["telemetry.events.dropped"] = \
                _COUNTERS.get("telemetry.events.dropped", 0) + 1
        _EVENTS.append({"name": name,
                        "t_s": time.perf_counter() - _EPOCH, **fields})


# ---------------------------------------------------------------------------
# distributed trace context
# ---------------------------------------------------------------------------

def set_trace(trace_id: Optional[str]) -> Optional[str]:
    """Set (or clear, with None) the calling thread's distributed-trace
    id; returns the previous value so callers can restore it.  Spans and
    events recorded while set carry ``trace: <id>``, which is how one
    submit's work is correlated across the front door and its worker."""
    prev = getattr(_TLS, "trace", None)
    _TLS.trace = trace_id
    return prev


def current_trace() -> Optional[str]:
    return getattr(_TLS, "trace", None)


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

class _NullSpan:
    """Shared do-nothing context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("name", "sync", "t0", "depth", "trace")

    def __init__(self, name: str, sync=None, trace=None):
        self.name = name
        self.sync = sync
        self.trace = trace

    def __enter__(self):
        stack = getattr(_TLS, "stack", None)
        if stack is None:
            stack = _TLS.stack = []
        self.depth = len(stack)
        stack.append(self.name)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self.sync is not None:
            # honest completion: a real device->host read, then subtract
            # the empty-queue round trip of that read itself
            # (utils/timing.py devget_sync / empty_queue_sync_s —
            # block_until_ready over the relay acks dispatch only)
            from ..utils.timing import devget_sync, empty_queue_sync_s

            devget_sync(self.sync)
            t1 = time.perf_counter()
            sync_s = empty_queue_sync_s(self.sync, reps=1)
            wall = max(t1 - self.t0 - sync_s, 0.0)
        else:
            wall = time.perf_counter() - self.t0
        _TLS.stack.pop()
        trace = self.trace if self.trace is not None \
            else getattr(_TLS, "trace", None)
        entry = {
            "name": self.name,
            "ts_s": self.t0 - _EPOCH,
            "dur_s": wall,
            "tid": threading.get_ident(),
            "depth": self.depth,
            "synced": self.sync is not None,
        }
        if trace is not None:
            entry["trace"] = trace
        with _LOCK:
            agg = _SPANS.get(self.name)
            if agg is None:
                _SPANS[self.name] = [1, wall, wall, wall]
            else:
                agg[0] += 1
                agg[1] += wall
                agg[2] = min(agg[2], wall)
                agg[3] = max(agg[3], wall)
            if len(_TRACE) == _TRACE.maxlen:
                # drop-OLDEST ring, same rationale as the event ring
                _COUNTERS["telemetry.trace.dropped"] = \
                    _COUNTERS.get("telemetry.trace.dropped", 0) + 1
            _TRACE.append(entry)
        return False


def span(name: str, sync=None, trace=None):
    """Nestable wall-clock timer.  `sync` takes the device array (e.g.
    the (2, 2^n) planes) whose queue the span must drain before its
    clock stops — without it the span is an untrusted host wall.
    `trace` pins a distributed-trace id on the recorded span (defaults
    to the thread's :func:`current_trace` — pass it explicitly when the
    span runs on a different thread than the one that minted the id,
    e.g. the executor's dispatch owner)."""
    if not _ENABLED:
        return _NULL_SPAN
    return _Span(name, sync, trace)


def record_span(name: str, start_s: float, dur_s: float,
                trace=None) -> None:
    """Append an already-measured interval to the trace ring and span
    aggregates — for callers that own their own stopwatch (e.g. the
    executor re-emitting a job's t_submit->t_done serve latency so the
    merged fleet timeline carries one bar per job and the raw durations
    can cross-check the bucketed histogram gauges).  `start_s` is a
    ``time.perf_counter()`` reading from THIS process."""
    if not _ENABLED:
        return
    if trace is None:
        trace = getattr(_TLS, "trace", None)
    entry = {
        "name": name,
        "ts_s": start_s - _EPOCH,
        "dur_s": dur_s,
        "tid": threading.get_ident(),
        "depth": 0,
        "synced": False,
    }
    if trace is not None:
        entry["trace"] = trace
    with _LOCK:
        agg = _SPANS.get(name)
        if agg is None:
            _SPANS[name] = [1, dur_s, dur_s, dur_s]
        else:
            agg[0] += 1
            agg[1] += dur_s
            agg[2] = min(agg[2], dur_s)
            agg[3] = max(agg[3], dur_s)
        if len(_TRACE) == _TRACE.maxlen:
            _COUNTERS["telemetry.trace.dropped"] = \
                _COUNTERS.get("telemetry.trace.dropped", 0) + 1
        _TRACE.append(entry)


# ---------------------------------------------------------------------------
# compile-cache accounting
# ---------------------------------------------------------------------------

class _JitProgram:
    """Transparent wrapper over a module-level jitted program that
    counts `compile.<name>.miss` (a call that grew the jit cache — XLA
    compiled) vs `.hit` (dispatch straight from cache).  Disabled path:
    one boolean test, then the raw call."""

    __slots__ = ("_fn", "_name")

    def __init__(self, name: str, fn):
        self._fn = fn
        self._name = name

    def __call__(self, *args, **kwargs):
        if not _ENABLED:
            return self._fn(*args, **kwargs)
        try:
            before = self._fn._cache_size()
        except Exception:
            before = None
        out = self._fn(*args, **kwargs)
        if before is None:
            inc(f"compile.{self._name}.call")
        elif self._fn._cache_size() > before:
            inc(f"compile.{self._name}.miss")
        else:
            inc(f"compile.{self._name}.hit")
        return out

    def __getattr__(self, attr):  # lower/_cache_size/etc. pass through
        return getattr(self._fn, attr)


def instrument_jit(name: str, fn):
    """Wrap a jitted callable for per-call compile hit/miss counting."""
    return _JitProgram(name, fn)


class ProgramCache:
    """Bounded LRU of compiled programs with hit/miss/eviction stats.

    Replacement for the module-global ``_PROGRAMS: dict`` pattern: a
    long-lived process no longer accumulates one compiled program (and
    its closed-over mesh) per key forever.  Keys are tuples; a key part
    produced by :meth:`mesh_token` is weakly tied to its mesh — when the
    mesh is garbage-collected every entry keyed to it is dropped, so
    dead meshes cannot pin compiled programs until LRU pressure.

    Stats are kept unconditionally (they are O(1) ints); the telemetry
    counters mirror them only while telemetry is enabled.
    """

    def __init__(self, name: str, cap: Optional[int] = None,
                 cap_env: str = "QRACK_TPU_PROGRAM_CACHE_CAP",
                 default_cap: int = 256):
        if cap is None:
            cap = int(os.environ.get(cap_env, str(default_cap)))
        self.name = name
        self.cap = max(1, cap)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self._od: "OrderedDict" = OrderedDict()
        self._lock = threading.Lock()

    def get_or_build(self, key, builder):
        with self._lock:
            fn = self._od.get(key)
            if fn is not None:
                self._od.move_to_end(key)
                self.hits += 1
                if _ENABLED:
                    inc(f"compile.{self.name}.hit")
                return fn
        fn = builder()  # build outside the lock: builders trace/compile
        with self._lock:
            self._od[key] = fn
            self._od.move_to_end(key)
            self.misses += 1
            if _ENABLED:
                inc(f"compile.{self.name}.miss")
            while len(self._od) > self.cap:
                self._od.popitem(last=False)
                self.evictions += 1
                if _ENABLED:
                    inc(f"compile.{self.name}.eviction")
        return fn

    def mesh_token(self, mesh) -> int:
        """A cache-key part for `mesh` that is weakly tied to it: a
        finalizer drops every entry containing the token once the mesh
        is collected (id() alone would let dead meshes pin programs)."""
        import weakref

        token = id(mesh)
        try:
            weakref.finalize(mesh, self._drop_token, token)
        except TypeError:
            pass  # non-weakref-able key source: LRU cap still bounds us
        return token

    def _drop_token(self, token: int) -> None:
        def has(part) -> bool:
            if part == token and isinstance(part, int):
                return True
            if isinstance(part, tuple):
                return any(has(p) for p in part)
            return False

        with self._lock:
            dead = [k for k in self._od if has(k)]
            for k in dead:
                del self._od[k]
                self.evictions += 1
            if dead and _ENABLED:
                inc(f"compile.{self.name}.eviction", len(dead))

    def stats(self) -> dict:
        return {"size": len(self._od), "cap": self.cap, "hits": self.hits,
                "misses": self.misses, "evictions": self.evictions}

    def __len__(self) -> int:
        return len(self._od)

    def __contains__(self, key) -> bool:
        return key in self._od

    def clear(self) -> None:
        with self._lock:
            self._od.clear()


# ---------------------------------------------------------------------------
# snapshot
# ---------------------------------------------------------------------------

def snapshot(include_events: bool = True) -> dict:
    """Plain-dict view of everything recorded so far (JSON-safe).

    Besides the raw stores, the snapshot *publishes* SLO gauges: every
    observed distribution contributes ``<name>.p50/.p95/.p99`` to the
    returned ``gauges`` (computed at snapshot time, never stored — a
    stale percentile gauge would outlive its histogram).  The
    ``epoch_unix_s`` wall anchor converts this process's relative span
    timestamps to absolute time for cross-process merging."""
    with _LOCK:
        gauges = dict(_GAUGES)
        hists = {name: h.to_dict() for name, h in _HISTS.items()}
        for name, h in _HISTS.items():
            for pname, v in h.percentiles().items():
                if v is not None:
                    gauges[f"{name}.{pname}"] = v
        out = {
            "enabled": _ENABLED,
            "pid": os.getpid(),
            "epoch_unix_s": _EPOCH_WALL,
            "counters": dict(_COUNTERS),
            "gauges": gauges,
            "hists": hists,
            "spans": {
                name: {"count": int(agg[0]), "total_s": agg[1],
                       "min_s": agg[2], "max_s": agg[3]}
                for name, agg in _SPANS.items()
            },
        }
        if include_events:
            out["events"] = list(_EVENTS)
    return out


def merge_snapshots(snaps) -> dict:
    """Fold N snapshot dicts (one per process/incarnation) into one:
    counters sum, span aggregates combine, histograms merge cell-wise,
    gauges last-write-wins in input order — EXCEPT the SLO percentile
    gauges, which are recomputed from the merged distributions (a
    fleet p99 is not any worker's p99)."""
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    spans: Dict[str, dict] = {}
    hists: Dict[str, Histogram] = {}
    for s in snaps:
        for k, v in (s.get("counters") or {}).items():
            counters[k] = counters.get(k, 0) + v
        gauges.update(s.get("gauges") or {})
        for k, d in (s.get("spans") or {}).items():
            agg = spans.get(k)
            if agg is None:
                spans[k] = dict(d)
            else:
                agg["count"] += d["count"]
                agg["total_s"] += d["total_s"]
                agg["min_s"] = min(agg["min_s"], d["min_s"])
                agg["max_s"] = max(agg["max_s"], d["max_s"])
        for k, d in (s.get("hists") or {}).items():
            h = hists.get(k)
            if h is None:
                hists[k] = Histogram.from_dict(d)
            else:
                h.merge(d)
    for name, h in hists.items():
        for pname, v in h.percentiles().items():
            if v is not None:
                gauges[f"{name}.{pname}"] = v
    return {"counters": counters, "gauges": gauges,
            "hists": {k: h.to_dict() for k, h in hists.items()},
            "spans": spans}


# exporters live in export.py; re-export the public surface
from .export import (  # noqa: E402  (cycle-safe: export imports nothing above lazily)
    chrome_trace, local_trace_source, merged_chrome_trace,
    write_chrome_trace, write_jsonl, write_merged_chrome_trace,
    xplane_bracket,
)
from .blackbox import FlightRecorder, read_blackbox  # noqa: E402

# arm the atexit JSONL dump when the env gate + out path are both set
if _ENABLED and os.environ.get("QRACK_TPU_TELEMETRY_OUT"):
    from .export import arm_atexit as _arm

    _arm()
