"""Shared roofline formula, device peak table, and the perf-regression sentinel.

This module is deliberately **stdlib-only**: no jax, no numpy, and no
package-relative imports.  ``scripts/perf_sentinel.py`` loads it directly by
file path with ``importlib`` so the campaign's evidence bookkeeping (which
runs under ``env -u PYTHONPATH`` while the axon tunnel may be wedged) can
never hang on backend init.  Everything here is the single source of truth:

- ``implied_gbps``      — the one implied-bandwidth formula (bytes/wall/1e9)
  that bench.py, turboquant_bench.py, microbench.py, and the campaign stages
  previously hand-rolled three-plus times.
- ``PEAK_GBPS`` / ``peak_gbps`` — the one per-device-class HBM peak table
  (v5e 819 GB/s default), env-overridable via ``QRACK_TPU_PEAK_GBPS``.
- ``plane_pass_bytes``  — bytes moved by one full sweep over the two ket
  planes (read + write).
- Trajectory loading + verdicts — parse the committed evidence
  (``docs/tpu_results.jsonl`` and the embedded JSONL ``"tail"`` strings in
  ``BENCH_*.json``) and stamp every fresh line better/same/worse/new within
  a noise band (``QRACK_SENTINEL_NOISE_BAND``, default 10%).
"""

from __future__ import annotations

import glob
import json
import os
import time
from typing import Dict, List, Optional

# HBM peak bandwidth per device class, GB/s.  Matched by substring against a
# lowercased device-kind string (jax reports e.g. "TPU v5 lite").  The v5e
# figure (819) is the number every committed evidence line has been
# honesty-checked against; it is also the fallback for cpu/unknown so CPU
# anchor lines quote their fraction of the *accelerator* roofline.
DEFAULT_PEAK_GBPS = 819.0
PEAK_GBPS = (
    ("v5 lite", 819.0),
    ("v5e", 819.0),
    ("v5litepod", 819.0),
    ("v5p", 2765.0),
    ("v6e", 1640.0),
    ("trillium", 1640.0),
    ("v4", 1228.0),
    ("v3", 900.0),
    ("v2", 700.0),
)

# Relative noise band for sentinel verdicts: a fresh wall within +/- band of
# the best committed wall is "same".
DEFAULT_NOISE_BAND = 0.10

VERDICTS = ("better", "same", "worse", "new", "replay")


def peak_gbps(kind: Optional[str]) -> float:
    """Peak HBM GB/s for a device-kind string; env override wins."""
    env = os.environ.get("QRACK_TPU_PEAK_GBPS", "")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    low = (kind or "").lower()
    for sub, peak in PEAK_GBPS:
        if sub in low:
            return peak
    return DEFAULT_PEAK_GBPS


def noise_band() -> float:
    env = os.environ.get("QRACK_SENTINEL_NOISE_BAND", "")
    if env:
        try:
            return max(0.0, float(env))
        except ValueError:
            pass
    return DEFAULT_NOISE_BAND


def implied_gbps(nbytes: float, wall_s: float) -> float:
    """The one implied-bandwidth formula: bytes moved / wall seconds / 1e9."""
    return float(nbytes) / max(float(wall_s), 1e-12) / 1e9


def plane_pass_bytes(width: int, esize: int = 4) -> int:
    """HBM bytes for one full sweep over the ket: 2 planes * 2^width amps
    * esize bytes, read + write."""
    return 2 * (1 << int(width)) * int(esize) * 2


def is_clamped(line: dict, peak: Optional[float] = None) -> bool:
    """True when a line's implied bandwidth exceeds the device-class peak —
    the relay-ack signature (dispatch acked, completion never timed)."""
    gbps = line.get("implied_hbm_gbps")
    if gbps is None:
        gbps = line.get("implied_codes_gbps")
    if gbps is None:
        return False
    if peak is None:
        dev = line.get("device_class") or {}
        peak = dev.get("peak_gbps") or peak_gbps(dev.get("kind"))
    try:
        return float(gbps) > float(peak)
    except (TypeError, ValueError):
        return False


# ---------------------------------------------------------------------------
# Committed-trajectory loading and verdicts
# ---------------------------------------------------------------------------

def line_key(line: dict) -> Optional[str]:
    """Stable comparison key for an evidence line (replay suffix folded in)."""
    metric = line.get("metric")
    if metric:
        key = str(metric)
        if key.endswith("_committed_evidence"):
            key = key[: -len("_committed_evidence")]
        return key
    gate = line.get("gate")
    if gate:
        key = "gate_%s_w%s" % (gate, line.get("width", "?"))
        bits = line.get("bits")
        if bits:
            key += "_b%s" % bits
        return key
    return None


def line_value(line: dict) -> Optional[float]:
    """Lower-is-better wall seconds for an evidence line, or None."""
    for field in ("value", "wall_s", "avg_wall_s", "avg"):
        v = line.get(field)
        if v is not None:
            try:
                v = float(v)
            except (TypeError, ValueError):
                return None
            return v if v > 0 else None
    return None


def _iter_jsonl(text: str):
    for raw in text.splitlines():
        raw = raw.strip()
        if not raw.startswith("{"):
            continue
        try:
            d = json.loads(raw)
        except ValueError:
            continue
        if isinstance(d, dict):
            yield d


def load_trajectory(root: str = ".") -> Dict[str, List[float]]:
    """Committed per-key wall history from docs/tpu_results.jsonl and the
    embedded JSONL ``"tail"`` strings of BENCH_*.json / MULTICHIP_*.json."""
    hist: Dict[str, List[float]] = {}

    def add(d: dict) -> None:
        if d.get("suspect_timing") or d.get("roofline_clamped"):
            return
        key, val = line_key(d), line_value(d)
        if key and val is not None:
            hist.setdefault(key, []).append(val)

    jsonl = os.path.join(root, "docs", "tpu_results.jsonl")
    if os.path.exists(jsonl):
        try:
            with open(jsonl) as fh:
                for d in _iter_jsonl(fh.read()):
                    add(d)
        except OSError:
            pass
    for pat in ("BENCH_*.json", "MULTICHIP_*.json"):
        for path in sorted(glob.glob(os.path.join(root, pat))):
            try:
                with open(path) as fh:
                    doc = json.load(fh)
            except (OSError, ValueError):
                continue
            tail = doc.get("tail") if isinstance(doc, dict) else None
            if isinstance(tail, str):
                for d in _iter_jsonl(tail):
                    add(d)
    return hist


def verdict(key: Optional[str], value: Optional[float],
            traj: Dict[str, List[float]],
            band: Optional[float] = None) -> str:
    """Compare a fresh wall against the best committed wall for its key."""
    if key is None or value is None:
        return "new"
    prior = traj.get(key)
    if not prior:
        return "new"
    if band is None:
        band = noise_band()
    best = min(prior)
    if value <= best * (1.0 - band):
        return "better"
    if value >= best * (1.0 + band):
        return "worse"
    return "same"


def stamp(line: dict, traj: Dict[str, List[float]],
          band: Optional[float] = None) -> str:
    """Stamp sentinel verdict (+ reference wall) into a line, in place.
    Replayed `_committed_evidence` lines get the "replay" verdict so they are
    distinguishable from fresh on-chip measurements at a glance."""
    metric = str(line.get("metric") or "")
    if metric.endswith("_committed_evidence") or line.get("replayed"):
        line["sentinel"] = "replay"
        line["fresh"] = False
        return "replay"
    key, val = line_key(line), line_value(line)
    v = verdict(key, val, traj, band)
    line["sentinel"] = v
    line["fresh"] = True
    prior = traj.get(key or "")
    if prior:
        line["sentinel_ref_wall_s"] = min(prior)
        line["sentinel_band"] = band if band is not None else noise_band()
    return v


def stamp_evidence_line(line: dict, traj: Dict[str, List[float]],
                        stage: Optional[str] = None,
                        default_device: Optional[dict] = None) -> dict:
    """Full campaign-evidence stamping: timestamp, stage, sentinel verdict,
    and a device-class fingerprint (kept if the line already carries one)."""
    line.setdefault("ts", time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
    if stage:
        line.setdefault("stage", stage)
    stamp(line, traj)
    if "device_class" not in line:
        dev = dict(default_device or {})
        if not dev:
            kind = os.environ.get("QRACK_TPU_DEVICE_KIND", "") or "unknown"
            dev = {"kind": kind, "peak_gbps": peak_gbps(kind)}
        line["device_class"] = dev
    return line
