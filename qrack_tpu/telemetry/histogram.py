"""Bounded log-bucket histogram: the merge-able distribution type
behind :func:`qrack_tpu.telemetry.observe`.

Buckets are geometric with ``SUBBUCKETS`` sub-buckets per octave
(ratio ``2**(1/8) ~ 1.09``), so any reported percentile is within
``2**(1/16) - 1 ~ 4.4%`` of the true sample — comfortably inside the
10% SLO-accuracy bar in docs/OBSERVABILITY.md — while a histogram
spanning a nanosecond to ~34 years of latency costs at most
``IDX_MAX - IDX_MIN + 1`` integer cells.  The bucket array is sparse
(dict) and JSON-safe via :meth:`to_dict`, which is what rides in
heartbeat records and fleet JSONL; :meth:`merge` adds another
histogram (or its dict form) cell-wise, which is exactly how the
supervisor folds N worker processes into one fleet distribution.

Exact ``min``/``max``/``sum``/``count`` are carried alongside the
buckets, so merged extremes stay exact and every percentile is clamped
into ``[min, max]``.
"""

from __future__ import annotations

import math
from typing import Dict, Iterable, Optional

SUBBUCKETS = 8                      # sub-buckets per octave (2x range)
_INV_LN2_SUB = SUBBUCKETS / math.log(2.0)
IDX_MIN = -30 * SUBBUCKETS          # ~1e-9 s: clamp, don't grow, below
IDX_MAX = 30 * SUBBUCKETS           # ~1e9 s: clamp, don't grow, above
_TINY = 2.0 ** -30


def bucket_index(value: float) -> int:
    """Bucket index for a positive value (non-positive values clamp to
    the lowest bucket — durations are never negative in practice)."""
    if value <= _TINY:
        return IDX_MIN
    i = math.floor(math.log(value) * _INV_LN2_SUB)
    if i < IDX_MIN:
        return IDX_MIN
    if i > IDX_MAX:
        return IDX_MAX
    return i


def bucket_mid(index: int) -> float:
    """Geometric midpoint of a bucket — the value a percentile reports."""
    return 2.0 ** ((index + 0.5) / SUBBUCKETS)


class Histogram:
    """Mergeable log-bucket histogram of non-negative samples."""

    __slots__ = ("count", "sum", "min", "max", "buckets")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.buckets: Dict[int, int] = {}

    # -- recording -----------------------------------------------------

    def record(self, value: float) -> None:
        value = float(value)
        self.count += 1
        self.sum += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value
        i = bucket_index(value)
        self.buckets[i] = self.buckets.get(i, 0) + 1

    @classmethod
    def of(cls, values: Iterable[float]) -> "Histogram":
        h = cls()
        for v in values:
            h.record(v)
        return h

    # -- accessors -----------------------------------------------------

    @property
    def mean(self) -> Optional[float]:
        return (self.sum / self.count) if self.count else None

    def percentile(self, q: float) -> Optional[float]:
        """Nearest-rank percentile (`q` in [0, 100]) from the bucket
        midpoints, clamped into the exact observed [min, max]."""
        if not self.count:
            return None
        target = max(1, math.ceil((q / 100.0) * self.count))
        cum = 0
        for i in sorted(self.buckets):
            cum += self.buckets[i]
            if cum >= target:
                return min(max(bucket_mid(i), self.min), self.max)
        return self.max  # unreachable unless counts drifted

    def percentiles(self, qs=(50, 95, 99)) -> Dict[str, Optional[float]]:
        return {f"p{q:g}": self.percentile(q) for q in qs}

    # -- merge + codec -------------------------------------------------

    def merge(self, other) -> "Histogram":
        """Fold another histogram (or its :meth:`to_dict` form) into
        this one, cell-wise; returns self."""
        if isinstance(other, dict):
            other = Histogram.from_dict(other)
        if not other.count:
            return self
        self.count += other.count
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        for i, c in other.buckets.items():
            self.buckets[i] = self.buckets.get(i, 0) + c
        return self

    def to_dict(self) -> dict:
        """JSON-safe form (bucket keys become strings)."""
        out = {"count": self.count, "sum": self.sum,
               "buckets": {str(i): c for i, c in self.buckets.items()}}
        if self.count:
            out["min"] = self.min
            out["max"] = self.max
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "Histogram":
        h = cls()
        h.count = int(d.get("count", 0))
        h.sum = float(d.get("sum", 0.0))
        h.min = float(d.get("min", math.inf))
        h.max = float(d.get("max", -math.inf))
        h.buckets = {int(i): int(c)
                     for i, c in (d.get("buckets") or {}).items()}
        return h

    @classmethod
    def merge_all(cls, dicts: Iterable) -> "Histogram":
        h = cls()
        for d in dicts:
            h.merge(d)
        return h

    def __repr__(self):
        if not self.count:
            return "Histogram(empty)"
        return (f"Histogram(n={self.count}, min={self.min:.3g}, "
                f"p50={self.percentile(50):.3g}, "
                f"p99={self.percentile(99):.3g}, max={self.max:.3g})")


__all__ = ["Histogram", "SUBBUCKETS", "IDX_MIN", "IDX_MAX",
           "bucket_index", "bucket_mid"]
