"""Telemetry exporters: JSONL snapshots, Chrome trace-event JSON, and
the jax.profiler xplane bracket.

Formats:

* **JSONL** — one :func:`qrack_tpu.telemetry.snapshot` dict per line,
  appended (a long campaign accumulates a history; consumers take the
  last line).  Armed at process exit by ``QRACK_TPU_TELEMETRY_OUT``.
* **Chrome trace-event JSON** — the `{"traceEvents": [...]}` object
  format (https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU):
  spans become ``"ph": "X"`` complete events, discrete telemetry events
  become ``"ph": "i"`` instants, and every counter's final value is one
  ``"ph": "C"`` sample at the end of the trace.  Loads directly in
  Perfetto / chrome://tracing.
* **Merged fleet trace** — :func:`merged_chrome_trace` folds N
  processes' trace sources (live snapshots or flight-recorder black
  boxes, each carrying its own ``epoch_unix_s`` wall anchor) into ONE
  Perfetto-loadable timeline, one track per worker incarnation, with
  every span's distributed-trace id in its args — so a single submit
  can be followed from the front door's ``frontdoor.apply`` through the
  worker's ``worker.submit.journal`` to the executor's
  ``serve.execute`` devget on one screen.
* **xplane** — :func:`xplane_bracket` wraps ``jax.profiler``
  start/stop_trace; the resulting ``*.xplane.pb`` dumps are what
  ``scripts/analyze_xplane.py`` parses for on-device op walls.
"""

from __future__ import annotations

import contextlib
import json
import os
from typing import Optional

_US = 1e6
_ATEXIT_ARMED = False


def write_jsonl(path: Optional[str] = None) -> str:
    """Append one snapshot line to `path` (default:
    QRACK_TPU_TELEMETRY_OUT).  Returns the path written."""
    from . import snapshot

    if path is None:
        path = os.environ.get("QRACK_TPU_TELEMETRY_OUT", "")
    if not path:
        raise ValueError(
            "no output path: pass one or set QRACK_TPU_TELEMETRY_OUT")
    with open(path, "a") as f:
        f.write(json.dumps(snapshot()) + "\n")
    return path


def _dump() -> None:
    """The registered exit hook: re-reads the enable gate and the out
    path at exit time, and never raises."""
    from . import _ENABLED

    if _ENABLED and os.environ.get("QRACK_TPU_TELEMETRY_OUT"):
        try:
            write_jsonl()
        except Exception:
            pass  # exit hooks must never raise


def arm_atexit() -> None:
    """Register the one-shot exit dump (idempotent; no-op without an
    out path at exit time)."""
    global _ATEXIT_ARMED
    if _ATEXIT_ARMED:
        return
    _ATEXIT_ARMED = True
    import atexit

    atexit.register(_dump)


def chrome_trace() -> dict:
    """Trace-event JSON object for the current telemetry state."""
    from . import _EVENTS, _LOCK, _TRACE, snapshot

    pid = os.getpid()
    evs = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": "qrack_tpu"},
    }]
    with _LOCK:
        trace = list(_TRACE)
        events = list(_EVENTS)
    end_us = 0.0
    for t in trace:
        ts = t["ts_s"] * _US
        dur = t["dur_s"] * _US
        end_us = max(end_us, ts + dur)
        evs.append({
            "name": t["name"], "ph": "X", "cat": "span",
            "ts": ts, "dur": dur, "pid": pid, "tid": t["tid"],
            "args": {"depth": t["depth"], "synced": t["synced"]},
        })
    for e in events:
        ts = e["t_s"] * _US
        end_us = max(end_us, ts)
        args = {k: v for k, v in e.items() if k not in ("name", "t_s")}
        evs.append({
            "name": e["name"], "ph": "i", "cat": "event", "s": "p",
            "ts": ts, "pid": pid, "tid": 0, "args": args,
        })
    snap = snapshot(include_events=False)
    for name, value in sorted(snap["counters"].items()):
        evs.append({
            "name": name, "ph": "C", "ts": end_us, "pid": pid, "tid": 0,
            "args": {"value": value},
        })
    # roofline gauges ride as counter tracks too: achieved-vs-peak
    # fractions next to the spans that produced them
    for name, value in sorted(snap["gauges"].items()):
        if name.startswith("roofline."):
            evs.append({
                "name": name, "ph": "C", "ts": end_us, "pid": pid,
                "tid": 0, "args": {"value": value},
            })
    return {"traceEvents": evs, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str) -> str:
    with open(path, "w") as f:
        json.dump(chrome_trace(), f)
    return path


# ---------------------------------------------------------------------------
# merged fleet trace
# ---------------------------------------------------------------------------

def local_trace_source(name: Optional[str] = None) -> dict:
    """This process's trace rings as a merge source for
    :func:`merged_chrome_trace` (same shape as a flight-recorder black
    box: name/pid/epoch_unix_s/spans/events)."""
    from . import _EPOCH_WALL, _EVENTS, _GAUGES, _LOCK, _TRACE

    pid = os.getpid()
    with _LOCK:
        return {"name": name or f"pid{pid}", "pid": pid,
                "epoch_unix_s": _EPOCH_WALL,
                "spans": list(_TRACE), "events": list(_EVENTS),
                "gauges": dict(_GAUGES)}


def merged_chrome_trace(sources) -> dict:
    """One Perfetto-loadable timeline from N processes' trace sources.

    Each source dict carries ``name`` (track label), ``pid``,
    ``epoch_unix_s`` (the wall clock at that process's telemetry import
    — see telemetry/__init__.py), and ``spans``/``events`` ring dumps.
    Relative timestamps are re-anchored as ``epoch_unix_s + ts_s`` and
    normalized to the earliest instant across the fleet, so spans from
    different processes land in true wall-clock order.  Every source
    gets its OWN display pid (sequential) even when OS pids collide —
    one track per worker incarnation; span trace ids ride in ``args``
    so Perfetto's query/args panel correlates a submit across tracks.
    """
    evs = []
    anchors = []
    for src in sources:
        epoch = float(src.get("epoch_unix_s") or 0.0)
        for t in src.get("spans") or []:
            anchors.append(epoch + t["ts_s"])
        for e in src.get("events") or []:
            anchors.append(epoch + e["t_s"])
    t0 = min(anchors) if anchors else 0.0
    for disp_pid, src in enumerate(sources, start=1):
        epoch = float(src.get("epoch_unix_s") or 0.0)
        label = src.get("name") or f"pid{src.get('pid')}"
        evs.append({"name": "process_name", "ph": "M", "pid": disp_pid,
                    "tid": 0,
                    "args": {"name": f"{label} (pid {src.get('pid')})"}})
        for t in src.get("spans") or []:
            args = {"depth": t.get("depth"), "synced": t.get("synced")}
            if t.get("trace") is not None:
                args["trace"] = t["trace"]
            evs.append({
                "name": t["name"], "ph": "X", "cat": "span",
                "ts": (epoch + t["ts_s"] - t0) * _US,
                "dur": t["dur_s"] * _US,
                "pid": disp_pid, "tid": t.get("tid", 0), "args": args,
            })
        src_end = 0.0
        for e in src.get("events") or []:
            args = {k: v for k, v in e.items() if k not in ("name", "t_s")}
            evs.append({
                "name": e["name"], "ph": "i", "cat": "event", "s": "p",
                "ts": (epoch + e["t_s"] - t0) * _US,
                "pid": disp_pid, "tid": 0, "args": args,
            })
        for t in src.get("spans") or []:
            src_end = max(src_end, (epoch + t["ts_s"] - t0 + t["dur_s"]))
        for e in src.get("events") or []:
            src_end = max(src_end, (epoch + e["t_s"] - t0))
        # roofline gauges (live snapshots and flight-recorder black
        # boxes both carry them) become per-source Perfetto counter
        # tracks, sampled at that source's last instant
        for gname, gval in sorted((src.get("gauges") or {}).items()):
            if gname.startswith("roofline."):
                evs.append({
                    "name": gname, "ph": "C", "ts": src_end * _US,
                    "pid": disp_pid, "tid": 0, "args": {"value": gval},
                })
    return {"traceEvents": evs, "displayTimeUnit": "ms"}


def write_merged_chrome_trace(path: str, sources) -> str:
    with open(path, "w") as f:
        json.dump(merged_chrome_trace(sources), f)
    return path


@contextlib.contextmanager
def xplane_bracket(logdir: Optional[str] = None, name: str = "telemetry"):
    """Bracket a region with a jax.profiler trace when telemetry is on
    and a log dir is configured (arg or QRACK_TPU_TELEMETRY_XPLANE);
    otherwise a pass-through.  The dump under `logdir` is the input to
    scripts/analyze_xplane.py."""
    from . import _ENABLED, event

    if logdir is None:
        logdir = os.environ.get("QRACK_TPU_TELEMETRY_XPLANE", "")
    if not (_ENABLED and logdir):
        yield None
        return
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()
        event("telemetry.xplane.dump", logdir=logdir, region=name)
