"""Per-dispatch roofline ledger: planned HBM bytes vs devget-honest walls.

Every guarded dispatch site (``tpu.fuse.flush``, ``pager.exchange``,
``serve.dispatch``, turboquant sweeps) reports the bytes it *planned* to move
through :func:`note_bytes`; sites that also own an honest wall clock call
:func:`record`, which derives implied HBM bandwidth through the one shared
formula in :mod:`qrack_tpu.telemetry.sentinel` and publishes

- ``roofline.<site>.implied_hbm_gbps``   histogram (+ p50/p95/p99 gauges)
- ``roofline.<site>.peak_frac``          achieved-vs-peak-fraction gauge
  (with per-width / per-stack facets when the caller supplies them)
- ``roofline.<site>.planned_bytes`` / ``.dispatches`` counters

Timing honesty is structural: a sample whose implied bandwidth exceeds the
device-class peak is the relay-ack signature (dispatch acked, completion
never timed).  Such samples bump ``roofline.honesty.clamped`` (counter +
event) and ``roofline.<site>.clamped``, and are **excluded** from the
histogram and gauges — they can flag a campaign stage as failed but never
enter committed evidence.

The device-class fingerprint (kind, HBM bytes, peak GB/s) is captured from an
*already-initialized* jax backend only — this module never triggers backend
init, because init over a wedged axon tunnel hangs for hours — and is
persisted next to ``xla_cache`` in the checkpoint store as
``device_class.json`` (the substrate the roadmap's autotuner reads).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
from typing import Optional

from qrack_tpu import telemetry as _tele
from .sentinel import implied_gbps, peak_gbps, plane_pass_bytes  # noqa: F401

FINGERPRINT_FILE = "device_class.json"

_FP: Optional[dict] = None


def _probe_backend() -> dict:
    """Best-effort device identity from an already-initialized jax backend.

    Returns {} unless jax is imported AND a backend exists — probing must be
    free of side effects (no init, no RPC) so the ledger is safe to call from
    processes that never touched the device."""
    if "jax" not in sys.modules:
        return {}
    try:
        from jax._src import xla_bridge

        if not getattr(xla_bridge, "_backends", None):
            return {}
        import jax

        devs = jax.devices()
        dev = devs[0]
        out = {
            "platform": str(getattr(dev, "platform", "") or ""),
            "kind": str(getattr(dev, "device_kind", "") or ""),
            "n_devices": len(devs),
        }
        stats = getattr(dev, "memory_stats", None)
        if callable(stats):
            try:
                hbm = (stats() or {}).get("bytes_limit")
                if hbm:
                    out["hbm_bytes"] = int(hbm)
            except Exception:
                pass
        return out
    except Exception:
        return {}


def device_class(refresh: bool = False,
                 platform_hint: Optional[str] = None) -> dict:
    """The device-class fingerprint: kind, platform, HBM bytes, peak GB/s.

    Resolution order: ``QRACK_TPU_DEVICE_KIND`` env override, live backend
    probe (side-effect free), persisted fingerprint from the checkpoint
    store, then the caller's platform hint (e.g. a bench child's reported
    platform when the parent never imports jax)."""
    global _FP
    if _FP is not None and not refresh:
        if _FP.get("kind") not in ("", "unknown") or platform_hint is None:
            return dict(_FP)
    fp = {"kind": "unknown", "platform": "", "hbm_bytes": None}
    env_kind = os.environ.get("QRACK_TPU_DEVICE_KIND", "")
    probed = _probe_backend()
    if probed:
        fp["platform"] = probed.get("platform", "")
        fp["kind"] = probed.get("kind") or probed.get("platform") or "unknown"
        if probed.get("hbm_bytes"):
            fp["hbm_bytes"] = probed["hbm_bytes"]
        if probed.get("n_devices"):
            fp["n_devices"] = probed["n_devices"]
    else:
        loaded = load_fingerprint(os.environ.get(
            "QRACK_SERVE_CHECKPOINT_DIR", ""))
        if loaded:
            fp.update({k: loaded[k] for k in
                       ("kind", "platform", "hbm_bytes", "n_devices")
                       if k in loaded})
        elif platform_hint:
            fp["kind"] = fp["platform"] = str(platform_hint)
    if env_kind:
        fp["kind"] = env_kind
    fp["peak_gbps"] = peak_gbps(fp["kind"])
    _FP = dict(fp)
    return fp


def _reset_fingerprint_cache() -> None:
    """Test hook: drop the cached fingerprint."""
    global _FP
    _FP = None


def persist_fingerprint(checkpoint_dir: str) -> Optional[str]:
    """Write the fingerprint next to xla_cache as <dir>/device_class.json.

    A persisted known kind is never overwritten by an unknown one (the serve
    process may restart while the tunnel is wedged).  Best-effort: never
    raises."""
    try:
        fp = device_class()
        path = os.path.join(checkpoint_dir, FINGERPRINT_FILE)
        if fp.get("kind") in ("", "unknown"):
            prior = load_fingerprint(checkpoint_dir)
            if prior and prior.get("kind") not in ("", "unknown", None):
                return path
        os.makedirs(checkpoint_dir, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=checkpoint_dir, suffix=".tmp")
        with os.fdopen(fd, "w") as fh:
            json.dump(fp, fh, sort_keys=True)
        os.replace(tmp, path)
        return path
    except Exception:
        return None


def load_fingerprint(checkpoint_dir: str) -> Optional[dict]:
    if not checkpoint_dir:
        return None
    try:
        with open(os.path.join(checkpoint_dir, FINGERPRINT_FILE)) as fh:
            fp = json.load(fh)
        return fp if isinstance(fp, dict) else None
    except (OSError, ValueError):
        return None


def note_bytes(site: str, nbytes: float) -> None:
    """Ledger entry for a dispatch whose wall is timed elsewhere (or not at
    all): planned HBM bytes + dispatch count per site."""
    if not _tele._ENABLED:
        return
    _tele.inc(f"roofline.{site}.dispatches")
    _tele.inc(f"roofline.{site}.planned_bytes", float(nbytes))


def record(site: str, nbytes: float, wall_s: float,
           width: Optional[int] = None, stack: Optional[str] = None,
           platform: Optional[str] = None) -> dict:
    """Full roofline sample for a devget-honest dispatch: planned bytes +
    wall → implied GB/s, peak fraction, and the honesty clamp.

    Returns the sample dict (implied_hbm_gbps, hbm_peak_gbps,
    hbm_roofline_frac, clamped, device_class) for callers that stamp JSON
    lines; telemetry publication is skipped when disabled, but the sample is
    always computed."""
    gbps = implied_gbps(nbytes, wall_s)
    dev = device_class(platform_hint=platform)
    peak = dev["peak_gbps"]
    frac = gbps / peak if peak else 0.0
    clamped = gbps > peak
    sample = {
        "implied_hbm_gbps": round(gbps, 2),
        "hbm_peak_gbps": peak,
        "hbm_roofline_frac": round(frac, 4),
        "clamped": clamped,
        "device_class": dev,
    }
    if not _tele._ENABLED:
        return sample
    note_bytes(site, nbytes)
    if clamped:
        _tele.inc(f"roofline.{site}.clamped")
        _tele.event("roofline.honesty.clamped", site=site,
                    gbps=round(gbps, 1), peak=peak, width=width)
        return sample
    _tele.observe(f"roofline.{site}.implied_hbm_gbps", gbps)
    _tele.gauge(f"roofline.{site}.peak_frac", round(frac, 4))
    if width is not None:
        facet = f"{stack}.w{width}" if stack else f"w{width}"
        _tele.gauge(f"roofline.{site}.{facet}.peak_frac", round(frac, 4))
    return sample


def note_verdict(v: str) -> None:
    """Count a sentinel verdict (better/same/worse/new/replay)."""
    if _tele._ENABLED and v:
        _tele.inc(f"roofline.sentinel.{v}")
