"""Crash flight recorder: a bounded ring of recent telemetry, persisted
atomically so a kill -9 leaves a readable black box.

Each supervised worker owns one :class:`FlightRecorder` pointed at
``<store>/blackbox/<name>-<pid>.json`` (one file per worker
INCARNATION — a restarted worker must not overwrite the corpse the
supervisor is about to autopsy).  :meth:`flush` snapshots the last-N
events and spans plus the counter/gauge totals under the telemetry
lock and lands them with the same tmp+fsync+rename discipline as
heartbeats (fleet/heartbeat.py) — a reader never sees a torn file, and
the newest complete flush survives any crash.  Flushes piggyback on
the heartbeat cadence (worker info_fn), so the box is at most one beat
stale when the process dies.

The file doubles as a merge source for
:func:`~qrack_tpu.telemetry.export.merged_chrome_trace`: it carries
the process's ``epoch_unix_s`` wall anchor alongside the span ring, so
a dead worker's last moments land on the fleet timeline in true order.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

DEFAULT_LAST_N = 256


class FlightRecorder:
    """Atomically-persisted ring of this process's recent telemetry."""

    def __init__(self, path: str, name: Optional[str] = None,
                 last_n: int = DEFAULT_LAST_N):
        self.path = path
        self.name = name or os.path.basename(path)
        self.last_n = int(last_n)
        self.flushes = 0
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)

    def flush(self) -> dict:
        """Write the current black box; returns the dict written.
        No-op (returns {}) while telemetry is disabled."""
        from . import _ENABLED, _EPOCH_WALL, _EVENTS, _LOCK, _TRACE, snapshot

        if not _ENABLED:
            return {}
        with _LOCK:
            events = list(_EVENTS)[-self.last_n:]
            spans = list(_TRACE)[-self.last_n:]
        snap = snapshot(include_events=False)
        self.flushes += 1
        box = {
            "name": self.name,
            "pid": os.getpid(),
            "epoch_unix_s": _EPOCH_WALL,
            "t_wall": time.time(),
            "flush_seq": self.flushes,
            "events": events,
            "spans": spans,
            "counters": snap["counters"],
            "gauges": snap["gauges"],
        }
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(box, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        return box


def read_blackbox(path: str) -> Optional[dict]:
    """Load a black box; None when absent or torn (a crash between
    tmp-write and rename leaves the previous complete flush, so a torn
    FINAL file is impossible — but an empty/garbled path still must not
    take the autopsy down with it)."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


__all__ = ["FlightRecorder", "read_blackbox", "DEFAULT_LAST_N"]
