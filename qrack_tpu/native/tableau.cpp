// Native CHP tableau kernels.
//
// TPU-native runtime split (SURVEY.md §7): XLA/Pallas owns the dense
// amplitude math; host-side combinatorial hot loops — the CHP
// measurement's rowsum cascade and canonical Gaussian elimination
// (reference: src/qstabilizer.cpp:1999 ForceM; gaussianCached
// include/qstabilizer.hpp:55) — are native C++ here, driven through
// ctypes over the engine's uint8 row matrices (zero copy).
//
// Layout contract (matches qrack_tpu.layers.stabilizer.QStabilizer):
//   x, z: uint8[2n+1][n] row-major; r: uint8[2n+1]
//   rows 0..n-1 destabilizers, n..2n-1 stabilizers, 2n scratch.

#include <cstdint>
#include <cstring>

namespace {

// Aaronson–Gottesman g-exponent summed over a row pair.
inline long g_sum(const uint8_t* x1, const uint8_t* z1,
                  const uint8_t* x2, const uint8_t* z2, long n) {
    long acc = 0;
    for (long j = 0; j < n; ++j) {
        const int a = x1[j], b = z1[j], c = x2[j], d = z2[j];
        if (a && b) {
            acc += d - c;
        } else if (a) {
            acc += d * (2 * c - 1);
        } else if (b) {
            acc += c * (1 - 2 * d);
        }
    }
    return acc;
}

inline void rowsum(uint8_t* x, uint8_t* z, uint8_t* r, long n, long h, long i) {
    uint8_t* xh = x + h * n;
    uint8_t* zh = z + h * n;
    const uint8_t* xi = x + i * n;
    const uint8_t* zi = z + i * n;
    const long phase = 2L * r[h] + 2L * r[i] + g_sum(xi, zi, xh, zh, n);
    r[h] = ((phase % 4 + 4) % 4) == 2 ? 1 : 0;
    for (long j = 0; j < n; ++j) {
        xh[j] ^= xi[j];
        zh[j] ^= zi[j];
    }
}

} // namespace

extern "C" {

// Measure qubit q. Returns 0/1 outcome, -1 = forced outcome impossible.
// rand_bit supplies the random result for the indeterminate branch.
int tb_force_m(uint8_t* x, uint8_t* z, uint8_t* r, long n,
               long q, int forced_val, int do_force, int do_apply,
               int rand_bit) {
    // random case: any stabilizer row with x[p][q]
    long p = -1;
    for (long i = n; i < 2 * n; ++i) {
        if (x[i * n + q]) { p = i; break; }
    }
    if (p < 0) {
        // deterministic: accumulate into scratch row 2n
        const long h = 2 * n;
        std::memset(x + h * n, 0, n);
        std::memset(z + h * n, 0, n);
        r[h] = 0;
        for (long i = 0; i < n; ++i) {
            if (x[i * n + q]) rowsum(x, z, r, n, h, i + n);
        }
        const int out = r[h];
        if (do_force && forced_val != out) return -1;
        return out;
    }
    const int out = do_force ? (forced_val ? 1 : 0) : (rand_bit ? 1 : 0);
    if (!do_apply) return out;
    for (long i = 0; i < 2 * n; ++i) {
        if (i != p && x[i * n + q]) rowsum(x, z, r, n, i, p);
    }
    std::memcpy(x + (p - n) * n, x + p * n, n);
    std::memcpy(z + (p - n) * n, z + p * n, n);
    r[p - n] = r[p];
    std::memset(x + p * n, 0, n);
    std::memset(z + p * n, 0, n);
    z[p * n + q] = 1;
    r[p] = out;
    return out;
}

// 1 if measurement of q is deterministic (Z eigenstate), else 0.
int tb_is_separable_z(const uint8_t* x, long n, long q) {
    for (long i = n; i < 2 * n; ++i) {
        if (x[i * n + q]) return 0;
    }
    return 1;
}

// In-place canonical Gaussian elimination of the stabilizer block
// handed over as standalone (n x n) matrices. Returns the X-rank.
long tb_canonical(uint8_t* x, uint8_t* z, uint8_t* r, long n) {
    auto mul_into = [&](long h, long i) {
        const long phase = 2L * r[h] + 2L * r[i]
            + g_sum(x + i * n, z + i * n, x + h * n, z + h * n, n);
        r[h] = ((phase % 4 + 4) % 4) == 2 ? 1 : 0;
        for (long j = 0; j < n; ++j) {
            x[h * n + j] ^= x[i * n + j];
            z[h * n + j] ^= z[i * n + j];
        }
    };
    auto swap_rows = [&](long a, long b) {
        if (a == b) return;
        for (long j = 0; j < n; ++j) {
            uint8_t t = x[a * n + j]; x[a * n + j] = x[b * n + j]; x[b * n + j] = t;
            t = z[a * n + j]; z[a * n + j] = z[b * n + j]; z[b * n + j] = t;
        }
        const uint8_t t = r[a]; r[a] = r[b]; r[b] = t;
    };
    long row = 0;
    for (long col = 0; col < n; ++col) {
        long piv = -1;
        for (long i = row; i < n; ++i) {
            if (x[i * n + col]) { piv = i; break; }
        }
        if (piv < 0) continue;
        swap_rows(row, piv);
        for (long i = 0; i < n; ++i) {
            if (i != row && x[i * n + col]) mul_into(i, row);
        }
        ++row;
    }
    const long x_rank = row;
    for (long col = 0; col < n; ++col) {
        long piv = -1;
        for (long i = row; i < n; ++i) {
            if (z[i * n + col]) { piv = i; break; }
        }
        if (piv < 0) continue;
        swap_rows(row, piv);
        for (long i = row; i < n; ++i) {
            if (i != row && z[i * n + col]) mul_into(i, row);
        }
        ++row;
    }
    return x_rank;
}

} // extern "C"
