"""Native kernel loader: builds and binds the C++ tableau hot loops.

Build-on-first-use with g++ (the image's native toolchain), cached as a
shared object beside the source; every entry point has a pure-Python
fallback in the stabilizer engine, so absence of a compiler only costs
speed (reference analogue: the OpenCL JIT + binary cache,
src/common/oclengine.cpp:150-202)."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "tableau.cpp")
_SO = os.path.join(_HERE, "_tableau.so")

_lib = None
_tried = False
_lock = threading.Lock()


def _build() -> bool:
    try:
        src_mtime = os.path.getmtime(_SRC)
        if os.path.exists(_SO) and os.path.getmtime(_SO) >= src_mtime:
            return True
        # no -march=native: a cached .so may outlive the build host's ISA
        # (SIGILL beats the graceful fallback); per-PID temp avoids
        # concurrent-build races corrupting the installed object
        tmp = f"{_SO}.{os.getpid()}.tmp"
        res = subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", _SRC, "-o", tmp],
            capture_output=True, timeout=120,
        )
        if res.returncode != 0:
            return False
        os.replace(tmp, _SO)
        return True
    except Exception:
        return False


def get_tableau_lib():
    """Return the bound ctypes library, or None (use Python fallback)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("QRACK_TPU_NO_NATIVE"):
            return None
        if not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
            u8p = ctypes.POINTER(ctypes.c_uint8)
            lib.tb_force_m.restype = ctypes.c_int
            lib.tb_force_m.argtypes = [u8p, u8p, u8p, ctypes.c_long, ctypes.c_long,
                                       ctypes.c_int, ctypes.c_int, ctypes.c_int,
                                       ctypes.c_int]
            lib.tb_is_separable_z.restype = ctypes.c_int
            lib.tb_is_separable_z.argtypes = [u8p, ctypes.c_long, ctypes.c_long]
            lib.tb_canonical.restype = ctypes.c_long
            lib.tb_canonical.argtypes = [u8p, u8p, u8p, ctypes.c_long]
            _lib = lib
        except Exception:
            _lib = None
        return _lib
