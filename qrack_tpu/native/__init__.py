"""Native kernel loader: builds and binds the C++ tableau hot loops.

Build-on-first-use with g++ (the image's native toolchain), cached as a
shared object beside the source; every entry point has a pure-Python
fallback in the stabilizer engine, so absence of a compiler only costs
speed (reference analogue: the OpenCL JIT + binary cache,
src/common/oclengine.cpp:150-202)."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "tableau.cpp")
_SO = os.path.join(_HERE, "_tableau.so")

_lib = None
_tried = False
_lock = threading.Lock()


def _build_so(src: str, so: str, compiler: str, extra=()) -> bool:
    """mtime-checked, per-PID-temp + atomic-replace native build (shared
    by every lazy loader here — the safety properties matter: a stale
    binary must rebuild, and concurrent first-use from two interpreters
    must never CDLL a half-written object)."""
    try:
        src_mtime = os.path.getmtime(src)
        if os.path.exists(so) and os.path.getmtime(so) >= src_mtime:
            return True
        # no -march=native: a cached .so may outlive the build host's ISA
        # (SIGILL beats the graceful fallback)
        tmp = f"{so}.{os.getpid()}.tmp"
        res = subprocess.run(
            [compiler, "-O3", "-shared", "-fPIC", *extra, src, "-o", tmp],
            capture_output=True, timeout=120,
        )
        if res.returncode != 0:
            return False
        os.replace(tmp, so)
        return True
    except Exception:
        return False


def _build() -> bool:
    return _build_so(_SRC, _SO, "g++")


def get_tableau_lib():
    """Return the bound ctypes library, or None (use Python fallback)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("QRACK_TPU_NO_NATIVE"):
            return None
        if not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
            u8p = ctypes.POINTER(ctypes.c_uint8)
            lib.tb_force_m.restype = ctypes.c_int
            lib.tb_force_m.argtypes = [u8p, u8p, u8p, ctypes.c_long, ctypes.c_long,
                                       ctypes.c_int, ctypes.c_int, ctypes.c_int,
                                       ctypes.c_int]
            lib.tb_is_separable_z.restype = ctypes.c_int
            lib.tb_is_separable_z.argtypes = [u8p, ctypes.c_long, ctypes.c_long]
            lib.tb_canonical.restype = ctypes.c_long
            lib.tb_canonical.argtypes = [u8p, u8p, u8p, ctypes.c_long]
            _lib = lib
        except Exception:
            _lib = None
        return _lib


# -- RDRAND/RDSEED hardware entropy (reference: rdrandwrapper.hpp) ------

_HW_SRC = os.path.join(_HERE, "hwrng.c")
_HW_SO = os.path.join(_HERE, "libqrack_hwrng.so")

_hw_lib = None
_hw_tried = False


def _hw_extra_flags():
    import platform

    if platform.machine() in ("x86_64", "i686", "AMD64"):
        return ("-mrdrnd", "-mrdseed")
    return ()


def get_hwrng_lib():
    """Bound RDRAND wrapper library, or None (os.urandom fallback)."""
    global _hw_lib, _hw_tried
    if _hw_lib is not None or _hw_tried:
        return _hw_lib
    with _lock:
        if _hw_lib is not None or _hw_tried:
            return _hw_lib
        _hw_tried = True
        if os.environ.get("QRACK_TPU_NO_NATIVE"):
            return None
        if not _build_so(_HW_SRC, _HW_SO, "gcc", _hw_extra_flags()):
            return None
        try:
            lib = ctypes.CDLL(_HW_SO)
            lib.qrack_hw_rdrand_supported.restype = ctypes.c_int
            lib.qrack_hw_rdseed_supported.restype = ctypes.c_int
            lib.qrack_rdrand64.restype = ctypes.c_int
            lib.qrack_rdrand64.argtypes = [ctypes.POINTER(ctypes.c_uint64)]
            lib.qrack_rdseed64.restype = ctypes.c_int
            lib.qrack_rdseed64.argtypes = [ctypes.POINTER(ctypes.c_uint64)]
            lib.qrack_rdrand_fill.restype = ctypes.c_int
            lib.qrack_rdrand_fill.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
            _hw_lib = lib if lib.qrack_hw_rdrand_supported() else None
        except Exception:
            _hw_lib = None
        return _hw_lib
