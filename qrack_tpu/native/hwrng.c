/* Hardware entropy: RDRAND/RDSEED instruction wrappers.
 *
 * Native counterpart of the reference's rdrandwrapper
 * (reference: include/common/rdrandwrapper.hpp:30-90 — RdRandom::
 * SupportsRDRAND/SupportsRDSEED via cpuid, NextRaw with bounded
 * retries).  Built as a plain shared library (scripts/build_hwrng.py)
 * and loaded with ctypes from qrack_tpu.utils.rng; every function is
 * safe to call on CPUs without the instructions (support is probed
 * with cpuid first, and the fill routine reports failure instead of
 * spinning).
 */

#include <stddef.h>
#include <stdint.h>

#if defined(__x86_64__) || defined(__i386__)
#include <cpuid.h>
#include <immintrin.h>

#define QRACK_RETRIES 16

int qrack_hw_rdrand_supported(void) {
    unsigned int eax, ebx, ecx, edx;
    if (!__get_cpuid(1, &eax, &ebx, &ecx, &edx)) return 0;
    return (ecx >> 30) & 1; /* CPUID.01H:ECX.RDRAND[bit 30] */
}

int qrack_hw_rdseed_supported(void) {
    unsigned int eax, ebx, ecx, edx;
    if (!__get_cpuid_count(7, 0, &eax, &ebx, &ecx, &edx)) return 0;
    return (ebx >> 18) & 1; /* CPUID.07H.0:EBX.RDSEED[bit 18] */
}

/* 1 on success (out filled), 0 on exhausted retries / unsupported. */
int qrack_rdrand64(uint64_t *out) {
    if (!qrack_hw_rdrand_supported()) return 0;
    for (int i = 0; i < QRACK_RETRIES; ++i) {
        unsigned long long v;
        if (_rdrand64_step(&v)) {
            *out = (uint64_t)v;
            return 1;
        }
    }
    return 0;
}

int qrack_rdseed64(uint64_t *out) {
    if (!qrack_hw_rdseed_supported()) return 0;
    for (int i = 0; i < QRACK_RETRIES; ++i) {
        unsigned long long v;
        if (_rdseed64_step(&v)) {
            *out = (uint64_t)v;
            return 1;
        }
    }
    return 0;
}

/* Fill len bytes from RDRAND; 1 on success, 0 if any word failed. */
int qrack_rdrand_fill(uint8_t *buf, size_t len) {
    size_t i = 0;
    while (i < len) {
        uint64_t v;
        if (!qrack_rdrand64(&v)) return 0;
        for (int b = 0; b < 8 && i < len; ++b, ++i)
            buf[i] = (uint8_t)(v >> (8 * b));
    }
    return 1;
}

#else /* non-x86: no instruction path; callers fall back to os.urandom */

int qrack_hw_rdrand_supported(void) { return 0; }
int qrack_hw_rdseed_supported(void) { return 0; }
int qrack_rdrand64(uint64_t *out) { (void)out; return 0; }
int qrack_rdseed64(uint64_t *out) { (void)out; return 0; }
int qrack_rdrand_fill(uint8_t *buf, size_t len) {
    (void)buf; (void)len; return 0;
}

#endif
