/* libqrack_capi: a real C ABI over the qrack_tpu flat API.
 *
 * Re-design of the reference's pinvoke surface as a thin embedding shim
 * (reference: include/pinvoke_api.hpp:42-349, src/pinvoke_api.cpp): the
 * exported symbols keep the reference's names and sid-based calling
 * convention; each forwards into the Python registry
 * (qrack_tpu.capi) through the CPython C API.  Consumers bind with
 * ctypes/dlopen exactly like PyQrack binds the reference's .so.
 *
 * Build: python scripts/build_capi_shim.py  (gcc -shared -fPIC against
 * libpython; see that script for the exact line).
 *
 * Threading: every entry takes the GIL via PyGILState_Ensure, so the
 * shim is callable from any thread once qrack_capi_init() ran.
 */

#include <Python.h>
#include <stdint.h>

typedef uint64_t uintq;

static PyObject* g_capi = NULL;

static int ensure_init(void) {
    if (g_capi) {
        return 0;
    }
    int initialized_here = 0;
    if (!Py_IsInitialized()) {
        Py_InitializeEx(0);
        initialized_here = 1;
    }
    PyGILState_STATE st = PyGILState_Ensure();
    /* honor PYTHONPATH even when embedded into an already-running
     * interpreter (the ctypes-consumer case) */
    PyRun_SimpleString(
        "import os, sys\n"
        "for _p in os.environ.get('PYTHONPATH', '').split(os.pathsep):\n"
        "    if _p and _p not in sys.path:\n"
        "        sys.path.insert(0, _p)\n");
    PyObject* mod = PyImport_ImportModule("qrack_tpu.capi");
    if (!mod) {
        PyErr_Print();
        PyGILState_Release(st);
        return -1;
    }
    g_capi = mod;
    PyGILState_Release(st);
    if (initialized_here) {
        /* Py_InitializeEx leaves this thread holding the GIL; release it
         * so other threads' PyGILState_Ensure calls can proceed */
        PyEval_SaveThread();
    }
    return 0;
}

/* Call capi.<name>(fmt-args); returns new ref or NULL (error printed). */
static PyObject* capi_call(const char* name, const char* fmt, ...) {
    if (ensure_init()) {
        return NULL;
    }
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject* fn = PyObject_GetAttrString(g_capi, name);
    PyObject* ret = NULL;
    if (fn) {
        va_list va;
        va_start(va, fmt);
        PyObject* args = Py_VaBuildValue(fmt, va);
        va_end(va);
        if (args) {
            ret = PyObject_CallObject(fn, args);
            Py_DECREF(args);
        }
        Py_DECREF(fn);
    }
    if (!ret) {
        PyErr_Print();
    }
    PyGILState_Release(st);
    return ret;
}

static long long as_ll(PyObject* o, long long dflt) {
    if (!o) {
        return dflt;
    }
    PyGILState_STATE st = PyGILState_Ensure();
    long long v = PyLong_Check(o) ? PyLong_AsLongLong(o)
                : (PyObject_IsTrue(o) ? 1 : 0);
    Py_DECREF(o);
    PyGILState_Release(st);
    return v;
}

static double as_d(PyObject* o, double dflt) {
    if (!o) {
        return dflt;
    }
    PyGILState_STATE st = PyGILState_Ensure();
    double v = PyFloat_AsDouble(o);
    Py_DECREF(o);
    PyGILState_Release(st);
    return v;
}

static PyObject* qlist(uintq n, const uintq* q) {
    PyObject* l = PyList_New((Py_ssize_t)n);
    for (uintq i = 0; i < n; ++i) {
        PyList_SetItem(l, (Py_ssize_t)i, PyLong_FromUnsignedLongLong(q[i]));
    }
    return l;
}

static PyObject* dlist(uintq n, const double* v) {
    PyObject* l = PyList_New((Py_ssize_t)n);
    for (uintq i = 0; i < n; ++i) {
        PyList_SetItem(l, (Py_ssize_t)i, PyFloat_FromDouble(v[i]));
    }
    return l;
}

/* ---- lifecycle ------------------------------------------------------ */

int qrack_capi_init(void) { return ensure_init(); }

uintq init_count_type(uintq q, int tn, int md, int sd, int sh, int bdt,
                      int pg, int nw, int hy, int oc, int hp) {
    return (uintq)as_ll(capi_call("init_count_type", "(Kiiiiiiiiii)",
                                  q, tn, md, sd, sh, bdt, pg, nw, hy, oc, hp), 0);
}

uintq init_count(uintq q) { return (uintq)as_ll(capi_call("init_count", "(K)", q), 0); }
uintq init(void) { return (uintq)as_ll(capi_call("init", "()"), 0); }
uintq init_clone(uintq sid) { return (uintq)as_ll(capi_call("init_clone", "(K)", sid), 0); }
void destroy(uintq sid) { Py_XDECREF(capi_call("destroy", "(K)", sid)); }
void seed(uintq sid, uintq s) { Py_XDECREF(capi_call("seed", "(KK)", sid, s)); }
uintq num_qubits(uintq sid) { return (uintq)as_ll(capi_call("num_qubits", "(K)", sid), 0); }
void allocateQubit(uintq sid, uintq qid) { Py_XDECREF(capi_call("allocateQubit", "(KK)", sid, qid)); }
int release(uintq sid, uintq qid) { return (int)as_ll(capi_call("release", "(KK)", sid, qid), 0); }
int get_error(uintq sid) { return (int)as_ll(capi_call("get_error", "(K)", sid), 0); }

/* ---- single-qubit gates -------------------------------------------- */

#define GATE1(NAME) \
    void NAME(uintq sid, uintq q) { Py_XDECREF(capi_call(#NAME, "(KK)", sid, q)); }

GATE1(X) GATE1(Y) GATE1(Z) GATE1(H) GATE1(S) GATE1(T)
GATE1(AdjS) GATE1(AdjT) GATE1(SX) GATE1(SY) GATE1(AdjSX) GATE1(AdjSY)

void U(uintq sid, uintq q, double theta, double phi, double lambda) {
    Py_XDECREF(capi_call("U", "(KKddd)", sid, q, theta, phi, lambda));
}

void Mtrx(uintq sid, double* m, uintq q) {
    /* m: 8 doubles, row-major re/im pairs (reference convention) */
    if (ensure_init()) return;
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject* l = PyList_New(4);
    for (int i = 0; i < 4; ++i) {
        PyList_SetItem(l, i, PyComplex_FromDoubles(m[2 * i], m[2 * i + 1]));
    }
    Py_XDECREF(capi_call("Mtrx", "(KNK)", sid, l, q));
    PyGILState_Release(st);
}

void R(uintq sid, uintq basis, double phi, uintq q) {
    Py_XDECREF(capi_call("R", "(KKdK)", sid, basis, phi, q));
}

/* ---- controlled gates ---------------------------------------------- */

#define GATEMC(NAME) \
    void NAME(uintq sid, uintq n, uintq* c, uintq q) { \
        if (ensure_init()) return; \
        PyGILState_STATE st = PyGILState_Ensure(); \
        Py_XDECREF(capi_call(#NAME, "(KNK)", sid, qlist(n, c), q)); \
        PyGILState_Release(st); \
    }

GATEMC(MCX) GATEMC(MCY) GATEMC(MCZ) GATEMC(MCH) GATEMC(MCS) GATEMC(MCT)
GATEMC(MCAdjS) GATEMC(MCAdjT)
GATEMC(MACX) GATEMC(MACY) GATEMC(MACZ) GATEMC(MACH) GATEMC(MACS) GATEMC(MACT)
GATEMC(MACAdjS) GATEMC(MACAdjT)

void MCMtrx(uintq sid, uintq n, uintq* c, double* m, uintq q) {
    if (ensure_init()) return;
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject* l = PyList_New(4);
    for (int i = 0; i < 4; ++i) {
        PyList_SetItem(l, i, PyComplex_FromDoubles(m[2 * i], m[2 * i + 1]));
    }
    Py_XDECREF(capi_call("MCMtrx", "(KNNK)", sid, qlist(n, c), l, q));
    PyGILState_Release(st);
}

void MCR(uintq sid, uintq basis, double phi, uintq n, uintq* c, uintq q) {
    if (ensure_init()) return;
    PyGILState_STATE st = PyGILState_Ensure();
    Py_XDECREF(capi_call("MCR", "(KKdNK)", sid, basis, phi, qlist(n, c), q));
    PyGILState_Release(st);
}

void SWAP(uintq sid, uintq q1, uintq q2) { Py_XDECREF(capi_call("SWAP", "(KKK)", sid, q1, q2)); }
void ISWAP(uintq sid, uintq q1, uintq q2) { Py_XDECREF(capi_call("ISWAP", "(KKK)", sid, q1, q2)); }
void FSim(uintq sid, double theta, double phi, uintq q1, uintq q2) {
    Py_XDECREF(capi_call("FSim", "(KddKK)", sid, theta, phi, q1, q2));
}
void CSWAP(uintq sid, uintq n, uintq* c, uintq q1, uintq q2) {
    if (ensure_init()) return;
    PyGILState_STATE st = PyGILState_Ensure();
    Py_XDECREF(capi_call("CSWAP", "(KNKK)", sid, qlist(n, c), q1, q2));
    PyGILState_Release(st);
}

/* ---- measurement / observables ------------------------------------- */

int M(uintq sid, uintq q) { return (int)as_ll(capi_call("M", "(KK)", sid, q), 0); }
int ForceM(uintq sid, uintq q, int r) { return (int)as_ll(capi_call("ForceM", "(KKi)", sid, q, r), 0); }
uintq MAll(uintq sid) { return (uintq)as_ll(capi_call("MAll", "(K)", sid), 0); }
double Prob(uintq sid, uintq q) { return as_d(capi_call("Prob", "(KK)", sid, q), 0.0); }
double ProbAll(uintq sid, uintq perm) { return as_d(capi_call("ProbAll", "(KK)", sid, perm), 0.0); }

double PermutationExpectation(uintq sid, uintq n, uintq* q) {
    if (ensure_init()) return 0.0;
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject* l = qlist(n, q);
    PyGILState_Release(st);
    return as_d(capi_call("PermutationExpectation", "(KN)", sid, l), 0.0);
}

double Variance(uintq sid, uintq n, uintq* q) {
    if (ensure_init()) return 0.0;
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject* l = qlist(n, q);
    PyGILState_Release(st);
    return as_d(capi_call("Variance", "(KN)", sid, l), 0.0);
}

double GetUnitaryFidelity(uintq sid) {
    return as_d(capi_call("GetUnitaryFidelity", "(K)", sid), 1.0);
}

uintq HighestProbAll(uintq sid) {
    return (uintq)as_ll(capi_call("HighestProbAll", "(K)", sid), 0);
}

size_t random_choice(uintq sid, size_t n, double* p) {
    if (ensure_init()) return 0;
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject* l = dlist(n, p);
    PyGILState_Release(st);
    return (size_t)as_ll(capi_call("random_choice", "(KN)", sid, l), 0);
}

void OutProbs(uintq sid, double* out, uintq len) {
    PyObject* arr = capi_call("OutProbs", "(K)", sid);
    if (!arr) {
        return;
    }
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject* seq = PySequence_Fast(arr, "probs");
    if (seq) {
        Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
        for (Py_ssize_t i = 0; i < n && (uintq)i < len; ++i) {
            out[i] = PyFloat_AsDouble(PySequence_Fast_GET_ITEM(seq, i));
        }
        Py_DECREF(seq);
    }
    Py_DECREF(arr);
    PyGILState_Release(st);
}

/* ---- structure / ALU ------------------------------------------------ */

uintq Compose(uintq sid1, uintq sid2) {
    return (uintq)as_ll(capi_call("Compose", "(KK)", sid1, sid2), 0);
}
uintq Decompose(uintq sid, uintq start, uintq len) {
    return (uintq)as_ll(capi_call("Decompose", "(KKK)", sid, start, len), 0);
}
void Dispose(uintq sid, uintq start, uintq len) {
    Py_XDECREF(capi_call("Dispose", "(KKK)", sid, start, len));
}

void ADD(uintq sid, uintq a, uintq start, uintq len) { Py_XDECREF(capi_call("ADD", "(KKKK)", sid, a, start, len)); }
void SUB(uintq sid, uintq a, uintq start, uintq len) { Py_XDECREF(capi_call("SUB", "(KKKK)", sid, a, start, len)); }
void MUL(uintq sid, uintq a, uintq start, uintq cstart, uintq len) {
    Py_XDECREF(capi_call("MUL", "(KKKKK)", sid, a, start, cstart, len));
}
void DIV(uintq sid, uintq a, uintq start, uintq cstart, uintq len) {
    Py_XDECREF(capi_call("DIV", "(KKKKK)", sid, a, start, cstart, len));
}
void MULN(uintq sid, uintq a, uintq m, uintq in_s, uintq out_s, uintq len) {
    Py_XDECREF(capi_call("MULN", "(KKKKKK)", sid, a, m, in_s, out_s, len));
}
void POWN(uintq sid, uintq a, uintq m, uintq in_s, uintq out_s, uintq len) {
    Py_XDECREF(capi_call("POWN", "(KKKKKK)", sid, a, m, in_s, out_s, len));
}

int TrySeparate1Qb(uintq sid, uintq q) { return (int)as_ll(capi_call("TrySeparate1Qb", "(KK)", sid, q), 0); }
int TrySeparate2Qb(uintq sid, uintq q1, uintq q2) {
    return (int)as_ll(capi_call("TrySeparate2Qb", "(KKK)", sid, q1, q2), 0);
}

void ResetAll(uintq sid) { Py_XDECREF(capi_call("ResetAll", "(K)", sid)); }
void qstabilizer_out_to_file(uintq sid, const char* f) {
    Py_XDECREF(capi_call("qstabilizer_out_to_file", "(Ks)", sid, f));
}
void qstabilizer_in_from_file(uintq sid, const char* f) {
    Py_XDECREF(capi_call("qstabilizer_in_from_file", "(Ks)", sid, f));
}
