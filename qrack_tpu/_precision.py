"""Shared QRACK_MATMUL_PRECISION parsing.

One helper so the package-level ``jax_default_matmul_precision`` update
and the per-einsum ``precision=`` overrides (ops/gatekernels.py) can
never disagree: '' and unset both mean the package default ('highest'),
and 'default'/'high'/'highest' map to the matching jax.lax.Precision.
Invalid non-empty values are passed through to jax.config.update, which
raises at import with jax's own error message.
"""

import os


def matmul_precision_setting() -> str:
    """Normalized QRACK_MATMUL_PRECISION string ('' / unset -> 'highest')."""
    return os.environ.get("QRACK_MATMUL_PRECISION", "").strip() or "highest"


def matmul_precision():
    """Per-einsum jax.lax.Precision matching the global setting.

    None for 'default' (defer to the global default, which the same
    setting controls) — so an env override affects both layers equally.
    """
    import jax

    return {
        "default": None,
        "high": jax.lax.Precision.HIGH,
        "highest": jax.lax.Precision.HIGHEST,
    }.get(matmul_precision_setting())
