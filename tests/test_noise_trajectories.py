"""Trajectory engine (qrack_tpu/noise/trajectories.py): Monte-Carlo
convergence against the analytic channel, batch-vs-sequential bit
parity across fuse windows, mid-batch checkpoint round-trip, HBM
chunking regressions, and the single-trace compile contract."""

import json

import numpy as np
import pytest

from qrack_tpu import telemetry as tele
from qrack_tpu.layers.qcircuit import QCircuit
from qrack_tpu.noise import (NoiseModel, QNoisy, amplitude_damping,
                             dephasing, depolarizing)
from qrack_tpu.noise import trajectories as traj
from qrack_tpu.noise.trajectories import (TrajectoryJob, run_trajectories,
                                          traj_chunk)

_H = np.array([[1, 1], [1, -1]], dtype=complex) / np.sqrt(2)
_X = np.array([[0, 1], [1, 0]], dtype=complex)
_Z = np.array([[1, 0], [0, -1]], dtype=complex)
_S = np.array([[1, 0], [0, 1j]], dtype=complex)


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for k in ("QRACK_NOISE_TRAJ_WINDOW", "QRACK_NOISE_TRAJ_CHUNK",
              "QRACK_ROUTE_HBM_BYTES"):
        monkeypatch.delenv(k, raising=False)
    yield
    tele.disable()
    tele.reset()


def _bell_circuit() -> QCircuit:
    c = QCircuit(2)
    c.append_1q(0, _H)
    c.append_ctrl((0,), 1, _X, 1)
    return c


def _mixed_circuit(n: int = 3) -> QCircuit:
    """A small circuit exercising 1q payloads and a controlled gate."""
    c = QCircuit(n)
    c.append_1q(0, _H)
    c.append_1q(1, _S)
    c.append_ctrl((0,), 1, _X, 1)
    c.append_1q(2, _H)
    c.append_ctrl((2,), 0, _Z, 1)
    return c


def _op_on(n: int, q: int, m: np.ndarray) -> np.ndarray:
    """Full 2^n matrix for a 1q operator with qubit 0 least significant
    (np.kron(high, low) index convention)."""
    full = np.eye(1)
    for k in range(n):
        full = np.kron(m if k == q else np.eye(2), full)
    return full


def _apply_channel(rho: np.ndarray, n: int, q: int, ch) -> np.ndarray:
    out = np.zeros_like(rho)
    for k in ch.kraus:
        kf = _op_on(n, q, np.asarray(k))
        out += kf @ rho @ kf.conj().T
    return out


def test_trajectory_average_converges_to_analytic():
    """B=2000 depolarized Bell prep: the trajectory-averaged per-qubit
    P(1) must sit within a 5-sigma binomial bound of the exact Kraus-sum
    density matrix (the ISSUE's convergence acceptance)."""
    lam = 0.1
    B = 2000
    ch = depolarizing(lam)
    model = NoiseModel(default=ch)
    circ = _bell_circuit()

    # analytic: H0, channel(q0); CNOT(0->1), channel(q0), channel(q1) --
    # the exact schedule lower_noisy emits (slots sorted per gate)
    rho = np.zeros((4, 4), dtype=complex)
    rho[0, 0] = 1.0
    h0 = _op_on(2, 0, _H)
    rho = h0 @ rho @ h0.conj().T
    rho = _apply_channel(rho, 2, 0, ch)
    cnot = np.array([[1, 0, 0, 0], [0, 0, 0, 1],
                     [0, 0, 1, 0], [0, 1, 0, 0]], dtype=complex)
    rho = cnot @ rho @ cnot.conj().T
    rho = _apply_channel(rho, 2, 0, ch)
    rho = _apply_channel(rho, 2, 1, ch)
    diag = np.real(np.diag(rho))
    p1_exact = np.array([diag[1] + diag[3], diag[2] + diag[3]])

    res = run_trajectories(circ, model, B, key=17)
    assert res.trajectories == B
    # mixed-unitary model: every importance weight is exactly 1
    assert np.all(res.weights == 1.0)
    assert np.all((res.samples >= 0) & (res.samples < 4))
    for q in range(2):
        p = p1_exact[q]
        sigma = np.sqrt(p * (1 - p) / B)
        assert abs(res.aggregate_p1[q] - p) < 5 * sigma + 1e-9, \
            (q, res.aggregate_p1[q], p, sigma)
        assert res.expectation_z(q) == pytest.approx(
            1.0 - 2.0 * res.aggregate_p1[q])


def test_bit_reproducible_from_key_and_trajectory_id():
    """Trajectories are pure functions of (key, trajectory_id): the same
    coordinates replay bit-identically, disjoint ids differ."""
    circ = _mixed_circuit()
    model = NoiseModel(default=depolarizing(0.2),
                       per_qubit={1: [amplitude_damping(0.3)]})
    a = run_trajectories(circ, model, 5, key=7)
    b = run_trajectories(circ, model, 5, key=7)
    assert np.array_equal(a.samples, b.samples)
    assert np.array_equal(a.p1, b.p1)
    assert np.array_equal(a.weights, b.weights)
    # the id list, not its order in the batch, decides the randomness
    c = run_trajectories(circ, model, 2, key=7, trajectory_ids=[3, 1])
    assert np.array_equal(c.p1[0], a.p1[3])
    assert np.array_equal(c.p1[1], a.p1[1])


@pytest.mark.parametrize("window", ["1", "16"])
def test_batch_matches_sequential_per_window(monkeypatch, window):
    """Batch-vs-sequential bit parity at fuse windows 1 AND 16: the
    B-batch and B separate single-trajectory runs draw identical
    branches and identical measurement bits, and their kets agree."""
    monkeypatch.setenv("QRACK_NOISE_TRAJ_WINDOW", window)
    circ = _mixed_circuit()
    model = NoiseModel(default=depolarizing(0.15),
                       per_qubit={0: [dephasing(0.2)],
                                  2: [amplitude_damping(0.25)]})
    B = 5
    batch = run_trajectories(circ, model, B, key=11, keep_planes=True)
    for i in range(B):
        one = run_trajectories(circ, model, 1, key=11,
                               trajectory_ids=[i], keep_planes=True)
        assert one.samples[0] == batch.samples[i], i
        assert one.weights[0] == pytest.approx(batch.weights[i],
                                               rel=1e-5, abs=1e-6)
        assert np.allclose(one.p1[0], batch.p1[i], atol=1e-5)
        assert np.allclose(one.planes[0], batch.planes[i], atol=1e-5)


def test_window_split_matches_whole_stream(monkeypatch):
    """QRACK_NOISE_TRAJ_WINDOW only changes program granularity, never
    the trajectory: 1-op and 16-op windows reproduce the whole-stream
    bits and kets."""
    circ = _mixed_circuit()
    model = NoiseModel(default=depolarizing(0.1),
                       per_qubit={1: [amplitude_damping(0.2)]})
    whole = run_trajectories(circ, model, 6, key=5, keep_planes=True)
    for w in ("1", "16"):
        monkeypatch.setenv("QRACK_NOISE_TRAJ_WINDOW", w)
        split = run_trajectories(circ, model, 6, key=5, keep_planes=True)
        assert np.array_equal(split.samples, whole.samples), w
        assert np.allclose(split.weights, whole.weights, atol=1e-6), w
        assert np.allclose(split.planes, whole.planes, atol=1e-5), w


def test_snapshot_resume_round_trip(monkeypatch):
    """A trajectory job checkpointed mid-batch (after 1 of 3 chunks),
    serialized through JSON, and resumed must land bit-identical to an
    uninterrupted run."""
    monkeypatch.setenv("QRACK_NOISE_TRAJ_CHUNK", "2")
    circ = _mixed_circuit()
    model = NoiseModel(default=depolarizing(0.1))
    full = TrajectoryJob(circ, model, 6, width=3, key=9).run().result()
    assert full.chunks == 3

    job = TrajectoryJob(circ, model, 6, width=3, key=9)
    job.step()
    assert not job.finished
    snap = json.loads(json.dumps(job.snapshot()))
    assert snap["kind"] == "noise.trajectories"
    assert snap["next"] == 1
    resumed = TrajectoryJob.resume(circ, model, snap).run().result()
    assert resumed.chunks == 3
    assert list(resumed.trajectory_ids) == list(full.trajectory_ids)
    assert np.array_equal(resumed.samples, full.samples)
    assert np.array_equal(resumed.p1, full.p1)
    assert np.array_equal(resumed.weights, full.weights)


def test_chunked_matches_unchunked(monkeypatch):
    """HBM chunking regression: forcing 2-trajectory chunks (3
    dispatch rounds) reproduces the single-dispatch batch exactly."""
    circ = _mixed_circuit()
    model = NoiseModel(default=depolarizing(0.1),
                       per_qubit={2: [amplitude_damping(0.2)]})
    whole = run_trajectories(circ, model, 6, key=13, keep_planes=True)
    assert whole.chunks == 1
    monkeypatch.setenv("QRACK_NOISE_TRAJ_CHUNK", "2")
    chunked = run_trajectories(circ, model, 6, key=13, keep_planes=True)
    assert chunked.chunks == 3
    assert np.array_equal(chunked.samples, whole.samples)
    assert np.allclose(chunked.weights, whole.weights, atol=1e-6)
    assert np.allclose(chunked.planes, whole.planes, atol=1e-5)


def test_hbm_budget_drives_chunk(monkeypatch):
    """Without an explicit chunk override the route HBM budget sizes the
    chunk: budget // (16 * 2^w) resident dense kets per dispatch."""
    # width 3: 16 B/amp * 8 amps = 128 bytes per trajectory
    monkeypatch.setenv("QRACK_ROUTE_HBM_BYTES", "256")
    assert traj_chunk(3, 100) == 2
    monkeypatch.setenv("QRACK_ROUTE_HBM_BYTES", "100")
    assert traj_chunk(3, 100) == 1        # never below 1
    monkeypatch.delenv("QRACK_ROUTE_HBM_BYTES")
    monkeypatch.setenv("QRACK_NOISE_TRAJ_CHUNK", "7")
    assert traj_chunk(3, 100) == 7        # explicit override wins
    assert traj_chunk(3, 4) == 4          # clamped to the batch


def test_single_trace_for_same_structure(monkeypatch):
    """The acceptance's compile contract: B trajectories of one circuit
    structure trace exactly ONCE (branch choices are runtime operands),
    and a second batch with different randomness is a pure cache hit."""
    traj.PROGRAMS.clear()
    tele.enable()
    tele.reset()
    circ = _mixed_circuit()
    model = NoiseModel(default=depolarizing(0.05),
                       per_qubit={1: [amplitude_damping(0.1)]})
    run_trajectories(circ, model, 4, key=3)
    run_trajectories(circ, model, 4, key=21)   # new branches, same shape
    c = tele.snapshot(include_events=False)["counters"]
    assert c.get("compile.noise.window.miss", 0) == 1, c
    assert c.get("compile.noise.window.hit", 0) >= 1, c
    assert c.get("compile.noise.miss", 0) == 1, c
    assert c.get("compile.noise.hit", 0) >= 1, c
    assert c.get("noise.traj.batches", 0) == 2
    assert c.get("noise.traj.trajectories", 0) == 8


def test_dead_trajectory_matches_oracle():
    """Importance sampling can draw a branch that annihilates the state
    (amplitude damping's K1 with no |1> amplitude).  The batch body and
    the QNoisy oracle must agree bit-for-bit on the outcome: weight 0
    and a |0...0> reset ket."""
    circ = QCircuit(1)
    circ.append_1q(0, _Z)          # Z|0> = |0>: no |1> amplitude
    model = NoiseModel(default=amplitude_damping(0.5))
    B = 64
    res = run_trajectories(circ, model, B, key=3, keep_planes=True)
    dead = res.weights == 0.0
    assert dead.any(), "no trajectory drew the annihilating branch"
    assert not dead.all()
    for i in range(B):
        eng = QNoisy(1, model=model, key=3, trajectory_id=i,
                     inner_layers="cpu")
        eng.run_circuit(circ)
        assert eng.weight == pytest.approx(res.weights[i], rel=1e-5), i
        psi = np.asarray(eng.GetQuantumState())
        got = res.planes[i][0] + 1j * res.planes[i][1]
        assert abs(abs(np.vdot(psi, got)) - 1.0) < 1e-6 or \
            (res.weights[i] == 0.0 and np.allclose(got, [1.0, 0.0])), i
    # dead trajectories drop out of the channel average entirely
    live = res.weights > 0
    assert np.allclose(
        res.aggregate_p1,
        (res.weights[live, None] * res.p1[live]).sum(0)
        / res.weights[live].sum())
