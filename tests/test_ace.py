"""ACE (approximate circuit elision) + fidelity guard in QUnit.

Validates the re-design of the reference's beyond-memory behavior
(reference: include/qunit.hpp:107-146 CheckFidelity/ElideCz,
src/qunit.cpp:455-477 entangle budget, :1823-1840 + :2715 shadows;
README.md:118): over-cap entangling gates degrade gracefully with
tracked fidelity < 1 when the guard is disabled, and raise an advisory
error (not a raw MemoryError) when it is active."""

import numpy as np
import pytest

from qrack_tpu import QEngineCPU
from qrack_tpu.layers.qunit import QUnit
from qrack_tpu.utils.rng import QrackRandom


def factory(n, **kw):
    kw.setdefault("rand_global_phase", False)
    return QEngineCPU(n, **kw)


def make(n, seed=1, ace=False, cap=None, **kw):
    q = QUnit(n, unit_factory=factory, rng=QrackRandom(seed),
              rand_global_phase=False, **kw)
    q.is_ace = ace
    q.SetAceMaxQubits(cap)
    return q


def entangle_pairs(q, n):
    for i in range(0, n - 1, 2):
        q.H(i)
        q.CNOT(i, i + 1)
        q.Prob(i + 1)   # force the buffered link into a real 2q unit


def test_guard_raises_advisory_not_memoryerror():
    q = make(6, cap=4)
    entangle_pairs(q, 6)          # 2q units: within cap
    q.CNOT(1, 2)                  # buffered...
    q.Prob(2)                     # ...merges to 4: still within cap
    q.CNOT(3, 4)                  # buffers as an invert link: no merge yet
    with pytest.raises(RuntimeError, match="ACE"):
        q.Prob(4)                 # target marginal forces the flush: 4+2 > 4


def test_cnot_above_guard_fires_at_flush_time():
    # buffered CZ links don't entangle; the guard fires when a
    # non-diagonal op forces the merge
    q = make(6, cap=3)
    entangle_pairs(q, 6)
    q.CZ(1, 2)                    # buffered: no entanglement, no error
    q.CNOT(1, 2)                  # absorbs into the same link: still lazy
    assert q.GetUnitaryFidelity() == 1.0
    with pytest.raises(RuntimeError, match="ACE"):
        q.Prob(2)                 # measuring the invert target forces it


def test_ace_elides_cz_with_fidelity_cost():
    q = make(6, ace=True, cap=3)
    entangle_pairs(q, 6)
    q.CZ(1, 2)                    # buffered
    q.CNOT(1, 2)                  # absorbed into the link
    q.Prob(2)                     # flush -> merge fails -> elide
    assert q.GetUnitaryFidelity() < 1.0
    # the state is still normalized and factored within the cap
    sizes = [s.unit.qubit_count for s in q.shards if s.unit is not None]
    assert max(sizes) <= 3
    probs = q.GetProbs()
    assert np.isclose(probs.sum(), 1.0, atol=1e-6)


def test_ace_cnot_shadow_conditions_on_likely_control():
    # control prepared near |1>: the shadow applies X to the target
    q = make(4, ace=True, cap=1)
    q.X(0)
    q.H(1)                        # make it non-definite so trim can't elide
    q.RY(0.2, 1)
    q.CNOT(1, 2)
    q.Prob(2)                     # force the buffered link down
    # cap=1 forbids ALL merges: the gate became a shadow
    assert all(s.cached for s in q.shards)
    assert q.GetUnitaryFidelity() < 1.0


def test_max_alloc_mb_enforced(monkeypatch):
    q = make(8)
    monkeypatch.setattr(q.config, "max_alloc_mb", 1)  # 1 MB => <= 16 qubits... 2^16*16B
    # 1 MB allows 2^16 amplitudes: merging 8 qubits is fine
    entangle_pairs(q, 8)
    q2 = make(30)
    monkeypatch.setattr(q2.config, "max_alloc_mb", 1)
    for i in range(0, 30, 2):
        q2.H(i)
        q2.CNOT(i, i + 1)
    # merging 15 two-qubit units would need 2^30 * 16 B >> 1 MB
    with pytest.raises(RuntimeError, match="ACE"):
        for i in range(1, 29, 2):
            q2.CNOT(i, i + 1)
        q2.GetQuantumState()      # flush forces the over-budget merges


def test_quantum_volume_32q_through_ace_stack():
    """BASELINE target 5: a 32-qubit quantum-volume circuit completes
    through the QUnit + ACE stack (reference runs QV at 32-40q via its
    approximate 4-subsystem mode, README.md:64) — bounded shard sizes,
    sane fidelity accounting, and a measurable register at a width no
    dense single ket in this container could represent."""
    from qrack_tpu.models import algorithms as algo

    n = 32
    q = make(n, seed=21, ace=True, cap=8)
    r = algo.quantum_volume(q, depth=4, rng=QrackRandom(22))
    assert 0 <= r < (1 << n)
    sizes = [s.unit.qubit_count for s in q.shards if s.unit is not None]
    assert not sizes or max(sizes) <= 8
    assert 0.0 < q.GetUnitaryFidelity() <= 1.0


def test_ace_full_circuit_stays_bounded():
    # a deep circuit over 12 qubits with a 4-qubit cap never exceeds the
    # cap and keeps a sane normalized state
    n = 12
    q = make(n, ace=True, cap=4)
    rng = QrackRandom(5)
    for layer in range(6):
        for i in range(n):
            q.H(i) if rng.randint(0, 2) else q.T(i)
        for i in range(layer % 2, n - 1, 2):
            q.CNOT(i, i + 1)
    sizes = [s.unit.qubit_count for s in q.shards if s.unit is not None]
    assert not sizes or max(sizes) <= 4
    assert 0.0 < q.GetUnitaryFidelity() <= 1.0
    r = q.MAll()
    assert 0 <= r < (1 << n)
