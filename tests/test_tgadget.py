"""Reverse T-gadget + near-Clifford rounding in QStabilizerHybrid.

Validates the re-design of the reference's T-injection path (reference:
src/qstabilizerhybrid.cpp:206-239, FractionalRzAngleWithFlush
include/qstabilizerhybrid.hpp:228-259): blocked non-Clifford phase
shards move onto tableau ancillae instead of materializing a ket, wide
T-circuits stay on the tableau, rounding trades fidelity for staying
Clifford, and exact amplitude parity (incl. global phase) survives."""

import math

import numpy as np
import pytest

from qrack_tpu import QEngineCPU
from qrack_tpu.layers.stabilizerhybrid import QStabilizerHybrid
from qrack_tpu.utils.rng import QrackRandom


def cpu_factory(n, **kw):
    kw.setdefault("rand_global_phase", False)
    return QEngineCPU(n, **kw)


def make(n, seed=1, **kw):
    return QStabilizerHybrid(n, engine_factory=cpu_factory,
                             rng=QrackRandom(seed), rand_global_phase=False, **kw)


def oracle(n, seed=1):
    return QEngineCPU(n, rng=QrackRandom(seed), rand_global_phase=False)


def test_gadget_fires_instead_of_materializing():
    q = make(3)
    o = oracle(3)
    for eng in (q, o):
        eng.H(0)
        eng.T(0)          # non-Clifford phase shard
        eng.CNOT(1, 0)    # blocked: non-diagonal gate on the shard qubit
        eng.H(1)
        eng.CNOT(0, 1)
    assert q.engine is None
    assert q._anc == 1
    np.testing.assert_allclose(q.GetQuantumState(), o.GetQuantumState(),
                               atol=1e-10)
    assert q.engine is None  # state read must not materialize the original


def test_t_depth_chain_stays_on_tableau():
    n = 5
    q = make(n, 7)
    q.max_ancilla = 24
    o = oracle(n, 7)
    for eng in (q, o):
        for layer in range(4):
            for i in range(n):
                eng.H(i)
                eng.T(i)
            for i in range(n - 1):
                eng.CNOT(i, i + 1)
    assert q.engine is None
    assert 0 < q._anc <= q.max_ancilla
    f = abs(np.vdot(q.GetQuantumState(), o.GetQuantumState())) ** 2
    assert f == pytest.approx(1.0, abs=1e-9)


def test_wide_t_circuit_no_materialization():
    # 30 logical qubits: a dense ket would be 16 GiB — the gadget keeps
    # everything on the tableau, including measurement
    n = 30
    q = make(n, 3)
    q.max_ancilla = 16
    for i in range(0, n, 3):
        q.H(i)
        q.T(i)
        q.CNOT((i + 1) % n, i)   # non-diagonal on the shard qubit: blocked
    assert q.engine is None
    assert q._anc > 0
    # untouched qubits stay separable: tableau-native measurement
    p = q.Prob(2)
    assert 0.0 <= p <= 1.0
    assert bool(q.M(2)) in (False, True)
    assert q.engine is None
    # a qubit entangled with buffered magic needs materialization, which
    # at this width is an honest MemoryError, not a silent wrong answer
    with pytest.raises(MemoryError):
        q.Prob(0)


def test_sector_flush_to_tableau():
    # Z.T shard: the Z part must fold into the tableau, only the T
    # residual goes to the ancilla
    q = make(2)
    o = oracle(2)
    for eng in (q, o):
        eng.H(0)
        eng.T(0)
        eng.Z(0)
        eng.S(0)        # shard angle = pi/4 + pi + pi/2 -> sector 3
        eng.CNOT(1, 0)  # block it
    assert q.engine is None and q._anc == 1
    np.testing.assert_allclose(q.GetQuantumState(), o.GetQuantumState(),
                               atol=1e-10)


def test_near_clifford_rounding_tracks_fidelity():
    q = make(2)
    q.SetNcrp(0.2)
    q.H(0)
    q.RZ(0.1, 0)      # |sin(0.05)| ~ 0.05 < 0.2: rounded away
    q.CNOT(1, 0)      # trigger the flush
    assert q.engine is None
    assert q._anc == 0
    assert q.GetUnitaryFidelity() < 1.0
    assert q.GetUnitaryFidelity() == pytest.approx(math.cos(0.05) ** 2, abs=1e-9)


def test_ancilla_budget_switches_to_engine():
    q = make(3)
    q.max_ancilla = 2
    o = oracle(3)
    for eng in (q, o):
        for k in range(4):
            eng.H(0)
            eng.T(0)
            eng.CNOT(1, 0)
    assert q.engine is not None  # budget exceeded: materialized
    f = abs(np.vdot(q.GetQuantumState(), o.GetQuantumState())) ** 2
    assert f == pytest.approx(1.0, abs=1e-9)


def test_compose_with_pending_ancillae():
    a = make(2, 1)
    b = make(2, 2)
    oa = oracle(2, 1)
    for eng in (a, oa):
        eng.H(0)
        eng.T(0)
        eng.CNOT(1, 0)   # gadget on side a
    b.H(1)
    b.T(1)
    b.CNOT(0, 1)         # gadget on side b
    ob = oracle(2, 2)
    ob.H(1)
    ob.T(1)
    ob.CNOT(0, 1)
    a.Compose(b)
    oa.Compose(ob)
    assert a.engine is None
    assert a._anc == 2
    np.testing.assert_allclose(a.GetQuantumState(), oa.GetQuantumState(),
                               atol=1e-10)


def test_disable_t_injection_env():
    q = make(2)
    q.SetTInjection(False)
    q.H(0)
    q.T(0)
    q.CNOT(1, 0)
    assert q.engine is not None  # old behavior: materialize


def test_measurement_after_gadget_matches_oracle_distribution():
    # the measured qubit is entangled with buffered ancilla magic, so a
    # raw tableau draw would be 50/50; the exact distribution comes from
    # the (cheap, 2-qubit) engine switch
    o = oracle(2)
    o.H(0)
    o.T(0)
    o.CNOT(1, 0)
    o.H(0)
    p1 = o.Prob(0)
    counts = {0: 0, 1: 0}
    trials = 120
    for trial in range(trials):
        q = make(2, seed=300 + trial)
        q.H(0)
        q.T(0)
        q.CNOT(1, 0)
        q.H(0)
        counts[int(q.M(0))] += 1
    rate = counts[1] / trials
    assert abs(rate - p1) < 0.15, (rate, p1)


def test_prob_through_entangled_ancilla_is_exact():
    # H T H |0>: the T gadgets onto an ancilla; the raw tableau marginal
    # would be 0.5 — the true answer is sin^2(pi/8)
    q = make(1)
    q.H(0)
    q.T(0)
    q.H(0)
    assert q._anc == 1 and q.engine is None
    assert q.Prob(0) == pytest.approx(math.sin(math.pi / 8) ** 2, abs=1e-9)
    assert q.engine is None  # Prob used a clone, not self
    # collapse follows the same distribution (engine switch path)
    o = oracle(1)
    o.H(0); o.T(0); o.H(0)
    got = q.ForceM(0, False, do_force=True)
    assert got is False


def test_compose_propagates_rounding_fidelity():
    a = make(2, 1)
    b = make(2, 2)
    b.SetNcrp(0.3)
    b.H(0)
    b.RZ(0.2, 0)
    b.CNOT(1, 0)
    assert b.GetUnitaryFidelity() < 1.0
    a.Compose(b)
    assert a.GetUnitaryFidelity() == pytest.approx(b.GetUnitaryFidelity(), abs=1e-12)


def test_clifford_pair_measures_on_tableau_despite_unrelated_ancilla():
    # a Bell pair untouched by any magic must stay tableau-measurable
    # even while an unrelated gadget ancilla exists elsewhere
    n = 30
    q = make(n, 9)
    q.H(0)
    q.CNOT(0, 1)          # pure Clifford Bell pair
    q.H(5)
    q.T(5)
    q.CNOT(6, 5)          # gadget ancilla entangled with {5, 6} only
    assert q._anc == 1
    assert q.Prob(0) == pytest.approx(0.5, abs=1e-12)
    b0 = q.M(0)
    b1 = q.M(1)
    assert b0 == b1       # Bell correlation preserved
    assert q.engine is None  # never materialized (would be 2^31)


def test_ancilla_recycling_bounds_long_t_stream():
    """Dead gadget ancillae recycle via tableau-native DisposeZ instead
    of accumulating toward max_ancilla (reference reuses/disposes dead
    ancillae, src/qstabilizerhybrid.cpp:206-239)."""
    q = make(4, 2)
    max_seen = 0
    for rnd in range(60):
        t = rnd % 4
        q.H(t)
        q.T(t)
        q.H(t)
        q.M(t)
        max_seen = max(max_seen, q._anc)
        assert q.engine is None, f"materialized at round {rnd}"
    assert max_seen <= 2


def test_magic_measurement_statistics_follow_true_marginal():
    # H.T.H|0>: P(0) = cos^2(pi/8) — the outcome draw must weight the
    # buffered ancilla magic even though collapse stays on the tableau
    wins, n = 0, 600
    for seed in range(n):
        q = make(1, seed)
        q.H(0)
        q.T(0)
        q.H(0)
        wins += 0 if q.M(0) else 1
    p = wins / n
    assert abs(p - math.cos(math.pi / 8) ** 2) < 0.05


def test_post_collapse_amplitudes_match_oracle_without_materializing():
    for seed in range(6):
        h = make(3, seed)
        o = oracle(3, seed)
        for eng in (h, o):
            eng.H(0); eng.T(0); eng.H(0); eng.CNOT(0, 1); eng.T(1); eng.H(1)
        r = h.ForceM(0, False, do_force=False)
        o.ForceM(0, r, do_force=True)
        assert h.engine is None
        np.testing.assert_allclose(
            h.GetQuantumState(), o.GetQuantumState(), atol=1e-7)
