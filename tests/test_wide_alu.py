"""Width-generic (split-index) sharded ALU: the >31-qubit code path.

A 34-qubit ket cannot exist in this container's RAM, so these tests
force the pager's wide path (`force_wide_alu`) at small widths: the
exact ring-gather + split-index programs that would run past int32
widths execute against the 8-device CPU mesh and must match the host
oracle bit-for-bit.  The split algebra itself never builds an index
wider than 31 bits by construction (reference: width-generic ALU
kernels, src/common/qheader_alu.cl:13-810)."""

import numpy as np
import pytest

from qrack_tpu import QEngineCPU
from qrack_tpu.parallel.pager import QPager
from qrack_tpu.utils.rng import QrackRandom


def make_pair(n, seed=3, n_pages=4):
    o = QEngineCPU(n, rng=QrackRandom(seed), rand_global_phase=False)
    p = QPager(n, rng=QrackRandom(seed), rand_global_phase=False, n_pages=n_pages)
    p.force_wide_alu = True
    return o, p


def prep(eng, n, seed=9):
    rng = QrackRandom(seed)
    for i in range(n):
        if rng.randint(0, 2):
            eng.H(i)
        if rng.randint(0, 2):
            eng.X(i)


def assert_match(o, p, atol=3e-5):
    np.testing.assert_allclose(p.GetQuantumState(), o.GetQuantumState(), atol=atol)


def test_inc_across_pages():
    n = 7  # registers spanning the 5-local/2-page boundary (4 pages)
    for start, length in ((0, 7), (3, 4), (4, 3), (5, 2)):
        o, p = make_pair(n)
        for eng in (o, p):
            prep(eng, n)
            eng.INC(5, start, length)
            eng.INC((1 << length) - 2, start, length)
        assert_match(o, p)


def test_cinc_and_incdecc():
    n = 7
    o, p = make_pair(n)
    for eng in (o, p):
        prep(eng, n)
        eng.CINC(3, 1, 4, (6,))     # paged control bit
        eng.INCDECC(9, 0, 5, 6)     # carry on a paged bit
        eng.INCDECC(1, 2, 3, 5)
    assert_match(o, p)


def test_incs_and_incdecsc():
    n = 7
    o, p = make_pair(n)
    for eng in (o, p):
        prep(eng, n)
        eng.INCS(5, 0, 4, 6)        # overflow flag on a paged bit
        eng.INCDECSC(3, 0, 4, 5, 6)
    assert_match(o, p)


def test_rol_xmask_hash():
    n = 7
    o, p = make_pair(n)
    table = list(np.random.RandomState(5).permutation(1 << 4))
    for eng in (o, p):
        prep(eng, n)
        eng.ROL(3, 1, 6)            # rotation across the page boundary
        eng.XMask(0b1100101)
        eng.Hash(2, 4, table)
    assert_match(o, p)


def test_mulmodnout_family_across_pages():
    n = 8
    o, p = make_pair(n, n_pages=4)
    for eng in (o, p):
        eng.H(0)
        eng.H(1)
        eng.H(2)
        eng.MULModNOut(5, 13, 0, 4, 3)     # out register spans pages
    assert_match(o, p)
    for eng in (o, p):
        eng.IMULModNOut(5, 13, 0, 4, 3)    # and undo
    assert_match(o, p)


def test_powmodnout_and_controlled():
    n = 8
    o, p = make_pair(n, n_pages=4)
    for eng in (o, p):
        eng.H(0)
        eng.H(1)
        eng.X(3)
        eng.POWModNOut(7, 15, 0, 4, 3)
    assert_match(o, p)
    o2, p2 = make_pair(n, n_pages=4)
    for eng in (o2, p2):
        eng.H(0)
        eng.H(1)
        eng.H(3)
        eng.CMULModNOut(4, 9, 0, 4, 2, (3,))
    assert_match(o2, p2)


def test_indexed_lda_adc():
    n = 8
    values = [3, 1, 2, 0]
    o, p = make_pair(n, n_pages=4)
    for eng in (o, p):
        eng.H(0)
        eng.H(1)
        eng.IndexedLDA(0, 2, 4, 2, values)   # value register on paged bits
    assert_match(o, p)
    o2, p2 = make_pair(n, n_pages=4)
    for eng in (o2, p2):
        eng.H(0)
        eng.X(4)
        eng.IndexedADC(0, 2, 3, 2, 7, [1, 2, 3, 0])
    assert_match(o2, p2)


def test_shor_order_finding_slice_wide_path():
    # the Shor-critical sequence (H ladder, POWModNOut, IQFT) through
    # the forced wide path
    n = 9
    o, p = make_pair(n, n_pages=4)
    for eng in (o, p):
        for i in range(4):
            eng.H(i)
        eng.POWModNOut(2, 15, 0, 4, 4)
        eng.IQFT(0, 4)
    assert_match(o, p)


def test_mul_div_across_pages():
    # non-modular MUL/DIV through the split-index gather (carry register
    # spans the page boundary: L=6 locals with 4 pages at n=8)
    for to_mul in (3, 6, 5):  # odd, even (k=1), odd
        o, p = make_pair(8, n_pages=4)
        for eng in (o, p):
            eng.H(0)
            eng.H(1)
            eng.H(2)
            eng.H(7)
            eng.MUL(to_mul, 0, 4, 3)
        assert_match(o, p)
        for eng in (o, p):
            eng.DIV(to_mul, 0, 4, 3)
        assert_match(o, p)


def test_cmul_cdiv_paged_control():
    o, p = make_pair(8, n_pages=4)
    for eng in (o, p):
        eng.H(0)
        eng.H(1)
        eng.H(7)                      # paged control in superposition
        eng.CMUL(3, 0, 4, 3, (7,))
    assert_match(o, p)
    for eng in (o, p):
        eng.CDIV(3, 0, 4, 3, (7,))
    assert_match(o, p)


def test_generic_diagonals_wide():
    # every _k_phase_fn caller through the split-index wide path
    n = 7
    o, p = make_pair(n)
    for eng in (o, p):
        prep(eng, n)
        eng.ZMask(0b1100101)               # parity spans pages
        eng.PhaseParity(0.7, 0b0110011)
        eng.UniformParityRZ(0b1010110, 0.3)
        eng.CUniformParityRZ((6,), 0b0010011, 0.4)
        eng.PhaseFlipIfLess(5, 3, 4)       # register spans the boundary
        eng.CPhaseFlipIfLess(3, 0, 4, 6)   # flag on a paged bit
        eng.PhaseFlip()
    assert_match(o, p)


def test_forcemparity_wide():
    n = 7
    o, p = make_pair(n)
    for eng in (o, p):
        prep(eng, n)
        eng.ForceMParity(0b1100011, True)
    assert_match(o, p)


def test_mul_div_table_free(monkeypatch):
    # the table-free uint32-limb form must match the oracle exactly —
    # it is the path with NO host-RAM ceiling past QRACK_WIDE_MUL_TABLE_QB
    monkeypatch.setenv("QRACK_WIDE_MUL_TABLE_FREE", "1")
    for to_mul in (3, 6, 5, 7):
        o, p = make_pair(8, n_pages=4)
        for eng in (o, p):
            eng.H(0)
            eng.H(1)
            eng.H(2)
            eng.H(7)
            eng.MUL(to_mul, 0, 4, 3)
        assert_match(o, p)
        for eng in (o, p):
            eng.DIV(to_mul, 0, 4, 3)
        assert_match(o, p)
    o, p = make_pair(8, n_pages=4)
    for eng in (o, p):
        eng.H(0)
        eng.H(7)
        eng.CMUL(3, 0, 4, 3, (7,))
        eng.CDIV(3, 0, 4, 3, (7,))
    assert_match(o, p)


def test_product_split_limbs_exact():
    # uint32 limb arithmetic vs exact Python ints at the widths the
    # tables can no longer reach (L up to 30)
    from qrack_tpu.ops import alu_kernels as alu

    rs = np.random.RandomState(7)
    for length in (5, 16, 24, 29, 30):
        mask = (1 << length) - 1
        xs = rs.randint(0, 1 << length, size=64, dtype=np.int64)
        for to_mul in (3, (1 << (length - 1)) + 5, (3 << length) | 9):
            lo, hi = alu._product_split(np, xs, to_mul & mask,
                                        (to_mul >> length) & mask, length)
            exact = xs.astype(object) * to_mul
            np.testing.assert_array_equal(
                lo.astype(np.int64), np.asarray([p & mask for p in exact]))
            np.testing.assert_array_equal(
                hi.astype(np.int64),
                np.asarray([(p >> length) & mask for p in exact]))


def test_mul_consts_inverse():
    from qrack_tpu.ops import alu_kernels as alu

    for to_mul, length in ((3, 8), (12, 10), (5, 30), (6, 29)):
        k, consts = alu.mul_consts(to_mul, length)
        odd = to_mul >> k
        assert (odd * int(consts[2])) % (1 << length) == 1
        assert int(consts[0]) == to_mul & ((1 << length) - 1)
    with pytest.raises(ValueError):
        alu.mul_consts(16, 3)   # v2 > length
    with pytest.raises(ValueError):
        alu.mul_consts(0, 4)


def test_mul_wide_rejects_overwide_pow2_factor():
    # v2(to_mul) > length: the truncated product map is not a bijection,
    # so the wide path refuses instead of silently corrupting the ket
    o, p = make_pair(8, n_pages=4)
    p.H(0)
    with pytest.raises(ValueError):
        p.MUL(16, 0, 4, 3)
