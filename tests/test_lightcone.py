"""Light-cone circuit engine (qrack_tpu.lightcone, docs/LIGHTCONE.md):
cone extraction/relabeling units, cone-width feature pins on the
algorithm-model IR builders, parity vs the dense CPU oracle across the
observable surface at fusion windows 1 AND 16, mid-circuit-measure
semantics (buffer projector while narrow, projector closure across
entangled reads, materialization past the cap), checkpoint round-trips
(direct and through serve recover), the w50 acceptance scenario
(auto-routed with no pin, analytically exact, forced dense refused),
the lightcone.slice fault site, and the `== lightcone ==` report
section.
"""

import math

import numpy as np
import pytest

from qrack_tpu import QEngineCPU, create_quantum_interface
from qrack_tpu import matrices as mat
from qrack_tpu import telemetry as tele
from qrack_tpu.layers.qcircuit import QCircuit
from qrack_tpu.lightcone.engine import compact_over, sliced_shape_key
from qrack_tpu.models.algorithms import (brickwork_qcircuit,
                                         brickwork_theta, ghz_qcircuit,
                                         qaoa_qcircuit,
                                         quantum_volume_qcircuit,
                                         trotter_qcircuit)
from qrack_tpu.models.qft import qft_qcircuit
from qrack_tpu.resilience import faults
from qrack_tpu.resilience.errors import InjectedFault
from qrack_tpu.route import MisrouteError, decide, extract_features
from qrack_tpu.utils.rng import QrackRandom


@pytest.fixture
def telemetry():
    tele.enable()
    tele.reset()
    yield tele
    tele.reset()


def _fidelity(a, b) -> float:
    a = np.asarray(a).ravel()
    b = np.asarray(b).ravel()
    return float(abs(np.vdot(a, b)) ** 2)


# ---------------------------------------------------------------------------
# cone extraction / relabeling units
# ---------------------------------------------------------------------------


def test_compact_over_brickwork_cone_widths():
    c = brickwork_qcircuit(50)
    compact, order = compact_over(c, (25,))
    # depth-4 brickwork: a bulk qubit's past cone is 6 wide
    assert order == list(range(22, 28))
    assert compact.qubit_count == 6
    # every relabeled gate lives on the compact register
    for g in compact.gates:
        assert all(0 <= q < compact.qubit_count for q in g.qubits())
    # edge qubit: the cone is clipped by the register boundary
    _, order0 = compact_over(c, (0,))
    assert order0 == [0, 1, 2, 3]


def test_compact_over_elides_trailing_gates_and_digest_disambiguates():
    c = QCircuit(4)
    c.append_1q(0, mat.H2)
    c.append_ctrl((0,), 1, mat.X2, 1)
    c.append_1q(1, mat.Y2)
    ca, oa = compact_over(c, (0,))
    cb, ob = compact_over(c, (0, 1))
    # the trailing Y(1) cannot influence Prob(0): elided from its cone
    assert len(ca.gates) == 2
    assert len(cb.gates) == 3
    # ...but both reads share the cone qubit SET — only the structure
    # digest tells the two sliced circuits apart (the cone-cache key)
    assert oa == ob == [0, 1]
    assert ca.structure_digest() != cb.structure_digest()


def test_compact_over_preserves_payloads_and_control_order():
    u = mat.u3_mtrx(0.7, 0.4, 0.5)
    c = QCircuit(9)
    c.append_1q(2, mat.H2)
    c.append_1q(5, mat.H2)
    c.append_ctrl((5, 2), 7, u, 2)
    compact, order = compact_over(c, (7,))
    assert order == [2, 5, 7]
    qmap = {q: i for i, q in enumerate(order)}
    g = compact.gates[-1]
    # control ORDER (not just the set) and the perm key survive the
    # relabeling — perm keys index control positions, not qubit numbers
    assert g.controls == (qmap[5], qmap[2])
    assert g.target == qmap[7]
    assert np.allclose(g.payloads[2], u)


def test_sliced_shape_key_is_offset_invariant():
    a = QCircuit(50)
    a.append_1q(3, mat.H2)
    a.append_ctrl((3,), 4, mat.X2, 1)
    b = QCircuit(50)
    b.append_1q(20, mat.H2)
    b.append_ctrl((20,), 21, mat.X2, 1)
    d = QCircuit(50)
    d.append_1q(20, mat.H2)
    # same local structure at different offsets: one admission bucket
    assert sliced_shape_key(a) == sliced_shape_key(b)
    assert sliced_shape_key(a) != sliced_shape_key(d)
    assert sliced_shape_key(brickwork_qcircuit(50))[0] == 50


# ---------------------------------------------------------------------------
# cone-width features on the algorithm-model IR builders
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("builder,width,max_cone,by_depth", [
    (lambda: brickwork_qcircuit(50), 50, 6, (1, 2, 4, 6)),
    (lambda: ghz_qcircuit(12), 12, 12, tuple(range(1, 13))),
    (lambda: qaoa_qcircuit(8, p=1), 8, 8,
     (1, 2, 2, 2, 3, 3, 3, 4, 4, 4, 5, 5, 5, 6, 6, 6, 7, 7, 7,
      8, 8, 8, 8, 8, 8, 8)),
    (lambda: quantum_volume_qcircuit(6, rng=QrackRandom(17)), 6, 6,
     (1, 2, 2, 4, 4, 6, 6, 6, 6, 6, 6, 6, 6)),
    (lambda: trotter_qcircuit(10, steps=1), 10, 10,
     (2, 2, 2, 3, 3, 3, 4, 4, 4, 5, 5, 5, 6, 6, 6, 7, 7, 7, 8, 8, 8,
      9, 9, 9, 10, 10, 10, 10)),
], ids=["brickwork50", "ghz12", "qaoa8", "qv6", "trotter10"])
def test_cone_width_features(builder, width, max_cone, by_depth):
    f = extract_features(builder(), width)
    assert f.max_cone_width == max_cone
    assert f.cone_width_by_depth == by_depth
    d = f.as_dict()
    assert d["max_cone_width"] == max_cone
    assert tuple(d["cone_width_by_depth"]) == by_depth


# ---------------------------------------------------------------------------
# parity vs the dense CPU oracle across the observable surface
# ---------------------------------------------------------------------------


def _random_shallow_qcircuit(n: int, n_gates: int, seed: int) -> QCircuit:
    rng = np.random.Generator(np.random.PCG64(seed))
    c = QCircuit(n)
    for _ in range(n_gates):
        kind = int(rng.integers(0, 4))
        if kind == 0:
            q = int(rng.integers(0, n))
            th, ph, lm = (float(x) for x in rng.uniform(0.0, 2.0, 3))
            c.append_1q(q, mat.u3_mtrx(th, ph, lm))
        else:
            qs = rng.choice(n, size=3, replace=False)
            a, b, t = (int(q) for q in qs)
            if kind == 1:
                c.append_ctrl((a,), b, mat.X2, 1)
            elif kind == 2:
                c.append_ctrl((a,), b, mat.Z2, 1)
            else:
                c.append_ctrl((a, b), t, mat.X2, 3)
    return c


@pytest.mark.parametrize("window", ["1", "16"])
@pytest.mark.parametrize("trial", [0, 1])
def test_observable_surface_parity_vs_dense_oracle(window, trial,
                                                   monkeypatch):
    monkeypatch.setenv("QRACK_TPU_FUSE_WINDOW", window)
    monkeypatch.delenv("QRACK_ROUTE", raising=False)
    n = 12
    circ = _random_shallow_qcircuit(n, 30, seed=7100 + trial)
    lc = create_quantum_interface("lightcone", n, rng=QrackRandom(trial),
                                  rand_global_phase=False)
    o = QEngineCPU(n, rng=QrackRandom(trial), rand_global_phase=False)
    circ.Run(lc)
    circ.Run(o)

    for q in range(n):
        assert abs(lc.Prob(q) - o.Prob(q)) < 1e-6
    for mask in (0b1, 0b101, 0b110011, (1 << n) - 1):
        assert abs(lc.ProbParity(mask) - o.ProbParity(mask)) < 1e-6
        assert abs(lc.ProbMask(mask, mask & 0b10101)
                   - o.ProbMask(mask, mask & 0b10101)) < 1e-6
        np.testing.assert_allclose(lc.ProbMaskAll(mask),
                                   o.ProbMaskAll(mask), atol=1e-6)
    bits = [0, 3, 7, 11]
    np.testing.assert_allclose(lc.ProbBitsAll(bits), o.ProbBitsAll(bits),
                               atol=1e-6)
    assert abs(lc.ExpectationBitsAll(bits) - o.ExpectationBitsAll(bits)) \
        < 1e-5
    for perm in (0, 1, 42, (1 << n) - 1):
        # random global phase: compare magnitudes, never raw amplitudes
        assert abs(abs(lc.GetAmplitude(perm))
                   - abs(o.GetAmplitude(perm))) < 1e-6
    np.testing.assert_allclose(np.asarray(lc.GetProbs()),
                               np.asarray(o.GetProbs()), atol=1e-6)
    assert _fidelity(lc.GetQuantumState(), o.GetQuantumState()) > 1 - 1e-6

    # shot keys index q_powers positions; every sampled key must sit in
    # the oracle's support (rng streams legitimately differ per stack)
    powers = [1 << b for b in bits]
    shots = lc.MultiShotMeasureMask(powers, 64)
    assert sum(shots.values()) == 64
    marg = np.asarray(o.ProbBitsAll(bits))
    for key in shots:
        assert marg[key] > 1e-9


# ---------------------------------------------------------------------------
# mid-circuit measurement: buffer projector while the cone is narrow
# ---------------------------------------------------------------------------


def test_m_records_projector_and_closure_reaches_entangled_reads(
        telemetry):
    lc = create_quantum_interface("lightcone", 12, seed=7)
    lc.H(0)
    lc.MCMtrxPerm((0,), mat.X2, 1, 1)
    lc.MCMtrxPerm((1,), mat.X2, 2, 1)
    m = float(lc.M(0))
    # collapse recorded into the buffer — no full-width register
    assert lc.sim is None
    assert len(lc.circuit.gates) == 4
    # the projector on q0 is a TRAILING gate from q1/q2's viewpoint,
    # but non-unitary: the slicer must pull it (and its history) into
    # every entangled read, or GHZ marginals come out 0.5
    assert abs(lc.Prob(0) - m) < 1e-6
    assert abs(lc.Prob(1) - m) < 1e-6
    assert abs(lc.Prob(2) - m) < 1e-6
    clone = lc.Clone()
    assert abs(clone.Prob(2) - m) < 1e-6
    snap = telemetry.snapshot()
    assert snap["counters"]["lightcone.m.projector"] == 1
    assert snap["counters"].get("lightcone.materialize.full", 0) == 0


def test_projector_across_product_cut_stays_elided():
    lc = create_quantum_interface("lightcone", 12, seed=3)
    lc.H(0)
    lc.H(5)
    lc.M(5)
    # q5's collapse is across a product cut: Prob(0)'s cone stays 1 wide
    _, order = lc._slice((0,))
    assert order == [0]
    assert abs(lc.Prob(0) - 0.5) < 1e-6


def test_force_m_zero_probability_raises():
    lc = create_quantum_interface("lightcone", 3, seed=1)
    lc.X(0)
    with pytest.raises(RuntimeError, match="zero probability"):
        lc.ForceM(0, False, do_force=True)


def test_m_past_cap_materializes(telemetry, monkeypatch):
    monkeypatch.setenv("QRACK_LIGHTCONE_M_MAX_QB", "2")
    lc = create_quantum_interface("lightcone", 6, seed=3)
    lc.H(0)
    for q in range(5):
        lc.MCMtrxPerm((q,), mat.X2, q + 1, 1)
    m = float(lc.M(5))   # past cone of q5 is all 6 qubits: > cap
    assert lc.sim is not None
    assert not lc.circuit.gates
    for q in range(6):
        assert abs(lc.Prob(q) - m) < 1e-6
    snap = telemetry.snapshot()
    assert snap["counters"]["lightcone.materialize.full"] == 1
    assert snap["counters"].get("lightcone.m.projector", 0) == 0


def test_force_m_matches_oracle_state():
    n = 8
    lc = create_quantum_interface("lightcone", n, seed=2,
                                  rand_global_phase=False)
    o = QEngineCPU(n, seed=2, rand_global_phase=False)
    for e in (lc, o):
        e.H(0)
        e.MCMtrxPerm((0,), mat.X2, 1, 1)
        e.H(2)
        e.MCMtrxPerm((2,), mat.X2, 3, 1)
    lc.ForceM(1, True)
    o.ForceM(1, True)
    assert _fidelity(lc.GetQuantumState(), o.GetQuantumState()) > 1 - 1e-6


# ---------------------------------------------------------------------------
# checkpoint round-trips: direct, and through serve recover
# ---------------------------------------------------------------------------


def test_lightcone_checkpoint_roundtrip_direct(tmp_path):
    from qrack_tpu.checkpoint import load_state, save_state

    n = 10
    lc = create_quantum_interface("lightcone", n, rng=QrackRandom(5),
                                  rand_global_phase=False)
    brickwork_qcircuit(n).Run(lc)
    _ = lc.Prob(4)          # warm one cone so the snapshot carries it
    lc.M(0)                 # and a recorded projector
    before = np.asarray(lc.GetQuantumState())
    path = str(tmp_path / "lightcone.qckpt")
    save_state(lc, path)
    back = load_state(path)
    assert back.sim is None
    assert len(back.circuit.gates) == len(lc.circuit.gates)
    f = _fidelity(before, back.GetQuantumState())
    assert f > 1 - 1e-6, f


def test_lightcone_session_checkpoint_roundtrip_serve_recover(
        monkeypatch, tmp_path):
    from qrack_tpu.serve import QrackService

    monkeypatch.setenv("QRACK_ROUTE", "lightcone")
    n = 10
    ck = str(tmp_path / "ck")
    a = QrackService(engine_layers="route", checkpoint_dir=ck,
                     batch_window_ms=5.0, tick_s=0.02)
    try:
        sid = a.create_session(n, seed=5, rand_global_phase=False)
        a.apply(sid, brickwork_qcircuit(n), timeout=120)
        out = a.drain()
        assert out == {"drained": [sid], "busy": []}
        with QrackService(engine_layers="route", checkpoint_dir=ck,
                          recover=True, batch_window_ms=5.0,
                          tick_s=0.02) as b:
            assert sid in b.sessions.ids()
            state = b.get_state(sid, timeout=120)
            sess = b.sessions.get(sid)
            assert sess.engine.current_stack() == "lightcone"
    finally:
        a.close()
    oracle = QEngineCPU(n, rng=QrackRandom(5), rand_global_phase=False)
    brickwork_qcircuit(n).Run(oracle)
    assert _fidelity(oracle.GetQuantumState(), state) > 1 - 1e-5


# ---------------------------------------------------------------------------
# w50 acceptance: auto-routed, analytically exact, forced dense refused
# ---------------------------------------------------------------------------


def test_w50_brickwork_auto_routes_lightcone_and_is_exact(telemetry,
                                                          monkeypatch):
    monkeypatch.delenv("QRACK_ROUTE", raising=False)
    d = decide(brickwork_qcircuit(50), 50)
    assert d.stack == "lightcone"
    assert d.reason == "cost"
    r = create_quantum_interface("route", 50, rng=QrackRandom(9))
    brickwork_qcircuit(50).Run(r)
    assert r.current_stack() == "lightcone"
    # CZ bricks are diagonal: Prob(q) = sin^2(theta_q / 2) exactly
    for q in (0, 1, 25, 49):
        want = math.sin(brickwork_theta(q) / 2.0) ** 2
        assert abs(r.Prob(q) - want) < 1e-6
    snap = telemetry.snapshot()
    assert snap["counters"]["lightcone.reads"] >= 4
    assert snap["counters"]["lightcone.cache.miss"] >= 1
    assert snap["counters"]["lightcone.gates.elided"] >= 1


def test_w50_forced_dense_refused(monkeypatch):
    monkeypatch.setenv("QRACK_ROUTE", "dense")
    r = create_quantum_interface("route", 50, rng=QrackRandom(9))
    with pytest.raises(MisrouteError, match="exceeds the dense ladder"):
        brickwork_qcircuit(50).Run(r)


def test_service_w50_shallow_next_to_dense(telemetry, monkeypatch):
    from qrack_tpu.serve import QrackService

    monkeypatch.delenv("QRACK_ROUTE", raising=False)
    svc = QrackService(engine_layers="route", batch_window_ms=1.0,
                       queue_budget_ms=120_000.0)
    try:
        wide = svc.create_session(50, seed=1)
        dense = svc.create_session(16, seed=2)
        h1 = svc.submit(wide, brickwork_qcircuit(50))
        h2 = svc.submit(dense, qft_qcircuit(16))
        h1.result(timeout=300)
        h2.result(timeout=300)
        stacks = {
            sid: svc.call(sid, lambda eng: eng.current_stack(),
                          mutates=False).result(timeout=60)
            for sid in (wide, dense)}
        assert stacks[wide] == "lightcone"
        assert stacks[dense] == "dense"
        for q in (0, 25, 49):
            p = svc.call(wide, lambda eng, q=q: eng.Prob(q),
                         mutates=False).result(timeout=120)
            assert abs(p - math.sin(brickwork_theta(q) / 2.0) ** 2) < 1e-6
        # a pinned-dense deployment refuses the same width AT submit,
        # while the dense tenant keeps serving under the pin
        monkeypatch.setenv("QRACK_ROUTE", "dense")
        pinned = svc.create_session(50, seed=3)
        with pytest.raises(MisrouteError, match="exceeds the dense ladder"):
            svc.submit(pinned, brickwork_qcircuit(50))
        assert abs(svc.prob(dense, 0, timeout=120) - 0.5) < 1e-3
    finally:
        svc.close()
    snap = telemetry.snapshot()
    assert snap["counters"]["route.jobs.lightcone"] >= 1
    assert snap["counters"]["route.jobs.dense"] >= 1


# ---------------------------------------------------------------------------
# lightcone.slice fault site: injected faults surface typed, never silent
# ---------------------------------------------------------------------------


def test_lightcone_slice_fault_surfaces_typed():
    lc = create_quantum_interface("lightcone", 6, seed=1)
    lc.H(0)
    try:
        faults.inject("lightcone.slice", "raise", after_n=0, times=1)
        with pytest.raises(InjectedFault):
            lc.Prob(0)
        # directive kinds the site must act out itself raise in-engine
        faults.inject("lightcone.slice", "hang", after_n=0, times=1)
        with pytest.raises(RuntimeError,
                           match="lightcone.slice injected fault"):
            lc.Prob(0)
    finally:
        faults.clear()
    assert abs(lc.Prob(0) - 0.5) < 1e-6   # state intact after the fault


# ---------------------------------------------------------------------------
# telemetry report: the == lightcone == section
# ---------------------------------------------------------------------------


def test_telemetry_report_lightcone_section(tmp_path, capsys):
    import importlib.util
    import pathlib

    tele.enable()
    tele.reset()
    tele.inc("lightcone.reads", 8)
    tele.inc("lightcone.reads.dense", 6)
    tele.inc("lightcone.reads.stabilizer", 2)
    tele.inc("lightcone.cache.hit", 5)
    tele.inc("lightcone.cache.miss", 3)
    tele.inc("lightcone.gates.cone", 30)
    tele.inc("lightcone.gates.elided", 70)
    tele.inc("lightcone.m.projector", 1)
    for w in (4.0, 6.0, 6.0, 6.0):
        tele.observe("lightcone.cone_width", w)
    out = tmp_path / "t.jsonl"
    tele.write_jsonl(str(out))
    tele.reset()

    path = (pathlib.Path(__file__).resolve().parent.parent
            / "scripts" / "telemetry_report.py")
    spec = importlib.util.spec_from_file_location("telemetry_report", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rep = mod.report(mod.load(str(out), aggregate=False), top=5)
    lc = rep["lightcone"]
    assert lc["elided_share"] == 0.7
    assert lc["cache_hit_rate"] == 0.625
    assert lc["rung_share.dense"] == 0.75
    assert lc["rung_share.stabilizer"] == 0.25
    assert lc["cone_width"]["count"] == 4
    assert lc["cone_width"]["max"] == 6.0
    assert mod.main([str(out)]) == 0
    assert "== lightcone ==" in capsys.readouterr().out
