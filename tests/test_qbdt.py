"""QBdt binary-decision-diagram engine vs the dense oracle."""

import numpy as np
import pytest

from qrack_tpu import QEngineCPU
from qrack_tpu.layers.qbdt import QBdt
from qrack_tpu.utils.rng import QrackRandom

from test_engine_matrix import random_circuit, align_phase


def make_pair(n, seed=1):
    b = QBdt(n, rng=QrackRandom(seed), rand_global_phase=False)
    d = QEngineCPU(n, rng=QrackRandom(seed), rand_global_phase=False)
    return b, d


def assert_match(b, d, atol=1e-7):
    got = align_phase(b.GetQuantumState(), d.GetQuantumState())
    np.testing.assert_allclose(got, d.GetQuantumState(), atol=atol)


def test_basis_and_1q_gates():
    b, d = make_pair(4)
    for eng in (b, d):
        eng.SetPermutation(0b1010)
        eng.H(0)
        eng.T(1)
        eng.U(2, 0.3, 0.7, -0.4)
    assert_match(b, d)


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_random_circuits(seed):
    n = 5
    b, d = make_pair(n, seed)
    random_circuit(b, QrackRandom(1500 + seed), 40, n)
    random_circuit(d, QrackRandom(1500 + seed), 40, n)
    assert_match(b, d, atol=1e-6)


def test_control_below_target():
    # control deeper than target in the tree
    b, d = make_pair(3)
    for eng in (b, d):
        eng.H(2)
        eng.CNOT(2, 0)   # control qubit 2 (deep), target 0 (root)
        eng.MCMtrxPerm((1, 2), np.array([[0, 1], [1, 0]]), 0, 0b10)
    assert_match(b, d)


def test_measurement():
    b, d = make_pair(4, seed=7)
    for eng in (b, d):
        eng.H(0)
        eng.CNOT(0, 1)
        eng.CNOT(1, 2)
        eng.rng.seed(9)
    assert b.Prob(2) == pytest.approx(d.Prob(2), abs=1e-9)
    assert b.M(1) == d.M(1)
    assert_match(b, d)


def test_ghz_compression():
    # GHZ at 40 qubits: dense impossible, tree is O(n) nodes
    b = QBdt(40, rng=QrackRandom(3), rand_global_phase=False)
    b.H(0)
    for i in range(39):
        b.CNOT(i, i + 1)
    assert b.node_count() <= 2 * 40 + 4
    assert b.Prob(35) == pytest.approx(0.5, abs=1e-9)
    b.rng.seed(5)
    m = b.M(20)
    assert b.Prob(0) == pytest.approx(1.0 if m else 0.0, abs=1e-9)
    amp = b.GetAmplitude((1 << 40) - 1 if m else 0)
    assert abs(amp) == pytest.approx(1.0, abs=1e-6)


def test_set_get_state_roundtrip():
    from helpers import rand_state

    psi = rand_state(5, 9)
    b = QBdt(5, rng=QrackRandom(1), rand_global_phase=False)
    b.SetQuantumState(psi)
    np.testing.assert_allclose(b.GetQuantumState(), psi, atol=1e-10)


def test_compose_and_clone():
    a, d = make_pair(2, seed=3)
    for eng in (a, d):
        eng.H(0)
        eng.CNOT(0, 1)
    other = QBdt(1, rng=QrackRandom(4), rand_global_phase=False)
    other.X(0)
    od = QEngineCPU(1, rng=QrackRandom(4), rand_global_phase=False)
    od.X(0)
    a.Compose(other)
    d.Compose(od)
    assert a.qubit_count == 3
    assert_match(a, d)
    c = a.Clone()
    c.X(0)
    assert abs(np.vdot(a.GetQuantumState(), c.GetQuantumState())) < 0.8


def test_bdt_hybrid_switches_on_blowup():
    from qrack_tpu.layers.qbdthybrid import QBdtHybrid

    def factory(n, **kw):
        kw.setdefault("rand_global_phase", False)
        return QEngineCPU(n, **kw)

    q = QBdtHybrid(6, engine_factory=factory, ratio_threshold=0.2,
                   rng=QrackRandom(5), rand_global_phase=False)
    d = QEngineCPU(6, rng=QrackRandom(5), rand_global_phase=False)
    # GHZ stays a tree
    for eng in (q, d):
        eng.H(0)
        for i in range(5):
            eng.CNOT(i, i + 1)
    assert q.isBinaryDecisionTree()
    # dense-entangling random circuit blows the tree up -> engine
    random_circuit(q, QrackRandom(1600), 60, 6)
    random_circuit(d, QrackRandom(1600), 60, 6)
    assert not q.isBinaryDecisionTree()
    got = align_phase(q.GetQuantumState(), d.GetQuantumState())
    np.testing.assert_allclose(got, d.GetQuantumState(), atol=1e-6)


def test_bdt_through_factory():
    from qrack_tpu import create_quantum_interface
    from qrack_tpu.models import algorithms as algo

    q = create_quantum_interface(["bdt_hybrid", "cpu"], 3, rng=QrackRandom(7))
    before, after = algo.teleport(q, prepare=lambda s: s.U(0, 0.8, 0.3, -0.5))
    assert abs(after - before) < 1e-5


# ---------------- attached dense-engine leaves ----------------
# (reference: tree-top over dense-engine leaves inside one ket,
#  include/qbdt.hpp:52-70 GetTraversal/SetTraversal + Attach)


@pytest.mark.parametrize("seed", [5, 6])
def test_attached_leaves_random_circuits(seed):
    """Same random battery, tree-top + dense-bottom representation."""
    n, att = 6, 3
    o = QEngineCPU(n, rng=QrackRandom(seed), rand_global_phase=False)
    q = QBdt(n, attached_qubits=att, rng=QrackRandom(seed),
             rand_global_phase=False)
    random_circuit(o, QrackRandom(300 + seed), 40, n)
    random_circuit(q, QrackRandom(300 + seed), 40, n)
    got = align_phase(q.GetQuantumState(), o.GetQuantumState())
    np.testing.assert_allclose(got, o.GetQuantumState(), atol=1e-8)


def test_attached_leaves_cross_region_gates():
    """Every control/target placement across the tree/leaf boundary."""
    n, att = 5, 2   # tree qubits 0-2, leaf qubits 3-4
    for ctrl, tgt in [(0, 4), (4, 0), (3, 4), (4, 3), (1, 2), (2, 3)]:
        o = QEngineCPU(n, rng=QrackRandom(7), rand_global_phase=False)
        q = QBdt(n, attached_qubits=att, rng=QrackRandom(7),
                 rand_global_phase=False)
        for e in (o, q):
            for i in range(n):
                e.H(i)
            e.T(ctrl)
            e.CNOT(ctrl, tgt)
            e.CZ(ctrl, tgt)
            e.RY(0.7, tgt)
        got = align_phase(q.GetQuantumState(), o.GetQuantumState())
        np.testing.assert_allclose(got, o.GetQuantumState(), atol=1e-8,
                                   err_msg=f"ctrl={ctrl} tgt={tgt}")


def test_attached_leaves_measurement():
    n, att = 6, 3
    q = QBdt(n, attached_qubits=att, rng=QrackRandom(11),
             rand_global_phase=False)
    q.H(0)
    q.CNOT(0, 5)      # entangle tree qubit with leaf qubit
    assert q.Prob(5) == pytest.approx(0.5, abs=1e-9)
    r = q.ForceM(5, True)
    assert r is True
    assert q.Prob(0) == pytest.approx(1.0, abs=1e-9)
    # leaf-region measurement collapsed the tree side too
    assert q.Prob(5) == pytest.approx(1.0, abs=1e-9)


def test_attached_beats_both_pure_forms():
    """GHZ over the low qubits tensor a RANDOM dense factor on the high
    qubits.  Tree-top + dense-bottom beats the pure dense ket on
    FOOTPRINT (a handful of nodes + one shared 2^k leaf vs 2^n
    amplitudes) and beats the pure tree on GATE TIME in the dense
    region (one vectorized kernel on the shared leaf vs a per-node
    Python recursion over ~2^k weight nodes) — the reason the reference
    hybridizes inside one representation instead of switching wholesale
    (include/qbdt.hpp:37-70)."""
    import time

    rng = np.random.Generator(np.random.PCG64(42))
    k, low = 8, 4
    n = low + k
    dense = rng.standard_normal(1 << k) + 1j * rng.standard_normal(1 << k)
    dense /= np.linalg.norm(dense)
    ghz = np.zeros(1 << low, np.complex128)
    ghz[0] = ghz[-1] = 1 / np.sqrt(2)
    full = np.kron(dense, ghz)   # high bits = dense factor

    hybrid = QBdt(n, attached_qubits=k, rng=QrackRandom(1),
                  rand_global_phase=False)
    hybrid.SetQuantumState(full)
    pure_tree = QBdt(n, rng=QrackRandom(2), rand_global_phase=False)
    pure_tree.SetQuantumState(full)

    # footprint: far below the dense ket's 2^n amplitudes
    assert hybrid.footprint_amps() < (1 << n) / 8
    # the dense factor is ONE shared leaf across both GHZ branches
    assert len({id(l) for l in hybrid._t.leaves.values()}) == 1

    def burst(q):
        t0 = time.perf_counter()
        for rep in range(3):
            for tq in range(low, n):     # gates in the dense region
                q.RY(0.1 + 0.01 * tq, tq)
                q.T(tq)
        return time.perf_counter() - t0

    t_tree = burst(pure_tree)
    t_hybrid = burst(hybrid)
    # vectorized leaf kernels vs per-node recursion: demand a clear win
    # (observed ~10x+; 2x margin keeps the test robust on loaded CI)
    assert t_hybrid < t_tree / 2, (t_hybrid, t_tree)

    # and both are still exact
    got = align_phase(hybrid.GetQuantumState(), pure_tree.GetQuantumState())
    np.testing.assert_allclose(got, pure_tree.GetQuantumState(), atol=1e-8)


def test_traversal_to_from_engine():
    """ToEngine/FromEngine roundtrip through the dense TPU engine
    (reference: GetTraversal/SetTraversal)."""
    n, att = 6, 2
    q = QBdt(n, attached_qubits=att, rng=QrackRandom(13),
             rand_global_phase=False)
    random_circuit(q, QrackRandom(14), 25, n)
    ref = q.GetQuantumState()
    eng = q.ToEngine()
    assert type(eng).__name__ == "QEngineTPU"
    back = QBdt.FromEngine(eng, attached_qubits=att, rng=QrackRandom(15),
                           rand_global_phase=False)
    got = align_phase(np.asarray(back.GetQuantumState()), ref)
    np.testing.assert_allclose(got, ref, atol=1e-5)
