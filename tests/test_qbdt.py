"""QBdt binary-decision-diagram engine vs the dense oracle."""

import numpy as np
import pytest

from qrack_tpu import QEngineCPU
from qrack_tpu.layers.qbdt import QBdt
from qrack_tpu.utils.rng import QrackRandom

from test_engine_matrix import random_circuit, align_phase


def make_pair(n, seed=1):
    b = QBdt(n, rng=QrackRandom(seed), rand_global_phase=False)
    d = QEngineCPU(n, rng=QrackRandom(seed), rand_global_phase=False)
    return b, d


def assert_match(b, d, atol=1e-7):
    got = align_phase(b.GetQuantumState(), d.GetQuantumState())
    np.testing.assert_allclose(got, d.GetQuantumState(), atol=atol)


def test_basis_and_1q_gates():
    b, d = make_pair(4)
    for eng in (b, d):
        eng.SetPermutation(0b1010)
        eng.H(0)
        eng.T(1)
        eng.U(2, 0.3, 0.7, -0.4)
    assert_match(b, d)


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_random_circuits(seed):
    n = 5
    b, d = make_pair(n, seed)
    random_circuit(b, QrackRandom(1500 + seed), 40, n)
    random_circuit(d, QrackRandom(1500 + seed), 40, n)
    assert_match(b, d, atol=1e-6)


def test_control_below_target():
    # control deeper than target in the tree
    b, d = make_pair(3)
    for eng in (b, d):
        eng.H(2)
        eng.CNOT(2, 0)   # control qubit 2 (deep), target 0 (root)
        eng.MCMtrxPerm((1, 2), np.array([[0, 1], [1, 0]]), 0, 0b10)
    assert_match(b, d)


def test_measurement():
    b, d = make_pair(4, seed=7)
    for eng in (b, d):
        eng.H(0)
        eng.CNOT(0, 1)
        eng.CNOT(1, 2)
        eng.rng.seed(9)
    assert b.Prob(2) == pytest.approx(d.Prob(2), abs=1e-9)
    assert b.M(1) == d.M(1)
    assert_match(b, d)


def test_ghz_compression():
    # GHZ at 40 qubits: dense impossible, tree is O(n) nodes
    b = QBdt(40, rng=QrackRandom(3), rand_global_phase=False)
    b.H(0)
    for i in range(39):
        b.CNOT(i, i + 1)
    assert b.node_count() <= 2 * 40 + 4
    assert b.Prob(35) == pytest.approx(0.5, abs=1e-9)
    b.rng.seed(5)
    m = b.M(20)
    assert b.Prob(0) == pytest.approx(1.0 if m else 0.0, abs=1e-9)
    amp = b.GetAmplitude((1 << 40) - 1 if m else 0)
    assert abs(amp) == pytest.approx(1.0, abs=1e-6)


def test_set_get_state_roundtrip():
    from helpers import rand_state

    psi = rand_state(5, 9)
    b = QBdt(5, rng=QrackRandom(1), rand_global_phase=False)
    b.SetQuantumState(psi)
    np.testing.assert_allclose(b.GetQuantumState(), psi, atol=1e-10)


def test_compose_and_clone():
    a, d = make_pair(2, seed=3)
    for eng in (a, d):
        eng.H(0)
        eng.CNOT(0, 1)
    other = QBdt(1, rng=QrackRandom(4), rand_global_phase=False)
    other.X(0)
    od = QEngineCPU(1, rng=QrackRandom(4), rand_global_phase=False)
    od.X(0)
    a.Compose(other)
    d.Compose(od)
    assert a.qubit_count == 3
    assert_match(a, d)
    c = a.Clone()
    c.X(0)
    assert abs(np.vdot(a.GetQuantumState(), c.GetQuantumState())) < 0.8


def test_bdt_hybrid_switches_on_blowup():
    from qrack_tpu.layers.qbdthybrid import QBdtHybrid

    def factory(n, **kw):
        kw.setdefault("rand_global_phase", False)
        return QEngineCPU(n, **kw)

    q = QBdtHybrid(6, engine_factory=factory, ratio_threshold=0.2,
                   rng=QrackRandom(5), rand_global_phase=False)
    d = QEngineCPU(6, rng=QrackRandom(5), rand_global_phase=False)
    # GHZ stays a tree
    for eng in (q, d):
        eng.H(0)
        for i in range(5):
            eng.CNOT(i, i + 1)
    assert q.isBinaryDecisionTree()
    # dense-entangling random circuit blows the tree up -> engine
    random_circuit(q, QrackRandom(1600), 60, 6)
    random_circuit(d, QrackRandom(1600), 60, 6)
    assert not q.isBinaryDecisionTree()
    got = align_phase(q.GetQuantumState(), d.GetQuantumState())
    np.testing.assert_allclose(got, d.GetQuantumState(), atol=1e-6)


def test_bdt_through_factory():
    from qrack_tpu import create_quantum_interface
    from qrack_tpu.models import algorithms as algo

    q = create_quantum_interface(["bdt_hybrid", "cpu"], 3, rng=QrackRandom(7))
    before, after = algo.teleport(q, prepare=lambda s: s.U(0, 0.8, 0.3, -0.5))
    assert abs(after - before) < 1e-5


# ---------------- attached dense-engine leaves ----------------
# (reference: tree-top over dense-engine leaves inside one ket,
#  include/qbdt.hpp:52-70 GetTraversal/SetTraversal + Attach)


@pytest.mark.parametrize("seed", [5, 6])
def test_attached_leaves_random_circuits(seed):
    """Same random battery, tree-top + dense-bottom representation."""
    n, att = 6, 3
    o = QEngineCPU(n, rng=QrackRandom(seed), rand_global_phase=False)
    q = QBdt(n, attached_qubits=att, rng=QrackRandom(seed),
             rand_global_phase=False)
    random_circuit(o, QrackRandom(300 + seed), 40, n)
    random_circuit(q, QrackRandom(300 + seed), 40, n)
    got = align_phase(q.GetQuantumState(), o.GetQuantumState())
    np.testing.assert_allclose(got, o.GetQuantumState(), atol=1e-8)


def test_attached_leaves_cross_region_gates():
    """Every control/target placement across the tree/leaf boundary."""
    n, att = 5, 2   # tree qubits 0-2, leaf qubits 3-4
    for ctrl, tgt in [(0, 4), (4, 0), (3, 4), (4, 3), (1, 2), (2, 3)]:
        o = QEngineCPU(n, rng=QrackRandom(7), rand_global_phase=False)
        q = QBdt(n, attached_qubits=att, rng=QrackRandom(7),
                 rand_global_phase=False)
        for e in (o, q):
            for i in range(n):
                e.H(i)
            e.T(ctrl)
            e.CNOT(ctrl, tgt)
            e.CZ(ctrl, tgt)
            e.RY(0.7, tgt)
        got = align_phase(q.GetQuantumState(), o.GetQuantumState())
        np.testing.assert_allclose(got, o.GetQuantumState(), atol=1e-8,
                                   err_msg=f"ctrl={ctrl} tgt={tgt}")


def test_attached_leaves_measurement():
    n, att = 6, 3
    q = QBdt(n, attached_qubits=att, rng=QrackRandom(11),
             rand_global_phase=False)
    q.H(0)
    q.CNOT(0, 5)      # entangle tree qubit with leaf qubit
    assert q.Prob(5) == pytest.approx(0.5, abs=1e-9)
    r = q.ForceM(5, True)
    assert r is True
    assert q.Prob(0) == pytest.approx(1.0, abs=1e-9)
    # leaf-region measurement collapsed the tree side too
    assert q.Prob(5) == pytest.approx(1.0, abs=1e-9)


def test_attached_beats_both_pure_forms():
    """GHZ over the low qubits tensor a RANDOM dense factor on the high
    qubits.  Tree-top + dense-bottom beats the pure dense ket on
    FOOTPRINT (a handful of nodes + one shared 2^k leaf vs 2^n
    amplitudes) and beats the pure tree on GATE TIME in the dense
    region (one vectorized kernel on the shared leaf vs a per-node
    Python recursion over ~2^k weight nodes) — the reason the reference
    hybridizes inside one representation instead of switching wholesale
    (include/qbdt.hpp:37-70)."""
    import time

    rng = np.random.Generator(np.random.PCG64(42))
    k, low = 8, 4
    n = low + k
    dense = rng.standard_normal(1 << k) + 1j * rng.standard_normal(1 << k)
    dense /= np.linalg.norm(dense)
    ghz = np.zeros(1 << low, np.complex128)
    ghz[0] = ghz[-1] = 1 / np.sqrt(2)
    full = np.kron(dense, ghz)   # high bits = dense factor

    hybrid = QBdt(n, attached_qubits=k, rng=QrackRandom(1),
                  rand_global_phase=False)
    hybrid.SetQuantumState(full)
    pure_tree = QBdt(n, rng=QrackRandom(2), rand_global_phase=False)
    pure_tree.SetQuantumState(full)

    # footprint: far below the dense ket's 2^n amplitudes
    assert hybrid.footprint_amps() < (1 << n) / 8
    # the dense factor is ONE shared leaf across both GHZ branches
    assert len({id(l) for l in hybrid._t.leaves.values()}) == 1

    def burst(q):
        t0 = time.perf_counter()
        for rep in range(3):
            for tq in range(low, n):     # gates in the dense region
                q.RY(0.1 + 0.01 * tq, tq)
                q.T(tq)
        return time.perf_counter() - t0

    t_tree = burst(pure_tree)
    t_hybrid = burst(hybrid)
    # vectorized leaf kernels vs per-node recursion: demand a clear win
    # (observed ~10x+; 2x margin keeps the test robust on loaded CI)
    assert t_hybrid < t_tree / 2, (t_hybrid, t_tree)

    # and both are still exact
    got = align_phase(hybrid.GetQuantumState(), pure_tree.GetQuantumState())
    np.testing.assert_allclose(got, pure_tree.GetQuantumState(), atol=1e-8)


def test_traversal_to_from_engine():
    """ToEngine/FromEngine roundtrip through the dense TPU engine
    (reference: GetTraversal/SetTraversal)."""
    n, att = 6, 2
    q = QBdt(n, attached_qubits=att, rng=QrackRandom(13),
             rand_global_phase=False)
    random_circuit(q, QrackRandom(14), 25, n)
    ref = q.GetQuantumState()
    eng = q.ToEngine()
    assert type(eng).__name__ == "QEngineTPU"
    back = QBdt.FromEngine(eng, attached_qubits=att, rng=QrackRandom(15),
                           rand_global_phase=False)
    got = align_phase(np.asarray(back.GetQuantumState()), ref)
    np.testing.assert_allclose(got, ref, atol=1e-5)


# ---------------- tree-native separation ----------------
# (reference: Decompose/Dispose operate on the tree without dense
#  materialization, include/qbdt.hpp:37-70, src/qbdt/tree.cpp)


def _product_halves(n, seed):
    """Product state: independent circuits on [0, n/2) and [n/2, n)."""
    q = QBdt(n, rng=QrackRandom(seed), rand_global_phase=False)
    h = n // 2
    q.H(0); q.T(0); q.CNOT(0, 1); q.RY(0.3, 2 % h)
    q.H(h); q.CNOT(h, h + 1); q.T(h + 1); q.RZ(0.7, h + 2 if h + 2 < n else h)
    return q


def test_tree_decompose_no_materialization(monkeypatch):
    """Decompose of a 24-qubit product state must stay on the tree:
    no dense fallback, no GetQuantumState, peak transient 2^12 not
    2^24 (the VERDICT r4 done-criterion)."""
    n, h = 24, 12
    q = _product_halves(n, seed=31)

    def boom(*a, **k):
        raise AssertionError("dense path used for a separable cut")

    monkeypatch.setattr(QBdt, "_dense_split", boom)
    monkeypatch.setattr(QBdt, "GetQuantumState", boom)
    dest = QBdt(h, rng=QrackRandom(32), rand_global_phase=False)
    q.Decompose(h, dest)
    monkeypatch.undo()

    assert q.qubit_count == h and dest.qubit_count == h
    # both factors normalized and equal to the independently-built halves
    a = QBdt(h, rng=QrackRandom(33), rand_global_phase=False)
    a.H(0); a.T(0); a.CNOT(0, 1); a.RY(0.3, 2)
    b = QBdt(h, rng=QrackRandom(34), rand_global_phase=False)
    b.H(0); b.CNOT(0, 1); b.T(1); b.RZ(0.7, 2)
    got_low = align_phase(q.GetQuantumState(), a.GetQuantumState())
    np.testing.assert_allclose(got_low, a.GetQuantumState(), atol=1e-7)
    got_high = align_phase(dest.GetQuantumState(), b.GetQuantumState())
    np.testing.assert_allclose(got_high, b.GetQuantumState(), atol=1e-7)


def test_tree_decompose_matches_dense(monkeypatch):
    """Tree-native middle-range Decompose == QEngineCPU Decompose."""
    n, start, length = 9, 3, 3
    q = QBdt(n, rng=QrackRandom(41), rand_global_phase=False)
    d = QEngineCPU(n, rng=QrackRandom(41), rand_global_phase=False)
    for eng in (q, d):
        eng.H(0); eng.CNOT(0, 1); eng.T(1)            # low block
        eng.H(start); eng.CNOT(start, start + 1)      # middle block
        eng.RY(0.4, start + 2)
        eng.H(6); eng.CNOT(6, 7); eng.CNOT(7, 8)      # high block
    monkeypatch.setattr(QBdt, "_dense_split", lambda *a, **k: (_ for _ in ()).throw(
        AssertionError("dense path used for a separable cut")))
    qd = QBdt(length, rng=QrackRandom(42), rand_global_phase=False)
    q.Decompose(start, qd)
    monkeypatch.undo()
    dd = QEngineCPU(length, rng=QrackRandom(43), rand_global_phase=False)
    d.Decompose(start, dd)
    got = align_phase(qd.GetQuantumState(), dd.GetQuantumState())
    np.testing.assert_allclose(got, dd.GetQuantumState(), atol=1e-6)
    got = align_phase(q.GetQuantumState(), d.GetQuantumState())
    np.testing.assert_allclose(got, d.GetQuantumState(), atol=1e-6)


def test_tree_dispose_separable(monkeypatch):
    n, h = 8, 4
    q = _product_halves(n, seed=51)
    ref = QBdt(h, rng=QrackRandom(52), rand_global_phase=False)
    ref.H(0); ref.T(0); ref.CNOT(0, 1); ref.RY(0.3, 2)
    monkeypatch.setattr(QBdt, "_dense_split", lambda *a, **k: (_ for _ in ()).throw(
        AssertionError("dense path used for a separable cut")))
    q.Dispose(h, h)
    monkeypatch.undo()
    assert q.qubit_count == h
    got = align_phase(q.GetQuantumState(), ref.GetQuantumState())
    np.testing.assert_allclose(got, ref.GetQuantumState(), atol=1e-7)


def test_dispose_perm_projects_exactly():
    """Dispose with a known disposed permutation strips entangled-basis
    registers exactly (projection + level strip, no separability)."""
    n = 6
    q = QBdt(n, rng=QrackRandom(61), rand_global_phase=False)
    d = QEngineCPU(n, rng=QrackRandom(61), rand_global_phase=False)
    for eng in (q, d):
        eng.SetPermutation(0b101 << 2)   # qubits [2,5) = 0b101
        eng.H(0); eng.CNOT(0, 1); eng.T(0)
        eng.RY(0.9, 5)
    q.Dispose(2, 3, 0b101)
    d.Dispose(2, 3, 0b101)
    got = align_phase(q.GetQuantumState(), d.GetQuantumState())
    np.testing.assert_allclose(got, d.GetQuantumState(), atol=1e-6)


def test_dispose_perm_zero_amplitude_raises():
    q = QBdt(4, rng=QrackRandom(62), rand_global_phase=False)
    q.SetPermutation(0)  # qubits 1,2 are |00>
    with pytest.raises(RuntimeError):
        q.Dispose(1, 2, 0b11)


def test_nonseparable_falls_back_dense():
    """An entangled cut must still work (dense fallback, exact)."""
    n = 6
    q = QBdt(n, rng=QrackRandom(71), rand_global_phase=False)
    d = QEngineCPU(n, rng=QrackRandom(71), rand_global_phase=False)
    for eng in (q, d):
        eng.H(0)
        for i in range(n - 1):
            eng.CNOT(i, i + 1)      # GHZ: no cut is separable
        eng.M(2)                    # collapse -> separable again? no:
        eng.H(3); eng.CNOT(3, 4)    # re-entangle across the cut
    # Dispose of [0,2) after full collapse of the GHZ chain is fine
    # dense; the point is no crash and state parity with the oracle
    q.Dispose(0, 2)
    d.Dispose(0, 2)
    got = align_phase(q.GetQuantumState(), d.GetQuantumState())
    np.testing.assert_allclose(got, d.GetQuantumState(), atol=1e-6)


def test_leaf_region_decompose(monkeypatch):
    """Decompose of the ENTIRE attached region via the shared-leaf cut."""
    n, att = 7, 3
    tq = n - att
    q = QBdt(n, attached_qubits=att, rng=QrackRandom(81),
             rand_global_phase=False)
    d = QEngineCPU(n, rng=QrackRandom(81), rand_global_phase=False)
    for eng in (q, d):
        eng.H(0); eng.CNOT(0, 1); eng.T(2)      # tree region
        eng.H(tq); eng.CNOT(tq, tq + 1); eng.RY(0.5, tq + 2)  # leaf region
    monkeypatch.setattr(QBdt, "_dense_split", lambda *a, **k: (_ for _ in ()).throw(
        AssertionError("dense path used for a shared-leaf cut")))
    qd = QBdt(att, rng=QrackRandom(82), rand_global_phase=False)
    q.Decompose(tq, qd)
    monkeypatch.undo()
    assert q.attached_qubits == 0 and q.qubit_count == tq
    dd = QEngineCPU(att, rng=QrackRandom(83), rand_global_phase=False)
    d.Decompose(tq, dd)
    got = align_phase(qd.GetQuantumState(), dd.GetQuantumState())
    np.testing.assert_allclose(got, dd.GetQuantumState(), atol=1e-6)
    got = align_phase(q.GetQuantumState(), d.GetQuantumState())
    np.testing.assert_allclose(got, d.GetQuantumState(), atol=1e-6)


# ---------------- engine-backed (device) leaves ----------------


@pytest.mark.parametrize("seed", [7, 8])
def test_device_leaves_match_host(seed, monkeypatch):
    """Device-resident leaf kets (XLA kernel path) == host-interned
    leaves == dense oracle, including cross-region gates."""
    monkeypatch.setenv("QRACK_QBDT_LEAF_DEVICE_QB", "1")
    n, att = 6, 3
    b = QBdt(n, attached_qubits=att, rng=QrackRandom(seed),
             rand_global_phase=False)
    assert b._leaf_on_device()
    d = QEngineCPU(n, rng=QrackRandom(seed), rand_global_phase=False)
    random_circuit(b, QrackRandom(1700 + seed), 30, n)
    random_circuit(d, QrackRandom(1700 + seed), 30, n)
    got = align_phase(b.GetQuantumState(), d.GetQuantumState())
    np.testing.assert_allclose(got, d.GetQuantumState(), atol=1e-5)
    # measurement + probability paths exercise the device reductions
    assert abs(b.Prob(n - 1) - d.Prob(n - 1)) < 1e-5


def test_add_guard_mixed_depth():
    """_add across inconsistent representations fails loudly (ADVICE r4)."""
    from qrack_tpu.layers.qbdt import _EngLeaf, _Tree

    t = QBdt(3, attached_qubits=1, rng=QrackRandom(91),
             rand_global_phase=False)
    node = t.root
    while not isinstance(node, _EngLeaf):
        node = node[1] if node[1] is not None else node[3]
    with pytest.raises(ValueError):
        t._add(node, 1.0 + 0j, _Tree.LEAF, 1.0 + 0j, {})


# ---------------- attached form reachable from the stack ----------------


def test_qbdthybrid_attached_wiring():
    from qrack_tpu.layers.qbdthybrid import QBdtHybrid

    q = QBdtHybrid(6, attached_qubits=3, rng=QrackRandom(95),
                   rand_global_phase=False)
    assert q.bdt.attached_qubits == 3
    d = QEngineCPU(6, rng=QrackRandom(95), rand_global_phase=False)
    for eng in (q, d):
        eng.H(0); eng.CNOT(0, 3); eng.T(4); eng.CNOT(4, 5)
    got = align_phase(q.GetQuantumState(), d.GetQuantumState())
    np.testing.assert_allclose(got, d.GetQuantumState(), atol=1e-6)
    q.SetPermutation(5)
    assert q.bdt.attached_qubits == 3   # survives the reset rebuild


def test_factory_bdt_attached():
    from qrack_tpu import create_quantum_interface

    q = create_quantum_interface("bdt_attached", 6, rng=QrackRandom(96),
                                 rand_global_phase=False)
    assert q.attached_qubits == 3      # default n//2
    q2 = create_quantum_interface("bdt_attached", 6, attached_qubits=2,
                                  rng=QrackRandom(97),
                                  rand_global_phase=False)
    assert q2.attached_qubits == 2


# ---------------- mid-insertion Compose / adaptive attach ----------------


@pytest.mark.parametrize("start", [0, 2, 4])
def test_mid_insertion_compose_matches_dense(start, monkeypatch):
    """Compose at an arbitrary start is a tree splice (reference:
    Compose(toCopy, start)); state parity with the dense oracle and no
    dense materialization on the tree path."""
    n, m = 4, 2
    q = QBdt(n, rng=QrackRandom(101), rand_global_phase=False)
    d = QEngineCPU(n, rng=QrackRandom(101), rand_global_phase=False)
    for eng in (q, d):
        eng.H(0); eng.CNOT(0, 1); eng.T(2); eng.RY(0.4, 3)
    oq = QBdt(m, rng=QrackRandom(102), rand_global_phase=False)
    od = QEngineCPU(m, rng=QrackRandom(102), rand_global_phase=False)
    for eng in (oq, od):
        eng.H(0); eng.CNOT(0, 1); eng.T(1)
    monkeypatch.setattr(QBdt, "GetQuantumState", lambda *a: (_ for _ in ()).throw(
        AssertionError("dense path used for a tree splice")))
    q.Compose(oq, start)
    monkeypatch.undo()
    d.Compose(od, start)
    assert q.qubit_count == n + m
    got = align_phase(q.GetQuantumState(), d.GetQuantumState())
    np.testing.assert_allclose(got, d.GetQuantumState(), atol=1e-7)


def test_mid_insertion_compose_attached_self():
    """Splice below an attached region keeps the leaves on top."""
    n, att, m = 5, 2, 2
    q = QBdt(n, attached_qubits=att, rng=QrackRandom(103),
             rand_global_phase=False)
    d = QEngineCPU(n, rng=QrackRandom(103), rand_global_phase=False)
    for eng in (q, d):
        eng.H(0); eng.CNOT(0, 4); eng.T(3)
    oq = QBdt(m, rng=QrackRandom(104), rand_global_phase=False)
    od = QEngineCPU(m, rng=QrackRandom(104), rand_global_phase=False)
    for eng in (oq, od):
        eng.RY(0.7, 0); eng.CNOT(0, 1)
    q.Compose(oq, 1)
    d.Compose(od, 1)
    assert q.attached_qubits == att and q.qubit_count == n + m
    got = align_phase(q.GetQuantumState(), d.GetQuantumState())
    np.testing.assert_allclose(got, d.GetQuantumState(), atol=1e-7)


def test_mid_insertion_allocate():
    q = QBdt(3, rng=QrackRandom(105), rand_global_phase=False)
    d = QEngineCPU(3, rng=QrackRandom(105), rand_global_phase=False)
    for eng in (q, d):
        eng.H(0); eng.CNOT(0, 2)
        eng.Allocate(1, 2)
    assert q.qubit_count == 5
    got = align_phase(q.GetQuantumState(), d.GetQuantumState())
    np.testing.assert_allclose(got, d.GetQuantumState(), atol=1e-7)


def test_hybrid_adaptive_attach_beats_engine_switch():
    """Bottom-half entanglement blows the pure tree but fits the
    attached form: the hybrid escalates tree -> attached, NOT engine."""
    from qrack_tpu.layers.qbdthybrid import QBdtHybrid

    n = 8
    q = QBdtHybrid(n, engine_factory=lambda m, **kw: QEngineCPU(
        m, **{**kw, "rand_global_phase": False}),
        ratio_threshold=0.02, rng=QrackRandom(106), rand_global_phase=False)
    d = QEngineCPU(n, rng=QrackRandom(106), rand_global_phase=False)
    # dense-entangle ONLY the top half (deep qubits = leaf region)
    for eng in (q, d):
        for i in range(n // 2, n):
            eng.H(i)
        eng.CZ(4, 5); eng.CNOT(5, 6); eng.T(6); eng.CZ(6, 7)
        eng.RY(0.8, 7); eng.CNOT(4, 7); eng.RZ(0.3, 5); eng.CNOT(6, 4)
        eng.U(5, 0.2, 0.4, 0.6); eng.CZ(7, 5)
    assert q.isBinaryDecisionTree()        # still a tree...
    assert q.bdt.attached_qubits > 0       # ...in the attached form
    got = align_phase(q.GetQuantumState(), d.GetQuantumState())
    np.testing.assert_allclose(got, d.GetQuantumState(), atol=1e-6)


def test_compose_start_out_of_range_raises():
    q = QBdt(3, rng=QrackRandom(107), rand_global_phase=False)
    other = QBdt(1, rng=QrackRandom(108), rand_global_phase=False)
    with pytest.raises(ValueError):
        q.Compose(other, -1)
    with pytest.raises(ValueError):
        q.Allocate(7, 2)
