"""FPPOW float64 device path (reference: fp16-fp128 via FPPOW,
include/common/qrack_types.hpp:88-138) + f32->f64 drift escalation.

Each case runs in a subprocess: jax_enable_x64 is process-global, and
the rest of the suite must keep the production f32 defaults.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script: str, **env_extra) -> str:
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env.update({"JAX_PLATFORMS": "cpu"}, **env_extra)
    res = subprocess.run(
        [sys.executable, "-c", f"import sys; sys.path.insert(0, {REPO!r})\n" + script],
        capture_output=True, text=True, timeout=420, env=env)
    assert res.returncode == 0, (res.stdout[-3000:], res.stderr[-3000:])
    return res.stdout


def test_fppow_float64_engine_matrix():
    """QRACK_TPU_FPPOW=float64 produces real f64 planes through the
    factory default, and conformance vs the complex128 oracle holds at
    f64 tolerance (not f32's)."""
    out = _run("""
import numpy as np
import jax.numpy as jnp
import qrack_tpu
from qrack_tpu.engines.tpu import QEngineTPU
from qrack_tpu.engines.cpu import QEngineCPU
from qrack_tpu.parallel.pager import QPager
from qrack_tpu.utils.rng import QrackRandom

t = QEngineTPU(4, rng=QrackRandom(1), rand_global_phase=False)
assert t.dtype == jnp.dtype('float64'), t.dtype
assert t._state.dtype == jnp.dtype('float64'), t._state.dtype
d = QEngineCPU(4, rng=QrackRandom(1), rand_global_phase=False)
p = QPager(4, n_pages=2, rng=QrackRandom(1), rand_global_phase=False)
assert p.dtype == jnp.dtype('float64')
for eng in (t, d, p):
    eng.H(0); eng.CNOT(0, 1); eng.T(1); eng.RY(0.37, 2)
    eng.CZ(2, 3); eng.QFT(0, 4); eng.RZ(0.11, 3)
ref = d.GetQuantumState()
for eng, name in ((t, 'tpu'), (p, 'pager')):
    got = np.asarray(eng.GetQuantumState())
    err = np.max(np.abs(got - ref))
    assert err < 1e-12, (name, err)   # f32 planes would sit at ~1e-7
print('F64_MATRIX_OK')
""", QRACK_TPU_FPPOW="float64")
    assert "F64_MATRIX_OK" in out


def test_f64_beats_f32_on_deep_circuit():
    """A deep rotation chain accumulates visible f32 error that the f64
    path eliminates — the escalation policy's reason to exist."""
    out = _run("""
import numpy as np
import jax
jax.config.update('jax_enable_x64', True)
import jax.numpy as jnp
import qrack_tpu
from qrack_tpu.engines.tpu import QEngineTPU
from qrack_tpu.engines.cpu import QEngineCPU
from qrack_tpu.utils.rng import QrackRandom

DEPTH = 1500
def circuit(eng):
    for i in range(DEPTH):
        q = i % 3
        eng.RY(0.1 + (i % 7) * 0.01, q)
        eng.RZ(0.2 + (i % 5) * 0.01, (q + 1) % 3)
        if i % 3 == 0:
            eng.CNOT(q, (q + 1) % 3)

f32 = QEngineTPU(3, dtype=jnp.float32, rng=QrackRandom(2), rand_global_phase=False)
f64 = QEngineTPU(3, dtype=jnp.float64, rng=QrackRandom(2), rand_global_phase=False)
ora = QEngineCPU(3, rng=QrackRandom(2), rand_global_phase=False)
for eng in (f32, f64, ora):
    circuit(eng)
ref = ora.GetQuantumState()
e32 = np.max(np.abs(np.asarray(f32.GetQuantumState()) - ref))
e64 = np.max(np.abs(np.asarray(f64.GetQuantumState()) - ref))
assert e32 > 1e-6, e32          # f32 demonstrably degraded at this depth
assert e64 < 1e-11, e64         # f64 stays at oracle precision
assert e64 * 100 < e32, (e32, e64)
print('DEEP_OK', e32, e64)
""")
    assert "DEEP_OK" in out


def test_auto_escalation_on_drift():
    """QRACK_TPU_AUTO_F64_DRIFT: sustained norm drift re-casts the
    resident planes to float64 mid-run with a warning."""
    out = _run("""
import warnings
import numpy as np
import jax.numpy as jnp
import qrack_tpu
from qrack_tpu.engines.tpu import QEngineTPU
from qrack_tpu.utils.rng import QrackRandom

e = QEngineTPU(3, rng=QrackRandom(3), rand_global_phase=False)
assert e.dtype == jnp.dtype('float32')
e._state = e._state * np.float32(1.01)   # inject 2% norm drift
with warnings.catch_warnings(record=True) as rec:
    warnings.simplefilter('always')
    for i in range(8):
        e.H(i % 3)
assert e.dtype == jnp.dtype('float64'), e.dtype
assert e._state.dtype == jnp.dtype('float64')
assert any('escalating' in str(r.message) for r in rec)
# engine still operates correctly after the switch
e.CNOT(0, 1)
p = e.Prob(1)
assert 0.0 <= p <= 1.0
print('ESCALATE_OK')
""", QRACK_TPU_AUTO_F64_DRIFT="1e-3", QRACK_TPU_DRIFT_CHECK_GATES="4")
    assert "ESCALATE_OK" in out
