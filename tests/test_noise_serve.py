"""Trajectory jobs through the serving plane: submit_trajectories
parity, route-aware HBM chunking, structural non-batchability, the
dense-cap misroute guard, and WAL journal + bit-identical recovery."""

import json

import numpy as np
import pytest

from qrack_tpu import resilience as res
from qrack_tpu import telemetry as tele
from qrack_tpu.layers.qcircuit import QCircuit
from qrack_tpu.noise import NoiseModel, amplitude_damping, depolarizing
from qrack_tpu.noise.trajectories import run_trajectories
from qrack_tpu.resilience import faults
from qrack_tpu.serve import QrackService, batcher
from qrack_tpu.serve.scheduler import Job
from qrack_tpu.serve.service import TRAJ_TAG

W = 5  # session width — every trajectory ket is (2, 2^W)

_H = np.array([[1, 1], [1, -1]], dtype=complex) / np.sqrt(2)
_X = np.array([[0, 1], [1, 0]], dtype=complex)


@pytest.fixture(autouse=True)
def _clean_serve(monkeypatch):
    for k in ("QRACK_NOISE_TRAJ_WINDOW", "QRACK_NOISE_TRAJ_CHUNK",
              "QRACK_ROUTE_HBM_BYTES", "QRACK_ROUTE_DENSE_MAX_QB"):
        monkeypatch.delenv(k, raising=False)
    faults.clear()
    res.reset_breaker()
    batcher.clear_programs()
    yield
    faults.clear()
    res.reset_breaker()
    res.disable()
    tele.disable()
    tele.reset()
    batcher.clear_programs()


def _svc(**kw) -> QrackService:
    kw.setdefault("engine_layers", "cpu")
    kw.setdefault("batch_window_ms", 5.0)
    kw.setdefault("queue_budget_ms", 60_000.0)
    kw.setdefault("tick_s", 0.02)
    return QrackService(**kw)


def _circ() -> QCircuit:
    c = QCircuit(W)
    c.append_1q(0, _H)
    c.append_ctrl((0,), 1, _X, 1)
    c.append_1q(2, _H)
    return c


def _model() -> NoiseModel:
    return NoiseModel(default=depolarizing(0.1),
                      per_qubit={1: [amplitude_damping(0.2)]})


def test_submit_trajectories_matches_direct():
    """The serving path adds queueing and journaling, never randomness:
    a submitted batch is bit-identical to a direct engine run."""
    direct = run_trajectories(_circ(), _model(), 6, width=W, key=7)
    with _svc() as svc:
        sid = svc.create_session(W)
        res_ = svc.submit_trajectories(sid, _circ(), _model(), 6,
                                       key=7).result(timeout=60)
    assert np.array_equal(res_.samples, direct.samples)
    assert np.array_equal(res_.p1, direct.p1)
    assert np.array_equal(res_.weights, direct.weights)


def test_trajectory_jobs_are_not_batchable():
    """The trajectory axis is pre-stacked: the batcher must never join
    two tenants into one trajectory dispatch."""
    tj = Job(None, "trajectories", fn=lambda eng: None)
    assert not tj.batchable
    cj = Job(None, "circuit", circuit=object(), shape_key=("w", W))
    assert cj.batchable


def test_routed_hbm_chunking_parity(monkeypatch):
    """A batch priced over the HBM budget is chunked down to fit
    (route.traj.* telemetry) and still lands bit-identical."""
    whole = run_trajectories(_circ(), _model(), 6, width=W, key=13)
    # width 5: 16 B/amp * 32 amps = 512 B per resident trajectory;
    # a 1 KiB budget admits 2 at a time -> 3 dispatch rounds
    monkeypatch.setenv("QRACK_ROUTE_HBM_BYTES", "1024")
    tele.enable()
    tele.reset()
    with _svc() as svc:
        sid = svc.create_session(W)
        res_ = svc.submit_trajectories(sid, _circ(), _model(), 6,
                                       key=13).result(timeout=60)
    assert res_.chunks == 3
    assert np.array_equal(res_.samples, whole.samples)
    assert np.allclose(res_.p1, whole.p1, atol=1e-6)
    snap = tele.snapshot(include_events=False)
    assert snap["counters"].get("route.traj.chunked", 0) >= 1
    assert snap["counters"].get("noise.traj.chunked", 0) >= 1
    assert snap["gauges"].get("route.traj.chunk") == 2


def test_trajectory_misroute_past_dense_cap(monkeypatch):
    """Trajectories need dense batch kets: a session wider than the
    dense cap must be refused with the router's typed error."""
    from qrack_tpu.route.router import MisrouteError

    monkeypatch.setenv("QRACK_ROUTE_DENSE_MAX_QB", str(W - 1))
    with _svc() as svc:
        sid = svc.create_session(W)
        with pytest.raises(MisrouteError):
            svc.submit_trajectories(sid, _circ(), _model(), 4)


def test_trajectory_wal_recovery_bit_identical(tmp_path):
    """A journaled-but-unsettled trajectory job (crash between WAL
    append and settle) replays at recover() bit-identically: the rng
    position IS the (key, trajectory_id, app_seq) counters in the
    spec — nothing else to persist."""
    ck = str(tmp_path / "ck")
    spec = json.dumps({"B": 4, "key": 7, "model": _model().to_dict(),
                       "tag": None}, sort_keys=True)
    a = _svc(checkpoint_dir=ck)
    try:
        sid = a.create_session(W)
        # simulate the crash window: entry journaled, job never settled
        a.store.wal_append(sid, _circ(), tag=TRAJ_TAG + spec)
        out = a.drain()
        assert out == {"drained": [sid], "busy": []}
    finally:
        a.close()

    with _svc(checkpoint_dir=ck) as b:
        summary = b.recover()
        assert summary["sessions"] == [sid]
        assert summary["wal_replayed"] == 1
        got = summary["trajectories"][sid]
        assert len(got) == 1
    oracle = run_trajectories(_circ(), _model(), 4, width=W, key=7)
    assert np.array_equal(got[0].samples, oracle.samples)
    assert np.array_equal(got[0].p1, oracle.p1)
    assert np.array_equal(got[0].weights, oracle.weights)
