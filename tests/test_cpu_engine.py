"""Conformance tests for the dense CPU oracle engine.

Modeled on the reference's per-gate probability/amplitude assertions and
metamorphic checks (reference: test/tests.cpp — QFT round-trips,
Compose/Decompose inverses, engine cross-equivalence)."""

import math

import numpy as np
import pytest

from qrack_tpu import QEngineCPU
from qrack_tpu import matrices as mat
from qrack_tpu.utils.rng import QrackRandom

from helpers import full_unitary, rand_state


def make_engine(n, **kw):
    kw.setdefault("rand_global_phase", False)
    kw.setdefault("rng", QrackRandom(42))
    return QEngineCPU(n, **kw)


def test_initial_state():
    q = make_engine(3)
    s = q.GetQuantumState()
    assert s[0] == 1.0 and np.allclose(s[1:], 0)
    q2 = make_engine(3)
    q2.SetPermutation(5)
    assert q2.GetAmplitude(5) == 1.0


@pytest.mark.parametrize("gate,m", [
    ("H", mat.H2), ("X", mat.X2), ("Y", mat.Y2), ("Z", mat.Z2),
    ("S", mat.S2), ("T", mat.T2), ("SqrtX", mat.SQRTX2), ("SqrtY", mat.SQRTY2),
])
def test_single_qubit_gates_match_matrix(gate, m):
    n = 3
    for target in range(n):
        q = make_engine(n)
        psi = rand_state(n, seed=7 + target)
        q.SetQuantumState(psi)
        getattr(q, gate)(target)
        expect = full_unitary(n, m, [target]) @ psi
        np.testing.assert_allclose(q.GetQuantumState(), expect, atol=1e-10)


def test_gate_inverses():
    n = 4
    q = make_engine(n)
    psi = rand_state(n, seed=3)
    q.SetQuantumState(psi)
    pairs = [
        (lambda: q.S(1), lambda: q.IS(1)),
        (lambda: q.T(2), lambda: q.IT(2)),
        (lambda: q.SqrtX(0), lambda: q.ISqrtX(0)),
        (lambda: q.SqrtY(3), lambda: q.ISqrtY(3)),
        (lambda: q.SqrtW(1), lambda: q.ISqrtW(1)),
        (lambda: q.U(2, 0.3, 0.7, -0.4), lambda: q.Mtrx(np.conj(mat.u3_mtrx(0.3, 0.7, -0.4).T), 2)),
        (lambda: q.AI(0, 0.5, 1.1), lambda: q.IAI(0, 0.5, 1.1)),
        (lambda: q.ISwap(0, 2), lambda: q.IISwap(0, 2)),
        (lambda: q.SqrtSwap(1, 3), lambda: q.ISqrtSwap(1, 3)),
        (lambda: q.U2(1, 0.2, 0.9), lambda: q.IU2(1, 0.2, 0.9)),
    ]
    for fwd, inv in pairs:
        fwd()
        inv()
        np.testing.assert_allclose(q.GetQuantumState(), psi, atol=1e-8)


def test_sqrt_gates_square_correctly():
    np.testing.assert_allclose(mat.SQRTX2 @ mat.SQRTX2, mat.X2, atol=1e-12)
    np.testing.assert_allclose(mat.SQRTY2 @ mat.SQRTY2, mat.Y2, atol=1e-12)
    w = (mat.X2 + mat.Y2) / math.sqrt(2)
    np.testing.assert_allclose(mat.SQRTW2 @ mat.SQRTW2, w, atol=1e-12)


def test_controlled_gates():
    n = 4
    psi = rand_state(n, seed=11)
    # CNOT truth table
    q = make_engine(2)
    q.SetPermutation(1)  # control qubit 0 set
    q.CNOT(0, 1)
    assert q.GetAmplitude(3) == pytest.approx(1.0)
    # general controlled matrix vs brute force
    q = make_engine(n)
    q.SetQuantumState(psi)
    m = mat.u3_mtrx(1.2, 0.4, -0.8)
    q.MCMtrx((1, 3), m, 0)
    # brute force: apply m to target 0 when qubits 1,3 both set
    u = np.eye(1 << n, dtype=np.complex128)
    for i in range(1 << n):
        if ((i >> 1) & 1) and ((i >> 3) & 1) and not (i & 1):
            j = i | 1
            u[i, i], u[i, j] = m[0, 0], m[0, 1]
            u[j, i], u[j, j] = m[1, 0], m[1, 1]
    np.testing.assert_allclose(q.GetQuantumState(), u @ psi, atol=1e-10)


def test_anti_and_perm_controls():
    n = 3
    psi = rand_state(n, seed=13)
    q = make_engine(n)
    q.SetQuantumState(psi)
    q.MACMtrx((1, 2), mat.X2, 0)  # applies X when q1=q2=0
    u = np.zeros((1 << n, 1 << n), dtype=np.complex128)
    for i in range(1 << n):
        if ((i >> 1) & 1) == 0 and ((i >> 2) & 1) == 0:
            u[i ^ 1, i] = 1
        else:
            u[i, i] = 1
    np.testing.assert_allclose(q.GetQuantumState(), u @ psi, atol=1e-12)
    # mixed perm: control q1 must be 1, q2 must be 0
    q2 = make_engine(n)
    q2.SetQuantumState(psi)
    q2.MCMtrxPerm((1, 2), mat.X2, 0, 0b01)
    u = np.zeros((1 << n, 1 << n), dtype=np.complex128)
    for i in range(1 << n):
        if ((i >> 1) & 1) == 1 and ((i >> 2) & 1) == 0:
            u[i ^ 1, i] = 1
        else:
            u[i, i] = 1
    np.testing.assert_allclose(q2.GetQuantumState(), u @ psi, atol=1e-12)


def test_swap_family():
    n = 3
    psi = rand_state(n, seed=17)
    q = make_engine(n)
    q.SetQuantumState(psi)
    q.Swap(0, 2)
    expect = np.empty_like(psi)
    for i in range(1 << n):
        b0, b2 = i & 1, (i >> 2) & 1
        j = (i & 0b010) | (b0 << 2) | b2
        expect[j] = psi[i]
    np.testing.assert_allclose(q.GetQuantumState(), expect, atol=1e-12)
    # ISwap matrix check
    q2 = make_engine(2)
    q2.SetQuantumState(rand_state(2, 5))
    q2.ISwap(0, 1)
    iswap = np.array([[1, 0, 0, 0], [0, 0, 1j, 0], [0, 1j, 0, 0], [0, 0, 0, 1]])
    np.testing.assert_allclose(q2.GetQuantumState(), iswap @ rand_state(2, 5), atol=1e-10)
    # FSim(0, phi) == CPhase(phi)
    q3 = make_engine(2)
    q3.SetQuantumState(rand_state(2, 6))
    q3.FSim(0.0, 0.7, 0, 1)
    cp = np.diag([1, 1, 1, np.exp(-0.7j)])
    np.testing.assert_allclose(q3.GetQuantumState(), cp @ rand_state(2, 6), atol=1e-10)


def test_qft_roundtrip():
    n = 5
    psi = rand_state(n, seed=23)
    q = make_engine(n)
    q.SetQuantumState(psi)
    q.QFT(0, n)
    q.IQFT(0, n)
    np.testing.assert_allclose(q.GetQuantumState(), psi, atol=1e-8)


def test_qft_matches_dft():
    """QFT on a basis state must produce the DFT column (up to Qrack's
    bit-order convention)."""
    n = 4
    for basis in (0, 1, 5, 15):
        q = make_engine(n)
        q.SetPermutation(basis)
        q.QFT(0, n)
        # Qrack's QFT maps |x> -> sum_k e^{2 pi i x k / 2^n} |rev(k)>;
        # verify via IQFT round-trip against the explicit DFT instead:
        state = q.GetQuantumState()
        # total norm preserved and flat magnitude spectrum
        np.testing.assert_allclose(np.abs(state), 1 / math.sqrt(1 << n), atol=1e-8)


def test_prob_and_measure():
    q = make_engine(1)
    q.H(0)
    assert q.Prob(0) == pytest.approx(0.5, abs=1e-9)
    # deterministic force
    q.ForceM(0, True)
    assert q.Prob(0) == pytest.approx(1.0, abs=1e-9)

    # statistics: measure H|0> many times
    ones = 0
    rng = QrackRandom(123)
    for _ in range(400):
        q = QEngineCPU(1, rng=rng.spawn(), rand_global_phase=False)
        q.H(0)
        if q.M(0):
            ones += 1
    assert 140 < ones < 260


def test_mall_and_multishot():
    q = make_engine(3)
    q.H(0)
    q.CNOT(0, 1)
    q.CNOT(0, 2)  # GHZ
    shots = q.MultiShotMeasureMask([1, 2, 4], 1000)
    assert set(shots.keys()) <= {0, 7}
    assert 380 < shots.get(0, 0) < 620
    r = q.MAll()
    assert r in (0, 7)
    assert q.GetAmplitude(r) == pytest.approx(1.0, abs=1e-6)


def test_prob_reg_mask_parity():
    n = 4
    psi = rand_state(n, seed=29)
    q = make_engine(n)
    q.SetQuantumState(psi)
    probs = np.abs(psi) ** 2
    # ProbReg over [1,2): value 2 means q1=0,q2=1
    expect = sum(probs[i] for i in range(16) if ((i >> 1) & 3) == 2)
    assert q.ProbReg(1, 2, 2) == pytest.approx(expect, abs=1e-9)
    expect_mask = sum(probs[i] for i in range(16) if (i & 0b1010) == 0b1000)
    assert q.ProbMask(0b1010, 0b1000) == pytest.approx(expect_mask, abs=1e-9)
    par = sum(probs[i] for i in range(16) if bin(i & 0b0110).count("1") % 2 == 1)
    assert q.ProbParity(0b0110) == pytest.approx(par, abs=1e-9)


def test_expectation_variance():
    n = 3
    psi = rand_state(n, seed=31)
    q = make_engine(n)
    q.SetQuantumState(psi)
    probs = np.abs(psi) ** 2
    exp_direct = sum(p * i for i, p in enumerate(probs))
    assert q.ExpectationBitsAll([0, 1, 2]) == pytest.approx(exp_direct, abs=1e-9)
    var_direct = sum(p * (i - exp_direct) ** 2 for i, p in enumerate(probs))
    assert q.VarianceBitsAll([0, 1, 2]) == pytest.approx(var_direct, abs=1e-9)


def test_compose_decompose():
    a = make_engine(2)
    a.H(0)
    a.CNOT(0, 1)
    sa = a.GetQuantumState()
    b = make_engine(2)
    b.X(0)
    sb = b.GetQuantumState()
    start = a.Compose(b)
    assert start == 2 and a.GetQubitCount() == 4
    np.testing.assert_allclose(a.GetQuantumState(), np.kron(sb, sa), atol=1e-12)
    # decompose back out
    dest = make_engine(2)
    a.Decompose(2, dest)
    assert a.GetQubitCount() == 2
    np.testing.assert_allclose(np.abs(a.GetQuantumState()), np.abs(sa), atol=1e-8)
    np.testing.assert_allclose(np.abs(dest.GetQuantumState()), np.abs(sb), atol=1e-8)


def test_compose_mid_insertion():
    a = make_engine(2)
    a.X(0)  # |01>
    b = make_engine(1)
    b.H(0)
    a.Compose(b, 1)  # insert between q0 and old q1
    assert a.GetQubitCount() == 3
    # now q0=1 (old q0), q1=+ (inserted), q2=0 (old q1)
    assert a.Prob(0) == pytest.approx(1.0)
    assert a.Prob(1) == pytest.approx(0.5)
    assert a.Prob(2) == pytest.approx(0.0)


def test_dispose_and_allocate():
    q = make_engine(3)
    q.X(0)
    q.H(2)
    q.Dispose(1, 1)  # qubit 1 is |0>
    assert q.GetQubitCount() == 2
    assert q.Prob(0) == pytest.approx(1.0)
    assert q.Prob(1) == pytest.approx(0.5)
    q.Allocate(1, 2)
    assert q.GetQubitCount() == 4
    assert q.Prob(0) == pytest.approx(1.0)
    assert q.Prob(1) == pytest.approx(0.0)
    assert q.Prob(2) == pytest.approx(0.0)
    assert q.Prob(3) == pytest.approx(0.5)


def test_clone_and_compare():
    q = make_engine(3)
    q.H(0)
    q.CNOT(0, 1)
    c = q.Clone()
    assert q.ApproxCompare(c, 1e-6)
    c.X(2)
    assert not q.ApproxCompare(c, 1e-6)
    assert q.SumSqrDiff(c) > 0.5


def test_sum_sqr_diff_phase_invariant():
    # regression: identical states with different global phases compare equal
    a = QEngineCPU(2, rng=QrackRandom(1))  # rand_global_phase default True
    b = QEngineCPU(2, rng=QrackRandom(2))
    a.H(0); a.CNOT(0, 1)
    b.H(0); b.CNOT(0, 1)
    assert a.SumSqrDiff(b) < 1e-9
    assert a.ApproxCompare(b, 1e-6)


def test_hardware_entropy_source():
    """RDRAND instruction path (reference: rdrandwrapper.hpp NextRaw /
    SupportsRDRAND): real hardware draws when the CPU supports it, and
    the os.urandom fallback keeps unseeded streams working regardless."""
    from qrack_tpu.utils import rng as rngmod

    b1 = rngmod.hw_entropy_bytes(32)
    b2 = rngmod.hw_entropy_bytes(32)
    assert len(b1) == 32 and b1 != b2
    if rngmod.hw_rdrand_supported():
        draws = {rngmod.hw_rand64() for _ in range(8)}
        assert None not in draws and len(draws) == 8  # 64-bit draws never collide
    # unseeded streams remain constructible + distinct
    a, b = rngmod.QrackRandom(), rngmod.QrackRandom()
    assert a.rand() != b.rand()


def test_hwrng_native_opt_out(monkeypatch):
    """QRACK_TPU_NO_NATIVE disables the instruction path; entropy still
    flows through the os.urandom fallback (reference: rdrandwrapper's
    non-RDRAND fallback)."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, "-c",
         f"import sys; sys.path.insert(0, {repo!r})\n"
         "from qrack_tpu.utils import rng\n"
         "assert not rng.hw_rdrand_supported()\n"
         "assert rng.hw_rand64() is None\n"
         "b = rng.hw_entropy_bytes(16)\n"
         "assert len(b) == 16 and b != rng.hw_entropy_bytes(16)\n"
         "print('NO_NATIVE_OK')"],
        capture_output=True, text=True, timeout=120,
        env={k: v for k, v in __import__('os').environ.items()
             if k != 'PYTHONPATH'} | {"QRACK_TPU_NO_NATIVE": "1",
                                      "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    assert "NO_NATIVE_OK" in out.stdout
