"""QStabilizer tableau vs the dense oracle on random Clifford circuits.

Reference model: per-gate assertions + cross-engine equivalence
(test/tests.cpp stabilizer cases)."""

import numpy as np
import pytest

from qrack_tpu import QEngineCPU
from qrack_tpu.layers.stabilizer import QStabilizer, CliffordError, clifford_sequence
from qrack_tpu import matrices as mat
from qrack_tpu.utils.rng import QrackRandom


def assert_same_state(stab, dense, atol=1e-8):
    """Compare up to global phase."""
    a = stab.GetQuantumState()
    b = dense.GetQuantumState()
    fidelity = abs(np.vdot(a, b)) ** 2
    assert fidelity == pytest.approx(1.0, abs=atol), fidelity


def make_pair(n, seed=1):
    s = QStabilizer(n, rng=QrackRandom(seed))
    d = QEngineCPU(n, rng=QrackRandom(seed), rand_global_phase=False)
    return s, d


CLIFFORD_1Q = ["H", "X", "Y", "Z", "S", "IS", "SqrtX", "ISqrtX", "SqrtY", "ISqrtY"]


def random_clifford(q, rng, depth, n):
    for _ in range(depth):
        kind = rng.randint(0, 14)
        t = rng.randint(0, n)
        if kind < 10:
            getattr(q, CLIFFORD_1Q[kind])(t)
        else:
            c = rng.randint(0, n)
            if c == t:
                continue
            if kind == 10:
                q.CNOT(c, t)
            elif kind == 11:
                q.CZ(c, t)
            elif kind == 12:
                q.Swap(c, t)
            elif kind == 13:
                q.CY(c, t)


def test_clifford_sequence_covers_group():
    for name in CLIFFORD_1Q:
        m = {
            "H": mat.H2, "X": mat.X2, "Y": mat.Y2, "Z": mat.Z2,
            "S": mat.S2, "IS": mat.IS2, "SqrtX": mat.SQRTX2, "ISqrtX": mat.ISQRTX2,
            "SqrtY": mat.SQRTY2, "ISqrtY": mat.ISQRTY2,
        }[name]
        assert clifford_sequence(m) is not None, name
    assert clifford_sequence(mat.T2) is None
    assert clifford_sequence(mat.u3_mtrx(0.3, 0.1, 0.2)) is None


@pytest.mark.parametrize("seed", [1, 2, 3, 4])
def test_random_clifford_matches_dense(seed):
    n = 5
    s, d = make_pair(n, seed)
    random_clifford(s, QrackRandom(500 + seed), 60, n)
    random_clifford(d, QrackRandom(500 + seed), 60, n)
    assert_same_state(s, d)


def test_ghz_and_measurement():
    n = 4
    s, _ = make_pair(n)
    s.H(0)
    for i in range(n - 1):
        s.CNOT(i, i + 1)
    assert s.Prob(0) == 0.5
    assert s.Prob(3) == 0.5
    s.rng.seed(7)
    m0 = s.M(0)
    # all qubits now deterministic and equal
    for q in range(n):
        assert s.Prob(q) == (1.0 if m0 else 0.0)
    assert s.M(3) == m0


def test_force_m():
    s, _ = make_pair(2)
    s.H(0)
    s.CNOT(0, 1)
    s.ForceM(0, True)
    assert s.Prob(1) == 1.0
    with pytest.raises(RuntimeError):
        s.ForceM(1, False)


def test_measurement_statistics():
    ones = 0
    rng = QrackRandom(99)
    for _ in range(300):
        s = QStabilizer(1, rng=rng.spawn())
        s.H(0)
        if s.M(0):
            ones += 1
    assert 100 < ones < 200


def test_non_clifford_raises():
    s, _ = make_pair(2)
    with pytest.raises(CliffordError):
        s.T(0)
    with pytest.raises(CliffordError):
        s.MCMtrx((0,), mat.H2, 1)  # controlled-H is not Clifford
    with pytest.raises(CliffordError):
        s.CCNOT(0, 1, 1) if False else s.MCMtrxPerm((0, 1), mat.X2, 1, 3)


def test_anti_controlled():
    s, d = make_pair(2)
    s.AntiCNOT(0, 1)
    d.AntiCNOT(0, 1)
    assert_same_state(s, d)
    assert s.Prob(1) == 1.0  # control q0=0 -> target flipped


def test_compose_and_dispose():
    s1, _ = make_pair(2, seed=3)
    s1.H(0)
    s1.CNOT(0, 1)
    s2 = QStabilizer(1, rng=QrackRandom(4))
    s2.X(0)
    start = s1.Compose(s2)
    assert start == 2 and s1.GetQubitCount() == 3
    d = QEngineCPU(3, rng=QrackRandom(1), rand_global_phase=False)
    d.H(0)
    d.CNOT(0, 1)
    d.X(2)
    assert_same_state(s1, d)
    # dispose the measured qubit
    s1.ForceM(0, True)
    s1.Dispose(0, 1)
    assert s1.GetQubitCount() == 2
    assert s1.Prob(0) == 1.0  # old q1 followed q0 via CNOT
    assert s1.Prob(1) == 1.0  # old q2 was X'd


def test_separability_checks():
    s, _ = make_pair(2)
    s.H(0)
    assert s.IsSeparableX(0)
    assert not s.IsSeparableZ(0)
    s2 = QStabilizer(2, rng=QrackRandom(1))
    assert s2.IsSeparableZ(0)
    s2.H(0)
    s2.CNOT(0, 1)
    assert not s2.IsSeparableZ(0)
    assert not s2.IsSeparableX(0)
    s3 = QStabilizer(1, rng=QrackRandom(2))
    s3.H(0)
    s3.S(0)
    assert s3.IsSeparableY(0)


def test_set_quantum_state_synthesis():
    # random stabilizer kets round-trip through synthesis
    for seed in (1, 2, 3):
        n = 4
        d = QEngineCPU(n, rng=QrackRandom(seed), rand_global_phase=False)
        random_clifford(d, QrackRandom(700 + seed), 40, n)
        ket = d.GetQuantumState()
        s = QStabilizer(n, rng=QrackRandom(seed))
        s.SetQuantumState(ket)
        fid = abs(np.vdot(s.GetQuantumState(), ket)) ** 2
        assert fid == pytest.approx(1.0, abs=1e-8)


def test_sampling_through_default_api():
    s, _ = make_pair(3)
    s.H(0)
    s.CNOT(0, 1)
    s.CNOT(1, 2)
    shots = s.MultiShotMeasureMask([1, 2, 4], 300)
    assert set(shots.keys()) <= {0, 7}


def test_near_clifford_rotation_not_misrecognized():
    # regression: coarse key quantization must not match small rotations
    import math
    for th in (0.055, 0.1, 0.2, 0.753):
        c, s_ = math.cos(th), math.sin(th)
        m = np.array([[c, -s_], [s_, c]])
        assert clifford_sequence(m) is None, th


def test_get_state_does_not_corrupt_tableau():
    # regression: ket extraction must not alias/canonicalize the live rows
    for seed in (4, 5, 6):
        s, d = make_pair(4, seed)
        random_clifford(s, QrackRandom(1200 + seed), 40, 4)
        random_clifford(d, QrackRandom(1200 + seed), 40, 4)
        _ = s.GetQuantumState()
        _ = s.GetQuantumState()
        for q in range(4):
            assert s.Prob(q) == pytest.approx(d.Prob(q), abs=1e-9), (seed, q)
        assert_same_state(s, d)


def test_phase_offset_io_tracking():
    # basis-state phase survives SetQuantumState round-trips
    amp = (0.6 - 0.8j)
    s = QStabilizer(2, rng=QrackRandom(1))
    ket = np.zeros(4, dtype=np.complex128)
    ket[2] = amp
    s.SetQuantumState(ket)
    np.testing.assert_allclose(s.GetQuantumState(), ket, atol=1e-12)
    # superposed stabilizer ket with nontrivial global phase
    d = QEngineCPU(2, rng=QrackRandom(2), rand_global_phase=False)
    d.H(0)
    d.CNOT(0, 1)
    bell = d.GetQuantumState() * np.exp(0.7j)
    s2 = QStabilizer(2, rng=QrackRandom(3))
    s2.SetQuantumState(bell)
    np.testing.assert_allclose(s2.GetQuantumState(), bell, atol=1e-10)
    # Compose multiplies offsets
    s3 = QStabilizer(1, rng=QrackRandom(4))
    s3.SetQuantumState(np.array([0, 1j], dtype=np.complex128))
    s2.Compose(s3)
    expect = np.kron(np.array([0, 1j]), bell)
    np.testing.assert_allclose(s2.GetQuantumState(), expect, atol=1e-10)


def test_phase_offset_survives_decompose_dispose():
    # regression: split/dispose rebuilds must adopt the recomputed offset
    a_ket = np.array([1, 1j], dtype=np.complex128) / np.sqrt(2)
    b_ket = np.array([0, 1], dtype=np.complex128)
    full = np.kron(b_ket, a_ket) * np.exp(0.9j)
    s = QStabilizer(2, rng=QrackRandom(1))
    s.SetQuantumState(full)
    dest = QStabilizer(1, rng=QrackRandom(2))
    s.Decompose(1, dest)
    rebuilt = np.kron(dest.GetQuantumState(), s.GetQuantumState())
    np.testing.assert_allclose(rebuilt, full, atol=1e-10)
    # dispose path
    s2 = QStabilizer(2, rng=QrackRandom(3))
    s2.SetQuantumState(full)
    s2.ForceM(1, True)
    s2.Dispose(1, 1)
    np.testing.assert_allclose(s2.GetQuantumState(), a_ket * np.exp(0.9j), atol=1e-10)


# ---------------------------------------------------------------------------
# per-gate global-phase tracking (reference: per-gate phaseOffset updates,
# src/qstabilizer.cpp:944-1010): with rand_global_phase=False, amplitude
# streams must equal the dense oracle EXACTLY through long Clifford
# circuits, with no IO-boundary canonicalization allowed to paper over a
# dropped gate phase (e.g. Z on |1> contributes -1).
# ---------------------------------------------------------------------------


def test_pergate_phase_exact_parity_random_streams():
    import random

    random.seed(23)
    n = 5
    for trial in range(12):
        st = QStabilizer(n, rng=QrackRandom(70 + trial), rand_global_phase=False)
        d = QEngineCPU(n, rng=QrackRandom(70 + trial), rand_global_phase=False)
        for _ in range(50):
            g = random.choice(["H", "S", "IS", "X", "Y", "Z", "CNOT", "CZ", "Swap"])
            q = random.randrange(n)
            q2 = (q + 1 + random.randrange(n - 1)) % n
            for eng in (st, d):
                if g in ("CNOT", "CZ", "Swap"):
                    getattr(eng, g)(q, q2)
                else:
                    getattr(eng, g)(q)
        np.testing.assert_allclose(
            st.GetQuantumState(), d.GetQuantumState(), atol=1e-10)


def test_pergate_phase_simple_identities():
    # Z|1> = -|1>, S|1> = i|1>, Y|0> = i|1>: pure global phases the
    # tableau cannot represent — phase_offset must carry them per gate
    st = QStabilizer(1, rng=QrackRandom(3), rand_global_phase=False)
    st.X(0)
    st.Z(0)
    np.testing.assert_allclose(st.GetQuantumState(), [0, -1], atol=1e-12)
    st.S(0)
    np.testing.assert_allclose(st.GetQuantumState(), [0, -1j], atol=1e-12)
    st2 = QStabilizer(1, rng=QrackRandom(3), rand_global_phase=False)
    st2.Y(0)
    np.testing.assert_allclose(st2.GetQuantumState(), [0, 1j], atol=1e-12)


def test_pergate_phase_through_forced_measurement():
    # collapse keeps surviving amplitudes' phases (up to +renorm)
    st = QStabilizer(2, rng=QrackRandom(5), rand_global_phase=False)
    d = QEngineCPU(2, rng=QrackRandom(5), rand_global_phase=False)
    for eng in (st, d):
        eng.H(0)
        eng.S(0)
        eng.CNOT(0, 1)
        eng.Z(1)
        eng.ForceM(0, True)
    np.testing.assert_allclose(st.GetQuantumState(), d.GetQuantumState(), atol=1e-10)


def test_pergate_phase_permute_qubits():
    st = QStabilizer(3, rng=QrackRandom(8), rand_global_phase=False)
    d = QEngineCPU(3, rng=QrackRandom(8), rand_global_phase=False)
    for eng in (st, d):
        eng.H(0)
        eng.S(0)
        eng.CNOT(0, 2)
        eng.Y(1)
    st.PermuteQubits([2, 0, 1])
    # oracle: same relabeling via swaps
    d.Swap(0, 2)  # now old2,old1,old0
    d.Swap(1, 2)  # -> old2, old0, old1
    np.testing.assert_allclose(st.GetQuantumState(), d.GetQuantumState(), atol=1e-10)


def test_clifford_controlled_monomials():
    # phased controlled monomials (Z_c·CZ, C(iX), anti-controlled forms)
    # are Clifford and must match the oracle exactly
    cases = [
        (np.diag([-1, 1]), 1),                       # Z_c · CZ
        (np.diag([1j, -1j]), 1),                     # S_c · CZ
        (np.array([[0, 1j], [1j, 0]]), 1),           # C(iX) = S_c · CX
        (np.array([[0, -1j], [1j, 0]]), 1),          # CY
        (np.diag([1, -1]), 0),                       # anti-CZ
        (np.array([[0, -1], [1, 0]]), 0),            # anti-C(-iY)
    ]
    for m, perm in cases:
        st = QStabilizer(2, rng=QrackRandom(4), rand_global_phase=False)
        d = QEngineCPU(2, rng=QrackRandom(4), rand_global_phase=False)
        for eng in (st, d):
            eng.H(0)
            eng.H(1)
            eng.S(1)
            eng.MCMtrxPerm((0,), m, 1, perm)
        np.testing.assert_allclose(
            st.GetQuantumState(), d.GetQuantumState(), atol=1e-10,
            err_msg=f"{m.tolist()} perm={perm}")


def test_layer_stacks_exact_phase_parity():
    # QStabilizerHybrid and QUnitClifford must inherit per-gate phase
    # exactness (inner tableaus receive rand_global_phase)
    import random

    from qrack_tpu.layers.stabilizerhybrid import QStabilizerHybrid
    from qrack_tpu.layers.qunitclifford import QUnitClifford

    random.seed(97)
    for trial in range(4):
        engs = [QEngineCPU(4, rng=QrackRandom(300 + trial), rand_global_phase=False),
                QStabilizerHybrid(4, rng=QrackRandom(300 + trial), rand_global_phase=False),
                QUnitClifford(4, rng=QrackRandom(300 + trial), rand_global_phase=False)]
        for _ in range(30):
            g = random.choice(["H", "S", "X", "Z", "Y", "CNOT", "CZ", "Swap"])
            q = random.randrange(4)
            q2 = (q + 1 + random.randrange(3)) % 4
            for e in engs:
                if g in ("CNOT", "CZ", "Swap"):
                    getattr(e, g)(q, q2)
                else:
                    getattr(e, g)(q)
        a = engs[0].GetQuantumState()
        for e in engs[1:]:
            np.testing.assert_allclose(e.GetQuantumState(), a, atol=1e-8,
                                       err_msg=f"{trial} {type(e).__name__}")


def test_dispose_z_native_parity_and_wide():
    """Tableau-native DisposeZ: exact amplitude parity vs the dense
    oracle after forced collapse, and works far past the old 20-qubit
    ket-projection cap (closes 'wide tableau disposal pending')."""
    rng = np.random.Generator(np.random.PCG64(5))
    gates = ["H", "S", "X", "Y", "Z", "CNOT", "CZ"]
    for trial in range(25):
        n = int(rng.integers(2, 7))
        st = QStabilizer(n, rng=QrackRandom(trial), rand_global_phase=False)
        o = QEngineCPU(n, rng=QrackRandom(trial), rand_global_phase=False)
        for _ in range(int(rng.integers(5, 25))):
            g = gates[int(rng.integers(0, len(gates)))]
            if g in ("CNOT", "CZ"):
                a, b = rng.choice(n, 2, replace=False)
                getattr(st, g)(int(a), int(b))
                getattr(o, g)(int(a), int(b))
            else:
                q = int(rng.integers(0, n))
                getattr(st, g)(q)
                getattr(o, g)(q)
        q = int(rng.integers(0, n))
        st.rng = o.rng = QrackRandom(999 + trial)
        r = st.ForceM(q, False, do_force=False)
        o.ForceM(q, r, do_force=True)
        assert st.DisposeZ(q) == r
        o.Dispose(q, 1, int(r))
        np.testing.assert_allclose(
            st.GetQuantumState(), o.GetQuantumState(), atol=1e-7)

    st = QStabilizer(40, rng=QrackRandom(1))
    for i in range(39):
        st.CNOT(i, i + 1)
    st.H(0)
    st.ForceM(20, False, do_force=False)
    st.DisposeZ(20)
    assert st.qubit_count == 39


def test_dispose_xy_basis_any_width():
    """Dispose of X/Y-eigenstate qubits rotates to Z in-tableau — no
    measurement detour, exact amplitudes, and width-generic."""
    # |+> and |i> qubits interleaved with an entangled pair
    st = QStabilizer(4, rng=QrackRandom(3), rand_global_phase=False)
    o = QEngineCPU(4, rng=QrackRandom(3), rand_global_phase=False)
    for eng in (st, o):
        eng.H(1)                 # |+> on q1
        eng.H(2); eng.S(2)       # |i> on q2
        eng.H(0); eng.CNOT(0, 3) # Bell pair on (q0, q3)
    st.Dispose(1, 2)
    o.Dispose(1, 2, 0)           # oracle needs the separable-perm hint
    assert st.qubit_count == 2
    f = abs(np.vdot(st.GetQuantumState(), o.GetQuantumState()))
    np.testing.assert_allclose(f, 1.0, atol=1e-7)

    # wide: 40 qubits, dispose an X-basis qubit inside a cluster chain
    w = QStabilizer(40, rng=QrackRandom(2))
    for i in range(38):
        w.CNOT(i, i + 1)
    w.H(39)
    w.Dispose(39, 1)
    assert w.qubit_count == 39

    # a span entangled WITHIN itself (Bell pair fully inside the span,
    # separable from the remainder) still refuses — the carved-out case
    e = QStabilizer(3, rng=QrackRandom(4))
    e.H(0); e.CNOT(0, 1)
    with pytest.raises(NotImplementedError):
        e.Dispose(0, 2)
    # and a qubit entangled with the outside refuses too
    with pytest.raises(NotImplementedError):
        e.Dispose(0, 1)


def test_product_span_decompose_any_width():
    """Width-generic Decompose of single-basis-separable spans: exact
    rem (x) dest == original reconstruction, X/Y bases included, and a
    40-qubit case that the old 2^n ket projection could never run."""
    rng = np.random.Generator(np.random.PCG64(3))
    gates = ["H", "S", "X", "Y", "Z", "CNOT", "CZ"]
    for trial in range(15):
        n = int(rng.integers(3, 7))
        st = QStabilizer(n, rng=QrackRandom(trial), rand_global_phase=False)
        for _ in range(int(rng.integers(5, 20))):
            g = gates[int(rng.integers(0, len(gates)))]
            if g in ("CNOT", "CZ"):
                a, b = rng.choice(n, 2, replace=False)
                getattr(st, g)(int(a), int(b))
            else:
                getattr(st, g)(int(rng.integers(0, n)))
        start = int(rng.integers(0, n - 1))
        length = int(rng.integers(1, min(3, n - start) + 1))
        for q in range(start, start + length):
            st.ForceM(q, False, do_force=False)
        full = st.GetQuantumState()
        dest = QStabilizer(length, rng=QrackRandom(500 + trial),
                           rand_global_phase=False)
        st.Decompose(start, dest)
        rem = st.GetQuantumState()
        dv = dest.GetQuantumState()
        rebuilt = np.zeros(1 << n, complex)
        for i in range(1 << (n - length)):
            lo = i & ((1 << start) - 1)
            hi = i >> start
            for j in range(1 << length):
                idx = lo | (j << start) | (hi << (start + length))
                rebuilt[idx] = rem[i] * dv[j]
        np.testing.assert_allclose(rebuilt, full, atol=1e-9)

    # X/Y-separable span, reconstruction-verified (no measurement)
    st = QStabilizer(4, rng=QrackRandom(21), rand_global_phase=False)
    st.H(1)             # X eigenstate |+>
    st.X(2)
    st.H(2)
    st.S(2)             # Y eigenstate |y->
    st.H(0)
    st.CNOT(0, 3)       # entangled REST around the span
    st.S(0)
    full = st.GetQuantumState()
    dest = QStabilizer(2, rng=QrackRandom(22), rand_global_phase=False)
    st.Decompose(1, dest)
    rem = st.GetQuantumState()
    dv = dest.GetQuantumState()
    rebuilt = np.zeros(16, complex)
    for i in range(4):
        lo, hi = i & 1, i >> 1
        for j in range(4):
            rebuilt[lo | (j << 1) | (hi << 3)] = rem[i] * dv[j]
    np.testing.assert_allclose(rebuilt, full, atol=1e-9)

    st = QStabilizer(40, rng=QrackRandom(9))
    st.H(10)
    st.H(11)
    st.S(11)
    dest = QStabilizer(2, rng=QrackRandom(3))
    st.Decompose(10, dest)
    assert st.qubit_count == 38 and dest.qubit_count == 2


def test_entangled_span_decompose_symplectic():
    """Decompose of spans entangled WITHIN themselves (but separable
    from the rest), via generator splitting + symplectic Gram-Schmidt —
    exact amplitudes incl. global phase, and width-generic."""
    rng = np.random.Generator(np.random.PCG64(11))
    gates = ["H", "S", "X", "Y", "Z", "CNOT", "CZ"]
    done = 0
    for trial in range(60):
        n = int(rng.integers(4, 8))
        start = int(rng.integers(0, n - 2))
        length = int(rng.integers(2, min(3, n - start - 1) + 1))
        span = set(range(start, start + length))
        rest = [q for q in range(n) if q not in span]
        st = QStabilizer(n, rng=QrackRandom(trial), rand_global_phase=False)
        # random Clifford WITHIN the span and WITHIN the rest (never
        # across), so the cut is separable but the span is entangled
        for _ in range(int(rng.integers(8, 25))):
            grp = sorted(span) if rng.integers(0, 2) else rest
            g = gates[int(rng.integers(0, len(gates)))]
            if g in ("CNOT", "CZ"):
                if len(grp) < 2:
                    g = "H"
                else:
                    a, b = rng.choice(len(grp), 2, replace=False)
                    getattr(st, g)(grp[int(a)], grp[int(b)])
                    continue
            getattr(st, g)(grp[int(rng.integers(0, len(grp)))])
        # ensure the span really is internally entangled some trials
        full = st.GetQuantumState()
        dest = QStabilizer(length, rng=QrackRandom(900 + trial),
                           rand_global_phase=False)
        st.Decompose(start, dest)
        rem = st.GetQuantumState()
        dv = dest.GetQuantumState()
        rebuilt = np.zeros(1 << n, complex)
        for i in range(1 << (n - length)):
            lo = i & ((1 << start) - 1)
            hi = i >> start
            for j in range(1 << length):
                rebuilt[lo | (j << start) | (hi << (start + length))] = \
                    rem[i] * dv[j]
        np.testing.assert_allclose(rebuilt, full, atol=1e-9)
        done += 1
    assert done == 60

    # width-generic: a 40-qubit register with an entangled GHZ-like
    # cluster inside the span — the old path would need a 2^40 ket
    st = QStabilizer(40, rng=QrackRandom(5))
    st.H(20)
    st.CNOT(20, 21)
    st.CNOT(21, 22)     # GHZ on 20..22, separable from everything else
    st.H(0)
    st.CNOT(0, 39)      # entangled pair OUTSIDE the span
    dest = QStabilizer(3, rng=QrackRandom(6))
    st.Decompose(20, dest)
    assert st.qubit_count == 37 and dest.qubit_count == 3
    dv = dest.GetQuantumState()
    np.testing.assert_allclose(abs(dv[0]), abs(dv[7]), atol=1e-9)
    assert abs(dv[0]) > 0.6   # GHZ: weight on |000> and |111>

    # truly cross-cut entanglement must still refuse wide
    st2 = QStabilizer(30, rng=QrackRandom(8))
    st2.H(4)
    st2.CNOT(4, 10)
    dest2 = QStabilizer(2, rng=QrackRandom(9))
    with pytest.raises(NotImplementedError):
        st2.Decompose(4, dest2)


def test_full_width_decompose():
    """Decompose with dest.qubit_count == qubit_count (empty remainder):
    regression — the generator-splitting path used to build a float64
    empty index array and raise IndexError (ADVICE r3)."""
    st = QStabilizer(3, rng=QrackRandom(1), rand_global_phase=False)
    st.H(0)
    st.CNOT(0, 1)
    st.CNOT(1, 2)
    full = st.GetQuantumState()
    dest = QStabilizer(3, rng=QrackRandom(2), rand_global_phase=False)
    st.Decompose(0, dest)
    assert st.qubit_count == 0 and dest.qubit_count == 3
    rem = st.GetQuantumState()          # scalar amplitude of the empty register
    np.testing.assert_allclose(rem, [1.0 + 0.0j], atol=1e-9)
    np.testing.assert_allclose(dest.GetQuantumState(), full, atol=1e-9)

    # width-generic: >20 qubits forces the generator path (no ket fallback)
    st = QStabilizer(25, rng=QrackRandom(3))
    st.H(0)
    st.CNOT(0, 24)
    dest = QStabilizer(25, rng=QrackRandom(4))
    st.Decompose(0, dest)
    assert st.qubit_count == 0 and dest.qubit_count == 25
    # the Bell pair must survive the transfer: perfectly correlated,
    # each marginal unbiased
    assert abs(dest.Prob(0) - 0.5) < 1e-9
    assert abs(dest.Prob(24) - 0.5) < 1e-9
    m = dest.M(0)
    assert dest.M(24) == m
