"""Seeded API fuzzer: random interleavings of the whole public op
vocabulary (gates, rotations, registers, ALU, swaps, parity, measures)
on the oracle vs the optimal layer stack, asserting state parity after
every trial.  The conformance battery runs fixed circuits per engine;
this hunts interaction bugs between op families and the QUnit shard /
fusion machinery (reference analogue: the randomized sections of
test/tests.cpp).
"""

import numpy as np
import pytest

from qrack_tpu import QEngineCPU, create_quantum_interface
from qrack_tpu.utils.rng import QrackRandom

N = 6


def _ops(rng):
    """One random op as (name, args) applied identically to both."""
    q = lambda: int(rng.integers(0, N))
    ang = lambda: float(rng.uniform(0, 2 * np.pi))

    def two():
        a = q()
        b = (a + 1 + int(rng.integers(0, N - 1))) % N
        return a, b

    def reg():
        start = int(rng.integers(0, N - 1))
        length = int(rng.integers(1, N - start + 1))
        return start, min(length, N - start)

    choices = []
    for g in ("H", "X", "Y", "Z", "S", "T"):
        choices.append((g, lambda g=g: (g, (q(),))))
    for g in ("RX", "RY", "RZ"):
        choices.append((g, lambda g=g: (g, (ang(), q()))))
    for g in ("CNOT", "CZ", "Swap", "ISwap"):
        choices.append((g, lambda g=g: (g, two())))
    choices.append(("CCNOT", lambda: ("CCNOT", (0, 1, 2 + q() % (N - 2)))))
    choices.append(("INC", lambda: ("INC", (int(rng.integers(0, 8)),) + reg())))
    choices.append(("ROL", lambda: ("ROL", (int(rng.integers(0, 3)),) + reg())))
    choices.append(("XMask", lambda: ("XMask", (int(rng.integers(1, 1 << N)),))))
    choices.append(("ZMask", lambda: ("ZMask", (int(rng.integers(1, 1 << N)),))))
    choices.append(("PhaseFlipIfLess",
                    lambda: ("PhaseFlipIfLess",
                             (int(rng.integers(1, 4)),) + reg())))
    choices.append(("SetBit", lambda: ("SetBit", (q(), bool(rng.integers(0, 2))))))
    name, make = choices[int(rng.integers(0, len(choices)))]
    return make()


@pytest.mark.parametrize("trial", range(12))
def test_random_api_interleavings_match_oracle(trial):
    rng = np.random.Generator(np.random.PCG64(1000 + trial))
    o = QEngineCPU(N, rng=QrackRandom(trial), rand_global_phase=False)
    s = create_quantum_interface("optimal", N, rng=QrackRandom(trial),
                                 rand_global_phase=False)
    for step in range(30):
        name, args = _ops(rng)
        getattr(o, name)(*args)
        getattr(s, name)(*args)
        if rng.integers(0, 10) == 0:     # occasional mid-stream reads
            qb = int(rng.integers(0, N))
            assert abs(o.Prob(qb) - s.Prob(qb)) < 3e-5, (trial, step, name)
    a = np.asarray(o.GetQuantumState())
    b = np.asarray(s.GetQuantumState())
    f = abs(np.vdot(a, b)) ** 2
    assert f > 1 - 1e-6, (trial, f)
    # and a forced measurement keeps both in the same collapsed state
    o.rng = s.rng = QrackRandom(5000 + trial)
    qb = trial % N
    r = o.M(qb)
    assert s.ForceM(qb, r) == r
    f = abs(np.vdot(np.asarray(o.GetQuantumState()),
                    np.asarray(s.GetQuantumState()))) ** 2
    assert f > 1 - 1e-6, (trial, f)


# the same fuzz vocabulary over the round-5 stacks: the sharded
# compressed ket (lossy — fidelity floor scaled to 16-bit codes) and
# the attached-leaf tree (exact)
_R5_STACKS = [
    ("turboquant_pager", {"bits": 16, "chunk_qb": 3, "block_pow": 2},
     1 - 1e-5),
    ("bdt_attached", {"attached_qubits": 3}, 1 - 1e-6),
]


@pytest.mark.parametrize("name,kw,floor",
                         _R5_STACKS, ids=[s[0] for s in _R5_STACKS])
@pytest.mark.parametrize("trial", range(4))
def test_fuzz_round5_stacks(name, kw, floor, trial):
    rng = np.random.Generator(np.random.PCG64(2000 + trial))
    o = QEngineCPU(N, rng=QrackRandom(trial), rand_global_phase=False)
    s = create_quantum_interface(name, N, rng=QrackRandom(trial),
                                 rand_global_phase=False, **kw)
    for step in range(25):
        op, args = _ops(rng)
        getattr(o, op)(*args)
        getattr(s, op)(*args)
        if rng.integers(0, 10) == 0:
            qb = int(rng.integers(0, N))
            assert abs(o.Prob(qb) - s.Prob(qb)) < 5e-4, (trial, step, op)
    a = np.asarray(o.GetQuantumState())
    b = np.asarray(s.GetQuantumState())
    f = abs(np.vdot(a, b)) ** 2 / (np.vdot(a, a).real * np.vdot(b, b).real)
    assert f > floor, (trial, f)


# deterministic basis-permutation ALU fuzz: every op here maps basis
# states to basis states, so the expected index is tracked bit-exactly
# with Python ints — a distinct angle from the amplitude-level fuzz
# (this is the in-tree slice of the round-5 soak that validated the
# closed-form index-gather ALU kernels across stacks)

def _perm_model(val, name, args, n):
    if name in ("INC", "DEC"):
        a, s, l = args
        reg = (val >> s) & ((1 << l) - 1)
        reg = (reg + (a if name == "INC" else -a)) & ((1 << l) - 1)
        return (val & ~(((1 << l) - 1) << s)) | (reg << s)
    if name in ("ROL", "ROR"):
        a, s, l = args
        reg = (val >> s) & ((1 << l) - 1)
        sh = (a % l) if l else 0
        if name == "ROR":
            sh = (l - sh) % l if l else 0
        if l:
            reg = ((reg << sh) | (reg >> (l - sh))) & ((1 << l) - 1)
        return (val & ~(((1 << l) - 1) << s)) | (reg << s)
    if name == "XMask":
        return val ^ args[0]
    if name == "Swap":
        a, b = args
        ba, bb = (val >> a) & 1, (val >> b) & 1
        val &= ~((1 << a) | (1 << b))
        return val | (ba << b) | (bb << a)
    if name == "CNOT":
        c, t = args
        return val ^ (1 << t) if (val >> c) & 1 else val
    raise KeyError(name)


def _perm_op(rng, n):
    kind = int(rng.integers(0, 6))
    if kind < 2:
        s = int(rng.integers(0, n - 1))
        l = int(rng.integers(1, n - s + 1))
        return ("INC" if kind == 0 else "DEC",
                (int(rng.integers(0, 16)), s, l))
    if kind == 2:
        s = int(rng.integers(0, n - 1))
        l = int(rng.integers(1, n - s + 1))
        return ("ROL" if rng.integers(0, 2) else "ROR",
                (int(rng.integers(0, 5)), s, l))
    if kind == 3:
        return ("XMask", (int(rng.integers(1, 1 << n)),))
    a = int(rng.integers(0, n))
    b = (a + 1 + int(rng.integers(0, n - 1))) % n
    return ("Swap", (a, b)) if kind == 4 else ("CNOT", (a, b))


@pytest.mark.parametrize("trial", range(6))
def test_alu_permutation_fuzz(trial):
    rng = np.random.Generator(np.random.PCG64(40000 + trial))
    val = int(rng.integers(0, 1 << N))
    stacks = [
        QEngineCPU(N, rng=QrackRandom(trial), rand_global_phase=False),
        create_quantum_interface("optimal", N, rng=QrackRandom(trial),
                                 rand_global_phase=False),
        create_quantum_interface("turboquant_pager", N, bits=16,
                                 chunk_qb=3, block_pow=2,
                                 rng=QrackRandom(trial),
                                 rand_global_phase=False),
    ]
    for e in stacks:
        e.SetPermutation(val)
    for step in range(20):
        name, args = _perm_op(rng, N)
        val = _perm_model(val, name, args, N)
        for e in stacks:
            getattr(e, name)(*args)
    for e in stacks:
        assert abs(abs(complex(e.GetAmplitude(val))) - 1.0) < 1e-3, \
            (trial, type(e).__name__, val)
