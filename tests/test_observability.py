"""Fleet observability plane, process-local half (docs/OBSERVABILITY.md):
log-bucket latency histograms + SLO gauges, the drop-oldest event ring,
distributed-trace context, the merged Perfetto exporter, the crash
flight recorder, snapshot merging, and the telemetry-name docs lint.

The cross-process half (heartbeat aggregation, postmortem collection,
merged fleet traces from real subprocess workers) lives in
tests/test_fleet.py."""

import importlib.util
import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from qrack_tpu import telemetry as tele
from qrack_tpu.telemetry import Histogram

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_telemetry():
    tele.disable()
    tele.reset()
    yield
    tele.disable()
    tele.reset()


# ---------------------------------------------------------------------------
# log-bucket histogram
# ---------------------------------------------------------------------------

def test_histogram_percentiles_within_slo_bar():
    """8 subbuckets/octave bounds midpoint error at 2^(1/16)-1 ~ 4.4%;
    the acceptance bar is 10% vs exact percentiles."""
    rng = np.random.default_rng(7)
    vals = np.exp(rng.normal(-5.0, 1.5, size=2000))  # lognormal walls
    h = Histogram.of(vals.tolist())
    assert h.count == 2000
    for q in (50, 90, 95, 99):
        exact = float(np.percentile(vals, q))
        got = h.percentile(q)
        assert abs(got - exact) / exact < 0.10, (q, got, exact)


def test_histogram_single_sample_is_exact():
    h = Histogram.of([0.0123])
    # clamped into [min, max]: a 1-sample histogram reports the sample,
    # not a bucket midpoint
    assert h.percentile(50) == pytest.approx(0.0123)
    assert h.percentile(99) == pytest.approx(0.0123)
    assert h.mean == pytest.approx(0.0123)


def test_histogram_merge_equals_combined():
    a = [0.001 * (i + 1) for i in range(100)]
    b = [0.5] * 50
    ha, hb, hall = Histogram.of(a), Histogram.of(b), Histogram.of(a + b)
    ha.merge(hb.to_dict())
    assert ha.count == hall.count
    assert ha.sum == pytest.approx(hall.sum)
    for q in (50, 95, 99):
        assert ha.percentile(q) == pytest.approx(hall.percentile(q))


def test_histogram_dict_round_trip_and_merge_all():
    h = Histogram.of([0.01, 0.1, 1.0, 10.0])
    d = json.loads(json.dumps(h.to_dict()))  # JSONL-safe
    h2 = Histogram.from_dict(d)
    assert h2.count == 4 and h2.min == h.min and h2.max == h.max
    assert h2.percentile(50) == pytest.approx(h.percentile(50))
    m = Histogram.merge_all([d, d])
    assert m.count == 8
    assert m.percentile(50) == pytest.approx(h.percentile(50))


def test_histogram_degenerate_values_no_crash():
    h = Histogram()
    assert h.percentile(50) is None
    h.record(0.0)       # clamps to the tiny-value floor bucket
    h.record(-1.0)
    h.record(1e30)      # clamps to the top (2^30) bucket
    assert h.count == 3
    # extremes land in the clamp buckets: ordering survives even though
    # magnitudes beyond the +-2^30s index range lose accuracy by design
    assert h.percentile(99) >= 2.0 ** 30
    assert h.percentile(1) < 1e-8
    assert h.max == 1e30 and h.min == -1.0


# ---------------------------------------------------------------------------
# event ring: drop-OLDEST (the satellite regression)
# ---------------------------------------------------------------------------

def test_event_ring_drops_oldest_not_newest(monkeypatch):
    """The old ring kept the FIRST cap events and dropped everything
    after — a postmortem of a long-lived worker would show its boot
    transcript.  The contract is the reverse: the newest events always
    survive."""
    monkeypatch.setattr(tele, "_EVENT_CAP", 8)
    tele.reset()  # rebind the ring at the patched cap
    tele.enable()
    for i in range(11):
        tele.event("ring.probe", i=i)
    snap = tele.snapshot()
    got = [e["i"] for e in snap["events"] if e["name"] == "ring.probe"]
    assert got == list(range(3, 11))          # event N+cap present ...
    assert 0 not in got                       # ... event 0 evicted
    assert snap["counters"]["telemetry.events.dropped"] == 3
    assert snap["counters"]["ring.probe"] == 11  # counter unaffected


# ---------------------------------------------------------------------------
# observe -> histogram + SLO gauges
# ---------------------------------------------------------------------------

def test_observe_feeds_histogram_and_publishes_slo_gauges():
    tele.enable()
    for v in [0.01] * 50 + [0.1] * 45 + [1.0] * 5:
        tele.observe("serve.latency", v)
    assert tele.percentile("serve.latency", 50) == pytest.approx(
        0.01, rel=0.05)
    snap = tele.snapshot()
    assert snap["hists"]["serve.latency"]["count"] == 100
    g = snap["gauges"]
    assert g["serve.latency.p50"] == pytest.approx(0.01, rel=0.05)
    assert g["serve.latency.p95"] == pytest.approx(0.1, rel=0.05)
    assert g["serve.latency.p99"] == pytest.approx(1.0, rel=0.05)
    # span-style aggregate still fed alongside
    assert snap["spans"]["serve.latency"]["count"] == 100


def test_histogram_name_cap_overflow_counted(monkeypatch):
    monkeypatch.setattr(tele, "_HIST_CAP", 2)
    tele.reset()
    tele.enable()
    tele.observe("cap.a", 0.1)
    tele.observe("cap.b", 0.1)
    tele.observe("cap.c", 0.1)  # beyond cap: span aggregate only
    snap = tele.snapshot()
    assert set(snap["hists"]) == {"cap.a", "cap.b"}
    assert tele.percentile("cap.c", 50) is None
    assert snap["spans"]["cap.c"]["count"] == 1
    assert snap["counters"]["telemetry.hists.dropped"] == 1


# ---------------------------------------------------------------------------
# distributed trace context
# ---------------------------------------------------------------------------

def test_trace_context_attaches_to_spans_and_events():
    tele.enable()
    assert tele.current_trace() is None
    prev = tele.set_trace("tag-123")
    assert prev is None and tele.current_trace() == "tag-123"
    with tele.span("traced.work"):
        pass
    tele.event("traced.mark")
    assert tele.set_trace(None) == "tag-123"
    with tele.span("untraced.work"):
        pass
    src = tele.local_trace_source("me")
    by_name = {s["name"]: s for s in src["spans"]}
    assert by_name["traced.work"]["trace"] == "tag-123"
    assert "trace" not in by_name["untraced.work"]
    ev = [e for e in src["events"] if e["name"] == "traced.mark"]
    assert ev and ev[0]["trace"] == "tag-123"
    assert src["pid"] == os.getpid()
    assert isinstance(src["epoch_unix_s"], float)


def test_record_span_emits_exact_interval_with_trace():
    """record_span() appends a caller-measured interval verbatim: the
    executor uses it to put each job's t_submit->t_done window on the
    trace ring, so the merged timeline carries raw serve latencies."""
    import time as _time

    tele.enable()
    t0 = _time.perf_counter() - 0.5
    tele.record_span("recorded.work", t0, 0.125, trace="job-9")
    tele.record_span("recorded.work", t0, 0.25)  # no thread trace set
    spans = [s for s in tele.local_trace_source()["spans"]
             if s["name"] == "recorded.work"]
    assert len(spans) == 2
    assert spans[0]["dur_s"] == 0.125 and spans[0]["trace"] == "job-9"
    assert spans[1]["dur_s"] == 0.25 and "trace" not in spans[1]
    # aggregates fold in like any other span
    agg = tele.snapshot()["spans"]["recorded.work"]
    assert agg["count"] == 2 and agg["max_s"] == 0.25
    tele.disable()
    tele.record_span("recorded.off", t0, 1.0)  # disabled: no-op
    tele.enable()
    assert all(s["name"] != "recorded.off"
               for s in tele.local_trace_source()["spans"])


def test_explicit_span_trace_wins_over_thread_local():
    """The executor runs jobs on its own thread: the span must carry
    the JOB's trace (pinned explicitly), not the dispatch thread's."""
    tele.enable()
    tele.set_trace("thread-tag")
    try:
        with tele.span("pinned.work", trace="job-tag"):
            pass
    finally:
        tele.set_trace(None)
    spans = tele.local_trace_source()["spans"]
    assert spans[-1]["trace"] == "job-tag"


def test_trace_context_is_thread_local():
    tele.enable()
    tele.set_trace("main-tag")
    seen = {}

    def other():
        seen["before"] = tele.current_trace()
        tele.set_trace("other-tag")
        tele.event("other.mark")

    t = threading.Thread(target=other)
    t.start()
    t.join()
    try:
        assert seen["before"] is None          # not inherited
        assert tele.current_trace() == "main-tag"  # not clobbered
    finally:
        tele.set_trace(None)


# ---------------------------------------------------------------------------
# multi-thread stress: no lost updates (satellite d)
# ---------------------------------------------------------------------------

def test_concurrent_inc_observe_span_no_lost_updates():
    tele.enable()
    n, n_threads = 2000, 8
    barrier = threading.Barrier(n_threads)

    def work(k):
        barrier.wait()
        for i in range(n):
            tele.inc("stress.count")
            tele.observe("stress.lat", 0.001 * ((i % 10) + 1))
            with tele.span(f"stress.span.{k}"):
                pass

    threads = [threading.Thread(target=work, args=(k,))
               for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    snap = tele.snapshot(include_events=False)
    assert snap["counters"]["stress.count"] == n * n_threads
    hist = snap["hists"]["stress.lat"]
    assert hist["count"] == n * n_threads
    assert sum(hist["buckets"].values()) == n * n_threads
    assert snap["spans"]["stress.lat"]["count"] == n * n_threads
    for k in range(n_threads):
        assert snap["spans"][f"stress.span.{k}"]["count"] == n


# ---------------------------------------------------------------------------
# merged Perfetto exporter
# ---------------------------------------------------------------------------

def _src(name, pid, epoch, spans, events=()):
    return {"name": name, "pid": pid, "epoch_unix_s": epoch,
            "spans": list(spans), "events": list(events)}


def test_merged_trace_one_track_per_incarnation_despite_pid_reuse():
    sp = {"dur_s": 0.5, "tid": 1, "depth": 0, "synced": False,
          "trace": "tag1"}
    s1 = _src("frontdoor", 500, 1000.0,
              [{"name": "frontdoor.apply", "ts_s": 1.0, **sp}],
              [{"name": "fleet.worker.dead", "t_s": 1.2, "trace": "tag1"}])
    # same OS pid (reuse after restart) but a separate incarnation:
    s2 = _src("w0", 500, 1000.6,
              [{"name": "serve.execute", "ts_s": 0.5, "dur_s": 0.2,
                "tid": 9, "depth": 0, "synced": False, "trace": "tag1"}])
    obj = tele.merged_chrome_trace([s1, s2])
    evs = obj["traceEvents"]
    xs = {e["name"]: e for e in evs if e["ph"] == "X"}
    # one display track per SOURCE, not per OS pid
    assert xs["frontdoor.apply"]["pid"] != xs["serve.execute"]["pid"]
    meta = [e for e in evs if e["ph"] == "M"
            and e["name"] == "process_name"]
    labels = {m["args"]["name"] for m in meta}
    assert any("frontdoor" in x for x in labels)
    assert any("w0" in x for x in labels)
    # wall-clock re-anchor: fd span at 1000+1.0=1001.0 is the fleet t0;
    # the worker span at 1000.6+0.5=1001.1 lands 100ms later
    assert xs["frontdoor.apply"]["ts"] == pytest.approx(0.0, abs=1e-6)
    assert xs["serve.execute"]["ts"] == pytest.approx(0.1e6, rel=1e-6)
    # the trace id survives into args on spans AND instants
    assert xs["frontdoor.apply"]["args"]["trace"] == "tag1"
    inst = [e for e in evs if e["ph"] == "i"]
    assert inst and inst[0]["args"]["trace"] == "tag1"


def test_write_merged_chrome_trace_is_loadable_json(tmp_path):
    tele.enable()
    with tele.span("merged.local"):
        pass
    path = tmp_path / "fleet_trace.json"
    tele.write_merged_chrome_trace(
        str(path), [tele.local_trace_source("fd")])
    obj = json.loads(path.read_text())
    assert any(e.get("name") == "merged.local"
               for e in obj["traceEvents"])


# ---------------------------------------------------------------------------
# crash flight recorder
# ---------------------------------------------------------------------------

def test_flight_recorder_round_trip_and_tail(tmp_path):
    path = tmp_path / "blackbox" / "w0-123.json"
    rec = tele.FlightRecorder(str(path), name="w0", last_n=4)
    assert rec.flush() == {}        # disabled: no box, no I/O
    assert not path.exists()
    tele.enable()
    for i in range(10):
        tele.event("box.mark", i=i)
    with tele.span("box.work"):
        pass
    rec.flush()
    box = tele.read_blackbox(str(path))
    assert box is not None and box["name"] == "w0"
    assert box["pid"] == os.getpid()
    assert isinstance(box["epoch_unix_s"], float)
    # the TAIL survives, bounded by last_n
    marks = [e["i"] for e in box["events"] if e["name"] == "box.mark"]
    assert marks == [6, 7, 8, 9]    # newest last_n events, oldest gone
    assert any(s["name"] == "box.work" for s in box["spans"])
    assert box["counters"]["box.mark"] == 10


def test_flight_recorder_flush_overwrites_atomically(tmp_path):
    path = tmp_path / "bb.json"
    tele.enable()
    rec = tele.FlightRecorder(str(path), name="w1")
    tele.event("first.flush")
    rec.flush()
    tele.event("second.flush")
    rec.flush()
    box = tele.read_blackbox(str(path))
    names = {e["name"] for e in box["events"]}
    assert {"first.flush", "second.flush"} <= names
    assert box["flush_seq"] == 2
    # unreadable/missing boxes answer None, never raise
    assert tele.read_blackbox(str(tmp_path / "nope.json")) is None
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert tele.read_blackbox(str(bad)) is None


# ---------------------------------------------------------------------------
# snapshot merging (the supervisor's aggregation primitive)
# ---------------------------------------------------------------------------

def test_merge_snapshots_recomputes_fleet_percentiles():
    """A fleet p99 is recomputed from the merged distribution — NOT
    last-write-wins over per-worker p99 gauges."""
    fast = Histogram.of([0.01] * 100)
    slow = Histogram.of([1.0] * 100)
    snaps = []
    for h, jobs in ((fast, 100), (slow, 100)):
        g = {f"serve.latency.{k}": v
             for k, v in h.percentiles().items()}
        snaps.append({"counters": {"serve.jobs.completed": jobs},
                      "gauges": g,
                      "hists": {"serve.latency": h.to_dict()},
                      "spans": {"serve.latency":
                                {"count": h.count, "total_s": h.sum,
                                 "min_s": h.min, "max_s": h.max}}})
    m = tele.merge_snapshots(snaps)
    assert m["counters"]["serve.jobs.completed"] == 200
    assert m["hists"]["serve.latency"]["count"] == 200
    # combined: ranks 101..200 are 1.0 -> p99 is the slow worker's 1.0,
    # p50 sits at the fast/slow boundary (rank 100 -> 0.01)
    assert m["gauges"]["serve.latency.p99"] == pytest.approx(1.0,
                                                             rel=0.05)
    assert m["gauges"]["serve.latency.p50"] == pytest.approx(0.01,
                                                             rel=0.05)
    sp = m["spans"]["serve.latency"]
    assert sp["count"] == 200 and sp["max_s"] == 1.0


# ---------------------------------------------------------------------------
# serving-plane wiring: latency histogram + tenant/stack facets
# ---------------------------------------------------------------------------

def test_serve_latency_histogram_with_tenant_and_stack_facets():
    from qrack_tpu.models.qft import qft_qcircuit
    from qrack_tpu.serve import QrackService

    tele.enable()
    with QrackService(engine_layers="cpu", batch_window_ms=5.0,
                      tick_s=0.02) as svc:
        sid = svc.create_session(3, seed=1, rand_global_phase=False)
        for _ in range(3):
            svc.apply(sid, qft_qcircuit(3), timeout=60)
    snap = tele.snapshot(include_events=False)
    hists = snap["hists"]
    assert hists["serve.latency"]["count"] == 3
    assert hists[f"serve.latency.tenant.{sid}"]["count"] == 3
    stacks = [k for k in hists
              if k.startswith("serve.latency.stack.")]
    assert stacks and sum(hists[k]["count"] for k in stacks) == 3
    assert snap["gauges"]["serve.latency.p50"] > 0
    assert hists["serve.queue_wait"]["count"] == 3


# ---------------------------------------------------------------------------
# telemetry_report.py --fleet + the docs lint (tier-1 satellites)
# ---------------------------------------------------------------------------

def _load_report_module():
    spec = importlib.util.spec_from_file_location(
        "telemetry_report",
        os.path.join(REPO, "scripts", "telemetry_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_telemetry_report_fleet_mode(tmp_path, capsys):
    fleet = {"kind": "fleet", "t_wall": 1.0,
             "counters": {"serve.jobs.completed": 7},
             "gauges": {"serve.latency.p50": 0.01,
                        "serve.latency.p99": 0.5},
             "hists": {"serve.latency":
                       Histogram.of([0.01] * 9 + [0.5]).to_dict()},
             "spans": {},
             "workers": {"w0:123": {"jobs_completed": 7,
                                    "serve.latency": {"count": 10,
                                                      "p50": 0.01,
                                                      "p99": 0.5}}},
             "postmortems": []}
    post = {"kind": "postmortem", "worker": "w1", "pid": 9, "t_wall": 2.0,
            "reason": "kill", "flush_seq": 3, "epoch_unix_s": 0.0,
            "last_events": [{"name": "worker.ready", "t_s": 0.1}],
            "last_spans": []}
    path = tmp_path / "fleet_telemetry.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps(fleet) + "\n")
        f.write(json.dumps(post) + "\n")
    mod = _load_report_module()
    rc = mod.main([str(path), "--fleet"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "SLO" in out and "w0:123" in out
    assert "postmortem" in out and "worker.ready" in out


def test_telemetry_report_noise_section(tmp_path, capsys):
    """Pinned: the `== noise ==` section reports trajectory-batch
    geometry (trajectories per batch, HBM chunk rate) and the
    devget-honest trajectories/s gauge (docs/NOISE.md)."""
    snap = {"counters": {"noise.traj.batches": 2,
                         "noise.traj.trajectories": 512,
                         "noise.traj.chunks": 4,
                         "noise.traj.chunked": 1,
                         "noise.traj.windows": 4,
                         "noise.traj.slots": 1024},
            "gauges": {"noise.traj.rate": 104.67,
                       "noise.traj.chunk_size": 128},
            "hists": {"noise.traj.wall_s":
                      Histogram.of([2.4, 2.5]).to_dict()},
            "spans": {}}
    mod = _load_report_module()
    rep = mod.report(snap, top=5)
    assert rep["noise"]["trajectories_per_batch"] == 256.0
    assert rep["noise"]["chunk_rate"] == 0.5
    assert rep["noise"]["noise.traj.rate"] == 104.67
    path = tmp_path / "telemetry.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps(snap) + "\n")
    rc = mod.main([str(path)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "== noise ==" in out
    assert "noise.traj.rate" in out
    # the trajectory wall histogram reports through the SLO section
    assert "noise.traj.wall_s" in out


def test_telemetry_docs_lint_is_clean():
    """Satellite: every telemetry name in qrack_tpu/ is documented and
    no documented pattern is dead — enforced in tier 1."""
    script = os.path.join(REPO, "scripts", "check_telemetry_docs.py")
    out = subprocess.run([sys.executable, script],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
