"""QUnit Schmidt-factoring layer: correctness + separability accounting."""

import numpy as np
import pytest

from qrack_tpu import QEngineCPU
from qrack_tpu.layers.qunit import QUnit
from qrack_tpu.utils.rng import QrackRandom

from test_engine_matrix import random_circuit


def factory(n, **kw):
    kw.setdefault("rand_global_phase", False)
    return QEngineCPU(n, **kw)


def make(n, seed=1, **kw):
    return QUnit(n, unit_factory=factory, rng=QrackRandom(seed),
                 rand_global_phase=False, **kw)


def oracle(n, seed=1):
    return QEngineCPU(n, rng=QrackRandom(seed), rand_global_phase=False)


def fid(a, b):
    return abs(np.vdot(a.GetQuantumState(), b.GetQuantumState())) ** 2


def test_single_qubit_gates_never_allocate_units():
    q = make(50)  # 50 qubits would be impossible densely
    for i in range(50):
        q.H(i)
        q.T(i)
        q.H(i)
    assert all(s.cached for s in q.shards)
    assert q.GetUnitCount() == 50
    # H T H |0>: P(1) = sin^2(pi/8)
    assert q.Prob(0) == pytest.approx(0.14644660940672624, abs=1e-9)


def test_entangle_and_factor_accounting():
    q = make(6)
    q.H(0)
    q.CNOT(0, 1)          # buffered invert link first...
    q.Prob(1)             # ...measuring the target forces unit {0,1}
    q.H(3)
    q.CNOT(3, 4)
    q.Prob(4)             # unit {3,4}
    assert q.GetUnitCount() == 4  # two 2q units + two cached
    assert q.GetMaxUnitSize() == 2
    q.CNOT(1, 3)          # buffered; flush merges into one 4q unit
    q.Prob(3)
    assert q.GetMaxUnitSize() == 4
    # measurement separates everything
    q.rng.seed(3)
    q.MAll()
    assert all(s.cached for s in q.shards)


def test_matches_oracle_random():
    n = 5
    for seed in (1, 2, 3):
        q = make(n, seed)
        o = oracle(n, seed)
        random_circuit(q, QrackRandom(400 + seed), 40, n)
        random_circuit(o, QrackRandom(400 + seed), 40, n)
        assert fid(q, o) == pytest.approx(1.0, abs=1e-6)


def test_control_elision():
    q = make(3)
    # control q0 is definitely |0>: CNOT must not entangle anything
    q.CNOT(0, 1)
    assert all(s.cached for s in q.shards)
    # control definitely |1>: gate applies but without entangling
    q.X(0)
    q.CNOT(0, 1)
    assert all(s.cached for s in q.shards)
    assert q.Prob(1) == pytest.approx(1.0)


def test_swap_is_bookkeeping():
    q = make(4)
    q.X(0)
    q.H(1)
    q.Swap(0, 1)
    assert all(s.cached for s in q.shards)
    assert q.Prob(1) == pytest.approx(1.0)
    assert q.Prob(0) == pytest.approx(0.5)


def test_measurement_separates():
    q = make(4, seed=7)
    q.H(0)
    for i in range(3):
        q.CNOT(i, i + 1)
    q.Prob(3)             # resolve the tail link: full GHZ unit
    assert q.GetMaxUnitSize() == 4
    q.rng.seed(5)
    m = q.M(2)
    # GHZ collapse: everything separable again
    assert all(s.cached for s in q.shards)
    for i in range(4):
        assert q.Prob(i) == pytest.approx(1.0 if m else 0.0, abs=1e-9)


def test_try_separate():
    q = make(3, seed=9)
    q.H(0)
    q.CNOT(0, 1)
    q.Prob(1)     # force the real entangle
    q.CNOT(0, 1)  # undone at the engine: product state, still one unit
    q.Prob(1)
    assert q.GetMaxUnitSize() == 2
    assert q.TrySeparate(1)
    assert q.shards[1].cached
    # X-basis separable qubit
    q2 = make(2, seed=11)
    q2.H(0)
    q2.CNOT(0, 1)
    q2.H(0)
    q2.H(1)   # (|00>+|01>+|10>-|11>)? no: H H on bell -> still entangled
    assert not q2.TrySeparate(0)


def test_qft_and_back():
    n = 6
    q = make(n, seed=13)
    o = oracle(n, seed=13)
    for eng in (q, o):
        eng.SetPermutation(0b101101)
        eng.QFT(0, n)
        eng.IQFT(0, n)
    assert fid(q, o) == pytest.approx(1.0, abs=1e-6)
    assert abs(q.GetAmplitude(0b101101)) == pytest.approx(1.0, abs=1e-5)


def test_alu_spanning_units():
    n = 7
    q = make(n, seed=15)
    o = oracle(n, seed=15)
    for eng in (q, o):
        eng.HReg(0, 3)
        eng.INC(5, 0, 3)   # stays within [0,3): MUL's carry reg keeps |0>
        eng.CINC(2, 0, 3, (6,))
        eng.MUL(3, 0, 3, 3)
        eng.PhaseFlipIfLess(3, 0, 3)
        eng.DIV(3, 0, 3, 3)
    assert fid(q, o) == pytest.approx(1.0, abs=1e-6)
    np.testing.assert_allclose(q.GetQuantumState(), o.GetQuantumState(), atol=1e-8)


def test_compose_decompose():
    a = make(2, seed=17)
    a.H(0)
    a.CNOT(0, 1)
    b = make(2, seed=18)
    b.X(0)
    start = a.Compose(b)
    assert start == 2 and a.qubit_count == 4
    o = oracle(4)
    o.H(0); o.CNOT(0, 1); o.X(2)
    assert fid(a, o) == pytest.approx(1.0, abs=1e-8)
    dest = make(2, seed=19)
    a.Decompose(2, dest)
    assert a.qubit_count == 2
    assert dest.Prob(0) == pytest.approx(1.0)
    assert dest.Prob(1) == pytest.approx(0.0)


def test_decompose_entangled_span():
    a = make(4, seed=21)
    a.H(0)
    a.CNOT(0, 1)
    a.H(2)
    a.CNOT(2, 3)
    dest = make(2, seed=22)
    a.Decompose(1, dest)  # span {1, 2}: cuts across two units
    # original Bell pair is destroyed (q1 was entangled with q0) — but
    # the operation must complete and preserve norms
    assert a.qubit_count == 2 and dest.qubit_count == 2
    p = a.GetProbs()
    assert np.isclose(p.sum(), 1.0, atol=1e-6)


def test_parity_across_units():
    q = make(4, seed=23)
    o = oracle(4, seed=23)
    for eng in (q, o):
        eng.H(0)
        eng.CNOT(0, 1)
        eng.H(2)
    assert q.ProbParity(0b0111) == pytest.approx(o.ProbParity(0b0111), abs=1e-9)
    assert q.ProbParity(0b0011) == pytest.approx(o.ProbParity(0b0011), abs=1e-9)


def test_multishot_and_expectation():
    n = 4
    q = make(n, seed=25)
    o = oracle(n, seed=25)
    for eng in (q, o):
        eng.H(0)
        eng.CNOT(0, 1)
        eng.RY(0.8, 2)
    assert q.ExpectationBitsAll([0, 1, 2, 3]) == pytest.approx(
        o.ExpectationBitsAll([0, 1, 2, 3]), abs=1e-6)
    sq = q.MultiShotMeasureMask([1, 2], 800)
    so = o.MultiShotMeasureMask([1, 2], 800)
    for k in range(4):
        assert abs(sq.get(k, 0) - so.get(k, 0)) < 140


def test_wide_sparse_circuit():
    # 40 qubits with only local entanglement: impossible densely, cheap here
    q = make(40, seed=27)
    for i in range(0, 40, 4):
        q.H(i)
        q.CNOT(i, i + 1)
        q.T(i + 1)
    assert q.GetMaxUnitSize() <= 2   # links may still be buffered
    assert q.GetAmplitude(0) != 0    # flushes: genuine 2q units now
    assert q.GetMaxUnitSize() == 2
    q.rng.seed(1)
    r = q.MAll()
    assert isinstance(r, int)


def test_two_qubit_cnot_probe_separation():
    """Reference: 2-qubit TrySeparate via controlled inverse state prep
    (src/qunit.cpp:781) — separates product pairs whose factors are NOT
    X/Y/Z eigenstates (the 1-qubit probes cannot)."""
    q = make(3, 11)
    o = oracle(3, 11)
    for eng in (q, o):
        eng.RY(0.3, 0)
        eng.RY(0.7, 1)
        eng.CNOT(0, 1)
        eng.Prob(1)      # force the real entangle
        eng.CNOT(0, 1)   # net identity, but the unit stays merged
        eng.Prob(1)
    assert any(not s.cached for s in q.shards[:2])
    assert not q._try_separate_1qb(0, 1e-8)  # 1q probes fail off-axis
    assert q.TrySeparate((0, 1))
    assert q.shards[0].cached and q.shards[1].cached
    assert fid(q, o) == pytest.approx(1.0, abs=1e-8)


def test_two_qubit_probe_nondestructive_on_entangled():
    q = make(2, 13)
    o = oracle(2, 13)
    for eng in (q, o):
        eng.H(0)
        eng.CNOT(0, 1)
        eng.RY(0.4, 1)
    assert not q.TrySeparate((0, 1))
    assert fid(q, o) == pytest.approx(1.0, abs=1e-7)


def test_product_fourier_fast_path_parity():
    """Closed-form basis-register QFT/IQFT (the optimizer-stack headline
    case, reference protocol test_qft_permutation_init): exact parity
    with the gate path, zero engine dispatches, generic fallback when
    the register is not a basis state."""
    for trial in range(8):
        perm = (trial * 23) & 63
        start, length = (0, 6) if trial % 2 == 0 else (1, 4)
        for inverse in (False, True):
            u = QUnit(6, rng=QrackRandom(trial), rand_global_phase=False)
            o = QEngineCPU(6, rng=QrackRandom(trial), rand_global_phase=False)
            for eng in (u, o):
                eng.SetPermutation(perm)
                (eng.IQFT if inverse else eng.QFT)(start, length)
            np.testing.assert_allclose(
                u.GetQuantumState(), o.GetQuantumState(), atol=1e-10)
            assert u.dispatch_count == 0
    u = QUnit(5, rng=QrackRandom(3), rand_global_phase=False)
    o = QEngineCPU(5, rng=QrackRandom(3), rand_global_phase=False)
    for eng in (u, o):
        eng.SetPermutation(9)
        eng.RY(0.7, 2)
        eng.QFT(0, 5)
    np.testing.assert_allclose(u.GetQuantumState(), o.GetQuantumState(),
                               atol=1e-7)
