"""QUnit gate-fusion buffers: phase links + pending 2x2s.

Validates the re-design of the reference's PhaseShard/basis-tag
machinery (reference: include/qengineshard.hpp:32-100, applied in
src/qunit.cpp:2433-2487): oracle parity is maintained while engine
dispatches drop materially, buffered CZ pairs cancel without ever
entangling, and measurement reduces pending links to local phases."""

import cmath
import math

import numpy as np
import pytest

from qrack_tpu import QEngineCPU
from qrack_tpu.layers.qunit import QUnit
from qrack_tpu.utils.rng import QrackRandom


def factory(n, **kw):
    kw.setdefault("rand_global_phase", False)
    return QEngineCPU(n, **kw)


def make(n, seed=1, **kw):
    return QUnit(n, unit_factory=factory, rng=QrackRandom(seed),
                 rand_global_phase=False, **kw)


def oracle(n, seed=1):
    return QEngineCPU(n, rng=QrackRandom(seed), rand_global_phase=False)


def fid(a, b):
    return abs(np.vdot(a.GetQuantumState(), b.GetQuantumState())) ** 2


def phase_heavy_circuit(q, rng, depth, n):
    """Supremacy-style circuit: 1q rotations + CZ/CPhase entanglers —
    the workload the reference's PhaseShard buffers accelerate."""
    for _ in range(depth):
        for i in range(n):
            r = rng.randint(0, 6)
            if r == 0:
                q.H(i)
            elif r == 1:
                q.T(i)
            elif r == 2:
                q.X(i)
            elif r == 3:
                q.S(i)
            elif r == 4:
                q.RZ(rng.rand() * math.pi, i)
            else:
                q.Y(i)
        for i in range(0, n - 1, 2):
            c, t = i, i + 1
            r = rng.randint(0, 3)
            if r == 0:
                q.CZ(c, t)
            elif r == 1:
                q.MCPhase((c,), 1.0,
                          cmath.exp(1j * rng.rand() * math.pi), t)
            else:
                q.CNOT(c, t)
        for i in range(1, n - 1, 2):
            q.CZ(i, i + 1)


def test_fusion_matches_oracle():
    n = 5
    for seed in (11, 12, 13):
        q = make(n, seed)
        o = oracle(n, seed)
        phase_heavy_circuit(q, QrackRandom(500 + seed), 6, n)
        phase_heavy_circuit(o, QrackRandom(500 + seed), 6, n)
        assert fid(q, o) == pytest.approx(1.0, abs=1e-6)


def test_fusion_reduces_dispatches():
    n = 6
    counts = {}
    for fusion in (True, False):
        q = make(n, 7, phase_fusion=fusion)
        phase_heavy_circuit(q, QrackRandom(900), 6, n)
        q.GetQuantumState()  # force flush so both do the same total work
        counts[fusion] = q.dispatch_count
    assert counts[True] < counts[False], counts
    # and the states agree with each other
    q1 = make(n, 7, phase_fusion=True)
    q2 = make(n, 7, phase_fusion=False)
    phase_heavy_circuit(q1, QrackRandom(900), 6, n)
    phase_heavy_circuit(q2, QrackRandom(900), 6, n)
    assert fid(q1, q2) == pytest.approx(1.0, abs=1e-6)


def test_cz_pair_cancels_without_entangling():
    q = make(2)
    q.H(0)
    q.H(1)
    q.CZ(0, 1)
    q.CZ(0, 1)
    # the pair cancelled in the link bag: no unit was ever allocated
    assert all(s.cached for s in q.shards)
    assert q.dispatch_count == 0
    st = q.GetQuantumState()
    assert np.allclose(st, np.full(4, 0.5), atol=1e-12)


def test_hh_cancels_on_entangled_shard():
    q = make(2)
    q.H(0)
    q.CNOT(0, 1)
    before = q.dispatch_count
    q.H(0)
    q.H(0)
    q.T(0)
    q.Z(0)
    assert q.dispatch_count == before  # all buffered, zero engine work
    o = oracle(2)
    o.H(0)
    o.CNOT(0, 1)
    o.T(0)
    o.Z(0)
    assert fid(q, o) == pytest.approx(1.0, abs=1e-9)


def test_measurement_reduces_link_without_entangling():
    # CZ between two superposed but separable qubits stays buffered;
    # measuring one endpoint reduces it to a local phase on the other —
    # entanglement never happens (reference: buffered-CZ elision)
    q = make(2, seed=5)
    q.H(0)
    q.H(1)
    q.CZ(0, 1)
    assert all(s.cached for s in q.shards)
    res = q.M(0)
    assert all(s.cached for s in q.shards)
    assert q.dispatch_count == 0
    # remaining qubit: |+> if res==0 else |->  (CZ phase applied)
    expect = np.array([1, -1 if res else 1]) / math.sqrt(2)
    st = q.GetQuantumState()
    sub = st[[0 + (1 if res else 0), 2 + (1 if res else 0)]]
    phase = sub[0] / expect[0]
    assert np.allclose(sub, phase * expect, atol=1e-9)


def test_link_through_anti_pending():
    # X pending on an entangled shard flips the link payload orientation
    for seed in (21, 22):
        q = make(3, seed)
        o = oracle(3, seed)
        for eng in (q, o):
            eng.H(0)
            eng.CNOT(0, 1)   # entangle
            eng.X(0)         # anti-diagonal pending on q's shard
            eng.CZ(0, 2)     # buffered through the flip
            eng.H(2)
            eng.CZ(0, 2)
            eng.H(0)         # general pending; forces flush on next probe
        assert fid(q, o) == pytest.approx(1.0, abs=1e-9)


def test_prob_through_buffers_is_free():
    q = make(2)
    q.H(0)
    q.CNOT(0, 1)
    base = q.dispatch_count
    q.T(0)       # diag pending
    q.X(0)       # composes to 'gen'? X @ T is anti-diagonal — still free
    assert q.Prob(0) == pytest.approx(0.5, abs=1e-9)
    assert q.dispatch_count == base


def test_qft_parity_with_fusion():
    n = 5
    q = make(n, 3)
    o = oracle(n, 3)
    for eng in (q, o):
        eng.X(0)
        eng.X(2)
        eng.QFT(0, n)
    assert fid(q, o) == pytest.approx(1.0, abs=1e-6)


def test_clone_copies_buffers():
    q = make(3)
    q.H(0)
    q.H(1)
    q.CZ(0, 1)
    q.T(1)
    c = q.Clone()
    sq = q.GetQuantumState()   # flushes q's buffers
    sc = c.GetQuantumState()   # clone must have its own copies
    assert np.allclose(np.abs(np.vdot(sq, sc)) ** 2, 1.0, atol=1e-9)


def test_dispose_shard_with_pending_link():
    # disposing a link-entangled cached shard must reduce the link, not
    # leave a dangling partner reference
    q = make(2)
    q.H(0)
    q.H(1)
    q.CZ(0, 1)
    q.Dispose(1, 1)
    q.T(0)
    q.H(0)
    assert 0.0 <= q.Prob(0) <= 1.0
    assert q.qubit_count == 1
    assert not q.shards[0].links


def test_maall_with_buffers_distribution():
    # GHZ-like with buffered phases: MAll outcomes must stay correlated
    hits = set()
    for trial in range(40):
        q = make(3, seed=100 + trial)
        q.H(0)
        q.CNOT(0, 1)
        q.CNOT(1, 2)
        q.Z(0)       # diag pending
        q.X(1)       # anti pending: flips outcome bit 1
        r = q.MAll()
        hits.add(r)
    assert hits <= {0b010, 0b101}, hits
    assert len(hits) == 2


# ---------------------------------------------------------------------------
# controlled-invert links (reference: PhaseShard isInvert buffering,
# include/qengineshard.hpp:62-100): CNOT-echo patterns cancel in the
# link bag and never dispatch to an engine
# ---------------------------------------------------------------------------


def test_cnot_echo_zero_dispatch():
    u = QUnit(3, rng=QrackRandom(1))
    u.H(0)
    u.H(1)
    d0 = u.dispatch_count
    u.CNOT(0, 1)
    u.S(1)
    u.Z(1)
    u.CNOT(0, 1)
    assert u.dispatch_count == d0
    o = QEngineCPU(3, rng=QrackRandom(1), rand_global_phase=False)
    o.H(0)
    o.H(1)
    o.CNOT(0, 1)
    o.S(1)
    o.Z(1)
    o.CNOT(0, 1)
    assert abs(np.vdot(u.GetQuantumState(), o.GetQuantumState())) ** 2 > 1 - 1e-9


def test_cy_and_anticnot_echo_cancel():
    u = QUnit(2, rng=QrackRandom(3))
    u.H(0)
    u.H(1)
    d0 = u.dispatch_count
    u.CY(0, 1)
    u.CY(0, 1)          # CY·CY = diag(1,1,-1,-1)·... stays buffered
    u.AntiCNOT(0, 1)
    u.AntiCNOT(0, 1)
    assert u.dispatch_count == d0
    o = QEngineCPU(2, rng=QrackRandom(3), rand_global_phase=False)
    o.H(0)
    o.H(1)
    o.CY(0, 1)
    o.CY(0, 1)
    o.AntiCNOT(0, 1)
    o.AntiCNOT(0, 1)
    assert abs(np.vdot(u.GetQuantumState(), o.GetQuantumState())) ** 2 > 1 - 1e-9


def test_invert_link_random_parity():
    import random

    random.seed(5)
    for trial in range(8):
        u = QUnit(4, rng=QrackRandom(200 + trial))
        o = QEngineCPU(4, rng=QrackRandom(200 + trial), rand_global_phase=False)
        for _ in range(45):
            g = random.choice(["H", "S", "X", "Y", "Z", "T", "CNOT", "CZ",
                               "CY", "AntiCNOT", "Swap", "M"])
            q = random.randrange(4)
            q2 = (q + 1 + random.randrange(3)) % 4
            if g == "M":
                r = u.M(q)
                o.ForceM(q, r)
                continue
            for e in (u, o):
                if g in ("CNOT", "CZ", "CY", "AntiCNOT", "Swap"):
                    getattr(e, g)(q, q2)
                else:
                    getattr(e, g)(q)
        fid = abs(np.vdot(u.GetQuantumState(), o.GetQuantumState())) ** 2
        assert fid > 1 - 1e-8, (trial, fid)


def test_invert_link_measurement_flush():
    # measuring the invert TARGET must account for the buffered CNOT
    u = QUnit(2, rng=QrackRandom(7))
    u.X(0)          # control definite |1> — but via link path when buffered
    u.H(0)
    u.CNOT(0, 1)    # Bell-ish via link
    p = u.Prob(1)   # target marginal must see the buffered X
    assert abs(p - 0.5) < 1e-9
