"""Guarded real-TPU smoke test: gate parity on the axon device.

Runs in a subprocess (the suite's conftest pins this process to the cpu
backend) with a hard timeout: the axon tunnel in this container can
wedge indefinitely, in which case the test SKIPS rather than hangs.
When the chip answers, "works on TPU" becomes a tested claim instead of
an inference (VERDICT round-1 weak #6)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PROBE = """
import sys
sys.path.insert(0, {repo!r})
import numpy as np
import jax
from qrack_tpu import QEngineCPU
from qrack_tpu.engines.tpu import QEngineTPU
from qrack_tpu.utils.rng import QrackRandom

plat = jax.devices()[0].platform
q = QEngineTPU(4, rng=QrackRandom(3), rand_global_phase=False)
o = QEngineCPU(4, rng=QrackRandom(3), rand_global_phase=False)
for eng in (q, o):
    eng.H(0); eng.CNOT(0, 1); eng.T(1); eng.H(2); eng.CZ(2, 3); eng.RY(0.3, 3)
f = abs(np.vdot(q.GetQuantumState(), o.GetQuantumState())) ** 2
assert abs(f - 1) < 1e-5, f
p = q.Prob(1)
assert abs(p - o.Prob(1)) < 1e-5
print("TPU_PARITY_OK", plat)
"""


def test_gate_parity_on_real_device():
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    try:
        res = subprocess.run(
            [sys.executable, "-c", PROBE.format(repo=REPO)],
            capture_output=True, text=True, timeout=90, env=env)
    except subprocess.TimeoutExpired:
        pytest.skip("axon TPU tunnel unresponsive (wedged) — device parity "
                    "skipped; re-run when the claim clears")
    if "TPU_PARITY_OK" not in res.stdout:
        if "UNIMPLEMENTED" in res.stderr or "axon" not in res.stdout + res.stderr:
            pytest.skip(f"TPU backend unavailable: {res.stderr[-300:]}")
        pytest.fail(f"device parity failed:\n{res.stderr[-1500:]}")
    plat = res.stdout.split()[-1]
    if plat not in ("axon", "tpu"):
        # parity held, but on a fallback backend (e.g. the suite was
        # launched without the axon sitecustomize on PYTHONPATH) — not
        # a failure, just no real-device evidence from this run
        pytest.skip(f"no TPU backend registered (probe ran on {plat})")
