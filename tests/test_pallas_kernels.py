"""Pallas fused gate-segment sweep (interpret mode on CPU): parity with
the XLA compile_fn path on random circuits."""

import numpy as np
import pytest

from qrack_tpu.layers.qcircuit import QCircuit
from qrack_tpu.models import qft as qftm
from qrack_tpu import matrices as mat
from qrack_tpu.utils.rng import QrackRandom


def build_circuit(n, seed, gates=30):
    rng = QrackRandom(seed)
    c = QCircuit(n)
    for _ in range(gates):
        kind = rng.randint(0, 5)
        t = rng.randint(0, n)
        if kind == 0:
            c.append_1q(t, mat.H2)
        elif kind == 1:
            c.append_1q(t, mat.T2)
        elif kind == 2:
            c.append_1q(t, np.asarray(mat.X2))
        elif kind == 3:
            ctl = rng.randint(0, n)
            if ctl != t:
                c.append_ctrl((ctl,), t, np.diag([1.0, -1.0 + 0j]), 1)  # CZ
        else:
            ctl = rng.randint(0, n)
            if ctl != t:
                c.append_ctrl((ctl,), t, np.asarray(mat.X2), 1)  # CNOT
    return c


@pytest.mark.parametrize("seed", [3, 4])
def test_pallas_segments_match_xla(seed):
    import jax

    n = 8
    c = build_circuit(n, seed)
    planes = qftm.basis_planes(n, 5)
    want = np.asarray(jax.jit(c.compile_fn(n))(planes))
    # tiny tiles force multi-block grids AND high-target bridges
    for bp in (4, 6, n):
        got = np.asarray(c.compile_fn_pallas(n, block_pow=bp,
                                             interpret=True)(planes))
        np.testing.assert_allclose(got, want, atol=3e-5, err_msg=f"bp={bp}")


def test_pallas_high_diag_and_controls():
    import jax

    n = 7
    c = QCircuit(n)
    c.append_1q(0, mat.H2)
    c.append_1q(n - 1, mat.H2)
    c.append_ctrl((n - 1,), 0, np.diag([1.0, 1j]), 1)   # high control, diag
    c.append_1q(n - 1, mat.T2)                          # high diag target
    c.append_ctrl((0,), 1, np.asarray(mat.X2), 1)
    planes = qftm.basis_planes(n, 0)
    want = np.asarray(jax.jit(c.compile_fn(n))(planes))
    got = np.asarray(c.compile_fn_pallas(n, block_pow=4, interpret=True)(planes))
    np.testing.assert_allclose(got, want, atol=3e-5)


# ---------------- fused compressed-ket kernels ----------------
# (ops/pallas_turboquant.py: dequant -> gate -> requant in one pass)


def test_tq_pallas_matches_xla_path(monkeypatch):
    """QRACK_USE_PALLAS=1 routes compressed gates through the fused
    kernel (interpret mode on CPU): state parity with the XLA chunk
    programs across generic/diagonal/controlled/cross-tile gates."""
    import numpy as np

    from qrack_tpu.engines.turboquant import QEngineTurboQuant
    from qrack_tpu.utils.rng import QrackRandom

    def fidelity(a, b):
        a, b = np.asarray(a), np.asarray(b)
        return abs(np.vdot(a, b)) ** 2 / (np.vdot(a, a).real
                                          * np.vdot(b, b).real)

    # kernel-parity test: pin per-gate dispatch on BOTH builds (the
    # pallas path never fuses, and windowed recompression rounds int16
    # codes differently enough to nick the 1e-9 fidelity bar)
    monkeypatch.setenv("QRACK_TPU_FUSE_WINDOW", "1")

    def build(use_pallas):
        if use_pallas:
            monkeypatch.setenv("QRACK_USE_PALLAS", "1")
        else:
            monkeypatch.delenv("QRACK_USE_PALLAS", raising=False)
        q = QEngineTurboQuant(8, bits=16, chunk_qb=5, block_pow=2,
                              rng=QrackRandom(70), rand_global_phase=False)
        # small tile so cross-TILE routing (target >= tile) is exercised
        q._PALLAS_TILE_POW = 4
        for i in range(8):
            q.H(i)
        q.CNOT(0, 3)        # generic inside tile
        q.T(2)              # diag inside tile
        q.CZ(1, 6)          # diag: control low, target above tile
        q.CNOT(6, 1)        # control above tile, target low (pallas)
        q.RZ(0.37, 7)       # diag above tile
        q.CNOT(0, 7)        # generic above tile -> XLA pair path
        q.RY(0.8, 2)
        return q.GetQuantumState()

    a = build(False)
    b = build(True)
    assert fidelity(a, b) > 1 - 1e-9


def test_tq_pallas_untouched_tiles_exact(monkeypatch):
    """Tiles failing the high-control test keep their codes bit-for-bit
    through the fused kernel (the XLA path's exactness contract)."""
    import numpy as np

    from qrack_tpu.engines.turboquant import QEngineTurboQuant
    from qrack_tpu.utils.rng import QrackRandom

    monkeypatch.setenv("QRACK_USE_PALLAS", "1")
    q = QEngineTurboQuant(7, bits=8, chunk_qb=4, block_pow=2,
                          rng=QrackRandom(71), rand_global_phase=False)
    q._PALLAS_TILE_POW = 4
    for i in range(7):
        q.H(i)
    before = np.asarray(q._codes).copy()
    # control on qubit 6 (above the 16-amp tile): half the tiles must
    # stay untouched exactly
    q.CNOT(6, 1)
    after = np.asarray(q._codes)
    T = 1 << 4
    rows_per_tile = T // 4
    tiles = before.shape[0] // rows_per_tile
    untouched = 0
    for t in range(tiles):
        sl = slice(t * rows_per_tile, (t + 1) * rows_per_tile)
        # tile t covers amplitudes with bit6 = (t >> 2) & 1 at tile_pow 4
        if ((t << 4) >> 6) & 1 == 0:
            assert np.array_equal(before[sl], after[sl]), t
            untouched += 1
    assert untouched == tiles // 2
