"""Pallas fused gate-segment sweep (interpret mode on CPU): parity with
the XLA compile_fn path on random circuits."""

import numpy as np
import pytest

from qrack_tpu.layers.qcircuit import QCircuit
from qrack_tpu.models import qft as qftm
from qrack_tpu import matrices as mat
from qrack_tpu.utils.rng import QrackRandom


def build_circuit(n, seed, gates=30):
    rng = QrackRandom(seed)
    c = QCircuit(n)
    for _ in range(gates):
        kind = rng.randint(0, 5)
        t = rng.randint(0, n)
        if kind == 0:
            c.append_1q(t, mat.H2)
        elif kind == 1:
            c.append_1q(t, mat.T2)
        elif kind == 2:
            c.append_1q(t, np.asarray(mat.X2))
        elif kind == 3:
            ctl = rng.randint(0, n)
            if ctl != t:
                c.append_ctrl((ctl,), t, np.diag([1.0, -1.0 + 0j]), 1)  # CZ
        else:
            ctl = rng.randint(0, n)
            if ctl != t:
                c.append_ctrl((ctl,), t, np.asarray(mat.X2), 1)  # CNOT
    return c


@pytest.mark.parametrize("seed", [3, 4])
def test_pallas_segments_match_xla(seed):
    import jax

    n = 8
    c = build_circuit(n, seed)
    planes = qftm.basis_planes(n, 5)
    want = np.asarray(jax.jit(c.compile_fn(n))(planes))
    # tiny tiles force multi-block grids AND high-target bridges
    for bp in (4, 6, n):
        got = np.asarray(c.compile_fn_pallas(n, block_pow=bp,
                                             interpret=True)(planes))
        np.testing.assert_allclose(got, want, atol=3e-5, err_msg=f"bp={bp}")


def test_pallas_high_diag_and_controls():
    import jax

    n = 7
    c = QCircuit(n)
    c.append_1q(0, mat.H2)
    c.append_1q(n - 1, mat.H2)
    c.append_ctrl((n - 1,), 0, np.diag([1.0, 1j]), 1)   # high control, diag
    c.append_1q(n - 1, mat.T2)                          # high diag target
    c.append_ctrl((0,), 1, np.asarray(mat.X2), 1)
    planes = qftm.basis_planes(n, 0)
    want = np.asarray(jax.jit(c.compile_fn(n))(planes))
    got = np.asarray(c.compile_fn_pallas(n, block_pow=4, interpret=True)(planes))
    np.testing.assert_allclose(got, want, atol=3e-5)
