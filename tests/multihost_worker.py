"""One process of a multi-process CPU-backend cluster (see
tests/test_multihost.py).

Brings up jax.distributed via qrack_tpu.parallel.cluster (env-driven:
QRACK_COORDINATOR / QRACK_NUM_PROCESSES / QRACK_PROCESS_ID), builds a
QPager over the GLOBAL device mesh spanning both processes, runs a
circuit whose paged-target gates ppermute across the process boundary,
and prints the resulting state + a measurement for the parent to check
against the numpy oracle.  This is the proof that the sharded kernels
are mesh-shape agnostic across hosts (reference analogue: the cluster
hooks SnuCL/GVirtuS, CMakeLists.txt:110,201-203 — never exercised
there; exercised here)."""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from qrack_tpu.utils.platform import pin_host_cpu

pin_host_cpu(int(os.environ.get("QRACK_WORKER_LOCAL_DEVICES", "4")))

from qrack_tpu.parallel.cluster import init_cluster, process_count, process_index

init_cluster()

import jax
import numpy as np

from qrack_tpu.parallel import QPager
from qrack_tpu.utils.rng import QrackRandom


def main() -> None:
    n = 7  # 8 pages x 16-amplitude shards
    # identical seed on every process: host-side measurement draws must
    # agree cluster-wide (see parallel/cluster.py docstring)
    q = QPager(n, rng=QrackRandom(777), rand_global_phase=False,
               devices=jax.devices(), n_pages=8)
    q.SetPermutation(0)
    for i in range(n):
        q.H(i)
    for i in range(n - 1):
        q.CNOT(i, i + 1)        # crosses local->paged at the boundary
    q.CZ(4, 6)                  # paged-paged diagonal
    q.Swap(0, 5)                # mixed local/paged swap
    q.T(6)                      # paged diagonal
    q.H(6)                      # paged target: ppermute pair exchange
    state = q.GetQuantumState()  # replicated collective fetch
    p3 = q.Prob(3)
    m = q.MAll()                 # collapse: identical draw everywhere

    # 2) the flagship fused sharded programs over the SAME global mesh:
    #    whole-circuit QFT / brick-wall RCS / fori_loop Grover running
    #    with shards owned by different processes (gloo as the DCN
    #    stand-in); reads go through a replicated-output fetch, the only
    #    read pattern legal when no process addresses every shard
    from jax.sharding import Mesh

    from qrack_tpu.models import grover as grm
    from qrack_tpu.models import qft as qftm
    from qrack_tpu.models import rcs as rcsm
    from qrack_tpu.parallel.cluster import replicate_program

    mesh = Mesh(np.array(jax.devices()), ("pages",))
    fetch = replicate_program(mesh, 1 << n)

    qfn, qsh = qftm.make_sharded_qft_fn(mesh, n)
    qout = qfn(qftm.basis_planes(n, 5, sharding=qsh))
    qamps = np.asarray(jax.device_get(fetch(qout, 0)))

    rfn, rsh = rcsm.make_sharded_rcs_fn(mesh, n, depth=4, seed=11)
    rout = rfn(qftm.basis_planes(n, 0, sharding=rsh))
    ramps = np.asarray(jax.device_get(fetch(rout, 0)))

    gfn, gsh, _ = grm.make_sharded_grover_fn(mesh, n, target=3)
    gout = gfn(qftm.basis_planes(n, 0, sharding=gsh))
    gamps = np.asarray(jax.device_get(fetch(gout, 0)))

    # 3) the sharded COMPRESSED ket over the same global mesh: chunked
    #    shard_map programs + b-bit ppermute pair exchange across the
    #    process boundary; reads go through the multi-host-safe paths
    #    (psum'd prob, all-gathered masses, replicated chunk decompress)
    from qrack_tpu.parallel.turboquant_pager import QPagerTurboQuant

    tq = QPagerTurboQuant(n, bits=16, chunk_qb=3, block_pow=2,
                          devices=jax.devices(), n_pages=8,
                          rng=QrackRandom(777), rand_global_phase=False)
    for i in range(n):
        tq.H(i)
    tq.CNOT(0, 6)       # page-bit target: cross-process code exchange
    tq.T(6)
    tq.CZ(5, 6)
    tq_p3 = tq.Prob(3)
    tq_p6 = tq.Prob(6)
    tq_amp0 = tq.GetAmplitude(0)      # block-local replicated fetch
    tq_m = tq.MAll()

    print("RESULT " + json.dumps({
        "proc": process_index(),
        "procs": process_count(),
        "n_global_devices": len(jax.devices()),
        "re": [float(x) for x in state.real],
        "im": [float(x) for x in state.imag],
        "prob3": float(p3),
        "mall": int(m),
        "qft_re": [float(x) for x in qamps[0]],
        "qft_im": [float(x) for x in qamps[1]],
        "rcs_norm": float((ramps[0] ** 2 + ramps[1] ** 2).sum()),
        "grover_p_target": grm.success_probability(gamps, 3),
        "tq_prob3": float(tq_p3),
        "tq_prob6": float(tq_p6),
        "tq_amp0_abs": abs(tq_amp0),
        "tq_mall": int(tq_m),
    }), flush=True)


if __name__ == "__main__":
    main()
