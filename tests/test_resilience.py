"""Resilience layer: fault grammar, watchdogged dispatch, circuit
breaker, and TPU→CPU failover with oracle-matching state.

Every test drives the programmatic fault API (resilience.faults.inject)
rather than QRACK_TPU_FAULTS, and restores the global resilience state
(fixture below) so the rest of the suite runs with the layer disabled —
the default off-path the <2% bench criterion is measured on.
"""

import time

import numpy as np
import pytest

from qrack_tpu import QEngineCPU, create_quantum_interface
from qrack_tpu import resilience as res
from qrack_tpu import telemetry as tele
from qrack_tpu.engines.hybrid import QHybrid
from qrack_tpu.resilience import faults
from qrack_tpu.utils.rng import QrackRandom


@pytest.fixture(autouse=True)
def _clean_resilience():
    faults.clear()
    res.reset_breaker()
    res.configure(max_retries=2, backoff_s=0.0, timeout_s=0.0)
    yield
    faults.clear()
    res.reset_breaker()
    res.configure()  # re-read env (defaults)
    res.disable()
    tele.disable()
    tele.reset()


# ---------------------------------------------------------------------------
# fault grammar
# ---------------------------------------------------------------------------

def test_fault_spec_grammar():
    s = faults.parse_spec("tpu.compile:raise:3")
    assert (s.site, s.kind, s.after_n, s.times) == ("tpu.compile", "raise", 3, 1)
    s = faults.parse_spec("pager.exchange:timeout:0+4")
    assert (s.after_n, s.times) == (0, 4)
    s = faults.parse_spec("*:device-loss:2+")
    assert s.times is None  # persistent
    s = faults.parse_spec("device_get:nan-poison:1:42")
    assert s.seed == 42
    with pytest.raises(ValueError):
        faults.parse_spec("just-a-site")
    with pytest.raises(ValueError):
        faults.parse_spec("site:unknown-kind:0")


def test_fault_spec_matching_and_firing():
    s = faults.FaultSpec(site="compile", kind="raise", after_n=2, times=2)
    assert s.matches("tpu.compile") and s.matches("compile")
    assert not s.matches("tpu.device_get")
    fires = [s.should_fire() for _ in range(6)]
    # 2 pass through, 2 fire, then healed
    assert fires == [False, False, True, True, False, False]
    wild = faults.FaultSpec(site="*", kind="raise")
    assert wild.matches("anything.at.all")


def test_fault_env_grammar_loads():
    n = faults.load_env("tpu.compile:raise:0,pager.exchange:hang:2+")
    assert n == 2
    assert [s.kind for s in faults.specs()] == ["raise", "hang"]
    faults.load_env("")
    assert not faults.specs()


def test_seeded_fault_is_deterministic():
    s1 = faults.FaultSpec(site="*", kind="raise", times=None, seed=7)
    s2 = faults.FaultSpec(site="*", kind="raise", times=None, seed=7)
    seq1 = [s1.should_fire() for _ in range(20)]
    seq2 = [s2.should_fire() for _ in range(20)]
    assert seq1 == seq2                      # same seed, same stream
    assert 0 < sum(seq1) < 20                # p=1/2: fires some, not all


# ---------------------------------------------------------------------------
# guarded dispatch: retry, backoff, give-up
# ---------------------------------------------------------------------------

def test_transient_fault_recovers_via_retry():
    res.enable()
    faults.inject("x.dispatch", "raise", after_n=0, times=1)
    calls = []
    out = res.call_guarded("x.dispatch", lambda: calls.append(1) or 42)
    assert out == 42 and len(calls) == 1  # fault fired pre-call, retry ran fn


def test_persistent_fault_gives_up_with_cause():
    res.enable()
    res.configure(max_retries=2)
    faults.inject("x.dispatch", "device-loss", after_n=0, times=None)
    with pytest.raises(res.DispatchGiveUp) as ei:
        res.call_guarded("x.dispatch", lambda: 42)
    # device-loss is non-retryable: exactly one attempt, cause preserved
    assert isinstance(ei.value.cause, res.DeviceLost)
    assert faults.specs()[0].fired == 1


def test_retry_count_matches_max_retries():
    res.enable()
    res.configure(max_retries=3)
    faults.inject("x.dispatch", "raise", after_n=0, times=None)
    with pytest.raises(res.DispatchGiveUp):
        res.call_guarded("x.dispatch", lambda: 42)
    assert faults.specs()[0].fired == 4  # 1 attempt + 3 retries


def test_retry_telemetry_counters():
    tele.enable()
    res.enable()
    res.configure(max_retries=2)
    faults.inject("x.dispatch", "raise", after_n=0, times=2)
    assert res.call_guarded("x.dispatch", lambda: 7) == 7
    c = tele.snapshot()["counters"]
    assert c.get("resilience.failure.x.dispatch") == 2
    assert c.get("resilience.fault.x.dispatch.raise") == 2


def test_injected_hang_is_caught_by_watchdog():
    res.enable()
    res.configure(max_retries=0, timeout_s=0.1)
    faults.inject("x.dispatch", "hang", after_n=0, times=None)
    t0 = time.perf_counter()
    with pytest.raises(res.DispatchGiveUp) as ei:
        res.call_guarded("x.dispatch", lambda: 42)
    assert isinstance(ei.value.cause, res.DispatchTimeout)
    assert time.perf_counter() - t0 < 5.0  # watchdog, not the stub's nap


def test_watchdog_times_out_real_slow_fn():
    res.enable()
    res.configure(max_retries=0, timeout_s=0.05)

    def slow():
        time.sleep(2.0)
        return "too late"

    with pytest.raises(res.DispatchGiveUp) as ei:
        res.call_guarded("x.dispatch", slow)
    assert isinstance(ei.value.cause, res.DispatchTimeout)


def test_validate_finite_catches_nan_output():
    res.enable()
    res.configure(max_retries=0, validate=True)
    bad = np.array([1.0, np.nan])
    with pytest.raises(res.DispatchGiveUp) as ei:
        res.call_guarded("x.dispatch", lambda: bad)
    assert isinstance(ei.value.cause, res.NaNPoisoned)
    res.configure(validate=False)
    assert res.call_guarded("x.dispatch", lambda: bad) is bad


def test_guarded_program_disabled_is_passthrough():
    prog = res.instrument_dispatch("x.dispatch", lambda a: a * 2)
    res.disable()
    faults.inject("x.dispatch", "raise", after_n=0, times=None)  # re-enables
    res.disable()
    assert prog(21) == 42  # disabled: fault never consulted


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------

def _fake_clock():
    t = [0.0]
    return t, (lambda: t[0])


def test_breaker_full_state_machine():
    t, clock = _fake_clock()
    br = res.CircuitBreaker(threshold=3, cooldown_s=10.0, clock=clock)
    for _ in range(2):
        br.record_failure("s")
    assert br.state == "closed"
    br.record_failure("s")
    assert br.state == "open" and br.trips == 1
    with pytest.raises(res.BreakerOpen):
        br.allow("s")
    t[0] = 10.1
    br.allow("s")  # cooldown elapsed: half-open probe allowed
    assert br.state == "half_open"
    br.record_failure("s")  # probe failed: re-open immediately
    assert br.state == "open" and br.trips == 2
    t[0] = 20.2
    br.allow("s")
    br.record_success()
    assert br.state == "closed" and br.consecutive_failures == 0


def test_breaker_success_resets_consecutive_count():
    br = res.CircuitBreaker(threshold=2, cooldown_s=10.0)
    br.record_failure("s")
    br.record_success()
    br.record_failure("s")
    assert br.state == "closed"  # never 2 consecutive


def test_breaker_trip_stops_dispatch_until_half_open():
    """Acceptance: an open breaker provably stops TPU dispatch — fn is
    never invoked while open, and runs again after the cooldown."""
    t, clock = _fake_clock()
    res.reset_breaker(res.CircuitBreaker(threshold=2, cooldown_s=30.0,
                                         clock=clock))
    res.enable()
    res.configure(max_retries=0)
    faults.inject("x.dispatch", "raise", after_n=0, times=2)
    calls = []
    for _ in range(2):
        with pytest.raises(res.DispatchGiveUp):
            res.call_guarded("x.dispatch", lambda: calls.append(1))
    assert res.get_breaker().state == "open" and not calls
    # while open: BreakerOpen without touching fn (fault already healed,
    # so any invocation WOULD succeed — proving the breaker is the gate)
    with pytest.raises(res.BreakerOpen):
        res.call_guarded("x.dispatch", lambda: calls.append(1))
    assert not calls
    t[0] = 30.1  # cooldown elapsed: half-open probe runs and closes
    assert res.call_guarded("x.dispatch", lambda: calls.append(1) or 9) == 9
    assert calls and res.get_breaker().state == "closed"


def test_breaker_events_in_telemetry():
    tele.enable()
    t, clock = _fake_clock()
    br = res.reset_breaker(res.CircuitBreaker(threshold=1, cooldown_s=5.0,
                                              clock=clock))
    br.record_failure("s")
    with pytest.raises(res.BreakerOpen):
        br.allow("s")
    t[0] = 5.1
    br.allow("s")
    br.record_success()
    names = [e["name"] for e in tele.snapshot()["events"]]
    assert "resilience.breaker.trip" in names
    assert "resilience.breaker.half_open" in names
    assert "resilience.breaker.close" in names
    assert tele.snapshot()["counters"]["resilience.breaker.rejected"] == 1


# ---------------------------------------------------------------------------
# failover: the circuit completes with oracle-matching state
# ---------------------------------------------------------------------------

N = 5


def _apply_prefix(e):
    e.H(0)
    e.CNOT(0, 1)
    e.T(1)
    e.RY(0.7, 2)


def _apply_suffix(e):
    e.CZ(1, 2)
    e.H(3)
    e.INC(3, 0, 3)


def _oracle_state():
    o = QEngineCPU(N, rng=QrackRandom(3), rand_global_phase=False)
    _apply_prefix(o)
    _apply_suffix(o)
    return np.asarray(o.GetQuantumState())


def _assert_oracle_match(engine):
    with faults.suspended():
        got = np.asarray(engine.GetQuantumState())
    want = _oracle_state()
    f = abs(np.vdot(want, got)) ** 2
    assert f > 1 - 1e-6, f


# (site, kind) matrix: persistent faults that must end in failover (or
# transparent retry for the transient rows) with identical results
_MATRIX = [
    ("tpu.compile", "raise"),
    ("tpu.compile", "device-loss"),
    ("tpu.compile", "timeout"),
    ("tpu.device_get", "raise"),
    ("tpu.device_get", "nan-poison"),
    ("compile", "device-loss"),  # bare category
]


@pytest.mark.parametrize("site,kind", _MATRIX,
                         ids=[f"{s}-{k}" for s, k in _MATRIX])
def test_tpu_failover_matrix_matches_oracle(site, kind):
    res.enable()
    q = create_quantum_interface("tpu", N, rng=QrackRandom(3),
                                 rand_global_phase=False)
    _apply_prefix(q)
    faults.inject(site, kind, after_n=0, times=None)
    _apply_suffix(q)        # compile-site rows fail over here...
    q.GetAmplitude(0)       # ...device_get rows on this guarded read
    assert type(q.engine).__name__ == "QEngineCPU"
    _assert_oracle_match(q)


@pytest.mark.parametrize("site,kind", [("pager.exchange", "raise"),
                                       ("pager.dispatch", "device-loss"),
                                       ("pager.device_get", "raise")])
def test_pager_failover_matrix_matches_oracle(site, kind, monkeypatch):
    # pin per-gate dispatch: this matrix targets the per-gate sites
    # (pager.exchange only exists there — fused windows run their
    # ppermutes inside tpu.fuse.flush, covered by test_fusion.py)
    monkeypatch.setenv("QRACK_TPU_FUSE_WINDOW", "1")
    res.enable()
    q = create_quantum_interface("pager", N, n_pages=4, rng=QrackRandom(3),
                                 rand_global_phase=False)
    _apply_prefix(q)
    faults.inject(site, kind, after_n=0, times=None)
    _apply_suffix(q)
    q.GetAmplitude(0)  # device_get rows fail over on this guarded read
    name = type(q.engine).__name__
    if site == "pager.exchange":
        # elastic landing: shrinking localizes every qubit, the exchange
        # site vanishes, the pager keeps serving ON the mesh — and since
        # `raise` is not a device-down signal, the boundary probe has
        # already grown it back to the construction page count
        assert name == "QPager"
        assert q.engine.n_pages == 4 and not q.engine.elastic_degraded
    else:
        # dispatch/device_get faults follow the shrunk pager (the site
        # exists at every page count), so the chain exits the mesh
        assert name in ("QEngineTPU", "QEngineCPU")
    _assert_oracle_match(q)


def test_transient_fault_is_invisible_midcircuit():
    res.enable()
    q = create_quantum_interface("tpu", N, rng=QrackRandom(3),
                                 rand_global_phase=False)
    _apply_prefix(q)
    faults.inject("tpu.compile", "raise", after_n=0, times=1)  # one blip
    _apply_suffix(q)
    assert type(q.engine).__name__ == "QEngineTPU"  # no failover
    _assert_oracle_match(q)


def test_hybrid_fails_over_in_place_and_stays_pinned():
    res.enable()
    h = QHybrid(N, tpu_threshold_qubits=2, rng=QrackRandom(3),
                rand_global_phase=False)
    _apply_prefix(h)
    faults.inject("tpu.compile", "raise", after_n=0, times=None)
    _apply_suffix(h)
    assert h._failed_over == "cpu"
    assert type(h._engine).__name__ == "QEngineCPU"
    _assert_oracle_match(h)
    # the ceiling sticks: ops keep running on CPU with the fault armed
    h.X(4)
    h.X(4)
    _assert_oracle_match(h)


def test_hybrid_construction_failover():
    res.enable()
    faults.inject("discover", "device-loss", after_n=0, times=None)
    h = QHybrid(N, tpu_threshold_qubits=2, device_id=0)
    assert h._failed_over == "cpu"
    assert type(h._engine).__name__ == "QEngineCPU"


def test_resilient_engine_build_construction_failover():
    res.enable()
    faults.inject("discover", "device-loss", after_n=0, times=None)
    q = create_quantum_interface("tpu", N, device_id=0)
    assert type(q.engine).__name__ == "QEngineCPU"
    q.H(0)
    assert abs(q.Prob(0) - 0.5) < 1e-6


def test_failover_emits_telemetry():
    tele.enable()
    res.enable()
    q = create_quantum_interface("tpu", N)
    faults.inject("tpu.compile", "raise", after_n=0, times=None)
    q.H(0)      # queues in the lazy gate window — no dispatch yet
    q.Prob(0)   # read boundary flushes; the compile fault fires HERE
    snap = tele.snapshot()
    assert snap["counters"].get("resilience.failovers", 0) >= 1
    assert any(e["name"].startswith("resilience.failover.")
               for e in snap["events"])


def test_wide_pager_failover_exhausts_chain_loudly():
    """When every fallback is unavailable (breaker open blocks the TPU
    hop, CPU cap below the width), failover must raise the constructor's
    error — not wedge, not silently truncate the ket."""
    from qrack_tpu.config import get_config, set_config

    old_cap = get_config().max_cpu_qubits
    set_config(max_cpu_qubits=4)
    try:
        res.enable()
        q = create_quantum_interface("pager", 6, n_pages=4)
        br = res.get_breaker()
        for _ in range(br.threshold):
            br.record_failure("pager.dispatch")  # trip: blocks TPU hop too
        with pytest.raises(MemoryError):
            q.H(0)     # queues lazily; the dispatch (and the loud
            q.Prob(0)  # chain-exhausted failure) surfaces at the read
    finally:
        set_config(max_cpu_qubits=old_cap)


# ---------------------------------------------------------------------------
# elastic re-paging: shrink on loss, serve degraded, grow on recovery
# (docs/ELASTICITY.md)
# ---------------------------------------------------------------------------

def test_flap_spec_grammar_and_device_down():
    s = faults.parse_spec("pager.dispatch:flap:2+3")
    assert (s.site, s.kind, s.after_n, s.times) == ("pager.dispatch",
                                                    "flap", 2, 3)
    with pytest.raises(ValueError):
        faults.parse_spec("pager.dispatch:flapp:0")
    faults.inject("pager.dispatch", "flap", after_n=1, times=2)
    assert not faults.device_down("pager.dispatch")  # window not open yet
    faults.check("pager.dispatch")                   # call 1 passes through
    assert faults.device_down("pager.dispatch")      # window open
    assert not faults.device_down("tpu.compile")     # other sites healthy
    for _ in range(2):
        with pytest.raises(res.DeviceLost):
            faults.check("pager.dispatch")
    assert not faults.device_down("pager.dispatch")  # flap healed itself
    faults.inject("tpu.dispatch", "device-loss", after_n=0, times=None)
    assert faults.device_down()              # any armed loss, any site
    with faults.suspended():
        assert not faults.device_down()      # snapshots must stand still


def test_pager_shrink_expand_roundtrip():
    """Structural round trip: shrink while the flap window is open, the
    probe refuses to grow until it heals, then one boundary restores the
    construction page count — and the amplitudes survive both repages."""
    tele.enable()
    res.enable()
    q = create_quantum_interface("pager", N, n_pages=4, rng=QrackRandom(3),
                                 rand_global_phase=False)
    _apply_prefix(q)
    pager = q.engine
    faults.inject("pager.dispatch", "flap", after_n=0, times=2)
    assert faults.device_down("pager.dispatch")
    pager.shrink_pages()
    assert pager.n_pages == 2 and pager.elastic_degraded
    assert not pager.maybe_reexpand()        # loss window still open
    assert pager.n_pages == 2
    for _ in range(2):                       # consume the flap: recovery
        with pytest.raises(res.DeviceLost):
            faults.check("pager.dispatch")
    assert pager.maybe_reexpand()
    assert pager.n_pages == 4 and not pager.elastic_degraded
    _apply_suffix(q)
    _assert_oracle_match(q)
    c = tele.snapshot()["counters"]
    assert c.get("elastic.repage.shrink") == 1
    assert c.get("elastic.repage.expand") == 1


def _rcs_ops():
    """Deterministic RCS-style brickwork: random single-qubit phase/H
    layers + CZ entanglers (no measurement — rng streams must stay
    uncoupled from the oracle's)."""
    gen = np.random.Generator(np.random.PCG64(7))
    ops = []
    for _ in range(4):
        for qb in range(N):
            ops.append((("T", "H", "S")[int(gen.integers(0, 3))], (qb,)))
        a = int(gen.integers(0, N))
        ops.append(("CZ", (a, (a + 1) % N)))
    return ops


def _fuzz_ops():
    """A slice of the API-fuzzer vocabulary (test_fuzz_api.py) minus
    measuring ops, so oracle and pager stay stream-independent."""
    gen = np.random.Generator(np.random.PCG64(11))
    q = lambda: int(gen.integers(0, N))
    ops = []
    for _ in range(16):
        kind = int(gen.integers(0, 6))
        if kind == 0:
            ops.append((("X", "Y", "Z", "H", "S", "T")[q()], (q(),)))
        elif kind == 1:
            ops.append((("RX", "RY", "RZ")[kind % 3],
                        (float(gen.uniform(0, 6.28)), q())))
        elif kind == 2:
            a = q()
            ops.append((("CNOT", "CZ", "Swap", "ISwap")[a % 4],
                        (a, (a + 1 + q() % (N - 1)) % N)))
        elif kind == 3:
            s = int(gen.integers(0, N - 1))
            ops.append(("INC", (int(gen.integers(0, 8)), s,
                                int(gen.integers(1, N - s + 1)))))
        elif kind == 4:
            ops.append(("XMask", (int(gen.integers(1, 1 << N)),)))
        else:
            ops.append(("ZMask", (int(gen.integers(1, 1 << N)),)))
    return ops


_ELASTIC_CIRCUITS = {
    "qft": lambda: ([("H", (0,)), ("CNOT", (0, 1)), ("RY", (0.7, 2))]
                    + [("QFT", (0, N))]),
    "rcs": _rcs_ops,
    "fuzz": _fuzz_ops,
}


@pytest.mark.parametrize("window", [1, 16])
@pytest.mark.parametrize("circ", sorted(_ELASTIC_CIRCUITS))
def test_pager_shrink_midcircuit_matrix(circ, window, monkeypatch):
    """A flap mid-circuit (fused window mid-flight included) shrinks the
    pager, the job finishes degraded ON the mesh, the next boundary
    grows it back — and the final state matches the CPU oracle."""
    monkeypatch.setenv("QRACK_TPU_FUSE_WINDOW", str(window))
    tele.enable()
    res.enable()
    ops = _ELASTIC_CIRCUITS[circ]()
    cut = len(ops) // 2
    q = create_quantum_interface("pager", N, n_pages=4, rng=QrackRandom(3),
                                 rand_global_phase=False)
    for name, args in ops[:cut]:
        getattr(q, name)(*args)
    # one DeviceLost at whatever guarded site fires next, then recovery
    faults.inject("*", "flap", after_n=0, times=1)
    for name, args in ops[cut:]:
        getattr(q, name)(*args)
    q.GetAmplitude(0)   # read boundary: flush + (for device_get) failover
    q.Prob(0)           # post-recovery boundary: the probe grows back
    c = tele.snapshot()["counters"]
    assert c.get("elastic.repage.shrink", 0) >= 1, (circ, window)
    assert type(q.engine).__name__ == "QPager"
    assert q.engine.n_pages == 4 and not q.engine.elastic_degraded
    with faults.suspended():
        got = np.asarray(q.GetQuantumState())
    o = QEngineCPU(N, rng=QrackRandom(3), rand_global_phase=False)
    for name, args in ops:
        getattr(o, name)(*args)
    want = np.asarray(o.GetQuantumState())
    f = abs(np.vdot(want, got)) ** 2
    assert f > 1 - 1e-6, (circ, window, f)


def test_pager_staircase_descends_through_shrink():
    """A PERSISTENT device loss re-fires on the shrunk pager, so the
    chain keeps descending — 4 → 2 → 1 pages — before exiting the mesh,
    and the final state still matches the oracle."""
    tele.enable()
    res.enable()
    q = create_quantum_interface("pager", N, n_pages=4, rng=QrackRandom(3),
                                 rand_global_phase=False)
    _apply_prefix(q)
    faults.inject("pager.dispatch", "device-loss", after_n=0, times=None)
    _apply_suffix(q)
    q.GetAmplitude(0)
    c = tele.snapshot()["counters"]
    assert c.get("elastic.repage.shrink", 0) >= 2     # 4→2 then 2→1
    assert type(q.engine).__name__ in ("QEngineTPU", "QEngineCPU")
    _assert_oracle_match(q)


def test_hybrid_unpins_after_device_recovery():
    """Regression for the stay-down asymmetry: a pinned CPU ceiling must
    lift at the next call boundary once the device-loss heals, not
    persist until process restart."""
    res.enable()
    h = QHybrid(N, tpu_threshold_qubits=2, rng=QrackRandom(3),
                rand_global_phase=False)
    _apply_prefix(h)
    faults.inject("tpu.compile", "device-loss", after_n=0, times=None)
    _apply_suffix(h)
    assert h._failed_over == "cpu"
    assert type(h._engine).__name__ == "QEngineCPU"
    faults.clear()          # the device comes back
    h.X(4)                  # boundary: probe passes, ceiling lifts
    assert h._failed_over is None
    assert type(h._engine).__name__ == "QEngineTPU"
    h.X(4)                  # undo so the oracle circuit is unchanged
    _assert_oracle_match(h)


# ---------------------------------------------------------------------------
# probe library
# ---------------------------------------------------------------------------

def test_probe_roundtrip_ok():
    r = res.run_probe(timeout_s=120.0)
    assert r.ok and not r.timed_out and "PROBE_OK" in r.output


def test_probe_timeout_sigterm_first():
    import sys

    # a child that ignores nothing: SIGTERM must end it inside the grace
    r = res.run_probe(timeout_s=0.3, term_grace_s=10.0,
                      python=sys.executable,
                      extra_env={"QRACK_PROBE_TEST_SLEEP": "1"})
    # the real payload may or may not finish in 0.3s on a loaded VM —
    # only the invariants matter: bounded return, coherent flags
    assert r.duration_s < 60.0
    if r.timed_out:
        assert not r.ok and not r.killed  # SIGTERM sufficed


# ---------------------------------------------------------------------------
# cluster init validation (satellite)
# ---------------------------------------------------------------------------

def test_init_cluster_rejects_partial_config(monkeypatch):
    from qrack_tpu.parallel import cluster

    monkeypatch.setattr(cluster, "_INITIALIZED", False)
    monkeypatch.setattr(cluster, "_INIT_ARGS", None)
    with pytest.raises(ValueError, match="num_processes"):
        cluster.init_cluster(coordinator_address="127.0.0.1:9999")
    with pytest.raises(ValueError, match="coordinator"):
        cluster.init_cluster(num_processes=2, process_id=0)
    monkeypatch.setenv("QRACK_NUM_PROCESSES", "2")
    with pytest.raises(ValueError, match="process_id"):
        cluster.init_cluster(coordinator_address="127.0.0.1:9999")


def test_init_cluster_repeat_semantics(monkeypatch):
    from qrack_tpu.parallel import cluster

    args = ("127.0.0.1:9999", 2, 0, None)
    monkeypatch.setattr(cluster, "_INITIALIZED", True)
    monkeypatch.setattr(cluster, "_INIT_ARGS", args)
    # identical repeat: idempotent no-op
    cluster.init_cluster(coordinator_address="127.0.0.1:9999",
                         num_processes=2, process_id=0)
    # different args: explicit error, not silent ignore
    with pytest.raises(RuntimeError, match="different arguments"):
        cluster.init_cluster(coordinator_address="10.0.0.1:1234",
                             num_processes=4, process_id=1)


# ---------------------------------------------------------------------------
# randomized soak (short slice; the full O(100) run is
# scripts/fault_soak.py)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_fault_soak_smoke():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "fault_soak", os.path.join(os.path.dirname(__file__),
                                   "..", "scripts", "fault_soak.py"))
    soak = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(soak)
    results = [soak.run_trial(t, seed=123) for t in range(9)]
    bad = [r for r in results if not r["ok"]]
    assert not bad, bad
