"""QCircuit / QTensorNetwork / QInterfaceNoisy / factory / models / QNeuron."""

import math

import numpy as np
import pytest

from qrack_tpu import QEngineCPU, create_quantum_interface, QNeuron
from qrack_tpu.layers.qcircuit import QCircuit, QCircuitGate
from qrack_tpu.layers.qtensornetwork import QTensorNetwork
from qrack_tpu.layers.noisy import QInterfaceNoisy
from qrack_tpu.layers.qunitmulti import QUnitMulti
from qrack_tpu import matrices as mat
from qrack_tpu.models import algorithms as algo
from qrack_tpu.utils.rng import QrackRandom

from test_engine_matrix import random_circuit


def cpu_factory(n, **kw):
    kw.setdefault("rand_global_phase", False)
    return QEngineCPU(n, **kw)


def fid(a, b):
    return abs(np.vdot(np.asarray(a.GetQuantumState()),
                       np.asarray(b.GetQuantumState()))) ** 2


# ---------------- QCircuit ----------------

def test_circuit_merging():
    c = QCircuit(2)
    c.append_1q(0, mat.H2)
    c.append_1q(0, mat.H2)   # H H = I: should cancel
    assert c.GetGateCount() == 0
    c.append_1q(0, mat.T2)
    c.append_1q(1, mat.H2)   # disjoint
    c.append_1q(0, mat.T2)   # merges with earlier T across disjoint H
    assert c.GetGateCount() == 2


def test_circuit_run_and_inverse():
    rng = QrackRandom(3)
    c = QCircuit(4)
    gates = []
    for _ in range(15):
        t = rng.randint(0, 4)
        m = mat.u3_mtrx(rng.rand(), rng.rand(), rng.rand())
        if rng.rand() < 0.4:
            ctl = rng.randint(0, 4)
            if ctl != t:
                c.append_ctrl((ctl,), t, m, 1)
                continue
        c.append_1q(t, m)
    q = cpu_factory(4, rng=QrackRandom(1))
    c.Run(q)
    c.Inverse().Run(q)
    assert abs(q.GetAmplitude(0)) == pytest.approx(1.0, abs=1e-8)


def test_circuit_past_light_cone():
    c = QCircuit(4)
    c.append_1q(0, mat.H2)
    c.append_ctrl((0,), 1, mat.X2, 1)
    c.append_1q(3, mat.H2)   # disjoint from qubit 0/1 cone
    cone = c.PastLightCone([1])
    assert cone.GetGateCount() == 2
    assert all(3 not in g.qubits() for g in cone.gates)


def test_circuit_compile_fn_matches_run():
    import jax

    from qrack_tpu.ops import gatekernels as gk

    rng = QrackRandom(7)
    c = QCircuit(5)
    for _ in range(20):
        t = rng.randint(0, 5)
        k = rng.randint(0, 3)
        if k == 0:
            c.append_1q(t, mat.H2)
        elif k == 1:
            c.append_1q(t, mat.u3_mtrx(rng.rand(), rng.rand(), rng.rand()))
        else:
            ctl = rng.randint(0, 5)
            if ctl != t:
                c.append_ctrl((ctl,), t, mat.X2, 1)
    q = cpu_factory(5, rng=QrackRandom(1))
    c.Run(q)
    fn = jax.jit(c.compile_fn(5))
    planes = fn(gk.to_planes(np.eye(1, 32, 0).ravel()))
    np.testing.assert_allclose(gk.from_planes(planes), q.GetQuantumState(), atol=3e-6)


# ---------------- QTensorNetwork ----------------

def test_tensornetwork_light_cone_elision():
    # a QUnit below makes full-width materialization cheap (the reference
    # stacks QTensorNetwork over QUnit the same way, SURVEY.md §1)
    from qrack_tpu.layers.qunit import QUnit

    def unit_stack(n, **kw):
        kw.setdefault("rand_global_phase", False)
        return QUnit(n, unit_factory=cpu_factory, **kw)

    q = QTensorNetwork(30, stack_factory=unit_stack, rng=QrackRandom(1),
                       rand_global_phase=False)
    # gates over 30 qubits, but the queried qubit's cone is 2 qubits wide
    for i in range(30):
        q.H(i)
    q.CNOT(0, 1)
    assert q.isBuffering()
    assert q.Prob(1) == pytest.approx(0.5, abs=1e-6)
    assert q.isBuffering()  # probability query must not materialize


def test_tensornetwork_matches_oracle():
    n = 5
    q = QTensorNetwork(n, stack_factory=cpu_factory, rng=QrackRandom(5),
                       rand_global_phase=False)
    o = cpu_factory(n, rng=QrackRandom(5))
    random_circuit(q, QrackRandom(600), 30, n)
    random_circuit(o, QrackRandom(600), 30, n)
    assert fid(q, o) == pytest.approx(1.0, abs=1e-8)
    # measurement materializes and stays consistent
    q.rng.seed(9)
    o.rng.seed(9)
    assert q.M(2) == o.M(2)
    assert fid(q, o) == pytest.approx(1.0, abs=1e-6)


# ---------------- noisy wrapper ----------------

def test_noisy_wrapper_degrades_fidelity():
    q = QInterfaceNoisy(2, inner_factory=cpu_factory, noise=0.2,
                        rng=QrackRandom(3))
    for _ in range(30):
        q.H(0)
        q.CNOT(0, 1)
    assert q.GetUnitaryFidelity() < 0.01
    q.ResetUnitaryFidelity()
    assert q.GetUnitaryFidelity() == 1.0
    # zero noise is exact
    q0 = QInterfaceNoisy(3, inner_factory=cpu_factory, noise=0.0,
                         rng=QrackRandom(4), rand_global_phase=False)
    o = cpu_factory(3, rng=QrackRandom(4))
    random_circuit(q0, QrackRandom(700), 20, 3)
    random_circuit(o, QrackRandom(700), 20, 3)
    assert fid(q0, o) == pytest.approx(1.0, abs=1e-8)


# ---------------- factory ----------------

@pytest.mark.parametrize("layers", [
    "cpu", "tpu", "optimal",
    ["unit", "stabilizer_hybrid", "cpu"],
    ["tensor_network", "unit", "cpu"],
    ["noisy", "unit", "cpu"],
    ["unit_multi", "cpu"],
    ["stabilizer"],
])
def test_factory_stacks_run_teleport(layers):
    ok = 0
    for t in range(5):
        q = create_quantum_interface(layers, 3, rng=QrackRandom(50 + t))
        if layers == ["stabilizer"]:
            q.H(0)  # Clifford-only payload
        else:
            q.U(0, 0.8, 0.3, -0.5)
        before, after = algo.teleport(q)
        ok += abs(after - before) < 1e-5
    assert ok == 5


def test_arranged_layers_full():
    from qrack_tpu import create_arranged_layers_full

    q = create_arranged_layers_full(sd=True, sh=True, hy=False, pg=False,
                                    oc=False, qubit_count=4,
                                    rng=QrackRandom(1), rand_global_phase=False)
    algo.ghz(q)
    q.rng.seed(3)
    r = q.MAll()
    assert r in (0, 0b1111)


# ---------------- models ----------------

def test_grover_model():
    q = create_quantum_interface("cpu", 7, rng=QrackRandom(5))
    assert algo.grover_search(q, 42) == 42


def test_shor_model():
    for seed in range(6):
        q = create_quantum_interface("cpu", 8, rng=QrackRandom(80 + seed))
        f = algo.shor_order_find(q, 7, 15, 4)
        if f is not None:
            assert f in (3, 5)
            return
    pytest.fail("no factor found in 6 rounds")


def test_rcs_and_xeb():
    n = 6
    q = cpu_factory(n, rng=QrackRandom(9))
    algo.random_circuit_sampling(q, 4, QrackRandom(10))
    probs = q.GetProbs()
    shots = q.MultiShotMeasureMask([1 << i for i in range(n)], 300)
    samples = [k for k, v in shots.items() for _ in range(v)]
    x = algo.xeb_fidelity(probs, samples)
    assert x > 0.3  # ideal sampler: XEB ~ 1


def test_quantum_volume_model():
    q = create_quantum_interface("optimal", 5, rng=QrackRandom(11))
    r = algo.quantum_volume(q, rng=QrackRandom(12))
    assert 0 <= r < 32


def test_qunit_multi_placement():
    q = QUnitMulti(6, unit_factory=cpu_factory, rng=QrackRandom(13),
                   device_ids=[0, 1], rand_global_phase=False)
    q.H(0)
    q.CNOT(0, 1)
    q.H(3)
    q.CNOT(3, 4)
    o = cpu_factory(6, rng=QrackRandom(13))
    o.H(0); o.CNOT(0, 1); o.H(3); o.CNOT(3, 4)
    assert fid(q, o) == pytest.approx(1.0, abs=1e-8)


# ---------------- QNeuron ----------------

def test_qneuron_learns_identity():
    q = create_quantum_interface("cpu", 2, rng=QrackRandom(21))
    neuron = QNeuron(q, [0], 1)
    # teach: output should equal input
    for epoch in range(40):
        for val in (False, True):
            q.SetPermutation(1 if val else 0)
            neuron.LearnPermutation(eta=0.25, expected=val)
    correct = 0
    for val in (False, True):
        q.SetPermutation(1 if val else 0)
        p = neuron.Predict(expected=val)
        correct += p > 0.8
    assert correct == 2


def test_controlled_phase_identity_not_dropped():
    # regression: CS then CIS-like payload product = i*I controlled must
    # keep the relative phase on the control subspace
    c = QCircuit(2)
    c.append_ctrl((0,), 1, np.diag([1, 1j]), 1)
    c.append_ctrl((0,), 1, np.diag([1j, 1]), 1)
    q = cpu_factory(2, rng=QrackRandom(1))
    q.H(0)
    c.Run(q)
    o = cpu_factory(2, rng=QrackRandom(1))
    o.H(0)
    o.MCMtrxPerm((0,), np.diag([1j, 1j]), 1, 1)
    np.testing.assert_allclose(q.GetQuantumState(), o.GetQuantumState(), atol=1e-10)
    # uncontrolled global-phase identity IS droppable
    c2 = QCircuit(1)
    c2.append_1q(0, np.diag([1j, 1j]) @ mat.H2)
    c2.append_1q(0, np.conj((np.diag([1j, 1j]) @ mat.H2).T))
    assert c2.GetGateCount() == 0


def test_tensornetwork_fused_materialization_on_tpu_engine():
    import time

    from qrack_tpu.engines.tpu import QEngineTPU

    def tpu_factory(n, **kw):
        kw.setdefault("rand_global_phase", False)
        return QEngineTPU(n, **kw)

    n = 8
    q = QTensorNetwork(n, stack_factory=tpu_factory, rng=QrackRandom(31),
                       rand_global_phase=False)
    o = cpu_factory(n, rng=QrackRandom(31))
    random_circuit(q, QrackRandom(900), 40, n)
    random_circuit(o, QrackRandom(900), 40, n)
    # observable query runs the light cone through ONE fused program
    assert q.Prob(3) == pytest.approx(o.Prob(3), abs=2e-6)
    assert fid(q, o) == pytest.approx(1.0, abs=1e-6)
    # collapsing measurement materializes through the fused path too
    q.rng.seed(5)
    o.rng.seed(5)
    assert q.M(2) == o.M(2)


def test_runfused_validates_and_caches():
    from qrack_tpu.engines.tpu import QEngineTPU
    from qrack_tpu.layers.qcircuit import QCircuit
    from qrack_tpu.ops import fusion as fu

    c = QCircuit(2)
    c.append_1q(5, mat.H2)  # widens the circuit, exceeds the engine below
    eng = QEngineTPU(4, rng=QrackRandom(1), rand_global_phase=False)
    with pytest.raises(ValueError):
        c.RunFused(eng)

    # caching: the parametric window program is keyed by STRUCTURE in
    # the shared fusion.PROGRAMS cache, so a same-shaped circuit with a
    # DIFFERENT rotation angle reuses the identical compiled program
    def phase_circ(ang):
        cc = QCircuit(3)
        cc.append_1q(0, mat.H2)
        cc.append_1q(1, np.diag([1.0, np.exp(1j * ang)]).astype(np.complex128))
        return cc

    e2 = QEngineTPU(3, rng=QrackRandom(2), rand_global_phase=False)
    c2 = phase_circ(0.3)
    c2.RunFused(e2)
    ops = fu.lower_gates(c2.gates)
    prog = fu.dense_window_program(3, fu.structure_of(ops), e2.dtype)
    c3 = phase_circ(1.1)
    c3.RunFused(e2)
    assert fu.dense_window_program(
        3, fu.structure_of(fu.lower_gates(c3.gates)), e2.dtype) is prog


def test_tensornetwork_rebuffers_after_measurement():
    """Reference behavior (qtensornetwork.hpp:73-83): a collapse runs the
    pending segment into the base stack, then buffering RESUMES — gates
    after a mid-circuit measurement stay in the IR."""
    n = 6
    q = QTensorNetwork(n, stack_factory=cpu_factory, rng=QrackRandom(8),
                       rand_global_phase=False)
    o = cpu_factory(n, rng=QrackRandom(8))
    for eng in (q, o):
        eng.H(0)
        eng.CNOT(0, 1)
    q.rng.seed(4)
    o.rng.seed(4)
    assert q.M(0) == o.M(0)
    assert not q.circuit.gates          # segment flushed by the collapse
    for eng in (q, o):
        eng.H(2)
        eng.CNOT(2, 3)
        eng.T(3)
    assert q.isBuffering()              # post-measurement gates buffered
    assert len(q.circuit.gates) > 0
    # light-cone queries work across the base + pending segment split
    assert q.Prob(3) == pytest.approx(o.Prob(3), abs=1e-9)
    assert q.isBuffering()
    assert fid(q, o) == pytest.approx(1.0, abs=1e-8)
    # second measurement: NO reseed — the interleaved queries above must
    # not have consumed from the measurement stream (regression guard
    # for query-path clones advancing the main rng)
    assert q.M(2) == o.M(2)
    for eng in (q, o):
        eng.H(4)
    assert q.isBuffering()
    assert fid(q, o) == pytest.approx(1.0, abs=1e-8)


def test_noisy_xeb_fidelity_sweep():
    """supreme_estimate-style sweep (reference:
    test/benchmarks.cpp test_noisy_fidelity_*): run the same RCS plan
    noiseless and at increasing depolarization; the measured state
    fidelity against the ideal ket must decrease monotonically-ish with
    noise and track the wrapper's logFidelity estimate to first order."""
    from qrack_tpu.models.rcs import reference_rcs_state

    n, depth, seed = 5, 4, 11
    ideal_eng = cpu_factory(n, rng=QrackRandom(1))
    ideal = reference_rcs_state(n, depth, seed, ideal_eng)

    fids = []
    for lam in (0.0, 0.01, 0.05):
        # average over stochastic noise realizations
        acc = 0.0
        reps = 8 if lam else 1
        for r in range(reps):
            q = QInterfaceNoisy(n, inner_factory=cpu_factory, noise=lam,
                                rng=QrackRandom(100 + r))
            st = reference_rcs_state(n, depth, seed, q)
            acc += abs(np.vdot(ideal, st)) ** 2
        fids.append(acc / reps)
    assert fids[0] > 0.999999
    assert fids[0] > fids[1] > fids[2]
    # first-order agreement between estimate and measurement at low noise
    q = QInterfaceNoisy(n, inner_factory=cpu_factory, noise=0.01,
                        rng=QrackRandom(5))
    reference_rcs_state(n, depth, seed, q)
    est = q.GetUnitaryFidelity()
    assert 0.2 < fids[1] / est < 2.5, (fids[1], est)


# ---------------- QUnitMulti device accounting ----------------

class _RecordingEngine(QEngineCPU):
    """CPU oracle + SetDevice recorder, standing in for QEngineTPU
    placement in the virtual-device tests."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.device_id = None

    def SetDevice(self, device_id):
        self.device_id = device_id


def _rec_factory(n, **kw):
    kw.setdefault("rand_global_phase", False)
    return _RecordingEngine(n, **kw)


def test_qunitmulti_packs_large_units_apart():
    """Two large subsystems must land on DIFFERENT devices when one
    device cannot hold both (reference: capability-aware
    RedistributeQEngines, src/qunitmulti.cpp:217)."""
    from qrack_tpu.layers.qunitmulti import DeviceInfo

    # each device holds exactly one 3-qubit c128 ket (128 bytes)
    table = [DeviceInfo(device_id=0, capacity_bytes=128),
             DeviceInfo(device_id=1, capacity_bytes=128)]
    q = QUnitMulti(6, unit_factory=_rec_factory, rng=QrackRandom(5),
                   device_table=table, rand_global_phase=False)
    # two 3-qubit entangled clumps
    q.H(0); q.CNOT(0, 1); q.CNOT(1, 2)
    q.H(3); q.CNOT(3, 4); q.CNOT(4, 5)
    units = {id(s.unit): s.unit for s in q.shards if s.unit is not None}
    assert len(units) == 2
    devs = sorted(u.device_id for u in units.values())
    assert devs == [0, 1]
    # accounting matches placement
    assert sorted(d.used_bytes for d in q.devices) == [128, 128]


def test_qunitmulti_over_allocation_rejected():
    """A subsystem no device can hold triggers the alloc guard
    (reference: src/common/oclengine.cpp:388); QUnit's machinery then
    either fails fast (fidelity guard active) or degrades to ACE
    elision instead of letting the runtime OOM (reference: README
    ACE-on-bad_alloc behavior)."""
    from qrack_tpu.layers.qunitmulti import DeviceInfo

    def build():
        table = [DeviceInfo(device_id=0, capacity_bytes=128),
                 DeviceInfo(device_id=1, capacity_bytes=128)]
        q = QUnitMulti(6, unit_factory=_rec_factory, rng=QrackRandom(6),
                       device_table=table, rand_global_phase=False)
        q.H(0); q.CNOT(0, 1); q.CNOT(1, 2)
        return q

    # guard active: entangling across clumps would need a 4-qubit unit
    # (256 bytes) exceeding every per-device budget -> fail fast
    q = build()
    with pytest.raises(RuntimeError, match="ACE"):
        q.CNOT(2, 3)

    # guard disabled: same pressure degrades to ACE elision, fidelity
    # drops below 1 but the program keeps running
    q2 = build()
    q2.is_ace = True
    q2.CNOT(2, 3)
    assert q2.GetUnitaryFidelity() < 1.0


def test_qunitmulti_weighted_preference():
    """Capability weights steer placement: the heavier device gets the
    bigger subsystem when both fit everywhere."""
    from qrack_tpu.layers.qunitmulti import DeviceInfo

    table = [DeviceInfo(device_id=0, capacity_bytes=1 << 20, weight=1.0),
             DeviceInfo(device_id=1, capacity_bytes=1 << 20, weight=4.0)]
    q = QUnitMulti(5, unit_factory=_rec_factory, rng=QrackRandom(7),
                   device_table=table, rand_global_phase=False)
    # FSim is non-diagonal 2-qubit: forces real unit merges (CNOT chains
    # alone stay in the commuting link bag and never materialize units)
    q.FSim(0.3, 0.2, 0, 1); q.FSim(0.3, 0.2, 1, 2)   # 3-qubit clump
    q.FSim(0.3, 0.2, 3, 4)                            # 2-qubit clump
    units = {id(s.unit): s.unit for s in q.shards if s.unit is not None}
    sizes = {u.qubit_count: u.device_id for u in units.values()}
    assert sizes[3] == 1     # biggest subsystem -> most capable device
    assert sizes[2] == 0     # next one spreads to the other device


def test_qunitmulti_unguarded_spread_and_warning():
    """Unguarded devices (capacity 0) warn once and still SPREAD fresh
    units by accounted bytes instead of piling onto device 0 (ADVICE r4:
    the inf-free_bytes tie always picked the first device)."""
    import warnings as _w

    from qrack_tpu.layers.qunitmulti import DeviceInfo

    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        QUnitMulti._build_device_table([0, 1])  # no env budget -> unguarded
    assert any("allocation guard is DISABLED" in str(r.message) for r in rec)

    table = [DeviceInfo(device_id=0, capacity_bytes=0),
             DeviceInfo(device_id=1, capacity_bytes=0)]
    q = QUnitMulti(4, unit_factory=_rec_factory, rng=QrackRandom(8),
                   device_table=table, rand_global_phase=False)
    q.FSim(0.3, 0.2, 0, 1)   # first 2-qubit unit
    q.FSim(0.3, 0.2, 2, 3)   # second unit must land on the OTHER device
    units = {id(s.unit): s.unit for s in q.shards if s.unit is not None}
    assert sorted(u.device_id for u in units.values()) == [0, 1]


def test_qunitmulti_measured_weights():
    """MeasureDeviceWeights derives capability from a live throughput
    probe; on one device class the weights stay ~uniform (documents the
    single-chip-class restriction of the default table)."""
    from qrack_tpu.layers.qunitmulti import DeviceInfo

    table = [DeviceInfo(device_id=0, capacity_bytes=1 << 20)]
    q = QUnitMulti(3, unit_factory=_rec_factory, rng=QrackRandom(9),
                   device_table=table, rand_global_phase=False)
    q.MeasureDeviceWeights(size=128, reps=2)
    assert q.devices[0].weight == 1.0   # fastest device normalizes to 1


def test_qunitmulti_weights_env_forms(monkeypatch):
    """QRACK_QUNITMULTI_WEIGHTS parses both the positional form (k-th
    token -> k-th SELECTED device) and the id=weight pair form (keyed by
    device id, robust to QRACK_QUNITMULTI_DEVICES reordering); mixing
    the two is rejected."""
    # positional: tokens follow the SELECTION order, not the device id
    assert QUnitMulti._parse_weights("1.0,4.0") == ([1.0, 4.0], None)
    # id=weight pairs: keyed by device id, unlisted ids default later
    assert QUnitMulti._parse_weights("0=1.0,3=4.0") == ([], {0: 1.0, 3: 4.0})
    assert QUnitMulti._parse_weights("") == ([], None)
    with pytest.raises(ValueError, match="mixes positional"):
        QUnitMulti._parse_weights("1.0,3=4.0")

    monkeypatch.setenv("QRACK_QUNITMULTI_DEVICES", "")
    monkeypatch.setenv("QRACK_QUNITMULTI_MAX_QB", "20")
    # pair form applies by id even when the selection reorders ids
    monkeypatch.setenv("QRACK_QUNITMULTI_WEIGHTS", "2=8.0,0=2.0")
    table = QUnitMulti._build_device_table([2, 0, 1])
    by_id = {d.device_id: d.weight for d in table}
    assert by_id == {2: 8.0, 0: 2.0, 1: 1.0}
    # positional form applies by selection position
    monkeypatch.setenv("QRACK_QUNITMULTI_WEIGHTS", "8.0,2.0")
    table = QUnitMulti._build_device_table([2, 0, 1])
    by_pos = [d.weight for d in table]
    assert by_pos == [8.0, 2.0, 1.0]
