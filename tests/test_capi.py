"""Flat registry API (pinvoke-surface parity)."""

import math

import numpy as np
import pytest

from qrack_tpu import capi
from qrack_tpu.pauli import Pauli


def test_lifecycle_and_gates():
    sid = capi.init_count_type(3, sd=True, sh=True, hy=False, pg=False, oc=False)
    capi.seed(sid, 42)
    assert capi.num_qubits(sid) == 3
    capi.H(sid, 0)
    capi.MCX(sid, [0], 1)
    capi.MCX(sid, [1], 2)
    assert capi.Prob(sid, 2) == pytest.approx(0.5, abs=1e-6)
    shots = capi.MeasureShots(sid, [0, 1, 2], 200)
    assert set(shots) <= {0, 7}
    r = capi.MAll(sid)
    assert r in (0, 7)
    cid = capi.init_clone(sid)
    assert capi.MAll(cid) == r
    capi.destroy(cid)
    capi.destroy(sid)


def test_pauli_measure_and_expectation():
    sid = capi.init_count_type(2, hy=False, pg=False, oc=False)
    capi.seed(sid, 7)
    capi.H(sid, 0)
    capi.MCX(sid, [0], 1)
    # <ZZ> on a Bell state: parity always even
    p = capi.JointEnsembleProbability(sid, [Pauli.PauliZ, Pauli.PauliZ], [0, 1])
    assert p == pytest.approx(0.0, abs=1e-9)
    assert capi.Measure(sid, [Pauli.PauliZ, Pauli.PauliZ], [0, 1]) is False
    capi.ResetAll(sid)
    capi.H(sid, 0)
    assert capi.PermutationExpectation(sid, [0]) == pytest.approx(0.5, abs=1e-6)
    capi.destroy(sid)


def test_compose_decompose_registry():
    a = capi.init_count_type(2, hy=False, pg=False, oc=False)
    b = capi.init_count_type(1, hy=False, pg=False, oc=False)
    capi.X(b, 0)
    capi.H(a, 0)
    start = capi.Compose(a, b)
    assert start == 2 and capi.num_qubits(a) == 3
    assert capi.Prob(a, 2) == pytest.approx(1.0)
    nid = capi.Decompose(a, 2, 1)
    assert capi.num_qubits(a) == 2
    assert capi.Prob(nid, 0) == pytest.approx(1.0)
    capi.destroy(a)
    capi.destroy(b)
    capi.destroy(nid)


def test_alu_and_state_io():
    sid = capi.init_count_type(6, hy=False, pg=False, oc=False)
    capi.seed(sid, 9)
    capi.ADD(sid, 5, 0, 4)
    assert capi.MAll(sid) == 5
    capi.ResetAll(sid)
    capi.H(sid, 0)
    ket = capi.OutKet(sid)
    assert abs(ket[0]) == pytest.approx(1 / math.sqrt(2), abs=1e-3)
    capi.InKet(sid, np.eye(1, 64, 3).ravel())
    assert capi.MAll(sid) == 3
    capi.destroy(sid)


def test_mcr_multi_control_and_identity_basis():
    # regression: all controls honored; PauliI is a controlled global phase
    sid = capi.init_count_type(3, hy=False, pg=False, oc=False)
    capi.seed(sid, 3)
    capi.X(sid, 0)  # only control 0 set; control 1 stays |0>
    capi.MCR(sid, Pauli.PauliX, math.pi, [0, 1], 2)
    assert capi.Prob(sid, 2) == pytest.approx(0.0, abs=1e-9)
    capi.X(sid, 1)
    capi.MCR(sid, Pauli.PauliX, math.pi, [0, 1], 2)
    assert capi.Prob(sid, 2) == pytest.approx(1.0, abs=1e-9)
    capi.destroy(sid)


def test_measure_shots_ordering():
    sid = capi.init_count_type(2, hy=False, pg=False, oc=False)
    capi.seed(sid, 11)
    capi.H(sid, 0)
    capi.MCX(sid, [0], 1)
    shots = capi.MeasureShots(sid, [0, 1], 200)
    # Bell: half 0, half 3 — and the list must be interleaved, not grouped
    first_half = shots[:100]
    assert 10 < sum(1 for s in first_half if s == 0) < 90
    capi.destroy(sid)
