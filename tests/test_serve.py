"""Serving subsystem: sessions, scheduler admission control, shape-
bucketed batch execution, and breaker-aware load shedding + failover.

Every test restores the global resilience/telemetry/batch-program
state (fixture below) so the rest of the suite runs with serving and
resilience disabled — the default off-path the <2% bench criterion is
measured on.
"""

import threading
import time

import numpy as np
import pytest

from qrack_tpu import QEngineCPU
from qrack_tpu import resilience as res
from qrack_tpu import telemetry as tele
from qrack_tpu.models.qft import qft_qcircuit
from qrack_tpu.resilience import faults
from qrack_tpu.resilience.breaker import CircuitBreaker
from qrack_tpu.serve import (LoadShed, QrackService, QueueBudgetExceeded,
                             QueueFull, ServiceStopped, SessionNotFound)
from qrack_tpu.serve import batcher
from qrack_tpu.utils.rng import QrackRandom

W = 6  # test width: big enough to batch, small enough to stay fast


@pytest.fixture(autouse=True)
def _clean_serve():
    faults.clear()
    res.reset_breaker()
    res.configure(max_retries=2, backoff_s=0.0, timeout_s=0.0)
    batcher.clear_programs()
    yield
    faults.clear()
    res.reset_breaker()
    res.configure()
    res.disable()
    tele.disable()
    tele.reset()
    batcher.clear_programs()


def _fidelity(a, b) -> float:
    a, b = np.asarray(a), np.asarray(b)
    return abs(np.vdot(a, b)) ** 2 / (np.vdot(a, a).real
                                      * np.vdot(b, b).real)


def _svc(**kw) -> QrackService:
    kw.setdefault("batch_window_ms", 5.0)
    kw.setdefault("queue_budget_ms", 60_000.0)
    kw.setdefault("tick_s", 0.02)
    return QrackService(**kw)


# ---------------------------------------------------------------------------
# tier-1 smoke: 8 concurrent CPU-engine sessions, full scheduler path
# ---------------------------------------------------------------------------

def test_eight_concurrent_cpu_sessions_match_oracles():
    with _svc(engine_layers="cpu") as svc:
        sids = [svc.create_session(W, seed=k, rand_global_phase=False)
                for k in range(8)]
        errors, states = [], {}

        def tenant(k: int, sid: str):
            try:
                svc.call(sid, lambda eng, k=k: eng.X(k % W)).result(30)
                svc.apply(sid, qft_qcircuit(W), timeout=60)
                states[k] = svc.get_state(sid, timeout=60)
            except BaseException as e:  # noqa: BLE001
                errors.append((k, e))

        threads = [threading.Thread(target=tenant, args=(k, sid))
                   for k, sid in enumerate(sids)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(90)
        assert not errors, errors
        for k in range(8):
            oracle = QEngineCPU(W, rng=QrackRandom(k),
                                rand_global_phase=False)
            oracle.X(k % W)
            qft_qcircuit(W).Run(oracle)
            assert _fidelity(oracle.GetQuantumState(),
                             states[k]) > 1 - 1e-6


# ---------------------------------------------------------------------------
# batching
# ---------------------------------------------------------------------------

def test_same_shape_jobs_from_different_tenants_cobatch():
    tele.enable()
    tele.reset()
    with _svc(engine_layers="tpu", batch_window_ms=500.0,
              max_batch=4) as svc:
        sids = [svc.create_session(W, seed=k, rand_global_phase=False)
                for k in range(4)]
        handles = [svc.submit(sid, qft_qcircuit(W)) for sid in sids]
        for h in handles:
            h.result(60)
        states = [svc.get_state(sid, timeout=60) for sid in sids]
    snap = tele.snapshot()
    # all four landed in ONE vmapped dispatch of one compiled program
    assert snap["counters"]["serve.batch.dispatches"] == 1
    assert snap["counters"]["serve.batch.jobs"] == 4
    assert snap["counters"]["compile.serve_batch.miss"] == 1
    oracle = QEngineCPU(W, rng=QrackRandom(0), rand_global_phase=False)
    qft_qcircuit(W).Run(oracle)
    expect = np.asarray(oracle.GetQuantumState())
    for st in states:
        assert _fidelity(expect, st) > 1 - 1e-6


def test_program_cache_reused_across_sessions():
    """Satellite: two sessions, identical circuit shape -> exactly one
    compile (miss) and one cache hit, even submitted sequentially."""
    tele.enable()
    tele.reset()
    with _svc(engine_layers="tpu") as svc:
        s1 = svc.create_session(W, seed=1)
        s2 = svc.create_session(W, seed=2)
        svc.apply(s1, qft_qcircuit(W), timeout=60)   # B=1 batch: compiles
        svc.apply(s2, qft_qcircuit(W), timeout=60)   # fresh object, same
        # digest, same B -> must reuse the program, not recompile
    snap = tele.snapshot()
    assert snap["counters"]["compile.serve_batch.miss"] == 1
    assert snap["counters"]["compile.serve_batch.hit"] == 1


def test_cobatching_never_reorders_a_tenants_stream():
    """Regression (caught by scripts/serve_soak.py): the batcher must
    not steal a session's LATER circuit into a batch while an EARLIER
    job of the same session is still queued."""
    gate = threading.Event()
    with _svc(engine_layers="tpu", batch_window_ms=50.0,
              max_batch=2) as svc:
        blocker = svc.create_session(W, seed=9)
        s1 = svc.create_session(W, seed=1, rand_global_phase=False)
        s2 = svc.create_session(W, seed=2, rand_global_phase=False)
        # park the executor so the next three jobs queue up together
        hold = svc.call(blocker, lambda eng: gate.wait(10))
        time.sleep(0.1)
        h1 = svc.submit(s1, qft_qcircuit(W))                 # batchable
        h2a = svc.call(s2, lambda eng: eng.X(0))             # earlier s2 job
        h2b = svc.submit(s2, qft_qcircuit(W))                # same shape
        gate.set()
        for h in (hold, h1, h2a, h2b):
            h.result(60)
        state = svc.get_state(s2, timeout=60)
    oracle = QEngineCPU(W, rng=QrackRandom(2), rand_global_phase=False)
    oracle.X(0)
    qft_qcircuit(W).Run(oracle)   # X BEFORE the QFT, as submitted
    assert _fidelity(oracle.GetQuantumState(), state) > 1 - 1e-6


# ---------------------------------------------------------------------------
# admission control / backpressure
# ---------------------------------------------------------------------------

def test_queue_full_is_typed_and_synchronous():
    gate = threading.Event()
    with _svc(engine_layers="cpu", max_depth=2) as svc:
        sid = svc.create_session(W, seed=0)
        hold = svc.call(sid, lambda eng: gate.wait(10))
        time.sleep(0.1)  # executor now parked on `hold`, queue empty
        keep = [svc.call(sid, lambda eng: None) for _ in range(2)]
        with pytest.raises(QueueFull):
            svc.call(sid, lambda eng: None)
        gate.set()
        for h in [hold] + keep:
            h.result(30)


def test_priority_orders_dispatch():
    gate = threading.Event()
    order = []
    with _svc(engine_layers="cpu", max_depth=16) as svc:
        s1 = svc.create_session(W, seed=1)
        s2 = svc.create_session(W, seed=2)
        blocker = svc.create_session(W, seed=3)
        hold = svc.call(blocker, lambda eng: gate.wait(10))
        time.sleep(0.1)
        lo = svc.call(s1, lambda eng: order.append("lo"), priority=0)
        hi = svc.call(s2, lambda eng: order.append("hi"), priority=5)
        gate.set()
        for h in (hold, lo, hi):
            h.result(30)
    assert order == ["hi", "lo"]


def test_queue_budget_expires_stale_jobs():
    gate = threading.Event()
    with _svc(engine_layers="cpu", queue_budget_ms=50.0) as svc:
        sid = svc.create_session(W, seed=0)
        hold = svc.call(sid, lambda eng: gate.wait(10))
        time.sleep(0.1)
        stale = svc.call(sid, lambda eng: None)
        time.sleep(0.2)   # exceed the 50ms budget while queued
        gate.set()
        hold.result(30)
        with pytest.raises(QueueBudgetExceeded):
            stale.result(30)


def test_session_lifecycle_errors():
    with _svc(engine_layers="cpu") as svc:
        with pytest.raises(SessionNotFound):
            svc.submit("s999999", qft_qcircuit(W))
        sid = svc.create_session(W, seed=0)
        svc.destroy_session(sid)
        with pytest.raises(SessionNotFound):
            svc.submit(sid, qft_qcircuit(W))


def test_stop_drains_queued_jobs_typed():
    gate = threading.Event()
    svc = _svc(engine_layers="cpu")
    sid = svc.create_session(W, seed=0)
    hold = svc.call(sid, lambda eng: gate.wait(10))
    time.sleep(0.1)
    queued = svc.call(sid, lambda eng: None)
    svc.close()
    gate.set()
    with pytest.raises(ServiceStopped):
        queued.result(30)
    with pytest.raises(ServiceStopped):
        svc.call(sid, lambda eng: None)
    hold.result(30)


def test_idle_sessions_evicted():
    with _svc(engine_layers="cpu", idle_evict_s=0.05, tick_s=0.02) as svc:
        sid = svc.create_session(W, seed=0)
        assert sid in svc.sessions.ids()
        deadline = time.monotonic() + 5.0
        while sid in svc.sessions.ids() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert sid not in svc.sessions.ids()


# ---------------------------------------------------------------------------
# load shedding + failover (the acceptance flow)
# ---------------------------------------------------------------------------

def test_breaker_open_sheds_tunnel_jobs_and_failover_recovers():
    res.reset_breaker(CircuitBreaker(threshold=2, cooldown_s=60.0))
    with _svc(engine_layers="tpu") as svc:
        hurt = svc.create_session(W, seed=1, rand_global_phase=False)
        bystander = svc.create_session(W, seed=2, rand_global_phase=False)
        faults.inject("serve.dispatch", "raise", times=None)  # persistent
        # in-flight job: dispatch fails past retry, breaker trips, the
        # session fails over down the chain and the job replays there
        h = svc.submit(hurt, qft_qcircuit(W))
        h.result(60)
        assert res.get_breaker().snapshot()["state"] == "open"
        stats = {s["sid"]: s for s in svc.sessions.stats()}
        assert stats[hurt]["failovers"] >= 1
        assert stats[hurt]["engine"] == "QEngineCPU"
        # new tunnel-bound work is refused with the typed error + hint
        with pytest.raises(LoadShed) as exc:
            svc.submit(bystander, qft_qcircuit(W))
        assert exc.value.retry_in_s > 0
        # the failed-over (now CPU-backed) session keeps being served
        svc.apply(hurt, qft_qcircuit(W), timeout=60)
        state = svc.get_state(hurt, timeout=60)
    oracle = QEngineCPU(W, rng=QrackRandom(1), rand_global_phase=False)
    qft_qcircuit(W).Run(oracle)
    qft_qcircuit(W).Run(oracle)
    assert _fidelity(oracle.GetQuantumState(), state) > 1 - 1e-6


def test_sync_failure_failover_does_not_double_apply():
    """Regression (caught by scripts/serve_soak.py): when the batch
    dispatch lands but the honest device_get sync escalates, the
    engines must be rolled back to pre-batch planes before the replay
    — otherwise the circuit applies twice."""
    res.reset_breaker(CircuitBreaker(threshold=100, cooldown_s=0.0))
    with _svc(engine_layers="tpu") as svc:
        sid = svc.create_session(W, seed=4, rand_global_phase=False)
        faults.inject("serve.device_get", "device-loss", times=None)
        svc.apply(sid, qft_qcircuit(W), timeout=60)
        faults.clear()
        state = svc.get_state(sid, timeout=60)
    oracle = QEngineCPU(W, rng=QrackRandom(4), rand_global_phase=False)
    qft_qcircuit(W).Run(oracle)
    assert _fidelity(oracle.GetQuantumState(), state) > 1 - 1e-6


# ---------------------------------------------------------------------------
# elastic capacity: degraded serving + drain/adopt (docs/ELASTICITY.md)
# ---------------------------------------------------------------------------

def test_elastic_pager_serves_degraded_then_reexpands(monkeypatch):
    """Acceptance flow: a pager session loses its exchange collective
    mid-serve, re-pages down the elastic staircase, KEEPS serving jobs
    degraded, and grows back to its construction page count at the
    first job boundary after the device heals — all telemetry-visible."""
    # window=1 disables the fuser: gates dispatch eagerly inside the
    # call job, so the injected exchange loss fires while serving
    monkeypatch.setenv("QRACK_TPU_FUSE_WINDOW", "1")
    tele.enable()
    tele.reset()
    Wp = 5
    with _svc(engine_layers="pager", n_pages=4) as svc:
        sid = svc.create_session(Wp, seed=3, rand_global_phase=False)
        svc.call(sid, lambda e: e.H(0)).result(60)    # healthy, 4 pages
        faults.inject("pager.exchange", "device-loss", times=None)
        # qubit 4 is global at 4 AND 2 pages, local at 1: the staircase
        # descends 4 -> 2 -> 1 and the replay lands on the single page
        svc.call(sid, lambda e: e.H(4)).result(60)
        # the degraded pager demonstrably serves jobs at reduced pages
        # (the pre-job recovery probe sees the loss window still open)
        info = svc.call(sid, lambda e: (e.n_pages,
                                        bool(e.elastic_degraded))).result(60)
        assert info == (1, True), info
        svc.call(sid, lambda e: e.CNOT(0, 1)).result(60)
        svc.call(sid, lambda e: e.T(1)).result(60)
        # device heals -> the next job boundary re-expands BEFORE the
        # job runs, so the same job observes the recovered topology
        faults.clear()
        info = svc.call(sid, lambda e: (e.n_pages,
                                        bool(e.elastic_degraded))).result(60)
        assert info == (4, False), info
        state = svc.get_state(sid, timeout=60)
    snap = tele.snapshot()
    assert snap["counters"]["elastic.repage.shrink"] == 2
    assert snap["counters"]["elastic.repage.expand"] == 1
    assert snap["gauges"]["elastic.pages"] == 4
    oracle = QEngineCPU(Wp, rng=QrackRandom(3), rand_global_phase=False)
    oracle.H(0)
    oracle.H(4)
    oracle.CNOT(0, 1)
    oracle.T(1)
    assert _fidelity(oracle.GetQuantumState(), state) > 1 - 1e-6


def test_drain_handoff_adopted_by_second_service(tmp_path):
    """drain() checkpoints idle sessions, disowns them, and releases
    the recovery lease; a peer sharing the store adopts the set with
    recover=True and serves the exact handed-over state."""
    ck = str(tmp_path / "ck")
    a = _svc(engine_layers="cpu", checkpoint_dir=ck)
    try:
        sid = a.create_session(W, seed=5, rand_global_phase=False)
        a.apply(sid, qft_qcircuit(W), timeout=60)
        assert a.stats()["lease"]["held"]
        out = a.drain()
        assert out == {"drained": [sid], "busy": []}
        assert sid not in a.sessions.ids()
        assert not a.lease_held
        with pytest.raises(SessionNotFound):
            a.get_state(sid, timeout=60)
        # the adopter: drain released the lease, so recover is admitted
        with _svc(engine_layers="cpu", checkpoint_dir=ck,
                  recover=True) as b:
            assert b.lease_held
            assert [s["sid"] for s in b.stats()["sessions"]] == [sid]
            state = b.get_state(sid, timeout=60)
    finally:
        a.close()
    oracle = QEngineCPU(W, rng=QrackRandom(5), rand_global_phase=False)
    qft_qcircuit(W).Run(oracle)
    assert _fidelity(oracle.GetQuantumState(), state) > 1 - 1e-6


def test_recover_refused_while_peer_holds_lease(tmp_path):
    """Two processes must never both replay the same WAL: while a live
    peer holds the store lease, recover=True fails with the typed
    error (and leaks no executor thread); after drain it is admitted."""
    from qrack_tpu.checkpoint import StoreLeaseHeld

    ck = str(tmp_path / "ck")
    with _svc(engine_layers="cpu", checkpoint_dir=ck) as a:
        sid = a.create_session(W, seed=1)
        with pytest.raises(StoreLeaseHeld) as exc:
            _svc(engine_layers="cpu", checkpoint_dir=ck, recover=True)
        assert "drain or stop" in str(exc.value)
        # the holder keeps serving; handing over unblocks the adopter
        assert a.drain() == {"drained": [sid], "busy": []}
        with _svc(engine_layers="cpu", checkpoint_dir=ck,
                  recover=True) as b:
            assert sid in b.sessions.ids()


# ---------------------------------------------------------------------------
# fault-spec parse-time validation (satellite)
# ---------------------------------------------------------------------------

def test_fault_spec_unknown_site_rejected_listing_valid():
    with pytest.raises(ValueError) as exc:
        faults.parse_spec("sreve.dispatch:raise:0")   # typo'd site
    msg = str(exc.value)
    assert "serve.dispatch" in msg and "tpu.compile" in msg
    with pytest.raises(ValueError):
        faults.load_env("serve.dispatch:raise:0,bogus.site:raise:0")
    assert faults.parse_spec("serve.dispatch:raise:0").site == "serve.dispatch"
    assert faults.parse_spec("serve.device_get:timeout:1+").times is None


def test_fault_spec_bad_counts_rejected_with_grammar():
    with pytest.raises(ValueError) as exc:
        faults.parse_spec("serve.dispatch:raise:soon")
    assert "after_n" in str(exc.value)
    with pytest.raises(ValueError):
        faults.parse_spec("serve.dispatch:raise:0:notaseed")


# ---------------------------------------------------------------------------
# randomized soak (short slice; the full run is scripts/serve_soak.py)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_serve_soak_smoke():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "serve_soak", os.path.join(os.path.dirname(__file__),
                                   "..", "scripts", "serve_soak.py"))
    soak = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(soak)
    results = [soak.run_trial(t, seed=123) for t in range(9)]
    bad = [r for r in results if not r["ok"]]
    assert not bad, bad


@pytest.mark.slow
def test_elastic_soak_smoke():
    """3-trial slice of scripts/elastic_soak.py: two in-process
    device-loss/flap trials (fusion windows 1 and 16) plus one kill -9
    two-process handoff trial."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "elastic_soak", os.path.join(os.path.dirname(__file__),
                                     "..", "scripts", "elastic_soak.py"))
    soak = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(soak)
    results = [soak.run_trial(t, seed=7) for t in range(3)]
    bad = [r for r in results if not r["ok"]]
    assert not bad, bad
