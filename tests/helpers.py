"""Independent brute-force oracle utilities for conformance tests.

Deliberately implemented with explicit index loops (not the engine's
vectorized index algebra) so engine bugs can't hide in shared code.
"""

from __future__ import annotations

import numpy as np


def full_unitary(n: int, m: np.ndarray, qubits) -> np.ndarray:
    """Expand unitary `m` over `qubits` (qubits[0] = LSB of m's index) to
    the full 2^n space. O(4^n) — test-size only."""
    k = len(qubits)
    dim = 1 << n
    u = np.zeros((dim, dim), dtype=np.complex128)
    for i in range(dim):
        sub = 0
        for j, q in enumerate(qubits):
            sub |= ((i >> q) & 1) << j
        base = i
        for q in qubits:
            base &= ~(1 << q)
        for sub2 in range(1 << k):
            i2 = base
            for j, q in enumerate(qubits):
                i2 |= ((sub2 >> j) & 1) << q
            u[i2, i] += m[sub2, sub]
    return u


def controlled(m: np.ndarray, n_controls: int, perm: int = None) -> np.ndarray:
    """Controlled expansion: m on target (LSB), controls above it."""
    if perm is None:
        perm = (1 << n_controls) - 1
    dim = 2 << n_controls
    u = np.eye(dim, dtype=np.complex128)
    # target = bit 0, controls = bits 1..n_controls
    for t in (0, 1):
        for t2 in (0, 1):
            u[(perm << 1) | t2, (perm << 1) | t] = m[t2, t]
    return u


def rand_state(n: int, seed: int) -> np.ndarray:
    g = np.random.Generator(np.random.PCG64(seed))
    v = g.normal(size=1 << n) + 1j * g.normal(size=1 << n)
    return (v / np.linalg.norm(v)).astype(np.complex128)
