"""Examples parity: the reference's teaching programs re-expressed
through this framework (reference: examples/*.cpp — ordered_list_search,
grovers_lookup, pearson32, quantum_perceptron,
quantum_associative_memory, cosmology, separability demos)."""

import numpy as np
import pytest

from qrack_tpu import QEngineCPU
from qrack_tpu.layers.qunit import QUnit
from qrack_tpu.models import algorithms as alg
from qrack_tpu.utils.rng import QrackRandom


def cpu_factory(n, **kw):
    kw.setdefault("rand_global_phase", False)
    kw.setdefault("rng", QrackRandom(7))
    return QEngineCPU(n, **kw)


def test_grover_lookup_search():
    idx_len, val_len = 4, 3
    values = [2] * (1 << idx_len)
    values[11] = 6
    q = cpu_factory(idx_len + val_len)
    got = alg.grover_lookup_search(q, values, 6, idx_len, val_len)
    assert got == 11


def test_ordered_list_search():
    idx_len, val_len = 5, 4
    target_key, target_value = 13, 6
    values = ([2] * target_key + [target_value]
              + [9] * ((1 << idx_len) - target_key - 1))
    q = cpu_factory(idx_len + val_len)
    got = alg.ordered_list_search(q, values, target_value, idx_len, val_len)
    assert got == target_key


def test_pearson_hash_demo():
    key_len = 4
    table = list(np.random.RandomState(3).permutation(1 << key_len))
    q = cpu_factory(key_len)
    shots = alg.pearson_hash_demo(q, table, key_len)
    # unitary hash of a uniform superposition stays uniform over outputs
    assert sum(shots.values()) == 64
    assert set(shots) <= set(range(1 << key_len))


def test_quantum_perceptron_learns_not():
    q = cpu_factory(2)
    acc = alg.quantum_perceptron(q, 0, 1)
    assert acc == 1.0


def test_quantum_associative_memory_recalls():
    q = cpu_factory(3)
    patterns = [(0b00, False), (0b01, True), (0b10, True), (0b11, False)]
    acc = alg.quantum_associative_memory(q, patterns, 2, 2)
    assert acc == 1.0


def test_cosmology_inflation_grows():
    widths = alg.cosmology_inflation(cpu_factory, 6, QrackRandom(5))
    assert widths == list(range(1, 8))


def test_separability_demo_on_qunit():
    q = QUnit(4, unit_factory=cpu_factory, rng=QrackRandom(2),
              rand_global_phase=False)
    out = alg.separability_demo(q)
    assert out["separable"]
    assert out["final_units"] == 4
