"""Coverage sweep of the wider QInterface surface: TimeEvolve, dyadic
rotations, register-spanning gates, factored expectations, RDM, QFTR —
metamorphic and oracle-compared (reference model: test/tests.cpp's
per-gate and register families)."""

import cmath
import math

import numpy as np
import pytest

from qrack_tpu import QEngineCPU, HamiltonianOp, uniform_hamiltonian_op
from qrack_tpu import matrices as mat
from qrack_tpu.utils.rng import QrackRandom

from helpers import rand_state


def make(n, seed=1):
    return QEngineCPU(n, rng=QrackRandom(seed), rand_global_phase=False)


def test_time_evolve_matches_expm():
    # single-term Hamiltonian: e^{-iHt} on qubit 1
    h_term = 0.7 * np.asarray(mat.X2) + 0.3 * np.asarray(mat.Z2)
    t = 0.9
    q = make(2)
    psi = rand_state(2, 5)
    q.SetQuantumState(psi)
    q.TimeEvolve([HamiltonianOp(target=1, matrix=h_term)], t)
    u = mat.exp_mtrx(-1j * t * h_term)
    expect = np.kron(u, np.eye(2)) @ psi  # qubit 1 is the high bit
    np.testing.assert_allclose(q.GetQuantumState(), expect, atol=1e-10)


def test_time_evolve_controlled_and_uniform():
    h_term = 0.5 * np.asarray(mat.Y2)
    t = 0.4
    q = make(2, seed=3)
    psi = rand_state(2, 7)
    q.SetQuantumState(psi)
    q.TimeEvolve([HamiltonianOp(target=0, matrix=h_term, controls=(1,))], t)
    u = mat.exp_mtrx(-1j * t * h_term)
    full = np.eye(4, dtype=np.complex128)
    full[2:, 2:] = u  # control qubit 1 set
    np.testing.assert_allclose(q.GetQuantumState(), full @ psi, atol=1e-10)
    # uniform: one generator per control permutation
    q2 = make(2, seed=4)
    q2.SetQuantumState(psi)
    op = uniform_hamiltonian_op((1,), 0, np.stack([0.2 * mat.X2, 0.6 * mat.Z2]))
    q2.TimeEvolve([op], t)
    u0 = mat.exp_mtrx(-1j * t * 0.2 * np.asarray(mat.X2))
    u1 = mat.exp_mtrx(-1j * t * 0.6 * np.asarray(mat.Z2))
    full2 = np.zeros((4, 4), dtype=np.complex128)
    full2[:2, :2] = u0
    full2[2:, 2:] = u1
    np.testing.assert_allclose(q2.GetQuantumState(), full2 @ psi, atol=1e-10)


def test_dyadic_rotations_match_radian_forms():
    # dyadAngle = -2*pi*num / 2^denomPower (reference qinterface.cpp:1310)
    q1, q2 = make(1), make(1)
    for eng in (q1, q2):
        eng.H(0)
    q1.RZDyad(3, 4, 0)
    q2.RZ((-math.pi * 3 * 2) / 16, 0)
    np.testing.assert_allclose(q1.GetQuantumState(), q2.GetQuantumState(), atol=1e-12)
    q3, q4 = make(1), make(1)
    q3.ExpXDyad(1, 2, 0)
    q4.ExpX((-math.pi * 2) / 4, 0)
    np.testing.assert_allclose(q3.GetQuantumState(), q4.GetQuantumState(), atol=1e-12)


def test_exp_family_inverses():
    psi = rand_state(2, 9)
    q = make(2)
    q.SetQuantumState(psi)
    q.ExpX(0.7, 0)
    q.ExpX(-0.7, 0)
    q.ExpY(0.4, 1)
    q.ExpY(-0.4, 1)
    q.ExpZ(1.1, 0)
    q.ExpZ(-1.1, 0)
    q.Exp(0.3, 1)
    q.Exp(-0.3, 1)
    np.testing.assert_allclose(q.GetQuantumState(), psi, atol=1e-10)


def test_exp_mtrx_controlled():
    psi = rand_state(2, 11)
    q = make(2)
    q.SetQuantumState(psi)
    g = 0.5 * np.asarray(mat.X2)
    q.ExpMtrx((1,), 0, g)
    u = mat.exp_mtrx(1j * g)
    full = np.eye(4, dtype=np.complex128)
    full[2:, 2:] = u
    np.testing.assert_allclose(q.GetQuantumState(), full @ psi, atol=1e-10)


def test_register_gates_match_loops():
    n = 4
    a, b = make(n), make(n)
    psi = rand_state(n, 13)
    a.SetQuantumState(psi)
    b.SetQuantumState(psi)
    a.HReg(1, 3)
    for i in range(1, 4):
        b.H(i)
    a.CNOTReg(0, 2, 2)
    for i in range(2):
        b.CNOT(i, 2 + i)
    a.RZReg(0.7, 0, 2)
    for i in range(2):
        b.RZ(0.7, i)
    a.SwapReg(0, 2, 2)
    for i in range(2):
        b.Swap(i, 2 + i)
    np.testing.assert_allclose(a.GetQuantumState(), b.GetQuantumState(), atol=1e-10)


def test_qftr_arbitrary_order_roundtrip():
    n = 4
    psi = rand_state(n, 15)
    q = make(n)
    q.SetQuantumState(psi)
    order = [2, 0, 3, 1]
    q.QFTR(order)
    q.IQFTR(order)
    np.testing.assert_allclose(q.GetQuantumState(), psi, atol=1e-8)


def test_rol_ror_inverse_on_superposition():
    n = 5
    psi = rand_state(n, 17)
    q = make(n)
    q.SetQuantumState(psi)
    q.ROL(2, 1, 4)
    q.ROR(2, 1, 4)
    np.testing.assert_allclose(q.GetQuantumState(), psi, atol=1e-10)


def test_factored_expectations():
    n = 3
    psi = rand_state(n, 19)
    q = make(n)
    q.SetQuantumState(psi)
    probs = np.abs(psi) ** 2
    # integer weights: value = sum_j perms[2j + bit_j]
    perms = [5, 11, 2, 7, 0, 3]
    expect = 0.0
    for i in range(8):
        v = sum(perms[2 * j + ((i >> j) & 1)] for j in range(3))
        expect += probs[i] * v
    assert q.ExpectationBitsFactorized([0, 1, 2], perms) == pytest.approx(expect, abs=1e-9)
    weights = [0.5, -1.5, 2.0, 0.25, -0.75, 1.0]
    expectf = 0.0
    for i in range(8):
        v = sum(weights[2 * j + ((i >> j) & 1)] for j in range(3))
        expectf += probs[i] * v
    assert q.ExpectationFloatsFactorized([0, 1, 2], weights) == pytest.approx(expectf, abs=1e-9)
    # variance forms agree with direct computation
    var = 0.0
    for i in range(8):
        v = sum(perms[2 * j + ((i >> j) & 1)] for j in range(3))
        var += probs[i] * (v - expect) ** 2
    assert q.VarianceBitsFactorized([0, 1, 2], perms) == pytest.approx(var, abs=1e-8)


def test_reduced_density_matrix():
    q = make(2)
    q.H(0)
    q.CNOT(0, 1)
    rho = q.GetReducedDensityMatrix([0])
    np.testing.assert_allclose(rho, np.eye(2) / 2, atol=1e-10)  # maximally mixed
    q2 = make(2)
    q2.H(0)
    rho2 = q2.GetReducedDensityMatrix([0])
    np.testing.assert_allclose(rho2, np.full((2, 2), 0.5), atol=1e-10)  # pure |+>
    # Rdm probability variants coincide with exact ones here
    assert q.ProbRdm(0) == q.Prob(0)
    assert q.ProbMaskRdm(False, 0b11, 0b11) == pytest.approx(q.ProbMask(0b11, 0b11))


def test_cprob_acprob():
    q = make(2)
    q.H(0)
    q.CNOT(0, 1)
    assert q.CProb(0, 1) == pytest.approx(1.0)   # P(q1=1 | q0=1)
    assert q.ACProb(0, 1) == pytest.approx(0.0)  # P(q1=1 | q0=0)


def test_phase_parity_and_masks():
    n = 3
    psi = rand_state(n, 21)
    a, b = make(n), make(n)
    a.SetQuantumState(psi)
    b.SetQuantumState(psi)
    a.ZMask(0b101)
    b.Z(0)
    b.Z(2)
    np.testing.assert_allclose(a.GetQuantumState(), b.GetQuantumState(), atol=1e-12)
    a.YMask(0b011)
    b.Y(0)
    b.Y(1)
    np.testing.assert_allclose(a.GetQuantumState(), b.GetQuantumState(), atol=1e-12)
    # PhaseParity forward/backward
    a.PhaseParity(0.8, 0b110)
    a.PhaseParity(-0.8, 0b110)
    np.testing.assert_allclose(a.GetQuantumState(), b.GetQuantumState(), atol=1e-10)


def test_depolarizing_channel_statistics():
    flips = 0
    rng = QrackRandom(23)
    for _ in range(300):
        q = QEngineCPU(1, rng=rng.spawn(), rand_global_phase=False)
        q.DepolarizingChannelWeak1Qb(0, 0.4)
        if q.Prob(0) > 0.5:
            flips += 1
    # X or Y applied with prob 2/3 * 0.3 = 0.2
    assert 30 < flips < 90


def test_lossy_roundtrip_through_stack():
    import tempfile

    from qrack_tpu import create_quantum_interface

    q = create_quantum_interface("optimal", 8, rng=QrackRandom(25),
                                 rand_global_phase=False)
    q.HReg(0, 8)
    for i in range(7):
        q.CNOT(i, i + 1)
        q.T(i)
    path = tempfile.mktemp()
    s0 = np.asarray(q.GetQuantumState())
    q.LossySaveStateVector(path)
    q.LossyLoadStateVector(path)
    s1 = np.asarray(q.GetQuantumState())
    assert abs(np.vdot(s0, s1)) ** 2 > 0.995


def test_expectation_pauli_unitary_layer_methods():
    """ExpectationPauliAll/VariancePauliAll/ExpectationUnitaryAll as
    QInterface methods (reference: include/qinterface.hpp:2688-2712),
    checked against dense linear algebra on a random state."""
    import numpy as np

    from qrack_tpu import QEngineCPU
    from qrack_tpu.pauli import Pauli
    from qrack_tpu.utils.rng import QrackRandom
    from helpers import rand_state

    n = 4
    q = QEngineCPU(n, rng=QrackRandom(3), rand_global_phase=False)
    st = rand_state(n, 55)
    q.SetQuantumState(st)

    X = np.array([[0, 1], [1, 0]], dtype=complex)
    Y = np.array([[0, -1j], [1j, 0]])
    Z = np.array([[1, 0], [0, -1]], dtype=complex)
    I = np.eye(2, dtype=complex)

    def dense_exp(ops_by_qubit):
        m = np.eye(1, dtype=complex)
        for qb in range(n):  # qubit 0 = LSB -> rightmost kron factor
            m = np.kron(ops_by_qubit.get(qb, I), m)
        return float(np.real(np.vdot(st, m @ st)))

    bits = [0, 2, 3]
    paulis = [Pauli.PauliX, Pauli.PauliY, Pauli.PauliZ]
    want = dense_exp(dict(zip(bits, (X, Y, Z))))
    got = q.ExpectationPauliAll(bits, paulis)
    assert abs(got - want) < 1e-8
    v = q.VariancePauliAll(bits, paulis)
    assert abs(v - (1.0 - want * want)) < 1e-8

    # unitary observable: U diag(+1,-1) U^dag per qubit
    rng = np.random.Generator(np.random.PCG64(9))
    a = rng.normal(size=(2, 2)) + 1j * rng.normal(size=(2, 2))
    u, _ = np.linalg.qr(a)
    obs = u @ np.diag([1.0, -1.0]) @ u.conj().T
    want_u = dense_exp({1: obs})
    got_u = q.ExpectationUnitaryAll([1], [u])
    assert abs(got_u - want_u) < 1e-8
    # state restored by the conjugation unwind
    np.testing.assert_allclose(q.GetQuantumState(), st, atol=1e-10)
