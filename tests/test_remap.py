"""Placement-table remapping on the 8-device virtual CPU mesh.

The communication-minimizing remap layer (parallel/pager.py placement
table + ops/fusion.py plan_remaps) must stay invisible to every
logical-level contract: state parity with the CPU oracle under the full
fuzz vocabulary, Swap/MetaSwap on any table, checkpoint round-trips
that carry a non-identity table, and elastic shrink mid-remapped-span.
The accounting tests pin the headline claim: ascending-gen-order
circuits (IQFT) ship exactly HALF the exchange bytes under the planner
(docs/PERFORMANCE.md derives why descending-order QFT cannot exceed
2g/(g+1) with per-window prologues — the bound the <= assertion
documents)."""

import numpy as np
import pytest

from qrack_tpu import QEngineCPU, create_quantum_interface
from qrack_tpu import telemetry as tele
from qrack_tpu.ops import fusion as fu
from qrack_tpu.parallel.pager import QPager
from qrack_tpu.utils.rng import QrackRandom

from test_fuzz_api import N, _ops


@pytest.fixture(autouse=True)
def _tele_clean():
    yield
    tele.disable()
    tele.reset()


def _fidelity(a, b) -> float:
    a, b = np.asarray(a), np.asarray(b)
    return float(abs(np.vdot(a, b)) ** 2 / (np.vdot(a, a).real
                                            * np.vdot(b, b).real))


def _op_skip_setbit(rng):
    # SetBit measures: cross-stack rng streams legitimately diverge on
    # measuring ops, so the soaks and this fuzz both re-roll it
    while True:
        name, args = _ops(rng)
        if name != "SetBit":
            return name, args


# ---------------------------------------------------------------------------
# fuzz parity: the whole non-measuring op vocabulary on a remap-on pager
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("collective", ["auto", "off"])
@pytest.mark.parametrize("window", [1, 16])
@pytest.mark.parametrize("trial", range(3))
def test_fuzz_parity_remap_on(trial, window, collective, monkeypatch):
    monkeypatch.setenv("QRACK_TPU_FUSE_WINDOW", str(window))
    rng = np.random.Generator(np.random.PCG64(7000 + trial))
    o = QEngineCPU(N, rng=QrackRandom(trial), rand_global_phase=False)
    s = create_quantum_interface("pager", N, n_pages=8, remap="on",
                                 collective=collective,
                                 rng=QrackRandom(trial),
                                 rand_global_phase=False)
    for step in range(25):
        name, args = _op_skip_setbit(rng)
        getattr(o, name)(*args)
        getattr(s, name)(*args)
        if rng.integers(0, 8) == 0:      # mid-stream reads flush windows
            qb = int(rng.integers(0, N))
            assert abs(o.Prob(qb) - s.Prob(qb)) < 3e-5, (trial, step, name)
    f = _fidelity(o.GetQuantumState(), s.GetQuantumState())
    assert f > 1 - 1e-6, (trial, window, f)


# ---------------------------------------------------------------------------
# non-identity tables under structural ops
# ---------------------------------------------------------------------------

def _force_nonid(o, p):
    """Drive both engines through a window whose hot paged targets make
    the planner fire, leaving ``p`` with a non-identity table."""
    for eng in (o, p):
        eng.SetPermutation(0b1011001)
        L = 4  # QPager(7, n_pages=8)
        eng.H(L)
        eng.H(L + 1)
        eng.H(L + 2)
        eng.RY(0.3, 1)
    p.GetAmplitude(0)  # read boundary: flush the fused window
    assert p._map_nonid()


def test_swap_meta_swap_on_nonidentity_table():
    n = 7
    o = QEngineCPU(n, rng=QrackRandom(9), rand_global_phase=False)
    p = QPager(n, rng=QrackRandom(9), rand_global_phase=False,
               n_pages=8, remap="on")
    _force_nonid(o, p)
    for eng in (o, p):
        eng.Swap(5, 6)      # page-page under SOME table state
        eng.Swap(0, 5)      # mixed local/global transposition
        eng.ISwap(2, 4)
        eng.CNOT(6, 0)
        eng.Swap(1, 2)      # local-local
    np.testing.assert_allclose(p.GetQuantumState(), o.GetQuantumState(),
                               atol=3e-5)


def test_checkpoint_roundtrip_nonidentity_table(tmp_path):
    from qrack_tpu.checkpoint import load_state, save_state

    n = 7
    o = QEngineCPU(n, rng=QrackRandom(11), rand_global_phase=False)
    p = QPager(n, rng=QrackRandom(11), rand_global_phase=False,
               n_pages=8, remap="on")
    _force_nonid(o, p)
    path = str(tmp_path / "remapped.qckpt")
    save_state(p, path)
    r = load_state(path)
    # the table travels with the pages: raw physical shards + qmap meta
    assert r._map_nonid()
    assert r._qmap == p._qmap
    assert np.array_equal(np.asarray(r.GetQuantumState()),
                          np.asarray(p.GetQuantumState()))
    # and the restored stack CONTINUES correctly from the mapped layout
    for eng in (o, p, r):
        eng.CNOT(5, 1)
        eng.T(6)
        eng.H(2)
    want = np.asarray(o.GetQuantumState())
    for eng in (p, r):
        np.testing.assert_allclose(eng.GetQuantumState(), want, atol=3e-5)


def test_shrink_mid_remapped_span_resets_table():
    n = 7
    o = QEngineCPU(n, rng=QrackRandom(13), rand_global_phase=False)
    p = QPager(n, rng=QrackRandom(13), rand_global_phase=False,
               n_pages=8, remap="on")
    _force_nonid(o, p)
    p.shrink_pages()
    # the repage gathers the LOGICAL view, so the table must reset
    assert p.n_pages == 4 and not p._map_nonid()
    for eng in (o, p):
        eng.H(5)
        eng.CZ(4, 6)
        eng.CNOT(6, 0)
    np.testing.assert_allclose(p.GetQuantumState(), o.GetQuantumState(),
                               atol=3e-5)


# ---------------------------------------------------------------------------
# exchange accounting: the 2x headline and its honest bound
# ---------------------------------------------------------------------------

def _iqft_bytes(width, n_pages, remap_mode, monkeypatch):
    monkeypatch.setenv("QRACK_TPU_FUSE_WINDOW", "16")
    tele.reset()
    tele.enable()
    q = QPager(width, rng=QrackRandom(5), rand_global_phase=False,
               n_pages=n_pages, remap=remap_mode)
    q.SetPermutation(777)
    q.IQFT(0, width)
    _ = q.GetAmplitude(0)  # flush; host fetch rides a SEPARATE counter
    c = tele.snapshot()["counters"]
    tele.disable()
    tele.reset()
    return c


def test_iqft_exchange_bytes_halved(monkeypatch):
    """w10 / 8 pages: the ascending-gen IQFT lets every hot paged target
    remap against a gen-done local, so the planner ships exactly half
    the bytes of the pair-exchange path (3 x nb/2 vs 3 x nb)."""
    off = _iqft_bytes(10, 8, "off", monkeypatch)
    auto = _iqft_bytes(10, 8, "auto", monkeypatch)
    ob = off.get("exchange.pager.bytes", 0)
    ab = auto.get("exchange.pager.bytes", 0)
    assert ab > 0 and ob >= 2 * ab, (ob, ab)
    # the remaps rode fused-window prologues, not separate dispatches
    assert auto.get("remap.pager.windows", 0) >= 1
    assert auto.get("remap.pager.pairs", 0) >= 3
    assert auto.get("exchange.pager.global_2x2", 0) == 0


def _circuit_ops(width, kind):
    """The registers.py gate streams as logical FusedOps (H -> gen,
    controlled phase -> cphase; payloads are placement-irrelevant)."""
    eye = np.eye(2, dtype=np.complex128)
    ops = []
    for i in range(width):
        if kind == "iqft":
            for j in range(i):
                ops.append(fu.FusedOp("cphase", i, 1 << (i - (j + 1)),
                                      1 << (i - (j + 1)), eye))
            ops.append(fu.FusedOp("gen", i, 0, 0, eye))
        else:  # qft: descending-gen order
            h = width - 1 - i
            for j in range(i):
                ops.append(fu.FusedOp("cphase", h + 1 + j, 1 << h,
                                      1 << h, eye))
            ops.append(fu.FusedOp("gen", h, 0, 0, eye))
    return ops


def _account(ops, width, L, window, remap_on, batched=True):
    """Replay the _dispatch_ops cost accounting host-side: window at a
    time, prologue swaps priced by the lowering's own accounting twin
    (ops/sharded.py exchange_cost — mirrors _tele_remap exactly),
    translated gens on paged targets at nb — exact at any width (pure
    arithmetic, no state allocated)."""
    from qrack_tpu.ops import sharded as shb

    nb = 2 * (1 << width) * 4  # f32 planes
    qmap = list(range(width))
    total = 0.0
    pairs = 0
    for s in range(0, len(ops), window):
        win = ops[s:s + window]
        rest = [("gen" if op.kind in ("gen", "inv") else "diag", op.target)
                for op in ops[s + window:]]
        if remap_on:
            swaps, qmap = fu.plan_remaps(win, L, qmap, rest,
                                         batched=batched)
            pairs += len(swaps)
            total += shb.exchange_cost(L, width - L, swaps,
                                       batched=batched) * nb
        for op in fu.translate_ops(win, qmap):
            if op.kind in ("gen", "inv") and op.target >= L:
                total += nb
    return total, pairs


def test_w26_iqft_accounting_batched_collective():
    """The acceptance-scale claim without the 2 GiB ket: w26 on 16
    pages (k=4).  Per-pair prologues ship nb/2 per paged qubit (the PR
    10 2x-halving baseline); the batched collective ships all four in
    one exchange at (1 - 2^-4) x nb — under 0.47x the per-pair bytes,
    0.55x required."""
    w, L = 26, 22
    ops = _circuit_ops(w, "iqft")
    nb = 2 * (1 << w) * 4
    off, _ = _account(ops, w, L, 16, remap_on=False)
    per_pair, pp_pairs = _account(ops, w, L, 16, remap_on=True,
                                  batched=False)
    batch, b_pairs = _account(ops, w, L, 16, remap_on=True, batched=True)
    assert off == 4 * nb
    assert pp_pairs == 4 and per_pair == 2 * nb, (per_pair, pp_pairs)
    assert b_pairs == 4 and batch == (1 - 2.0 ** -4) * nb, (batch, b_pairs)
    assert batch <= 0.55 * per_pair, (batch, per_pair)


def test_w26_qft_accounting_delivery_ratio():
    """Descending-gen QFT: every per-pair remap victim still owes a gen,
    so PR 10 prologues were bound at 2g/(g+1) and never fired (per-pair
    == remap-off == 3nb at w26/8 pages).  The batched collective breaks
    the bound: two k=3 batches (hot trio in window 1, pay-back trio once
    its victims are gen-done) ship 2 x (1 - 2^-3) x nb = 1.75nb — a
    12/7 ~ 1.71x delivery ratio vs remap-off, >= 1.6x required."""
    w, L = 26, 23
    ops = _circuit_ops(w, "qft")
    nb = 2 * (1 << w) * 4
    off, _ = _account(ops, w, L, 16, remap_on=False)
    per_pair, _ = _account(ops, w, L, 16, remap_on=True, batched=False)
    batch, _ = _account(ops, w, L, 16, remap_on=True, batched=True)
    assert off == 3 * nb and per_pair == off, (off, per_pair)
    assert batch == 2 * (1 - 2.0 ** -3) * nb, batch
    assert off / batch >= 1.6, (off, batch)


# ---------------------------------------------------------------------------
# measured batched collective: telemetry bytes on a real pager, driven
# through QCircuit.Run so the planner sees the full-circuit lookahead
# ---------------------------------------------------------------------------

def _iqft_qcircuit(width):
    """registers.py IQFT gate order as a QCircuit (ascending-gen:
    cphases then H per target) — Run() primes the fuser lookahead."""
    from qrack_tpu.layers.qcircuit import QCircuit

    h = np.array([[1, 1], [1, -1]], dtype=np.complex128) / np.sqrt(2)
    c = QCircuit(width)
    for i in range(width):
        for j in range(i):
            ph = np.exp(-1j * np.pi / 2.0 ** (j + 1))
            c.append_ctrl([i - (j + 1)], i,
                          np.diag([1.0, ph]).astype(np.complex128), 1)
        c.append_1q(i, h)
    return c


def _measured_circuit_bytes(width, n_pages, collective, monkeypatch):
    monkeypatch.setenv("QRACK_TPU_FUSE_WINDOW", "16")
    circ = _iqft_qcircuit(width)
    o = QEngineCPU(width, rng=QrackRandom(3), rand_global_phase=False)
    o.SetPermutation(314)
    circ.Run(o)
    tele.reset()
    tele.enable()
    q = QPager(width, rng=QrackRandom(3), rand_global_phase=False,
               n_pages=n_pages, remap="auto", collective=collective)
    q.SetPermutation(314)
    circ.Run(q)
    _ = q.GetAmplitude(0)  # read boundary: flush the fused window
    c = tele.snapshot()["counters"]
    tele.disable()
    tele.reset()
    f = _fidelity(o.GetQuantumState(), q.GetQuantumState())
    return c, f


def test_collective_measured_w10(monkeypatch):
    """w10 IQFT / 8 pages, measured: the batched lowering ships exactly
    (1 - 2^-3) x nb in ONE collective where per-pair ships 3 x nb/2 —
    the (1 - 2^-k)x ratio of mpiQulacs' fused exchange, on the wire."""
    nb = 2 * (1 << 10) * 4
    on, f_on = _measured_circuit_bytes(10, 8, "auto", monkeypatch)
    off, f_off = _measured_circuit_bytes(10, 8, "off", monkeypatch)
    assert f_on > 1 - 1e-6 and f_off > 1 - 1e-6, (f_on, f_off)
    assert on.get("exchange.pager.bytes", 0) == (1 - 2.0 ** -3) * nb, on
    assert on.get("exchange.pager.collective_bytes", 0) \
        == on["exchange.pager.bytes"]
    assert on.get("remap.pager.batched", 0) >= 1
    assert off.get("exchange.pager.bytes", 0) == 1.5 * nb, off
    assert off.get("remap.pager.batched", 0) == 0
    assert off.get("exchange.pager.collective_bytes", 0) == 0


# ---------------------------------------------------------------------------
# the permutation lowering itself: random transposition batches vs the
# numpy bit-permutation oracle, on a real 8-device mesh
# ---------------------------------------------------------------------------

def test_apply_remap_random_oracle():
    """apply_remap (batched AND per-pair) must realize the composed bit
    permutation of any transposition sequence — local, mixed and
    page-page, including the page-bit swaps the DCN pass emits."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from qrack_tpu.ops import sharded as shb
    from qrack_tpu.parallel.pager import _compat_shard_map

    L, g = 4, 3
    n = L + g
    mesh = Mesh(np.array(jax.devices()[:1 << g]), ("pages",))
    sh = NamedSharding(mesh, P(None, "pages"))
    rng = np.random.default_rng(17)
    for trial in range(8):
        swaps = tuple(tuple(int(x) for x in
                            rng.choice(n, size=2, replace=False))
                      for _ in range(int(rng.integers(1, 6))))
        state = rng.normal(size=(2, 1 << n)).astype(np.float32)
        src = shb.compose_swaps(n, swaps)
        j = np.zeros(1 << n, dtype=np.int64)
        for p in range(n):
            j |= ((np.arange(1 << n) >> p) & 1) << src[p]
        want = state[:, j]
        for batched in (True, False):
            prog = jax.jit(_compat_shard_map(
                lambda local: shb.apply_remap(local, 1 << g, L, swaps,
                                              batched=batched),
                mesh=mesh, in_specs=P(None, "pages"),
                out_specs=P(None, "pages")))
            got = np.asarray(prog(jax.device_put(state, sh)))
            np.testing.assert_array_equal(got, want,
                                          err_msg=f"{trial} {batched} "
                                                  f"{swaps}")


# ---------------------------------------------------------------------------
# DCN-aware planning: the multi-host cost model prefers ICI page bits
# ---------------------------------------------------------------------------

def test_plan_remaps_dcn_weights_prefer_ici():
    """With non-uniform page-bit weights (DCN stand-in) the planner
    moves a hot qubit OFF the expensive page bit onto a gen-done ICI
    one — a pure page-bit transposition — when evicting to a local
    would charge the victim at the DCN rate."""
    eye = np.eye(2, dtype=np.complex128)
    L, n = 4, 6            # g=2: page bit 0 ICI, page bit 1 DCN
    weights = (1.0, 4.0)
    ops = [fu.FusedOp("gen", 5, 0, 0, eye)]
    look = [("gen", q) for q in range(L)]  # every local still owes one
    swaps, qmap = fu.plan_remaps(ops, L, list(range(n)), look,
                                 weights=weights, batched=True)
    assert swaps == ((4, 5),), swaps       # page-page, off the DCN bit
    assert qmap[5] == 4 and qmap[4] == 5
    # uniform weights: same window fires nothing (net-zero local swap)
    swaps_u, qmap_u = fu.plan_remaps(ops, L, list(range(n)), look,
                                     weights=None, batched=True)
    assert swaps_u == () and qmap_u == list(range(n))


def test_page_bit_weights_standin():
    """cluster.page_bit_weights: single host is uniform (None) unless
    the DCN stand-in forces the top bits to DCN pricing."""
    import jax

    from qrack_tpu.parallel import cluster

    devs = jax.devices()[:8]
    assert cluster.page_bit_weights(devs) is None
    w = cluster.page_bit_weights(devs, dcn_bits=1)
    assert w is not None and len(w) == 3
    assert w[2] == cluster.dcn_weight() and w[0] == w[1] == 1.0
    assert cluster.page_bit_kinds(devs) == ("ici",) * 3


# ---------------------------------------------------------------------------
# structural ops mid-BATCHED-prologue
# ---------------------------------------------------------------------------

def test_shrink_mid_batched_prologue_resets_table():
    """Elastic shrink right after a >= 2-pair batched prologue: the
    repage gathers the LOGICAL view, the table resets, and the stack
    stays on-oracle."""
    n = 7
    o = QEngineCPU(n, rng=QrackRandom(21), rand_global_phase=False)
    p = QPager(n, rng=QrackRandom(21), rand_global_phase=False,
               n_pages=8, remap="on")
    tele.reset()
    tele.enable()
    _force_nonid(o, p)
    c = tele.snapshot()["counters"]
    tele.disable()
    tele.reset()
    assert c.get("remap.pager.batched", 0) >= 1, c
    assert c.get("exchange.pager.collective_bytes", 0) > 0, c
    p.shrink_pages()
    assert p.n_pages == 4 and not p._map_nonid()
    for eng in (o, p):
        eng.RY(0.7, 5)
        eng.CNOT(6, 2)
        eng.H(0)
    np.testing.assert_allclose(p.GetQuantumState(), o.GetQuantumState(),
                               atol=3e-5)
