"""Placement-table remapping on the 8-device virtual CPU mesh.

The communication-minimizing remap layer (parallel/pager.py placement
table + ops/fusion.py plan_remaps) must stay invisible to every
logical-level contract: state parity with the CPU oracle under the full
fuzz vocabulary, Swap/MetaSwap on any table, checkpoint round-trips
that carry a non-identity table, and elastic shrink mid-remapped-span.
The accounting tests pin the headline claim: ascending-gen-order
circuits (IQFT) ship exactly HALF the exchange bytes under the planner
(docs/PERFORMANCE.md derives why descending-order QFT cannot exceed
2g/(g+1) with per-window prologues — the bound the <= assertion
documents)."""

import numpy as np
import pytest

from qrack_tpu import QEngineCPU, create_quantum_interface
from qrack_tpu import telemetry as tele
from qrack_tpu.ops import fusion as fu
from qrack_tpu.parallel.pager import QPager
from qrack_tpu.utils.rng import QrackRandom

from test_fuzz_api import N, _ops


@pytest.fixture(autouse=True)
def _tele_clean():
    yield
    tele.disable()
    tele.reset()


def _fidelity(a, b) -> float:
    a, b = np.asarray(a), np.asarray(b)
    return float(abs(np.vdot(a, b)) ** 2 / (np.vdot(a, a).real
                                            * np.vdot(b, b).real))


def _op_skip_setbit(rng):
    # SetBit measures: cross-stack rng streams legitimately diverge on
    # measuring ops, so the soaks and this fuzz both re-roll it
    while True:
        name, args = _ops(rng)
        if name != "SetBit":
            return name, args


# ---------------------------------------------------------------------------
# fuzz parity: the whole non-measuring op vocabulary on a remap-on pager
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("window", [1, 16])
@pytest.mark.parametrize("trial", range(3))
def test_fuzz_parity_remap_on(trial, window, monkeypatch):
    monkeypatch.setenv("QRACK_TPU_FUSE_WINDOW", str(window))
    rng = np.random.Generator(np.random.PCG64(7000 + trial))
    o = QEngineCPU(N, rng=QrackRandom(trial), rand_global_phase=False)
    s = create_quantum_interface("pager", N, n_pages=8, remap="on",
                                 rng=QrackRandom(trial),
                                 rand_global_phase=False)
    for step in range(25):
        name, args = _op_skip_setbit(rng)
        getattr(o, name)(*args)
        getattr(s, name)(*args)
        if rng.integers(0, 8) == 0:      # mid-stream reads flush windows
            qb = int(rng.integers(0, N))
            assert abs(o.Prob(qb) - s.Prob(qb)) < 3e-5, (trial, step, name)
    f = _fidelity(o.GetQuantumState(), s.GetQuantumState())
    assert f > 1 - 1e-6, (trial, window, f)


# ---------------------------------------------------------------------------
# non-identity tables under structural ops
# ---------------------------------------------------------------------------

def _force_nonid(o, p):
    """Drive both engines through a window whose hot paged targets make
    the planner fire, leaving ``p`` with a non-identity table."""
    for eng in (o, p):
        eng.SetPermutation(0b1011001)
        L = 4  # QPager(7, n_pages=8)
        eng.H(L)
        eng.H(L + 1)
        eng.H(L + 2)
        eng.RY(0.3, 1)
    p.GetAmplitude(0)  # read boundary: flush the fused window
    assert p._map_nonid()


def test_swap_meta_swap_on_nonidentity_table():
    n = 7
    o = QEngineCPU(n, rng=QrackRandom(9), rand_global_phase=False)
    p = QPager(n, rng=QrackRandom(9), rand_global_phase=False,
               n_pages=8, remap="on")
    _force_nonid(o, p)
    for eng in (o, p):
        eng.Swap(5, 6)      # page-page under SOME table state
        eng.Swap(0, 5)      # mixed local/global transposition
        eng.ISwap(2, 4)
        eng.CNOT(6, 0)
        eng.Swap(1, 2)      # local-local
    np.testing.assert_allclose(p.GetQuantumState(), o.GetQuantumState(),
                               atol=3e-5)


def test_checkpoint_roundtrip_nonidentity_table(tmp_path):
    from qrack_tpu.checkpoint import load_state, save_state

    n = 7
    o = QEngineCPU(n, rng=QrackRandom(11), rand_global_phase=False)
    p = QPager(n, rng=QrackRandom(11), rand_global_phase=False,
               n_pages=8, remap="on")
    _force_nonid(o, p)
    path = str(tmp_path / "remapped.qckpt")
    save_state(p, path)
    r = load_state(path)
    # the table travels with the pages: raw physical shards + qmap meta
    assert r._map_nonid()
    assert r._qmap == p._qmap
    assert np.array_equal(np.asarray(r.GetQuantumState()),
                          np.asarray(p.GetQuantumState()))
    # and the restored stack CONTINUES correctly from the mapped layout
    for eng in (o, p, r):
        eng.CNOT(5, 1)
        eng.T(6)
        eng.H(2)
    want = np.asarray(o.GetQuantumState())
    for eng in (p, r):
        np.testing.assert_allclose(eng.GetQuantumState(), want, atol=3e-5)


def test_shrink_mid_remapped_span_resets_table():
    n = 7
    o = QEngineCPU(n, rng=QrackRandom(13), rand_global_phase=False)
    p = QPager(n, rng=QrackRandom(13), rand_global_phase=False,
               n_pages=8, remap="on")
    _force_nonid(o, p)
    p.shrink_pages()
    # the repage gathers the LOGICAL view, so the table must reset
    assert p.n_pages == 4 and not p._map_nonid()
    for eng in (o, p):
        eng.H(5)
        eng.CZ(4, 6)
        eng.CNOT(6, 0)
    np.testing.assert_allclose(p.GetQuantumState(), o.GetQuantumState(),
                               atol=3e-5)


# ---------------------------------------------------------------------------
# exchange accounting: the 2x headline and its honest bound
# ---------------------------------------------------------------------------

def _iqft_bytes(width, n_pages, remap_mode, monkeypatch):
    monkeypatch.setenv("QRACK_TPU_FUSE_WINDOW", "16")
    tele.reset()
    tele.enable()
    q = QPager(width, rng=QrackRandom(5), rand_global_phase=False,
               n_pages=n_pages, remap=remap_mode)
    q.SetPermutation(777)
    q.IQFT(0, width)
    _ = q.GetAmplitude(0)  # flush; host fetch rides a SEPARATE counter
    c = tele.snapshot()["counters"]
    tele.disable()
    tele.reset()
    return c


def test_iqft_exchange_bytes_halved(monkeypatch):
    """w10 / 8 pages: the ascending-gen IQFT lets every hot paged target
    remap against a gen-done local, so the planner ships exactly half
    the bytes of the pair-exchange path (3 x nb/2 vs 3 x nb)."""
    off = _iqft_bytes(10, 8, "off", monkeypatch)
    auto = _iqft_bytes(10, 8, "auto", monkeypatch)
    ob = off.get("exchange.pager.bytes", 0)
    ab = auto.get("exchange.pager.bytes", 0)
    assert ab > 0 and ob >= 2 * ab, (ob, ab)
    # the remaps rode fused-window prologues, not separate dispatches
    assert auto.get("remap.pager.windows", 0) >= 1
    assert auto.get("remap.pager.pairs", 0) >= 3
    assert auto.get("exchange.pager.global_2x2", 0) == 0


def _circuit_ops(width, kind):
    """The registers.py gate streams as logical FusedOps (H -> gen,
    controlled phase -> cphase; payloads are placement-irrelevant)."""
    eye = np.eye(2, dtype=np.complex128)
    ops = []
    for i in range(width):
        if kind == "iqft":
            for j in range(i):
                ops.append(fu.FusedOp("cphase", i, 1 << (i - (j + 1)),
                                      1 << (i - (j + 1)), eye))
            ops.append(fu.FusedOp("gen", i, 0, 0, eye))
        else:  # qft: descending-gen order
            h = width - 1 - i
            for j in range(i):
                ops.append(fu.FusedOp("cphase", h + 1 + j, 1 << h,
                                      1 << h, eye))
            ops.append(fu.FusedOp("gen", h, 0, 0, eye))
    return ops


def _account(ops, width, L, window, remap_on):
    """Replay the _dispatch_ops cost accounting host-side: window at a
    time, remap prologue swaps at nb/2 per paged pair, translated gens
    on paged targets at nb — exact at any width (pure arithmetic)."""
    nb = 2 * (1 << width) * 4  # f32 planes
    qmap = list(range(width))
    total = 0
    pairs = 0
    for s in range(0, len(ops), window):
        win = ops[s:s + window]
        rest = [("gen" if op.kind in ("gen", "inv") else "diag", op.target)
                for op in ops[s + window:]]
        if remap_on:
            swaps, qmap = fu.plan_remaps(win, L, qmap, rest)
            pairs += len(swaps)
            for p1, p2 in swaps:
                if max(p1, p2) >= L:
                    total += nb // 2
        for op in fu.translate_ops(win, qmap):
            if op.kind in ("gen", "inv") and op.target >= L:
                total += nb
    return total, pairs


def test_w26_iqft_accounting_2x():
    """The acceptance-scale claim without the 512 MiB ket: at w26 on 8
    pages the planner moves each of the 3 paged qubits once (gen-done
    victims, zero pay-back) — exactly half the off-mode bytes."""
    w, L = 26, 23
    ops = _circuit_ops(w, "iqft")
    off, _ = _account(ops, w, L, 16, remap_on=False)
    auto, pairs = _account(ops, w, L, 16, remap_on=True)
    nb = 2 * (1 << w) * 4
    assert off == 3 * nb
    assert pairs == 3 and auto * 2 == off, (off, auto, pairs)


def test_w26_qft_accounting_never_worse():
    """Descending-gen QFT: every remap victim still owes a gen, so
    per-window prologues cannot beat 2g/(g+1) — the planner must simply
    never ship MORE than the pair-exchange path."""
    w, L = 26, 23
    ops = _circuit_ops(w, "qft")
    off, _ = _account(ops, w, L, 16, remap_on=False)
    auto, _ = _account(ops, w, L, 16, remap_on=True)
    assert auto <= off, (off, auto)
