"""Stacks validated against a NON-SELF oracle (torch independent dense
sim — role parity with the reference's Qiskit/MPS validation scripts,
scripts/rcs_nn_qiskit_validation.py)."""

import sys
import os

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))

from cross_validate import validate  # noqa: E402


@pytest.mark.parametrize("seed", [7, 21])
def test_stacks_match_torch_oracle(seed):
    for r in validate(6, 6, seed):
        assert r["fidelity"] == pytest.approx(1.0, abs=1e-7), r
