"""Routed TurboQuant tier: the memory-axis cost model that steers
over-width dense jobs onto the compressed rung, the single-pass fused
window's sweep economics, the chunk-mass fidelity guard, and the
quantized escalation ladder (drift giveup -> dense) — end to end
through the factory "route" pseudo-terminal and the serving plane
(docs/ROUTING.md, docs/PERFORMANCE.md).
"""

import numpy as np
import pytest

from qrack_tpu import QEngineCPU, create_quantum_interface
from qrack_tpu import resilience as res
from qrack_tpu import telemetry as tele
from qrack_tpu.engines.turboquant import QEngineTurboQuant
from qrack_tpu.models.qft import qft_qcircuit
from qrack_tpu.resilience import faults
from qrack_tpu.resilience import integrity as integ
from qrack_tpu.route import cost as rc
from qrack_tpu.serve import QrackService
from qrack_tpu.utils.rng import QrackRandom

N = 6
_TQ = {"bits": 16, "chunk_qb": 3, "block_pow": 2}
_TQ_FLOOR = 1 - 1e-5  # 16-bit codes at w6: comfortably above the
#                       ladder's 1e-3 serving contract


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv("QRACK_ROUTE", raising=False)
    monkeypatch.delenv("QRACK_ROUTE_HBM_BYTES", raising=False)
    faults.clear()
    yield
    faults.clear()
    integ.reset()
    integ.set_enabled(False)
    res.disable()
    tele.disable()
    tele.reset()


def _fidelity(a, b) -> float:
    a, b = np.asarray(a), np.asarray(b)
    return abs(np.vdot(a, b)) ** 2 / (np.vdot(a, a).real
                                      * np.vdot(b, b).real)


# ---------------------------------------------------------------------------
# memory-axis cost model
# ---------------------------------------------------------------------------

def test_hbm_bytes_dense_is_sixteen_per_amp():
    f = rc._WidthOnly(20)
    k = rc.RouteKnobs.from_env()
    assert rc.hbm_bytes("dense", f, k) == 16.0 * (1 << 20)


def test_hbm_bytes_turboquant_beats_dense_and_pages_divide():
    k = rc.RouteKnobs.from_env()
    f = rc._WidthOnly(24)
    dense = rc.hbm_bytes("dense", f, k)
    tq = rc.hbm_bytes("turboquant", f, k)
    # int8 codes are 8x under the f32 planes at rest; the model's 2x
    # transient factor (decompressed working chunks) nets out >3x
    assert 0 < tq < dense / 3
    import dataclasses

    k4 = dataclasses.replace(k, tq_pages=4)
    assert rc.hbm_bytes("turboquant", f, k4) == pytest.approx(tq / 4)


def test_ladder_stack_walks_dense_then_turboquant_then_none():
    assert rc.ladder_stack(10) == "dense"
    assert rc.ladder_stack(rc._TQ_BASE_CAP) == "turboquant"
    assert rc.ladder_stack(60) is None


def test_small_hbm_budget_blocks_dense_below_width_cap(monkeypatch):
    # an 8-qubit dense ket is 4 KiB; a 2 KiB budget must block it and
    # hand the job to the compressed rung — the width cap alone would
    # have admitted dense
    monkeypatch.setenv("QRACK_ROUTE_HBM_BYTES", "2048")
    assert rc.ladder_stack(8) == "turboquant"
    tele.enable()
    tele.reset()
    q = create_quantum_interface(("route",), 8, rng=QrackRandom(3),
                                 rand_global_phase=False)
    d = q.plan(qft_qcircuit(8))
    assert d.stack != "dense"
    snap = tele.snapshot()
    assert snap["counters"].get("route.hbm.dense_blocked", 0) >= 1
    assert "route.hbm.budget_bytes" in snap["gauges"]


# ---------------------------------------------------------------------------
# routed fuzz vs the CPU oracle, per-gate AND fused windows
# ---------------------------------------------------------------------------

def _fuzz_ops(rng):
    """The test_fuzz_api vocabulary minus SetBit (a measuring op:
    cross-stack rng streams legitimately diverge on collapse)."""
    from test_fuzz_api import _ops

    while True:
        name, args = _ops(rng)
        if name != "SetBit":
            return name, args


@pytest.mark.parametrize("window", [1, 16])
@pytest.mark.parametrize("trial", range(3))
def test_routed_turboquant_fuzz_matches_oracle(monkeypatch, window, trial):
    monkeypatch.setenv("QRACK_TPU_FUSE_WINDOW", str(window))
    monkeypatch.setenv("QRACK_ROUTE", "turboquant")
    rng = np.random.Generator(np.random.PCG64(7000 + trial))
    o = QEngineCPU(N, rng=QrackRandom(trial), rand_global_phase=False)
    s = create_quantum_interface(("route",), N, rng=QrackRandom(trial),
                                 rand_global_phase=False, **_TQ)
    for step in range(25):
        op, args = _fuzz_ops(rng)
        getattr(o, op)(*args)
        getattr(s, op)(*args)
        if rng.integers(0, 10) == 0:
            qb = int(rng.integers(0, N))
            assert abs(o.Prob(qb) - s.Prob(qb)) < 5e-4, (trial, step, op)
    assert s.current_stack() == "turboquant"
    f = _fidelity(o.GetQuantumState(), s.GetQuantumState())
    assert f > _TQ_FLOOR, (trial, window, f)


# ---------------------------------------------------------------------------
# single-pass fused windows: counted sweep economics
# ---------------------------------------------------------------------------

def _sweep_count(window: int, monkeypatch) -> int:
    monkeypatch.setenv("QRACK_TPU_FUSE_WINDOW", str(window))
    tele.enable()
    tele.reset()
    # default (single-chunk) geometry: every target is chunk-local, so
    # the whole stream is window-admissible — the configuration the
    # sweep economics are quoted for (docs/PERFORMANCE.md)
    eng = QEngineTurboQuant(N, rng=QrackRandom(2), rand_global_phase=False,
                            bits=16, block_pow=2)
    rng = np.random.Generator(np.random.PCG64(42))
    for _ in range(3):
        for t in range(N):
            eng.H(t)
            eng.RZ(float(rng.uniform(0, 2 * np.pi)), t)
    _ = eng.GetQuantumState()
    n = tele.snapshot()["counters"].get("tq.sweeps", 0)
    tele.disable()
    tele.reset()
    return int(n)


def test_fused_window_cuts_sweeps_at_least_4x(monkeypatch):
    per_gate = _sweep_count(1, monkeypatch)
    fused = _sweep_count(16, monkeypatch)
    assert per_gate >= 4 * fused, (per_gate, fused)


def test_fused_window_sweeps_saved_counter(monkeypatch):
    monkeypatch.setenv("QRACK_TPU_FUSE_WINDOW", "16")
    tele.enable()
    tele.reset()
    eng = QEngineTurboQuant(N, rng=QrackRandom(2), rand_global_phase=False,
                            **_TQ)
    for t in range(N):
        eng.H(t)
    _ = eng.GetQuantumState()
    c = tele.snapshot()["counters"]
    assert c.get("fuse.tq.windows", 0) >= 1
    ops = c.get("fuse.tq.ops", 0)
    assert ops >= 2
    # one decompress+recompress per WINDOW instead of per op
    assert c.get("fuse.tq.sweeps_saved", 0) == 2 * (ops - c["fuse.tq.windows"])


# ---------------------------------------------------------------------------
# serving plane: over-budget dense request served on the compressed rung
# ---------------------------------------------------------------------------

def test_overbudget_dense_job_routes_to_turboquant_and_serves(monkeypatch):
    monkeypatch.setenv("QRACK_ROUTE_HBM_BYTES", "2048")  # blocks dense w8
    tele.enable()
    tele.reset()
    with QrackService(engine_layers="route", batch_window_ms=5.0,
                      tick_s=0.02) as svc:
        sid = svc.create_session(8, seed=5, rand_global_phase=False, **_TQ)
        svc.apply(sid, qft_qcircuit(8), timeout=120)
        state = svc.get_state(sid, timeout=120)
    snap = tele.snapshot()
    assert snap["counters"].get("route.built.turboquant", 0) >= 1
    oracle = QEngineCPU(8, rng=QrackRandom(5), rand_global_phase=False)
    qft_qcircuit(8).Run(oracle)
    assert _fidelity(oracle.GetQuantumState(), state) > 1 - 1e-3


def test_quantized_session_checkpoint_roundtrip_serve_recover(
        monkeypatch, tmp_path):
    monkeypatch.setenv("QRACK_ROUTE", "turboquant")
    ck = str(tmp_path / "ck")
    a = QrackService(engine_layers="route", checkpoint_dir=ck,
                     batch_window_ms=5.0, tick_s=0.02)
    try:
        sid = a.create_session(N, seed=5, rand_global_phase=False, **_TQ)
        a.apply(sid, qft_qcircuit(N), timeout=120)
        out = a.drain()
        assert out == {"drained": [sid], "busy": []}
        with QrackService(engine_layers="route", checkpoint_dir=ck,
                          recover=True, batch_window_ms=5.0,
                          tick_s=0.02) as b:
            assert sid in b.sessions.ids()
            state = b.get_state(sid, timeout=120)
            sess = b.sessions.get(sid)
            assert sess.engine.current_stack() == "turboquant"
    finally:
        a.close()
    oracle = QEngineCPU(N, rng=QrackRandom(5), rand_global_phase=False)
    qft_qcircuit(N).Run(oracle)
    assert _fidelity(oracle.GetQuantumState(), state) > _TQ_FLOOR


# ---------------------------------------------------------------------------
# fidelity guard: exhausted drift replays escalate up the ladder
# ---------------------------------------------------------------------------

def test_drift_giveup_escalates_routed_session_to_dense(monkeypatch):
    monkeypatch.setenv("QRACK_ROUTE", "turboquant")
    monkeypatch.setenv("QRACK_TPU_INTEGRITY_REPLAYS", "0")
    tele.enable()
    tele.reset()
    q = create_quantum_interface(("route",), 4, rng=QrackRandom(7),
                                 rand_global_phase=False, **_TQ)
    # spread mass into EVERY block row (both planes, all amplitudes)
    # first: a strike on an empty block's scale multiplies zero codes
    # and is legitimately invisible to the chunk-mass fingerprint
    for t in range(4):
        q.H(t)
    q.RZ(1.0, 0)
    _ = q.Prob(0)  # clean flush of the prep
    assert q.current_stack() == "turboquant"
    integ.set_enabled(True)
    res.enable()
    q.H(1)
    q.H(2)
    faults.inject("tpu.fuse.flush", "amp-corrupt", times=1, seed=11)
    state = q.GetQuantumState()
    faults.clear()
    # the poisoned window was re-dispatched on the dense rung, not
    # served from corrupted codes
    assert q.current_stack() == "dense"
    assert q._escalated
    oracle = QEngineCPU(4, rng=QrackRandom(7), rand_global_phase=False)
    for t in range(4):
        oracle.H(t)
    oracle.RZ(1.0, 0)
    oracle.H(1)
    oracle.H(2)
    assert _fidelity(oracle.GetQuantumState(), state) > 1 - 1e-3
    c = tele.snapshot()["counters"]
    assert c.get("integrity.replay.giveup", 0) == 1
    assert c.get("route.misroute.escalated", 0) == 1


def test_drift_giveup_fails_over_wrapped_engine_to_dense(monkeypatch):
    monkeypatch.setenv("QRACK_TPU_INTEGRITY_REPLAYS", "0")
    from qrack_tpu.resilience.failover import ResilientEngine

    integ.set_enabled(True)
    e = ResilientEngine(QEngineTurboQuant(4, rng=QrackRandom(7),
                                          rand_global_phase=False,
                                          bits=8))
    e.H(0)
    e.H(1)
    e.H(2)
    faults.inject("tpu.fuse.flush", "amp-corrupt", times=1, seed=11)
    state = e.GetQuantumState()
    faults.clear()
    assert type(e.engine).__name__ == "QEngineTPU"
    oracle = QEngineCPU(4, rng=QrackRandom(7), rand_global_phase=False)
    oracle.H(0)
    oracle.H(1)
    oracle.H(2)
    # int8 requantization rode along in the carried state: the ladder's
    # serving contract (1e-3) is the right floor here, not exactness
    assert _fidelity(oracle.GetQuantumState(), state) > 1 - 1e-3


def test_clean_quantized_stream_passes_guard(monkeypatch):
    # the guard must not false-positive on legitimate requantization
    # drift: a long clean stream under the armed guard serves at full
    # quantized fidelity with zero violations
    integ.set_enabled(True)
    res.enable()
    tele.enable()
    tele.reset()
    e = QEngineTurboQuant(N, rng=QrackRandom(7), rand_global_phase=False,
                          **_TQ)
    o = QEngineCPU(N, rng=QrackRandom(7), rand_global_phase=False)
    rng = np.random.Generator(np.random.PCG64(9))
    for _ in range(40):
        t = int(rng.integers(N))
        th = float(rng.uniform(0, 2 * np.pi))
        for q in (e, o):
            q.H(t)
            q.RZ(th, t)
    f = _fidelity(o.GetQuantumState(), e.GetQuantumState())
    assert f > _TQ_FLOOR
    c = tele.snapshot()["counters"]
    assert c.get("integrity.replay.giveup", 0) == 0
    assert not any(k.startswith("integrity.violation") for k in c)
