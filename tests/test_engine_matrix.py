"""One conformance battery × engine matrix (SURVEY.md §4 design).

Every engine/stack listed in ENGINE_FACTORIES runs the same randomized
circuit batteries; the complex128 CPU oracle is the ground truth
(reference: test/tests.cpp engine-matrix globals, test/test_main.cpp:24)."""

import math

import numpy as np
import pytest

from qrack_tpu import QEngineCPU
from qrack_tpu.engines.tpu import QEngineTPU
from qrack_tpu import matrices as mat
from qrack_tpu.utils.rng import QrackRandom


def _pager(n, **kw):
    from qrack_tpu.parallel.pager import QPager

    return QPager(n, n_pages=4, **kw)


def _hybrid(n, **kw):
    from qrack_tpu.engines.hybrid import QHybrid

    return QHybrid(n, tpu_threshold_qubits=4, pager_threshold_qubits=7, **kw)


def _stabhybrid(n, **kw):
    from qrack_tpu.layers.stabilizerhybrid import QStabilizerHybrid

    def factory(m, **fkw):
        fkw.setdefault("rand_global_phase", False)
        return QEngineCPU(m, **fkw)

    return QStabilizerHybrid(n, engine_factory=factory, **kw)


def _qunit(n, **kw):
    from qrack_tpu.layers.qunit import QUnit

    def factory(m, **fkw):
        fkw.setdefault("rand_global_phase", False)
        return QEngineCPU(m, **fkw)

    return QUnit(n, unit_factory=factory, **kw)


def _full_stack(n, **kw):
    """QUnit -> QStabilizerHybrid -> QEngineCPU (the reference's default
    optimal-stack shape, SURVEY.md §1)."""
    from qrack_tpu.layers.qunit import QUnit
    from qrack_tpu.layers.stabilizerhybrid import QStabilizerHybrid

    def eng_factory(m, **fkw):
        fkw.setdefault("rand_global_phase", False)
        return QEngineCPU(m, **fkw)

    def sh_factory(m, **fkw):
        return QStabilizerHybrid(m, engine_factory=eng_factory, **fkw)

    return QUnit(n, unit_factory=sh_factory, **kw)


def _sparse(n, **kw):
    from qrack_tpu.engines.sparse import QEngineSparse

    return QEngineSparse(n, **kw)


def _bdt_hybrid(n, **kw):
    from qrack_tpu.layers.qbdthybrid import QBdtHybrid

    return QBdtHybrid(n, **kw)


def _bdt_attached(n, **kw):
    """Tree-top/dense-bottom single representation (attached leaves)."""
    from qrack_tpu.layers.qbdt import QBdt

    return QBdt(n, attached_qubits=n // 2, **kw)


ENGINE_FACTORIES = {
    "tpu": lambda n, **kw: QEngineTPU(n, **kw),
    "pager": _pager,
    "hybrid": _hybrid,
    "stabhybrid": _stabhybrid,
    "qunit": _qunit,
    "full_stack": _full_stack,
    "sparse": _sparse,
    "bdt_hybrid": _bdt_hybrid,
    "bdt_attached": _bdt_attached,
}

# permutation-gather ALU (Hash/Indexed*) needs a _k_gather-backed engine;
# the bare attached tree runs the gate battery but not those (QBdtHybrid
# covers the forwarding path the reference uses for heavy ALU)
ALU_FACTORIES = {k: v for k, v in ENGINE_FACTORIES.items()
                 if k != "bdt_attached"}


def _stabilizer(n, **kw):
    from qrack_tpu.layers.stabilizer import QStabilizer

    kw.pop("rand_global_phase", None)
    return QStabilizer(n, **kw)


def _unit_clifford(n, **kw):
    from qrack_tpu.layers.qunitclifford import QUnitClifford

    return QUnitClifford(n, **kw)


# Clifford-restricted battery x Clifford-capable matrix: QUnitClifford
# (and the bare tableau) reject non-Clifford payloads, so they get their
# own shared battery (reference: --proc-stabilizer layer flags run the
# same suite restricted to what the stack supports, test/test_main.cpp)
CLIFFORD_FACTORIES = {
    "stabilizer": _stabilizer,
    "unit_clifford": _unit_clifford,
    "stabhybrid": _stabhybrid,
    "qunit_over_stabhybrid": _full_stack,
}


def random_clifford_circuit(q, rng, gates, n):
    for _ in range(gates):
        kind = rng.randint(0, 7)
        t = rng.randint(0, n)
        if kind == 0:
            q.H(t)
        elif kind == 1:
            q.S(t)
        elif kind == 2:
            q.X(t)
        elif kind == 3:
            q.Z(t)
        elif kind == 4:
            q.Y(t)
        else:
            c = rng.randint(0, n)
            if c != t:
                q.CNOT(c, t) if kind == 5 else q.CZ(c, t)


@pytest.mark.parametrize("name", list(CLIFFORD_FACTORIES))
def test_clifford_battery_matches_oracle(name):
    n = 6
    for seed in (31, 32):
        o = oracle(n, rng=QrackRandom(seed), rand_global_phase=False)
        q = CLIFFORD_FACTORIES[name](n, rng=QrackRandom(seed),
                                     rand_global_phase=False)
        random_clifford_circuit(o, QrackRandom(700 + seed), 40, n)
        random_clifford_circuit(q, QrackRandom(700 + seed), 40, n)
        got = align_phase(np.asarray(q.GetQuantumState(), dtype=np.complex128),
                          np.asarray(o.GetQuantumState(), dtype=np.complex128))
        np.testing.assert_allclose(got, o.GetQuantumState(), atol=2e-5)
        # measurement parity on the shared stream
        q2 = CLIFFORD_FACTORIES[name](n, rng=QrackRandom(seed),
                                      rand_global_phase=False)
        o2 = oracle(n, rng=QrackRandom(seed), rand_global_phase=False)
        random_clifford_circuit(o2, QrackRandom(800 + seed), 30, n)
        random_clifford_circuit(q2, QrackRandom(800 + seed), 30, n)
        assert abs(q2.Prob(2) - o2.Prob(2)) < 2e-5


def oracle(n, **kw):
    return QEngineCPU(n, **kw)


def both(n, seed=11):
    o = oracle(n, rng=QrackRandom(seed), rand_global_phase=False)
    return o, {
        name: f(n, rng=QrackRandom(seed), rand_global_phase=False)
        for name, f in ENGINE_FACTORIES.items()
    }


def align_phase(got, expect):
    """Rotate `got` by the global phase that best matches `expect`
    (tableau-backed stacks canonicalize global phase — physically
    irrelevant, reference tracks it as a separate phaseOffset)."""
    k = int(np.argmax(np.abs(expect)))
    if abs(got[k]) < 1e-12:
        return got
    ph = expect[k] / got[k]
    ph /= abs(ph) if abs(ph) > 0 else 1.0
    return got * ph


def assert_match(o, others, atol=2e-5):
    expect = o.GetQuantumState()
    for name, q in others.items():
        got = align_phase(q.GetQuantumState(), expect)
        np.testing.assert_allclose(got, expect, atol=atol, err_msg=name)


def random_circuit(q, rng, depth, n, allow_measure=False):
    """Apply an identical random gate sequence to engine q."""
    for _ in range(depth):
        kind = rng.randint(0, 12)
        t = rng.randint(0, n)
        if kind == 0:
            q.H(t)
        elif kind == 1:
            q.X(t)
        elif kind == 2:
            q.RY(rng.rand() * 2 * math.pi, t)
        elif kind == 3:
            q.RZ(rng.rand() * 2 * math.pi, t)
        elif kind == 4:
            q.T(t)
        elif kind == 5:
            c = rng.randint(0, n)
            if c != t:
                q.CNOT(c, t)
        elif kind == 6:
            c = rng.randint(0, n)
            if c != t:
                q.CZ(c, t)
        elif kind == 7:
            c = rng.randint(0, n)
            if c != t:
                q.Swap(c, t)
        elif kind == 8:
            q.U(t, rng.rand(), rng.rand(), rng.rand())
        elif kind == 9:
            c = rng.randint(0, n)
            if c != t:
                q.AntiCNOT(c, t)
        elif kind == 10:
            c1, c2 = rng.randint(0, n), rng.randint(0, n)
            if len({c1, c2, t}) == 3:
                q.CCNOT(c1, c2, t)
        elif kind == 11:
            c = rng.randint(0, n)
            if c != t:
                q.ISwap(c, t)


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_random_circuits_match_oracle(seed):
    n = 5
    o, others = both(n, seed)
    random_circuit(o, QrackRandom(100 + seed), 40, n)
    for q in others.values():
        random_circuit(q, QrackRandom(100 + seed), 40, n)
    assert_match(o, others)


@pytest.mark.parametrize("name", list(ENGINE_FACTORIES))
def test_qft_matches_oracle(name):
    n = 6
    o, others = both(n, 5)
    q = others[name]
    for eng in (o, q):
        eng.SetPermutation(0b101101)
        eng.QFT(0, n)
    np.testing.assert_allclose(q.GetQuantumState(), o.GetQuantumState(), atol=2e-5)
    for eng in (o, q):
        eng.IQFT(0, n)
    np.testing.assert_allclose(q.GetQuantumState(), o.GetQuantumState(), atol=2e-5)
    assert abs(q.GetAmplitude(0b101101)) == pytest.approx(1.0, abs=1e-4)


@pytest.mark.parametrize("name", list(ALU_FACTORIES))
def test_alu_matches_oracle(name):
    n = 8
    o, others = both(n, 7)
    q = others[name]
    for eng in (o, q):
        eng.HReg(0, 4)
        eng.INC(5, 0, 4)
        eng.CINC(3, 0, 3, (6,))
        eng.INCDECC(2, 0, 3, 5)
        eng.ROL(1, 0, 4)
        eng.PhaseFlipIfLess(7, 0, 4)
        eng.Hash(0, 2, [2, 0, 3, 1])
    assert_match(o, {name: q})


@pytest.mark.parametrize("name", list(ALU_FACTORIES))
def test_mul_and_modular_match_oracle(name):
    n = 8
    o, others = both(n, 9)
    q = others[name]
    for eng in (o, q):
        eng.HReg(0, 3)
        eng.MUL(3, 0, 3, 3)
        eng.DIV(3, 0, 3, 3)
        eng.MULModNOut(5, 7, 0, 3, 3)
    assert_match(o, {name: q})


@pytest.mark.parametrize("name", list(ENGINE_FACTORIES))
def test_measurement_statistics_match(name):
    n = 4
    o, others = both(n, 13)
    q = others[name]
    for eng in (o, q):
        eng.H(0)
        eng.CNOT(0, 1)
        eng.H(2)
    # same rng seed -> same measurement outcomes
    for eng in (o, q):
        eng.rng.seed(42)
    ro = [o.M(i) for i in range(n)]
    rq = [q.M(i) for i in range(n)]
    assert ro == rq
    assert_match(o, {name: q}, atol=5e-5)


@pytest.mark.parametrize("name", list(ENGINE_FACTORIES))
def test_parity_and_uc_match(name):
    n = 4
    o, others = both(n, 17)
    q = others[name]
    mtrxs = [mat.u3_mtrx(0.3 * k, 0.1 * k, -0.2 * k) for k in range(4)]
    for eng in (o, q):
        eng.HReg(0, n)
        eng.UniformParityRZ(0b0110, 0.7)
        eng.PhaseParity(0.9, 0b1011)
        eng.UCMtrx((1, 2), mtrxs, 0)
    assert_match(o, {name: q})
    assert q.ProbParity(0b0110) == pytest.approx(o.ProbParity(0b0110), abs=1e-5)


@pytest.mark.parametrize("name", list(ENGINE_FACTORIES))
def test_compose_decompose_match(name):
    o, others = both(3, 19)
    q = others[name]
    for eng, mk in ((o, oracle), (q, ENGINE_FACTORIES[name])):
        eng.H(0)
        eng.CNOT(0, 1)
        other = mk(2, rng=QrackRandom(7), rand_global_phase=False)
        other.X(0)
        other.H(1)
        eng.Compose(other)
        assert eng.GetQubitCount() == 5
    assert_match(o, {name: q})
    for eng, mk in ((o, oracle), (q, ENGINE_FACTORIES[name])):
        dest = mk(2, rng=QrackRandom(8), rand_global_phase=False)
        eng.Decompose(3, dest)
        assert eng.GetQubitCount() == 3
    assert_match(o, {name: q})


@pytest.mark.parametrize("name", list(ENGINE_FACTORIES))
def test_expectation_and_multishot(name):
    n = 5
    o, others = both(n, 23)
    q = others[name]
    for eng in (o, q):
        random_circuit(eng, QrackRandom(55), 30, n)
    assert q.ExpectationBitsAll(list(range(n))) == pytest.approx(
        o.ExpectationBitsAll(list(range(n))), abs=1e-3)
    assert q.VarianceBitsAll([0, 2, 4]) == pytest.approx(
        o.VarianceBitsAll([0, 2, 4]), abs=1e-3)
    so = o.MultiShotMeasureMask([1, 4], 2000)
    sq = q.MultiShotMeasureMask([1, 4], 2000)
    for k in range(4):
        assert abs(so.get(k, 0) - sq.get(k, 0)) < 220


def test_multishot_vectorized_bulk():
    """Bulk MultiShotMeasureMask on the TPU engine: the draw + masked-bit
    compaction run as one device program (reference bulk op:
    src/qinterface/qinterface.cpp:807).  Checks exact correlation
    structure and totals at a shot count the old per-shot Python loop
    made painful."""
    n, shots = 12, 50_000
    q = QEngineTPU(n, seed=7)
    for b in range(n):
        if b != 5:
            q.H(b)
    q.CNOT(0, 5)        # q5 copies q0
    out = q.MultiShotMeasureMask([1 << 0, 1 << 3, 1 << 5], shots)
    assert sum(out.values()) == shots
    # key bit0 (q0) and bit2 (q5) perfectly correlated
    assert all(((k >> 0) & 1) == ((k >> 2) & 1) for k in out)
    m0 = sum(c for k, c in out.items() if k & 1) / shots
    assert abs(m0 - 0.5) < 0.02
