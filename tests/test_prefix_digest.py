"""QCircuit prefix-digest chain: the O(1)-per-length keys the serving
prefix cache (serve/prefix_cache.py) shares kets by.

Contract under test (layers/qcircuit.py):
- prefix_digest(k) is stable: appending more gates never changes the
  digest of an already-hashed prefix (the chain is append-only);
- two circuits share prefix_digest(k) iff their first k gates are equal
  (targets, controls, payload bytes);
- prefix_digest(len(gates)) == structure_digest(), prefix_digest(0) is
  the fixed empty digest, and lengths past the end raise IndexError;
- a non-unitary payload (recorded measurement/projection) terminates
  shareable_prefix_len — projective outcomes are per-tenant;
- split_at copies gates verbatim, NOT through AppendGate's peephole
  merging, so prefix+suffix re-trace to the digested sequence.
"""

import hashlib

import numpy as np
import pytest

from qrack_tpu import matrices as mat
from qrack_tpu.layers.qcircuit import QCircuit

W = 5


def _ry(theta: float) -> np.ndarray:
    c, s = np.cos(theta / 2.0), np.sin(theta / 2.0)
    return np.array([[c, -s], [s, c]], dtype=np.complex128)


def _ring(circ: QCircuit, width: int = W) -> None:
    for q in range(width - 1):
        circ.append_ctrl((q,), q + 1, mat.X2, 1)


def _prep(width: int = W, layers: int = 2, seed: int = 7) -> QCircuit:
    """Deterministic shareable state-prep: H wall + layers x (CX ring +
    seeded RY layer)."""
    circ = QCircuit()
    rng = np.random.default_rng(seed)
    for q in range(width):
        circ.append_1q(q, mat.H2)
    for _ in range(layers):
        _ring(circ, width)
        for q in range(width):
            circ.append_1q(q, _ry(rng.uniform(0.0, 2.0 * np.pi)))
    return circ


def _tenant(tail_seed: int, prep_seed: int = 7) -> QCircuit:
    """Shared prep + per-tenant tail.  The tail STARTS with a CX ring:
    AppendGate merges a same-target uncontrolled gate into the previous
    gate's payload, so a rotation appended straight after the prep's
    rotation layer would mutate the shared gates and fork the digest."""
    circ = _prep(seed=prep_seed)
    _ring(circ)
    rng = np.random.default_rng(tail_seed)
    for q in range(W):
        circ.append_1q(q, _ry(rng.uniform(0.0, 2.0 * np.pi)))
    return circ


def _shared_boundary() -> int:
    """Gate index where two same-prep tenants provably diverge: the
    prep plus the (identical) tail ring."""
    return len(_prep().gates) + (W - 1)


# ---------------------------------------------------------------------------
# stability + equality
# ---------------------------------------------------------------------------

def test_prefix_digests_stable_under_append():
    circ = _prep()
    before = [circ.prefix_digest(k) for k in range(len(circ.gates) + 1)]
    _ring(circ)  # controlled gates cannot merge into the 1q tail
    for q in range(W):
        circ.append_1q(q, _ry(0.3 * (q + 1)))
    after = [circ.prefix_digest(k) for k in range(len(before))]
    assert after == before


def test_prefix_digest_equal_iff_prefix_equal():
    a, b = _tenant(tail_seed=1), _tenant(tail_seed=2)
    k_shared = _shared_boundary()
    for k in (0, 1, k_shared // 2, k_shared):
        assert a.prefix_digest(k) == b.prefix_digest(k)
    # first tail rotation differs -> every longer prefix differs
    for k in range(k_shared + 1, len(a.gates) + 1):
        assert a.prefix_digest(k) != b.prefix_digest(k)
    # different prep seed -> divergence from the first seeded gate on
    c = _tenant(tail_seed=1, prep_seed=8)
    assert a.prefix_digest(len(a.gates)) != c.prefix_digest(len(c.gates))


def test_prefix_digest_endpoints_and_range():
    circ = _prep()
    n = len(circ.gates)
    assert circ.prefix_digest(n) == circ.structure_digest()
    empty = hashlib.sha1().hexdigest()
    assert circ.prefix_digest(0) == empty
    assert QCircuit().prefix_digest(0) == empty
    with pytest.raises(IndexError):
        circ.prefix_digest(n + 1)


def test_append_merge_hazard_documented():
    """A same-target uncontrolled append merges into the previous gate:
    the digest AT the old boundary changes (the boundary gate's payload
    was rewritten), which is exactly why shared-prefix tenants must
    start their tails with an entangling barrier."""
    circ = _prep()
    n = len(circ.gates)
    frozen = _prep().structure_digest()
    last_target = circ.gates[-1].target
    circ.append_1q(last_target, _ry(0.123))      # merges, no new gate
    assert len(circ.gates) == n
    assert circ.prefix_digest(n) != frozen


# ---------------------------------------------------------------------------
# shareable_prefix_len: measurement terminates sharing
# ---------------------------------------------------------------------------

def test_measurement_terminates_shareable_prefix():
    circ = _prep()
    n = len(circ.gates)
    assert circ.shareable_prefix_len() == n
    # a projector payload is non-unitary — the recorded collapse draws
    # per-tenant rng, so nothing at or past it may be shared.  Appended
    # after a ring so the peephole cannot fold it into a unitary gate.
    _ring(circ)
    proj = np.array([[1, 0], [0, 0]], dtype=np.complex128)
    circ.append_1q(0, proj)
    _ring(circ)
    assert circ.shareable_prefix_len() == n + (W - 1)
    assert len(circ.gates) > circ.shareable_prefix_len()


# ---------------------------------------------------------------------------
# split_at: verbatim copies, no re-merge
# ---------------------------------------------------------------------------

def test_split_at_copies_verbatim():
    circ = _tenant(tail_seed=3)
    k = _shared_boundary()
    pre, suf = circ.split_at(k)
    assert len(pre.gates) + len(suf.gates) == len(circ.gates)
    assert pre.structure_digest() == circ.prefix_digest(k)
    # the suffix starts with 1q rotations that WOULD merge under
    # AppendGate — verbatim copy must preserve the gate boundary
    whole = _tenant(tail_seed=3)
    assert (pre.structure_digest() != whole.structure_digest()
            or k == len(whole.gates))
    recomposed = QCircuit(circ.qubit_count)
    recomposed.gates = [g.clone() for g in pre.gates + suf.gates]
    assert recomposed.structure_digest() == circ.structure_digest()
    # mutating the split halves never touches the original
    suf.gates[0].payloads[0] = np.asarray(mat.Y2)
    assert circ.structure_digest() == whole.structure_digest()
